"""Host-side RNG with exact MT19937 stream parity to the reference.

The reference seeds a Mersenne-Twister (mt19937ar) with ``init_by_array`` and
draws measurement outcomes with ``genrand_real1`` (ref: QuEST/src/mt19937ar.c,
QuEST_common.c:155-170).  Reproducing the identical outcome stream requires the
same generator, same seeding, and same draw points, so we implement the
standard MT19937 algorithm here (it is a public, well-specified algorithm).

Measurement is inherently a host round-trip (data-dependent collapse), so a
host-side Python generator costs nothing extra on TPU.  Batched stochastic
workloads should use ``jax.random`` instead; this generator exists for
reference-parity of ``measure()``/``seedQuEST()`` semantics.
"""

from __future__ import annotations

import os
import time

_N = 624
_M = 397
_MATRIX_A = 0x9908B0DF
_UPPER_MASK = 0x80000000
_LOWER_MASK = 0x7FFFFFFF
_U32 = 0xFFFFFFFF


class MT19937:
    """The standard 32-bit Mersenne Twister (mt19937ar variant)."""

    def __init__(self) -> None:
        self.mt = [0] * _N
        self.mti = _N + 1

    def init_genrand(self, s: int) -> None:
        self.mt[0] = s & _U32
        for i in range(1, _N):
            self.mt[i] = (1812433253 * (self.mt[i - 1] ^ (self.mt[i - 1] >> 30)) + i) & _U32
        self.mti = _N

    def init_by_array(self, init_key) -> None:
        self.init_genrand(19650218)
        i, j = 1, 0
        k = max(_N, len(init_key))
        for _ in range(k):
            self.mt[i] = ((self.mt[i] ^ ((self.mt[i - 1] ^ (self.mt[i - 1] >> 30)) * 1664525))
                          + init_key[j] + j) & _U32
            i += 1
            j += 1
            if i >= _N:
                self.mt[0] = self.mt[_N - 1]
                i = 1
            if j >= len(init_key):
                j = 0
        for _ in range(_N - 1):
            self.mt[i] = ((self.mt[i] ^ ((self.mt[i - 1] ^ (self.mt[i - 1] >> 30)) * 1566083941))
                          - i) & _U32
            i += 1
            if i >= _N:
                self.mt[0] = self.mt[_N - 1]
                i = 1
        self.mt[0] = 0x80000000

    def genrand_int32(self) -> int:
        if self.mti >= _N:
            if self.mti == _N + 1:  # never seeded
                self.init_genrand(5489)
            mt = self.mt
            for kk in range(_N - _M):
                y = (mt[kk] & _UPPER_MASK) | (mt[kk + 1] & _LOWER_MASK)
                mt[kk] = mt[kk + _M] ^ (y >> 1) ^ (_MATRIX_A if y & 1 else 0)
            for kk in range(_N - _M, _N - 1):
                y = (mt[kk] & _UPPER_MASK) | (mt[kk + 1] & _LOWER_MASK)
                mt[kk] = mt[kk + (_M - _N)] ^ (y >> 1) ^ (_MATRIX_A if y & 1 else 0)
            y = (mt[_N - 1] & _UPPER_MASK) | (mt[0] & _LOWER_MASK)
            mt[_N - 1] = mt[_M - 1] ^ (y >> 1) ^ (_MATRIX_A if y & 1 else 0)
            self.mti = 0
        y = self.mt[self.mti]
        self.mti += 1
        y ^= y >> 11
        y ^= (y << 7) & 0x9D2C5680
        y ^= (y << 15) & 0xEFC60000
        y ^= y >> 18
        return y & _U32

    def genrand_real1(self) -> float:
        """Uniform on [0,1] with 32-bit resolution (matches reference draws)."""
        return self.genrand_int32() * (1.0 / 4294967295.0)


# The process-global generator, mirroring the reference's single static MT
# state shared by all Quregs.
_GLOBAL = MT19937()


def seed_quest(seed_array) -> None:
    """User seeding, ref: seedQuEST (QuEST_common.c:209-214)."""
    _GLOBAL.init_by_array([int(s) & _U32 for s in seed_array])


def seed_quest_default() -> None:
    """Default seeding by [msec-time, pid], ref: QuEST_common.c:182-204."""
    msecs = int(time.time() * 1000)
    pid = os.getpid()
    seed_quest([msecs, pid])


def rand_real1() -> float:
    return _GLOBAL.genrand_real1()
