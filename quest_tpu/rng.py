"""Host-side RNG with exact MT19937 stream parity to the reference.

The reference seeds a Mersenne-Twister (mt19937ar) with ``init_by_array`` and
draws measurement outcomes with ``genrand_real1`` (ref: QuEST/src/mt19937ar.c,
QuEST_common.c:155-170).  Reproducing the identical outcome stream requires the
same generator, same seeding, and same draw points, so we implement the
standard MT19937 algorithm here (it is a public, well-specified algorithm).

Measurement is inherently a host round-trip (data-dependent collapse), so a
host-side Python generator costs nothing extra on TPU.  Batched stochastic
workloads should use ``jax.random`` instead; this generator exists for
reference-parity of ``measure()``/``seedQuEST()`` semantics.
"""

from __future__ import annotations

import os
import time

_N = 624
_M = 397
_MATRIX_A = 0x9908B0DF
_UPPER_MASK = 0x80000000
_LOWER_MASK = 0x7FFFFFFF
_U32 = 0xFFFFFFFF


class MT19937:
    """The standard 32-bit Mersenne Twister (mt19937ar variant)."""

    def __init__(self) -> None:
        self.mt = [0] * _N
        self.mti = _N + 1

    def init_genrand(self, s: int) -> None:
        self.mt[0] = s & _U32
        for i in range(1, _N):
            self.mt[i] = (1812433253 * (self.mt[i - 1] ^ (self.mt[i - 1] >> 30)) + i) & _U32
        self.mti = _N

    def init_by_array(self, init_key) -> None:
        self.init_genrand(19650218)
        i, j = 1, 0
        k = max(_N, len(init_key))
        for _ in range(k):
            self.mt[i] = ((self.mt[i] ^ ((self.mt[i - 1] ^ (self.mt[i - 1] >> 30)) * 1664525))
                          + init_key[j] + j) & _U32
            i += 1
            j += 1
            if i >= _N:
                self.mt[0] = self.mt[_N - 1]
                i = 1
            if j >= len(init_key):
                j = 0
        for _ in range(_N - 1):
            self.mt[i] = ((self.mt[i] ^ ((self.mt[i - 1] ^ (self.mt[i - 1] >> 30)) * 1566083941))
                          - i) & _U32
            i += 1
            if i >= _N:
                self.mt[0] = self.mt[_N - 1]
                i = 1
        self.mt[0] = 0x80000000

    def genrand_int32(self) -> int:
        if self.mti >= _N:
            if self.mti == _N + 1:  # never seeded
                self.init_genrand(5489)
            mt = self.mt
            for kk in range(_N - _M):
                y = (mt[kk] & _UPPER_MASK) | (mt[kk + 1] & _LOWER_MASK)
                mt[kk] = mt[kk + _M] ^ (y >> 1) ^ (_MATRIX_A if y & 1 else 0)
            for kk in range(_N - _M, _N - 1):
                y = (mt[kk] & _UPPER_MASK) | (mt[kk + 1] & _LOWER_MASK)
                mt[kk] = mt[kk + (_M - _N)] ^ (y >> 1) ^ (_MATRIX_A if y & 1 else 0)
            y = (mt[_N - 1] & _UPPER_MASK) | (mt[0] & _LOWER_MASK)
            mt[_N - 1] = mt[_M - 1] ^ (y >> 1) ^ (_MATRIX_A if y & 1 else 0)
            self.mti = 0
        y = self.mt[self.mti]
        self.mti += 1
        y ^= y >> 11
        y ^= (y << 7) & 0x9D2C5680
        y ^= (y << 15) & 0xEFC60000
        y ^= y >> 18
        return y & _U32

    def genrand_real1(self) -> float:
        """Uniform on [0,1] with 32-bit resolution (matches reference draws)."""
        return self.genrand_int32() * (1.0 / 4294967295.0)

    # -- batched draws (vectorized twist, draw-for-draw identical stream) --

    def _twist_block_np(self, mt):
        """One full MT19937 twist, vectorized.  ``mt`` is a uint32 ndarray of
        length N, updated in place to the next block of raw state words.

        Data-dependence: every y for kk < N-1 reads only pre-twist words
        (each mt[kk] is written strictly after its y is formed); the second
        loop's mt[kk-(N-M)] is a lag-(N-M) recurrence on already-twisted
        words, resolved here in chunks of N-M; the final word reads new
        mt[0]/mt[M-1].
        """
        import numpy as np
        old = mt.copy()
        y = (old[:-1] & _UPPER_MASK) | (old[1:] & _LOWER_MASK)
        mag = np.where(y & 1, np.uint32(_MATRIX_A), np.uint32(0))
        lag = _N - _M
        mt[:lag] = old[_M:] ^ (y[:lag] >> 1) ^ mag[:lag]
        start = lag
        while start < _N - 1:
            end = min(start + lag, _N - 1)
            mt[start:end] = (mt[start - lag:end - lag]
                             ^ (y[start:end] >> 1) ^ mag[start:end])
            start = end
        y_last = (int(old[_N - 1]) & _UPPER_MASK) | (int(mt[0]) & _LOWER_MASK)
        mt[_N - 1] = int(mt[_M - 1]) ^ (y_last >> 1) ^ (_MATRIX_A if y_last & 1 else 0)

    @staticmethod
    def _temper_np(y):
        y = y.copy()
        y ^= y >> 11
        y ^= (y << 7) & 0x9D2C5680
        y ^= (y << 15) & 0xEFC60000
        y ^= y >> 18
        return y

    def genrand_int32_batch(self, n: int):
        """``n`` consecutive draws as a uint32 ndarray — the identical stream
        ``genrand_int32`` would produce with ``n`` scalar calls, at numpy
        speed (one vectorized twist per 624 outputs)."""
        import numpy as np
        n = int(n)
        out = np.empty(n, np.uint32)
        if n == 0:
            return out
        if self.mti == _N + 1:  # never seeded (scalar path parity)
            self.init_genrand(5489)
        mt = np.array(self.mt, np.uint32)
        filled = 0
        while filled < n:
            if self.mti >= _N:
                self._twist_block_np(mt)
                self.mti = 0
            take = min(_N - self.mti, n - filled)
            out[filled:filled + take] = self._temper_np(
                mt[self.mti:self.mti + take])
            self.mti += take
            filled += take
        self.mt = [int(w) for w in mt]
        return out

    def genrand_real1_batch(self, n: int):
        return self.genrand_int32_batch(n) * (1.0 / 4294967295.0)


# The process-global generator, mirroring the reference's single static MT
# state shared by all Quregs.
_GLOBAL = MT19937()


def seed_quest(seed_array) -> None:
    """User seeding, ref: seedQuEST (QuEST_common.c:209-214)."""
    _GLOBAL.init_by_array([int(s) & _U32 for s in seed_array])


def default_seed_array() -> list:
    """This process's candidate default seeds: [msec-time, pid]
    (ref: QuEST_common.c:182-204)."""
    return [int(time.time() * 1000) & _U32, os.getpid() & _U32]


def seed_quest_default() -> None:
    """Default seeding by [msec-time, pid], ref: QuEST_common.c:182-204.

    Multi-process contract: the reference broadcasts rank 0's seed array to
    every rank before seeding (MPI_Bcast, QuEST_cpu_distributed.c:1318-1329)
    so all ranks draw the identical measurement-outcome stream.  We reproduce
    that with ``broadcast_one_to_all`` from process 0 whenever JAX runs
    multi-process; without it two hosts would pick different collapse
    outcomes and silently corrupt a shared sharded state.
    """
    seeds = default_seed_array()
    import jax
    if jax.process_count() > 1:
        import numpy as np
        from jax.experimental import multihost_utils
        seeds = [int(s) for s in
                 multihost_utils.broadcast_one_to_all(np.asarray(seeds, np.uint32))]
    seed_quest(seeds)


def rand_real1() -> float:
    return _GLOBAL.genrand_real1()


def rand_real1_batch(n: int):
    """``n`` draws from the global stream, vectorized (same stream order as
    ``n`` calls to ``rand_real1``)."""
    return _GLOBAL.genrand_real1_batch(n)
