"""Whole-circuit compilation: record a gate list, emit ONE fused XLA program.

This layer has no analogue in the reference, which dispatches one kernel per
API call (ref: QuEST.c:177-660 — every gate is a separate library call with
its own OpenMP/MPI/CUDA launch).  Under XLA that per-gate model would leave
fusion on the table: a circuit compiled as a single jitted program lets the
compiler fuse adjacent diagonal/elementwise gates into single HBM passes,
batch rotations into one matmul, and schedule cross-shard collectives — the
performance model TPUs want.  The eager per-gate API (api.py) remains the
compatibility surface; this is the TPU-native fast path.

A :class:`Circuit` is a host-side IR of (kind, targets, controls, matrix)
records.  ``compile_circuit`` closes over the static structure and returns a
jitted ``state -> state`` function; parametric use goes through
``apply_circuit`` on a Qureg.  Matrices are embedded as compile-time
constants (gate structure is trace-time structure, the resolution of the
reference's runtime qubit-index dispatch — SURVEY §7 hard part (b)).
"""

from __future__ import annotations

import dataclasses
import math
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from . import _compat
from . import obs as _obs
from .ops import apply as _ap

__all__ = ["Circuit", "DensityCircuit", "GateOp", "compile_circuit",
           "apply_circuit", "op_operands", "op_param_count", "structural_op",
           "param_vector", "lifted_operands", "validate_density_operands",
           "random_circuit", "qft_circuit"]


@dataclasses.dataclass(frozen=True)
class GateOp:
    kind: str                      # 'matrix' | 'diagonal' | 'x' | 'y' | 'swap'
    targets: tuple
    controls: tuple = ()
    control_states: tuple = ()
    matrix: tuple | None = None    # flattened real-pair payload (hashable)
    shape: tuple | None = None

    def payload(self) -> np.ndarray:
        return np.asarray(self.matrix, dtype=np.float64).reshape(self.shape)


class Circuit:
    """Recorded gate sequence on ``num_qubits`` qubits.

    Builder methods mirror the API's gate set; each appends an IR record.
    ``compile()`` returns a jitted pure function over the (2, 2^n) SoA state.
    """

    def __init__(self, num_qubits: int):
        self.num_qubits = num_qubits
        self.ops: list[GateOp] = []

    # --- recording ---------------------------------------------------------
    def _record(self, op: GateOp) -> None:
        """The one append point every builder method funnels through, so a
        subclass can transform recorded ops uniformly (DensityCircuit
        doubles each unitary with its conjugate shadow)."""
        self.ops.append(op)

    def _mat(self, u, targets, controls=(), control_states=()):
        up = _ap.mat_pair(u)
        self._record(GateOp("matrix", tuple(targets), tuple(controls),
                            tuple(control_states),
                            tuple(up.ravel()), up.shape))
        return self

    def _diag(self, d, targets, controls=(), control_states=()):
        d = np.asarray(d, dtype=np.complex128)
        dp = np.stack([d.real, d.imag])
        self._record(GateOp("diagonal", tuple(targets), tuple(controls),
                            tuple(control_states),
                            tuple(dp.ravel()), dp.shape))
        return self

    def unitary(self, target, u):
        return self._mat(u, (target,))

    def multi_qubit_unitary(self, targets, u, controls=(), control_states=()):
        return self._mat(u, tuple(targets), tuple(controls), tuple(control_states))

    def compact_unitary(self, target, alpha, beta):
        return self._mat([[alpha, -np.conj(beta)], [beta, np.conj(alpha)]], (target,))

    def h(self, target):
        s = 1.0 / math.sqrt(2.0)
        return self._mat([[s, s], [s, -s]], (target,))

    def x(self, target, controls=()):
        self._record(GateOp("x", (target,), tuple(controls)))
        return self

    def y(self, target, controls=()):
        self._record(GateOp("y", (target,), tuple(controls)))
        return self

    def z(self, target, controls=()):
        return self._diag([1.0, -1.0], (target,), tuple(controls))

    def cnot(self, control, target):
        return self.x(target, (control,))

    def cz(self, q1, q2):
        return self.z(q2, (q1,))

    def s(self, target):
        return self._diag([1.0, 1j], (target,))

    def t(self, target):
        return self._diag([1.0, np.exp(1j * math.pi / 4)], (target,))

    def phase_shift(self, target, angle, controls=()):
        return self._diag([1.0, np.exp(1j * angle)], (target,), tuple(controls))

    def rx(self, target, angle):
        c, s = math.cos(angle / 2), math.sin(angle / 2)
        return self._mat([[c, -1j * s], [-1j * s, c]], (target,))

    def ry(self, target, angle):
        c, s = math.cos(angle / 2), math.sin(angle / 2)
        return self._mat([[c, -s], [s, c]], (target,))

    def rz(self, target, angle):
        return self._diag([np.exp(-1j * angle / 2), np.exp(1j * angle / 2)], (target,))

    def swap(self, q1, q2):
        self._record(GateOp("swap", (q1, q2)))
        return self

    def multi_rotate_z(self, targets, angle):
        """exp(-i angle/2 Z⊗..⊗Z): a parity-keyed diagonal
        (ref: multiRotateZ, QuEST_cpu.c:3109).

        Narrow strings record a dense 2^k diagonal (feeds the native fusion
        engine); wide strings record an O(1)-payload ``mrz`` op dispatched to
        the mask-based kernel — a dense diagonal would cost 2^k host memory
        and jit-key hashing."""
        targets = tuple(targets)
        if len(targets) <= 10:
            par = np.array([bin(b).count("1") & 1
                            for b in range(1 << len(targets))])
            return self._diag(np.exp(-0.5j * angle * (1 - 2 * par)), targets)
        self._record(GateOp("mrz", targets, (), (), (float(angle),), None))
        return self

    def multi_rotate_pauli(self, targets, paulis, angle):
        """exp(-i angle/2 P⊗..) via basis-change to Z and back
        (ref: statevec_multiRotatePauli, QuEST_common.c:411-448).
        All-identity strings record nothing — the reference deliberately
        skips the rotation (and its global phase) on an empty mask."""
        fac = 1.0 / math.sqrt(2.0)
        targets = tuple(targets)
        codes = tuple(int(p) for p in paulis)
        assert len(codes) == len(targets)
        mask = [t for t, p in zip(targets, codes) if p]
        if not mask:
            return self
        for t, p in zip(targets, codes):
            if p == 1:  # X: Ry(-pi/2) rotates Z -> X
                self._mat([[fac, fac], [-fac, fac]], (t,))
            elif p == 2:  # Y: Rx(pi/2) rotates Z -> Y
                self._mat([[fac, -1j * fac], [-1j * fac, fac]], (t,))
        self.multi_rotate_z(mask, angle)
        for t, p in zip(targets, codes):
            if p == 1:
                self._mat([[fac, -fac], [fac, fac]], (t,))
            elif p == 2:
                self._mat([[fac, 1j * fac], [1j * fac, fac]], (t,))
        return self

    # --- compilation -------------------------------------------------------
    def __len__(self) -> int:
        return len(self.ops)

    def key(self, structural: bool = False, engine: str | None = None) -> tuple:
        """Hashable identity of the recorded gate list.

        ``structural=True`` returns the STRUCTURAL key: op kinds, wires,
        control states and payload arities with every continuous payload
        (gate matrices, diagonal entries, the mrz angle) lifted out.  Two
        circuits differing only in rotation angles — the shape of a
        million-user parameterized workload — share one structural key and
        therefore ONE compiled program in the serve-layer compilation cache
        (quest_tpu/serve/cache.py), where the default key would force one
        XLA compile per angle assignment.  Discrete payloads (``bitperm``
        destination wires) stay in the key: they select the program's data
        movement, not its operands.

        ``engine`` tags the key with the RESOLVED compiled-circuit backend
        ("xla" | "pallas"; ``compile_circuit`` resolves "auto" before any
        keying).  The tag is part of program identity: the same op list
        lowered through the XLA gate engine and through the Pallas epoch
        executor (ops/epoch_pallas.py) are different executables, and a
        cache entry compiled under one must never be served to a request
        planned for the other.  ``engine=None`` and the default
        ``engine="xla"`` key identically (backward compatible)."""
        ops = (tuple(structural_op(op) for op in self.ops) if structural
               else tuple(self.ops))
        if engine is not None and engine != "xla":
            return (("engine", engine),) + ops
        return ops

    def optimize(self, max_pack: int = 7) -> "Circuit":
        """Run the native gate-fusion engine (native/fusion.cpp): merges
        adjacent/commuting gates and kron-packs runs of parallel gates into
        multi-target gates of up to ``max_pack`` qubits (7 = one 128-wide
        MXU tile), so the compiled program makes far fewer HBM passes.
        No-op if the native library is unavailable.

        Mutates ``self.ops`` IN PLACE and returns ``self`` (builder-style
        chaining — the return value is not a copy).  The rewrite invalidates
        every derived artefact: ``key()`` reflects the fused list on the
        next call, and the density-matrix shadow cache is dropped so a
        subsequent ``apply_circuit`` on a density register rebuilds its
        conjugated twin list from the fused ops."""
        from .native import fuse_ops
        self.ops = fuse_ops(self.ops, max_pack=max_pack)
        self._shadow_cache = None
        return self

    def schedule(self, num_devices: int, **kwargs) -> "Circuit":
        """Comm-aware scheduled copy of this circuit for a ``num_devices``-
        way amplitude mesh (parallel/scheduler.py): commutation-DAG
        reordering groups cross-shard dense gates into shared permutation
        epochs, swap networks are fused into single bit-permutation
        collectives, and a greedy logical->physical placement search scored
        by the ICI time model (parallel/planner.py) may relabel the circuit.
        Returns a NEW equivalent Circuit; ``self`` is unmodified.

        ``schedule(..., overlap=True, pipeline_chunks=C)`` attaches the
        pipelined executor's chunking plan (parallel/executor.py) so
        ``compile_circuit(..., overlap=True)`` double-buffers chunked
        collectives against gate compute; chunking is layout-only and the
        op list is unchanged.

        Inputs are validated with the runtime layer's codes: a bad
        ``num_devices`` (non-integer, < 1, or not a power of two) raises
        ``E_INVALID_NUM_RANKS`` and an unknown keyword or a
        non-power-of-two ``pipeline_chunks`` raises
        ``E_INVALID_SCHEDULE_OPTION``.  Set
        ``QUEST_TPU_VALIDATE_SCHEDULE=1`` to translation-validate every
        scheduled circuit against its input (analysis/equivalence.py); see
        docs/SCHEDULER.md."""
        from .parallel import scheduler as _sched
        return _sched.schedule(self, num_devices, **kwargs)


class DensityCircuit(Circuit):
    """Density-matrix circuit on ``num_qubits`` qubits, recorded DIRECTLY as
    its Choi-doubled ``2n``-qubit program (PAPER.md L4: U rho U† runs as
    U ⊗ U* on a flattened 2n-qubit statevector; row/ket index in qubits
    0..n-1, column/bra index in n..2n-1 — the getDensityAmp convention of
    ops/decoherence.py).

    Every inherited unitary builder records the op AND its conjugate shadow
    on the bra wires (the ``_record`` hook), so the recorded op list is an
    ORDINARY 2n-qubit circuit: ``compile_circuit(engine="auto")``, the
    Pallas epoch executor, the comm-aware scheduler, the serve cache's
    parameter lift and the translation validator all apply unchanged.

    Channel methods (:meth:`damp`, :meth:`depolarise`, :meth:`dephase`,
    :meth:`two_qubit_dephase`, :meth:`mix_pauli`, :meth:`kraus`) record the
    channel's SUPEROPERATOR as a plain matrix/diagonal op on the doubled
    ``(q, q+n)`` wires (ops/decoherence.py static builders).  The channel
    payload is continuous, so a probability sweep shares ONE structural
    class — one compiled program per (skeleton, channel mask) in the serve
    cache, probabilities riding in the operand vector.  ``channel_slots``
    records which op indices are channels: the analyzer validates those as
    trace-preserving superoperators instead of unitaries, and serve
    admission re-validates the operand slices (``E_INVALID_KRAUS_OPS``).
    ``channel_log`` carries (op_index, kind, density targets, args) — the
    oracle record ``analysis.check_density_lowering`` proves the recorded
    superoperators against the channels' defining Kraus operators."""

    def __init__(self, num_qubits: int):
        super().__init__(2 * num_qubits)
        self.density_qubits = int(num_qubits)
        self.channel_slots: set[int] = set()
        self.channel_log: list[tuple] = []

    def _record(self, op: GateOp) -> None:
        n = self.density_qubits
        for q in op.targets + op.controls:
            if not 0 <= q < n:
                from .validation import MESSAGES, ErrorCode, QuESTError
                raise QuESTError(ErrorCode.INVALID_TARGET_QUBIT,
                                 MESSAGES[ErrorCode.INVALID_TARGET_QUBIT]
                                 + f" (density wire {q} of {n}.)",
                                 "DensityCircuit")
        self.ops.append(op)
        self.ops.append(_shadow_op(op, n))

    def optimize(self, max_pack: int = 7) -> "Circuit":
        """REFUSED on a density circuit: the native fusion engine rewrites
        the op list in place, which would leave ``channel_slots`` /
        ``channel_log`` indexing the pre-fusion list (serve admission and
        the analyzer would then validate the wrong operand slices) and
        break the (op, shadow) pairing the density prover certifies.  The
        epoch executor already fuses the doubled program at compile time —
        there is nothing for record-time fusion to win here."""
        from .validation import MESSAGES, ErrorCode, QuESTError
        raise QuESTError(
            ErrorCode.INVALID_SCHEDULE_OPTION,
            MESSAGES[ErrorCode.INVALID_SCHEDULE_OPTION]
            + " DensityCircuit.optimize() is unsupported: record-time "
            "fusion would orphan the channel metadata and the mirrored "
            "pairing; the epoch executor fuses the doubled program at "
            "compile time instead.", "DensityCircuit.optimize")

    # --- decoherence channels ---------------------------------------------
    def _channel(self, kind: str, targets: tuple, op: GateOp, *args):
        self.channel_slots.add(len(self.ops))
        self.channel_log.append((len(self.ops), kind, targets) + args)
        self.ops.append(op)
        return self

    def _doubled(self, targets) -> tuple:
        """Validated doubled wire tuple of a channel's density targets —
        the same record-time contract the unitary builders get from
        ``_record`` (range) plus uniqueness, with the eager API's codes."""
        from .validation import MESSAGES, ErrorCode, QuESTError
        n = self.density_qubits
        ts = tuple(int(t) for t in targets)
        for t in ts:
            if not 0 <= t < n:
                raise QuESTError(ErrorCode.INVALID_TARGET_QUBIT,
                                 MESSAGES[ErrorCode.INVALID_TARGET_QUBIT]
                                 + f" (density wire {t} of {n}.)",
                                 "DensityCircuit")
        if len(set(ts)) != len(ts):
            raise QuESTError(ErrorCode.TARGETS_NOT_UNIQUE,
                             MESSAGES[ErrorCode.TARGETS_NOT_UNIQUE],
                             "DensityCircuit")
        return ts + tuple(t + n for t in ts)

    def dephase(self, target: int, prob: float):
        """rho -> (1-p) rho + p Z rho Z: a DIAGONAL superoperator on the
        doubled pair (ref: densmatr_mixDephasing, QuEST_cpu.c:79)."""
        from .ops import decoherence as _deco
        from .validation import validate_one_qubit_dephase_prob
        validate_one_qubit_dephase_prob(prob, "DensityCircuit.dephase")
        dp = _deco.dephasing_diag(prob)
        return self._channel(
            "dephase", (int(target),),
            GateOp("diagonal", self._doubled((target,)), (), (),
                   tuple(dp.ravel()), dp.shape), float(prob))

    def two_qubit_dephase(self, q1: int, q2: int, prob: float):
        """Two-qubit dephasing (ref: densmatr_mixTwoQubitDephasing)."""
        from .ops import decoherence as _deco
        from .validation import validate_two_qubit_dephase_prob
        validate_two_qubit_dephase_prob(prob, "DensityCircuit.two_qubit_dephase")
        dp = _deco.two_qubit_dephasing_diag(prob)
        return self._channel(
            "dephase2", (int(q1), int(q2)),
            GateOp("diagonal", self._doubled((q1, q2)), (), (),
                   tuple(dp.ravel()), dp.shape), float(prob))

    def depolarise(self, target: int, prob: float):
        """One-qubit depolarising: a dense 4x4 superoperator on (q, q+n)
        (ref: densmatr_mixDepolarisingLocal, QuEST_cpu.c:125)."""
        from .ops import decoherence as _deco
        from .validation import validate_one_qubit_depol_prob
        validate_one_qubit_depol_prob(prob, "DensityCircuit.depolarise")
        sp = _deco.depolarising_superop(prob)
        return self._channel(
            "depol", (int(target),),
            GateOp("matrix", self._doubled((target,)), (), (),
                   tuple(sp.ravel()), sp.shape), float(prob))

    def damp(self, target: int, prob: float):
        """Amplitude damping |1><1| -> |0><0| with probability p
        (ref: densmatr_mixDampingLocal, QuEST_cpu.c:174)."""
        from .ops import decoherence as _deco
        from .validation import validate_one_qubit_damping_prob
        validate_one_qubit_damping_prob(prob, "DensityCircuit.damp")
        sp = _deco.damping_superop(prob)
        return self._channel(
            "damp", (int(target),),
            GateOp("matrix", self._doubled((target,)), (), (),
                   tuple(sp.ravel()), sp.shape), float(prob))

    def mix_pauli(self, target: int, prob_x: float, prob_y: float,
                  prob_z: float):
        """Pauli channel {sqrt(1-px-py-pz) I, sqrt(px) X, sqrt(py) Y,
        sqrt(pz) Z} as one Kraus superoperator (ref: densmatr_mixPauli)."""
        from .validation import validate_pauli_probs
        validate_pauli_probs(prob_x, prob_y, prob_z,
                             "DensityCircuit.mix_pauli")
        s = math.sqrt(max(0.0, 1.0 - prob_x - prob_y - prob_z))
        ops = [s * np.eye(2),
               math.sqrt(prob_x) * np.array([[0.0, 1.0], [1.0, 0.0]]),
               math.sqrt(prob_y) * np.array([[0.0, -1.0j], [1.0j, 0.0]]),
               math.sqrt(prob_z) * np.diag([1.0, -1.0])]
        return self.kraus((target,), ops)

    def kraus(self, targets, ops):
        """General Kraus map: ONE dense superoperator matrix on the doubled
        targets (ref: densmatr_applyKrausSuperoperator path).  The operator
        list is validated trace-preserving at RECORD time — a malformed map
        raises ``E_INVALID_KRAUS_OPS`` here instead of surfacing as silent
        trace drift at execution."""
        from .ops import decoherence as _deco
        from .validation import (validate_kraus_cptp, validate_kraus_sizes,
                                 validate_num_kraus_ops)
        targets = tuple(int(t) for t in targets)
        ops = [np.asarray(k, np.complex128) for k in ops]
        validate_num_kraus_ops(len(targets), len(ops), "DensityCircuit.kraus")
        validate_kraus_sizes(ops, len(targets), "DensityCircuit.kraus")
        validate_kraus_cptp(ops, "DensityCircuit.kraus", eps=1e-10)
        sp = _deco.kraus_superoperator(ops)
        return self._channel(
            "kraus", targets,
            GateOp("matrix", self._doubled(targets), (), (),
                   tuple(sp.ravel()), sp.shape),
            tuple(tuple(tuple(map(complex, row)) for row in k)
                  for k in ops))


def validate_density_operands(circuit, params=None, func: str = "submit") -> None:
    """Admission-time channel validation of a :class:`DensityCircuit`: every
    channel slot's superoperator operand (from ``params`` when given — the
    parameter-lifted sweep — else the recorded payload) must preserve
    Tr(rho); a non-trace-preserving map raises ``E_INVALID_KRAUS_OPS``
    (the serve-submit half of the Kraus validation satellite)."""
    from .ops import decoherence as _deco
    from .precision import real_eps
    from .validation import MESSAGES, ErrorCode, QuESTError
    slots = getattr(circuit, "channel_slots", None)
    if not slots:
        return
    pvec = (np.asarray(params, np.float64).ravel()
            if params is not None else None)
    # tolerance at the LOOSEST precision the compiled executables consume:
    # a tenant's probability sweep may round-trip through float32 (the
    # epoch engine's plane dtype), and a map that is trace-preserving to
    # f32 working precision must not bounce at the front door
    eps = 10 * real_eps(jnp.float32)
    off = 0
    for i, op in enumerate(circuit.ops):
        c = op_param_count(op)
        if i in slots:
            payload = (pvec[off:off + c].reshape(op.shape)
                       if pvec is not None else op.payload())
            k = len(op.targets) // 2
            if op.kind == "diagonal":
                payload = np.stack([np.diag(payload[0]),
                                    np.diag(payload[1])])
            if not _deco.superop_trace_preserving(payload, k, eps):
                raise QuESTError(
                    ErrorCode.INVALID_KRAUS_OPS,
                    MESSAGES[ErrorCode.INVALID_KRAUS_OPS]
                    + f" (channel op {i} on wires {op.targets}.)", func)
        off += c


def op_operands(op: GateOp, state_dtype) -> dict:
    """Device operands the compiled path feeds the gate kernels for ``op``.

    Single source of truth for per-op operand construction: ``_apply_one``
    consumes it when tracing, and ``quest_tpu.analysis.abstract_eval``
    compares it against the eager API's operand contract — the mrz angle in
    particular must stay float64 on BOTH paths (api.py multiRotateZ passes
    ``jnp.float64(angle)``; an f32-cast angle here would give compiled f32
    states different phases than eager ones)."""
    if op.kind in ("matrix", "diagonal"):
        return {"payload": jnp.asarray(op.payload(), dtype=state_dtype)}
    if op.kind == "mrz":
        return {"angle": jnp.asarray(op.matrix[0], dtype=jnp.float64)}
    return {}


def op_param_count(op: GateOp) -> int:
    """Number of continuous (liftable) payload scalars of ``op``: the flat
    real-pair matrix/diagonal payload, or the single mrz angle.  Discrete
    payloads (``bitperm`` wire destinations) and payload-free kinds count
    zero — they are structure, not operands."""
    if op.kind in ("matrix", "diagonal"):
        if op.matrix is not None:
            return len(op.matrix)
        return int(np.prod(op.shape))
    if op.kind == "mrz":
        return 1
    return 0


def structural_op(op: GateOp) -> GateOp:
    """The payload-free twin of ``op`` used by structural keys: continuous
    payloads dropped, arity (``shape``) kept so the lifted operand layout is
    still derivable from the key alone."""
    if op.kind in ("matrix", "diagonal"):
        return GateOp(op.kind, op.targets, op.controls, op.control_states,
                      None, op.shape)
    if op.kind == "mrz":
        return GateOp(op.kind, op.targets, op.controls, op.control_states,
                      None, None)
    return op


def param_vector(ops) -> np.ndarray:
    """The flat float64 operand vector of a circuit (or op list): every
    continuous payload concatenated in op order.  This is the runtime
    ``params`` argument of a parameter-lifted program — the circuit's
    structural key plus this vector reconstruct it exactly
    (serve/cache.py circuit_from_params)."""
    if isinstance(ops, Circuit):
        ops = ops.ops
    chunks = []
    for op in ops:
        if op_param_count(op):
            if op.matrix is None:
                raise ValueError(
                    "param_vector needs concrete payloads; got a structural "
                    f"op ({op.kind} on {op.targets})")
            chunks.append(np.asarray(op.matrix, dtype=np.float64).ravel())
    if not chunks:
        return np.zeros((0,), np.float64)
    return np.concatenate(chunks)


def lifted_operands(op: GateOp, params: jax.Array, offset, state_dtype) -> dict:
    """:func:`op_operands` twin for parameter-lifted programs (the serve
    compilation cache): operands are STATIC slices of a runtime float64
    vector instead of compile-time constants, so one compiled program
    serves every payload assignment of its structural class.  The dtype
    contract matches ``op_operands`` exactly — payloads cast to the state
    dtype, the mrz angle kept float64 (params are float64 end-to-end)."""
    if op.kind in ("matrix", "diagonal"):
        size = int(np.prod(op.shape))
        return {"payload": params[offset:offset + size]
                .reshape(op.shape).astype(state_dtype)}
    if op.kind == "mrz":
        return {"angle": params[offset]}
    return {}


def _apply_one(state: jax.Array, op: GateOp, operands: dict | None = None) -> jax.Array:
    if operands is None:
        operands = op_operands(op, state.dtype)
    if op.kind == "matrix":
        return _ap.apply_matrix(state, operands["payload"], op.targets,
                                op.controls, op.control_states)
    if op.kind == "diagonal":
        return _ap.apply_diagonal(state, operands["payload"], op.targets,
                                  op.controls, op.control_states)
    if op.kind == "x":
        return _ap.apply_pauli_x(state, op.targets[0], op.controls, op.control_states)
    if op.kind == "y":
        return _ap.apply_pauli_y(state, op.targets[0], op.controls, op.control_states)
    if op.kind == "y*":  # conjugated Y for density-matrix shadow ops
        return _ap.apply_pauli_y(state, op.targets[0], op.controls, op.control_states,
                                 conj_fac=-1)
    if op.kind == "swap":
        return _ap.swap_qubit_amps(state, op.targets[0], op.targets[1])
    if op.kind == "mrz":
        return _ap.apply_multi_rotate_z(state, operands["angle"], op.targets)
    if op.kind == "bitperm":
        # fused qubit permutation (scheduler-emitted): content of bit
        # targets[i] moves to position matrix[i] — one transpose collective
        return _ap.apply_bit_permutation(
            state, op.targets, tuple(int(d) for d in op.matrix))
    raise ValueError(f"unknown gate kind {op.kind}")


def _shadow_op(op: GateOp, n: int) -> GateOp:
    """The conjugated column-side twin of a gate for the Choi-flattened
    density matrix (same rule as the eager API's shadow, ref: QuEST.c:8-10)."""
    kind = "y*" if op.kind == "y" else op.kind
    conj_matrix = op.matrix
    if op.kind == "mrz":
        conj_matrix = (-op.matrix[0],)  # conj(exp(-i a/2 Z..Z)) = same at -a
    elif op.kind == "bitperm":
        # payload is the destination-wire list, not a matrix: shift it to the
        # column side with the targets (a real permutation is its own conj)
        conj_matrix = tuple(float(int(d) + n) for d in op.matrix)
    elif op.matrix is not None:
        p = op.payload()
        conj_matrix = tuple(np.stack([p[0], -p[1]]).ravel())
    return GateOp(kind, tuple(t + n for t in op.targets),
                  tuple(c + n for c in op.controls), op.control_states,
                  conj_matrix, op.shape)


def _apply_one_routed(state: jax.Array, op: GateOp, perm: tuple,
                      operands: dict | None = None):
    """Apply one op under a deferred logical->physical bit permutation:
    dense gates may extend the permutation instead of swapping back
    (ops/apply.py apply_matrix_routed); every other kind is position-
    agnostic and just translates its wires.  Returns (state, perm).
    ``operands`` overrides the compile-time-constant payload with traced
    arrays (the parameter-lifted path, :func:`lifted_operands`)."""
    if op.kind == "matrix":
        u = (operands["payload"] if operands is not None
             else jnp.asarray(op.payload(), dtype=state.dtype))
        return _ap.apply_matrix_routed(state, u, op.targets, op.controls,
                                       op.control_states, perm)
    if op.kind == "bitperm":
        # both the source wires AND the destination payload are logical:
        # translate each through the live routing permutation
        t = tuple(perm[q] for q in op.targets)
        d = tuple(perm[int(x)] for x in op.matrix)
        return _ap.apply_bit_permutation(state, t, d), perm
    t = tuple(perm[q] for q in op.targets)
    c = tuple(perm[q] for q in op.controls)
    if t != op.targets or c != op.controls:
        op = GateOp(op.kind, t, c, op.control_states, op.matrix, op.shape)
    return _apply_one(state, op, operands), perm


def _run_ops_routed(state: jax.Array, ops: tuple, params=None,
                    offsets: tuple | None = None) -> jax.Array:
    """Whole-program op chain with deferred routing: wide minor-block gates
    swap INTO prefix positions once and the swap-back is paid once at the
    end (reconcile) instead of per gate — on a sharded state each avoided
    pair is two avoided all-to-alls (the reference's own unfixed TODO,
    QuEST_cpu_distributed.c:1376-1379).

    With ``params`` (a traced float64 vector) and ``offsets`` (a static
    per-op offset tuple) the chain runs PARAMETER-LIFTED: each op's
    continuous payload is sliced from ``params`` instead of embedded as a
    constant, so the traced program is shared by every circuit of the
    structural class (serve/cache.py)."""
    perm = tuple(range(_ap.num_qubits_of(state)))
    for i, op in enumerate(ops):
        operands = (None if params is None
                    else lifted_operands(op, params, offsets[i], state.dtype))
        state, perm = _apply_one_routed(state, op, perm, operands)
    return _ap.reconcile_perm(state, perm)


@partial(jax.jit, static_argnames=("ops",))
def _run_ops(state: jax.Array, ops: tuple) -> jax.Array:
    return _run_ops_routed(state, ops)


def _split_engine_key(kops: tuple) -> tuple:
    """Inverse of :meth:`Circuit.key` ``engine=``: (engine, op tuple)."""
    if kops and kops[0] == ("engine", "pallas"):
        return "pallas", kops[1:]
    return "xla", kops


@partial(jax.jit, static_argnames=("kops",))
def _run_ops_engine(state: jax.Array, kops: tuple) -> jax.Array:
    """Whole-circuit program keyed on the ENGINE-TAGGED circuit key
    (:meth:`Circuit.key` ``engine=``), so the jit cache can never hand an
    XLA-lowered executable to a pallas-planned call or vice versa.  The
    pallas lowering (ops/epoch_pallas.py) runs fused aliased block/fiber
    passes with the deferred qubit map reconciled at the end, falling back
    per-window — never per-program — to the XLA gate engine for ops the
    epoch planner cannot lower."""
    engine, ops = _split_engine_key(kops)
    if engine == "pallas":
        from .ops import epoch_pallas as _ep
        return _ep.run_ops_planes(state, ops)
    return _run_ops_routed(state, ops)


@lru_cache(maxsize=256)
def _donated_program(ops: tuple, engine: str = "xla"):
    """One donating program per (op tuple, engine) — since PR 5 an adapter
    over the serve subsystem's parameter-lifted compilation cache
    (quest_tpu/serve/cache.py), so there is ONE program cache with ONE
    byte-budgeted eviction policy.  The compiled ``(state, params)``
    executable is cached there on the STRUCTURAL key
    (:meth:`Circuit.key` ``structural=True``): equal-structure circuits
    differing only in rotation angles share one XLA program, where the old
    per-op-tuple cache compiled once per angle assignment.  This wrapper
    just closes over the op tuple's concrete operand vector
    (:func:`param_vector`); an entry evicted from the serve cache
    recompiles transparently on next use.

    ``engine`` must be RESOLVED ("xla" | "pallas", never "auto") — it is
    part of the cache class key (serve/cache.py CacheOptions.engine), so an
    executable lowered through one backend is never served to a request
    planned for the other."""
    from .serve.cache import global_cache
    return global_cache().donating_runner(ops, engine=engine)


def compile_circuit(circuit: Circuit, donate: bool = False,
                    num_devices: int | None = None, overlap: bool = False,
                    pipeline_chunks: int | None = None,
                    engine: str = "auto", chip=None):
    """Return a jitted ``state -> state`` applying the whole circuit as one
    XLA program.  ``donate=True`` reuses the input buffer (allocation-free
    iteration) — callers must not hold other references to the state; the
    donated program lives in the serve layer's parameter-lifted compilation
    cache keyed on ``circuit.key(structural=True)`` (see _donated_program:
    equal-structure circuits differing only in gate payloads share one
    compiled executable).
    ``num_devices`` runs the comm-aware scheduler first
    (:meth:`Circuit.schedule`): the compiled program is the scheduled,
    collective-minimised equivalent for an ``num_devices``-way amplitude
    mesh.

    ``engine`` selects the compiled-circuit backend: ``"xla"`` is the
    per-gate/fused gate engine, ``"pallas"`` forces the in-place Pallas
    epoch executor (ops/epoch_pallas.py: fused aliased block/fiber passes
    plus a deferred qubit map — the generalized qft_inplace machinery) and
    the default ``"auto"`` resolves through the planner's engine cost model
    (parallel/planner.py ``select_engine``, scored on ``chip`` — default
    v5e) BEFORE anything is keyed, so the resolved engine is part of every
    program/cache identity (:meth:`Circuit.key` ``engine=``).  The epoch
    engine is single-device (its deferred permutation must materialize
    before sharded collectives — docs/DESIGN.md); forcing it on a mesh
    raises ``E_INVALID_SCHEDULE_OPTION``.  The returned function carries
    the decision as ``run.engine`` / ``run.engine_plan`` (the auditable
    per-epoch lowering) and, when the epoch engine is resolved, a
    plane-pair entry ``run.planes(re, im) -> (re, im)`` that applies the
    same plan to plane storage with no (2, N) stack anywhere — both
    planes donated when ``donate=True`` (``run.planes`` is None on the
    XLA engine).  A non-f32
    state falls back to the XLA program at call time — the epoch engine is
    f32-only.

    ``overlap=True`` (implied by ``pipeline_chunks``) additionally lowers
    the scheduled circuit through the pipelined executor
    (parallel/executor.py): every cross-shard collective is split into
    ``pipeline_chunks`` independent chunked collectives issued while the
    gate run computes the previous chunk, so XLA's async start/done
    scheduling hides ICI time behind HBM/MXU work.  Requires
    ``num_devices``; a bad chunk count raises
    ``E_INVALID_SCHEDULE_OPTION``.  Overlapped programs carry a device
    mesh and are NOT cached on ``circuit.key()`` — hold on to the returned
    function."""
    from .parallel import planner as _planner
    if overlap or pipeline_chunks is not None:
        from .validation import MESSAGES, ErrorCode, QuESTError
        if num_devices is None:
            raise QuESTError(
                ErrorCode.INVALID_SCHEDULE_OPTION,
                MESSAGES[ErrorCode.INVALID_SCHEDULE_OPTION]
                + " overlap=True requires num_devices=.", "compile_circuit")
        if engine == "pallas":
            # the pipelined executor is an XLA-engine lowering: its chunked
            # collectives are exactly what the epoch engine's deferred
            # qubit map cannot coexist with (docs/DESIGN.md)
            raise QuESTError(
                ErrorCode.INVALID_SCHEDULE_OPTION,
                MESSAGES[ErrorCode.INVALID_SCHEDULE_OPTION]
                + " engine='pallas' unavailable with overlap=True.",
                "compile_circuit")
        from .parallel import executor as _exec
        circuit = circuit.schedule(num_devices, overlap=True,
                                   pipeline_chunks=pipeline_chunks)
        return _exec.overlapped_program(circuit, num_devices, donate=donate)
    with _obs.span("circuit.compile", ops=len(circuit.ops),
                   num_devices=num_devices or 1) as _csp:
        if num_devices is not None and num_devices > 1:
            choice = _planner.select_engine(circuit, num_devices,
                                            chip or _planner.V5E,
                                            requested=engine)
            circuit = circuit.schedule(num_devices)
        else:
            choice = _planner.select_engine(circuit, 1, chip or _planner.V5E,
                                            requested=engine)
        if _csp is not None:
            _csp.attrs["engine"] = choice["engine"]
    resolved = choice["engine"]
    ops = circuit.key()
    if donate:
        shared = _donated_program(ops, resolved)

        # fresh wrapper per call: the underlying program is lru-shared
        # across equal (ops, engine) keys, but the engine metadata set
        # below belongs to THIS call's selection — mutating the shared
        # closure would rewrite attributes held by earlier callers
        def run(state: jax.Array) -> jax.Array:
            return shared(state)
    elif resolved == "pallas":
        kops = circuit.key(engine="pallas")

        def run(state: jax.Array) -> jax.Array:
            if state.dtype != jnp.float32:   # f32-only engine: fall back
                return _run_ops(state, ops)
            # x64 off while tracing: the Mosaic lowering constraint shared
            # by every in-place engine (safe: mrz phases precompute in f64
            # host-side, so no traced f64 operand exists — epoch_pallas)
            with _compat.enable_x64(False):
                return _run_ops_engine(state, kops)
    else:
        def run(state: jax.Array) -> jax.Array:
            return _run_ops(state, ops)

    inner = run

    def traced(state: jax.Array) -> jax.Array:
        # free when tracing is off; an enabled run records a circuit.run
        # span (and the matching XProf TraceAnnotation) around dispatch,
        # and folds the host-side dispatch wall into the runtime counters
        # (obs/counters.py) so the scrape reports dispatch totals next to
        # compile totals
        if not _obs.tracing_enabled():
            return inner(state)
        with _obs.span("circuit.run", engine=resolved,
                       ops=len(circuit.ops)) as sp:
            out = inner(state)
        if sp is not None:
            _obs.record_dispatch(sp.dur)
        return out

    traced.engine = resolved
    traced.engine_reason = choice["reason"]
    traced.engine_plan = choice["plan"]
    traced.engine_calibration = choice.get("calibration")
    # plane-pair entry (epoch engine only): ``run.planes(re, im)`` applies
    # the same plan to (re, im) plane storage with the residual qubit map
    # reconciled per plane and no (2, N) stack anywhere — both planes
    # donated under donate=True, the truly in-place path plane-storage
    # registers need at the 30q single-chip ceiling (ops/epoch_pallas.py
    # jit_program_planes; aliasing audited by analysis.audit_epoch_donation)
    if resolved == "pallas":
        from .serve.cache import global_cache
        traced.planes = global_cache().epoch_plane_runner(ops, donate=donate)
    else:
        traced.planes = None
    return traced


def apply_circuit(qureg, circuit: Circuit) -> None:
    """Apply a compiled circuit to a Qureg (statevector path; density quregs
    get the conjugated shadow ops, cached per (circuit, n)).  A
    :class:`DensityCircuit` is ALREADY Choi-doubled (shadows and channel
    superoperators recorded inline), so it runs as-is on a density qureg of
    the matching width — the path noise channels ride."""
    density_n = getattr(circuit, "density_qubits", None)
    if density_n is not None:
        from .validation import MESSAGES, ErrorCode, QuESTError
        if (not qureg.is_density_matrix
                or qureg.num_qubits_represented != density_n):
            raise QuESTError(
                ErrorCode.MISMATCHING_QUREG_DIMENSIONS,
                MESSAGES[ErrorCode.MISMATCHING_QUREG_DIMENSIONS]
                + f" (DensityCircuit of {density_n} density "
                "qubits needs a density qureg of the same width.)",
                "apply_circuit")
        qureg.amps = _run_ops(qureg.amps, circuit.key())
        return
    if qureg.is_density_matrix:
        n = qureg.num_qubits_represented
        src = circuit.key()
        # cache keyed on (n, source ops): appending gates after a previous
        # density application must rebuild the shadow list (tuple equality
        # short-circuits on element identity, so a hit is O(len) pointer
        # compares)
        cache = getattr(circuit, "_shadow_cache", None)
        if cache is None or cache[0] != n or cache[1] != src:
            ops = []
            for op in src:
                ops.append(op)
                ops.append(_shadow_op(op, n))
            cache = (n, src, tuple(ops))
            circuit._shadow_cache = cache
        qureg.amps = _run_ops(qureg.amps, cache[2])
    else:
        qureg.amps = _run_ops(qureg.amps, circuit.key())


# ---------------------------------------------------------------------------
# circuit generators (benchmark workloads; ref analogue: the random-circuit
# and QFT configs in BASELINE.md)
# ---------------------------------------------------------------------------

def random_circuit(num_qubits: int, depth: int, seed: int = 0,
                   entangle: bool = True) -> Circuit:
    """Depth layers of Haar-random single-qubit gates + a CZ ladder — the
    standard random-circuit benchmark (BASELINE.md: 20q Clifford+T / 34q
    random circuit)."""
    rng = np.random.default_rng(seed)
    c = Circuit(num_qubits)
    for layer in range(depth):
        for q in range(num_qubits):
            g = rng.normal(size=(2, 2)) + 1j * rng.normal(size=(2, 2))
            u, r = np.linalg.qr(g)
            u = u * (np.diag(r) / np.abs(np.diag(r)))
            c.unitary(q, u)
        if entangle:
            for q in range(layer % 2, num_qubits - 1, 2):
                c.cz(q, q + 1)
    return c


def qft_circuit(num_qubits: int) -> Circuit:
    """Quantum Fourier transform: H + controlled-phase ladder + reversal swaps
    (BASELINE.md config 5: 28q QFT — the distributed diagonal-gate path)."""
    c = Circuit(num_qubits)
    for q in range(num_qubits - 1, -1, -1):
        c.h(q)
        for j in range(q):
            c.phase_shift(q, math.pi / (1 << (q - j)), controls=(j,))
    for q in range(num_qubits // 2):
        c.swap(q, num_qubits - 1 - q)
    return c
