"""User-facing value types: complex matrices, Pauli Hamiltonians, diagonal ops.

Ref analogues: ``Complex``/``ComplexMatrix2/4/N`` (QuEST.h:103-141),
``Vector`` (QuEST.h:148-151), ``PauliHamil`` (QuEST.h:158-169),
``DiagonalOp`` (QuEST.h:178-194), ``enum pauliOpType`` (QuEST.h:96).

The reference stores matrices as separate real/imag 2-D C arrays (a C99
constraint); here a matrix is simply a complex ndarray, and the constructors
below exist for source-level familiarity (`ComplexMatrix2(real=.., imag=..)`)
and for the file-based PauliHamil loader.
"""

from __future__ import annotations

import dataclasses
import enum

import jax
import jax.numpy as jnp
import numpy as np

from .validation import ErrorCode, QuESTError, _throw, validate_diag_op_elems


class PauliOpType(enum.IntEnum):
    PAULI_I = 0
    PAULI_X = 1
    PAULI_Y = 2
    PAULI_Z = 3


PAULI_I = PauliOpType.PAULI_I
PAULI_X = PauliOpType.PAULI_X
PAULI_Y = PauliOpType.PAULI_Y
PAULI_Z = PauliOpType.PAULI_Z

# dense 2x2 Pauli matrices, indexed by code
PAULI_MATRICES = np.stack([
    np.eye(2, dtype=np.complex128),
    np.array([[0, 1], [1, 0]], dtype=np.complex128),
    np.array([[0, -1j], [1j, 0]], dtype=np.complex128),
    np.array([[1, 0], [0, -1]], dtype=np.complex128),
])


def Complex(real: float = 0.0, imag: float = 0.0) -> complex:
    """Ref analogue: Complex struct (QuEST.h:103)."""
    return complex(real, imag)


def Vector(x: float, y: float, z: float):
    """Ref analogue: Vector (QuEST.h:148-151)."""
    return (float(x), float(y), float(z))


def _matrix_from_parts(real, imag, dim: int) -> np.ndarray:
    if real is None and imag is None:
        return np.zeros((dim, dim), dtype=np.complex128)
    r = np.zeros((dim, dim)) if real is None else np.asarray(real, dtype=np.float64)
    i = np.zeros((dim, dim)) if imag is None else np.asarray(imag, dtype=np.float64)
    m = r + 1j * i
    if m.shape != (dim, dim):
        raise QuESTError(ErrorCode.INVALID_UNITARY_SIZE,
                         f"expected a {dim}x{dim} matrix, got shape {m.shape}")
    return m


def ComplexMatrix2(real=None, imag=None) -> np.ndarray:
    return _matrix_from_parts(real, imag, 2)


def ComplexMatrix4(real=None, imag=None) -> np.ndarray:
    return _matrix_from_parts(real, imag, 4)


def create_complex_matrix_n(num_qubits: int) -> np.ndarray:
    """Ref analogue: createComplexMatrixN (QuEST.c) — a zeroed 2^n x 2^n matrix."""
    if num_qubits < 1:
        _throw(ErrorCode.INVALID_NUM_QUBITS, "createComplexMatrixN")
    return np.zeros((2 ** num_qubits, 2 ** num_qubits), dtype=np.complex128)


def init_complex_matrix_n(m: np.ndarray, real, imag) -> None:
    """Ref analogue: initComplexMatrixN — in-place fill from re/im parts."""
    m[...] = np.asarray(real, dtype=np.float64) + 1j * np.asarray(imag, dtype=np.float64)


def as_matrix(u, num_targets: int) -> np.ndarray:
    """Coerce any user matrix (ndarray / nested lists / jnp) to complex ndarray."""
    m = np.asarray(u, dtype=np.complex128)
    dim = 2 ** num_targets
    if m.shape != (dim, dim):
        _throw(ErrorCode.INVALID_UNITARY_SIZE)
    return m


# ---------------------------------------------------------------------------
# PauliHamil
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class PauliHamil:
    """Weighted sum of Pauli strings (ref: PauliHamil, QuEST.h:158-169)."""

    num_qubits: int
    num_sum_terms: int
    # shape (num_sum_terms, num_qubits), int codes 0..3
    pauli_codes: np.ndarray = None
    # shape (num_sum_terms,), real
    term_coeffs: np.ndarray = None

    def __post_init__(self):
        if self.pauli_codes is None:
            self.pauli_codes = np.zeros((self.num_sum_terms, self.num_qubits), dtype=np.int32)
        if self.term_coeffs is None:
            self.term_coeffs = np.zeros(self.num_sum_terms, dtype=np.float64)


def create_pauli_hamil(num_qubits: int, num_sum_terms: int) -> PauliHamil:
    if num_qubits < 1 or num_sum_terms < 1:
        _throw(ErrorCode.INVALID_PAULI_HAMIL_PARAMS, "createPauliHamil")
    return PauliHamil(num_qubits, num_sum_terms)


def init_pauli_hamil(hamil: PauliHamil, coeffs, codes) -> None:
    """Ref analogue: initPauliHamil — codes is the flat
    [term0 qubit0..qubitN-1, term1 ...] layout of the reference."""
    # validate BEFORE narrowing: invalid codes may be far outside int32
    # (e.g. (enum)-1 arrives as 2^32-1 through the C shim's unsigned enum)
    codes = np.asarray(codes, dtype=np.int64).reshape(hamil.num_sum_terms, hamil.num_qubits)
    for c in codes.ravel():
        if c not in (0, 1, 2, 3):
            _throw(ErrorCode.INVALID_PAULI_CODE, "initPauliHamil")
    codes = codes.astype(np.int32)
    hamil.term_coeffs = np.asarray(coeffs, dtype=np.float64).reshape(hamil.num_sum_terms)
    hamil.pauli_codes = codes


def create_pauli_hamil_from_file(fn: str) -> PauliHamil:
    """Parse the reference's plain-text format: each line is a coefficient
    followed by one Pauli code per qubit (ref: createPauliHamilFromFile,
    QuEST.c:1169-1251).  Qubit count is inferred from the first line."""
    try:
        with open(fn) as f:
            lines = [ln.split() for ln in f if ln.strip()]
    except OSError:
        _throw(ErrorCode.CANNOT_OPEN_FILE, "createPauliHamilFromFile", fn)
    if not lines:
        _throw(ErrorCode.INVALID_PAULI_HAMIL_FILE_PARAMS, "createPauliHamilFromFile", fn)
    num_qubits = len(lines[0]) - 1
    num_terms = len(lines)
    if num_qubits < 1:
        _throw(ErrorCode.INVALID_PAULI_HAMIL_FILE_PARAMS, "createPauliHamilFromFile", fn)
    coeffs = np.zeros(num_terms)
    codes = np.zeros((num_terms, num_qubits), dtype=np.int32)
    for t, tok in enumerate(lines):
        try:
            coeffs[t] = float(tok[0])
        except (ValueError, IndexError):
            _throw(ErrorCode.CANNOT_PARSE_PAULI_HAMIL_FILE_COEFF, "createPauliHamilFromFile", fn)
        if len(tok) != num_qubits + 1:
            _throw(ErrorCode.CANNOT_PARSE_PAULI_HAMIL_FILE_PAULI, "createPauliHamilFromFile", fn)
        for q in range(num_qubits):
            try:
                code = int(tok[1 + q])
            except ValueError:
                _throw(ErrorCode.CANNOT_PARSE_PAULI_HAMIL_FILE_PAULI, "createPauliHamilFromFile", fn)
            if code not in (0, 1, 2, 3):
                _throw(ErrorCode.INVALID_PAULI_HAMIL_FILE_PAULI_CODE,
                       "createPauliHamilFromFile", fn, code)
            codes[t, q] = code
    hamil = PauliHamil(num_qubits, num_terms)
    init_pauli_hamil(hamil, coeffs, codes)
    return hamil


def destroy_pauli_hamil(hamil: PauliHamil) -> None:
    """Ref analogue: destroyPauliHamil — GC handles it; kept for API parity."""


def report_pauli_hamil(hamil: PauliHamil) -> None:
    for t in range(hamil.num_sum_terms):
        codes = "\t".join(str(int(c)) for c in hamil.pauli_codes[t])
        print(f"{hamil.term_coeffs[t]}\t{codes}")


# ---------------------------------------------------------------------------
# DiagonalOp
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class DiagonalOp:
    """Distributed 2^N-element diagonal operator (ref: DiagonalOp, QuEST.h:178-194).

    Stored as a (2, 2^N) SoA real pair sharded identically to a same-size
    Qureg, so elementwise application needs no resharding."""

    num_qubits: int
    env: object
    amps: jax.Array | None = None


def create_diagonal_op(num_qubits: int, env) -> DiagonalOp:
    if num_qubits < 1:
        _throw(ErrorCode.INVALID_NUM_CREATE_QUBITS, "createDiagonalOp")
    if num_qubits > 63:  # calcLog2(SIZE_MAX): elements must index in size_t
        _throw(ErrorCode.NUM_AMPS_EXCEED_TYPE, "createDiagonalOp")
    if 2 ** num_qubits < env.num_ranks:
        _throw(ErrorCode.DISTRIB_DIAG_OP_TOO_SMALL, "createDiagonalOp")
    from .precision import CONFIG
    amps = jnp.zeros((2, 2 ** num_qubits), dtype=CONFIG.real_dtype)
    if env.sharding is not None:
        amps = jax.device_put(amps, env.sharding)
    return DiagonalOp(num_qubits, env, amps)


def destroy_diagonal_op(op: DiagonalOp, env=None) -> None:
    op.amps = None


def sync_diagonal_op(op: DiagonalOp) -> None:
    """Ref analogue: syncDiagonalOp (host->GPU copy) — jax arrays are already
    device-resident; block for completeness."""
    if op.amps is not None:
        op.amps.block_until_ready()


def init_diagonal_op(op: DiagonalOp, real, imag) -> None:
    re = np.asarray(real, dtype=np.float64).ravel()
    im = np.asarray(imag, dtype=np.float64).ravel()
    if re.shape != (2 ** op.num_qubits,) or im.shape != (2 ** op.num_qubits,):
        _throw(ErrorCode.INVALID_NUM_ELEMS, "initDiagonalOp")
    new = jnp.asarray(np.stack([re, im]), dtype=op.amps.dtype)
    if op.env.sharding is not None:
        new = jax.device_put(new, op.env.sharding)
    op.amps = new


def set_diagonal_op_elems(op: DiagonalOp, start_ind: int, real, imag, num_elems: int) -> None:
    validate_diag_op_elems(op, start_ind, num_elems, "setDiagonalOpElems")
    re = np.asarray(real, dtype=np.float64).ravel()[:num_elems]
    im = np.asarray(imag, dtype=np.float64).ravel()[:num_elems]
    new = op.amps.at[:, start_ind:start_ind + num_elems].set(
        jnp.asarray(np.stack([re, im]), dtype=op.amps.dtype))
    if op.env.sharding is not None:
        new = jax.device_put(new, op.env.sharding)
    op.amps = new
