"""The public, reference-compatible API surface.

Every function follows the reference's dispatch contract
(ref: QuEST/src/QuEST.c:5-10): validate inputs, invoke the backend op, apply
the density-matrix shadow op (the conjugated gate on the column-side qubits,
ref: QuEST.c:8-10 and e.g. rotateX at :188-197), and record QASM.

Names are exported in both the reference's camelCase (``hadamard``,
``controlledNot``, ``calcExpecPauliHamil``…) and used internally in
snake_case.  The backend is the functional op layer in ``quest_tpu.ops`` —
pure jitted jnp programs over (possibly sharded) amplitude arrays.
"""

from __future__ import annotations

import math
import os

import jax
import jax.numpy as jnp
import numpy as np

from . import rng
from .environment import (QuESTEnv, create_quest_env, destroy_quest_env,
                          get_environment_string, report_quest_env,
                          sync_quest_env, sync_quest_success)
from .matrices import (PAULI_MATRICES, Complex, ComplexMatrix2, ComplexMatrix4,
                       DiagonalOp, PauliHamil, PauliOpType, Vector, as_matrix,
                       create_complex_matrix_n, create_diagonal_op,
                       create_pauli_hamil, create_pauli_hamil_from_file,
                       destroy_diagonal_op, destroy_pauli_hamil,
                       init_complex_matrix_n, init_diagonal_op,
                       init_pauli_hamil, report_pauli_hamil,
                       set_diagonal_op_elems, sync_diagonal_op)
from .ops import apply as _ap
from .ops import calc as _calc
from .ops import decoherence as _deco
from .ops import init as _init
from .ops import measure as _meas
from .precision import real_eps
from . import qureg as _qureg_mod
from .qureg import (Qureg, create_clone_qureg, create_density_qureg,
                    create_qureg, destroy_qureg)
from . import validation as V
from .validation import QuESTError

__all__ = [
    # environment
    "createQuESTEnv", "destroyQuESTEnv", "syncQuESTEnv", "syncQuESTSuccess",
    "reportQuESTEnv", "getEnvironmentString", "seedQuEST", "seedQuESTDefault",
    # registers
    "createQureg", "createDensityQureg", "createCloneQureg", "destroyQureg",
    "getNumQubits", "getNumAmps", "reportQuregParams",
    # matrices / hamiltonians / diagonal ops
    "createComplexMatrixN", "destroyComplexMatrixN", "initComplexMatrixN",
    "createPauliHamil", "destroyPauliHamil", "createPauliHamilFromFile",
    "initPauliHamil", "reportPauliHamil",
    "createDiagonalOp", "destroyDiagonalOp", "syncDiagonalOp",
    "initDiagonalOp", "setDiagonalOpElems", "applyDiagonalOp",
    "calcExpecDiagonalOp",
    # init
    "initBlankState", "initZeroState", "initPlusState", "initClassicalState",
    "initPureState", "initDebugState", "initStateFromAmps", "setAmps",
    "cloneQureg", "setDensityAmps",
    # amplitude access
    "getAmp", "getRealAmp", "getImagAmp", "getProbAmp", "getDensityAmp",
    # unitaries & gates
    "compactUnitary", "unitary", "rotateX", "rotateY", "rotateZ",
    "rotateAroundAxis", "controlledRotateX", "controlledRotateY",
    "controlledRotateZ", "controlledRotateAroundAxis",
    "controlledCompactUnitary", "controlledUnitary", "multiControlledUnitary",
    "multiStateControlledUnitary", "pauliX", "pauliY", "pauliZ", "hadamard",
    "sGate", "tGate", "phaseShift", "controlledPhaseShift",
    "multiControlledPhaseShift", "controlledPhaseFlip",
    "multiControlledPhaseFlip", "controlledNot", "controlledPauliY",
    "swapGate", "sqrtSwapGate", "multiRotateZ", "multiRotatePauli",
    "twoQubitUnitary", "controlledTwoQubitUnitary",
    "multiControlledTwoQubitUnitary", "multiQubitUnitary",
    "controlledMultiQubitUnitary", "multiControlledMultiQubitUnitary",
    # measurement
    "calcProbOfOutcome", "collapseToOutcome", "measure", "measureWithStats",
    "calcProbOfAllOutcomes", "sampleOutcomes",
    "calcPartialTrace", "calcVonNeumannEntropy",
    # calculations
    "calcTotalProb", "calcInnerProduct", "calcDensityInnerProduct",
    "calcPurity", "calcFidelity", "calcHilbertSchmidtDistance",
    "calcExpecPauliProd", "calcExpecPauliSum", "calcExpecPauliHamil",
    # numeric-health helpers (QuEST's calcTotalProb runtime-sanity
    # surface, snake-case; obs/numerics.py is the telemetry twin)
    "calc_total_prob", "calc_purity", "calc_fidelity",
    # decoherence
    "mixDephasing", "mixTwoQubitDephasing", "mixDepolarising", "mixDamping",
    "mixTwoQubitDepolarising", "mixPauli", "mixKrausMap", "mixTwoQubitKrausMap",
    "mixMultiQubitKrausMap", "mixDensityMatrix",
    # operators
    "applyPauliSum", "applyPauliHamil", "applyTrotterCircuit",
    "applyQFT", "applyFullQFT", "applyMatrix2",
    "applyMatrix4", "applyMatrixN", "applyMultiControlledMatrixN",
    "setWeightedQureg",
    # QASM
    "startRecordingQASM", "stopRecordingQASM", "clearRecordedQASM",
    "printRecordedQASM", "writeRecordedQASMToFile",
    # reporting / debug
    "reportState", "reportStateToScreen", "copyStateToGPU", "copyStateFromGPU",
    "initStateDebug", "compareStates", "initStateOfSingleQubit",
    "initStateFromSingleFile", "QuESTPrecision",
    # types
    "Qureg", "QuESTEnv", "Complex", "ComplexMatrix2", "ComplexMatrix4",
    "Vector", "PauliHamil", "DiagonalOp", "PauliOpType", "QuESTError",
    "fromComplex", "toComplex", "getStaticComplexMatrixN",
]


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _ts(x) -> tuple:
    """Normalise a qubit / list of qubits to a tuple of ints."""
    if isinstance(x, (int, np.integer)):
        return (int(x),)
    return tuple(int(q) for q in x)


def _shift(ts: tuple, n: int) -> tuple:
    return tuple(t + n for t in ts)


# Opt-in cache-pressure valve for workloads that compile an unbounded stream
# of distinct gate arrangements (e.g. the reference's generator-driven Catch2
# suite: thousands of unique (targets, controls, states) programs exhaust the
# process mmap budget long before RAM). Every N dispatched ops, drop all
# compiled programs; they recompile on demand.
_CLEAR_EVERY = int(os.environ.get("QUEST_TPU_CLEAR_CACHES_EVERY", "0"))
_op_count = [0]


def _maybe_clear_caches() -> None:
    if _CLEAR_EVERY:
        _op_count[0] += 1
        if _op_count[0] % _CLEAR_EVERY == 0:
            jax.clear_caches()


def _pinned(qureg: Qureg, state, fn, dyn: tuple, statics: tuple):
    """Dispatch one op program with the env sharding pinned inside it
    (ops/apply.py constrained_op): the eager multi-device path then never
    needs the Qureg setter's corrective resharding pass — `_repin` stays a
    debug fallback (its invocation count is asserted zero by the
    distributed tests)."""
    sh = qureg.env.sharding if qureg.env is not None else None
    if sh is None:
        return fn(state, *dyn, *statics)
    return _ap.constrained_op(state, tuple(dyn), fn, tuple(statics), sh)


def _apply_unitary(qureg: Qureg, u, targets, controls=(), control_states=()):
    _maybe_clear_caches()
    """Gate + conjugated shadow on the column side for density matrices
    (ref: QuEST.c:8-10).  ``u`` is a complex host matrix; the op layer takes
    (2, d, d) real pairs.  Density matrices dispatch ONE fused program for
    gate + shadow (apply_matrix_density) instead of two."""
    up = _ap.mat_pair(u)
    if qureg._planes is not None and qureg.uses_plane_storage():
        _apply_unitary_planes(qureg, up, tuple(targets), tuple(controls))
        return
    if qureg.is_density_matrix:
        qureg.amps = _pinned(
            qureg, qureg.amps, _ap.apply_matrix_density, (jnp.asarray(up),),
            (tuple(targets), tuple(controls), tuple(control_states),
             qureg.num_qubits_represented))
    else:
        # apply_matrix keeps the eager Pallas fast-path dispatch on a single
        # device; traced inside constrained_op its Pallas branch self-skips
        qureg.amps = _pinned(
            qureg, qureg.amps, _ap.apply_matrix, (jnp.asarray(up),),
            (tuple(targets), tuple(controls), tuple(control_states)))


def _apply_unitary_planes(qureg: Qureg, up, targets, controls):
    """Plane-storage gate path (the 30q single-chip ceiling): single-qubit
    dense gates run through the in-place Pallas engine
    (ops/pallas_layer.apply_1q_gate_planes, one donated HBM pass); anything
    wider needs the stacked engine, whose extra state copy is exactly what
    this regime cannot hold."""
    from .ops import pallas_layer as _pl

    if len(targets) != 1 or controls:
        V._throw("E_PLANE_ONLY_1Q", "applyUnitary")
    target = qureg.logical_to_physical(targets[0])
    re, im = qureg.take_planes()
    re, im = _pl.apply_1q_gate_planes(re, im, up, target)
    qureg.set_planes(re, im, qureg.qubit_map)


def _diag_pair(diag) -> np.ndarray:
    d = np.asarray(diag, dtype=np.complex128)
    return np.stack([d.real, d.imag])


def _apply_diag(qureg: Qureg, diag, targets, controls=(), control_states=()):
    _maybe_clear_caches()
    dp = _diag_pair(diag)
    if qureg._planes is not None and qureg.uses_plane_storage():
        # a 1q diagonal is a dense 2x2; reuse the plane-mode gate path
        if len(dp[0]) != 2 or len(targets) != 1 or controls:
            V._throw("E_PLANE_ONLY_1Q", "applyDiagonal")
        up = np.stack([np.diag(dp[0]), np.diag(dp[1])])
        _apply_unitary_planes(qureg, up, tuple(targets), ())
        return
    if qureg.is_density_matrix:
        qureg.amps = _pinned(
            qureg, qureg.amps, _ap.apply_diagonal_density, (jnp.asarray(dp),),
            (tuple(targets), tuple(controls), tuple(control_states),
             qureg.num_qubits_represented))
    else:
        qureg.amps = _pinned(
            qureg, qureg.amps, _ap.apply_diagonal, (jnp.asarray(dp),),
            (tuple(targets), tuple(controls), tuple(control_states)))


def _rotation_matrix(angle: float, axis) -> np.ndarray:
    """R(θ, n̂) = cos(θ/2) I − i sin(θ/2) n̂·σ (ref: getComplexPairFromRotation,
    QuEST_common.c)."""
    ux, uy, uz = axis
    norm = math.sqrt(ux * ux + uy * uy + uz * uz)
    ux, uy, uz = ux / norm, uy / norm, uz / norm
    c, s = math.cos(angle / 2), math.sin(angle / 2)
    return np.array([[c - 1j * s * uz, (-1j * ux - uy) * s],
                     [(-1j * ux + uy) * s, c + 1j * s * uz]], dtype=np.complex128)


def _compact_matrix(alpha: complex, beta: complex) -> np.ndarray:
    return np.array([[alpha, -np.conj(beta)], [beta, np.conj(alpha)]],
                    dtype=np.complex128)


# ---------------------------------------------------------------------------
# environment & registers
# ---------------------------------------------------------------------------

createQuESTEnv = create_quest_env
destroyQuESTEnv = destroy_quest_env
syncQuESTEnv = sync_quest_env
syncQuESTSuccess = sync_quest_success
reportQuESTEnv = report_quest_env
getEnvironmentString = get_environment_string

createQureg = create_qureg
createDensityQureg = create_density_qureg
createCloneQureg = create_clone_qureg
destroyQureg = destroy_qureg

createComplexMatrixN = create_complex_matrix_n
initComplexMatrixN = init_complex_matrix_n
createPauliHamil = create_pauli_hamil
destroyPauliHamil = destroy_pauli_hamil
createPauliHamilFromFile = create_pauli_hamil_from_file
initPauliHamil = init_pauli_hamil
reportPauliHamil = report_pauli_hamil
createDiagonalOp = create_diagonal_op
destroyDiagonalOp = destroy_diagonal_op
syncDiagonalOp = sync_diagonal_op
initDiagonalOp = init_diagonal_op
setDiagonalOpElems = set_diagonal_op_elems


def destroyComplexMatrixN(m) -> None:
    """Ref parity only — ndarray lifetime is GC-managed."""


def fromComplex(c) -> complex:
    """Ref analogue: fromComplex macro (QuEST_complex.h) — Complex -> qcomp."""
    return complex(c)


def toComplex(c) -> complex:
    """Ref analogue: toComplex macro (QuEST_complex.h) — qcomp -> Complex."""
    return complex(c)


def getStaticComplexMatrixN(real, imag) -> np.ndarray:
    """Ref analogue: getStaticComplexMatrixN macro (QuEST.h) — build a
    ComplexMatrixN from nested real/imag lists without explicit create/destroy."""
    r = np.asarray(real, dtype=np.float64)
    i = np.asarray(imag, dtype=np.float64)
    return r + 1j * i


def seedQuEST(seed_array, num_seeds: int | None = None):
    """Seed the global MT19937 (ref: seedQuEST, QuEST_common.c:209-214).

    Multi-process contract: in a multi-host run EVERY process must call this
    with the SAME seed array (the reference requires the same: its seedQuEST
    is rank-local and only the *default* path broadcasts).  Identical seeds
    keep every rank's measurement-outcome stream in lockstep, which a shared
    sharded state depends on.  ``seedQuESTDefault`` handles the broadcast
    automatically."""
    if num_seeds is not None:
        seed_array = list(seed_array)[:num_seeds]
    rng.seed_quest(seed_array)


def seedQuESTDefault():
    """Default seeding from [msec-time, pid], broadcast from process 0 to all
    processes in a multi-host run so all ranks draw identical outcomes
    (ref: QuEST_common.c:182-204 + MPI_Bcast at
    QuEST_cpu_distributed.c:1318-1329)."""
    rng.seed_quest_default()


def getNumQubits(qureg: Qureg) -> int:
    return qureg.num_qubits_represented


def getNumAmps(qureg: Qureg) -> int:
    V.validate_state_vec_qureg(qureg, "getNumAmps")
    return qureg.num_amps_total


def reportQuregParams(qureg: Qureg) -> None:
    """Ref: reportQuregParams (QuEST_common.c:234-243)."""
    print("QUBITS:")
    print(f"Number of qubits is {qureg.num_qubits_represented}.")
    print(f"Number of amps is {qureg.num_amps_total}.")
    num_chunks = getattr(qureg.env, "num_ranks", 1) or 1
    print(f"Number of amps per rank is {qureg.num_amps_total // num_chunks}.")


# ---------------------------------------------------------------------------
# state initialisation
# ---------------------------------------------------------------------------

def _pinned_init(qureg: Qureg, fn, statics: tuple):
    """Initial states generated directly in the env sharding (each device
    fills only its own window; no separate placement pass)."""
    sh = qureg.env.sharding if qureg.env is not None else None
    return _init.build_state(fn, statics, sh)


def initBlankState(qureg: Qureg) -> None:
    if qureg.uses_plane_storage():
        qureg._planes = None  # free the old planes BEFORE allocating new
        qureg.set_planes(*_init.blank_state_planes(qureg.num_amps_total,
                                                   qureg.dtype))
    else:
        qureg.set_amps_array(_pinned_init(qureg, _init.blank_state, (qureg.num_amps_total, qureg.dtype)))
    qureg.qasm.record_comment("Here, the register was initialised to an unphysical all-zero-amplitudes state.")


def initZeroState(qureg: Qureg) -> None:
    if qureg.uses_plane_storage():
        qureg._planes = None  # free the old planes BEFORE allocating new
        qureg.set_planes(*_init.zero_state_planes(qureg.num_amps_total,
                                                  qureg.dtype))
    else:
        qureg.set_amps_array(_pinned_init(qureg, _init.zero_state, (qureg.num_amps_total, qureg.dtype)))
    qureg.qasm.record_init_zero()


def initPlusState(qureg: Qureg) -> None:
    if qureg.is_density_matrix:
        qureg.set_amps_array(_pinned_init(
            qureg, _init.densmatr_plus_state,
            (qureg.num_qubits_represented, qureg.dtype)))
    elif qureg.uses_plane_storage():
        qureg._planes = None  # free the old planes BEFORE allocating new
        qureg.set_planes(*_init.plus_state_planes(qureg.num_amps_total,
                                                  qureg.dtype))
    else:
        qureg.set_amps_array(_pinned_init(
            qureg, _init.plus_state, (qureg.num_amps_total, qureg.dtype)))
    qureg.qasm.record_init_plus()


def initClassicalState(qureg: Qureg, state_ind: int) -> None:
    V.validate_state_index(qureg, state_ind, "initClassicalState")
    if qureg.is_density_matrix:
        qureg.set_amps_array(_pinned_init(
            qureg, _init.densmatr_classical_state,
            (qureg.num_qubits_represented, int(state_ind), qureg.dtype)))
    elif qureg.uses_plane_storage():
        qureg._planes = None  # free the old planes BEFORE allocating new
        qureg.set_planes(*_init.classical_state_planes(
            qureg.num_amps_total, int(state_ind), qureg.dtype))
    else:
        qureg.set_amps_array(_pinned_init(
            qureg, _init.classical_state,
            (qureg.num_amps_total, int(state_ind), qureg.dtype)))
    qureg.qasm.record_init_classical(int(state_ind))


def initPureState(qureg: Qureg, pure: Qureg) -> None:
    """Ref: initPureState (QuEST.c) — copy ψ, or form ρ=|ψ><ψ|."""
    V.validate_second_qureg_state_vec(pure, "initPureState")
    V.validate_matching_qureg_dims(qureg, pure, "initPureState")
    if qureg.is_density_matrix:
        qureg.set_amps_array(_init.densmatr_pure_state(
            pure.amps, qureg.num_qubits_represented).astype(qureg.dtype))
    else:
        qureg.set_amps_array(pure.amps.astype(qureg.dtype))
    qureg.qasm.record_comment("Here, the register was initialised to an undisclosed given pure state.")


def initDebugState(qureg: Qureg) -> None:
    qureg.set_amps_array(_init.debug_state(qureg.num_amps_total, qureg.dtype))
    qureg.qasm.record_comment("Here, the register was initialised to an undisclosed debugging state.")


initStateDebug = initDebugState


def initStateOfSingleQubit(qureg: Qureg, qubit_id: int, outcome: int) -> None:
    """Debug API (ref: QuEST_debug.h:25-54)."""
    V.validate_state_vec_qureg(qureg, "initStateOfSingleQubit")
    V.validate_target(qureg, qubit_id, "initStateOfSingleQubit")
    V.validate_outcome(outcome, "initStateOfSingleQubit")
    qureg.set_amps_array(_init.state_of_single_qubit(
        qureg.num_qubits_in_state_vec, int(qubit_id), int(outcome), qureg.dtype))


def _soa(reals, imags) -> np.ndarray:
    return np.stack([np.asarray(reals, dtype=np.float64).ravel(),
                     np.asarray(imags, dtype=np.float64).ravel()])


def initStateFromAmps(qureg: Qureg, reals, imags) -> None:
    V.validate_state_vec_qureg(qureg, "initStateFromAmps")
    vals = _soa(reals, imags)
    if vals.shape[1] != qureg.num_amps_total:
        V._throw(V.ErrorCode.INVALID_NUM_AMPS, "initStateFromAmps")
    qureg.set_amps_array(jnp.asarray(vals, dtype=qureg.dtype))


def setAmps(qureg: Qureg, start_ind: int, reals, imags, num_amps: int) -> None:
    V.validate_state_vec_qureg(qureg, "setAmps")
    V.validate_num_amps(qureg, start_ind, num_amps, "setAmps")
    vals = _soa(reals, imags)[:, :num_amps]
    qureg.set_amps_array(
        qureg.amps.at[:, start_ind:start_ind + num_amps].set(
            jnp.asarray(vals, dtype=qureg.dtype)))


def setDensityAmps(qureg: Qureg, reals, imags) -> None:
    """Debug API (ref: QuEST_debug.h setDensityAmps) — overwrite all 4^N
    elements, given in the flattened (row + col·2^N) storage order."""
    V.validate_density_matr_qureg(qureg, "setDensityAmps")
    qureg.set_amps_array(jnp.asarray(_soa(reals, imags), dtype=qureg.dtype))


def cloneQureg(target: Qureg, copy: Qureg) -> None:
    V.validate_matching_qureg_types(target, copy, "cloneQureg")
    V.validate_matching_qureg_dims(target, copy, "cloneQureg")
    target.set_amps_array(copy.amps.astype(target.dtype))


def compareStates(a: Qureg, b: Qureg, precision: float) -> bool:
    """Debug API (ref: QuEST_debug.h compareStates)."""
    V.validate_matching_qureg_dims(a, b, "compareStates")
    diff = np.asarray(a.amps, dtype=np.float64) - np.asarray(b.amps, dtype=np.float64)
    return bool(np.all(np.abs(diff) < precision))


# ---------------------------------------------------------------------------
# amplitude access
# ---------------------------------------------------------------------------

def _amp_at(qureg: Qureg, index: int) -> complex:
    if qureg._planes is not None:
        idx = qureg.permute_amp_index(int(index))
        re, im = qureg.planes
        return complex(float(re[idx]), float(im[idx]))
    pair = np.asarray(qureg.amps[:, int(index)], dtype=np.float64)
    return complex(pair[0], pair[1])


def getAmp(qureg: Qureg, index: int) -> complex:
    V.validate_state_vec_qureg(qureg, "getAmp")
    V.validate_amp_index(qureg, index, "getAmp")
    return _amp_at(qureg, index)


def getRealAmp(qureg: Qureg, index: int) -> float:
    V.validate_state_vec_qureg(qureg, "getRealAmp")
    V.validate_amp_index(qureg, index, "getRealAmp")
    return float(qureg.amps[0, int(index)])


def getImagAmp(qureg: Qureg, index: int) -> float:
    V.validate_state_vec_qureg(qureg, "getImagAmp")
    V.validate_amp_index(qureg, index, "getImagAmp")
    return float(qureg.amps[1, int(index)])


def getProbAmp(qureg: Qureg, index: int) -> float:
    V.validate_state_vec_qureg(qureg, "getProbAmp")
    V.validate_amp_index(qureg, index, "getProbAmp")
    a = _amp_at(qureg, index)
    return a.real * a.real + a.imag * a.imag


def getDensityAmp(qureg: Qureg, row: int, col: int) -> complex:
    """ρ(r,c) at flat index r + c·2^N (ref: getDensityAmp, QuEST.c:709-719)."""
    V.validate_density_matr_qureg(qureg, "getDensityAmp")
    dim = 1 << qureg.num_qubits_represented
    if not (0 <= int(row) < dim and 0 <= int(col) < dim):
        V._throw(V.ErrorCode.INVALID_AMP_INDEX, "getDensityAmp")
    return _amp_at(qureg, int(row) + int(col) * dim)


# ---------------------------------------------------------------------------
# unitaries
# ---------------------------------------------------------------------------

def compactUnitary(qureg: Qureg, target: int, alpha, beta) -> None:
    V.validate_target(qureg, target, "compactUnitary")
    V.validate_unitary_complex_pair(complex(alpha), complex(beta), "compactUnitary",
                                    eps=real_eps(qureg.dtype))
    _apply_unitary(qureg, _compact_matrix(complex(alpha), complex(beta)), _ts(target))
    qureg.qasm.record_compact_unitary(complex(alpha), complex(beta), (), int(target))


def unitary(qureg: Qureg, target: int, u) -> None:
    V.validate_target(qureg, target, "unitary")
    u = as_matrix(u, 1)
    V.validate_one_qubit_unitary(u, "unitary", eps=real_eps(qureg.dtype))
    _apply_unitary(qureg, u, _ts(target))
    qureg.qasm.record_unitary(u, (), int(target))


def rotateX(qureg: Qureg, target: int, angle: float) -> None:
    V.validate_target(qureg, target, "rotateX")
    _apply_unitary(qureg, _rotation_matrix(angle, (1, 0, 0)), _ts(target))
    qureg.qasm.record_gate("rotate_x", (), int(target), (angle,))


def rotateY(qureg: Qureg, target: int, angle: float) -> None:
    V.validate_target(qureg, target, "rotateY")
    _apply_unitary(qureg, _rotation_matrix(angle, (0, 1, 0)), _ts(target))
    qureg.qasm.record_gate("rotate_y", (), int(target), (angle,))


def rotateZ(qureg: Qureg, target: int, angle: float) -> None:
    V.validate_target(qureg, target, "rotateZ")
    _apply_diag(qureg, _rz_diag(angle), _ts(target))
    qureg.qasm.record_gate("rotate_z", (), int(target), (angle,))


def _rz_diag(angle: float) -> np.ndarray:
    return np.array([np.exp(-0.5j * angle), np.exp(0.5j * angle)],
                    dtype=np.complex128)


def rotateAroundAxis(qureg: Qureg, target: int, angle: float, axis) -> None:
    V.validate_target(qureg, target, "rotateAroundAxis")
    V.validate_vector(axis, "rotateAroundAxis")
    _apply_unitary(qureg, _rotation_matrix(angle, axis), _ts(target))
    qureg.qasm.record_axis_rotation(angle, axis, (), int(target))


def controlledRotateX(qureg: Qureg, control: int, target: int, angle: float) -> None:
    V.validate_control_target(qureg, control, target, "controlledRotateX")
    _apply_unitary(qureg, _rotation_matrix(angle, (1, 0, 0)), _ts(target), _ts(control))
    qureg.qasm.record_gate("rotate_x", _ts(control), int(target), (angle,))


def controlledRotateY(qureg: Qureg, control: int, target: int, angle: float) -> None:
    V.validate_control_target(qureg, control, target, "controlledRotateY")
    _apply_unitary(qureg, _rotation_matrix(angle, (0, 1, 0)), _ts(target), _ts(control))
    qureg.qasm.record_gate("rotate_y", _ts(control), int(target), (angle,))


def controlledRotateZ(qureg: Qureg, control: int, target: int, angle: float) -> None:
    V.validate_control_target(qureg, control, target, "controlledRotateZ")
    _apply_diag(qureg, _rz_diag(angle), _ts(target), _ts(control))
    qureg.qasm.record_gate("rotate_z", _ts(control), int(target), (angle,))


def controlledRotateAroundAxis(qureg: Qureg, control: int, target: int,
                               angle: float, axis) -> None:
    V.validate_control_target(qureg, control, target, "controlledRotateAroundAxis")
    V.validate_vector(axis, "controlledRotateAroundAxis")
    _apply_unitary(qureg, _rotation_matrix(angle, axis), _ts(target), _ts(control))
    qureg.qasm.record_axis_rotation(angle, axis, _ts(control), int(target))


def controlledCompactUnitary(qureg: Qureg, control: int, target: int, alpha, beta) -> None:
    V.validate_control_target(qureg, control, target, "controlledCompactUnitary")
    V.validate_unitary_complex_pair(complex(alpha), complex(beta),
                                    "controlledCompactUnitary", eps=real_eps(qureg.dtype))
    _apply_unitary(qureg, _compact_matrix(complex(alpha), complex(beta)),
                   _ts(target), _ts(control))
    qureg.qasm.record_compact_unitary(complex(alpha), complex(beta),
                                      _ts(control), int(target))


def controlledUnitary(qureg: Qureg, control: int, target: int, u) -> None:
    V.validate_control_target(qureg, control, target, "controlledUnitary")
    u = as_matrix(u, 1)
    V.validate_one_qubit_unitary(u, "controlledUnitary", eps=real_eps(qureg.dtype))
    _apply_unitary(qureg, u, _ts(target), _ts(control))
    qureg.qasm.record_unitary(u, _ts(control), int(target))


def multiControlledUnitary(qureg: Qureg, controls, num_controls=None, target=None, u=None) -> None:
    controls, target, u = _legacy_mc_args(controls, num_controls, target, u)
    V.validate_multi_controls_target(qureg, controls, target, "multiControlledUnitary")
    u = as_matrix(u, 1)
    V.validate_one_qubit_unitary(u, "multiControlledUnitary", eps=real_eps(qureg.dtype))
    _apply_unitary(qureg, u, _ts(target), _ts(controls))
    qureg.qasm.record_unitary(u, _ts(controls), int(target))


def _legacy_mc_args(controls, num_controls, target, u):
    """Accept both (controls, numControls, target, u) — the C signature — and
    the Pythonic (controls, target, u)."""
    if u is None:
        u = target
        target = num_controls
        return _ts(controls), int(target), u
    return _ts(controls)[:int(num_controls)], int(target), u


def multiStateControlledUnitary(qureg: Qureg, controls, control_state,
                                num_controls=None, target=None, u=None) -> None:
    """Controls conditioned on an arbitrary bit pattern (ref: QuEST.h
    multiStateControlledUnitary)."""
    if u is None:
        u = target
        target = num_controls
    else:
        controls = _ts(controls)[:int(num_controls)]
    controls = _ts(controls)
    V.validate_multi_controls_target(qureg, controls, target, "multiStateControlledUnitary")
    V.validate_control_state(control_state, len(controls), "multiStateControlledUnitary")
    u = as_matrix(u, 1)
    V.validate_one_qubit_unitary(u, "multiStateControlledUnitary", eps=real_eps(qureg.dtype))
    cs = tuple(int(b) for b in control_state)
    _apply_unitary(qureg, u, _ts(target), controls, cs)
    qureg.qasm.record_comment(
        "Here, an undisclosed multi-state-controlled unitary was applied.")


# --- fixed gates -----------------------------------------------------------

_HADAMARD = np.array([[1, 1], [1, -1]], dtype=np.complex128) / math.sqrt(2)


def pauliX(qureg: Qureg, target: int) -> None:
    V.validate_target(qureg, target, "pauliX")
    if qureg._planes is not None and qureg.uses_plane_storage():
        _apply_unitary_planes(qureg, _ap.mat_pair(np.array([[0, 1], [1, 0]])),
                              (int(target),), ())
        qureg.qasm.record_gate("sigma_x", (), int(target))
        return
    amps = _pinned(qureg, qureg.amps, _ap.apply_pauli_x, (), (int(target),))
    if qureg.is_density_matrix:
        amps = _pinned(qureg, amps, _ap.apply_pauli_x, (),
                       (int(target) + qureg.num_qubits_represented,))
    qureg.amps = amps
    qureg.qasm.record_gate("sigma_x", (), int(target))


def pauliY(qureg: Qureg, target: int) -> None:
    V.validate_target(qureg, target, "pauliY")
    if qureg._planes is not None and qureg.uses_plane_storage():
        _apply_unitary_planes(qureg, _ap.mat_pair(np.array([[0, -1j], [1j, 0]])),
                              (int(target),), ())
        qureg.qasm.record_gate("sigma_y", (), int(target))
        return
    amps = _pinned(qureg, qureg.amps, _ap.apply_pauli_y, (), (int(target),))
    if qureg.is_density_matrix:
        # shadow is conj(Y) = -Y
        amps = _pinned(qureg, amps, _ap.apply_pauli_y, (),
                       (int(target) + qureg.num_qubits_represented, (), (), -1))
    qureg.amps = amps
    qureg.qasm.record_gate("sigma_y", (), int(target))


def pauliZ(qureg: Qureg, target: int) -> None:
    V.validate_target(qureg, target, "pauliZ")
    _apply_diag(qureg, np.array([1, -1], dtype=np.complex128), _ts(target))
    qureg.qasm.record_gate("sigma_z", (), int(target))


def hadamard(qureg: Qureg, target: int) -> None:
    V.validate_target(qureg, target, "hadamard")
    _apply_unitary(qureg, _HADAMARD, _ts(target))
    qureg.qasm.record_gate("hadamard", (), int(target))


def sGate(qureg: Qureg, target: int) -> None:
    V.validate_target(qureg, target, "sGate")
    _apply_diag(qureg, np.array([1, 1j], dtype=np.complex128), _ts(target))
    qureg.qasm.record_gate("s", (), int(target))


def tGate(qureg: Qureg, target: int) -> None:
    V.validate_target(qureg, target, "tGate")
    _apply_diag(qureg, np.array([1, np.exp(0.25j * np.pi)], dtype=np.complex128),
                _ts(target))
    qureg.qasm.record_gate("t", (), int(target))


def phaseShift(qureg: Qureg, target: int, angle: float) -> None:
    V.validate_target(qureg, target, "phaseShift")
    _apply_diag(qureg, np.array([1, np.exp(1j * angle)], dtype=np.complex128),
                _ts(target))
    qureg.qasm.record_gate("phase_shift", (), int(target), (angle,))


def controlledPhaseShift(qureg: Qureg, q1: int, q2: int, angle: float) -> None:
    V.validate_control_target(qureg, q1, q2, "controlledPhaseShift")
    _apply_diag(qureg, np.array([1, np.exp(1j * angle)], dtype=np.complex128),
                _ts(q2), _ts(q1))
    qureg.qasm.record_gate("phase_shift", _ts(q1), int(q2), (angle,))


def multiControlledPhaseShift(qureg: Qureg, qubits, num_qubits=None, angle=None) -> None:
    if angle is None:
        angle = num_qubits
    else:
        qubits = _ts(qubits)[:int(num_qubits)]
    qubits = _ts(qubits)
    V.validate_multi_qubits(qureg, qubits, "multiControlledPhaseShift")
    _apply_diag(qureg, np.array([1, np.exp(1j * float(angle))], dtype=np.complex128),
                (qubits[-1],), tuple(qubits[:-1]))
    qureg.qasm.record_gate("phase_shift", tuple(qubits[:-1]), int(qubits[-1]),
                           (float(angle),))


def controlledPhaseFlip(qureg: Qureg, q1: int, q2: int) -> None:
    V.validate_control_target(qureg, q1, q2, "controlledPhaseFlip")
    _apply_diag(qureg, np.array([1, -1], dtype=np.complex128), _ts(q2), _ts(q1))
    qureg.qasm.record_gate("sigma_z", _ts(q1), int(q2))


def multiControlledPhaseFlip(qureg: Qureg, qubits, num_qubits=None) -> None:
    if num_qubits is not None:
        qubits = _ts(qubits)[:int(num_qubits)]
    qubits = _ts(qubits)
    V.validate_multi_qubits(qureg, qubits, "multiControlledPhaseFlip")
    _apply_diag(qureg, np.array([1, -1], dtype=np.complex128),
                (qubits[-1],), tuple(qubits[:-1]))
    qureg.qasm.record_gate("sigma_z", tuple(qubits[:-1]), int(qubits[-1]))


def controlledNot(qureg: Qureg, control: int, target: int) -> None:
    V.validate_control_target(qureg, control, target, "controlledNot")
    amps = _pinned(qureg, qureg.amps, _ap.apply_pauli_x, (),
                   (int(target), _ts(control)))
    if qureg.is_density_matrix:
        n = qureg.num_qubits_represented
        amps = _pinned(qureg, amps, _ap.apply_pauli_x, (),
                       (int(target) + n, _ts(int(control) + n)))
    qureg.amps = amps
    qureg.qasm.record_gate("sigma_x", _ts(control), int(target))


def controlledPauliY(qureg: Qureg, control: int, target: int) -> None:
    V.validate_control_target(qureg, control, target, "controlledPauliY")
    amps = _ap.apply_pauli_y(qureg.amps, int(target), _ts(control))
    if qureg.is_density_matrix:
        n = qureg.num_qubits_represented
        amps = _ap.apply_pauli_y(amps, int(target) + n, _ts(int(control) + n),
                                 conj_fac=-1)
    qureg.amps = amps
    qureg.qasm.record_gate("sigma_y", _ts(control), int(target))


def swapGate(qureg: Qureg, q1: int, q2: int) -> None:
    V.validate_unique_targets(qureg, q1, q2, "swapGate")
    amps = _pinned(qureg, qureg.amps, _ap.swap_qubit_amps, (),
                   (int(q1), int(q2)))
    if qureg.is_density_matrix:
        n = qureg.num_qubits_represented
        amps = _pinned(qureg, amps, _ap.swap_qubit_amps, (),
                       (int(q1) + n, int(q2) + n))
    qureg.amps = amps
    qureg.qasm.record_comment(
        f"Here, a swap gate was applied to qubits {int(q1)} and {int(q2)}")


_SQRT_SWAP = np.array([
    [1, 0, 0, 0],
    [0, 0.5 + 0.5j, 0.5 - 0.5j, 0],
    [0, 0.5 - 0.5j, 0.5 + 0.5j, 0],
    [0, 0, 0, 1]], dtype=np.complex128)


def sqrtSwapGate(qureg: Qureg, q1: int, q2: int) -> None:
    V.validate_unique_targets(qureg, q1, q2, "sqrtSwapGate")
    _apply_unitary(qureg, _SQRT_SWAP, (int(q1), int(q2)))
    qureg.qasm.record_comment(
        f"Here, a sqrt-swap gate was applied to qubits {int(q1)} and {int(q2)}")


def multiRotateZ(qureg: Qureg, qubits, num_qubits=None, angle=None) -> None:
    if angle is None:
        angle = num_qubits
    else:
        qubits = _ts(qubits)[:int(num_qubits)]
    qubits = _ts(qubits)
    V.validate_multi_targets(qureg, qubits, "multiRotateZ")
    amps = _pinned(qureg, qureg.amps, _ap.apply_multi_rotate_z,
                   (jnp.float64(angle),), (qubits,))
    if qureg.is_density_matrix:
        n = qureg.num_qubits_represented
        amps = _pinned(qureg, amps, _ap.apply_multi_rotate_z,
                       (jnp.float64(-angle),), (_shift(qubits, n),))
    qureg.amps = amps
    qureg.qasm.record_comment(
        f"Here, a multiRotateZ of angle {float(angle):g} was applied.")


def _multi_rotate_pauli_statevec(amps, targets, paulis, angle, apply_conj: bool):
    """Basis-rotate X/Y targets onto Z, multiRotateZ, rotate back
    (ref: statevec_multiRotatePauli, QuEST_common.c:411-448)."""
    fac = 1 / math.sqrt(2)
    # Ry(-pi/2): Z -> X;  Rx(pi/2)^(* if conj): Z -> Y
    ry = _ap.mat_pair(_compact_matrix(fac, -fac))
    rx = _ap.mat_pair(_compact_matrix(fac, (1j * fac) if apply_conj else (-1j * fac)))
    mask_targets = []
    for t, p in zip(targets, paulis):
        p = int(p)
        if p == PauliOpType.PAULI_I:
            continue
        mask_targets.append(t)
        if p == PauliOpType.PAULI_X:
            amps = _ap.apply_matrix(amps, ry, (t,))
        elif p == PauliOpType.PAULI_Y:
            amps = _ap.apply_matrix(amps, rx, (t,))
    # all-identity Pauli strings apply NOTHING — the reference explicitly
    # skips the rotation when the mask is empty ("does nothing if there are
    # no qubits to 'rotate'", QuEST_common.c:436-437), deliberately omitting
    # the e^{-i angle/2} global phase, and its test suite requires that
    if mask_targets:
        a = -angle if apply_conj else angle
        amps = _ap.apply_multi_rotate_z(amps, jnp.float64(a), tuple(mask_targets))
    ry_inv = _ap.mat_pair(_compact_matrix(fac, fac))
    rx_inv = _ap.mat_pair(_compact_matrix(fac, (-1j * fac) if apply_conj else (1j * fac)))
    for t, p in zip(targets, paulis):
        p = int(p)
        if p == PauliOpType.PAULI_X:
            amps = _ap.apply_matrix(amps, ry_inv, (t,))
        elif p == PauliOpType.PAULI_Y:
            amps = _ap.apply_matrix(amps, rx_inv, (t,))
    return amps


def multiRotatePauli(qureg: Qureg, targets, paulis, num_targets=None, angle=None) -> None:
    if angle is None:
        angle = num_targets
    else:
        targets = _ts(targets)[:int(num_targets)]
        paulis = list(paulis)[:int(num_targets)]
    targets = _ts(targets)
    V.validate_multi_targets(qureg, targets, "multiRotatePauli")
    V.validate_pauli_codes(paulis, len(targets), "multiRotatePauli")
    amps = _multi_rotate_pauli_statevec(qureg.amps, targets, paulis,
                                        float(angle), False)
    if qureg.is_density_matrix:
        n = qureg.num_qubits_represented
        amps = _multi_rotate_pauli_statevec(amps, _shift(targets, n), paulis,
                                            float(angle), True)
    qureg.amps = amps
    qureg.qasm.record_comment("Here, a multiRotatePauli was applied.")


# --- multi-qubit dense unitaries ------------------------------------------

def twoQubitUnitary(qureg: Qureg, t1: int, t2: int, u) -> None:
    V.validate_unique_targets(qureg, t1, t2, "twoQubitUnitary")
    u = as_matrix(u, 2)
    V.validate_two_qubit_unitary(u, "twoQubitUnitary", eps=real_eps(qureg.dtype))
    V.validate_multi_qubit_matrix_fits_in_shard(qureg, 2, "twoQubitUnitary")
    _apply_unitary(qureg, u, (int(t1), int(t2)))
    qureg.qasm.record_comment("Here, an undisclosed 2-qubit unitary was applied.")


def controlledTwoQubitUnitary(qureg: Qureg, control: int, t1: int, t2: int, u) -> None:
    V.validate_multi_controls_multi_targets(qureg, _ts(control), (int(t1), int(t2)),
                                            "controlledTwoQubitUnitary")
    u = as_matrix(u, 2)
    V.validate_two_qubit_unitary(u, "controlledTwoQubitUnitary", eps=real_eps(qureg.dtype))
    V.validate_multi_qubit_matrix_fits_in_shard(qureg, 2, "controlledTwoQubitUnitary")
    _apply_unitary(qureg, u, (int(t1), int(t2)), _ts(control))
    qureg.qasm.record_comment("Here, an undisclosed controlled 2-qubit unitary was applied.")


def multiControlledTwoQubitUnitary(qureg: Qureg, controls, num_controls=None,
                                   t1=None, t2=None, u=None) -> None:
    if u is None:
        u = t2
        t2 = t1
        t1 = num_controls
    else:
        controls = _ts(controls)[:int(num_controls)]
    controls = _ts(controls)
    V.validate_multi_controls_multi_targets(qureg, controls, (int(t1), int(t2)),
                                            "multiControlledTwoQubitUnitary")
    u = as_matrix(u, 2)
    V.validate_two_qubit_unitary(u, "multiControlledTwoQubitUnitary",
                                 eps=real_eps(qureg.dtype))
    V.validate_multi_qubit_matrix_fits_in_shard(qureg, 2, "multiControlledTwoQubitUnitary")
    _apply_unitary(qureg, u, (int(t1), int(t2)), controls)
    qureg.qasm.record_comment(
        "Here, an undisclosed multi-controlled 2-qubit unitary was applied.")


def multiQubitUnitary(qureg: Qureg, targets, num_targets=None, u=None) -> None:
    if u is None:
        u = num_targets
    else:
        targets = _ts(targets)[:int(num_targets)]
    targets = _ts(targets)
    V.validate_multi_targets(qureg, targets, "multiQubitUnitary")
    u = as_matrix(u, len(targets))
    V.validate_multi_qubit_unitary(u, len(targets), "multiQubitUnitary",
                                   eps=real_eps(qureg.dtype))
    V.validate_multi_qubit_matrix_fits_in_shard(qureg, len(targets), "multiQubitUnitary")
    _apply_unitary(qureg, u, targets)
    qureg.qasm.record_comment("Here, an undisclosed multi-qubit unitary was applied.")


def controlledMultiQubitUnitary(qureg: Qureg, ctrl: int, targets, num_targets=None,
                                u=None) -> None:
    if u is None:
        u = num_targets
    else:
        targets = _ts(targets)[:int(num_targets)]
    targets = _ts(targets)
    V.validate_multi_controls_multi_targets(qureg, _ts(ctrl), targets,
                                            "controlledMultiQubitUnitary")
    u = as_matrix(u, len(targets))
    V.validate_multi_qubit_unitary(u, len(targets), "controlledMultiQubitUnitary",
                                   eps=real_eps(qureg.dtype))
    V.validate_multi_qubit_matrix_fits_in_shard(qureg, len(targets),
                                                "controlledMultiQubitUnitary")
    _apply_unitary(qureg, u, targets, _ts(ctrl))
    qureg.qasm.record_comment(
        "Here, an undisclosed controlled multi-qubit unitary was applied.")


def multiControlledMultiQubitUnitary(qureg: Qureg, ctrls, num_ctrls=None,
                                     targets=None, num_targets=None, u=None) -> None:
    if u is None:
        u = targets
        targets = num_ctrls
    else:
        ctrls = _ts(ctrls)[:int(num_ctrls)]
        targets = _ts(targets)[:int(num_targets)]
    ctrls, targets = _ts(ctrls), _ts(targets)
    V.validate_multi_controls_multi_targets(qureg, ctrls, targets,
                                            "multiControlledMultiQubitUnitary")
    u = as_matrix(u, len(targets))
    V.validate_multi_qubit_unitary(u, len(targets), "multiControlledMultiQubitUnitary",
                                   eps=real_eps(qureg.dtype))
    V.validate_multi_qubit_matrix_fits_in_shard(qureg, len(targets),
                                                "multiControlledMultiQubitUnitary")
    _apply_unitary(qureg, u, targets, ctrls)
    qureg.qasm.record_comment(
        "Here, an undisclosed multi-controlled multi-qubit unitary was applied.")


# ---------------------------------------------------------------------------
# non-unitary matrix application (ref: applyMatrix2/4/N — left-multiply only,
# no density shadow, no unitarity check)
# ---------------------------------------------------------------------------

def applyMatrix2(qureg: Qureg, target: int, u) -> None:
    V.validate_target(qureg, target, "applyMatrix2")
    qureg.amps = _ap.apply_matrix(qureg.amps, _ap.mat_pair(as_matrix(u, 1)), _ts(target))
    qureg.qasm.record_comment("Here, an undisclosed 2-by-2 matrix was applied.")


def applyMatrix4(qureg: Qureg, t1: int, t2: int, u) -> None:
    V.validate_unique_targets(qureg, t1, t2, "applyMatrix4")
    V.validate_multi_qubit_matrix_fits_in_shard(qureg, 2, "applyMatrix4")
    qureg.amps = _ap.apply_matrix(qureg.amps, _ap.mat_pair(as_matrix(u, 2)),
                                  (int(t1), int(t2)))
    qureg.qasm.record_comment("Here, an undisclosed 4-by-4 matrix was applied.")


def applyMatrixN(qureg: Qureg, targets, num_targets=None, u=None) -> None:
    if u is None:
        u = num_targets
    else:
        targets = _ts(targets)[:int(num_targets)]
    targets = _ts(targets)
    V.validate_multi_targets(qureg, targets, "applyMatrixN")
    u = as_matrix(u, len(targets))
    V.validate_multi_qubit_matrix_size(u, len(targets), "applyMatrixN")
    V.validate_multi_qubit_matrix_fits_in_shard(qureg, len(targets), "applyMatrixN")
    qureg.amps = _ap.apply_matrix(qureg.amps, _ap.mat_pair(u), targets)
    qureg.qasm.record_comment("Here, an undisclosed matrix was applied.")


def applyMultiControlledMatrixN(qureg: Qureg, ctrls, num_ctrls=None, targets=None,
                                num_targets=None, u=None) -> None:
    if u is None:
        u = targets
        targets = num_ctrls
    else:
        ctrls = _ts(ctrls)[:int(num_ctrls)]
        targets = _ts(targets)[:int(num_targets)]
    ctrls, targets = _ts(ctrls), _ts(targets)
    V.validate_multi_controls_multi_targets(qureg, ctrls, targets,
                                            "applyMultiControlledMatrixN")
    u = as_matrix(u, len(targets))
    V.validate_multi_qubit_matrix_size(u, len(targets), "applyMultiControlledMatrixN")
    V.validate_multi_qubit_matrix_fits_in_shard(qureg, len(targets),
                                                "applyMultiControlledMatrixN")
    qureg.amps = _ap.apply_matrix(qureg.amps, _ap.mat_pair(u), targets, ctrls)
    qureg.qasm.record_comment("Here, an undisclosed controlled matrix was applied.")


# ---------------------------------------------------------------------------
# measurement
# ---------------------------------------------------------------------------

def _prob_of_zero(qureg: Qureg, target: int) -> float:
    if qureg.is_density_matrix:
        return float(_meas.densmatr_prob_of_zero(
            qureg.amps, int(target), qureg.num_qubits_represented))
    if qureg._planes is not None:
        re, im = qureg.planes
        return float(_meas.prob_of_zero_planes(
            re, im, qureg.logical_to_physical(int(target))))
    return float(_meas.prob_of_zero(qureg.amps, int(target)))


def calcProbOfOutcome(qureg: Qureg, target: int, outcome: int) -> float:
    V.validate_target(qureg, target, "calcProbOfOutcome")
    V.validate_outcome(outcome, "calcProbOfOutcome")
    p0 = _prob_of_zero(qureg, target)
    return p0 if int(outcome) == 0 else 1.0 - p0


def _collapse(qureg: Qureg, target: int, outcome: int, prob: float) -> None:
    if qureg._planes is not None:
        t = qureg.logical_to_physical(int(target))
        re, im = qureg.take_planes()
        re, im = _meas.collapse_planes(re, im, t, int(outcome),
                                       jnp.float64(prob))
        qureg.set_planes(re, im, qureg.qubit_map)
        return
    if qureg.is_density_matrix:
        qureg.amps = _pinned(
            qureg, qureg.amps, _collapse_dm_fn, (jnp.float64(prob),),
            (int(target), int(outcome), qureg.num_qubits_represented))
    else:
        qureg.amps = _pinned(
            qureg, qureg.amps, _collapse_sv_fn, (jnp.float64(prob),),
            (int(target), int(outcome)))


def _collapse_sv_fn(state, prob, target, outcome):
    """collapse_to_outcome with prob as the leading dynamic operand (module-
    level def: a stable identity for constrained_op's static-fn cache)."""
    return _meas.collapse_to_outcome(state, target, outcome, prob)


def _collapse_dm_fn(state, prob, target, outcome, num_qubits):
    return _meas.densmatr_collapse_to_outcome(state, target, outcome, prob,
                                              num_qubits)


def collapseToOutcome(qureg: Qureg, target: int, outcome: int) -> float:
    V.validate_target(qureg, target, "collapseToOutcome")
    V.validate_outcome(outcome, "collapseToOutcome")
    p0 = _prob_of_zero(qureg, target)
    prob = p0 if int(outcome) == 0 else 1.0 - p0
    V.validate_measurement_prob(prob, "collapseToOutcome", eps=real_eps(qureg.dtype))
    _collapse(qureg, target, outcome, prob)
    qureg.qasm.record_comment(
        f"Here, qubit {int(target)} was collapsed to outcome {int(outcome)}")
    return prob


def calcPartialTrace(qureg: Qureg, trace_qubits) -> Qureg:
    """Trace out ``trace_qubits``, returning a NEW density Qureg over the
    remaining qubits (kept qubit i of the result = i-th smallest kept index).

    TPU-native extension (no v3.2 analogue; QuEST added calcPartialTrace in
    a later major version).  Density input: bit-routing swaps + a
    block-trace contraction (ops/calc.py densmatr_partial_trace).
    Pure-state input: the reduced matrix is the Gram matrix of 2^t-amp
    slices — one pair of MXU matmuls, never the 4^n outer product."""
    trace_qubits = _ts(trace_qubits)
    V.validate_multi_targets(qureg, trace_qubits, "calcPartialTrace")
    n = qureg.num_qubits_represented
    keep = tuple(q for q in range(n) if q not in trace_qubits)
    if not keep:  # tracing every qubit leaves no register
        V._throw(V.ErrorCode.INVALID_NUM_TARGETS, "calcPartialTrace")
    V.validate_create_num_qubits(len(keep), qureg.env, "calcPartialTrace",
                                 factor=2)
    if qureg.is_density_matrix:
        amps = _calc.densmatr_partial_trace(qureg.amps, keep, n)
    else:
        amps = _calc.statevec_partial_trace(qureg.amps, keep)
    out = Qureg(len(keep), qureg.env, is_density_matrix=True,
                dtype=qureg.dtype)
    out.set_amps_array(amps)
    return out


def calcVonNeumannEntropy(qureg: Qureg, keep_qubits=None, base: float = 2.0) -> float:
    """Entanglement entropy S(ρ_A) = −Tr ρ_A log ρ_A of the reduced state
    over ``keep_qubits`` (default: the whole register), in units of
    ``log base`` (bits by default).

    TPU-native extension: the reduction to ρ_A runs on device
    (calcPartialTrace kernels); only the 2^m x 2^m eigenproblem runs host-side."""
    if base <= 0 or base == 1.0:
        raise ValueError(f"calcVonNeumannEntropy: invalid log base {base}")
    n = qureg.num_qubits_represented
    if keep_qubits is None:
        keep_qubits = list(range(n))
    keep_qubits = _ts(keep_qubits)
    V.validate_multi_targets(qureg, keep_qubits, "calcVonNeumannEntropy")
    keep = tuple(sorted(keep_qubits))
    if not qureg.is_density_matrix:
        if len(keep) == n:
            return 0.0  # a pure state has zero entropy
        if len(keep) > n - len(keep):
            # S(A) = S(complement) for pure states: always diagonalise the
            # SMALLER side (keeping 16 of 20 qubits would otherwise mean a
            # 2^16-dim eigenproblem where the complement needs a 16-dim one)
            keep = tuple(q for q in range(n) if q not in keep)
        amps = _calc.statevec_partial_trace(qureg.amps, keep)
        m = len(keep)
    elif len(keep) == n:
        amps = qureg.amps
        m = n
    else:
        amps = _calc.densmatr_partial_trace(qureg.amps, keep, n)
        m = len(keep)
    a = np.asarray(amps)
    dim = 1 << m
    rho = (a[0] + 1j * a[1]).reshape(dim, dim).T  # flat is column-major
    lam = np.linalg.eigvalsh(rho)
    lam = lam[lam > 1e-15]
    return float(-(lam * (np.log(lam) / np.log(base))).sum())


def calcProbOfAllOutcomes(qureg: Qureg, qubits) -> np.ndarray:
    """Joint probability of every outcome of the listed qubits, as a 2^k
    float64 vector whose index bit i is the outcome of ``qubits[i]``.

    TPU-native extension (the reference's v3.2 surface only queries one
    qubit at a time, calcProbOfOutcome; the name and index convention match
    the function QuEST added in v3.4).  One fused device pass — a grouped
    structured reduction, no per-outcome dispatch (ops/measure.py)."""
    qubits = _ts(qubits)
    V.validate_multi_targets(qureg, qubits, "calcProbOfAllOutcomes")
    if qureg.is_density_matrix:
        p = _meas.densmatr_prob_all_outcomes(qureg.amps, tuple(qubits),
                                             qureg.num_qubits_represented)
    else:
        p = _meas.prob_all_outcomes(qureg.amps, tuple(qubits))
    return np.asarray(p)


def sampleOutcomes(qureg: Qureg, num_samples: int, qubits=None) -> np.ndarray:
    """Draw ``num_samples`` joint measurement outcomes of ``qubits`` (default:
    all) WITHOUT collapsing the state — the multi-shot readout of a
    variational/sampling workload (2^k-outcome histogram + inverse-CDF draw,
    instead of num_samples destructive measure() calls on cloned registers).

    TPU-native extension.  Outcome bit i = qubits[i]; draws come from the
    global MT19937 stream (seedQuEST), so runs are reproducible and every
    rank of a multi-process env draws identically (the reference's seed
    broadcast contract, ref: QuEST_cpu_distributed.c:1318-1329)."""
    n = qureg.num_qubits_represented
    if qubits is None:
        qubits = list(range(n))
    qubits = _ts(qubits)
    V.validate_multi_targets(qureg, qubits, "sampleOutcomes")
    num_samples = int(num_samples)
    if num_samples < 1:
        raise ValueError("sampleOutcomes: num_samples must be >= 1")
    probs = calcProbOfAllOutcomes(qureg, qubits)
    cdf = np.cumsum(probs)
    total = cdf[-1]
    if not np.isfinite(total) or total <= 0:
        raise ValueError(f"sampleOutcomes: unnormalisable state (sum {total})")
    draws = rng.rand_real1_batch(num_samples)
    outcomes = np.searchsorted(cdf, draws * total, side="right")
    # genrand_real1 is inclusive of 1.0 (2^-32 per draw): clamp endpoint
    # overshoot to the LAST POSITIVE-probability outcome, never a zero one
    last_pos = np.nonzero(probs > 0)[0][-1]
    outcomes = np.minimum(outcomes, last_pos).astype(np.int64)
    qureg.qasm.record_comment(
        f"Here, {num_samples} outcomes of {len(qubits)} qubits were sampled.")
    return outcomes


def measureWithStats(qureg: Qureg, target: int):
    """Returns (outcome, outcomeProb).  Outcome drawn from the global MT19937
    exactly as the reference (ref: generateMeasurementOutcome,
    QuEST_common.c:155-170)."""
    V.validate_target(qureg, target, "measureWithStats")
    eps = real_eps(qureg.dtype)
    zero_prob = _prob_of_zero(qureg, target)
    if zero_prob < eps:
        outcome = 1
    elif 1 - zero_prob < eps:
        outcome = 0
    else:
        outcome = int(rng.rand_real1() > zero_prob)
    prob = zero_prob if outcome == 0 else 1 - zero_prob
    _collapse(qureg, target, outcome, prob)
    qureg.qasm.record_measurement(int(target))
    return outcome, prob


def measure(qureg: Qureg, target: int) -> int:
    outcome, _ = measureWithStats(qureg, target)
    return outcome


# ---------------------------------------------------------------------------
# calculations
# ---------------------------------------------------------------------------

def calcTotalProb(qureg: Qureg) -> float:
    V.validate_qureg_init(qureg, "calcTotalProb")
    if qureg.is_density_matrix:
        return float(_calc.total_prob_densmatr(qureg.amps, qureg.num_qubits_represented))
    if qureg._planes is not None:
        return float(_meas.total_prob_planes(*qureg.planes))
    return float(_calc.total_prob_statevec(qureg.amps))


def calcInnerProduct(bra: Qureg, ket: Qureg) -> complex:
    V.validate_state_vec_qureg(bra, "calcInnerProduct")
    V.validate_state_vec_qureg(ket, "calcInnerProduct")
    V.validate_matching_qureg_dims(bra, ket, "calcInnerProduct")
    ip = np.asarray(_calc.inner_product(bra.amps, ket.amps))
    return complex(ip[0], ip[1])


def calcDensityInnerProduct(rho1: Qureg, rho2: Qureg) -> float:
    V.validate_density_matr_qureg(rho1, "calcDensityInnerProduct")
    V.validate_density_matr_qureg(rho2, "calcDensityInnerProduct")
    V.validate_matching_qureg_dims(rho1, rho2, "calcDensityInnerProduct")
    return float(_calc.densmatr_inner_product(rho1.amps, rho2.amps))


def calcPurity(qureg: Qureg) -> float:
    V.validate_qureg_init(qureg, "calcPurity")
    V.validate_density_matr_qureg(qureg, "calcPurity")
    return float(_calc.purity(qureg.amps))


def calcFidelity(qureg: Qureg, pure: Qureg) -> float:
    V.validate_qureg_init(qureg, "calcFidelity")
    V.validate_qureg_init(pure, "calcFidelity")
    V.validate_second_qureg_state_vec(pure, "calcFidelity")
    V.validate_matching_qureg_dims(qureg, pure, "calcFidelity")
    if qureg.is_density_matrix:
        return float(_calc.densmatr_fidelity(qureg.amps, pure.amps,
                                             qureg.num_qubits_represented))
    ip = np.asarray(_calc.inner_product(qureg.amps, pure.amps))
    return float(ip[0] ** 2 + ip[1] ** 2)


def calc_total_prob(qureg: Qureg) -> float:
    """Numeric-health twin of :func:`calcTotalProb` (QuEST's canonical
    mid-circuit sanity check): the register's total probability — L2 norm
    of a statevector, trace of a density matrix — with validation-layer
    errors (``E_QUREG_NOT_INITIALISED``) on destroyed registers.  A
    unit-norm result within the ulp band of obs/numerics.py says the
    register is still a physical state; the serve layer computes the same
    reduction on-device as a probe (docs/OBSERVABILITY.md)."""
    return calcTotalProb(qureg)


def calc_purity(qureg: Qureg) -> float:
    """Numeric-health twin of :func:`calcPurity`: Tr(rho^2) of a density
    register (1 for pure, 1/2^n for maximally mixed), with
    validation-layer errors on destroyed or non-density registers."""
    return calcPurity(qureg)


def calc_fidelity(qureg: Qureg, pure: Qureg) -> float:
    """Numeric-health twin of :func:`calcFidelity`: |<pure|psi>|^2 (or
    <pure|rho|pure> for a density register) against a pure reference
    state, with validation-layer errors on destroyed registers and
    mismatched dimensions."""
    return calcFidelity(qureg, pure)


def calcHilbertSchmidtDistance(a: Qureg, b: Qureg) -> float:
    V.validate_density_matr_qureg(a, "calcHilbertSchmidtDistance")
    V.validate_density_matr_qureg(b, "calcHilbertSchmidtDistance")
    V.validate_matching_qureg_dims(a, b, "calcHilbertSchmidtDistance")
    return float(jnp.sqrt(_calc.hilbert_schmidt_distance_squared(a.amps, b.amps)))


_Z_DIAG = np.array([[1.0, -1.0], [0.0, 0.0]])  # (re, im) pair of diag(1, -1)


def _apply_pauli_prod(amps, targets, codes):
    """X/Y/Z factors on the row-side qubits (ref: statevec_applyPauliProd,
    QuEST_common.c:451-463)."""
    for t, c in zip(targets, codes):
        c = int(c)
        if c == PauliOpType.PAULI_X:
            amps = _ap.apply_pauli_x(amps, int(t))
        elif c == PauliOpType.PAULI_Y:
            amps = _ap.apply_pauli_y(amps, int(t))
        elif c == PauliOpType.PAULI_Z:
            amps = _ap.apply_diagonal(amps, _Z_DIAG, (int(t),))
    return amps


def calcExpecPauliProd(qureg: Qureg, targets, codes, num_targets=None,
                       workspace=None) -> float:
    if workspace is None and not isinstance(num_targets, (int, np.integer, type(None))):
        workspace = num_targets
        num_targets = None
    if num_targets is not None:
        targets = _ts(targets)[:int(num_targets)]
        codes = list(codes)[:int(num_targets)]
    targets = _ts(targets)
    V.validate_multi_targets(qureg, targets, "calcExpecPauliProd")
    V.validate_pauli_codes(codes, len(targets), "calcExpecPauliProd")
    if workspace is not None:
        V.validate_matching_qureg_types(qureg, workspace, "calcExpecPauliProd")
        V.validate_matching_qureg_dims(qureg, workspace, "calcExpecPauliProd")
    prod_amps = _apply_pauli_prod(qureg.amps, targets, codes)
    if workspace is not None:
        workspace.amps = prod_amps
    if qureg.is_density_matrix:
        return float(_calc.total_prob_densmatr(prod_amps, qureg.num_qubits_represented))
    return float(_calc.inner_product(prod_amps, qureg.amps)[0])


def _pauli_sum_masks(codes: np.ndarray):
    """Per-term bit masks of a (terms, n) Pauli-code array: x = mask(X|Y),
    zy = mask(Z|Y), yc = #Y mod 4 — the traced-mask form used by the
    density-matrix Pauli-sum kernel (ops/calc.py)."""
    codes = np.asarray(codes, dtype=np.int64)
    weights = (np.uint64(1) << np.arange(codes.shape[1], dtype=np.uint64))
    x = ((codes == PauliOpType.PAULI_X) | (codes == PauliOpType.PAULI_Y)) @ weights
    zy = ((codes == PauliOpType.PAULI_Z) | (codes == PauliOpType.PAULI_Y)) @ weights
    yc = (codes == PauliOpType.PAULI_Y).sum(axis=1) % 4
    return (jnp.asarray(x, dtype=jnp.uint64), jnp.asarray(zy, dtype=jnp.uint64),
            jnp.asarray(yc, dtype=jnp.int32))


def _pauli_sum_terms(codes: np.ndarray) -> tuple:
    """STATIC ((x, zy, yc), ...) term tuple for the structured statevector
    Pauli-sum kernels (ops/calc.py) — masks as Python ints so each term
    lowers to static layout moves instead of a dynamic gather."""
    codes = np.asarray(codes, dtype=np.int64)
    out = []
    for row in codes:
        x = zy = yc = 0
        for q, c in enumerate(row):
            if c in (PauliOpType.PAULI_X, PauliOpType.PAULI_Y):
                x |= 1 << q
            if c in (PauliOpType.PAULI_Z, PauliOpType.PAULI_Y):
                zy |= 1 << q
            if c == PauliOpType.PAULI_Y:
                yc += 1
        out.append((x, zy, yc % 4))
    return tuple(out)


def calcExpecPauliSum(qureg: Qureg, all_codes, term_coeffs, num_sum_terms=None,
                      workspace=None) -> float:
    """Σ_t c_t <P_t> as ONE compiled program — one structured pass per term
    with no per-term dispatch or workspace clone (SURVEY §3.5; the
    reference makes O(terms·n) full-state passes, QuEST_common.c:480-492)."""
    if workspace is None and not isinstance(num_sum_terms, (int, np.integer, type(None))):
        workspace = num_sum_terms
        num_sum_terms = None
    n = qureg.num_qubits_represented
    codes = np.asarray(all_codes, dtype=np.int64).reshape(-1, n)
    coeffs = np.asarray(term_coeffs, dtype=np.float64).ravel()
    if num_sum_terms is not None:
        V.validate_num_pauli_sum_terms(int(num_sum_terms), "calcExpecPauliSum")
        codes = codes[:int(num_sum_terms)]
        coeffs = coeffs[:int(num_sum_terms)]
    V.validate_num_pauli_sum_terms(len(codes), "calcExpecPauliSum")
    V.validate_pauli_codes(codes.ravel(), codes.size, "calcExpecPauliSum")
    if workspace is not None:
        # the fused kernel needs no workspace, but the reference's contract
        # still validates it (ref: validateMatchingQuregTypes/Dims)
        V.validate_matching_qureg_types(qureg, workspace, "calcExpecPauliSum")
        V.validate_matching_qureg_dims(qureg, workspace, "calcExpecPauliSum")
    if workspace is not None:
        # parity with the reference: the workspace ends up holding the last
        # term's Pauli product (QuEST_common.c:488 leaves it so)
        workspace.amps = _apply_pauli_prod(qureg.amps, tuple(range(n)), codes[-1])
    cf = jnp.asarray(coeffs)
    if qureg.is_density_matrix:
        xm, zym, yc = _pauli_sum_masks(codes)
        return float(_calc.expec_pauli_sum_densmatr(qureg.amps, xm, zym, yc, cf, n))
    return float(_calc.expec_pauli_sum_statevec(qureg.amps, _pauli_sum_terms(codes), cf))


def calcExpecPauliHamil(qureg: Qureg, hamil: PauliHamil, workspace=None) -> float:
    V.validate_pauli_hamil(hamil, "calcExpecPauliHamil")
    V.validate_matching_hamil_qureg_dims(qureg, hamil, "calcExpecPauliHamil")
    return calcExpecPauliSum(qureg, hamil.pauli_codes, hamil.term_coeffs,
                             hamil.num_sum_terms, workspace)


# ---------------------------------------------------------------------------
# decoherence
# ---------------------------------------------------------------------------

def mixDephasing(qureg: Qureg, target: int, prob: float) -> None:
    V.validate_density_matr_qureg(qureg, "mixDephasing")
    V.validate_target(qureg, target, "mixDephasing")
    V.validate_one_qubit_dephase_prob(prob, "mixDephasing")
    qureg.amps = _pinned(qureg, qureg.amps, _deco.mix_dephasing,
                         (jnp.float64(prob),),
                         (int(target), qureg.num_qubits_represented))
    qureg.qasm.record_comment(
        f"Here, a phase-damping channel of probability {prob:g} was applied to qubit {int(target)}")


def mixTwoQubitDephasing(qureg: Qureg, q1: int, q2: int, prob: float) -> None:
    V.validate_density_matr_qureg(qureg, "mixTwoQubitDephasing")
    V.validate_unique_targets(qureg, q1, q2, "mixTwoQubitDephasing")
    V.validate_two_qubit_dephase_prob(prob, "mixTwoQubitDephasing")
    qureg.amps = _pinned(qureg, qureg.amps, _deco.mix_two_qubit_dephasing,
                         (jnp.float64(prob),),
                         (int(q1), int(q2), qureg.num_qubits_represented))
    qureg.qasm.record_comment(
        f"Here, a two-qubit dephasing channel of probability {prob:g} was applied.")


def mixDepolarising(qureg: Qureg, target: int, prob: float) -> None:
    V.validate_density_matr_qureg(qureg, "mixDepolarising")
    V.validate_target(qureg, target, "mixDepolarising")
    V.validate_one_qubit_depol_prob(prob, "mixDepolarising")
    qureg.amps = _pinned(qureg, qureg.amps, _deco.mix_depolarising,
                         (jnp.float64(prob),),
                         (int(target), qureg.num_qubits_represented))
    qureg.qasm.record_comment(
        f"Here, a depolarising channel of probability {prob:g} was applied to qubit {int(target)}")


def mixDamping(qureg: Qureg, target: int, prob: float) -> None:
    V.validate_density_matr_qureg(qureg, "mixDamping")
    V.validate_target(qureg, target, "mixDamping")
    V.validate_one_qubit_damping_prob(prob, "mixDamping")
    qureg.amps = _pinned(qureg, qureg.amps, _deco.mix_damping,
                         (jnp.float64(prob),),
                         (int(target), qureg.num_qubits_represented))
    qureg.qasm.record_comment(
        f"Here, an amplitude damping channel of probability {prob:g} was applied to qubit {int(target)}")


def mixTwoQubitDepolarising(qureg: Qureg, q1: int, q2: int, prob: float) -> None:
    """ρ → (1-p)ρ + p/15 Σ_{P≠I⊗I} PρP, via a 16-operator Kraus superoperator
    (the reference's three-phase masked kernels, QuEST_cpu.c:387-695, are a
    memory-traffic optimisation of exactly this channel)."""
    V.validate_density_matr_qureg(qureg, "mixTwoQubitDepolarising")
    V.validate_unique_targets(qureg, q1, q2, "mixTwoQubitDepolarising")
    V.validate_two_qubit_depol_prob(prob, "mixTwoQubitDepolarising")
    p = float(prob)
    ops = []
    for i in range(4):
        for j in range(4):
            fac = math.sqrt(1 - p) if (i == 0 and j == 0) else math.sqrt(p / 15)
            ops.append(fac * np.kron(PAULI_MATRICES[j], PAULI_MATRICES[i]))
    qureg.amps = _deco.apply_kraus_map(qureg.amps, ops, (int(q1), int(q2)),
                                       qureg.num_qubits_represented,
                                       validate=False)  # CPTP by construction
    qureg.qasm.record_comment(
        f"Here, a two-qubit depolarising channel of probability {p:g} was applied.")


def mixPauli(qureg: Qureg, target: int, prob_x: float, prob_y: float,
             prob_z: float) -> None:
    """Kraus map {√(1-px-py-pz) I, √px X, √py Y, √pz Z}
    (ref: densmatr_mixPauli, QuEST_common.c:676-696)."""
    V.validate_density_matr_qureg(qureg, "mixPauli")
    V.validate_target(qureg, target, "mixPauli")
    V.validate_pauli_probs(prob_x, prob_y, prob_z, "mixPauli")
    facs = [math.sqrt(max(0.0, 1 - prob_x - prob_y - prob_z)),
            math.sqrt(prob_x), math.sqrt(prob_y), math.sqrt(prob_z)]
    ops = [facs[i] * PAULI_MATRICES[i] for i in range(4)]
    qureg.amps = _deco.apply_kraus_map(qureg.amps, ops, (int(target),),
                                       qureg.num_qubits_represented,
                                       validate=False)  # CPTP by construction
    qureg.qasm.record_comment(
        f"Here, a Pauli noise channel was applied to qubit {int(target)}")


def _mix_kraus(qureg: Qureg, targets, ops, num_ops, func: str) -> None:
    if num_ops is not None:
        ops = list(ops)[:int(num_ops)]
    ops = list(ops)
    targets = _ts(targets)
    V.validate_density_matr_qureg(qureg, func)
    V.validate_multi_targets(qureg, targets, func)
    V.validate_num_kraus_ops(len(targets), len(ops), func)
    V.validate_kraus_sizes(ops, len(targets), func)
    V.validate_kraus_cptp(ops, func, eps=real_eps(qureg.dtype))
    V.validate_multi_qubit_matrix_fits_in_shard(qureg, 2 * len(targets), func)
    qureg.amps = _deco.apply_kraus_map(qureg.amps, ops, targets,
                                       qureg.num_qubits_represented,
                                       validate=False)  # validate_kraus_cptp ran above
    qureg.qasm.record_comment(
        f"Here, an undisclosed Kraus map was applied to {len(targets)} qubit(s)")


def mixKrausMap(qureg: Qureg, target: int, ops, num_ops=None) -> None:
    _mix_kraus(qureg, (int(target),), ops, num_ops, "mixKrausMap")


def mixTwoQubitKrausMap(qureg: Qureg, t1: int, t2: int, ops, num_ops=None) -> None:
    _mix_kraus(qureg, (int(t1), int(t2)), ops, num_ops, "mixTwoQubitKrausMap")


def mixMultiQubitKrausMap(qureg: Qureg, targets, num_targets=None, ops=None,
                          num_ops=None) -> None:
    if ops is None:
        ops = num_targets
        num_targets = None
    if num_targets is not None:
        targets = _ts(targets)[:int(num_targets)]
    _mix_kraus(qureg, targets, ops, num_ops, "mixMultiQubitKrausMap")


def mixDensityMatrix(qureg: Qureg, prob: float, other: Qureg) -> None:
    V.validate_density_matr_qureg(qureg, "mixDensityMatrix")
    V.validate_density_matr_qureg(other, "mixDensityMatrix")
    V.validate_matching_qureg_dims(qureg, other, "mixDensityMatrix")
    V.validate_prob(prob, "mixDensityMatrix")
    qureg.amps = _deco.mix_density_matrix(qureg.amps, jnp.float64(prob), other.amps)
    qureg.qasm.record_comment(
        f"Here, the register was mixed with probability {float(prob):g}")


# ---------------------------------------------------------------------------
# operator application
# ---------------------------------------------------------------------------

def applyPauliSum(in_qureg: Qureg, all_codes, term_coeffs, num_sum_terms,
                  out_qureg: Qureg) -> None:
    """out = Σ_t c_t P_t |in> as ONE compiled program, one structured pass per term
    (ref: statevec_applyPauliSum, QuEST_common.c:493-515, which clones and
    accumulates per term; row-side products on density quregs, as there)."""
    V.validate_matching_qureg_types(in_qureg, out_qureg, "applyPauliSum")
    V.validate_matching_qureg_dims(in_qureg, out_qureg, "applyPauliSum")
    n = in_qureg.num_qubits_represented
    V.validate_num_pauli_sum_terms(int(num_sum_terms), "applyPauliSum")
    codes = np.asarray(all_codes, dtype=np.int64).reshape(-1, n)[:int(num_sum_terms)]
    coeffs = np.asarray(term_coeffs, dtype=np.float64).ravel()[:int(num_sum_terms)]
    V.validate_num_pauli_sum_terms(len(codes), "applyPauliSum")
    V.validate_pauli_codes(codes.ravel(), codes.size, "applyPauliSum")
    out_qureg.amps = _calc.apply_pauli_sum(
        in_qureg.amps, _pauli_sum_terms(codes),
        jnp.asarray(coeffs)).astype(out_qureg.dtype)


def applyPauliHamil(in_qureg: Qureg, hamil: PauliHamil, out_qureg: Qureg) -> None:
    V.validate_pauli_hamil(hamil, "applyPauliHamil")
    V.validate_matching_hamil_qureg_dims(in_qureg, hamil, "applyPauliHamil")
    applyPauliSum(in_qureg, hamil.pauli_codes, hamil.term_coeffs,
                  hamil.num_sum_terms, out_qureg)


def _apply_exponentiated_pauli_hamil(qureg: Qureg, hamil: PauliHamil, fac: float,
                                     reverse: bool) -> None:
    """First-order product formula exp(-i fac H) ≈ Π_j exp(-i fac c_j h_j)
    (ref: applyExponentiatedPauliHamil, QuEST_common.c:698+)."""
    n = hamil.num_qubits
    vec_targets = tuple(range(n))
    dens_targets = tuple(range(n, 2 * n))
    order = range(hamil.num_sum_terms)
    if reverse:
        order = reversed(order)
    for t in order:
        angle = 2 * fac * float(hamil.term_coeffs[t])
        codes = hamil.pauli_codes[t]
        qureg.amps = _multi_rotate_pauli_statevec(
            qureg.amps, vec_targets, codes, angle, False)
        if qureg.is_density_matrix:
            qureg.amps = _multi_rotate_pauli_statevec(
                qureg.amps, dens_targets, codes, angle, True)


def _apply_symmetrized_trotter(qureg: Qureg, hamil: PauliHamil, time: float,
                               order: int) -> None:
    """Symmetrized Suzuki recursion (ref: applySymmetrizedTrotterCircuit,
    QuEST_common.c:755-775)."""
    if order == 1:
        _apply_exponentiated_pauli_hamil(qureg, hamil, time, False)
    elif order == 2:
        _apply_exponentiated_pauli_hamil(qureg, hamil, time / 2.0, False)
        _apply_exponentiated_pauli_hamil(qureg, hamil, time / 2.0, True)
    else:
        p = 1.0 / (4 - 4 ** (1.0 / (order - 1)))
        lower = order - 2
        _apply_symmetrized_trotter(qureg, hamil, p * time, lower)
        _apply_symmetrized_trotter(qureg, hamil, p * time, lower)
        _apply_symmetrized_trotter(qureg, hamil, (1 - 4 * p) * time, lower)
        _apply_symmetrized_trotter(qureg, hamil, p * time, lower)
        _apply_symmetrized_trotter(qureg, hamil, p * time, lower)


def applyTrotterCircuit(qureg: Qureg, hamil: PauliHamil, time: float,
                        order: int, reps: int) -> None:
    V.validate_pauli_hamil(hamil, "applyTrotterCircuit")
    V.validate_matching_hamil_qureg_dims(qureg, hamil, "applyTrotterCircuit")
    V.validate_trotter_params(order, reps, "applyTrotterCircuit")
    qureg.qasm.record_comment(
        f"Beginning of Trotter circuit (time {float(time):g}, order {order}, {reps} repetitions).")
    if time != 0:
        for _ in range(reps):
            _apply_symmetrized_trotter(qureg, hamil, float(time) / reps, order)
    qureg.qasm.record_comment("End of Trotter circuit")


def applyQFT(qureg: Qureg, qubits, num_qubits=None) -> None:
    """Quantum Fourier transform on the register formed by ``qubits``
    (``qubits[0]`` = least-significant), ordered output.

    TPU-native extension matching the name QuEST added in v3.5 (the v3.2
    reference ships QFT only as an example circuit).  Dispatches ONE fused
    XLA program (the compiled circuit path — per-gate dispatch would pay
    ~n²/2 launches); density registers get the conjugated column-side
    shadow, i.e. ρ → FρF†."""
    qubits = _ts(qubits)
    if num_qubits is not None:
        qubits = qubits[:int(num_qubits)]
    V.validate_multi_targets(qureg, qubits, "applyQFT")
    from .circuit import GateOp, _run_ops, qft_circuit

    base = qft_circuit(len(qubits))
    ops = []
    for op in base.ops:
        ops.append(GateOp(op.kind,
                          tuple(qubits[t] for t in op.targets),
                          tuple(qubits[c] for c in op.controls),
                          op.control_states, op.matrix, op.shape))
    if qureg.is_density_matrix:
        from .circuit import _shadow_op
        n = qureg.num_qubits_represented
        ops = [o for op in ops for o in (op, _shadow_op(op, n))]
    qureg.amps = _run_ops(qureg.amps, tuple(ops))
    qureg.qasm.record_comment(
        f"Here, a QFT was applied to {len(qubits)} qubits.")


# At/above this qubit count the QFT engine's trailing bit-reversal cannot
# fit (it needs a second copy of each plane in flight on the 15.75 GiB
# chip), so applyFullQFT runs the transform UNORDERED and records the
# reversal in the register's logical->physical qubit_map instead of paying
# the data movement — the API translates through the map, so callers see
# the ordered result.  Tests patch this down to exercise the deferred-map
# path at small sizes.
_QFT_UNORDERED_MIN_QUBITS = 30


def applyFullQFT(qureg: Qureg) -> None:
    """QFT on every qubit of the register (QuEST v3.5's applyFullQFT name).

    Statevector f32 registers with n >= 17 on an accelerator — and every
    plane-storage register (the 30q single-chip ceiling) — route through
    the in-place Pallas QFT engine (ops/qft_inplace.py — ~2(n-17)+1 HBM
    passes instead of n²/2 gates; measured 2.7e11 amps/s at 30q), consuming
    the register's own buffers (donated planes, one state copy of peak
    HBM).  At n >= 30 the transform is stored bit-reversed with the
    reversal deferred into ``qureg.qubit_map`` (see
    _QFT_UNORDERED_MIN_QUBITS); everything else takes the fused circuit
    program."""
    n = qureg.num_qubits_represented
    from .ops import qft_inplace as _qi

    engine_ok = (not qureg.is_density_matrix
                 and qureg.dtype == jnp.dtype(jnp.float32)
                 and _qi.layer_supported(n)
                 and (qureg.env is None or qureg.env.sharding is None)
                 and (qureg._planes is not None
                      or jax.default_backend() != "cpu"))
    if engine_ok:
        if qureg.qubit_map is not None:
            # the engine assumes physical == logical order; reconcile the
            # deferred permutation first (possible only below the ceiling)
            if 2 * qureg.dtype.itemsize * qureg.num_amps_total >= _qureg_mod.PLANE_MATERIALIZE_LIMIT_BYTES:
                V._throw(V.ErrorCode.PLANE_ONLY, "applyFullQFT")
            qureg.materialize_stacked()  # reconciles the map
        ordered = n < _QFT_UNORDERED_MIN_QUBITS
        re, im = qureg.take_planes()
        re, im = _qi.qft_planes(re, im, bit_reversal=ordered)
        qureg.set_planes(re, im,
                         None if ordered else tuple(range(n - 1, -1, -1)))
        qureg.qasm.record_comment(
            f"Here, a full QFT was applied to {n} qubits (in-place engine"
            f"{'' if ordered else ', deferred bit-reversal'}).")
        return
    applyQFT(qureg, list(range(n)))


def applyDiagonalOp(qureg: Qureg, op: DiagonalOp) -> None:
    V.validate_diag_op_init(op, "applyDiagonalOp")
    V.validate_matching_qureg_diag_dims(qureg, op, "applyDiagonalOp")
    if qureg.is_density_matrix:
        qureg.amps = _ap.densmatr_apply_diagonal(qureg.amps, op.amps,
                                                 qureg.num_qubits_represented)
    else:
        qureg.amps = _ap.apply_full_diagonal(qureg.amps, op.amps)
    qureg.qasm.record_comment("Here, an undisclosed diagonal operator was applied.")


def calcExpecDiagonalOp(qureg: Qureg, op: DiagonalOp) -> complex:
    V.validate_diag_op_init(op, "calcExpecDiagonalOp")
    V.validate_matching_qureg_diag_dims(qureg, op, "calcExpecDiagonalOp")
    if qureg.is_density_matrix:
        pair = _calc.expec_diagonal_op_densmatr(
            qureg.amps, op.amps, qureg.num_qubits_represented)
    else:
        pair = _calc.expec_diagonal_op_statevec(qureg.amps, op.amps)
    pair = np.asarray(pair)
    return complex(pair[0], pair[1])


def setWeightedQureg(fac1, qureg1: Qureg, fac2, qureg2: Qureg, fac_out,
                     out: Qureg) -> None:
    V.validate_matching_qureg_types(qureg1, qureg2, "setWeightedQureg")
    V.validate_matching_qureg_types(qureg1, out, "setWeightedQureg")
    V.validate_matching_qureg_dims(qureg1, qureg2, "setWeightedQureg")
    V.validate_matching_qureg_dims(qureg1, out, "setWeightedQureg")
    def _fac(f):
        f = complex(f)
        return jnp.asarray([f.real, f.imag], dtype=jnp.float64)
    out.amps = _init.weighted_qureg(
        _fac(fac1), qureg1.amps, _fac(fac2), qureg2.amps, _fac(fac_out), out.amps)
    out.qasm.record_comment("Here, the register was set to a weighted sum of registers.")


# ---------------------------------------------------------------------------
# QASM
# ---------------------------------------------------------------------------

def startRecordingQASM(qureg: Qureg) -> None:
    qureg.qasm.is_logging = True


def stopRecordingQASM(qureg: Qureg) -> None:
    qureg.qasm.is_logging = False


def clearRecordedQASM(qureg: Qureg) -> None:
    qureg.qasm.clear()


def printRecordedQASM(qureg: Qureg) -> None:
    qureg.qasm.print()


def writeRecordedQASMToFile(qureg: Qureg, filename: str) -> None:
    try:
        qureg.qasm.write_to_file(filename)
    except OSError:
        V._throw(V.ErrorCode.CANNOT_OPEN_FILE, "writeRecordedQASMToFile", filename)


# ---------------------------------------------------------------------------
# reporting / debug
# ---------------------------------------------------------------------------

def reportState(qureg: Qureg) -> None:
    """CSV dump (ref: reportState, QuEST_common.c:216-232)."""
    with open("state_rank_0.csv", "w") as f:
        f.write("real, imag\n")
        arr = np.asarray(qureg.amps)
        for re, im in zip(arr[0], arr[1]):
            f.write(f"{re:.12f}, {im:.12f}\n")


def reportStateToScreen(qureg: Qureg, env: QuESTEnv = None, report_rank: int = 0) -> None:
    """Stdout format matches the reference exactly (ref: QuEST_cpu.c:1366-1388,
    REAL_STRING_FORMAT = %.14f) so reference-program output diffs clean."""
    if qureg.num_qubits_in_state_vec > 5:
        print("Error: reportStateToScreen will not print output for systems of "
              "more than 5 qubits.")
        return
    arr = np.asarray(qureg.amps, dtype=np.float64)
    if report_rank:
        print("Reporting state from rank 0 [")
    else:
        print("Reporting state [")
    print("real, imag")
    for re, im in zip(arr[0], arr[1]):
        print(f"{re:.14f}, {im:.14f}")
    print("]")


def QuESTPrecision() -> int:
    """Runtime precision, 1 (f32) or 2 (f64) (ref: QuEST_debug.h:55 — there a
    compile-time constant)."""
    from .precision import get_precision
    return get_precision()


def _amps_buffer(qureg: Qureg) -> np.ndarray:
    """C-shim helper: the amplitudes as a C-contiguous (2, numAmps) float64
    array (the shim memcpys this into the C Qureg's host stateVec mirror for
    copyStateFromGPU, ref: QuEST_gpu.cu:451-473)."""
    return np.ascontiguousarray(np.asarray(qureg.amps, dtype=np.float64))


def initStateFromSingleFile(qureg: Qureg, filename: str, env: QuESTEnv = None) -> int:
    """Load amplitudes from a single text file of ``re, im`` lines with
    ``#`` comments — the debug-API loader (ref: statevec_initStateFromSingleFile,
    QuEST_cpu.c:1625-1673).  Returns 1 on success, 0 if the file cannot be
    opened, like the reference.  Unparseable non-comment lines count toward
    the index but leave zeros (the reference's sscanf leaves the slot as-is)."""
    V.validate_state_vec_qureg(qureg, "initStateFromSingleFile")
    try:
        f = open(filename)
    except OSError:
        return 0
    total = qureg.num_amps_total
    re = np.zeros(total)
    im = np.zeros(total)
    idx = 0
    with f:
        for line in f:
            if line.startswith("#") or idx >= total:
                continue
            parts = line.split(",")
            try:
                re[idx] = float(parts[0])
                im[idx] = float(parts[1])
            except (ValueError, IndexError):
                pass
            idx += 1
    amps = jnp.asarray(np.stack([re, im]), dtype=qureg.dtype)
    qureg.set_amps_array(amps)
    return 1


def _validate_create_qureg(num_qubits: int, num_ranks: int, is_density: int) -> None:
    """C-shim helper: validate createQureg params against the C-side env
    struct's rank count (C programs may modify env.numRanks directly — the
    reference's own tests do exactly that)."""
    # mirror the reference's unsigned comparison: a negative C int rank
    # count (e.g. an overflowed (int)pow(2, 2n) in user code) converts to a
    # huge unsigned value and must fail the amps-per-rank check
    env = QuESTEnv(mesh=None, num_ranks=int(num_ranks) % (1 << 64))
    V.validate_create_num_qubits(
        int(num_qubits), env,
        "createDensityQureg" if is_density else "createQureg",
        factor=2 if is_density else 1)


def _validate_create_diag(num_qubits: int, num_ranks: int) -> None:
    """C-shim helper: createDiagonalOp validation against the C env struct's
    rank count (see _validate_create_qureg)."""
    env = QuESTEnv(mesh=None, num_ranks=int(num_ranks) % (1 << 64))
    if num_qubits < 1:
        V._throw(V.ErrorCode.INVALID_NUM_CREATE_QUBITS, "createDiagonalOp")
    if num_qubits > 63:
        V._throw(V.ErrorCode.NUM_AMPS_EXCEED_TYPE, "createDiagonalOp")
    if 2 ** num_qubits < env.num_ranks:
        V._throw(V.ErrorCode.DISTRIB_DIAG_OP_TOO_SMALL, "createDiagonalOp")


def _matrix_from_buffer(num_qubits: int, buf: bytes) -> np.ndarray:
    """C-shim helper: rebuild a complex matrix from the shim's packed
    (re-plane, im-plane) float64 buffer — O(1) Python objects per matrix
    instead of one per element."""
    dim = 1 << int(num_qubits)
    arr = np.frombuffer(buf, dtype=np.float64).reshape(2, dim, dim)
    return arr[0] + 1j * arr[1]


def _hamil_buffers(hamil: PauliHamil):
    """C-shim helper: (flat int32 codes, float64 coeffs) contiguous arrays."""
    codes = np.ascontiguousarray(np.asarray(hamil.pauli_codes, dtype=np.int32).ravel())
    coeffs = np.ascontiguousarray(np.asarray(hamil.term_coeffs, dtype=np.float64))
    return codes, coeffs


def copyStateToGPU(qureg: Qureg) -> None:
    """No-op: jax arrays live on-device (ref parity: copyStateToGPU)."""


def copyStateFromGPU(qureg: Qureg) -> None:
    """No-op: host reads fetch on demand (ref parity: copyStateFromGPU)."""
