"""quest_tpu.grad — adjoint-gradient serving for variational training.

The serving stack (PRs 5/11) ran only FORWARD circuits; this package makes
``(energy, gradient)`` a first-class served request (ROADMAP item 6):

- :mod:`.adjoint` — the structural-class-lifted adjoint program
  ``(state, params, coeffs) -> (energy, grad)``: O(1)-state reverse gate
  replay (three live statevectors at any depth), compiled ONCE per
  (circuit class, Hamiltonian mask shape) by the serve compile cache's
  gradient entry kind (serve/cache.py ``grad_entry_for``), plus the
  admission validation (``E_GRADIENT_NOT_UNITARY`` /
  ``E_GRADIENT_DENSITY_MODE``).
- :class:`GradResult` — what ``QuESTService.submit_gradient`` futures
  resolve to.
- :mod:`.loop` — :func:`training_loop`: the submit-ahead pipelined
  optimizer driver (multi-start chains microbatch into one ``lax.map``
  dispatch per wave; one compile per training run).

See docs/SERVING.md "Gradient serving".
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .adjoint import (adjoint_terms_fn, grad_group_signature, hamil_masks,
                      validate_gradient_circuit)
from .loop import TrainingResult, sgd, training_loop

__all__ = ["GradResult", "adjoint_terms_fn", "grad_group_signature",
           "hamil_masks", "validate_gradient_circuit",
           "TrainingResult", "sgd", "training_loop"]


@dataclasses.dataclass
class GradResult:
    """One completed gradient request: the energy ``<psi|H|psi>`` and the
    full parameter gradient at the submitted angles, plus the batch
    context it executed in — the gradient twin of
    :class:`~quest_tpu.serve.service.ServeResult`.  ``cache_outcome`` and
    ``numeric_health`` feed the deploy router exactly like forward
    results: gradient classes are routable classes with their own
    affinity, and a NaN in the backward pass quarantines the (class,
    replica) placement."""
    energy: float
    gradient: np.ndarray
    batch_size: int
    request_id: int
    cache_outcome: str | None = None
    numeric_health: dict | None = None
