"""The lifted adjoint-gradient program: ``(state, params, coeffs) ->
(energy, gradient)`` compiled ONCE per (ansatz class, Hamiltonian shape).

``autodiff.adjoint_gradient_fn`` (PR seed) already implements the
O(1)-state adjoint-differentiation method — reverse gate replay holding
three live statevectors for any depth, where taped reverse-mode holds
depth+1.  But it closes over the initial state AND the Hamiltonian's term
coefficients, so every (ansatz, Hamiltonian) pair is its own jit trace:
the one-compile-per-tenant defect the serve cache fixed for forward
circuits, reborn for gradients.  This module factors the adjoint sweep
into a PURE body over three runtime operands:

- ``state``  — the initial statevector (the serving layer's |0..0> or a
  tenant-supplied register),
- ``params`` — the flat float64 parameter vector a :class:`ParamCircuit`'s
  ``Param`` placeholders index (the lift is free: parametric angles are
  runtime operands by construction, unlike forward GateOp payloads),
- ``coeffs`` — the Hamiltonian's term coefficients.  The PACKED TERM MASKS
  (:func:`hamil_masks`) stay static — they select the Pauli-sum kernel's
  data movement, i.e. the program — so a Hamiltonian-coefficient sweep
  (bond-length scans, re-weighted MaxCut) reuses one executable while a
  different Pauli structure is honestly a different class.

The serve cache (serve/cache.py ``grad_entry_for``) keys ONE such program
on (num_qubits, op tuple, masks): an optimizer driving thousands of steps
with the same circuit skeleton and different angles — the variational-
training workload of ROADMAP item 6 — compiles once, total.

Admission validation lives here too (:func:`validate_gradient_circuit`):
the adjoint method's unitarity requirement surfaces as clean
``QuESTError`` codes (``E_GRADIENT_NOT_UNITARY`` /
``E_GRADIENT_DENSITY_MODE``) at BOTH entry points — program construction
and ``QuESTService.submit_gradient`` admission — instead of the bare
``ValueError``\\ s the seed raised.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..validation import ErrorCode, MESSAGES, QuESTError

__all__ = ["validate_gradient_circuit", "hamil_masks", "adjoint_terms_fn",
           "grad_group_signature"]

#: static GateOp kinds the backward sweep can invert exactly (x/y/swap are
#: self-inverse, mrz negates its angle, matrix takes the conjugate
#: transpose, diagonal the reciprocal — exact for unit-modulus entries)
_INVERTIBLE_STATIC = ("matrix", "diagonal", "x", "y", "swap", "mrz")


def _unitary_eps() -> float:
    from ..precision import CONFIG
    return float(CONFIG.real_eps)


def validate_gradient_circuit(pc, func: str = "adjoint_gradient_fn") -> None:
    """The adjoint method's admission contract, as clean validation codes.

    ``E_GRADIENT_NOT_UNITARY`` — a noise channel (dephase / depolarise /
    damp: CPTP maps, not unitaries), a gate kind with no exact inverse, a
    non-unitary embedded matrix, or a non-unit-modulus diagonal: any of
    these breaks the backward sweep's uncompute (psi and the adjoint state
    must evolve by U^-1 = U^dagger).  Matrices are checked host-side
    against the precision layer's REAL_EPS — the same tolerance the eager
    API's unitarity guards use."""
    from ..autodiff import ParamCircuit, ParamOp, _NOISE_KINDS

    if not isinstance(pc, ParamCircuit):
        raise TypeError(
            f"{func} takes a ParamCircuit (quest_tpu.autodiff), got "
            f"{type(pc)!r}")
    eps = _unitary_eps()
    for op in pc.ops:
        if isinstance(op, ParamOp):
            if op.kind in _NOISE_KINDS:
                raise QuESTError(
                    ErrorCode.GRADIENT_NOT_UNITARY,
                    MESSAGES[ErrorCode.GRADIENT_NOT_UNITARY]
                    + f" (noise channel {op.kind!r} on {op.targets})", func)
            continue
        if op.kind not in _INVERTIBLE_STATIC:
            raise QuESTError(
                ErrorCode.GRADIENT_NOT_UNITARY,
                MESSAGES[ErrorCode.GRADIENT_NOT_UNITARY]
                + f" (gate kind {op.kind!r} has no exact inverse here)",
                func)
        if op.kind == "matrix":
            p = op.payload()
            m = p[0] + 1j * p[1]
            if not np.allclose(m @ m.conj().T, np.eye(m.shape[0]),
                               atol=max(eps, 1e-10)):
                raise QuESTError(
                    ErrorCode.GRADIENT_NOT_UNITARY,
                    MESSAGES[ErrorCode.GRADIENT_NOT_UNITARY]
                    + f" (embedded matrix on {op.targets} is not unitary)",
                    func)
        elif op.kind == "diagonal":
            p = op.payload()
            mag2 = p[0] ** 2 + p[1] ** 2
            if not np.allclose(mag2, 1.0, atol=max(eps, 1e-10)):
                raise QuESTError(
                    ErrorCode.GRADIENT_NOT_UNITARY,
                    MESSAGES[ErrorCode.GRADIENT_NOT_UNITARY]
                    + f" (diagonal on {op.targets} is not unit-modulus)",
                    func)


def hamil_masks(hamil) -> tuple:
    """The Hamiltonian's STATIC packed term masks ``((x, zy, yc), ...)`` —
    per term: the X|Y bit mask, the Z|Y bit mask and the Y count mod 4
    (api.py ``_pauli_sum_terms``, the structured Pauli-sum kernel's static
    form).  This tuple is the Hamiltonian's contribution to the gradient
    class key: same Pauli structure = same program, coefficients ride as a
    runtime operand."""
    from ..api import _pauli_sum_terms
    from .. import validation as V

    V.validate_pauli_hamil(hamil, "hamil_masks")
    return _pauli_sum_terms(np.asarray(hamil.pauli_codes))


def grad_group_signature(pc, masks) -> tuple:
    """The hashable gradient-class signature ``("grad", op tuple, masks)``
    shared by the service's batching key, the cache's structural key and
    the router's affinity key.  The op tuple needs no payload lift:
    ``Param`` placeholders ARE structural (frozen index/scale/shift
    records), and a recorded ansatz's static gates (h walls, CZ ladders)
    are identical across tenants by construction — two builds of the same
    ansatz recipe hash equal."""
    return ("grad", tuple(pc.ops), tuple(masks))


def adjoint_terms_fn(ops, num_qubits: int, num_params: int, terms,
                     return_state: bool = False, barriers: bool = True):
    """The pure adjoint sweep ``(state, params, coeffs) -> (energy,
    gradient)`` over static ``terms`` masks — the body every gradient
    program variant (single, batched, probed) lowers, and the one
    ``autodiff.adjoint_gradient_fn`` closes its constants over.

    Forward applies the circuit with no taping; the head is the fused
    Pauli-sum ``|lam> = H|psi>`` (ops/calc.py) and ``E = <psi|lam>``; the
    backward sweep walks the ops in reverse, taking one generator inner
    product ``Im<lam|P_c G|psi>`` per parametric gate and uncomputing BOTH
    states by gate inverses — three live statevectors at any depth.  The
    per-step ``optimization_barrier`` pins the uncompute schedule (without
    it XLA holds many steps' buffers live at once; observed HBM OOM at
    28q) and is also what makes the ``lax.map`` batch lowering
    bit-identical to serial execution.

    ``return_state=True`` additionally returns the round-tripped |psi>
    (forward then fully uncomputed) — the probe point of the instrumented
    serving variant: its norm must equal the INPUT norm, so uncompute
    drift and backward-pass NaN both surface on the numeric ledger.

    ``barriers=False`` builds the barrier-free twin for transforms that
    lack an ``optimization_barrier`` rule on this jax (``jax.vmap`` — the
    serve cache's ``mode='vmap'`` throughput lowering, which makes no
    bit-identity or peak-memory claims)."""
    from .. import precision as _prec
    from ..autodiff import (Param, _apply_param_op, _gen_inner_im,
                            _inverse_gate_op)
    from ..circuit import GateOp, _apply_one

    ops = tuple(ops)
    terms = tuple(terms)
    inv_static = {id(op): _inverse_gate_op(op)
                  for op in ops if isinstance(op, GateOp)}
    bar = jax.lax.optimization_barrier if barriers else (lambda x: x)

    def value_and_grad(state, params, coeffs):
        from ..ops import calc as _calc

        params = jnp.asarray(params)
        if not jnp.issubdtype(params.dtype, jnp.floating):
            params = params.astype(_prec.CONFIG.real_dtype)
        coeffs = jnp.asarray(coeffs)
        psi = state
        for op in ops:  # forward, no taping
            psi = (_apply_one(psi, op) if isinstance(op, GateOp)
                   else _apply_param_op(psi, op, params, None))
        # barriers around the head: every later backward step consumes the
        # previous step's barrier output, but the FIRST step — and the
        # Pauli-sum head itself — would otherwise read raw forward
        # dataflow, the one place left where a lax.map batch body and the
        # singleton program can contract FMAs differently (observed: a
        # one-ulp drift on exactly the final parameter's gradient, fed by
        # the head fusing the last gate's application into its first
        # term).  Batched gradients bit-identical to serial, by
        # construction — the same discipline as the per-step barrier below.
        psi = bar(psi)
        lam = _calc.apply_pauli_sum(psi, terms, coeffs)
        lam = bar(lam)
        energy = jnp.sum(psi[0] * lam[0] + psi[1] * lam[1])
        grads = jnp.zeros(num_params, dtype=params.dtype)
        for op in reversed(ops):
            if isinstance(op, GateOp):
                inv = inv_static[id(op)]
                psi = _apply_one(psi, inv)
                lam = _apply_one(lam, inv)
            else:
                if isinstance(op.param, Param):
                    contrib = _gen_inner_im(lam, psi, op) * op.param.scale
                    grads = grads.at[op.param.index].add(
                        contrib.astype(params.dtype))
                psi = _apply_param_op(psi, op, params, None, invert=True)
                lam = _apply_param_op(lam, op, params, None, invert=True)
            # pin the schedule: without the barrier XLA may hold many
            # uncompute steps' buffers live at once (observed HBM OOM at 28q)
            psi, lam, grads = bar((psi, lam, grads))
        if return_state:
            return energy, grads.astype(params.dtype), psi
        return energy, grads.astype(params.dtype)

    return value_and_grad
