"""The optimizer-loop driver: variational training over gradient serving.

A VQE/QAOA training run is thousands of optimizer steps with the SAME
circuit skeleton and different angles — the workload gradient serving
exists for.  :func:`training_loop` drives it through any object with a
``submit_gradient`` front door (a :class:`~quest_tpu.serve.service.QuESTService`,
a deploy :class:`~quest_tpu.deploy.router.Router` or
:class:`~quest_tpu.deploy.pool.ReplicaPool`) with SUBMIT-AHEAD pipelining:
every chain's next step is submitted the moment its gradient resolves, so
while the host runs one chain's optimizer math the service is already
batching/dispatching the others' device work — and multi-start chains
(``init_params`` of shape (S, P)) land in the same structural class, so
the service microbatches them into ONE compiled ``lax.map`` dispatch per
wave.  One compile serves the entire training run: step 1's class miss is
the only trace, every later step is a cache hit (pinned in
tests/test_grad.py).

The update rule is any ``update(params, gradient, step) -> params``
callable (:func:`sgd` is the batteries-included default); determinism is
inherited from serving's bit-identity contract — batched gradients are
bit-identical to serial execution, so a training run's trajectory does not
depend on how its steps happened to co-batch.
"""

from __future__ import annotations

import dataclasses
import time
from concurrent.futures import FIRST_COMPLETED, wait

import numpy as np

__all__ = ["sgd", "training_loop", "TrainingResult"]


def sgd(lr: float = 0.05):
    """Plain gradient descent ``params - lr * grad`` (the default update;
    any ``update(params, gradient, step) -> params`` callable slots in —
    optax users wrap their ``opt.update`` here)."""
    lr = float(lr)

    def update(params, gradient, step):
        return params - lr * np.asarray(gradient)

    return update


@dataclasses.dataclass
class TrainingResult:
    """One finished run: final parameters and the full energy history.
    ``params`` / ``energies`` keep the submitted shape — (P,) and (steps,)
    for a single chain, (S, P) and (S, steps) for multi-start."""
    params: np.ndarray
    energies: np.ndarray
    steps: int
    requests: int
    wall_seconds: float

    @property
    def best_energy(self) -> float:
        return float(np.min(self.energies[..., -1]))


def training_loop(service, circuit, hamiltonian, init_params, steps: int,
                  update=None, *, lr: float = 0.05,
                  deadline_ms: float | None = None,
                  probes: bool | None = None,
                  timeout_s: float = 600.0) -> TrainingResult:
    """Run ``steps`` optimizer steps per chain through gradient serving.

    ``init_params`` is one parameter vector (P,) or a multi-start stack
    (S, P).  Each chain's step ``k+1`` is submitted as soon as step ``k``'s
    ``(energy, gradient)`` resolves and the host update is applied —
    chains pipeline against each other, and same-class submissions
    microbatch.  ``update(params, gradient, step)`` defaults to
    :func:`sgd`(``lr``).  The recorded energy history is the energy AT the
    submitted parameters (so ``energies[..., 0]`` is the initial point's
    energy and the final ``params`` has had ``steps`` updates applied)."""
    if update is None:
        update = sgd(lr)
    steps = int(steps)
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    p0 = np.asarray(init_params, np.float64)
    single = p0.ndim == 1
    chains = p0[None, :].copy() if single else p0.copy()
    n_chains = chains.shape[0]
    energies = np.zeros((n_chains, steps), np.float64)
    step_of = [0] * n_chains
    t0 = time.perf_counter()
    inflight = {
        service.submit_gradient(circuit, chains[i], hamiltonian,
                                deadline_ms=deadline_ms, probes=probes): i
        for i in range(n_chains)}
    requests = n_chains
    while inflight:
        done, _ = wait(list(inflight), timeout=timeout_s,
                       return_when=FIRST_COMPLETED)
        if not done:
            raise TimeoutError(
                f"training_loop: no gradient resolved within {timeout_s}s "
                f"({len(inflight)} chain(s) in flight)")
        for fut in done:
            i = inflight.pop(fut)
            res = fut.result()
            k = step_of[i]
            energies[i, k] = float(res.energy)
            chains[i] = np.asarray(
                update(chains[i], np.asarray(res.gradient), k), np.float64)
            step_of[i] = k + 1
            if k + 1 < steps:
                # submit-ahead: this chain goes straight back into the
                # batching window while the loop turns to the next future
                inflight[service.submit_gradient(
                    circuit, chains[i], hamiltonian,
                    deadline_ms=deadline_ms, probes=probes)] = i
                requests += 1
    wall = time.perf_counter() - t0
    if single:
        return TrainingResult(chains[0], energies[0], steps, requests, wall)
    return TrainingResult(chains, energies, steps, requests, wall)
