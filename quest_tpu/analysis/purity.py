"""Pass 3: source-level JAX-purity lint over the quest_tpu tree itself.

The round-5 review bugs (eager/compiled dtype drift, storage misrouting)
belong to a *source* bug class no circuit-level check can see: host Python
leaking into traced code.  This linter parses each module's AST and flags
jit-unsafe patterns inside jit-decorated functions — conservatively: a rule
fires only on provable violations (a traced *parameter name* used directly),
never on derived values, so the pass stays false-positive-free on a clean
tree and is enforceable in tier-1 CI (``python -m quest_tpu.analysis
--self-lint``).

Rules
-----
- ``P_TRACED_PYTHON_BRANCH``: ``if``/``while`` whose test names a traced
  parameter of the enclosing jit function (trace-time branch).
- ``P_HOST_CAST_ON_TRACED``: ``float()``/``int()``/``bool()`` on a traced
  parameter (concretization error / host round-trip).
- ``P_NUMPY_ON_TRACED``: ``np.*(...)`` with a traced parameter argument
  (trace-time host compute frozen into the program).
- ``P_ANGLE_NOT_F64``: an ``apply_multi_rotate_z`` angle operand cast to a
  dtype other than ``jnp.float64`` (the circuit.py:208 bug class; the
  eager path pins float64).
- ``P_HOST_CALLBACK_IN_SHARD_MAP``: ``jax.debug.callback`` /
  ``pure_callback`` / ``io_callback`` / ``host_callback`` inside a
  shard_map-decorated function.
- ``P_IMPORT_TIME_STATE_MUTATION``: module-import-time mutation of
  process-global state — ``jax.config``, global RNG state
  (``np.random.seed`` / ``random.seed``), or process hooks
  (``atexit.register``): import order silently changes behaviour
  process-wide.  Allowlisted sites: ``quest_tpu/_compat.py`` (the single
  place the package-wide x64 default is set) and ``quest_tpu/obs/trace.py``
  (the span-recorder singleton's crash-dump atexit hook — one process, one
  trace).
- ``P_DAEMON_THREAD_LEAK`` (``serve/`` and ``deploy/`` files only): every
  ``threading.Thread`` constructed in the runtime packages must either be
  joined — a ``.join(...)`` in the same function, or (for ``self.X``
  threads) a ``self.X.join(...)`` anywhere in the module's shutdown/close
  paths — or be daemonized WITH a ``# daemon-ok: <reason>`` comment on the
  construction statement.  An unjoined non-daemon thread blocks process
  exit; an uncommented daemon thread is a worker nobody owns.
"""

from __future__ import annotations

import ast
import os
import re

from .diagnostics import AnalysisCode, Diagnostic, Severity, diag

_HOST_CASTS = ("float", "int", "bool")
_CALLBACK_NAMES = ("callback", "pure_callback", "io_callback", "host_callback")
_F64_NAMES = ("float64",)

# import-time global-state mutators (calls) and the config objects whose
# attribute assignment mutates process state.  atexit.register is in the
# list because an import-time exit hook is process-global state installed
# by import order — exactly the class of side effect this rule exists to
# keep out of library modules.
_IMPORT_MUTATOR_CALLS = ("jax.config.update", "config.update",
                         "np.random.seed", "numpy.random.seed",
                         "random.seed", "np.random.set_state",
                         "numpy.random.set_state", "atexit.register")
_IMPORT_MUTATOR_TARGETS = ("jax.config", "config")
# the modules allowed to mutate process state at import time — full path
# suffixes, so a stray _compat.py elsewhere is NOT exempt: _compat.py (the
# single site setting the package-wide x64 default) and obs/trace.py (the
# module-level span-recorder singleton registers its crash-dump atexit
# hook; one process, one trace — docs/OBSERVABILITY.md)
_IMPORT_MUTATION_ALLOWLIST = ("quest_tpu/_compat.py",
                              "quest_tpu/obs/trace.py")

# the runtime packages whose threads the P_DAEMON_THREAD_LEAK rule owns
# (path fragments; the analysis CLI lints the installed tree, tests lint
# synthetic sources with matching names)
_THREAD_LEAK_SCOPES = ("quest_tpu/serve/", "quest_tpu/deploy/")
_DAEMON_OK_RE = re.compile(r"#\s*daemon-ok:\s*\S")


def _dotted(node: ast.AST) -> str:
    """'jax.jit' for Attribute/Name chains, '' for anything else."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _decorator_call(dec: ast.AST) -> tuple[str, list, list]:
    """(dotted name, args, keywords) of a decorator, unwrapping partial()."""
    if isinstance(dec, ast.Call):
        name = _dotted(dec.func)
        if name in ("partial", "functools.partial") and dec.args:
            inner = _dotted(dec.args[0])
            return inner, dec.args[1:], dec.keywords
        return name, dec.args, dec.keywords
    return _dotted(dec), [], []


def _static_names(keywords: list, func: ast.FunctionDef) -> set[str]:
    """Parameter names excluded from tracing by static_argnames/argnums."""
    params = [a.arg for a in func.args.posonlyargs + func.args.args]
    static: set[str] = set()
    for kw in keywords:
        if kw.arg == "static_argnames":
            for node in ast.walk(kw.value):
                if isinstance(node, ast.Constant) and isinstance(node.value, str):
                    static.add(node.value)
        elif kw.arg == "static_argnums":
            for node in ast.walk(kw.value):
                if isinstance(node, ast.Constant) and isinstance(node.value, int):
                    if 0 <= node.value < len(params):
                        static.add(params[node.value])
    return static


def _jit_traced_params(func: ast.FunctionDef) -> set[str] | None:
    """Traced parameter names if ``func`` is jit-decorated, else None."""
    for dec in func.decorator_list:
        name, _args, keywords = _decorator_call(dec)
        if name in ("jax.jit", "jit"):
            params = {a.arg for a in func.args.posonlyargs + func.args.args}
            return params - _static_names(keywords, func)
    return None


def _is_shard_mapped(func: ast.FunctionDef) -> bool:
    for dec in func.decorator_list:
        name, _args, _kw = _decorator_call(dec)
        if name in ("shard_map", "jax.shard_map",
                    "jax.experimental.shard_map.shard_map"):
            return True
    return False


def _names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


# Attributes of a traced array that are static trace-time metadata: reading
# them (and branching on them) is host-safe, so `if state.dtype == ...` is
# NOT a traced branch even though `state` is traced.
_STATIC_ATTRS = frozenset(
    ("dtype", "shape", "ndim", "size", "itemsize", "sharding", "aval",
     "device", "weak_type"))


def _traced_value_names(node: ast.AST) -> set[str]:
    """Names used as VALUES in ``node``, skipping static-metadata reads."""
    names: set[str] = set()

    def walk(n: ast.AST) -> None:
        if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
            return
        if isinstance(n, ast.Name):
            names.add(n.id)
        for child in ast.iter_child_nodes(n):
            walk(child)

    walk(node)
    return names


class _Linter(ast.NodeVisitor):
    def __init__(self, filename: str):
        self.filename = filename
        self.out: list[Diagnostic] = []
        # innermost enclosing traced-parameter scope (None outside jit)
        self._traced: set[str] | None = None
        self._in_shard_map = False

    def _emit(self, code: str, node: ast.AST, detail: str) -> None:
        self.out.append(diag(code, Severity.ERROR, file=self.filename,
                             line=getattr(node, "lineno", None), detail=detail))

    # --- scope tracking ----------------------------------------------------
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        traced = _jit_traced_params(node)
        shard_mapped = _is_shard_mapped(node)
        prev, prev_sm = self._traced, self._in_shard_map
        if traced is not None:
            self._traced = traced
        if shard_mapped:
            self._in_shard_map = True
        self.generic_visit(node)
        self._traced, self._in_shard_map = prev, prev_sm

    visit_AsyncFunctionDef = visit_FunctionDef

    # --- rules -------------------------------------------------------------
    def _traced_in(self, node: ast.AST) -> set[str]:
        if not self._traced:
            return set()
        return self._traced & _traced_value_names(node)

    def visit_If(self, node: ast.If) -> None:
        hit = self._traced_in(node.test)
        if hit:
            self._emit(AnalysisCode.TRACED_PYTHON_BRANCH, node,
                       f"if on traced {sorted(hit)}")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        hit = self._traced_in(node.test)
        if hit:
            self._emit(AnalysisCode.TRACED_PYTHON_BRANCH, node,
                       f"while on traced {sorted(hit)}")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        name = _dotted(node.func)
        # host casts on a traced parameter, passed directly
        if name in _HOST_CASTS and self._traced:
            for arg in node.args:
                if isinstance(arg, ast.Name) and arg.id in self._traced:
                    self._emit(AnalysisCode.HOST_CAST_ON_TRACED, node,
                               f"{name}({arg.id})")
        # numpy on a traced parameter
        if name.startswith(("np.", "numpy.")) and self._traced:
            for arg in node.args:
                if isinstance(arg, ast.Name) and arg.id in self._traced:
                    self._emit(AnalysisCode.NUMPY_ON_TRACED, node,
                               f"{name}({arg.id}, ...)")
        # mrz angle must not be cast away from float64
        if name.endswith("apply_multi_rotate_z") and len(node.args) >= 2:
            self._check_angle(node.args[1])
        # host callbacks under shard_map
        if self._in_shard_map and name.split(".")[-1] in _CALLBACK_NAMES:
            self._emit(AnalysisCode.CALLBACK_IN_SHARD_MAP, node, name)
        self.generic_visit(node)

    def _check_angle(self, angle: ast.AST) -> None:
        """Flag only *provably* narrowing casts: jnp.asarray(x, dtype=D) or
        x.astype(D) with D a named dtype other than float64, or an explicit
        jnp.float32(...) constructor.  Bare names pass (unknowable here; the
        abstract-eval pass checks the built operand)."""
        if not isinstance(angle, ast.Call):
            return
        name = _dotted(angle.func)
        if name.split(".")[-1] == "float32":
            self._emit(AnalysisCode.ANGLE_NOT_F64, angle, f"{name}(...)")
            return
        dtype_node = None
        if name.split(".")[-1] in ("asarray", "array"):
            for kw in angle.keywords:
                if kw.arg == "dtype":
                    dtype_node = kw.value
        elif name.endswith(".astype") and angle.args:
            dtype_node = angle.args[0]
        if dtype_node is None:
            return
        dtype_name = _dotted(dtype_node)
        if dtype_name and dtype_name.split(".")[-1] not in _F64_NAMES:
            self._emit(AnalysisCode.ANGLE_NOT_F64, angle,
                       f"angle cast to {dtype_name}")


def _lint_import_time(tree: ast.Module, filename: str) -> list[Diagnostic]:
    """Flag global-state mutation that executes at module import: walks
    every statement reachable WITHOUT entering a function body (class
    bodies, if/try/with blocks and loops all run at import).
    ``quest_tpu/_compat.py`` is the single allowlisted site (the
    package-wide x64 default)."""
    normalized = os.path.normpath(filename).replace(os.sep, "/")
    if normalized.endswith(_IMPORT_MUTATION_ALLOWLIST):
        return []
    out: list[Diagnostic] = []

    def scan(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue  # runs at call time, not import time
            if isinstance(child, ast.Call):
                name = _dotted(child.func)
                if name in _IMPORT_MUTATOR_CALLS:
                    out.append(diag(
                        AnalysisCode.IMPORT_TIME_STATE_MUTATION,
                        Severity.ERROR, file=filename, line=child.lineno,
                        detail=f"{name}(...) at module import time"))
            elif isinstance(child, ast.Assign):
                for target in child.targets:
                    if (isinstance(target, ast.Attribute)
                            and _dotted(target.value)
                            in _IMPORT_MUTATOR_TARGETS):
                        out.append(diag(
                            AnalysisCode.IMPORT_TIME_STATE_MUTATION,
                            Severity.ERROR, file=filename, line=child.lineno,
                            detail=(f"assignment to {_dotted(target.value)}."
                                    f"{target.attr} at module import time")))
            scan(child)

    scan(tree)
    return out


def _thread_ctor(node: ast.AST) -> ast.Call | None:
    if (isinstance(node, ast.Call)
            and _dotted(node.func) in ("threading.Thread", "Thread")):
        return node
    return None


def _lint_thread_leaks(tree: ast.Module, filename: str,
                       source: str) -> list[Diagnostic]:
    """``P_DAEMON_THREAD_LEAK`` over serve/ and deploy/ modules: every
    constructed thread must be joined (same function, or ``self.X.join``
    anywhere in the module for ``self.X`` threads) or daemonized with a
    reasoned ``# daemon-ok:`` comment on its construction statement."""
    normalized = os.path.normpath(filename).replace(os.sep, "/")
    if not any(scope in normalized for scope in _THREAD_LEAK_SCOPES):
        return []
    lines = source.splitlines()

    def has_daemon_ok(start: int, end: int) -> bool:
        # the statement's own lines, plus the contiguous comment block
        # directly above it (the conventional place for the reason)
        while start > 1 and lines[start - 2].lstrip().startswith("#"):
            start -= 1
        return any(_DAEMON_OK_RE.search(lines[i - 1])
                   for i in range(start, min(end, len(lines)) + 1))

    # every `self.X.join(...)` receiver attr in the module (the shutdown/
    # close path of a worker-owning class joins its own thread attribute)
    self_joins: set[str] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"):
            recv = node.func.value
            if (isinstance(recv, ast.Attribute)
                    and isinstance(recv.value, ast.Name)
                    and recv.value.id == "self"):
                self_joins.add(recv.attr)

    out: list[Diagnostic] = []

    def check_ctor(ctor: ast.Call, st: ast.stmt,
                   has_local_join: bool) -> None:
        daemon = any(kw.arg == "daemon"
                     and isinstance(kw.value, ast.Constant)
                     and kw.value.value is True
                     for kw in ctor.keywords)
        if daemon:
            if not has_daemon_ok(st.lineno, st.end_lineno or st.lineno):
                out.append(diag(
                    AnalysisCode.DAEMON_THREAD_LEAK, Severity.ERROR,
                    file=filename, line=ctor.lineno,
                    detail="daemon=True without a '# daemon-ok: <reason>' "
                           "comment"))
            return
        if has_local_join:
            return
        self_attr = None
        if (isinstance(st, ast.Assign) and len(st.targets) == 1
                and isinstance(st.targets[0], ast.Attribute)
                and isinstance(st.targets[0].value, ast.Name)
                and st.targets[0].value.id == "self"):
            self_attr = st.targets[0].attr
        if self_attr is not None and self_attr in self_joins:
            return
        out.append(diag(
            AnalysisCode.DAEMON_THREAD_LEAK, Severity.ERROR,
            file=filename, line=ctor.lineno,
            detail="thread is never joined (no .join in this function"
                   + (f", no self.{self_attr}.join in the module"
                      if self_attr else "")
                   + ") and not daemonized"))

    def scan_function(fn: ast.AST) -> None:
        # names bound to threads in THIS function: assignment targets whose
        # value constructs a Thread (including list-builds), receivers of
        # .append(Thread(...)), plus for-loop / comprehension variables
        # iterating over such a name — a `.join(` only counts when its
        # receiver is one of these (a stray os.path.join or sep.join must
        # not silently satisfy the rule)
        joinable: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and any(
                    _thread_ctor(n) is not None for n in ast.walk(node.value)):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        joinable.add(t.id)
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr == "append"
                  and isinstance(node.func.value, ast.Name)
                  and any(_thread_ctor(n) is not None
                          for a in node.args for n in ast.walk(a))):
                joinable.add(node.func.value.id)
        grew = True
        while grew:         # loop aliases can chain (for t in ts: ...)
            grew = False
            for node in ast.walk(fn):
                targets: list = []
                if (isinstance(node, ast.For)
                        and isinstance(node.iter, ast.Name)
                        and node.iter.id in joinable):
                    targets = [node.target]
                elif (isinstance(node, ast.comprehension)
                      and isinstance(node.iter, ast.Name)
                      and node.iter.id in joinable):
                    targets = [node.target]
                for t in targets:
                    if isinstance(t, ast.Name) and t.id not in joinable:
                        joinable.add(t.id)
                        grew = True
        has_local_join = any(
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "join"
            and ((isinstance(node.func.value, ast.Name)
                  and node.func.value.id in joinable)
                 or (isinstance(node.func.value, ast.Subscript)
                     and isinstance(node.func.value.value, ast.Name)
                     and node.func.value.value.id in joinable))
            for node in ast.walk(fn))

        def descend(node: ast.AST, cur_stmt: ast.stmt | None) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue    # a nested def's threads get their own scan
                st = child if isinstance(child, ast.stmt) else cur_stmt
                ctor = _thread_ctor(child)
                if ctor is not None and st is not None:
                    check_ctor(ctor, st, has_local_join)
                descend(child, st)

        descend(fn, None)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_function(node)
    return out


def lint_source(source: str, filename: str = "<string>") -> list[Diagnostic]:
    """Lint one module's source text; returns purity diagnostics."""
    tree = ast.parse(source, filename=filename)
    linter = _Linter(filename)
    linter.visit(tree)
    return (linter.out + _lint_import_time(tree, filename)
            + _lint_thread_leaks(tree, filename, source))


def lint_paths(paths) -> list[Diagnostic]:
    """Lint ``.py`` files / directory trees; returns all diagnostics."""
    out: list[Diagnostic] = []
    files: list[str] = []
    for path in paths:
        if os.path.isdir(path):
            for root, _dirs, names in os.walk(path):
                files.extend(os.path.join(root, f) for f in sorted(names)
                             if f.endswith(".py"))
        else:
            files.append(path)
    for f in files:
        with open(f, "r", encoding="utf-8") as fh:
            out.extend(lint_source(fh.read(), f))
    return out


def lint_package() -> list[Diagnostic]:
    """Lint the installed quest_tpu tree (the --self-lint target)."""
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return lint_paths([pkg_root])
