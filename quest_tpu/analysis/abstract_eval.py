"""Pass 2: eager-vs-compiled abstract-eval consistency.

The same gate reaches the kernels through two doors: the eager API
(api.py dispatches one program per call) and the compiled circuit path
(circuit.py ``_apply_one`` inside one fused program).  Nothing forces the
two to construct identical operands — which is exactly how the
multiRotateZ angle was once cast to the state dtype on the compiled path
while the eager path kept float64.  This pass runs every recorded op
through ``jax.eval_shape`` on BOTH paths (abstract: no device work, no
compile) and asserts shape/dtype/sharding agreement, plus per-operand
dtype contracts that pin trace-time casting decisions.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .. import circuit as _circuit
from ..ops import apply as _ap
from .diagnostics import AnalysisCode, Diagnostic, Severity, diag


def _eager_matrix(state, op):
    # api.py _apply_unitary: payload enters as the host-side f64 pair
    return _ap.apply_matrix(state, jnp.asarray(op.payload()), op.targets,
                            op.controls, op.control_states)


def _eager_diagonal(state, op):
    return _ap.apply_diagonal(state, jnp.asarray(op.payload()), op.targets,
                              op.controls, op.control_states)


def _eager_x(state, op):
    return _ap.apply_pauli_x(state, op.targets[0], op.controls,
                             op.control_states)


def _eager_y(state, op):
    return _ap.apply_pauli_y(state, op.targets[0], op.controls,
                             op.control_states)


def _eager_y_conj(state, op):
    return _ap.apply_pauli_y(state, op.targets[0], op.controls,
                             op.control_states, conj_fac=-1)


def _eager_swap(state, op):
    return _ap.swap_qubit_amps(state, op.targets[0], op.targets[1])


def _eager_mrz(state, op):
    # api.py multiRotateZ: the angle is ALWAYS float64 on the eager path
    return _ap.apply_multi_rotate_z(state, jnp.float64(op.matrix[0]),
                                    op.targets)


def _eager_bitperm(state, op):
    # scheduler-only op (no eager API twin): the contract is the kernel's
    # own static-wire signature on both paths
    return _ap.apply_bit_permutation(state, op.targets,
                                     tuple(int(d) for d in op.matrix))


# the eager API's dispatch, kind by kind (mirrors api.py); tests monkeypatch
# entries to seed violations
EAGER_MIRROR = {
    "matrix": _eager_matrix,
    "diagonal": _eager_diagonal,
    "x": _eager_x,
    "y": _eager_y,
    "y*": _eager_y_conj,
    "swap": _eager_swap,
    "mrz": _eager_mrz,
    "bitperm": _eager_bitperm,
}

# Per-operand dtype contracts at kernel entry.  Dense/diagonal payloads are
# deliberately absent: the kernels cast payloads to the state dtype
# internally, so either width is sound.  Parameters that feed trig before
# any state-dtype cast must stay wide on both paths.
OPERAND_CONTRACTS = {
    "mrz": {"angle": jnp.dtype(jnp.float64)},
}


def check_abstract_eval(circuit, dtype=jnp.float32,
                        sharding=None) -> list[Diagnostic]:
    """Abstract-eval every op of ``circuit`` on the eager and compiled paths
    over a ``dtype`` state and report any disagreement.  Pure host work:
    ``jax.eval_shape`` traces with abstract values only."""
    out: list[Diagnostic] = []
    dtype = jnp.dtype(dtype)
    n = circuit.num_qubits
    kwargs = {"sharding": sharding} if sharding is not None else {}
    spec = jax.ShapeDtypeStruct((2, 1 << n), dtype, **kwargs)
    for i, op in enumerate(circuit.ops):
        eager_fn = EAGER_MIRROR.get(op.kind)
        if eager_fn is None:
            continue  # unknown kinds are the IR pass's finding
        compiled, c_err = _try_eval(partial(_apply_one_flipped, op), spec)
        eager, e_err = _try_eval(partial(eager_fn, op=op), spec)
        if c_err and e_err:
            # both paths refuse to trace: a semantically invalid op — the
            # IR pass owns that finding (bounds, payload shape, ...)
            continue
        if c_err or e_err:
            which, err = ("compiled", c_err) if c_err else ("eager", e_err)
            out.append(diag(
                AnalysisCode.EAGER_COMPILED_SHAPE_MISMATCH, Severity.ERROR,
                op_index=i,
                detail=f"only the {which} path fails to trace: {err}"))
            continue
        if compiled.shape != eager.shape:
            out.append(diag(
                AnalysisCode.EAGER_COMPILED_SHAPE_MISMATCH, Severity.ERROR,
                op_index=i,
                detail=f"compiled {compiled.shape} vs eager {eager.shape}"))
        if compiled.dtype != eager.dtype:
            out.append(diag(
                AnalysisCode.EAGER_COMPILED_DTYPE_MISMATCH, Severity.ERROR,
                op_index=i,
                detail=f"compiled {compiled.dtype} vs eager {eager.dtype}"))
        elif compiled.dtype != dtype:
            # both paths agree but silently promoted/demoted the state
            out.append(diag(
                AnalysisCode.EAGER_COMPILED_DTYPE_MISMATCH, Severity.ERROR,
                op_index=i,
                detail=f"state {dtype} promoted to {compiled.dtype} on both paths"))
        csh = getattr(compiled, "sharding", None)
        esh = getattr(eager, "sharding", None)
        if csh is not None and esh is not None and csh != esh:
            out.append(diag(
                AnalysisCode.EAGER_COMPILED_SHARDING_MISMATCH, Severity.ERROR,
                op_index=i, detail=f"compiled {csh} vs eager {esh}"))
        _check_operand_contracts(i, op, dtype, out)
    return out


def _apply_one_flipped(op, state):
    return _circuit._apply_one(state, op)


def _try_eval(fn, spec):
    """(result, None) on success, (None, short error text) if tracing the op
    fails — invalid ops (bad wires, wrong payload shape) raise arbitrarily
    deep in the kernels."""
    try:
        return jax.eval_shape(fn, spec), None
    except Exception as e:  # noqa: BLE001 - kernels raise many types
        return None, f"{type(e).__name__}: {e}"[:120]


def _check_operand_contracts(i: int, op, dtype, out: list) -> None:
    contracts = OPERAND_CONTRACTS.get(op.kind)
    if not contracts:
        return
    # abstract: operand construction itself runs under eval_shape so no
    # device buffers are built for large payloads
    operands = jax.eval_shape(lambda: _circuit.op_operands(op, dtype))
    for name, want in contracts.items():
        got = operands.get(name)
        if got is not None and got.dtype != want:
            out.append(diag(
                AnalysisCode.OPERAND_DTYPE_DRIFT, Severity.ERROR, op_index=i,
                detail=f"operand '{name}' of '{op.kind}': compiled path "
                       f"builds {got.dtype}, eager contract is {want}"))
