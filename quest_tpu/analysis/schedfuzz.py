"""Schedule-fuzzing harness: forced thread interleavings for the serve path.

The static pass (analysis/concurrency.py) proves the locking DISCIPLINE;
it deliberately exempts the ``# lock-free:`` surfaces — the SLO health
ring, the single-word saturation gauge, the metrics registry's snapshot
path — whose safety argument is "a torn read is tolerated by
construction".  That argument is dynamic, so it gets a dynamic prover:
this module drives the real objects under **seed-deterministic forced
interleavings** and asserts every snapshot a concurrent reader takes is
internally consistent.

How the forcing works (:class:`Interleaver`): each fuzzed thread installs
a ``sys.settrace`` hook that fires on every LINE of code in the target
files; the hook is a token-passing scheduler — at each line the thread
publishes itself runnable, a seeded RNG picks which registered thread owns
the token next, and everyone else waits.  That turns the interpreter's
coarse, rarely-adversarial preemption into line-granular schedule control:
a check-then-act race that a plain stress loop hits once in 10^5 runs is
forced on the first seed that alternates the two threads (the double-
``start()`` race fixed in this PR reproduces exactly this way —
tests/test_concurrency.py).

The harness never INTRODUCES a deadlock: a thread that waits too long for
the token (because the token holder is blocked on a real application
lock) times out, records a ``stall``, and proceeds — forced scheduling
degrades toward free-running rather than hanging the suite.  Runs are
reproducible per ``seed`` up to that stall escape hatch.

``run_smoke`` is the CI surface (``python -m quest_tpu.analysis
--concurrency --fuzz-smoke --json``): a few seeds over each canonical
lock-free scenario — ``slo.health()`` under writer storms, the labeled
metrics scrape parsed and checked monotone mid-increment, live
``queue_saturation()`` during a submit storm, flight-recorder ring dumps
racing admissions, and router route/report feedback races.  Any invariant
violation or unexpected exception comes back as a
``T_SCHEDULE_FUZZ_FAILURE`` ERROR diagnostic.
"""

from __future__ import annotations

import random
import sys
import threading
import time

__all__ = ["Interleaver", "run_smoke", "fuzz_slo_health",
           "fuzz_metrics_snapshot", "fuzz_queue_saturation",
           "fuzz_flight_ring", "fuzz_router"]


class _FuzzLock:
    """Instrumented drop-in for a ``threading.Lock`` attribute of an
    object under fuzz: a failed acquire SPINS THROUGH YIELD POINTS instead
    of blocking the OS thread, so the scheduler keeps seeing the thread as
    runnable and the token keeps flowing — a thread parked at a yield
    point while holding this lock can always be scheduled to release it.
    Install with :meth:`Interleaver.wrap_lock`."""

    def __init__(self, interleaver: "Interleaver", real):
        self._il = interleaver
        self._real = real

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if not blocking:
            return self._real.acquire(False)
        while not self._real.acquire(False):
            if not self._il._yield_point():
                time.sleep(0.0002)   # scheduler disengaged: plain backoff
        return True

    def release(self) -> None:
        self._real.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._real.locked()


class Interleaver:
    """Token-passing line-level scheduler over a set of thunks.

    ``targets`` is a tuple of filename suffixes; only frames executing in
    matching files hit yield points (everything else free-runs, so jax /
    stdlib internals are never slowed).  ``max_switches`` bounds the
    forced-scheduling phase; past it the run free-runs to completion.

    The token only ever goes to a thread that is at a yield point (parked
    or the caller): handing it to a thread blocked inside an uninstrumented
    lock would stall the schedule for nothing.  A parked thread whose turn
    never comes times out (``stall_timeout_s`` x ``max_stalls``), records a
    ``stall`` and proceeds — the harness degrades toward free-running
    instead of ever introducing a deadlock of its own.
    """

    def __init__(self, seed: int = 0, targets: tuple = (),
                 max_switches: int = 4000, stall_timeout_s: float = 0.02,
                 max_stalls: int = 3):
        self.seed = int(seed)
        self.targets = tuple(targets)
        self.max_switches = int(max_switches)
        self.stall_timeout_s = float(stall_timeout_s)
        self.max_stalls = int(max_stalls)
        self._rng = random.Random(self.seed)
        self._cv = threading.Condition()
        self._live: set = set()      # guarded-by: _cv (registered thread indices)
        self._parked: set = set()    # guarded-by: _cv (indices waiting at a yield)
        self._token: int | None = None   # guarded-by: _cv
        self._index: dict = {}       # guarded-by: _cv (ident -> index)
        # lock-free: written by run() before any worker thread exists
        self._barrier: threading.Barrier | None = None
        self.switches = 0            # guarded-by: _cv
        self.stalls = 0              # guarded-by: _cv
        # lock-free: list.append is GIL-atomic and the list is only read after join()
        self.errors: list = []

    def wrap_lock(self, real) -> _FuzzLock:
        """Instrument one lock object (assign the result back onto the
        fuzzed object's lock attribute)."""
        return _FuzzLock(self, real)

    # -- the scheduler core ---------------------------------------------------
    def _yield_point(self) -> bool:
        """One scheduling decision; returns False once the forced phase is
        over (callers may back off on their own)."""
        # lock-free: reads this thread's own registration, written before its thunk ran
        me = self._index.get(threading.get_ident())
        if me is None:
            return False
        with self._cv:
            if self.switches >= self.max_switches or len(self._live) <= 1:
                return False
            self.switches += 1
            pick = self._rng.choice(sorted(self._live))
            self._token = pick
            self._cv.notify_all()
            if pick == me:
                return True
            self._parked.add(me)
            waits = 0
            try:
                while (self._token != me and waits < self.max_stalls
                       and self.switches < self.max_switches):
                    if not self._cv.wait(self.stall_timeout_s):
                        waits += 1
                if self._token != me:
                    self.stalls += 1
            finally:
                self._parked.discard(me)
        return True

    def _trace(self, frame, event, _arg):
        if event != "call":
            return None
        fname = frame.f_code.co_filename
        if fname.endswith(self.targets):
            return self._local_trace
        return None

    def _local_trace(self, _frame, event, _arg):
        if event == "line":
            self._yield_point()
        return self._local_trace

    def _wrap(self, idx: int, thunk):
        def go():
            ident = threading.get_ident()
            with self._cv:
                self._index[ident] = idx
                self._live.add(idx)
            sys.settrace(self._trace)
            try:
                if self._barrier is not None:
                    # every thread registers before any runs: a fast thunk
                    # must not drain before its rivals exist
                    try:
                        self._barrier.wait()
                    except threading.BrokenBarrierError:
                        pass
                thunk()
            except BaseException as exc:  # noqa: BLE001 — the finding itself
                self.errors.append(f"thread[{idx}] "
                                   f"{type(exc).__name__}: {exc}")
            finally:
                sys.settrace(None)
                with self._cv:
                    self._live.discard(idx)
                    self._parked.discard(idx)
                    if self._token == idx:
                        self._token = (self._rng.choice(sorted(self._live))
                                       if self._live else None)
                    self._cv.notify_all()
        return go

    def run(self, thunks, timeout_s: float = 60.0) -> dict:
        """Run ``thunks`` concurrently under forced interleaving; returns
        ``{"switches", "stalls", "errors", "completed"}``."""
        self._barrier = threading.Barrier(len(thunks), timeout=10.0)
        threads = [threading.Thread(target=self._wrap(i, t),
                                    name=f"schedfuzz-{i}", daemon=True)
                   for i, t in enumerate(thunks)]
        for t in threads:
            t.start()
        completed = True
        deadline = time.monotonic() + timeout_s
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
            completed &= not t.is_alive()
        # lock-free: every worker is joined (or timed out and abandoned) above
        switches, stalls, errors = self.switches, self.stalls, self.errors
        return {"seed": self.seed, "switches": switches, "stalls": stalls,
                "errors": list(errors), "completed": completed}


def _target(module_suffix: str) -> str:
    import os
    return module_suffix.replace("/", os.sep)


# ---------------------------------------------------------------------------
# canonical scenarios: the annotated lock-free surfaces, stress-proven
# ---------------------------------------------------------------------------

def fuzz_slo_health(seed: int = 0, iters: int = 80) -> dict:
    """``slo.health()`` — the router's per-decision lock-free snapshot —
    under two observe/observe_queue writer storms: every snapshot must be
    internally consistent (non-negative windowed counts, saturation within
    [0, 1], p99 one of the ring's bucket edges)."""
    from ..obs.slo import _HEALTH_LAT_BUCKETS, SLOMonitor
    il = Interleaver(seed, targets=(_target("obs/slo.py"),))
    mon = SLOMonitor()
    mon._lock = il.wrap_lock(mon._lock)
    violations: list = []
    edges = set(_HEALTH_LAT_BUCKETS) | {0.0}

    def writer(base: int):
        def go():
            for i in range(iters):
                mon.observe(f"class{(base + i) % 3}", 0.0009 * (i % 7),
                            deadline_ok=(i % 5 != 0))
                mon.observe_queue(i % 17, 16)
        return go

    def reader():
        for _ in range(iters):
            h = mon.health()
            if not 0.0 <= h["saturation"] <= 1.0:
                violations.append(f"saturation {h['saturation']} out of "
                                  "[0, 1]")
            if h["burn_rate"] < 0:
                violations.append(f"negative burn rate {h['burn_rate']}")
            if min(h["window_hits"], h["window_misses"],
                   h["window_samples"]) < 0:
                violations.append(f"negative window count in {h}")
            if h["window_hits"] + h["window_misses"] > h["window_samples"]:
                violations.append(
                    f"deadline'd {h['window_hits'] + h['window_misses']} "
                    f"exceeds window samples {h['window_samples']}")
            if h["p99_s"] not in edges:
                violations.append(f"p99 {h['p99_s']} is not a bucket edge")

    res = il.run([writer(0), writer(1), reader])
    res.update({"scenario": "slo_health", "violations": violations})
    return res


def fuzz_metrics_snapshot(seed: int = 0, iters: int = 40) -> dict:
    """The labeled metrics registry scraped mid-increment: every
    ``to_prometheus`` text must parse (cumulative histogram buckets
    included) and every counter sample must be monotone non-decreasing
    across successive scrapes."""
    from ..serve.metrics import Metrics, parse_prometheus
    il = Interleaver(seed, targets=(_target("serve/metrics.py"),))
    m = Metrics()
    m._lock = il.wrap_lock(m._lock)
    views = [m.labeled(replica=str(i)) for i in range(2)]
    # pre-seed one sample: an EMPTY registry legitimately fails
    # parse_prometheus ("no metric samples found"), and the scenario is
    # about mid-increment consistency, not the empty-scrape contract
    m.inc("fuzz_seed_total")
    violations: list = []

    def writer(i: int):
        def go():
            v = views[i]
            for k in range(iters):
                v.inc("routed_total")
                v.inc("shed_total", labels={"reason": "burn"})
                v.set_gauge("queue_depth", k)
                v.observe("request_latency_seconds", 0.001 * k)
        return go

    def reader():
        last: dict = {}
        for _ in range(iters):
            try:
                parsed = parse_prometheus(m.to_prometheus())
            except ValueError as exc:
                violations.append(f"scrape failed to parse: {exc}")
                continue
            for name, samples in parsed.items():
                if not name.endswith("_total"):
                    continue
                for labels, value in samples.items():
                    key = (name, labels)
                    if value < last.get(key, 0.0):
                        violations.append(
                            f"counter {name}{{{labels}}} went backwards: "
                            f"{last[key]} -> {value}")
                    last[key] = value

    res = il.run([writer(0), writer(1), reader])
    res.update({"scenario": "metrics_snapshot", "violations": violations})
    return res


def fuzz_queue_saturation(seed: int = 0, iters: int = 30) -> dict:
    """Live ``queue_saturation()`` reads racing a submit storm against a
    deliberately stopped worker (the queue fills and bounces): the reading
    must stay within [0, 1] and the bounce path must raise only
    ``E_QUEUE_FULL``."""
    from ..circuit import Circuit
    from ..serve.service import QuESTService
    from ..validation import ErrorCode, QuESTError
    svc = QuESTService(start=False, max_queue=8, max_batch=4)
    c = Circuit(2)
    c.h(0).cnot(0, 1)
    violations: list = []

    def writer():
        for _ in range(iters):
            try:
                svc.submit(c)
            except QuESTError as exc:
                if exc.code != ErrorCode.QUEUE_FULL:
                    violations.append(f"submit raised {exc.code}")

    def reader():
        for _ in range(iters):
            s = svc.queue_saturation()
            if not 0.0 <= s <= 1.0:
                violations.append(f"queue_saturation {s} out of [0, 1]")

    res = Interleaver(seed, targets=(_target("serve/service.py"),)).run(
        [writer, writer, reader])
    try:
        svc.shutdown(drain=False)
    except Exception as exc:        # noqa: BLE001 — part of the verdict
        violations.append(f"shutdown after storm raised {exc!r}")
    res.update({"scenario": "queue_saturation", "violations": violations})
    return res


def fuzz_flight_ring(seed: int = 0, iters: int = 60) -> dict:
    """Flight-recorder ring dumps racing admission appends and resolves:
    a dump is a bounded, well-formed snapshot (depth <= capacity, every
    record dict carrying its terminal fields) no matter where the writers
    are mid-append."""
    from ..obs.flight import FlightRecorder
    il = Interleaver(seed, targets=(_target("obs/flight.py"),))
    rec = FlightRecorder(capacity=16)
    rec._lock = il.wrap_lock(rec._lock)
    violations: list = []

    def writer(base: int):
        def go():
            for i in range(iters):
                rid = base * iters + i
                rec.admit(rid, f"class{i % 3}", i % 16)
                rec.resolve(rid, "ok", batch_id=i, wait_s=0.0)
        return go

    def reader():
        for i in range(iters):
            doc = rec.dump(f"fuzz-{i}")
            if len(doc["records"]) > rec.capacity:
                violations.append(
                    f"dump holds {len(doc['records'])} records, capacity "
                    f"{rec.capacity}")
            for r in doc["records"]:
                if "outcome" not in r or "request_id" not in r:
                    violations.append(f"malformed dump record {r}")
            snap = rec.snapshot()
            if snap["depth"] > rec.capacity:
                violations.append(f"ring depth {snap['depth']} exceeds "
                                  f"capacity {rec.capacity}")

    res = il.run([writer(0), writer(1), reader])
    res.update({"scenario": "flight_ring", "violations": violations})
    return res


class _FakeService:
    def __init__(self):
        self.saturation = 0.0

    def queue_saturation(self):
        return self.saturation


class _FakeReplica:
    def __init__(self, index: int):
        self.index = index
        self.service = _FakeService()

    def health(self):
        return {"burn_rate": 0.0}


def fuzz_router(seed: int = 0, iters: int = 40) -> dict:
    """Router ``route()`` decisions racing ``report()`` cache-outcome
    feedback (the eviction/re-placement path): every decision must name a
    real replica and every snapshot must be internally consistent
    (placements within the replica set)."""
    from ..circuit import Circuit, qft_circuit
    from ..deploy.router import Router
    il = Interleaver(seed, targets=(_target("deploy/router.py"),))
    replicas = [_FakeReplica(i) for i in range(3)]
    router = Router(replicas)
    router._lock = il.wrap_lock(router._lock)
    c1 = qft_circuit(3)
    c2 = Circuit(3)
    c2.h(0).cnot(0, 1)
    keys = [router.class_key(c1), router.class_key(c2)]
    indices = {r.index for r in replicas}
    violations: list = []

    def decider():
        for i in range(iters):
            replica, decision = router.route(c1 if i % 2 else c2)
            if replica.index not in indices:
                violations.append(f"routed to unknown replica "
                                  f"{replica.index}")
            if decision["replica"] != replica.index:
                violations.append("decision record disagrees with the "
                                  "returned replica")

    def feeder():
        for i in range(iters):
            ck = keys[i % 2]
            router.report(ck, i % 3, "hit" if i % 3 else "miss")

    def checker():
        for _ in range(iters):
            snap = router.snapshot()
            for ck, idx in snap["placements"].items():
                if idx not in indices:
                    violations.append(
                        f"placement {ck} -> {idx} names no replica")

    res = il.run([decider, feeder, checker])
    res.update({"scenario": "router", "violations": violations})
    return res


_SCENARIOS = (fuzz_slo_health, fuzz_metrics_snapshot, fuzz_queue_saturation,
              fuzz_flight_ring, fuzz_router)


def run_smoke(seeds=(0, 1), iters: int | None = None) -> dict:
    """The CI smoke: every scenario under every seed.  Returns one
    machine-readable document; ``violations`` aggregates invariant
    failures AND unexpected thread exceptions (each becomes a
    ``T_SCHEDULE_FUZZ_FAILURE`` diagnostic in the CLI)."""
    rows: list = []
    violations: list = []
    for fn in _SCENARIOS:
        for seed in seeds:
            kw = {} if iters is None else {"iters": iters}
            row = fn(seed=seed, **kw)
            rows.append({k: row[k] for k in ("scenario", "seed", "switches",
                                             "stalls", "completed")}
                        | {"violations": len(row["violations"]),
                           "errors": len(row["errors"])})
            violations += [f"{row['scenario']}[seed={seed}]: {v}"
                           for v in row["violations"]]
            violations += [f"{row['scenario']}[seed={seed}]: {e}"
                           for e in row["errors"]]
            if not row["completed"]:
                violations.append(f"{row['scenario']}[seed={seed}]: "
                                  "did not complete (possible deadlock)")
    return {"scenarios": rows, "violations": violations,
            "seeds": [int(s) for s in seeds]}
