"""quest_tpu.analysis — static analysis for circuits and the codebase.

Cooperating passes, all pure host work (no device allocation; the
jaxpr audit optionally compiles but never executes), mirroring the role
QuEST_validation.c plays in the reference but *ahead* of run time:

1. :func:`analyze_circuit` — whole-circuit IR checks: wire bounds,
   payload unitarity, shard fit, memory footprint vs the target mesh
   (parallel/planner.py's cost model), plane-storage compatibility, and
   optimization hints.
2. :func:`check_abstract_eval` — eager-vs-compiled consistency via
   ``jax.eval_shape``: shape/dtype/sharding agreement per op plus
   per-operand dtype contracts (the multiRotateZ f32-angle bug class).
3. :func:`lint_paths` / :func:`lint_package` — AST purity lint over the
   source tree for jit-unsafe host-Python patterns.
4. :func:`check_equivalence` / :func:`verify_schedule` — translation
   validation of scheduler/optimizer rewrites (Pauli tableau, phase
   polynomial, dense-window domains; ``V_*`` codes) without touching a
   2^n state; :func:`check_overlap_plan` extends the proof to the
   pipelined executor's chunked lowering (chunking is layout-only).
5. :func:`audit_dispatch` / :func:`audit_schedule_pair` /
   :func:`audit_overlap` — lowered-jaxpr / compiled-HLO collective,
   donation and async-overlap audit against the planner's comm model.
6. :func:`audit_concurrency_package` — lock-discipline audit over the
   serve/deploy/obs runtime (``# guarded-by:`` / ``# lock-free:``
   annotations, lock-order graph, blocking-under-lock; ``T_*`` codes)
   with :func:`run_schedule_fuzz_smoke` as its dynamic twin: forced
   thread interleavings stress-proving the lock-free read surfaces.
7. :func:`audit_staticcheck_package` /
   :func:`audit_served_classes` — compile-economics static checker
   (``S_*`` codes, analysis/staticcheck.py): AST rules for unlifted
   literal gate parameters, recompile-keyed jit boundaries, hot-path
   host syncs and f64-forcing flows, plus a jaxpr diff proving every
   served structural class is closed over its operand vector (one XLA
   program per class, not per request).

CLI: ``python -m quest_tpu.analysis --self-lint`` (the tier-1 CI gate),
``--verify-schedule`` (the scheduler translation-validation smoke),
``--concurrency [--fuzz-smoke]`` (the lock-discipline gate) and
``--staticcheck`` (the compile-economics gate), see
``python -m quest_tpu.analysis --help`` and docs/ANALYSIS.md.
"""

from .diagnostics import (AnalysisCode, Diagnostic, Severity,  # noqa: F401
                          max_severity, message_for)
from .circuit_ir import analyze_circuit  # noqa: F401
from .abstract_eval import check_abstract_eval  # noqa: F401
from .purity import lint_package, lint_paths, lint_source  # noqa: F401
from .equivalence import (check_density_lowering,  # noqa: F401
                          check_density_plan,
                          check_epoch_plan, check_equivalence,
                          check_overlap_plan, probe_epoch_execution,
                          verify_schedule)
from .jaxpr_audit import (audit_dispatch, audit_epoch_donation,  # noqa: F401
                          audit_overlap, audit_schedule_pair,
                          count_hlo_async_collectives,
                          count_hlo_collectives, count_jaxpr_collectives,
                          donation_aliased)
from .concurrency import (  # noqa: F401
    audit_package as audit_concurrency_package,
    audit_paths as audit_concurrency_paths,
    audit_source as audit_concurrency_source,
    strip_first_lock_scope)
from .schedfuzz import (  # noqa: F401
    Interleaver,
    run_smoke as run_schedule_fuzz_smoke)
from .staticcheck import (  # noqa: F401
    audit_package as audit_staticcheck_package,
    audit_paths as audit_staticcheck_paths,
    audit_source as audit_staticcheck_source,
    audit_served_classes,
    corpus_report as staticcheck_corpus_report)

__all__ = [
    "AnalysisCode", "Diagnostic", "Severity", "max_severity", "message_for",
    "analyze_circuit", "check_abstract_eval",
    "lint_source", "lint_paths", "lint_package",
    "check_equivalence", "check_overlap_plan", "verify_schedule",
    "check_epoch_plan", "probe_epoch_execution",
    "check_density_lowering", "check_density_plan",
    "audit_dispatch", "audit_epoch_donation", "audit_overlap",
    "audit_schedule_pair",
    "count_jaxpr_collectives", "count_hlo_collectives",
    "count_hlo_async_collectives", "donation_aliased",
    "audit_concurrency_package", "audit_concurrency_paths",
    "audit_concurrency_source", "strip_first_lock_scope",
    "Interleaver", "run_schedule_fuzz_smoke",
    "audit_staticcheck_package", "audit_staticcheck_paths",
    "audit_staticcheck_source", "audit_served_classes",
    "staticcheck_corpus_report",
]
