"""Pass 5: lowered-program audit of the compiled dispatch path.

The planner (parallel/planner.py) predicts, per gate, what GSPMD will do
on an amplitude mesh — and the scheduler (parallel/scheduler.py) now makes
REWRITE decisions against that model.  Nothing so far checked the model
against what XLA actually lowers: a partitioner regression (or a planner
bug) would silently mis-cost every scheduling decision.  This pass closes
the loop statically:

1. :func:`count_jaxpr_collectives` traces the dispatch path with
   ``jax.make_jaxpr`` (abstract — no device work) and walks every eqn,
   recursing through pjit/scan/cond/shard_map sub-jaxprs, counting the
   explicit collective primitives (``ppermute`` / ``psum`` /
   ``all_gather`` / ``all_to_all`` ...).  The GSPMD gate path must contain
   NONE (its collectives are partitioner-inserted); the shard_map kernels
   (parallel/collectives.py) show exactly their documented ones.

2. :func:`audit_dispatch` additionally lowers and compiles the program
   against a real ``num_devices`` mesh (when that many devices exist) and
   counts the state-sized collectives in the compiled HLO — tiny scalar
   reductions are latency noise, so ops moving less than half a shard row
   are ignored, the same threshold tests/test_distributed_lowering.py
   gates on.  The count is cross-checked against
   ``planner.comm_summary``'s prediction.  One *logical* exchange event of
   the model legitimately lowers to a handful of HLO collectives (GSPMD
   spells a pairwise exchange as all-gather + all-reduce partial-sum
   pairs, per SoA plane), so the gate is a factor bound: more than
   ``_HLO_OPS_PER_EVENT`` HLO collectives per predicted event is
   ``A_COLLECTIVE_COUNT_MISMATCH`` (the comm model undercosts this
   circuit); ANY state-sized collective on a circuit the planner models as
   comm-FREE is ``A_UNEXPECTED_ALLGATHER`` (a lost sharding annotation —
   the full-state round-trip failure mode).  :func:`audit_schedule_pair`
   runs the sharper scheduler-level check: the SCHEDULED program must not
   compile to more state-sized collectives than the unscheduled one — the
   HLO-level twin of the planner-level ``A_SCHEDULE_COMM_REGRESSION``
   gate, over exactly the pair bench.py measures.

3. The same compiled artifact is audited for donation:
   ``donate=True`` programs must compile with an ``input_output_alias``
   entry, else the donation is silently ignored and every iteration pays a
   full extra state allocation (``A_DONATION_UNUSED``).

CLI: part of ``--verify-schedule`` (docs/ANALYSIS.md); the CI smoke runs
it on the scheduled 22q QFT over the 8-virtual-device mesh — the same
pair bench.py measures.

This module also hosts the jaxpr-side half of pass 8, the compile-economics
static checker (analysis/staticcheck.py): :func:`trace_lifted_class` /
:func:`trace_embedded_ops` trace the per-request program a serve cache
entry actually runs, :func:`diff_trace_constants` diffs two such traces
constant-by-constant (any difference under an operand perturbation is a
per-request recompile, ``S_CLASS_NOT_CLOSED``), and
:func:`scan_x64_promotion` weak-type-scans a trace for f32→f64 promoting
equations and promoted program outputs (``S_X64_PROMOTION``).
"""

from __future__ import annotations

import re
from typing import Any

import numpy as np

from .diagnostics import AnalysisCode, Diagnostic, Severity, diag

__all__ = ["count_jaxpr_collectives", "count_hlo_collectives",
           "count_hlo_async_collectives", "donation_aliased",
           "audit_dispatch", "audit_schedule_pair", "audit_overlap",
           "trace_lifted_class", "trace_embedded_ops",
           "diff_trace_constants", "scan_x64_promotion"]

# how many HLO collectives one planner comm event may legitimately lower
# to: a pairwise exchange spells as an (all-gather, all-reduce) partial-sum
# pair per SoA plane plus a layout permute — measured on the scheduled
# QFT pairs, the partitioner stays well under this
_HLO_OPS_PER_EVENT = 6

# explicit jaxpr-level collective primitives (shard_map / manual kernels)
JAXPR_COLLECTIVES = ("ppermute", "pbroadcast", "psum", "psum2", "pmax",
                     "pmin", "all_gather", "all_to_all", "pgather",
                     "psum_scatter", "reduce_scatter")

# partitioner-inserted HLO collectives (bench.py counts the same set)
HLO_COLLECTIVES = ("collective-permute", "all-gather", "all-to-all",
                   "all-reduce", "reduce-scatter")


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------

def _sub_jaxprs(value: Any):
    """Yield every jaxpr reachable from one eqn param value (ClosedJaxpr,
    raw Jaxpr, or containers of either — covers pjit/cond/scan/shard_map)."""
    try:
        from jax._src import core as _core
    except ImportError:  # pragma: no cover - jax moved the module
        from jax import core as _core  # type: ignore[no-redef]
    if isinstance(value, _core.Jaxpr):
        yield value
    elif hasattr(value, "jaxpr") and isinstance(getattr(value, "jaxpr", None),
                                                _core.Jaxpr):
        yield value.jaxpr
    elif isinstance(value, (list, tuple)):
        for item in value:
            yield from _sub_jaxprs(item)


def count_jaxpr_collectives(jaxpr) -> dict:
    """Histogram of explicit collective primitives in a (Closed)Jaxpr,
    recursing through every sub-jaxpr.  Accepts the return value of
    ``jax.make_jaxpr(f)(*args)``."""
    counts: dict = {}

    def walk(jx) -> None:
        for eqn in jx.eqns:
            name = eqn.primitive.name
            if name in JAXPR_COLLECTIVES:
                counts[name] = counts.get(name, 0) + 1
            for value in eqn.params.values():
                for sub in _sub_jaxprs(value):
                    walk(sub)

    walk(jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr)
    return counts


def make_dispatch_jaxpr(circuit, dtype=None):
    """Abstract trace of the compiled dispatch path for ``circuit`` — the
    exact program ``compile_circuit`` runs, traced via ShapeDtypeStruct
    (no device allocation)."""
    import jax
    import jax.numpy as jnp
    from ..circuit import _run_ops_routed
    ops = circuit.key()
    spec = jax.ShapeDtypeStruct((2, 1 << circuit.num_qubits),
                                dtype or jnp.float32)
    return jax.make_jaxpr(lambda s: _run_ops_routed(s, ops))(spec)


# ---------------------------------------------------------------------------
# pass 8 (staticcheck.py) helpers: per-request trace, constant diff,
# weak-type scan
# ---------------------------------------------------------------------------

def trace_lifted_class(num_qubits: int, skeleton, offsets, num_params: int,
                       dtype=None):
    """Abstract trace of a LIFTED cache entry's per-request program — the
    ``(state, params)`` body serve/cache.py compiles once per structural
    class.  Payloads arrive through the abstract params operand, so the
    trace is payload-free by construction."""
    import jax
    import jax.numpy as jnp
    from ..circuit import _run_ops_routed
    spec = jax.ShapeDtypeStruct((2, 1 << num_qubits), dtype or jnp.float64)
    pav = jax.ShapeDtypeStruct((int(num_params),), jnp.float64)
    return jax.make_jaxpr(
        lambda s, p: _run_ops_routed(s, skeleton, p, offsets))(spec, pav)


def trace_embedded_ops(num_qubits: int, ops, dtype=None):
    """Abstract trace of the payload-EMBEDDING program an opaque cache
    entry (overlap / pallas — ``skeleton is None``) runs per request:
    state-only signature, gate payloads baked in as trace constants."""
    import jax
    import jax.numpy as jnp
    from ..circuit import _run_ops_routed
    spec = jax.ShapeDtypeStruct((2, 1 << num_qubits), dtype or jnp.float64)
    return jax.make_jaxpr(lambda s: _run_ops_routed(s, tuple(ops)))(spec)


def _const_key(value) -> tuple | None:
    """A comparable fingerprint for a numeric constant, None for
    non-numeric values (functions, dimension descriptors, ...)."""
    if isinstance(value, (bool, int, float, complex, str)):
        return ("scalar", repr(value))
    if isinstance(value, np.ndarray) or np.isscalar(value):
        arr = np.asarray(value)
        return ("array", arr.shape, str(arr.dtype), arr.tobytes())
    if isinstance(value, tuple) and all(
            isinstance(v, (bool, int, float, complex, str)) for v in value):
        return ("tuple", repr(value))
    return None


def _trace_rows(jaxpr) -> list[tuple]:
    """Flatten a (Closed)Jaxpr into comparable rows: one per equation
    (recursing sub-jaxprs) carrying the primitive name, every Literal
    invar's fingerprint, and every numeric eqn param's fingerprint."""
    try:
        from jax._src import core as _core
    except ImportError:  # pragma: no cover - jax moved the module
        from jax import core as _core  # type: ignore[no-redef]
    rows: list[tuple] = []

    def walk(jx) -> None:
        for eqn in jx.eqns:
            lits = tuple(_const_key(v.val) for v in eqn.invars
                         if isinstance(v, _core.Literal))
            pkeys = []
            for k in sorted(eqn.params):
                key = _const_key(eqn.params[k])
                if key is not None:
                    pkeys.append((k, key))
            rows.append((eqn.primitive.name, lits, tuple(pkeys)))
            for value in eqn.params.values():
                for sub in _sub_jaxprs(value):
                    walk(sub)

    walk(jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr)
    return rows


def diff_trace_constants(j1, j2) -> list[str]:
    """Diff two traces of what must be ONE compiled program: closed
    consts, equation sequence, literal invars, numeric eqn params.  Every
    returned string is a constant (or structure) that changed between the
    two requests — i.e. a per-request recompile, proven abstractly."""
    diffs: list[str] = []
    c1 = [np.asarray(c) for c in getattr(j1, "consts", [])]
    c2 = [np.asarray(c) for c in getattr(j2, "consts", [])]
    if len(c1) != len(c2):
        diffs.append(f"closed-const count {len(c1)} vs {len(c2)}")
    else:
        for i, (a, b) in enumerate(zip(c1, c2)):
            if (a.shape != b.shape or a.dtype != b.dtype
                    or a.tobytes() != b.tobytes()):
                diffs.append(
                    f"closed const #{i} ({a.dtype}{a.shape}) differs")
    r1, r2 = _trace_rows(j1), _trace_rows(j2)
    if len(r1) != len(r2):
        diffs.append(f"equation count {len(r1)} vs {len(r2)}")
        return diffs
    for i, (a, b) in enumerate(zip(r1, r2)):
        if a[0] != b[0]:
            diffs.append(f"eqn #{i}: primitive {a[0]} vs {b[0]}")
        elif a[1] != b[1]:
            diffs.append(f"eqn #{i} ({a[0]}): literal operand differs")
        elif a[2] != b[2]:
            diffs.append(f"eqn #{i} ({a[0]}): numeric eqn param differs")
    return diffs


def scan_x64_promotion(jaxpr, expect=None) -> tuple:
    """Weak-type scan of a trace: find every equation that takes an
    ``expect``-dtype (default float32) input and produces a float64
    output — the promotion events — and report the program's output
    dtypes.  Returns ``(events, out_dtypes)`` where each event is
    ``(primitive, in_dtypes, out_dtypes)``."""
    import jax.numpy as jnp
    expect_dt = np.dtype(expect if expect is not None else jnp.float32)
    f64 = np.dtype(np.float64)

    def _dt(v):
        aval = getattr(v, "aval", None)
        dt = getattr(aval, "dtype", None)
        return np.dtype(dt) if dt is not None else None

    events: list[tuple] = []

    def walk(jx) -> None:
        for eqn in jx.eqns:
            ins = [_dt(v) for v in eqn.invars]
            outs = [_dt(v) for v in eqn.outvars]
            if (any(o == f64 for o in outs if o is not None)
                    and any(i == expect_dt for i in ins if i is not None)):
                events.append((eqn.primitive.name,
                               [str(i) for i in ins if i is not None],
                               [str(o) for o in outs if o is not None]))
            for value in eqn.params.values():
                for sub in _sub_jaxprs(value):
                    walk(sub)

    inner = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    walk(inner)
    out_dtypes = [d for d in (_dt(v) for v in inner.outvars)
                  if d is not None]
    return events, out_dtypes


# ---------------------------------------------------------------------------
# compiled-HLO collective counting (size-filtered)
# ---------------------------------------------------------------------------

_SHAPE_RE = re.compile(r"\w\d*\[([0-9,]+)\]")


def count_hlo_collectives(compiled_text: str, min_elems: int = 0) -> dict:
    """Histogram of HLO collectives moving >= ``min_elems`` elements.
    Size-filtering drops factor-side scalar reductions (f64[2] psums) that
    are latency, not data motion — the planner models data motion."""
    counts: dict = {}
    for line in compiled_text.splitlines():
        for op in HLO_COLLECTIVES:
            if f"{op}(" not in line and f"{op}-start(" not in line:
                continue
            sizes = [int(np.prod([int(d) for d in dims.split(",")]))
                     for dims in _SHAPE_RE.findall(line)]
            if not min_elems or (sizes and max(sizes) >= min_elems):
                counts[op] = counts.get(op, 0) + 1
            break
    return counts


def donation_aliased(compiled_text: str) -> bool:
    """True iff the compiled module aliases an input buffer to the output
    (the executable form a ``donate_argnums`` promise must take)."""
    return "input_output_alias" in compiled_text


def count_hlo_async_collectives(compiled_text: str) -> dict:
    """``{"starts": S, "separated": K}`` — async collective ``*-start``
    instructions in compiled HLO, and how many have at least one
    NON-COLLECTIVE instruction scheduled between the start and its own
    ``*-done``: the separation is where the backend can hop gate compute
    onto the chip while the chunk is on the wire.  ``separated == 0`` with
    hideable events planned is the ``A_COLLECTIVE_NOT_OVERLAPPED``
    signal.

    Each start is paired with the done that CONSUMES its result id (the
    token left of ``=``) when one is found, not merely the next ``-done``
    line, and intervening start/done bookkeeping of other collectives
    does not count as separation — a fully serialized interleaving like
    ``start.1; start.2; done.1; done.2`` hides nothing and reports 0."""
    lines = [ln for ln in compiled_text.splitlines() if "=" in ln]
    starts = separated = 0
    for i, ln in enumerate(lines):
        if not any(f"{op}-start(" in ln for op in HLO_COLLECTIVES):
            continue
        starts += 1
        lhs = ln.split("=", 1)[0].strip()
        result_id = lhs.split()[-1] if lhs else ""
        done_at = None
        for j in range(i + 1, len(lines)):
            if "-done(" in lines[j] and (not result_id
                                         or result_id in lines[j]):
                done_at = j
                break
        if done_at is None:  # no id-matched done: fall back to the next one
            for j in range(i + 1, len(lines)):
                if "-done(" in lines[j]:
                    done_at = j
                    break
        if done_at is None:
            continue
        if any("-start(" not in b and "-done(" not in b
               for b in lines[i + 1:done_at]):
            separated += 1
    return {"starts": starts, "separated": separated}


# ---------------------------------------------------------------------------
# the audit
# ---------------------------------------------------------------------------

def audit_dispatch(circuit, num_devices: int = 1, *, dtype=None,
                   donate: bool = True, pipeline_chunks: int = 1,
                   label: str = "circuit") -> tuple[dict, list[Diagnostic]]:
    """Audit the lowered dispatch path of ``circuit`` against the planner's
    comm model for an ``num_devices``-way amplitude mesh.

    Always performs the abstract jaxpr walk; additionally lowers + compiles
    against a real mesh when the process has ``num_devices`` devices
    (CI uses the 8-virtual-device CPU mesh), cross-checking the state-sized
    collective count against ``planner.comm_summary`` and auditing buffer
    donation.  ``pipeline_chunks`` widens the per-event lowering bound: a
    program executed through the chunked overlapped executor legitimately
    lowers each planned comm event to up to C chunk-sized collectives.
    Returns ``(report, diagnostics)``."""
    import jax
    import jax.numpy as jnp
    from ..circuit import _run_ops_routed
    from ..parallel import planner as _planner

    n = circuit.num_qubits
    dtype = dtype or jnp.float32
    ops = circuit.key()
    jaxpr_counts = count_jaxpr_collectives(make_dispatch_jaxpr(circuit, dtype))
    predicted = _planner.comm_summary(
        circuit, num_devices,
        bytes_per_amp=8 if jnp.dtype(dtype) == jnp.float32 else 16)
    report: dict = {
        "label": label,
        "num_devices": num_devices,
        "jaxpr_collectives": jaxpr_counts,
        "predicted_comm_events": predicted["comm_events"],
        "predicted_reshard_events": predicted["reshard_events"],
        "hlo_collectives": None,
        "donation_aliased": None,
    }
    out: list[Diagnostic] = []

    # the GSPMD gate path must carry no explicit collectives of its own:
    # any here would double whatever the partitioner inserts
    if jaxpr_counts:
        out.append(diag(
            AnalysisCode.COLLECTIVE_COUNT_MISMATCH, Severity.ERROR,
            detail=(f"{label}: explicit collectives {jaxpr_counts} in the "
                    "traced dispatch path (GSPMD inserts its own on top)")))

    devices = jax.devices()
    if num_devices <= 1 or len(devices) < num_devices:
        return report, out

    text = _compiled_text(circuit, num_devices, dtype, donate)
    shard_amps = (1 << n) // num_devices
    hlo = count_hlo_collectives(
        text, min_elems=shard_amps // (2 * max(1, pipeline_chunks)))
    measured = sum(hlo.values())
    report["hlo_collectives"] = hlo
    report["donation_aliased"] = donation_aliased(text)

    if predicted["comm_events"] == 0 and measured:
        out.append(diag(
            AnalysisCode.UNEXPECTED_ALLGATHER, Severity.ERROR,
            detail=(f"{label}: planner models this circuit comm-free on "
                    f"{num_devices} devices but the compiled program moves "
                    f"state-sized data: {hlo}")))
    elif measured > (_HLO_OPS_PER_EVENT * max(1, pipeline_chunks)
                     * predicted["comm_events"]):
        out.append(diag(
            AnalysisCode.COLLECTIVE_COUNT_MISMATCH, Severity.WARNING,
            detail=(f"{label}: compiled HLO has {measured} state-sized "
                    f"collectives ({hlo}) vs {predicted['comm_events']} "
                    f"planner-predicted comm events (> "
                    f"{_HLO_OPS_PER_EVENT * max(1, pipeline_chunks)}x: the "
                    "model undercosts this circuit)")))

    if donate and not report["donation_aliased"]:
        out.append(diag(
            AnalysisCode.DONATION_UNUSED, Severity.WARNING,
            detail=(f"{label}: donate=True compiled without an "
                    "input_output_alias — the state buffer is NOT reused")))
    return report, out


def _compiled_text(circuit, num_devices: int, dtype, donate: bool,
                   per_op: bool = False) -> str:
    import jax
    from ..circuit import _apply_one, _run_ops_routed
    from ..parallel.mesh import amp_sharding, make_amps_mesh
    mesh = make_amps_mesh(jax.devices()[:num_devices])
    sharding = amp_sharding(mesh)
    ops = circuit.key()

    def run_routed(s):
        return _run_ops_routed(s, ops)

    def run_per_op(s):
        # bench.py's pair methodology: one eager-shaped kernel per op, so
        # scheduling deltas stay visible (the routed executor would defer
        # both variants' permutations into the same trailing reconcile)
        for op in ops:
            s = _apply_one(s, op)
        return s

    # output sharding pinned to the input's, exactly like bench.py's pairs:
    # otherwise the partitioner may virtualise a trailing permutation into
    # an output-layout relabel and the counts stop being comparable
    fn = jax.jit(run_per_op if per_op else run_routed,
                 out_shardings=sharding,
                 donate_argnums=(0,) if donate else ())
    spec = jax.ShapeDtypeStruct((2, 1 << circuit.num_qubits), dtype,
                                sharding=sharding)
    return fn.lower(spec).compile().as_text()


def audit_schedule_pair(circuit, scheduled, num_devices: int, *,
                        dtype=None,
                        label: str = "pair") -> tuple[dict, list[Diagnostic]]:
    """HLO-level scheduler regression gate: compile BOTH members of an
    (unscheduled, scheduled) pair against the mesh and require the
    scheduled program to contain no more state-sized collectives than the
    unscheduled one — the partitioner-observed twin of the planner-level
    ``A_SCHEDULE_COMM_REGRESSION`` check, over the same pair bench.py
    measures.  Host + compile work only; nothing executes."""
    import jax
    import jax.numpy as jnp
    dtype = dtype or jnp.float32
    report: dict = {"label": label, "num_devices": num_devices,
                    "unscheduled_hlo": None, "scheduled_hlo": None}
    out: list[Diagnostic] = []
    if num_devices <= 1 or len(jax.devices()) < num_devices:
        return report, out
    shard_amps = (1 << circuit.num_qubits) // num_devices
    before = count_hlo_collectives(
        _compiled_text(circuit, num_devices, dtype, False, per_op=True),
        min_elems=shard_amps // 2)
    after = count_hlo_collectives(
        _compiled_text(scheduled, num_devices, dtype, False, per_op=True),
        min_elems=shard_amps // 2)
    report["unscheduled_hlo"] = before
    report["scheduled_hlo"] = after
    if sum(after.values()) > sum(before.values()):
        out.append(diag(
            AnalysisCode.COLLECTIVE_COUNT_MISMATCH, Severity.ERROR,
            detail=(f"{label}: scheduling INCREASED compiled state-sized "
                    f"collectives {sum(before.values())} -> "
                    f"{sum(after.values())} ({before} -> {after})")))
    return report, out


def audit_overlap(circuit, num_devices: int, pipeline_chunks: int, *,
                  dtype=None,
                  label: str = "overlap") -> tuple[dict, list[Diagnostic]]:
    """Audit the PIPELINED executor's compiled program
    (parallel/executor.py) against its own overlap plan.

    Compiles ``circuit`` through ``overlapped_program`` on the real mesh
    (when the process has the devices) and checks:

    - the chunk-sized collective count stays within the widened per-event
      bound (``_HLO_OPS_PER_EVENT x C`` per planned event —
      ``A_COLLECTIVE_COUNT_MISMATCH`` beyond it);
    - every collective the plan expects to HIDE shows async start/done
      separation in the compiled HLO; none at all is
      ``A_COLLECTIVE_NOT_OVERLAPPED`` (WARNING — expected on CPU meshes,
      whose backend runs collectives synchronously; a regression on TPU).

    Host + compile work only; nothing executes."""
    import jax
    import jax.numpy as jnp
    from ..parallel import executor as _exec

    dtype = dtype or jnp.float32
    plan = getattr(circuit, "_overlap_plan", None)
    if plan is None or plan.pipeline_chunks != pipeline_chunks \
            or plan.num_devices != num_devices:
        plan = _exec.plan_overlap(circuit, num_devices, pipeline_chunks)
    report: dict = {
        "label": label, "num_devices": num_devices,
        "pipeline_chunks": pipeline_chunks,
        "planned_events": len(plan.events),
        "chunked_events": sum(1 for e in plan.events if e.chunks > 1),
        "hideable_events": sum(1 for e in plan.events if e.hideable),
        "hlo_collectives": None, "hlo_async": None,
    }
    out: list[Diagnostic] = []
    if num_devices <= 1 or len(jax.devices()) < num_devices:
        return report, out
    from ..parallel import planner as _planner
    fn = _exec.overlapped_program(circuit, num_devices, pipeline_chunks)
    from ..parallel.mesh import amp_sharding, make_amps_mesh
    sharding = amp_sharding(make_amps_mesh(jax.devices()[:num_devices]))
    spec = jax.ShapeDtypeStruct((2, 1 << circuit.num_qubits), dtype,
                                sharding=sharding)
    text = fn.lower(spec).compile().as_text()
    shard_amps = (1 << circuit.num_qubits) // num_devices
    hlo = count_hlo_collectives(
        text, min_elems=shard_amps // (2 * max(1, pipeline_chunks)))
    async_counts = count_hlo_async_collectives(text)
    report["hlo_collectives"] = hlo
    report["hlo_async"] = async_counts
    measured = sum(hlo.values())
    predicted = _planner.comm_summary(
        circuit, num_devices,
        bytes_per_amp=8 if jnp.dtype(dtype) == jnp.float32 else 16)
    bound = (_HLO_OPS_PER_EVENT * max(1, pipeline_chunks)
             * predicted["comm_events"])
    if measured > bound:
        out.append(diag(
            AnalysisCode.COLLECTIVE_COUNT_MISMATCH, Severity.WARNING,
            detail=(f"{label}: overlapped program compiles to {measured} "
                    f"chunk-sized collectives ({hlo}) vs a bound of "
                    f"{bound} for {predicted['comm_events']} planned "
                    f"events x {pipeline_chunks} chunks")))
    if report["hideable_events"] and any(e.chunks > 1 and e.hideable
                                         for e in plan.events) \
            and async_counts["separated"] == 0:
        out.append(diag(
            AnalysisCode.COLLECTIVE_NOT_OVERLAPPED, Severity.WARNING,
            detail=(f"{label}: {report['hideable_events']} event(s) "
                    f"planned as hidden but the compiled HLO shows "
                    f"{async_counts['starts']} async start(s) with zero "
                    "start/done separation")))
    return report, out


def audit_epoch_donation(circuit, *, label: str = "circuit"
                         ) -> tuple[dict, list[Diagnostic]]:
    """Audit the epoch executor's donated plane-pair program
    (ops/epoch_pallas.py ``jit_program_planes``): both plane buffers are
    donated, so the compiled module MUST carry ``input_output_alias``
    entries — that aliasing is what makes the fused passes run truly in
    place (one state copy of peak HBM at the 30q single-chip ceiling).  A
    missing alias means every call pays two extra plane allocations:
    ``A_DONATION_UNUSED``, the same contract :func:`audit_dispatch`
    enforces for the (2, N) donate path.  Returns ``(report,
    diagnostics)``; the report also counts the custom-call sites of the
    lowered Pallas kernels so the CLI can show the pass count survived
    compilation."""
    import jax
    import jax.numpy as jnp

    from .. import _compat
    from ..ops import epoch_pallas as _ep
    from ..ops.apply import reconcile_perm_planes

    n = circuit.num_qubits
    ops = circuit.key()
    plan = _ep.plan_circuit(ops, n)

    def run(re, im):
        re, im, perm = _ep.run_planes(re, im, ops)
        return reconcile_perm_planes(re, im, perm)

    spec = jax.ShapeDtypeStruct((1 << n,), jnp.float32)
    with _compat.enable_x64(False):
        text = jax.jit(run, donate_argnums=(0, 1)).lower(
            spec, spec).compile().as_text()
    report = {
        "label": label,
        "num_qubits": n,
        "donation_aliased": donation_aliased(text),
        "pallas_passes": plan.pallas_passes,
        "hbm_passes": plan.hbm_passes,
    }
    out: list[Diagnostic] = []
    if not report["donation_aliased"]:
        out.append(diag(
            AnalysisCode.DONATION_UNUSED, Severity.WARNING,
            detail=(f"{label}: the epoch executor's donated plane-pair "
                    "program compiled without an input_output_alias — the "
                    "plane buffers are NOT reused and the in-place "
                    "aliasing chain is broken")))
    return report, out
