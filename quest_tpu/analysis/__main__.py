"""``python -m quest_tpu.analysis`` — the static-analysis CLI.

Modes (combinable; exit status is 1 iff any ERROR-severity diagnostic):

- ``--self-lint``: purity-lint the installed quest_tpu tree (the CI gate).
- ``--lint PATH [PATH ...]``: purity-lint arbitrary files/trees.
- ``--concurrency``: lock-discipline audit over the serve/deploy/obs
  runtime packages (analysis/concurrency.py): per lock-owning class,
  every shared attribute's reads/writes are checked against its
  ``# guarded-by:`` / ``# lock-free:`` annotation (``T_*`` codes: missing
  guards, inconsistent guards, lock-order cycles, blocking calls under a
  lock).  ``--concurrency-paths PATH ...`` audits arbitrary trees
  instead.  ``--fuzz-smoke`` additionally runs the schedule-fuzzing
  harness (analysis/schedfuzz.py) over the annotated lock-free read
  surfaces — forced interleavings asserting every concurrent snapshot is
  internally consistent; violations are ``T_SCHEDULE_FUZZ_FAILURE``
  errors.  Under ``--json`` everything lands in the single document's
  ``"concurrency"`` section (classes, lock graph, fuzz rows) with
  severities in the shared ``diagnostics``/``summary`` sections the CI
  gate already parses.
- ``--staticcheck``: compile-economics static audit
  (analysis/staticcheck.py; ``S_*`` codes).  Layer 1: AST rules over the
  whole quest_tpu tree plus examples/ — literal gate parameters the
  param_vector lift should carry (``S_UNLIFTED_LITERAL``),
  recompile-keyed jit boundaries (``S_RECOMPILE_HAZARD``), host syncs
  reachable from submission roots (``S_HOST_SYNC_IN_HOT_PATH``),
  f64-forcing flows inside traced functions (``S_X64_PROMOTION``);
  waivers ``# unlifted-ok:`` / ``# recompile-ok:`` / ``# host-sync-ok:``
  / ``# x64-ok:`` with REQUIRED reasons.  Layer 2: per serve-selftest
  structural class, the per-request program is re-traced under an
  operand perturbation and diffed constant by constant — any difference
  is a per-request recompile proven at trace time
  (``S_CLASS_NOT_CLOSED``) — and a weak-type scan of the f32 trace pins
  ``S_X64_PROMOTION`` on the program actually served.
  ``--staticcheck-paths PATH ...`` audits arbitrary trees (AST layer
  only); ``--no-served-classes`` skips the jaxpr layer.  Under
  ``--json`` everything lands in the ``"staticcheck"`` section.
- ``--qft N`` / ``--random N DEPTH``: analyze a generated benchmark circuit.
- ``--circuit module:attr``: import and analyze a user circuit — ``attr``
  may be a :class:`quest_tpu.Circuit` or a zero-argument factory.
- ``--schedule``: additionally run the comm-aware scheduler
  (parallel/scheduler.py) on each circuit and print the planner-predicted
  before/after comm report; a scheduled circuit the model rates as MORE
  communication is an ERROR (A_SCHEDULE_COMM_REGRESSION) — the CI smoke
  gate that scheduling savings stay nonnegative.
- ``--verify-schedule``: translation-validate each circuit's scheduled
  rewrite (analysis/equivalence.py: Pauli tableau + phase polynomial +
  dense windows; ``V_*`` codes) AND audit the lowered dispatch path
  against the planner's comm model (analysis/jaxpr_audit.py: collective
  counts, unexpected gathers, donation aliasing) — the CI scheduler-
  correctness smoke.  Implies ``--schedule``'s scheduling step.

- ``--trace-report``: execute each circuit single-device with span tracing
  on (quest_tpu/obs), print the per-span/per-request view, and record a
  model-vs-measured ledger row (predicted vs measured wall /
  collective-count); ledger drift reports as ``O_MODEL_DRIFT`` (WARNING —
  the ``obs-selftest`` CI job gates on zero).  Under ``--json`` the mode
  honors the ONE-machine-readable-document contract like every other
  mode: per-circuit rows land in ``"trace_report"`` (ledger row + Chrome
  trace, no human-text blobs) and the process ledger is summarized in a
  top-level ``"ledger"`` section CI parses instead of grepping.

- ``--numeric-report``: execute each circuit through the probe-
  instrumented program variant (quest_tpu/obs/numerics.py): assert the
  instrumented primary output BIT-IDENTICAL to the uninstrumented one
  (violation: ``A_NUMERIC_PROBE_DIVERGENCE``, ERROR), record a numeric
  drift ledger row (norm vs the precision-and-depth-derived ulp band,
  NaN/Inf counts), and — with ``--engine pallas`` inside the epoch
  envelope — run the epoch plan pass by pass with a probe at every
  fused-pass boundary, independently confirming the planner's pass
  count.  Ledger findings report as ``O_NUMERIC_DRIFT`` (WARNING) /
  ``O_NUMERIC_NAN`` (ERROR); under ``--json`` per-circuit rows land in
  ``"numeric_report"`` and the process numeric ledger in a top-level
  ``"numeric_ledger"`` section (the CI ``numeric-selftest`` gate parses
  both).

- ``--serve-audit``: machine-prove the serve layer's parameter-lifted
  compilation cache (analysis/serve_audit.py): per structural class, the
  skeleton + operand-vector reconstruction is translation-validated
  against the request circuit, the lifted ``(state, params)`` program is
  probed against the eager path, and an angle-perturbed twin must share
  the cache entry — any violation is ``A_PARAM_LIFT_DIVERGENCE``.  Audits
  the listed circuits, or the serve selftest workload when none are given.

- ``--calibrate``: run the on-device calibration harness
  (quest_tpu/obs/calibrate.py) on the live backend — per-gate XLA
  appliers by qubit position class, Pallas epoch passes (interpret mode
  off-TPU), collectives by payload bytes when a mesh is visible — fit
  the planner's constants, write the versioned profile to
  ``--calibration-out`` (default ``calibration_profile.json``), ACTIVATE
  it for the rest of the invocation (so a combined ``--trace-report``
  runs under the fitted band), and report which engine/placement
  decisions flip under measured constants vs the hard-coded defaults.
  ``--calibration PATH`` loads and activates an existing profile
  instead (the deployment path: schedule/trace-report/serve decisions
  under the fleet's own measured constants).

Circuit modes run the IR pass and the eager/compiled abstract-eval pass
against the deployment described by ``--devices/--precision/--chip``.

``--json`` switches stdout to ONE machine-readable JSON document —
``{"diagnostics": [...], "circuits": [...], "schedule": [...],
"verify": [...], "serve_audit": [...], "trace_report": [...],
"calibration": {...}, "ledger": {...}, "summary": {...}}`` — so CI
gates parse severities instead of grepping text.  Exit status is
unchanged.
"""

from __future__ import annotations

import argparse
import importlib
import json
import sys

from .abstract_eval import check_abstract_eval
from .circuit_ir import analyze_circuit
from .diagnostics import Severity
from .purity import lint_package, lint_paths


def _load_circuit(spec: str):
    module_name, _, attr = spec.partition(":")
    if not attr:
        raise SystemExit(f"--circuit takes module:attr, got {spec!r}")
    obj = getattr(importlib.import_module(module_name), attr)
    return obj() if callable(obj) else obj


def _chip(name: str):
    from ..parallel import planner
    try:
        return {"v5e": planner.V5E, "v5p": planner.V5P}[name]
    except KeyError:
        raise SystemExit(f"unknown chip {name!r} (v5e | v5p)")


def _dtype(precision: int):
    import jax.numpy as jnp
    return jnp.float32 if precision == 1 else jnp.float64


def _schedule_report(label: str, circuit, args, scheduled, echo) -> tuple:
    """Planner-predicted savings of ``scheduled`` vs ``circuit``; an ERROR
    diagnostic iff the scheduled circuit models as MORE communication than
    the input, or iff the overlap-aware time model predicts the pipelined
    executor SLOWER than the serial schedule (the CI smoke contracts)."""
    from ..parallel.scheduler import schedule_savings
    from .diagnostics import AnalysisCode, Severity, diag
    report = schedule_savings(circuit, args.devices, chip=_chip(args.chip),
                              precision=args.precision, scheduled=scheduled,
                              pipeline_chunks=args.overlap_chunks,
                              engine=args.engine)
    report["label"] = label
    echo(f"{label}: schedule savings " + json.dumps(report, default=float))
    echo(f"{label}: engine {report['engine_chosen']} "
         f"({report['engine_reason']}); epochs "
         + json.dumps(report["engine_epochs"], default=float))
    out = []
    if (report["comm_events_after"] > report["comm_events_before"]
            or report["comm_bytes_after"] > report["comm_bytes_before"]):
        out.append(diag(AnalysisCode.SCHEDULE_COMM_REGRESSION, Severity.ERROR,
                        detail=(f"{label}: events "
                                f"{report['comm_events_before']}->"
                                f"{report['comm_events_after']}, bytes "
                                f"{report['comm_bytes_before']}->"
                                f"{report['comm_bytes_after']}")))
    if (report.get("model_seconds_overlapped") is not None
            and report["model_seconds_overlapped"]
            > report["model_seconds_after"] * (1 + 1e-9)):
        out.append(diag(AnalysisCode.OVERLAP_MODEL_REGRESSION, Severity.ERROR,
                        detail=(f"{label}: "
                                f"{report['model_seconds_overlapped']:.3g}s "
                                f"overlapped vs "
                                f"{report['model_seconds_after']:.3g}s "
                                "serial")))
    return report, out


def _verify_report(label: str, circuit, args, scheduled, echo) -> tuple:
    """Translation validation + lowered-program audit of one scheduled
    rewrite (the --verify-schedule payload).  With --overlap-chunks the
    chunking plan is proven layout-only (check_overlap_plan) and the
    pipelined executor's compiled program is audited (audit_overlap)."""
    from .equivalence import check_equivalence, check_overlap_plan
    from .jaxpr_audit import audit_dispatch, audit_overlap, \
        audit_schedule_pair
    found = check_equivalence(circuit, scheduled)
    plan = getattr(scheduled, "_overlap_plan", None)
    if plan is not None:
        found += check_overlap_plan(scheduled, plan)
    report = {
        "label": label,
        "devices": args.devices,
        "ops_in": len(circuit.ops),
        "ops_scheduled": len(scheduled.ops),
        "equivalence_diagnostics": len(found),
        "proven_equivalent": not found,
    }
    audit, d2 = audit_dispatch(scheduled, args.devices,
                               dtype=_dtype(args.precision), label=label)
    pair, d3 = audit_schedule_pair(circuit, scheduled, args.devices,
                                   dtype=_dtype(args.precision), label=label)
    d4: list = []
    if plan is not None:
        overlap, d4 = audit_overlap(scheduled, args.devices,
                                    plan.pipeline_chunks,
                                    dtype=_dtype(args.precision),
                                    label=label)
        report["overlap_audit"] = overlap
    report["dispatch_audit"] = audit
    report["hlo_pair"] = {k: pair[k]
                          for k in ("unscheduled_hlo", "scheduled_hlo")}
    d5: list = []
    if getattr(scheduled, "density_qubits", None) is not None:
        # the density half of the rollout gate — the Choi-doubling itself
        # (mirrored pairing, conjugate twist, channel superoperators vs
        # the Kraus oracle) — is IR-level and ENGINE-INDEPENDENT: it runs
        # for every density circuit, epoch envelope or not (an
        # out-of-window wrong-conjugate shadow must not sail through)
        from .equivalence import check_density_lowering
        dproof = check_density_lowering(scheduled)
        report["density_proven"] = not dproof
        d5 += dproof
    if args.engine == "pallas" and args.devices <= 1:
        # the epoch-executor rollout gate (docs/ANALYSIS.md): the Pallas
        # lowering of the scheduled circuit is proven IR-equivalent
        # (check_epoch_plan: same V_* domains) and the actual kernels are
        # probed in interpret mode where the register fits
        from ..ops import epoch_pallas as _ep
        if _ep.epoch_supported(scheduled.num_qubits, args.precision):
            from .equivalence import (check_epoch_plan,
                                      probe_epoch_execution)
            plan_e = _ep.plan_circuit(scheduled.key(), scheduled.num_qubits)
            proof = check_epoch_plan(scheduled, plan_e)
            probe = probe_epoch_execution(scheduled)
            d5 += proof + probe
            report["epoch_plan"] = plan_e.summary()
            # the IR proof stands alone; the probe's skip warning beyond
            # its register cap must not read as a failed proof
            report["epoch_proven"] = not proof and not any(
                d.severity >= Severity.ERROR for d in probe)
            report["epoch_probe_executed"] = not any(
                d.code == "V_UNVERIFIED_REGION" for d in probe)
        else:
            report["epoch_plan"] = None
            report["epoch_proven"] = False
            report["epoch_probe_executed"] = False
            report["epoch_skip_reason"] = (
                f"outside the epoch engine envelope (f32, "
                f"{_ep.MIN_QUBITS} <= n <= {_ep.MAX_QUBITS})")
    echo(f"{label}: verify-schedule " + json.dumps(report, default=float))
    return report, found + d2 + d3 + d4 + d5


def _calibrate_report(args, circuits, echo) -> dict:
    """The ``--calibrate`` payload: run the harness, persist + activate
    the profile, and report which engine/placement decisions flip under
    the measured constants (the proof the planner is actually reading
    them).  Engine decisions are scored on the TPU-class spec (the
    deterministic dispatch rule); placement flips are reported when
    ``--devices`` names a mesh."""
    from ..obs import calibrate as _cal
    from ..parallel import planner as _planner
    from ..parallel.scheduler import greedy_placement

    chip = _chip(args.chip)
    profile = _cal.run_calibration(chip=chip)
    doc = _cal.save_profile(profile, args.calibration_out)
    _cal.activate(profile)
    echo(f"calibration: profile {profile.profile_id} "
         f"({profile.platform}/{profile.device_kind or '-'}) written to "
         f"{args.calibration_out}; wall band "
         f"[{profile.wall_band[0]:.3g}, {profile.wall_band[1]:.3g}]")

    suite = list(circuits)
    if not suite:
        from ..circuit import qft_circuit, random_circuit
        suite = [("qft(17)", qft_circuit(17)), ("qft(22)", qft_circuit(22)),
                 ("random(20,3)", random_circuit(20, 3, seed=11))]
    decisions = []
    engine_flips = placement_flips = 0
    for label, circuit in suite:
        row: dict = {"label": label}
        with _cal.use_profile(None):
            base = _planner.select_engine(circuit, 1, chip, args.precision,
                                          backend="tpu")
        with _cal.use_profile(profile):
            cal = _planner.select_engine(circuit, 1, chip, args.precision,
                                         backend="tpu")
        row["engine_default"] = base["engine"]
        row["engine_calibrated"] = cal["engine"]
        row["engine_flipped"] = base["engine"] != cal["engine"]
        row["engine_reason_calibrated"] = cal["reason"]
        # the decision's OWN provenance stamp (select_engine attaches it):
        # the CI gate checks the profile id here, proving the decision was
        # actually scored on the fitted constants
        row["calibration"] = cal["calibration"]
        engine_flips += row["engine_flipped"]
        if args.devices > 1:
            with _cal.use_profile(None):
                sig0 = greedy_placement(circuit, args.devices, chip,
                                        args.precision)
            with _cal.use_profile(profile):
                sig1 = greedy_placement(circuit, args.devices, chip,
                                        args.precision)
            row["placement_default"] = list(sig0)
            row["placement_calibrated"] = list(sig1)
            row["placement_flipped"] = sig0 != sig1
            placement_flips += row["placement_flipped"]
        decisions.append(row)
        echo(f"{label}: engine {row['engine_default']} -> "
             f"{row['engine_calibrated']}"
             + (" (FLIPPED)" if row["engine_flipped"] else "")
             + (f"; placement flipped: {row.get('placement_flipped')}"
                if args.devices > 1 else ""))
    return {"profile": doc, "path": args.calibration_out,
            "decisions": decisions, "engine_flips": engine_flips,
            "placement_flips": placement_flips}


def _trace_report_run(label: str, circuit, args, echo) -> tuple:
    """The ``--trace-report`` payload for one circuit: compile it for the
    requested engine, execute it single-device with tracing on, and record
    a model-vs-measured ledger row (quest_tpu/obs/ledger.py) — predicted
    seconds / HBM passes / comm events from the planner's engine model next
    to measured wall time and the compiled-HLO collective count.  Ledger
    drift findings come back as WARNING diagnostics with the ledger's
    ``O_MODEL_DRIFT`` code (zero of them is the ci.yml ``obs-selftest``
    gate on the 17q QFT CPU run)."""
    import time

    import jax
    import jax.numpy as jnp

    from .. import obs as _obs
    from ..circuit import compile_circuit
    from ..parallel import planner as _planner
    from .diagnostics import Severity, diag
    from .jaxpr_audit import count_hlo_collectives

    was_enabled = _obs.tracing_enabled()
    _obs.enable_tracing()
    _obs.reset_tracing()
    try:
        run = compile_circuit(circuit, engine=args.engine)
        dtype = _dtype(args.precision)
        if run.engine == "pallas":
            dtype = jnp.float32     # the epoch engine's envelope
        n = circuit.num_qubits
        state = jnp.zeros((2, 1 << n), dtype).at[0, 0].set(1.0)
        t0 = time.perf_counter()
        jax.block_until_ready(run(state))          # compile + warm
        compile_s = time.perf_counter() - t0
        _obs.record_compile(compile_s)
        t0 = time.perf_counter()
        jax.block_until_ready(run(state))
        measured_s = time.perf_counter() - t0
        hbm = _obs.update_hbm_watermark()          # None on CPU backends
        # compiled-HLO observation: the epoch engine traces with x64 off
        # (the Mosaic constraint, circuit.py), so its audit lowering must
        # run under the same flag or aval dtypes drift mid-trace
        from .. import _compat
        with _compat.enable_x64(run.engine != "pallas"
                                and jax.config.jax_enable_x64):
            text = jax.jit(run).lower(state).compile().as_text()
        measured_coll = sum(count_hlo_collectives(
            text, min_elems=(1 << n) // 2).values())
        model = _planner.engine_time_model(circuit, _chip(args.chip),
                                           args.precision)
        if run.engine == "pallas":
            predicted_s = model["pallas_seconds"]
            passes = model["pallas_hbm_passes"]
        else:
            predicted_s = model["xla_seconds"]
            passes = model["xla_hbm_passes"]
        # the run is SINGLE-device (the mode's contract), so the ledger row
        # compares the single-device model against the single-device
        # measurement — mixing an --devices N prediction with a 1-device
        # compile would mask real comm-model drift
        predicted_coll = _planner.comm_summary(circuit, 1)["comm_events"]
        rec = _obs.global_ledger().record(
            label, engine=run.engine, num_devices=1,
            platform=jax.default_backend(),
            predicted_seconds=predicted_s,
            measured_seconds=measured_s,
            predicted_hbm_passes=passes,
            predicted_collectives=predicted_coll,
            measured_hlo_collectives=measured_coll,
            compile_seconds=compile_s,
            hbm_peak_bytes=(hbm or {}).get("peak_bytes_in_use"),
            warn=False)
        spans = _obs.recorder().spans()
        # the document stays MACHINE-readable end to end (the PR 3 --json
        # contract): the human span-tree view is echoed in text mode only,
        # never embedded as a text blob inside the JSON payload
        report = {
            "label": label,
            "engine": run.engine,
            "engine_reason": run.engine_reason,
            "spans": len(spans),
            "measured_seconds": measured_s,
            "ledger": rec.as_dict(),
            "chrome_trace": _obs.chrome_trace(spans),
        }
        echo(f"{label}: trace-report {len(spans)} span(s), engine "
             f"{run.engine}, {measured_s:.3g}s measured "
             f"(model {predicted_s:.3g}s), {measured_coll} HLO "
             f"collective(s) vs {predicted_coll} predicted event(s)")
        echo(_obs.trace_report(spans))
        from ..obs.ledger import MODEL_DRIFT
        found = [diag(MODEL_DRIFT, Severity.WARNING,
                      detail=f"{label}: {f}") for f in rec.findings]
        return report, found
    finally:
        if not was_enabled:
            _obs.disable_tracing()


def _numeric_report_run(label: str, circuit, args, echo) -> tuple:
    """The ``--numeric-report`` payload for one circuit: the probed twin
    of the program is executed beside the plain one (bit-identity
    asserted — probes are pure reductions grafted BESIDE the dataflow,
    A_NUMERIC_PROBE_DIVERGENCE if one ever leaks in), the final-state
    probe is judged by the numeric drift ledger, and — on the Pallas
    engine inside the epoch envelope — the plan runs pass by pass with a
    probe at every fused-pass boundary, independently confirming the
    planner's pass count (obs/numerics.py epoch_pass_probes)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..circuit import _run_ops
    from ..obs import numerics as _num
    from .diagnostics import AnalysisCode, Severity, diag

    n = circuit.num_qubits
    dtype = _dtype(args.precision)
    ops = circuit.key()
    ledger = _num.global_numeric_ledger()
    state = jnp.zeros((2, 1 << n), dtype).at[0, 0].set(1.0)
    plain = np.asarray(jax.block_until_ready(_run_ops(state, ops)))
    out, probe = _num.run_ops_probed(state, ops)
    out = np.asarray(jax.block_until_ready(out))
    bit_identical = bool(np.array_equal(out, plain))
    found: list = []
    rec = ledger.record(label, probe, engine="xla",
                        dtype=str(jnp.dtype(dtype)), num_qubits=n,
                        num_ops=len(ops), warn=False)
    report = {"label": label, "ops": len(ops),
              "precision": args.precision,
              "bit_identical": bit_identical,
              "ledger": rec.as_dict(), "epoch": None}
    if not bit_identical:
        found.append(diag(AnalysisCode.NUMERIC_PROBE_DIVERGENCE,
                          Severity.ERROR,
                          detail=f"{label}: instrumented primary output "
                                 "diverged from the uninstrumented run"))
    recs = [rec]
    if args.engine == "pallas":
        from ..ops import epoch_pallas as _ep
        if _ep.epoch_supported(n, 1):
            st32 = jnp.zeros((2, 1 << n), jnp.float32).at[0, 0].set(1.0)
            base = np.asarray(jax.block_until_ready(
                _ep.jit_program(ops)(st32)))
            out_e, points, plan = _num.epoch_pass_probes(ops, n, st32)
            out_e = np.asarray(jax.block_until_ready(out_e))
            xla_segments = sum(1 for s in plan["segments"]
                               if s["engine"] == "xla")
            rec_e = ledger.record(
                f"{label}/epoch", _num.state_probe_vector(jnp.asarray(out_e)),
                engine="pallas", dtype="float32", num_qubits=n,
                num_ops=len(ops), probe_points=tuple(points), warn=False)
            recs.append(rec_e)
            epoch = {
                "plan": plan,
                "probe_points": points,
                "pass_probe_count": len(points),
                # the plan said N fused passes; N probes observed N
                # intermediate states — the runtime confirmation of the
                # planner's fused-pass boundaries
                "boundaries_confirmed": len(points)
                == plan["pallas_passes"] + xla_segments,
                "bit_identical": bool(np.array_equal(out_e, base)),
                "ledger": rec_e.as_dict(),
            }
            report["epoch"] = epoch
            if not epoch["bit_identical"]:
                found.append(diag(
                    AnalysisCode.NUMERIC_PROBE_DIVERGENCE, Severity.ERROR,
                    detail=f"{label}: per-pass-probed epoch output "
                           "diverged from the uninstrumented program"))
        else:
            report["epoch"] = {
                "skip_reason": "outside the epoch engine envelope (f32, "
                               f"{_ep.MIN_QUBITS} <= n <= {_ep.MAX_QUBITS})"}
    for r in recs:
        for f in r.findings:
            nan = _num.NUMERIC_NAN in f
            found.append(diag(
                AnalysisCode.NUMERIC_NAN if nan
                else AnalysisCode.NUMERIC_DRIFT,
                Severity.ERROR if nan else Severity.WARNING,
                detail=f"{r.label}: {f}"))
    echo(f"{label}: numeric-report bit_identical={bit_identical}, norm "
         f"{rec.norm:.17g} (drift {rec.norm_drift:.3g}, band "
         f"{rec.band:.3g}), {rec.nan_count} NaN / {rec.inf_count} Inf"
         + (f"; epoch: {report['epoch']['pass_probe_count']} probe "
            f"point(s), boundaries_confirmed="
            f"{report['epoch']['boundaries_confirmed']}"
            if report["epoch"] and "pass_probe_count" in report["epoch"]
            else ""))
    return report, found


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m quest_tpu.analysis",
        description="Static circuit analyzer + JAX-purity lint for quest_tpu.")
    parser.add_argument("--self-lint", action="store_true",
                        help="purity-lint the quest_tpu package tree")
    parser.add_argument("--lint", nargs="+", metavar="PATH",
                        help="purity-lint the given files/directories")
    parser.add_argument("--concurrency", action="store_true",
                        help="lock-discipline audit over the serve/deploy/"
                             "obs runtime packages (docs/ANALYSIS.md "
                             "pass 7)")
    parser.add_argument("--concurrency-paths", nargs="+", metavar="PATH",
                        dest="concurrency_paths",
                        help="audit these files/trees instead of the "
                             "installed runtime packages (implies "
                             "--concurrency)")
    parser.add_argument("--fuzz-smoke", action="store_true",
                        dest="fuzz_smoke",
                        help="with --concurrency: run the schedule-fuzz "
                             "smoke (analysis/schedfuzz.py) over the "
                             "lock-free read surfaces; inconsistent "
                             "snapshots are T_SCHEDULE_FUZZ_FAILURE "
                             "errors (implies --concurrency)")
    parser.add_argument("--fuzz-seeds", type=int, default=2,
                        dest="fuzz_seeds", metavar="N",
                        help="interleaving seeds per fuzz scenario "
                             "(default %(default)s)")
    parser.add_argument("--staticcheck", action="store_true",
                        help="compile-economics static audit (S_* codes): "
                             "AST rules over quest_tpu + examples plus the "
                             "traced-served-class jaxpr diff "
                             "(analysis/staticcheck.py)")
    parser.add_argument("--staticcheck-paths", nargs="+", metavar="PATH",
                        dest="staticcheck_paths",
                        help="audit these files/trees with the S_* AST "
                             "rules only (implies --staticcheck, skips the "
                             "served-class audit)")
    parser.add_argument("--no-served-classes", action="store_true",
                        help="with --staticcheck: skip the Layer-2 traced "
                             "served-class audit (AST rules only)")
    parser.add_argument("--qft", type=int, metavar="N",
                        help="analyze an N-qubit QFT circuit")
    parser.add_argument("--random", nargs=2, type=int, metavar=("N", "DEPTH"),
                        help="analyze an N-qubit depth-DEPTH random circuit")
    parser.add_argument("--circuit", metavar="MODULE:ATTR", action="append",
                        help="import and analyze a Circuit (or factory); "
                             "repeatable")
    parser.add_argument("--schedule", action="store_true",
                        help="run the comm-aware scheduler on each circuit "
                             "and report predicted comm savings")
    parser.add_argument("--verify-schedule", action="store_true",
                        dest="verify_schedule",
                        help="translation-validate each circuit's scheduled "
                             "rewrite and audit the lowered dispatch path")
    parser.add_argument("--serve-audit", action="store_true",
                        dest="serve_audit",
                        help="machine-prove the serve cache's parameter "
                             "lift per structural class (round-trip "
                             "equivalence + lifted-vs-eager probe + key "
                             "stability; analysis/serve_audit.py).  Audits "
                             "the --qft/--random/--circuit circuits, or "
                             "the serve selftest workload when none are "
                             "given; --devices > 1 audits the scheduler-"
                             "composed cache path")
    parser.add_argument("--trace-report", action="store_true",
                        dest="trace_report",
                        help="execute each circuit single-device with span "
                             "tracing on (quest_tpu/obs), print the "
                             "per-request/per-span report, and record a "
                             "model-vs-measured ledger row; ledger drift "
                             "is reported as O_MODEL_DRIFT (WARNING)")
    parser.add_argument("--numeric-report", action="store_true",
                        dest="numeric_report",
                        help="execute each circuit through the probe-"
                             "instrumented program (quest_tpu/obs/"
                             "numerics.py): bit-identity asserted, a "
                             "numeric drift ledger row recorded, and "
                             "(--engine pallas) per-pass probes at every "
                             "fused-pass boundary; findings report as "
                             "O_NUMERIC_DRIFT / O_NUMERIC_NAN")
    parser.add_argument("--calibrate", action="store_true",
                        help="run the on-device calibration harness "
                             "(quest_tpu/obs/calibrate.py), write the "
                             "fitted profile to --calibration-out, "
                             "activate it for this invocation, and report "
                             "which engine/placement decisions flip under "
                             "measured constants")
    parser.add_argument("--calibration", metavar="PATH",
                        help="load + activate an existing calibration "
                             "profile before any other mode runs (the "
                             "planner then reads its fitted constants and "
                             "the ledger checks walls against its band)")
    parser.add_argument("--calibration-out", metavar="PATH",
                        dest="calibration_out",
                        default="calibration_profile.json",
                        help="where --calibrate writes the profile "
                             "(default %(default)s)")
    parser.add_argument("--overlap-chunks", type=int, default=None,
                        dest="overlap_chunks", metavar="C",
                        help="schedule with the pipelined executor's "
                             "overlap plan at C chunks per shard "
                             "(parallel/executor.py); the schedule report "
                             "grows overlapped model columns and "
                             "--verify-schedule proves the chunking "
                             "layout-only and audits the compiled program")
    parser.add_argument("--engine", default="auto",
                        choices=("auto", "xla", "pallas"),
                        help="compiled-circuit backend for the engine "
                             "columns of --schedule and (with 'pallas') "
                             "the epoch-executor verification of "
                             "--verify-schedule: the lowering is proven "
                             "IR-equivalent and the kernels probed in "
                             "interpret mode (default auto)")
    parser.add_argument("--devices", type=int, default=1,
                        help="mesh size for the deployment model (default 1)")
    parser.add_argument("--precision", type=int, default=1, choices=(1, 2),
                        help="1 = f32 SoA, 2 = f64 (default 1)")
    parser.add_argument("--chip", default="v5e", help="v5e | v5p (default v5e)")
    parser.add_argument("--no-hints", action="store_true",
                        help="suppress HINT-severity findings")
    parser.add_argument("--strict", action="store_true",
                        help="fail on WARNING as well as ERROR")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit ONE machine-readable JSON document "
                             "instead of text lines")
    args = parser.parse_args(argv)

    doc: dict = {"circuits": [], "schedule": [], "verify": [],
                 "serve_audit": [], "trace_report": [], "numeric_report": [],
                 "concurrency": None, "staticcheck": None,
                 "diagnostics": [], "summary": {}}

    def echo(line: str) -> None:
        if not args.as_json:
            print(line)

    diagnostics = []
    ran = False
    if args.self_lint:
        diagnostics += lint_package()
        ran = True
    if args.lint:
        diagnostics += lint_paths(args.lint)
        ran = True

    if args.fuzz_smoke or args.concurrency_paths:
        args.concurrency = True
    if args.concurrency:
        ran = True
        from .concurrency import audit_package, audit_paths
        if args.concurrency_paths:
            report, found = audit_paths(args.concurrency_paths)
        else:
            report, found = audit_package()
        echo(f"concurrency: {len(report['classes'])} lock-owning class(es) "
             f"over {report['files']} file(s), "
             f"{len(report['lock_graph']['edges'])} acquisition edge(s), "
             f"{len(report['lock_graph']['cycles'])} cycle(s), "
             f"{len(found)} finding(s)")
        report["fuzz"] = None
        if args.fuzz_smoke:
            from .diagnostics import AnalysisCode, diag
            from .schedfuzz import run_smoke
            fuzz = run_smoke(seeds=range(max(1, args.fuzz_seeds)))
            report["fuzz"] = fuzz
            found = found + [
                diag(AnalysisCode.SCHEDULE_FUZZ_FAILURE, Severity.ERROR,
                     detail=v)
                for v in fuzz["violations"]]
            for row in fuzz["scenarios"]:
                echo(f"fuzz {row['scenario']}[seed={row['seed']}]: "
                     f"{row['switches']} forced switch(es), "
                     f"{row['violations']} violation(s), "
                     f"{row['errors']} error(s)")
        doc["concurrency"] = report
        diagnostics += found

    if args.staticcheck_paths:
        args.staticcheck = True
    if args.staticcheck:
        ran = True
        from .staticcheck import (audit_package as _static_package,
                                  audit_paths as _static_paths,
                                  audit_served_classes)
        if args.staticcheck_paths:
            report, found = _static_paths(args.staticcheck_paths)
        else:
            report, found = _static_package()
        echo(f"staticcheck: {report['files']} file(s), "
             f"{report['findings']} finding(s), "
             f"{report['waived']} waived, "
             f"{len(report['hot_path_functions'])} hot-path function(s)")
        class_rows = None
        if not (args.staticcheck_paths or args.no_served_classes):
            class_rows, cfound = audit_served_classes()
            found = found + cfound
            for row in class_rows:
                echo(f"staticcheck class {row['label']}: "
                     f"{'lifted' if row['lifted'] else 'OPAQUE'} "
                     f"({row['engine']}), twin_shares_entry="
                     f"{row['twin_shares_entry']}, "
                     f"{row['trace_differences']} trace diff(s), f32 out "
                     f"{','.join(row['f32_output_dtypes'])}")
        doc["staticcheck"] = {"ast": report, "classes": class_rows}
        diagnostics += found

    circuits = []
    if args.qft is not None:
        from ..circuit import qft_circuit
        circuits.append((f"qft({args.qft})", qft_circuit(args.qft)))
    if args.random is not None:
        from ..circuit import random_circuit
        n, depth = args.random
        circuits.append((f"random({n},{depth})", random_circuit(n, depth)))
    for spec in args.circuit or ():
        circuits.append((spec, _load_circuit(spec)))

    if args.calibration:
        # load BEFORE any model runs: every schedule/engine/trace-report
        # decision below is then scored on the profile's fitted constants
        from ..obs import calibrate as _cal
        prof = _cal.activate(_cal.load_profile(args.calibration))
        echo(f"calibration: profile {prof.profile_id} loaded from "
             f"{args.calibration} (age {prof.age_s():.0f}s"
             + (", STALE" if prof.stale() else "") + ")")
    if args.calibrate:
        ran = True
        doc["calibration"] = _calibrate_report(args, circuits, echo)

    for label, circuit in circuits:
        ran = True
        found = analyze_circuit(circuit, num_devices=args.devices,
                                precision=args.precision,
                                chip=_chip(args.chip),
                                hints=not args.no_hints)
        found += check_abstract_eval(circuit, dtype=_dtype(args.precision))
        if args.schedule or args.verify_schedule or args.overlap_chunks:
            scheduled = circuit.schedule(args.devices, chip=_chip(args.chip),
                                         precision=args.precision,
                                         pipeline_chunks=args.overlap_chunks)
            report, extra = _schedule_report(label, circuit, args, scheduled,
                                             echo)
            doc["schedule"].append(report)
            found += extra
            if args.verify_schedule:
                report, extra = _verify_report(label, circuit, args,
                                               scheduled, echo)
                doc["verify"].append(report)
                found += extra
        if args.trace_report:
            report, extra = _trace_report_run(label, circuit, args, echo)
            doc["trace_report"].append(report)
            found += extra
        if args.numeric_report:
            report, extra = _numeric_report_run(label, circuit, args, echo)
            doc["numeric_report"].append(report)
            found += extra
        diagnostics += found
        doc["circuits"].append({"label": label, "ops": len(circuit.ops),
                                "findings": len(found)})
        echo(f"{label}: {len(circuit.ops)} ops, {len(found)} finding(s)")

    if args.serve_audit:
        ran = True
        from .serve_audit import (audit_grad_lift, audit_param_lift,
                                  default_workload)
        targets = ([(label, c) for label, c in circuits]
                   if circuits else default_workload())
        reports, found = audit_param_lift(
            targets, num_devices=args.devices,
            dtype=_dtype(args.precision))
        doc["serve_audit"] = reports
        diagnostics += found
        for r in reports:
            echo(f"{r['label']}: serve-audit " + json.dumps(r, default=float))
        if not circuits:
            # the gradient arm (quest_tpu/grad): runs on the default
            # gradient workload when no explicit circuits were given
            # (explicit --circuit factories are forward circuits)
            greports, gfound = audit_grad_lift()
            doc["serve_audit_grad"] = greports
            diagnostics += gfound
            for r in greports:
                echo(f"{r['label']}: serve-audit-grad "
                     + json.dumps(r, default=float))

    if args.trace_report:
        # the process-ledger summary, one section of the single document:
        # the obs-selftest CI gate reads drift counts from HERE (and
        # O_MODEL_DRIFT severities from "diagnostics") instead of grepping
        from .. import obs as _obs
        led = _obs.global_ledger()
        doc["ledger"] = {"records": led.as_dicts(),
                         "drift_total": led.snapshot()["drift_total"]}

    if args.numeric_report:
        # same one-document contract for the numeric ledger: the CI
        # numeric-selftest gate reads NaN/drift totals from HERE and
        # O_NUMERIC_* severities from "diagnostics"
        from ..obs import numerics as _num
        nled = _num.global_numeric_ledger()
        snap = nled.snapshot()
        doc["numeric_ledger"] = {"records": nled.as_dicts(),
                                 "probed_total": snap["probed_total"],
                                 "nan_total": snap["nan_total"],
                                 "drift_total": snap["drift_total"]}

    if not ran:
        parser.print_usage()
        return 2

    fail_at = Severity.WARNING if args.strict else Severity.ERROR
    if args.no_hints:
        # drop hints everywhere at once so the JSON document stays
        # internally consistent (diagnostics array == summary counts)
        diagnostics = [d for d in diagnostics
                       if d.severity != Severity.HINT]
    for d in diagnostics:
        doc["diagnostics"].append(
            {"code": d.code, "severity": d.severity.name,
             "location": d.location, "message": d.message})
        echo(d.format())
    n_err = sum(d.severity >= fail_at for d in diagnostics)
    doc["summary"] = {
        "diagnostics": len(diagnostics),
        "fail_at": fail_at.name,
        "failing": n_err,
        "counts": {s.name: sum(d.severity == s for d in diagnostics)
                   for s in Severity},
    }
    echo(f"{len(diagnostics)} diagnostic(s), {n_err} at/above "
         f"{fail_at.name.lower()}")
    if args.as_json:
        json.dump(doc, sys.stdout, indent=1, default=float)
        print()
    return 1 if n_err else 0


if __name__ == "__main__":
    sys.exit(main())
