"""``python -m quest_tpu.analysis`` — the static-analysis CLI.

Modes (combinable; exit status is 1 iff any ERROR-severity diagnostic):

- ``--self-lint``: purity-lint the installed quest_tpu tree (the CI gate).
- ``--lint PATH [PATH ...]``: purity-lint arbitrary files/trees.
- ``--qft N`` / ``--random N DEPTH``: analyze a generated benchmark circuit.
- ``--circuit module:attr``: import and analyze a user circuit — ``attr``
  may be a :class:`quest_tpu.Circuit` or a zero-argument factory.
- ``--schedule``: additionally run the comm-aware scheduler
  (parallel/scheduler.py) on each circuit and print the planner-predicted
  before/after comm report; a scheduled circuit the model rates as MORE
  communication is an ERROR (A_SCHEDULE_COMM_REGRESSION) — the CI smoke
  gate that scheduling savings stay nonnegative.

Circuit modes run the IR pass and the eager/compiled abstract-eval pass
against the deployment described by ``--devices/--precision/--chip``.
"""

from __future__ import annotations

import argparse
import importlib
import json
import sys

from .abstract_eval import check_abstract_eval
from .circuit_ir import analyze_circuit
from .diagnostics import Severity
from .purity import lint_package, lint_paths


def _load_circuit(spec: str):
    module_name, _, attr = spec.partition(":")
    if not attr:
        raise SystemExit(f"--circuit takes module:attr, got {spec!r}")
    obj = getattr(importlib.import_module(module_name), attr)
    return obj() if callable(obj) else obj


def _chip(name: str):
    from ..parallel import planner
    try:
        return {"v5e": planner.V5E, "v5p": planner.V5P}[name]
    except KeyError:
        raise SystemExit(f"unknown chip {name!r} (v5e | v5p)")


def _dtype(precision: int):
    import jax.numpy as jnp
    return jnp.float32 if precision == 1 else jnp.float64


def _schedule_report(label: str, circuit, args) -> list:
    """Run the comm-aware scheduler, print the planner-predicted savings as
    one JSON line, and return an ERROR diagnostic iff the scheduled circuit
    models as MORE communication than the input (the CI smoke contract)."""
    from ..parallel.scheduler import schedule_savings
    from .diagnostics import AnalysisCode, Severity, diag
    report = schedule_savings(circuit, args.devices, chip=_chip(args.chip),
                              precision=args.precision)
    print(f"{label}: schedule savings "
          + json.dumps(report, default=float))
    out = []
    if (report["comm_events_after"] > report["comm_events_before"]
            or report["comm_bytes_after"] > report["comm_bytes_before"]):
        out.append(diag(AnalysisCode.SCHEDULE_COMM_REGRESSION, Severity.ERROR,
                        detail=(f"{label}: events "
                                f"{report['comm_events_before']}->"
                                f"{report['comm_events_after']}, bytes "
                                f"{report['comm_bytes_before']}->"
                                f"{report['comm_bytes_after']}")))
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m quest_tpu.analysis",
        description="Static circuit analyzer + JAX-purity lint for quest_tpu.")
    parser.add_argument("--self-lint", action="store_true",
                        help="purity-lint the quest_tpu package tree")
    parser.add_argument("--lint", nargs="+", metavar="PATH",
                        help="purity-lint the given files/directories")
    parser.add_argument("--qft", type=int, metavar="N",
                        help="analyze an N-qubit QFT circuit")
    parser.add_argument("--random", nargs=2, type=int, metavar=("N", "DEPTH"),
                        help="analyze an N-qubit depth-DEPTH random circuit")
    parser.add_argument("--circuit", metavar="MODULE:ATTR",
                        help="import and analyze a Circuit (or factory)")
    parser.add_argument("--schedule", action="store_true",
                        help="run the comm-aware scheduler on each circuit "
                             "and report predicted comm savings")
    parser.add_argument("--devices", type=int, default=1,
                        help="mesh size for the deployment model (default 1)")
    parser.add_argument("--precision", type=int, default=1, choices=(1, 2),
                        help="1 = f32 SoA, 2 = f64 (default 1)")
    parser.add_argument("--chip", default="v5e", help="v5e | v5p (default v5e)")
    parser.add_argument("--no-hints", action="store_true",
                        help="suppress HINT-severity findings")
    parser.add_argument("--strict", action="store_true",
                        help="fail on WARNING as well as ERROR")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit diagnostics as JSON lines")
    args = parser.parse_args(argv)

    diagnostics = []
    ran = False
    if args.self_lint:
        diagnostics += lint_package()
        ran = True
    if args.lint:
        diagnostics += lint_paths(args.lint)
        ran = True

    circuits = []
    if args.qft is not None:
        from ..circuit import qft_circuit
        circuits.append((f"qft({args.qft})", qft_circuit(args.qft)))
    if args.random is not None:
        from ..circuit import random_circuit
        n, depth = args.random
        circuits.append((f"random({n},{depth})", random_circuit(n, depth)))
    if args.circuit:
        circuits.append((args.circuit, _load_circuit(args.circuit)))
    for label, circuit in circuits:
        ran = True
        found = analyze_circuit(circuit, num_devices=args.devices,
                                precision=args.precision,
                                chip=_chip(args.chip),
                                hints=not args.no_hints)
        found += check_abstract_eval(circuit, dtype=_dtype(args.precision))
        if args.schedule:
            found += _schedule_report(label, circuit, args)
        diagnostics += found
        print(f"{label}: {len(circuit.ops)} ops, "
              f"{len(found)} finding(s)")

    if not ran:
        parser.print_usage()
        return 2

    fail_at = Severity.WARNING if args.strict else Severity.ERROR
    for d in diagnostics:
        if args.no_hints and d.severity == Severity.HINT:
            continue
        if args.as_json:
            print(json.dumps({"code": d.code, "severity": d.severity.name,
                              "location": d.location, "message": d.message}))
        else:
            print(d.format())
    n_err = sum(d.severity >= fail_at for d in diagnostics)
    print(f"{len(diagnostics)} diagnostic(s), {n_err} at/above "
          f"{fail_at.name.lower()}")
    return 1 if n_err else 0


if __name__ == "__main__":
    sys.exit(main())
