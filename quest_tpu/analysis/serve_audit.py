"""Pass 6: machine-proving the serve cache's parameter lift.

The serve subsystem (quest_tpu/serve) compiles ONE ``(state, params)``
program per structural class and claims it computes what per-circuit
compilation would have — for EVERY angle assignment, including classes the
scheduler rewrote (serve/cache.py).  That claim is a compiler-shaped one,
so it gets the PR 3 treatment: validate the artifact, don't trust the
rewriter.

Per structural class this audit proves three things:

1. **Round-trip** — the class skeleton + the circuit's operand vector
   reconstruct a circuit (``serve.cache.circuit_from_params``) that
   :func:`analysis.equivalence.check_equivalence` PROVES equivalent to the
   request circuit.  For a mesh class the skeleton is the SCHEDULED op
   order with provenance-gathered operand slots, so this certifies the
   scheduler-composed cache entry end to end (reordering, bitperm fusion,
   placement relabeling, slot provenance) with the Pauli-tableau /
   phase-polynomial / dense-window domains — never a 2^n state.
2. **Lifted execution** — the class's compiled lifted program run on a
   probe state agrees with the eager per-circuit program.  Tolerance is a
   few f64 ulps, NOT zero: embedding payloads as constants lets XLA
   contract FMAs differently than the runtime-operand program (measured
   1-2 ulp on CPU; docs/SERVING.md "numerics"), which is a codegen
   identity, not a lift defect.
3. **Key stability** — an angle-perturbed twin of the circuit lands on the
   SAME cache entry (a structural-key instability would silently bring
   back one-compile-per-tenant).

Any violation is ``A_PARAM_LIFT_DIVERGENCE`` (ERROR).  Wired into
``python -m quest_tpu.analysis --serve-audit`` and the CI ``serve-selftest``
job; with no explicit circuits the serve selftest's workload classes are
audited (serve/selftest.py ``audit_circuits``).
"""

from __future__ import annotations

import numpy as np

from .diagnostics import AnalysisCode, Diagnostic, Severity, diag

__all__ = ["audit_param_lift", "default_workload"]

def _probe_eps(dtype) -> float:
    """FMA-contraction slack scaled to the PROBE dtype: a few ulps over a
    deep circuit — 1e-13 for f64, 1e-4 for f32 (one f32 ulp is ~1e-7, and
    accelerator codegen may legally differ per gate)."""
    import numpy as np
    return 1e-13 if np.dtype(dtype).itemsize >= 8 else 1e-4


def default_workload() -> list:
    """(label, circuit, perturbed-twin) per serve-selftest class."""
    from ..serve.selftest import audit_circuits
    return audit_circuits()


def _probe_state(num_qubits: int, dtype, seed: int = 0):
    import jax.numpy as jnp
    rng = np.random.default_rng(seed)
    v = rng.normal(size=(2, 1 << num_qubits))
    v /= np.sqrt((v ** 2).sum())
    return jnp.asarray(v, dtype)


def audit_param_lift(circuits, *, num_devices: int = 1, dtype=None,
                     label_prefix: str = "") -> tuple:
    """Audit each ``(label, circuit[, twin])`` entry's structural class.

    Returns ``(reports, diagnostics)``: one report dict per class and the
    ``A_PARAM_LIFT_DIVERGENCE`` findings (plus any pass-through equivalence
    diagnostics).  ``num_devices > 1`` audits the scheduler-composed cache
    path."""
    import jax.numpy as jnp

    from .. import circuit as _circ
    from ..serve.cache import CacheOptions, CompileCache, circuit_from_params
    from .equivalence import check_equivalence

    if dtype is None:
        dtype = jnp.float64
    options = (CacheOptions(num_devices=num_devices)
               if num_devices and num_devices > 1 else CacheOptions())
    cache = CompileCache()  # isolated: the audit must not warm serving caches
    reports: list[dict] = []
    out: list[Diagnostic] = []
    for item in circuits:
        label, circuit = item[0], item[1]
        twin = item[2] if len(item) > 2 else None
        label = f"{label_prefix}{label}"
        n = circuit.num_qubits
        ops = circuit.key()
        entry = cache.entry_for(ops, n, options)
        report = {"label": label, "num_qubits": n, "ops": len(ops),
                  "num_devices": num_devices,
                  "skeleton_ops": len(entry.skeleton or ()),
                  "lifted_params": entry.num_params}

        # 1. round-trip reconstruction, proven by the PR 3 validator
        recon = circuit_from_params(n, entry.skeleton, entry.offsets,
                                    _circ.param_vector(ops))
        eq = check_equivalence(circuit, recon)
        errors = [d for d in eq if d.severity >= Severity.ERROR]
        report["roundtrip_proven"] = not eq
        report["roundtrip_diagnostics"] = len(eq)
        if errors:
            out.append(diag(AnalysisCode.PARAM_LIFT_DIVERGENCE,
                            Severity.ERROR,
                            detail=(f"{label}: skeleton+params reconstruction "
                                    f"is NOT the request circuit "
                                    f"({errors[0].message})")))
        out.extend(eq)  # unverified-region warnings surface as themselves

        # 2. lifted program vs eager program on a probe state
        probe = _probe_state(n, dtype)
        lifted = np.asarray(cache.execute(ops, probe, num_qubits=n,
                                          options=options))
        eager = np.asarray(_circ._run_ops(probe, ops))
        worst = float(np.abs(lifted - eager).max())
        report["probe_max_abs_diff"] = worst
        if not np.isfinite(worst) or worst > _probe_eps(dtype):
            out.append(diag(AnalysisCode.PARAM_LIFT_DIVERGENCE,
                            Severity.ERROR,
                            detail=(f"{label}: lifted program diverges from "
                                    f"the eager path on a probe state "
                                    f"(max |diff| {worst:.3g})")))

        # 3. structural-key stability across an angle-perturbed twin
        if twin is not None:
            entry2 = cache.entry_for(twin.key(), twin.num_qubits, options)
            report["twin_shares_entry"] = entry2 is entry
            if entry2 is not entry:
                out.append(diag(AnalysisCode.PARAM_LIFT_DIVERGENCE,
                                Severity.ERROR,
                                detail=(f"{label}: an angle-perturbed twin "
                                        "missed the class's cache entry — "
                                        "the structural key is unstable")))
        reports.append(report)
    return reports, out
