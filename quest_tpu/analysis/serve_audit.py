"""Pass 6: machine-proving the serve cache's parameter lift.

The serve subsystem (quest_tpu/serve) compiles ONE ``(state, params)``
program per structural class and claims it computes what per-circuit
compilation would have — for EVERY angle assignment, including classes the
scheduler rewrote (serve/cache.py).  That claim is a compiler-shaped one,
so it gets the PR 3 treatment: validate the artifact, don't trust the
rewriter.

Per structural class this audit proves three things:

1. **Round-trip** — the class skeleton + the circuit's operand vector
   reconstruct a circuit (``serve.cache.circuit_from_params``) that
   :func:`analysis.equivalence.check_equivalence` PROVES equivalent to the
   request circuit.  For a mesh class the skeleton is the SCHEDULED op
   order with provenance-gathered operand slots, so this certifies the
   scheduler-composed cache entry end to end (reordering, bitperm fusion,
   placement relabeling, slot provenance) with the Pauli-tableau /
   phase-polynomial / dense-window domains — never a 2^n state.
2. **Lifted execution** — the class's compiled lifted program run on a
   probe state agrees with the eager per-circuit program.  Tolerance is a
   few f64 ulps, NOT zero: embedding payloads as constants lets XLA
   contract FMAs differently than the runtime-operand program (measured
   1-2 ulp on CPU; docs/SERVING.md "numerics"), which is a codegen
   identity, not a lift defect.
3. **Key stability** — an angle-perturbed twin of the circuit lands on the
   SAME cache entry (a structural-key instability would silently bring
   back one-compile-per-tenant).

Any violation is ``A_PARAM_LIFT_DIVERGENCE`` (ERROR).  Wired into
``python -m quest_tpu.analysis --serve-audit`` and the CI ``serve-selftest``
job; with no explicit circuits the serve selftest's workload classes are
audited (serve/selftest.py ``audit_circuits``).
"""

from __future__ import annotations

import numpy as np

from .diagnostics import AnalysisCode, Diagnostic, Severity, diag

__all__ = ["audit_param_lift", "audit_grad_lift", "default_workload",
           "default_grad_workload"]

def _probe_eps(dtype) -> float:
    """FMA-contraction slack scaled to the PROBE dtype: a few ulps over a
    deep circuit — 1e-13 for f64, 1e-4 for f32 (one f32 ulp is ~1e-7, and
    accelerator codegen may legally differ per gate)."""
    import numpy as np
    return 1e-13 if np.dtype(dtype).itemsize >= 8 else 1e-4


def default_workload() -> list:
    """(label, circuit, perturbed-twin) per serve-selftest class."""
    from ..serve.selftest import audit_circuits
    return audit_circuits()


def _probe_state(num_qubits: int, dtype, seed: int = 0):
    import jax.numpy as jnp
    rng = np.random.default_rng(seed)
    v = rng.normal(size=(2, 1 << num_qubits))
    v /= np.sqrt((v ** 2).sum())
    return jnp.asarray(v, dtype)


def audit_param_lift(circuits, *, num_devices: int = 1, dtype=None,
                     label_prefix: str = "") -> tuple:
    """Audit each ``(label, circuit[, twin])`` entry's structural class.

    Returns ``(reports, diagnostics)``: one report dict per class and the
    ``A_PARAM_LIFT_DIVERGENCE`` findings (plus any pass-through equivalence
    diagnostics).  ``num_devices > 1`` audits the scheduler-composed cache
    path."""
    import jax.numpy as jnp

    from .. import circuit as _circ
    from ..serve.cache import CacheOptions, CompileCache, circuit_from_params
    from .equivalence import check_equivalence

    if dtype is None:
        dtype = jnp.float64
    options = (CacheOptions(num_devices=num_devices)
               if num_devices and num_devices > 1 else CacheOptions())
    cache = CompileCache()  # isolated: the audit must not warm serving caches
    reports: list[dict] = []
    out: list[Diagnostic] = []
    for item in circuits:
        label, circuit = item[0], item[1]
        twin = item[2] if len(item) > 2 else None
        label = f"{label_prefix}{label}"
        n = circuit.num_qubits
        ops = circuit.key()
        entry = cache.entry_for(ops, n, options)
        report = {"label": label, "num_qubits": n, "ops": len(ops),
                  "num_devices": num_devices,
                  "skeleton_ops": len(entry.skeleton or ()),
                  "lifted_params": entry.num_params}

        # 1. round-trip reconstruction, proven by the PR 3 validator
        recon = circuit_from_params(n, entry.skeleton, entry.offsets,
                                    _circ.param_vector(ops))
        eq = check_equivalence(circuit, recon)
        errors = [d for d in eq if d.severity >= Severity.ERROR]
        report["roundtrip_proven"] = not eq
        report["roundtrip_diagnostics"] = len(eq)
        if errors:
            out.append(diag(AnalysisCode.PARAM_LIFT_DIVERGENCE,
                            Severity.ERROR,
                            detail=(f"{label}: skeleton+params reconstruction "
                                    f"is NOT the request circuit "
                                    f"({errors[0].message})")))
        out.extend(eq)  # unverified-region warnings surface as themselves

        # 2. lifted program vs eager program on a probe state
        probe = _probe_state(n, dtype)
        lifted = np.asarray(cache.execute(ops, probe, num_qubits=n,
                                          options=options))
        eager = np.asarray(_circ._run_ops(probe, ops))
        worst = float(np.abs(lifted - eager).max())
        report["probe_max_abs_diff"] = worst
        if not np.isfinite(worst) or worst > _probe_eps(dtype):
            out.append(diag(AnalysisCode.PARAM_LIFT_DIVERGENCE,
                            Severity.ERROR,
                            detail=(f"{label}: lifted program diverges from "
                                    f"the eager path on a probe state "
                                    f"(max |diff| {worst:.3g})")))

        # 3. structural-key stability across an angle-perturbed twin
        if twin is not None:
            entry2 = cache.entry_for(twin.key(), twin.num_qubits, options)
            report["twin_shares_entry"] = entry2 is entry
            if entry2 is not entry:
                out.append(diag(AnalysisCode.PARAM_LIFT_DIVERGENCE,
                                Severity.ERROR,
                                detail=(f"{label}: an angle-perturbed twin "
                                        "missed the class's cache entry — "
                                        "the structural key is unstable")))
        reports.append(report)
    return reports, out


def default_grad_workload() -> list:
    """(label, ParamCircuit factory, PauliHamil) per gradient-serving
    class — factories, so key-stability is probed across two INDEPENDENT
    builds of the same ansatz recipe (the multi-tenant reality: every
    tenant records its own circuit object)."""
    from ..models import (hardware_efficient_ansatz, maxcut_hamiltonian,
                          qaoa_maxcut_circuit, tfim_hamiltonian)
    edges = [(i, (i + 1) % 6) for i in range(6)]
    return [
        ("grad_hea6", lambda: hardware_efficient_ansatz(6, 2),
         tfim_hamiltonian(6)),
        ("grad_qaoa6", lambda: qaoa_maxcut_circuit(6, edges, 2),
         maxcut_hamiltonian(6, edges)),
    ]


def audit_grad_lift(workloads=None, *, seed: int = 0,
                    label_prefix: str = "") -> tuple:
    """Pass 6's gradient arm: prove the ADJOINT lift (quest_tpu/grad +
    serve/cache.py ``grad_entry_for``).  Per (ansatz, Hamiltonian) class:

    1. **Lifted vs eager** — the cache's compiled ``(state, params,
       coeffs)`` adjoint program agrees with the direct
       ``adjoint_gradient_fn`` closure (constants embedded) on random
       angles — few-ulp tolerance, the same FMA-contraction freedom as
       the forward lift.
    2. **Independent oracle** — energy AND gradient agree with
       ``jax.value_and_grad(expectation_fn(...))``, taped reverse-mode
       through an entirely different program.
    3. **Key stability** — a SECOND independent build of the ansatz
       recipe (new Circuit objects, same structure) plus an
       angle-perturbed request land on the same gradient cache entry.

    Any violation is ``A_PARAM_LIFT_DIVERGENCE`` (ERROR) — the audit a
    drifted lifted-adjoint reconstruction must fail."""
    import jax
    import jax.numpy as jnp

    from ..autodiff import adjoint_gradient_fn, expectation_fn
    from ..grad import adjoint as _gradadj
    from ..serve.cache import CompileCache

    if workloads is None:
        workloads = default_grad_workload()
    cache = CompileCache()  # isolated: the audit must not warm serving caches
    reports: list[dict] = []
    out: list[Diagnostic] = []
    rng = np.random.default_rng(seed)
    for label, factory, hamil in workloads:
        pc = factory() if callable(factory) else factory
        label = f"{label_prefix}{label}"
        n = pc.num_qubits
        masks = _gradadj.hamil_masks(hamil)
        entry = cache.grad_entry_for(tuple(pc.ops), n, pc.num_params, masks)
        st = jnp.zeros((2, 1 << n), jnp.float64).at[0, 0].set(1.0)
        cf = jnp.asarray(np.asarray(hamil.term_coeffs, np.float64))
        params = jnp.asarray(rng.uniform(-1.5, 1.5, pc.num_params))
        prog = cache.grad_single_program(entry, st)
        e_l, g_l = prog.call(st, params, cf)
        report = {"label": label, "num_qubits": n, "ops": len(pc.ops),
                  "num_params": pc.num_params, "hamil_terms": len(masks)}

        # 1. lifted program vs the direct (constant-embedded) adjoint
        e_d, g_d = adjoint_gradient_fn(pc, hamil)(params)
        worst = max(abs(float(e_l) - float(e_d)),
                    float(np.abs(np.asarray(g_l) - np.asarray(g_d)).max()))
        report["lifted_vs_eager_max_abs_diff"] = worst
        if not np.isfinite(worst) or worst > 1e-11:
            out.append(diag(AnalysisCode.PARAM_LIFT_DIVERGENCE,
                            Severity.ERROR,
                            detail=(f"{label}: lifted adjoint program "
                                    "diverges from the eager "
                                    f"adjoint_gradient_fn (max |diff| "
                                    f"{worst:.3g})")))

        # 2. independent taped-AD oracle
        e_o, g_o = jax.value_and_grad(expectation_fn(pc, hamil))(params)
        worst_o = max(abs(float(e_l) - float(e_o)),
                      float(np.abs(np.asarray(g_l) - np.asarray(g_o)).max()))
        report["vs_jax_grad_max_abs_diff"] = worst_o
        if not np.isfinite(worst_o) or worst_o > 1e-9:
            out.append(diag(AnalysisCode.PARAM_LIFT_DIVERGENCE,
                            Severity.ERROR,
                            detail=(f"{label}: served gradient diverges "
                                    "from jax.grad through the unlifted "
                                    f"program (max |diff| {worst_o:.3g})")))

        # 3. key stability across an independent build of the recipe
        if callable(factory):
            twin = factory()
            entry2 = cache.grad_entry_for(tuple(twin.ops), n,
                                          twin.num_params, masks)
            report["twin_shares_entry"] = entry2 is entry
            if entry2 is not entry:
                out.append(diag(AnalysisCode.PARAM_LIFT_DIVERGENCE,
                                Severity.ERROR,
                                detail=(f"{label}: an independent build of "
                                        "the ansatz recipe missed the "
                                        "gradient cache entry — the class "
                                        "key is unstable")))
        reports.append(report)
    return reports, out
