"""Pass 8: compile-economics static checker over the serving surface.

The product at fleet scale is the compile-cache hit rate (ROADMAP item 7):
ONE XLA program per structural class, payloads riding in the lifted
``param_vector`` operand.  The two costliest regressions in this repo's
history broke exactly that invariant and were found only by benchmarks —
overlap classes "cached but not lifted" recompiling per angle set, and
weak-type f64 promotion walking f32 programs into the XLA:TPU X64-rewriter
miscompile wall.  Both were statically detectable.  This pass (the ``S_*``
family) makes them machine-checked, the way pass 7 (concurrency.py) made
the lock discipline checkable.

**Layer 1 — AST audit** (:func:`audit_paths` / :func:`audit_package`, same
skeleton as concurrency.py), four rules:

- ``S_UNLIFTED_LITERAL`` — a continuous gate parameter (rotation angle,
  channel probability) written as a Python float literal at a builder
  call site (``c.ry(q, 0.37)``).  Through a LIFTED class the literal is
  harmless (it lands in the operand vector), but through an opaque class
  (overlap / pallas engines — ``CacheEntry.skeleton is None``) it becomes
  a compiled constant and every distinct value compiles its own program.
  Statically the engine is unknowable, so the rule demands data-bound
  parameters or a reasoned waiver: ``# unlifted-ok: <reason>``.
- ``S_RECOMPILE_HAZARD`` — jit boundaries keyed so routine inputs change
  the compile key: a ``jax.jit`` wrapper constructed AND invoked inside a
  function body (fresh cache per call; the AOT ``jax.jit(f).lower(...)``
  chain is exempt), or a float literal / unhashable literal passed to a
  declared static argument of a jit boundary defined in the same module
  (one program per knob value).  Waiver: ``# recompile-ok: <reason>``.
- ``S_HOST_SYNC_IN_HOT_PATH`` — ``.item()``, ``block_until_ready``,
  ``jax.device_get``, ``np.asarray``/``np.array`` in a function reachable
  (intra-module, ``self.``-call and bare-call edges) from a submission
  root: any method/function named in :data:`HOT_PATH_ROOTS` or annotated
  ``# hot-path``.  The submitter thread must never block on a device
  transfer — the worker thread owns device latency (serve/service.py's
  split).  Waiver: ``# host-sync-ok: <reason>``.
- ``S_X64_PROMOTION`` — inside a jit-decorated function, traced-parameter
  arithmetic mixed with a strong-typed ``np.*`` value (NumPy scalars and
  arrays promote f32 operands to f64 under x64; weak Python literals and
  ``np.pi``-style plain floats do not), or an explicit
  ``.astype(float64)`` on a traced parameter.  Waiver: ``# x64-ok:
  <reason>``.

Waiver reasons are REQUIRED, exactly like ``# lock-free:``: an annotation
with an empty reason does not waive.

**Layer 2 — traced-class audit** (:func:`audit_served_classes`): for every
structural class a serve workload registers, take its cache entry twice —
once for the request circuit, once for an operand-perturbed twin — and
trace the program the cache will actually run per request
(jaxpr_audit.trace_lifted_class / trace_embedded_ops).  The jaxprs are
diffed constant-by-constant (jaxpr_audit.diff_trace_constants): ANY
difference is a per-request recompile proven at trace time,
``S_CLASS_NOT_CLOSED`` — the lifted program's trace is payload-free by
construction and passes; an opaque class embeds payloads as constants and
fails.  A weak-type scan of the f32-state trace
(jaxpr_audit.scan_x64_promotion) pins ``S_X64_PROMOTION`` on the actual
program: an f32 request whose RESULT leaves the program as f64 has been
promoted before TPU lowering.

A refutation corpus (:data:`CORPUS`, :func:`corpus_report`) keeps the
checker honest: every rule must flag its seeded-bad snippet and stay
silent on the fixed twin (tests/test_staticcheck.py and the CI lint job
both assert it).  CLI: ``python -m quest_tpu.analysis --staticcheck``.
"""

from __future__ import annotations

import ast
import os
import re

from .diagnostics import AnalysisCode, Diagnostic, Severity, diag

__all__ = ["audit_paths", "audit_package", "audit_source",
           "audit_served_classes", "corpus_report", "CORPUS",
           "HOT_PATH_ROOTS"]

#: function/method names that anchor the submission-side hot path; the
#: reachability scan also roots at any def annotated ``# hot-path``
HOT_PATH_ROOTS = frozenset((
    "submit", "submit_gradient", "submit_batch", "route", "dispatch",
))

#: builder methods taking continuous parameters, mapped to the positions
#: (0-based in the call's positional args) and keyword names that carry
#: them — the operands the param_vector lift exists for (circuit.py)
_CONTINUOUS_ARGS = {
    "phase_shift": ((1,), ("angle",)),
    "rx": ((1,), ("angle",)),
    "ry": ((1,), ("angle",)),
    "rz": ((1,), ("angle",)),
    "multi_rotate_z": ((1,), ("angle",)),
    "multi_rotate_pauli": ((2,), ("angle",)),
    "compact_unitary": ((1, 2), ("alpha", "beta")),
    "dephase": ((1,), ("prob",)),
    "two_qubit_dephase": ((2,), ("prob",)),
    "depolarise": ((1,), ("prob",)),
    "damp": ((1,), ("prob",)),
    "mix_pauli": ((1, 2, 3), ("prob_x", "prob_y", "prob_z")),
}

_UNLIFTED_RE = re.compile(r"#\s*unlifted-ok:\s*(.*?)\s*$")
_RECOMPILE_RE = re.compile(r"#\s*recompile-ok:\s*(.*?)\s*$")
_HOSTSYNC_RE = re.compile(r"#\s*host-sync-ok:\s*(.*?)\s*$")
_X64_RE = re.compile(r"#\s*x64-ok:\s*(.*?)\s*$")
_HOTPATH_RE = re.compile(r"#\s*hot-path\b")

_WAIVERS = {
    AnalysisCode.UNLIFTED_LITERAL: _UNLIFTED_RE,
    AnalysisCode.RECOMPILE_HAZARD: _RECOMPILE_RE,
    AnalysisCode.HOST_SYNC_IN_HOT_PATH: _HOSTSYNC_RE,
    AnalysisCode.X64_PROMOTION: _X64_RE,
}

#: jit entry points (dotted call names)
_JIT_NAMES = frozenset(("jax.jit", "jit"))
_PARTIAL_NAMES = frozenset(("partial", "functools.partial"))

#: host-synchronising dotted calls (`.item()` is matched structurally)
_SYNC_DOTTED = frozenset((
    "jax.block_until_ready", "jax.device_get",
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
))

#: ``np.*`` attributes that are plain Python floats (weak-typed — they do
#: NOT promote f32 arithmetic) and so are exempt from the x64 rule
_NP_WEAK_CONSTS = frozenset((
    "np.pi", "np.e", "np.inf", "np.nan", "np.euler_gamma",
    "numpy.pi", "numpy.e", "numpy.inf", "numpy.nan", "numpy.euler_gamma",
))


def _dotted(node: ast.AST) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


class _Annotations:
    """Per-file comment annotations by line number (concurrency.py's
    convention: the statement's first line or the directly preceding
    pure-comment line)."""

    def __init__(self, source: str):
        self.lines = source.splitlines()

    def _line(self, lineno: int | None) -> str:
        if lineno is None or not 1 <= lineno <= len(self.lines):
            return ""
        return self.lines[lineno - 1]

    def _match(self, pattern: re.Pattern, lineno: int | None):
        m = pattern.search(self._line(lineno))
        if m is None and lineno is not None:
            prev = self._line(lineno - 1).strip()
            if prev.startswith("#"):
                m = pattern.search(prev)
        return m

    def waiver(self, code: str, lineno: int | None) -> str | None:
        """The reason string of the code's waiver comment ('' when present
        but unreasoned — which does NOT waive), None when absent."""
        m = self._match(_WAIVERS[code], lineno)
        return m.group(1) if m else None

    def hot_path(self, lineno: int | None) -> bool:
        return self._match(_HOTPATH_RE, lineno) is not None


def _literal_only(node: ast.AST) -> bool:
    """True for an expression built ONLY from numeric literals (unary sign
    and arithmetic allowed) — no Names, no Calls, so provably not bound
    from data."""
    if isinstance(node, ast.Constant):
        return (isinstance(node.value, (int, float))
                and not isinstance(node.value, bool))
    if isinstance(node, ast.UnaryOp) and isinstance(node.op,
                                                    (ast.UAdd, ast.USub)):
        return _literal_only(node.operand)
    if isinstance(node, ast.BinOp):
        return _literal_only(node.left) and _literal_only(node.right)
    return False


def _has_float(node: ast.AST) -> bool:
    return any(isinstance(n, ast.Constant) and isinstance(n.value, float)
               for n in ast.walk(node))


def _mentions(node: ast.AST, names) -> str | None:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in names:
            return n.id
    return None


def _np_strong(node: ast.AST) -> str | None:
    """Dotted name of a strong-typed ``np.*`` value inside ``node`` (a call
    like np.float64/np.sqrt or an array attribute), None if the expression
    only touches weak float constants like np.pi."""
    for n in ast.walk(node):
        d = _dotted(n.func) if isinstance(n, ast.Call) else (
            _dotted(n) if isinstance(n, ast.Attribute) else "")
        if (d.startswith(("np.", "numpy."))
                and d not in _NP_WEAK_CONSTS):
            return d
    return None


def _is_jit_call(call: ast.Call) -> bool:
    """True for ``jax.jit(...)`` and ``partial(jax.jit, ...)``."""
    d = _dotted(call.func)
    if d in _JIT_NAMES:
        return True
    return (d in _PARTIAL_NAMES and call.args
            and _dotted(call.args[0]) in _JIT_NAMES)


def _static_names(call: ast.Call, argnames: list) -> set:
    """Static parameter names declared by a jit(...) / partial(jax.jit,...)
    call, resolved against the wrapped function's argument names."""
    statics: set = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            statics |= {e.value for e in elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)}
        elif kw.arg == "static_argnums":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if (isinstance(e, ast.Constant) and isinstance(e.value, int)
                        and 0 <= e.value < len(argnames)):
                    statics.add(argnames[e.value])
    return statics


class _JitBoundary:
    def __init__(self, argnames, statics, node):
        self.argnames = argnames        # positional parameter names, in order
        self.statics = statics          # subset declared static
        self.node = node                # the FunctionDef (x64 rule scope)


def _collect_jit_boundaries(tree: ast.Module) -> dict:
    """name -> _JitBoundary for jit-wrapped functions defined in this
    module: decorator form (@jax.jit / @partial(jax.jit, ...)) and
    assignment form (g = jax.jit(f, ...))."""
    defs = {n.name: n for n in tree.body if isinstance(n, ast.FunctionDef)}
    out: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                statics: set | None = None
                if isinstance(dec, ast.Call) and _is_jit_call(dec):
                    statics = _static_names(
                        dec, [a.arg for a in node.args.args])
                elif _dotted(dec) in _JIT_NAMES:
                    statics = set()
                if statics is not None:
                    out[node.name] = _JitBoundary(
                        [a.arg for a in node.args.args], statics, node)
        elif (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and _is_jit_call(node.value)):
            call = node.value
            wrapped = None
            if _dotted(call.func) in _JIT_NAMES and call.args:
                wrapped = call.args[0]
            elif _dotted(call.func) in _PARTIAL_NAMES and len(call.args) > 1:
                wrapped = call.args[1]
            if isinstance(wrapped, ast.Name) and wrapped.id in defs:
                fn = defs[wrapped.id]
                argnames = [a.arg for a in fn.args.args]
                out[node.targets[0].id] = _JitBoundary(
                    argnames, _static_names(call, argnames), fn)
    return out


class _FileAudit:
    """One module's Layer-1 findings."""

    def __init__(self, filename: str, source: str):
        self.filename = filename
        self.ann = _Annotations(source)
        self.tree = ast.parse(source, filename=filename)
        self.diagnostics: list[Diagnostic] = []
        self.waived = 0
        self.hot_path: list[str] = []

    def _emit(self, code: str, lineno: int, detail: str) -> None:
        reason = self.ann.waiver(code, lineno)
        if reason:
            self.waived += 1
            return
        if reason == "":
            detail += " (waiver present but UNREASONED — refused)"
        self.diagnostics.append(diag(code, Severity.ERROR,
                                     file=self.filename, line=lineno,
                                     detail=detail))

    # -- rule 1: literal continuous gate parameters -----------------------
    def check_unlifted_literals(self) -> None:
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _CONTINUOUS_ARGS):
                continue
            positions, kwnames = _CONTINUOUS_ARGS[node.func.attr]
            candidates = [node.args[i] for i in positions
                          if i < len(node.args)]
            candidates += [kw.value for kw in node.keywords
                           if kw.arg in kwnames]
            for arg in candidates:
                if _literal_only(arg) and _has_float(arg):
                    self._emit(
                        AnalysisCode.UNLIFTED_LITERAL, node.lineno,
                        f"literal {ast.unparse(arg)} passed to "
                        f".{node.func.attr}() — bind from data so the "
                        f"param_vector lift can carry it")

    # -- rule 2: recompile-keyed jit boundaries ---------------------------
    def check_recompile_hazards(self) -> None:
        boundaries = _collect_jit_boundaries(self.tree)
        # (a) jit wrapper constructed and invoked inside a function body
        for fn in ast.walk(self.tree):
            if not isinstance(fn, ast.FunctionDef):
                continue
            for node in ast.walk(fn):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Call)
                        and _is_jit_call(node.func)):
                    self._emit(
                        AnalysisCode.RECOMPILE_HAZARD, node.lineno,
                        "jax.jit wrapper constructed AND invoked per call "
                        "— a fresh compile cache every invocation; hoist "
                        "the wrapper to module/attribute scope")
        # (b) float / unhashable literal fed to a declared static argument
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in boundaries):
                continue
            b = boundaries[node.func.id]
            bound: list[tuple] = []
            for i, arg in enumerate(node.args):
                if i < len(b.argnames) and b.argnames[i] in b.statics:
                    bound.append((b.argnames[i], arg))
            bound += [(kw.arg, kw.value) for kw in node.keywords
                      if kw.arg in b.statics]
            for pname, arg in bound:
                if _literal_only(arg) and _has_float(arg):
                    self._emit(
                        AnalysisCode.RECOMPILE_HAZARD, node.lineno,
                        f"float literal {ast.unparse(arg)} passed to "
                        f"STATIC argument '{pname}' of {node.func.id}() — "
                        "one compiled program per value of a continuous "
                        "knob; make it an operand")
                elif isinstance(arg, (ast.List, ast.Dict, ast.Set)):
                    self._emit(
                        AnalysisCode.RECOMPILE_HAZARD, node.lineno,
                        f"unhashable literal passed to STATIC argument "
                        f"'{pname}' of {node.func.id}() — the jit cache "
                        "key cannot hash it")

    # -- rule 3: host syncs reachable from submission roots ---------------
    def check_host_syncs(self) -> None:
        # function table: (class name or "", def name) -> FunctionDef
        table: dict = {}
        for node in self.tree.body:
            if isinstance(node, ast.FunctionDef):
                table[("", node.name)] = node
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, ast.FunctionDef):
                        table[(node.name, sub.name)] = sub
        roots = [key for key, fn in table.items()
                 if key[1] in HOT_PATH_ROOTS or self.ann.hot_path(fn.lineno)]
        # BFS over intra-module call edges, remembering the root
        reach: dict = {key: key[1] for key in roots}
        frontier = list(roots)
        while frontier:
            cls, name = frontier.pop()
            for node in ast.walk(table[(cls, name)]):
                if not isinstance(node, ast.Call):
                    continue
                callee = None
                if (isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"
                        and (cls, node.func.attr) in table):
                    callee = (cls, node.func.attr)
                elif (isinstance(node.func, ast.Name)
                        and ("", node.func.id) in table):
                    callee = ("", node.func.id)
                if callee is not None and callee not in reach:
                    reach[callee] = reach[(cls, name)]
                    frontier.append(callee)
        for (cls, name), root in sorted(reach.items()):
            self.hot_path.append(
                f"{cls + '.' if cls else ''}{name} (via {root})")
            for node in ast.walk(table[(cls, name)]):
                if not isinstance(node, ast.Call):
                    continue
                sync = None
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item" and not node.args):
                    sync = ".item()"
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "block_until_ready"):
                    sync = "block_until_ready"
                elif _dotted(node.func) in _SYNC_DOTTED:
                    sync = _dotted(node.func)
                if sync:
                    self._emit(
                        AnalysisCode.HOST_SYNC_IN_HOT_PATH, node.lineno,
                        f"{sync} in {cls + '.' if cls else ''}{name}, "
                        f"reachable from hot-path root '{root}' — the "
                        "submitter thread must not wait on a device value")

    # -- rule 4: f64-forcing flows inside traced functions ----------------
    def check_x64_promotion(self) -> None:
        for b in _collect_jit_boundaries(self.tree).values():
            traced = {a for a in b.argnames if a not in b.statics
                      and a != "self"}
            for node in ast.walk(b.node):
                if isinstance(node, ast.BinOp):
                    for side, other in ((node.left, node.right),
                                        (node.right, node.left)):
                        strong = _np_strong(other)
                        if strong and _mentions(side, traced):
                            self._emit(
                                AnalysisCode.X64_PROMOTION, node.lineno,
                                f"traced value mixed with strong-typed "
                                f"{strong} — under x64 this promotes f32 "
                                "programs to f64; use a weak Python "
                                "scalar or a jnp cast tied to the state "
                                "dtype")
                            break
                elif (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "astype" and node.args):
                    tgt = node.args[0]
                    named = (_dotted(tgt).endswith("float64")
                             or (isinstance(tgt, ast.Constant)
                                 and tgt.value == "float64"))
                    if named and _mentions(node.func.value, traced):
                        self._emit(
                            AnalysisCode.X64_PROMOTION, node.lineno,
                            ".astype(float64) on a traced parameter — "
                            "explicit promotion before TPU lowering")

    def run(self) -> None:
        self.check_unlifted_literals()
        self.check_recompile_hazards()
        self.check_host_syncs()
        self.check_x64_promotion()


def _audit_sources(sources: list[tuple]) -> tuple[dict, list[Diagnostic]]:
    """Audit ``[(filename, source), ...]``.  Returns (report, diagnostics)."""
    diagnostics: list[Diagnostic] = []
    waived = 0
    hot_path: list[str] = []
    by_code: dict = {}
    for filename, source in sources:
        audit = _FileAudit(filename, source)
        audit.run()
        diagnostics += audit.diagnostics
        waived += audit.waived
        hot_path += [f"{filename}: {h}" for h in audit.hot_path]
        for d in audit.diagnostics:
            by_code[d.code] = by_code.get(d.code, 0) + 1
    report = {
        "files": len(sources),
        "findings": len(diagnostics),
        "waived": waived,
        "by_code": dict(sorted(by_code.items())),
        "hot_path_functions": hot_path,
    }
    return report, diagnostics


def audit_source(source: str, filename: str = "<string>") -> list[Diagnostic]:
    """Audit one module's source text (the refutation-corpus entry point)."""
    _report, diagnostics = _audit_sources([(filename, source)])
    return diagnostics


def audit_paths(paths) -> tuple[dict, list[Diagnostic]]:
    """Audit ``.py`` files / directory trees."""
    files: list[str] = []
    for path in paths:
        if os.path.isdir(path):
            for root, _dirs, names in os.walk(path):
                files.extend(os.path.join(root, f) for f in sorted(names)
                             if f.endswith(".py"))
        else:
            files.append(path)
    sources = []
    for f in files:
        with open(f, "r", encoding="utf-8") as fh:
            sources.append((f, fh.read()))
    return _audit_sources(sources)


def audit_package() -> tuple[dict, list[Diagnostic]]:
    """Audit the whole installed quest_tpu tree plus the repo's examples/
    directory (the ``--staticcheck`` CLI target and the CI gate)."""
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = [pkg_root]
    examples = os.path.join(os.path.dirname(pkg_root), "examples")
    if os.path.isdir(examples):
        paths.append(examples)
    return audit_paths(paths)


# ---------------------------------------------------------------------------
# Layer 2: the traced-served-class audit (jaxpr diff + weak-type scan)
# ---------------------------------------------------------------------------

def audit_served_classes(workloads=None, *, options=None, dtype=None,
                         label_prefix: str = "") -> tuple:
    """Prove each served structural class is CLOSED over its parameters.

    Per ``(label, circuit[, perturbed-twin])`` workload entry: take the
    class's cache entry for the request AND for an operand-perturbed twin
    (built from the structural skeleton when no twin is supplied), trace
    the per-request program the cache will actually run
    (``(state, params)`` for a lifted entry; payload-embedding state-only
    program for an opaque one), and diff the two traces constant by
    constant.  Any difference — or a twin landing on a different cache
    entry — is ``S_CLASS_NOT_CLOSED`` (ERROR): a per-request recompile
    proven without compiling anything.  The f32-state trace is also
    weak-type-scanned; a program whose RESULT dtype is promoted past f32
    is ``S_X64_PROMOTION`` (ERROR) pinned on the actual served program.

    Returns ``(reports, diagnostics)``."""
    import jax.numpy as jnp

    from .. import circuit as _circ
    from ..serve.cache import CacheOptions, CompileCache, circuit_from_params
    from .jaxpr_audit import (diff_trace_constants, scan_x64_promotion,
                              trace_embedded_ops, trace_lifted_class)

    if workloads is None:
        from .serve_audit import default_workload
        workloads = default_workload()
    if options is None:
        options = CacheOptions()
    if dtype is None:
        dtype = jnp.float64
    cache = CompileCache()  # isolated: the audit must not warm serving caches
    reports: list[dict] = []
    out: list[Diagnostic] = []
    for item in workloads:
        label, circuit = item[0], item[1]
        twin = item[2] if len(item) > 2 else None
        label = f"{label_prefix}{label}"
        n = circuit.num_qubits
        ops = circuit.key()
        entry = cache.entry_for(ops, n, options)
        lifted = entry.skeleton is not None
        report = {"label": label, "num_qubits": n, "ops": len(ops),
                  "engine": options.engine, "overlap": bool(options.overlap),
                  "lifted": lifted}

        # operand-perturbed twin ops (an independent request of the class)
        if twin is not None:
            twin_ops = twin.key()
        else:
            skeleton = tuple(_circ.structural_op(op) for op in ops)
            offsets, total = [], 0
            for op in ops:
                offsets.append(total)
                total += _circ.op_param_count(op)
            if total:
                pvec = _circ.param_vector(ops)
                twin_ops = circuit_from_params(
                    n, skeleton, tuple(offsets), pvec + 0.25).key()
            else:
                twin_ops = ops  # parameter-free class: nothing to perturb
        entry2 = cache.entry_for(twin_ops, n, options)
        report["twin_shares_entry"] = entry2 is entry
        if entry2 is not entry:
            out.append(diag(
                AnalysisCode.CLASS_NOT_CLOSED, Severity.ERROR,
                detail=(f"{label}: an operand-perturbed twin missed the "
                        "class's cache entry — the structural key is "
                        "unstable, one entry per tenant")))

        # trace the per-request program for both requests and diff
        if lifted:
            j1 = trace_lifted_class(n, entry.skeleton, entry.offsets,
                                    entry.num_params, dtype=dtype)
            j2 = trace_lifted_class(n, entry2.skeleton, entry2.offsets,
                                    entry2.num_params, dtype=dtype)
        else:
            j1 = trace_embedded_ops(n, ops, dtype=dtype)
            j2 = trace_embedded_ops(n, twin_ops, dtype=dtype)
        diffs = diff_trace_constants(j1, j2)
        report["trace_differences"] = len(diffs)
        if diffs:
            out.append(diag(
                AnalysisCode.CLASS_NOT_CLOSED, Severity.ERROR,
                detail=(f"{label}: re-tracing with a perturbed operand "
                        f"vector changed the program ({diffs[0]}"
                        + (f"; {len(diffs)} differences in all"
                           if len(diffs) > 1 else "")
                        + ") — every request with new angles recompiles")))

        # weak-type scan of the f32 request's trace
        if lifted:
            jf = trace_lifted_class(n, entry.skeleton, entry.offsets,
                                    entry.num_params, dtype=jnp.float32)
        else:
            jf = trace_embedded_ops(n, ops, dtype=jnp.float32)
        events, out_dtypes = scan_x64_promotion(jf, expect=jnp.float32)
        report["f32_promotion_eqns"] = len(events)
        report["f32_output_dtypes"] = sorted({str(d) for d in out_dtypes})
        promoted = [d for d in out_dtypes if str(d) == "float64"]
        if promoted:
            out.append(diag(
                AnalysisCode.X64_PROMOTION, Severity.ERROR,
                detail=(f"{label}: an f32 request's program RETURNS "
                        "float64 — the class was promoted before TPU "
                        f"lowering ({len(events)} promoting equation(s))")))
        reports.append(report)
    return reports, out


# ---------------------------------------------------------------------------
# the refutation corpus: every rule must flag its seeded bug and pass the
# fixed twin (tests/test_staticcheck.py + the CI lint job)
# ---------------------------------------------------------------------------

CORPUS = (
    {
        "name": "literal_angle",
        "code": AnalysisCode.UNLIFTED_LITERAL,
        "bad": (
            "def build_probe(num_qubits):\n"
            "    from quest_tpu import Circuit\n"
            "    c = Circuit(num_qubits)\n"
            "    for q in range(num_qubits):\n"
            "        c.ry(q, 0.37)\n"
            "    return c\n"
        ),
        "good": (
            "def build_probe(num_qubits, angles):\n"
            "    from quest_tpu import Circuit\n"
            "    c = Circuit(num_qubits)\n"
            "    for q in range(num_qubits):\n"
            "        c.ry(q, angles[q])\n"
            "    return c\n"
        ),
    },
    {
        "name": "per_call_jit",
        "code": AnalysisCode.RECOMPILE_HAZARD,
        "bad": (
            "import jax\n"
            "\n"
            "def run_once(state):\n"
            "    return jax.jit(lambda s: s * 2.0)(state)\n"
        ),
        "good": (
            "import jax\n"
            "\n"
            "_step = jax.jit(lambda s: s * 2.0)\n"
            "\n"
            "def run_once(state):\n"
            "    return _step(state)\n"
        ),
    },
    {
        "name": "float_static_arg",
        "code": AnalysisCode.RECOMPILE_HAZARD,
        "bad": (
            "import jax\n"
            "from functools import partial\n"
            "\n"
            "@partial(jax.jit, static_argnames=('angle',))\n"
            "def rotate(state, angle):\n"
            "    return state * angle\n"
            "\n"
            "def serve_request(state):\n"
            "    return rotate(state, 0.37)\n"
        ),
        "good": (
            "import jax\n"
            "\n"
            "@jax.jit\n"
            "def rotate(state, angle):\n"
            "    return state * angle\n"
            "\n"
            "def serve_request(state):\n"
            "    return rotate(state, 0.37)\n"
        ),
    },
    {
        "name": "submit_host_sync",
        "code": AnalysisCode.HOST_SYNC_IN_HOT_PATH,
        "bad": (
            "import numpy as np\n"
            "\n"
            "class Service:\n"
            "    def submit(self, state):\n"
            "        return self._enqueue(state)\n"
            "\n"
            "    def _enqueue(self, state):\n"
            "        host = np.asarray(state)\n"
            "        self._queue.append(host)\n"
        ),
        "good": (
            "import numpy as np\n"
            "\n"
            "class Service:\n"
            "    def submit(self, state):\n"
            "        self._queue.append(state)\n"
            "\n"
            "    def _drain(self, state):\n"
            "        host = np.asarray(state)\n"
            "        return host\n"
        ),
    },
    {
        "name": "np_scalar_in_trace",
        "code": AnalysisCode.X64_PROMOTION,
        "bad": (
            "import jax\n"
            "import numpy as np\n"
            "\n"
            "@jax.jit\n"
            "def scale(state):\n"
            "    return state * np.float64(2.0)\n"
        ),
        "good": (
            "import jax\n"
            "\n"
            "@jax.jit\n"
            "def scale(state):\n"
            "    return state * 2.0\n"
        ),
    },
)


def corpus_report() -> tuple[list, list[Diagnostic]]:
    """Run every corpus pair through the auditor.  Returns (rows,
    diagnostics): a row per entry and an ERROR diagnostic for every
    mutation the checker failed to flag (or fixed twin it wrongly
    flagged) — the checker refuting itself."""
    rows: list[dict] = []
    out: list[Diagnostic] = []
    for entry in CORPUS:
        bad = audit_source(entry["bad"], f"<corpus:{entry['name']}:bad>")
        good = audit_source(entry["good"], f"<corpus:{entry['name']}:good>")
        hit = any(d.code == entry["code"] for d in bad)
        clean = not good
        rows.append({"name": entry["name"], "code": entry["code"],
                     "bad_flagged": hit, "good_clean": clean})
        if not hit:
            out.append(diag(entry["code"], Severity.ERROR,
                            detail=(f"corpus '{entry['name']}': the seeded "
                                    "bug was NOT flagged — the checker "
                                    "lost this rule")))
        if not clean:
            out.append(diag(good[0].code, Severity.ERROR,
                            detail=(f"corpus '{entry['name']}': the FIXED "
                                    "twin was flagged — false positive "
                                    f"({good[0].message})")))
    return rows, out
