"""Pass 1: whole-circuit IR analysis, before any tracing or device work.

The reference checks every input at call time (QuEST_validation.c); the
circuit layer deliberately skips those checks while *recording* (builder
methods are hot paths), deferring them to trace time where they surface as
deep XLA shape errors.  This pass walks the recorded ``GateOp`` list on the
host and reports everything the validation layer *would* have raised —
with the same ``E_*`` codes — plus projections no runtime check can make:
memory footprint against the target mesh (parallel/planner.py's model),
plane-storage compatibility, and optimization hints.
"""

from __future__ import annotations

import numpy as np

from .. import qureg as _qureg
from ..parallel import planner as _planner
from ..precision import real_eps
from ..validation import ErrorCode, _is_unitary
from .diagnostics import AnalysisCode, Diagnostic, Severity, diag

# kinds whose payload is a dense unitary / unit-modulus diagonal
_DENSE_KINDS = ("matrix",)
_DIAG_KINDS = ("diagonal",)
_KNOWN_KINDS = ("matrix", "diagonal", "x", "y", "y*", "swap", "mrz",
                "bitperm")


def _op_matrix(op) -> np.ndarray | None:
    """Complex payload of a dense op (None for payload-free kinds)."""
    if op.kind not in _DENSE_KINDS or op.matrix is None:
        return None
    p = op.payload()
    return p[0] + 1j * p[1]


def _op_diagonal(op) -> np.ndarray | None:
    if op.kind not in _DIAG_KINDS or op.matrix is None:
        return None
    p = op.payload()
    return p[0] + 1j * p[1]


def _check_wires(i: int, op, n: int, out: list) -> None:
    targets = [int(t) for t in op.targets]
    controls = [int(c) for c in op.controls]
    for t in targets:
        if not 0 <= t < n:
            out.append(diag(ErrorCode.INVALID_TARGET_QUBIT, Severity.ERROR,
                            op_index=i, detail=f"target {t} of {n} qubits"))
    for c in controls:
        if not 0 <= c < n:
            out.append(diag(ErrorCode.INVALID_CONTROL_QUBIT, Severity.ERROR,
                            op_index=i, detail=f"control {c} of {n} qubits"))
    if len(set(targets)) != len(targets):
        out.append(diag(ErrorCode.TARGETS_NOT_UNIQUE, Severity.ERROR,
                        op_index=i, detail=f"targets {tuple(targets)}"))
    if len(set(controls)) != len(controls):
        out.append(diag(ErrorCode.CONTROLS_NOT_UNIQUE, Severity.ERROR,
                        op_index=i, detail=f"controls {tuple(controls)}"))
    if set(targets) & set(controls):
        out.append(diag(ErrorCode.CONTROL_TARGET_COLLISION, Severity.ERROR,
                        op_index=i,
                        detail=f"shared wires {tuple(set(targets) & set(controls))}"))
    if op.control_states:
        if len(op.control_states) != len(controls):
            out.append(diag(ErrorCode.MISMATCHING_NUM_CONTROL_STATES,
                            Severity.ERROR, op_index=i))
        for b in op.control_states:
            if int(b) not in (0, 1):
                out.append(diag(ErrorCode.INVALID_CONTROLS_BIT_STATE,
                                Severity.ERROR, op_index=i,
                                detail=f"state {b}"))


def _check_channel_payload(i: int, op, eps: float, out: list) -> None:
    """A density channel slot (DensityCircuit.channel_slots): the payload
    is a SUPEROPERATOR on the doubled (q, q+n) wires — deliberately
    non-unitary — so the validity condition is trace preservation
    (the same invariant serve admission enforces, E_INVALID_KRAUS_OPS),
    not unitarity."""
    from ..ops.decoherence import superop_trace_preserving
    if op.kind not in ("matrix", "diagonal") or op.matrix is None:
        out.append(diag(ErrorCode.INVALID_KRAUS_OPS, Severity.ERROR,
                        op_index=i,
                        detail=f"channel slot holds a '{op.kind}' op"))
        return
    k = len(op.targets) // 2
    payload = op.payload()
    if op.kind == "diagonal":
        if payload.shape != (2, 1 << len(op.targets)):
            out.append(diag(ErrorCode.INVALID_UNITARY_SIZE, Severity.ERROR,
                            op_index=i, detail=f"shape {payload.shape}"))
            return
        payload = np.stack([np.diag(payload[0]), np.diag(payload[1])])
    dim = 1 << len(op.targets)
    if payload.shape != (2, dim, dim):
        out.append(diag(ErrorCode.INVALID_UNITARY_SIZE, Severity.ERROR,
                        op_index=i, detail=f"shape {payload.shape}"))
        return
    if not superop_trace_preserving(payload, k, 10 * eps):
        out.append(diag(ErrorCode.INVALID_KRAUS_OPS, Severity.ERROR,
                        op_index=i,
                        detail="channel superoperator does not preserve "
                               "Tr(rho)"))


def _check_payload(i: int, op, eps: float, out: list) -> None:
    if op.kind == "bitperm":
        # payload is the destination-wire list of a qubit permutation
        # (parallel/scheduler.py), not a matrix: the only validity condition
        # is that it permutes exactly the target wires
        dests = tuple(int(d) for d in (op.matrix or ()))
        if sorted(dests) != sorted(op.targets):
            out.append(diag(AnalysisCode.INVALID_BIT_PERMUTATION,
                            Severity.ERROR, op_index=i,
                            detail=f"targets {op.targets} -> {dests}"))
        return
    mat = _op_matrix(op)
    if mat is not None:
        dim = 1 << len(op.targets)
        if mat.shape != (dim, dim):
            out.append(diag(ErrorCode.INVALID_UNITARY_SIZE, Severity.ERROR,
                            op_index=i,
                            detail=f"shape {mat.shape} for {len(op.targets)} targets"))
            return
        # matrix norms compound rounding: same widened tolerance the runtime
        # CPTP check uses (validation.py validate_kraus_cptp)
        if not _is_unitary(mat, 10 * eps):
            out.append(diag(ErrorCode.NON_UNITARY_MATRIX, Severity.ERROR,
                            op_index=i))
        return
    d = _op_diagonal(op)
    if d is not None:
        if d.shape != (1 << len(op.targets),):
            out.append(diag(ErrorCode.INVALID_UNITARY_SIZE, Severity.ERROR,
                            op_index=i,
                            detail=f"{d.shape[0]} diagonal entries for {len(op.targets)} targets"))
            return
        if np.any(np.abs(np.abs(d) - 1.0) > 10 * eps):
            out.append(diag(ErrorCode.NON_UNITARY_MATRIX, Severity.ERROR,
                            op_index=i, detail="diagonal entry off the unit circle"))


def _check_memory(circuit, num_devices: int, precision: int,
                  chip: _planner.ChipSpec, out: list) -> None:
    fp = _planner.memory_footprint(circuit.num_qubits, num_devices, precision)
    if fp["peak_shard_bytes"] > chip.hbm_bytes:
        out.append(diag(
            AnalysisCode.STATE_EXCEEDS_MESH_MEMORY, Severity.ERROR,
            detail=(f"{fp['peak_shard_bytes'] / 2**30:.1f} GiB working set "
                    f"per device vs {chip.hbm_bytes / 2**30:.1f} GiB HBM "
                    f"({chip.name} x{num_devices})")))
    if fp["sub_tile_shard"]:
        shard_amps = (1 << circuit.num_qubits) // num_devices
        out.append(diag(
            AnalysisCode.SUBTILE_SHARD, Severity.WARNING,
            detail=(f"{shard_amps} amps/shard over {num_devices} devices "
                    "(found-by-audit in the 9q x 8-device config: dense "
                    "kernels charged the 'subtile' comm class)")))


def _check_shard_fit(i: int, op, circuit, num_devices: int, out: list) -> None:
    # multi-target dense gates only: the routed amplitude groups must be
    # shard-local (validation.validate_multi_qubit_matrix_fits_in_shard);
    # 1q gates cross shards via collective-permute and never hit this
    if op.kind in _DENSE_KINDS and len(op.targets) > 1 and num_devices > 1:
        if (1 << len(op.targets)) > (1 << circuit.num_qubits) // num_devices:
            out.append(diag(ErrorCode.CANNOT_FIT_MULTI_QUBIT_MATRIX,
                            Severity.ERROR, op_index=i,
                            detail=f"{len(op.targets)} targets over {num_devices} devices"))


def _plane_mode_predicted(circuit, num_devices: int, precision: int) -> bool:
    """Would a register of this size take plane-pair storage?  Mirrors
    Qureg.uses_plane_storage minus the backend gate (the analyzer targets
    the accelerator deployment, where the gate passes)."""
    if precision != 1 or num_devices > 1:
        return False
    return 2 * 4 * (1 << circuit.num_qubits) >= _qureg.PLANE_STORAGE_MIN_BYTES


def _check_plane_compat(i: int, op, out: list) -> None:
    if len(op.targets) > 1 or op.controls:
        out.append(diag(ErrorCode.PLANE_ONLY_1Q, Severity.WARNING, op_index=i,
                        detail=f"kind '{op.kind}' on wires {op.targets + op.controls}"))


def _is_inverse_pair(a, b, eps: float) -> bool:
    """Do adjacent ops ``a`` then ``b`` compose to the identity?"""
    if (a.targets != b.targets or a.controls != b.controls
            or a.control_states != b.control_states):
        return False
    if a.kind != b.kind:
        return False
    if a.kind in ("x", "y", "swap"):
        return True  # self-inverse on identical wires
    if a.kind == "mrz":
        return abs(a.matrix[0] + b.matrix[0]) < eps
    ma, mb = _op_matrix(a), _op_matrix(b)
    if ma is not None and mb is not None:
        return bool(np.all(np.abs(mb @ ma - np.eye(ma.shape[0])) < 10 * eps))
    da, db = _op_diagonal(a), _op_diagonal(b)
    if da is not None and db is not None:
        return bool(np.all(np.abs(da * db - 1.0) < 10 * eps))
    return False


def _check_hints(circuit, eps: float, out: list) -> None:
    ops = circuit.ops
    for i in range(len(ops) - 1):
        if _is_inverse_pair(ops[i], ops[i + 1], eps):
            out.append(diag(AnalysisCode.ADJACENT_INVERSE_PAIR, Severity.HINT,
                            op_index=i,
                            detail=f"ops {i} and {i + 1} ({ops[i].kind}) cancel"))
    # maximal runs of uncontrolled 1q gates on one target (a 1q diagonal is
    # a dense 2x2 for fusion purposes)
    run_start, run_target = None, None
    for i, op in enumerate(ops + [None]):
        is_1q = (op is not None
                 and op.kind in ("matrix", "diagonal", "x", "y")
                 and len(op.targets) == 1 and not op.controls)
        t = op.targets[0] if is_1q else None
        if is_1q and t == run_target:
            continue
        if run_target is not None and i - run_start >= 2:
            out.append(diag(AnalysisCode.FUSABLE_1Q_RUN, Severity.HINT,
                            op_index=run_start,
                            detail=f"ops {run_start}..{i - 1} on qubit {run_target}"))
        run_start, run_target = (i, t) if is_1q else (None, None)


def analyze_circuit(circuit, *, num_devices: int = 1, precision: int = 1,
                    chip: _planner.ChipSpec = _planner.V5E,
                    hints: bool = True) -> list[Diagnostic]:
    """Analyze a recorded :class:`quest_tpu.Circuit` against a deployment
    (``num_devices`` chips of ``chip`` at ``precision``).  Returns structured
    :class:`Diagnostic`\\ s; ERROR severity means the circuit would raise or
    OOM at runtime, WARNING flags gates that die only in a specific regime
    (plane storage), HINT marks optimization opportunities."""
    out: list[Diagnostic] = []
    eps = real_eps(None)
    n = circuit.num_qubits
    # density channel slots (circuit.DensityCircuit) hold superoperators —
    # validated trace-preserving, not unitary
    channel_slots = getattr(circuit, "channel_slots", frozenset())
    plane_mode = _plane_mode_predicted(circuit, num_devices, precision)
    for i, op in enumerate(circuit.ops):
        if op.kind not in _KNOWN_KINDS:
            out.append(diag(AnalysisCode.UNKNOWN_GATE_KIND, Severity.ERROR,
                            op_index=i, detail=f"kind '{op.kind}'"))
            continue
        _check_wires(i, op, n, out)
        if i in channel_slots:
            _check_channel_payload(i, op, eps, out)
        else:
            _check_payload(i, op, eps, out)
        _check_shard_fit(i, op, circuit, num_devices, out)
        if plane_mode:
            _check_plane_compat(i, op, out)
    _check_memory(circuit, num_devices, precision, chip, out)
    if hints:
        _check_hints(circuit, eps, out)
    return out
