"""Pass 4: translation validation for scheduler/optimizer rewrites.

PR 2 turned the planner into an optimizer: ``Circuit.schedule()`` reorders
ops along a commutation DAG, fuses swap networks into ``bitperm``
collectives and relabels wires through placement permutations — and its
correctness was attested only by randomized statevector tests.  This pass
proves ``schedule()``/``optimize()`` output equivalent to its input
*without touching a 2^n state*, the same move QuEST_validation.c makes for
inputs but applied to the compiler's own rewrites (classic translation
validation: validate each emitted program, not the rewriter).

The proof is compositional, over three abstract domains:

1. **Permutation normalization** (:func:`_normalize_perms`).  ``swap`` and
   ``bitperm`` ops are permutation matrices; both circuits are rewritten
   into (relabeled core ops) x (one residual wire permutation), exactly.
   Residual permutations must agree bit-for-bit — this discharges swap-
   network fusion, placement relabeling and epoch brackets symbolically.

2. **Trace matching** (:func:`_match_cores`).  Core ops are matched 1:1
   across the two circuits under a *semantic* commutation oracle (disjoint
   wires; diagonal-family pairs; shared-wires-are-controls; else a dense
   commutator check on the <= ``max_window_qubits``-wire union).  Matched
   pairs cancel by the Mazurkiewicz-trace argument: each matched op
   commutes past every unmatched op before it, on both sides.

3. **Residue windows.**  Whatever fails to match is split into wire-
   connected components and each window is proven equivalent by the first
   domain that keeps precision: the *phase-polynomial domain* for the
   diagonal family (rz / phase_shift / multiRotateZ merge and commute,
   chi-basis polynomial or pointwise product diagonal — exact), the
   *Clifford/Pauli domain* (conjugating symbolic Pauli generators through
   H/X/Y/Z/S/CNOT/CZ and any payload recognized as Clifford — exact up to
   global phase, which one agreeing window-state probe then pins), and —
   only where both lose precision — a dense-matrix check on the window
   (product of the <= k-wire payloads, never the full state).  Windows too
   wide even for that are probed with random window STATES (2^w vectors,
   still never the full 2^n state): a probe disagreement is an exact
   disproof witness; probe agreement alone stays unverified.

A disproof emits ``V_SEMANTICS_CHANGED`` (ERROR) with a witness; a window
no domain can decide emits ``V_UNVERIFIED_REGION`` (WARNING).  An empty
diagnostic list is a *proof* of equivalence (up to the float tolerance of
the dense/probe certificates).

Entry points: :func:`check_equivalence`, :func:`verify_schedule`, the CLI
``--verify-schedule`` mode, and ``QUEST_TPU_VALIDATE_SCHEDULE=1`` (which
makes ``Circuit.schedule()`` self-validate).  See docs/ANALYSIS.md.
"""

from __future__ import annotations

import math
from typing import Iterable, Sequence

import numpy as np

from .diagnostics import AnalysisCode, Diagnostic, Severity, diag

__all__ = ["check_equivalence", "check_overlap_plan", "verify_schedule",
           "check_density_lowering", "check_density_plan"]

# dense windows: 2^10 x 2^10 complex is the largest matrix worth building
_MAX_WINDOW_QUBITS = 10
# diagonal windows compare as a 2^w product VECTOR — much wider is fine
_MAX_DIAG_QUBITS = 20
# random-vector window probes cost one 2^w VECTOR per side — wider still
_MAX_PROBE_QUBITS = 22
# commutator checks run inside the matcher's inner loop: keep them smaller
_MAX_COMMUTE_QUBITS = 8
_EPS = 1e-9

_DIAG_FAMILY = ("diagonal", "mrz")


# ---------------------------------------------------------------------------
# dense gate algebra (numpy, oracle conventions: qubit j of an op's local
# wire list (targets first, then controls) is bit j of the payload index)
# ---------------------------------------------------------------------------

def _op_base(op) -> np.ndarray:
    """Complex matrix of ``op`` on its TARGET wires only (no controls)."""
    if op.kind == "matrix":
        p = op.payload()
        return p[0] + 1j * p[1]
    if op.kind == "diagonal":
        p = op.payload()
        return np.diag(p[0] + 1j * p[1])
    if op.kind == "x":
        return np.array([[0, 1], [1, 0]], dtype=complex)
    if op.kind == "y":
        return np.array([[0, -1j], [1j, 0]])
    if op.kind == "y*":
        return np.array([[0, 1j], [-1j, 0]])
    if op.kind == "swap":
        return np.array([[1, 0, 0, 0], [0, 0, 1, 0],
                         [0, 1, 0, 0], [0, 0, 0, 1]], dtype=complex)
    if op.kind == "mrz":
        k = len(op.targets)
        if k > _MAX_WINDOW_QUBITS:
            raise _TooWide(k)
        par = np.array([bin(b).count("1") & 1 for b in range(1 << k)])
        return np.diag(np.exp(-0.5j * float(op.matrix[0]) * (1 - 2 * par)))
    raise _TooWide(len(op.targets))  # unknown kinds: treat as opaque


class _TooWide(Exception):
    """An op/window too wide for the dense domain."""


def _embed_unitary(w: int, base: np.ndarray, target_pos: Sequence[int],
                   control_pos: Sequence[int] = (),
                   control_states: Sequence[int] = ()) -> np.ndarray:
    """Full 2^w x 2^w operator of a controlled gate whose targets sit at
    window bit positions ``target_pos`` (oracle full_operator, local form)."""
    if w > _MAX_WINDOW_QUBITS:
        raise _TooWide(w)
    states = list(control_states) or [1] * len(control_pos)
    dim = 1 << w
    k = len(target_pos)
    out = np.zeros((dim, dim), dtype=complex)
    for col in range(dim):
        if not all(((col >> c) & 1) == s
                   for c, s in zip(control_pos, states)):
            out[col, col] = 1.0
            continue
        in_sub = 0
        for j, t in enumerate(target_pos):
            in_sub |= ((col >> t) & 1) << j
        rest = col
        for t in target_pos:
            rest &= ~(1 << t)
        for out_sub in range(1 << k):
            row = rest
            for j, t in enumerate(target_pos):
                row |= ((out_sub >> j) & 1) << t
            out[row, col] = base[out_sub, in_sub]
    return out


def _window_unitary(ops: Iterable, support: Sequence[int]) -> np.ndarray:
    """Dense unitary of an op list on the window ``support`` (sorted wires;
    window bit i is wire support[i]).  Raises :class:`_TooWide` beyond the
    dense limit."""
    pos = {w: i for i, w in enumerate(support)}
    u = np.eye(1 << len(support), dtype=complex)
    for op in ops:
        g = _embed_unitary(len(support), _op_base(op),
                           [pos[t] for t in op.targets],
                           [pos[c] for c in op.controls], op.control_states)
        u = g @ u
    return u


# ---------------------------------------------------------------------------
# permutation normalization: swap/bitperm ops -> one residual content map
# ---------------------------------------------------------------------------

def _normalize_perms(ops: Sequence, n: int) -> tuple[list, tuple]:
    """Rewrite ``ops`` as (core ops, residual permutation): every ``swap``/
    ``bitperm`` is absorbed into a running content permutation and later ops
    have their wires translated through it.  Exact: the circuit equals
    ``P(residual) . core`` as operators.  Returns core as (orig_index, op)
    pairs and the residual as the tuple ``pi`` with ``pi[origin] = final
    position of the content that started on wire origin``."""
    from ..circuit import GateOp
    pi = list(range(n))    # pi[origin] = current position
    inv = list(range(n))   # inv[position] = origin
    core: list = []
    for idx, op in enumerate(ops):
        if op.kind == "swap":
            a, b = int(op.targets[0]), int(op.targets[1])
            oa, ob = inv[a], inv[b]
            inv[a], inv[b] = ob, oa
            pi[oa], pi[ob] = b, a
            continue
        if op.kind == "bitperm":
            src = [int(t) for t in op.targets]
            dst = [int(d) for d in op.matrix]
            origins = [inv[s] for s in src]
            for o, d in zip(origins, dst):
                pi[o] = d
                inv[d] = o
            continue
        t = tuple(inv[q] for q in op.targets)
        c = tuple(inv[q] for q in op.controls)
        if (t, c) != (op.targets, op.controls):
            op = GateOp(op.kind, t, c, op.control_states, op.matrix, op.shape)
        core.append((idx, op))
    return core, tuple(pi)


# ---------------------------------------------------------------------------
# the semantic commutation oracle
# ---------------------------------------------------------------------------

def _wires(op) -> tuple:
    return op.targets + op.controls


def _overall_diagonal(op) -> bool:
    """True iff the op's full matrix (controls included) is diagonal."""
    return op.kind in _DIAG_FAMILY


def _commutes(a, b, eps: float = _EPS) -> bool:
    """Conservative semantic commutation: True only when provable.  Fast
    exact rules first (disjoint wires; two diagonal matrices; one diagonal
    whose shared wires are all the other's controls — block-diagonality);
    then a dense commutator check on the wire union when it fits."""
    wa, wb = set(_wires(a)), set(_wires(b))
    shared = wa & wb
    if not shared:
        return True
    if _overall_diagonal(a) and _overall_diagonal(b):
        return True
    if _overall_diagonal(a) and shared <= set(b.controls):
        return True
    if _overall_diagonal(b) and shared <= set(a.controls):
        return True
    union = sorted(wa | wb)
    if len(union) > _MAX_COMMUTE_QUBITS:
        return False
    try:
        ua = _window_unitary([a], union)
        ub = _window_unitary([b], union)
    except _TooWide:
        return False
    return bool(np.all(np.abs(ua @ ub - ub @ ua) < eps))


def _op_identical(a, b, eps: float = _EPS) -> bool:
    if (a.kind != b.kind or a.targets != b.targets or a.controls != b.controls
            or a.control_states != b.control_states or a.shape != b.shape):
        return False
    if a.matrix is None or b.matrix is None:
        return a.matrix == b.matrix
    if a.matrix == b.matrix:
        return True
    ma, mb = np.asarray(a.matrix), np.asarray(b.matrix)
    return ma.shape == mb.shape and bool(np.all(np.abs(ma - mb) < eps))


def _match_cores(core_a: list, core_b: list) -> tuple[list, list]:
    """Greedy trace matching of two perm-normalized op lists.  An op of A
    may match an identical op of B only if BOTH commute past every still-
    unmatched op before them in their own list — so matched pairs cancel
    exactly and ``A == B  iff  residue_A == residue_B``.  Returns the two
    residues as (orig_index, op) lists."""
    matched = [False] * len(core_b)
    residue_a: list = []
    memo: dict = {}

    def commutes(a, b) -> bool:
        key = (id(a), id(b))  # content-determined, so id aliasing is safe
        hit = memo.get(key)
        if hit is None:
            hit = memo[key] = _commutes(a, b)
        return hit

    for _ia, a in core_a:
        found = None
        for j, (_ib, b) in enumerate(core_b):
            if matched[j] or not _op_identical(a, b):
                continue
            ok = all(commutes(bp, b)
                     for jp, (_ibp, bp) in enumerate(core_b[:j])
                     if not matched[jp])
            if ok and all(commutes(ap, a) for _iap, ap in residue_a):
                found = j
            break  # identical later copies face the same blockers
        if found is None:
            residue_a.append((_ia, a))
        else:
            matched[found] = True
    residue_b = [pair for j, pair in enumerate(core_b) if not matched[j]]
    return residue_a, residue_b


# ---------------------------------------------------------------------------
# phase-polynomial domain (the diagonal family)
# ---------------------------------------------------------------------------

def _op_diag_entries(op) -> np.ndarray:
    """Full diagonal of a diagonal-family op over its own wires (targets
    LSB-first, then controls): entry 1 wherever the controls are
    unsatisfied."""
    if op.kind == "mrz":
        k = len(op.targets)
        if k > _MAX_DIAG_QUBITS:
            raise _TooWide(k)
        par = np.array([bin(b).count("1") & 1 for b in range(1 << k)])
        return np.exp(-0.5j * float(op.matrix[0]) * (1 - 2 * par))
    p = op.payload()
    d = p[0] + 1j * p[1]
    kt, kc = len(op.targets), len(op.controls)
    if kt + kc > _MAX_DIAG_QUBITS:
        raise _TooWide(kt + kc)
    if not kc:
        return d
    states = list(op.control_states) or [1] * kc
    out = np.ones(1 << (kt + kc), dtype=complex)
    idx = np.arange(1 << (kt + kc))
    ctrl_ok = np.ones(len(idx), dtype=bool)
    for j, s in enumerate(states):
        ctrl_ok &= ((idx >> (kt + j)) & 1) == s
    out[ctrl_ok] = d[idx[ctrl_ok] & ((1 << kt) - 1)]
    return out


def _product_diagonal(ops: Iterable, support: Sequence[int]) -> np.ndarray:
    """Pointwise product diagonal of a diagonal-family op list over the
    window — a 2^w VECTOR, exact, no angle-branch ambiguity."""
    w = len(support)
    if w > _MAX_DIAG_QUBITS:
        raise _TooWide(w)
    pos = {q: i for i, q in enumerate(support)}
    idx = np.arange(1 << w)
    d = np.ones(1 << w, dtype=complex)
    for op in ops:
        entries = _op_diag_entries(op)
        sub = np.zeros(len(idx), dtype=np.int64)
        for j, q in enumerate(_wires(op)):
            sub |= ((idx >> pos[q]) & 1) << j
        d *= entries[sub]
    return d


def _chi_poly(ops: Iterable) -> dict | None:
    """Phase polynomial of a diagonal-family op list in the chi basis:
    ``phi(x) = sum_m c[m] * (-1)^popcount(x & m)`` with ``m`` a wire mask.
    ``mrz`` contributes one term analytically at ANY width (the whole point
    of this domain: multiRotateZ merges verify symbolically where the
    2^k product vector would not fit); small ``diagonal`` payloads are
    Walsh-decomposed from their principal-branch angles.  None when some op
    has no exact chi form (non-unit entries, too wide)."""
    poly: dict = {}

    def add(mask: int, coeff: float) -> None:
        c = poly.get(mask, 0.0) + coeff
        if abs(c) < 1e-15:
            poly.pop(mask, None)
        else:
            poly[mask] = c

    for op in ops:
        if op.kind == "mrz":
            mask = 0
            for t in op.targets:
                mask |= 1 << t
            add(mask, -0.5 * float(op.matrix[0]))
            continue
        wires = _wires(op)
        if op.kind != "diagonal" or len(wires) > 8:
            return None
        entries = _op_diag_entries(op)
        if np.any(np.abs(np.abs(entries) - 1.0) > 1e-9):
            return None  # not a pure phase: leave to the dense domains
        theta = np.angle(entries)
        k = len(wires)
        sub = np.arange(1 << k)
        for m_local in range(1 << k):
            signs = 1 - 2 * (np.array(
                [bin(s & m_local).count("1") & 1 for s in sub]))
            c = float(np.dot(theta, signs)) / (1 << k)
            if abs(c) < 1e-15:
                continue
            mask = 0
            for j, q in enumerate(wires):
                if (m_local >> j) & 1:
                    mask |= 1 << q
            add(mask, c)
    return poly


def _poly_diff_verdict(pa: dict, pb: dict, eps: float) -> tuple[str, str]:
    """('equal'|'changed'|'unknown', detail) for two chi polynomials."""
    diff: dict = dict(pa)
    for m, c in pb.items():
        diff[m] = diff.get(m, 0.0) - c
    diff = {m: c for m, c in diff.items()
            if (abs(math.remainder(c, 2 * math.pi)) > eps if m == 0
                else abs(c) > eps)}
    if not diff:
        return "equal", ""
    # the difference only depends on wires appearing in its masks: evaluate
    # it pointwise there (mod 2pi) when that restriction is narrow enough
    wires = sorted({q for m in diff for q in range(m.bit_length())
                    if (m >> q) & 1})
    if len(wires) <= _MAX_DIAG_QUBITS:
        pos = {q: i for i, q in enumerate(wires)}
        vals = np.zeros(1 << len(wires))
        for m, c in diff.items():
            lm = 0
            for q in pos:
                if (m >> q) & 1:
                    lm |= 1 << pos[q]
            par = np.array([bin(x & lm).count("1") & 1
                            for x in range(len(vals))])
            vals += c * (1 - 2 * par)
        off = np.abs(np.remainder(vals + math.pi, 2 * math.pi) - math.pi)
        if np.all(off < 1e-7):
            return "equal", ""
        x = int(np.argmax(off))
        return "changed", (f"phase polynomials differ by "
                           f"{vals[x]:+.6g} rad at basis assignment {x:#x} "
                           f"over wires {tuple(wires)}")
    return "unknown", (f"phase-polynomial residual over {len(wires)} wires "
                       "is too wide to evaluate pointwise")


# ---------------------------------------------------------------------------
# Clifford / Pauli domain
# ---------------------------------------------------------------------------
# A Pauli is (x_mask, z_mask, ph) meaning i^ph * prod_q X_q^x Z_q^z (X left
# of Z per wire).  Conjugation tables are derived NUMERICALLY from each
# op's dense payload on its own <=3 wires — no hand-written phase rules to
# get wrong, and any payload that happens to be Clifford (H, S, CZ, CNOT,
# controlled-X, Haar accidents) is recognized automatically.

def _pmul(a: tuple, b: tuple) -> tuple:
    ax, az, ap = a
    bx, bz, bp = b
    ph = (ap + bp + 2 * bin(az & bx).count("1")) & 3
    return (ax ^ bx, az ^ bz, ph)


def _pauli_matrix(k: int, x: int, z: int) -> np.ndarray:
    singles = {
        (0, 0): np.eye(2, dtype=complex),
        (1, 0): np.array([[0, 1], [1, 0]], dtype=complex),
        (0, 1): np.array([[1, 0], [0, -1]], dtype=complex),
        (1, 1): np.array([[0, -1], [1, 0]], dtype=complex),  # XZ
    }
    m = np.eye(1, dtype=complex)
    for j in range(k - 1, -1, -1):  # bit j of the index <-> wire j (LSB)
        m = np.kron(m, singles[((x >> j) & 1, (z >> j) & 1)])
    return m


_clifford_cache: dict = {}


def _clifford_action(op) -> dict | None:
    """Images of the single-wire generators X_j / Z_j under conjugation by
    ``op`` (local wire order: targets then controls), or None when the op
    is not Clifford or too wide to decide."""
    key = (op.kind, len(op.targets), len(op.controls), op.control_states,
           op.matrix)
    if key in _clifford_cache:
        return _clifford_cache[key]
    k = len(op.targets) + len(op.controls)
    action: dict | None = {}
    if k > 3:
        action = None
    else:
        try:
            u = _embed_unitary(k, _op_base(op), range(len(op.targets)),
                               range(len(op.targets), k), op.control_states)
        except _TooWide:
            u = None
        if u is None:
            action = None
        else:
            for j in range(k):
                for name, (gx, gz) in (("X", (1 << j, 0)),
                                       ("Z", (0, 1 << j))):
                    m = u @ _pauli_matrix(k, gx, gz) @ u.conj().T
                    img = _decompose_pauli(k, m)
                    if img is None:
                        action = None
                        break
                    action[(j, name)] = img
                if action is None:
                    break
    _clifford_cache[key] = action
    return action


def _decompose_pauli(k: int, m: np.ndarray) -> tuple | None:
    """(x, z, ph) with m == i^ph X^x Z^z, or None if m is not a phased
    Pauli string."""
    dim = 1 << k
    for x in range(dim):
        for z in range(dim):
            c = np.trace(_pauli_matrix(k, x, z).conj().T @ m) / dim
            if abs(abs(c) - 1.0) < 1e-7:
                ph = int(round(np.angle(c) / (math.pi / 2))) & 3
                if abs(c - 1j ** ph) < 1e-7:
                    return (x, z, ph)
                return None
    return None


def _conjugate(p: tuple, op, pos: dict) -> tuple | None:
    """Image of window Pauli ``p`` under conjugation by ``op`` (window wire
    positions via ``pos``), or None when the op is not Clifford."""
    x, z, ph = p
    wires = _wires(op)
    local = [(j, pos[q]) for j, q in enumerate(wires)]
    if not any(((x >> wp) | (z >> wp)) & 1 for _, wp in local):
        return p
    action = _clifford_action(op)
    if action is None:
        return None
    img = (0, 0, 0)
    rest_x, rest_z = x, z
    for j, wp in local:
        xb, zb = (x >> wp) & 1, (z >> wp) & 1
        rest_x &= ~(1 << wp)
        rest_z &= ~(1 << wp)
        if xb:
            img = _pmul(img, _shift(action[(j, "X")], local))
        if zb:
            img = _pmul(img, _shift(action[(j, "Z")], local))
    return _pmul((rest_x, rest_z, ph), img)


def _shift(p_local: tuple, local: list) -> tuple:
    """Map an op-local Pauli onto window bit positions."""
    lx, lz, ph = p_local
    x = z = 0
    for j, wp in local:
        x |= ((lx >> j) & 1) << wp
        z |= ((lz >> j) & 1) << wp
    return (x, z, ph)


def _pauli_equiv(ops_a: list, ops_b: list,
                 support: Sequence[int]) -> bool | None:
    """Conjugate every generator X_i / Z_i of the window through both op
    lists; equal images on all generators prove the window unitaries equal
    up to one global phase.  None when some op is not Clifford."""
    pos = {q: i for i, q in enumerate(support)}
    for i in range(len(support)):
        for gen in ((1 << i, 0, 0), (0, 1 << i, 0)):
            pa: tuple | None = gen
            for op in ops_a:
                pa = _conjugate(pa, op, pos)
                if pa is None:
                    return None
            pb: tuple | None = gen
            for op in ops_b:
                pb = _conjugate(pb, op, pos)
                if pb is None:
                    return None
            if pa != pb:
                return False
    return True


# ---------------------------------------------------------------------------
# random-vector window probes: sound REFUTATION for windows too wide for a
# dense matrix — one 2^w vector per side, never a 2^w x 2^w matrix (and
# never the full 2^n state: windows are residue components only).  A probe
# disagreement is an exact witness that the window unitaries differ; probe
# agreement alone proves nothing, but combined with matching Pauli
# tableaux it pins the one remaining global-phase degree of freedom.
# ---------------------------------------------------------------------------

def _apply_op_vec(vec: np.ndarray, op, pos: dict, w: int) -> np.ndarray:
    """Apply one op to a 2^w window vector (window bit p = wire with
    pos[wire] = p), diagonal kinds as vectorized entry multiplies, dense
    kinds as a k-wire tensor contraction."""
    wires = _wires(op)
    if _overall_diagonal(op):
        idx = np.arange(1 << w)
        if op.kind == "mrz":
            mask = 0
            for t in op.targets:
                mask |= 1 << pos[t]
            par = np.zeros(1 << w, dtype=np.int64)
            m = mask
            while m:
                bpos = (m & -m).bit_length() - 1
                par ^= (idx >> bpos) & 1
                m &= m - 1
            return vec * np.exp(-0.5j * float(op.matrix[0]) * (1 - 2 * par))
        entries = _op_diag_entries(op)
        sub = np.zeros(1 << w, dtype=np.int64)
        for j, q in enumerate(wires):
            sub |= ((idx >> pos[q]) & 1) << j
        return vec * entries[sub]
    k = len(wires)
    g = _embed_unitary(k, _op_base(op), range(len(op.targets)),
                       range(len(op.targets), k), op.control_states)
    t = vec.reshape([2] * w)
    src = [w - 1 - pos[q] for q in wires]     # axis of op wire j
    dst = [k - 1 - j for j in range(k)]       # wire j -> bit j of the rows
    t = np.moveaxis(t, src, dst)
    t = (g @ t.reshape(1 << k, -1)).reshape([2] * w)
    return np.moveaxis(t, dst, src).reshape(-1)


def _probe_window(ops_a: list, ops_b: list, support: Sequence[int],
                  probes: int = 2) -> tuple[bool, float] | None:
    """Apply both op lists to shared random window states; returns
    (all probes agree, max |delta|), or None when the window is too wide
    even for vectors."""
    w = len(support)
    if w > _MAX_PROBE_QUBITS:
        return None
    pos = {q: i for i, q in enumerate(support)}
    rng = np.random.RandomState(1234 + w)
    worst = 0.0
    for _ in range(probes):
        v = rng.randn(1 << w) + 1j * rng.randn(1 << w)
        v /= np.linalg.norm(v)
        va, vb = v, v
        try:
            for op in ops_a:
                va = _apply_op_vec(va, op, pos, w)
            for op in ops_b:
                vb = _apply_op_vec(vb, op, pos, w)
        except _TooWide:
            return None
        worst = max(worst, float(np.max(np.abs(va - vb))) if w else 0.0)
    return worst < 1e-8, worst


# ---------------------------------------------------------------------------
# residue windows
# ---------------------------------------------------------------------------

def _components(residue_a: list, residue_b: list) -> list:
    """Split both residues into wire-connected components (ops in different
    components commute exactly, so each window verifies independently)."""
    parent: dict = {}

    def find(w: int) -> int:
        while parent.setdefault(w, w) != w:
            parent[w] = parent[parent[w]]
            w = parent[w]
        return w

    for _, op in residue_a + residue_b:
        ws = _wires(op)
        for q in ws[1:]:
            parent[find(ws[0])] = find(q)
    comps: dict = {}
    for side, residue in (("a", residue_a), ("b", residue_b)):
        for idx, op in residue:
            root = find(_wires(op)[0])
            comps.setdefault(root, {"a": [], "b": []})[side].append((idx, op))
    return list(comps.values())


def _verify_window(ops_a: list, ops_b: list, eps: float) -> list[Diagnostic]:
    """Prove one residue window equivalent, trying the domains in precision
    order: phase polynomial (diagonal family, exact at any width), dense
    window (exact, <= _MAX_WINDOW_QUBITS wires), Pauli tableau (exact up to
    global phase, any width)."""
    support = sorted({q for _, op in ops_a + ops_b for q in _wires(op)})
    where = (f"ops {[i for i, _ in ops_a]} (input) vs "
             f"{[i for i, _ in ops_b]} (rewrite) on wires {tuple(support)}")
    first = ops_a[0][0] if ops_a else (ops_b[0][0] if ops_b else None)
    la, lb = [op for _, op in ops_a], [op for _, op in ops_b]

    if all(_overall_diagonal(op) for op in la + lb):
        pa, pb = _chi_poly(la), _chi_poly(lb)
        if pa is not None and pb is not None:
            verdict, detail = _poly_diff_verdict(pa, pb, eps)
            if verdict == "equal":
                return []
            if verdict == "changed":
                return [diag(AnalysisCode.SEMANTICS_CHANGED, Severity.ERROR,
                             op_index=first, detail=f"{where}: {detail}")]
        try:
            da = _product_diagonal(la, support)
            db = _product_diagonal(lb, support)
        except _TooWide:
            pass
        else:
            err = float(np.max(np.abs(da - db))) if len(da) else 0.0
            if err < eps:
                return []
            x = int(np.argmax(np.abs(da - db)))
            return [diag(AnalysisCode.SEMANTICS_CHANGED, Severity.ERROR,
                         op_index=first,
                         detail=(f"{where}: product diagonals differ by "
                                 f"{err:.3g} at window index {x:#x}"))]
        probe = _probe_window(la, lb, support)
        if probe is not None and not probe[0]:
            return [diag(AnalysisCode.SEMANTICS_CHANGED, Severity.ERROR,
                         op_index=first,
                         detail=(f"{where}: random window-state probes "
                                 f"differ (max |delta| = {probe[1]:.3g})"))]
        return [diag(AnalysisCode.UNVERIFIED_REGION, Severity.WARNING,
                     op_index=first,
                     detail=f"{where}: diagonal window too wide for both "
                            "the chi polynomial and the product vector")]

    if len(support) <= _MAX_WINDOW_QUBITS:
        try:
            ua = _window_unitary(la, support)
            ub = _window_unitary(lb, support)
        except _TooWide:
            pass
        else:
            err = float(np.max(np.abs(ua - ub)))
            if err < max(eps, 1e-10 * ua.shape[0]):
                return []
            return [diag(AnalysisCode.SEMANTICS_CHANGED, Severity.ERROR,
                         op_index=first,
                         detail=(f"{where}: dense window unitaries differ "
                                 f"(max |delta| = {err:.3g})"))]

    verdict = _pauli_equiv(la, lb, support)
    if verdict is False:
        return [diag(AnalysisCode.SEMANTICS_CHANGED, Severity.ERROR,
                     op_index=first,
                     detail=f"{where}: Pauli generator images differ")]
    probe = _probe_window(la, lb, support)
    if probe is not None and not probe[0]:
        return [diag(AnalysisCode.SEMANTICS_CHANGED, Severity.ERROR,
                     op_index=first,
                     detail=(f"{where}: random window-state probes differ "
                             f"(max |delta| = {probe[1]:.3g})"))]
    if verdict is True and probe is not None and probe[0]:
        # tableau equality leaves exactly one global-phase degree of
        # freedom; one agreeing nonzero probe vector pins it to 1: proven
        return []
    if verdict is True:
        return [diag(AnalysisCode.UNVERIFIED_REGION, Severity.WARNING,
                     op_index=first,
                     detail=(f"{where}: Clifford tableaux agree (equal up "
                             "to global phase) but the window is too wide "
                             "for the phase certificate"))]
    return [diag(AnalysisCode.UNVERIFIED_REGION, Severity.WARNING,
                 op_index=first,
                 detail=(f"{where}: window exceeds the dense limit"
                         + ("; random window-state probes agree"
                            if probe is not None else "")))]


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def check_equivalence(before, after, *, eps: float = _EPS) -> list[Diagnostic]:
    """Translation-validate ``after`` against ``before`` (two
    :class:`quest_tpu.Circuit`\\ s).  Pure host work, never a 2^n state.
    Returns [] iff the circuits are PROVEN to implement the same unitary;
    ``V_SEMANTICS_CHANGED`` (ERROR) diagnostics carry a disagreement
    witness, ``V_UNVERIFIED_REGION`` (WARNING) marks rewrites no abstract
    domain could decide."""
    if before.num_qubits != after.num_qubits:
        return [diag(AnalysisCode.SEMANTICS_CHANGED, Severity.ERROR,
                     detail=(f"qubit counts differ: {before.num_qubits} vs "
                             f"{after.num_qubits}"))]
    core_a, perm_a = _normalize_perms(before.ops, before.num_qubits)
    core_b, perm_b = _normalize_perms(after.ops, after.num_qubits)
    if perm_a != perm_b:
        moved = [q for q in range(len(perm_a)) if perm_a[q] != perm_b[q]]
        return [diag(AnalysisCode.SEMANTICS_CHANGED, Severity.ERROR,
                     detail=(f"residual wire permutations differ on wires "
                             f"{tuple(moved)}: input "
                             f"{tuple(perm_a[q] for q in moved)} vs rewrite "
                             f"{tuple(perm_b[q] for q in moved)}"))]
    residue_a, residue_b = _match_cores(core_a, core_b)
    out: list[Diagnostic] = []
    for comp in _components(residue_a, residue_b):
        out.extend(_verify_window(comp["a"], comp["b"], eps))
    return out


def check_overlap_plan(circuit, plan) -> list[Diagnostic]:
    """Prove an overlapped-executor chunking plan
    (parallel/executor.py OverlapPlan) layout-only for ``circuit``.

    The chunked lowering is equivalent by construction iff, per event, the
    chunk bits are amplitude-index positions NO op of the window reads or
    moves (slicing along an untouched bit commutes with every such op),
    they lie below the sharded range (so slicing itself is shard-local),
    and a 'pairwise' event really is the plain 1-target uncontrolled dense
    exchange its shard_map engine implements.  A violated condition means
    the chunk programs would compute a DIFFERENT state —
    ``V_SEMANTICS_CHANGED``, same contract as the IR domains above."""
    from ..parallel import planner as _planner
    out: list[Diagnostic] = []
    n = circuit.num_qubits
    local_q = _planner.local_qubit_count(n, plan.num_devices)
    for e in plan.events:
        if not (0 <= e.start < e.stop <= len(circuit.ops)):
            out.append(diag(AnalysisCode.SEMANTICS_CHANGED, Severity.ERROR,
                            op_index=e.start,
                            detail=(f"overlap plan window [{e.start}, "
                                    f"{e.stop}) outside the op list")))
            continue
        window = circuit.ops[e.start:e.stop]
        if e.kind == "pairwise":
            op = window[0]
            if not (len(window) == 1 and len(op.targets) == 1
                    and not op.controls and op.targets[0] >= local_q
                    and op.kind in ("matrix", "x", "y")):
                out.append(diag(
                    AnalysisCode.SEMANTICS_CHANGED, Severity.ERROR,
                    op_index=e.start,
                    detail=(f"pairwise overlap event on op[{e.start}] "
                            f"({op.kind}, targets {op.targets}, controls "
                            f"{op.controls}) is not a 1-target "
                            "uncontrolled dense exchange")))
            continue
        used: set = set()
        for op in window:
            used |= set(op.targets) | set(op.controls)
            if op.kind == "bitperm":
                used |= {int(d) for d in op.matrix}
        bad = sorted(b for b in e.chunk_bits
                     if b in used or not 0 <= b < local_q)
        if bad or len(set(e.chunk_bits)) != len(e.chunk_bits) \
                or e.chunks != 1 << len(e.chunk_bits):
            out.append(diag(
                AnalysisCode.SEMANTICS_CHANGED, Severity.ERROR,
                op_index=e.start,
                detail=(f"overlap chunk bits {e.chunk_bits} of window "
                        f"[{e.start}, {e.stop}) are not free shard-local "
                        f"positions (window wires {tuple(sorted(used))}, "
                        f"local range [0, {local_q}); offending {bad})")))
    return out


def verify_schedule(circuit, scheduled=None, num_devices: int | None = None,
                    **schedule_kwargs) -> list[Diagnostic]:
    """Schedule ``circuit`` (unless ``scheduled`` is given) and translation-
    validate the result.  The programmatic form of the CLI's
    ``--verify-schedule`` and of ``QUEST_TPU_VALIDATE_SCHEDULE=1``.

    ``overlap=True`` / ``pipeline_chunks=`` kwargs flow through to
    :meth:`Circuit.schedule`; when the scheduled circuit carries an
    overlapped-executor chunking plan, the plan is additionally proven
    layout-only (:func:`check_overlap_plan`) so the chunked lowering is
    covered by the same proof as the IR rewrite."""
    if scheduled is None:
        if num_devices is None:
            raise ValueError("verify_schedule needs scheduled= or num_devices=")
        scheduled = circuit.schedule(num_devices, **schedule_kwargs)
    out = check_equivalence(circuit, scheduled)
    plan = getattr(scheduled, "_overlap_plan", None)
    if plan is not None:
        out += check_overlap_plan(scheduled, plan)
    return out


# ---------------------------------------------------------------------------
# Pallas epoch-executor lowering (ops/epoch_pallas.py): the rollout gate
# ---------------------------------------------------------------------------

#: largest register the numerical probe will actually execute (interpret
#: mode on CPU — one block pass per 2^17 amps; beyond this the IR proof
#: stands alone and the probe reports V_UNVERIFIED_REGION)
_MAX_EPOCH_PROBE_QUBITS = 18


def check_epoch_plan(circuit, plan=None) -> list[Diagnostic]:
    """Translation-validate the Pallas epoch executor's lowering of
    ``circuit`` (ops/epoch_pallas.py ``plan_circuit``): the plan's claimed
    execution — every segment's physically-rewired ops in pass order,
    followed by one ``bitperm`` materializing the deferred qubit map — must
    be PROVEN equivalent to the recorded circuit by the same abstract
    domains that certify scheduler rewrites (swap/bitperm normalization,
    1:1 core matching, Pauli-tableau / phase-polynomial / dense <= 2^10
    window oracles).  This is the IR half of the rollout gate; the kernel
    half is :func:`probe_epoch_execution`."""
    from ..circuit import Circuit, GateOp
    from ..ops import epoch_pallas as _ep
    if plan is None:
        plan = _ep.plan_circuit(circuit.key(), circuit.num_qubits)
    rec = Circuit(circuit.num_qubits)
    rec.ops = [op for seg in plan.segments for op in seg.ops]
    # reconcile_perm's mapping: content at position perm[q] returns to q
    mapping = {p: q for q, p in enumerate(plan.residual_perm) if p != q}
    if mapping:
        support = tuple(sorted(mapping))
        rec.ops.append(GateOp("bitperm", support, (), (),
                              tuple(float(mapping[w]) for w in support), None))
    return check_equivalence(circuit, rec)


# ---------------------------------------------------------------------------
# density (Choi-doubled) lowering: the superoperator window domain
# ---------------------------------------------------------------------------

def _vec_density(rho: np.ndarray) -> np.ndarray:
    """vec of a w-qubit density matrix in the engine layout: flat index =
    row_bits + (col_bits << w) (the getDensityAmp convention)."""
    return rho.T.reshape(-1)   # column-major: index = row + col * 2^w


def check_density_lowering(circuit, *, eps: float = 1e-8,
                           probes: int = 2) -> list[Diagnostic]:
    """Prove a :class:`~quest_tpu.circuit.DensityCircuit`'s Choi-doubled
    recording faithful to its DENSITY-level semantics — the translation
    step :func:`check_equivalence` cannot see, because both sides of that
    proof are already doubled op lists.

    Two obligations, both discharged on <= ``_MAX_WINDOW_QUBITS``-wire
    doubled windows (never a 4^n state):

    1. **Mirrored-pass pairing + conjugate twist.**  Every unitary op must
       be immediately followed by its bra-side shadow — same kind on wires
       shifted by n with the payload CONJUGATED — and the pair's doubled
       window operator must equal ``conj(U) ⊗ U`` for the op's full
       controlled unitary U (dense compare on random flattened window
       density matrices).  A wrong-conjugate mutation (a shadow recorded
       unconjugated) is refuted here with a witness.

    2. **Channel superoperators against the Kraus oracle.**  Every channel
       slot's recorded payload is applied two INDEPENDENT ways to random
       window density matrices: as the recorded doubled-window operator,
       and through ``ops/decoherence._superop_apply`` driving the
       superoperator ``Σ conj(K)⊗K`` rebuilt from the channel's DEFINING
       Kraus operators (``decoherence.channel_kraus`` — never the payload
       builders, so a corrupted payload cannot self-certify).  Mismatch or
       a non-trace-preserving map is ``V_SEMANTICS_CHANGED``.

    Returns [] iff every pair and channel is proven; windows too wide for
    the dense oracle report ``V_UNVERIFIED_REGION`` (the payload-level
    conjugation check still applies)."""
    import jax.numpy as jnp

    from ..circuit import _shadow_op
    from ..ops import decoherence as _deco
    n = getattr(circuit, "density_qubits", None)
    if n is None:
        return [diag(AnalysisCode.SEMANTICS_CHANGED, Severity.ERROR,
                     detail="not a DensityCircuit: no density_qubits "
                            "marker / channel log to verify against")]
    channels = {rec[0]: rec for rec in getattr(circuit, "channel_log", ())}
    out: list[Diagnostic] = []
    rng = np.random.RandomState(971)

    def rand_rho(w: int) -> np.ndarray:
        a = rng.randn(1 << w, 1 << w) + 1j * rng.randn(1 << w, 1 << w)
        rho = a @ a.conj().T
        return rho / np.trace(rho)

    i = 0
    ops = list(circuit.ops)
    while i < len(ops):
        op = ops[i]
        rec = channels.get(i)
        if rec is not None:
            _, kind, targets = rec[:3]
            args = rec[3:]
            doubled = tuple(targets) + tuple(t + n for t in targets)
            if tuple(op.targets) != doubled or op.controls:
                out.append(diag(
                    AnalysisCode.SEMANTICS_CHANGED, Severity.ERROR,
                    op_index=i,
                    detail=(f"channel op {i} ({kind}) on wires "
                            f"{op.targets}: expected the doubled pair "
                            f"{doubled}")))
                i += 1
                continue
            k = len(targets)
            if 2 * k > _MAX_WINDOW_QUBITS:
                out.append(diag(
                    AnalysisCode.UNVERIFIED_REGION, Severity.WARNING,
                    op_index=i,
                    detail=(f"channel op {i} ({kind}): {2 * k}-wire "
                            "doubled window exceeds the dense oracle")))
                i += 1
                continue
            from ..circuit import GateOp
            kraus = _deco.channel_kraus(kind, *args)
            sp = _deco.kraus_superoperator(kraus)
            # recorded payload on window-local wires: matrix index bit j
            # <-> op.targets[j], so the local twin just renumbers targets
            local = GateOp(op.kind, tuple(range(2 * k)), (), (),
                           op.matrix, op.shape)
            got_m = _window_unitary([local], range(2 * k))
            worst = 0.0
            for _ in range(probes):
                rho = rand_rho(k)
                vec = _vec_density(rho)
                state = jnp.stack([jnp.asarray(vec.real),
                                   jnp.asarray(vec.imag)])
                # the INDEPENDENT application engine: decoherence's
                # gather/dense superoperator path on the flattened window
                oracle = _deco._superop_apply(
                    state, jnp.asarray(sp), tuple(range(2 * k)), None)
                want = (np.asarray(oracle[0])
                        + 1j * np.asarray(oracle[1]))
                got = got_m @ vec
                worst = max(worst, float(np.max(np.abs(got - want))))
            if worst > eps:
                out.append(diag(
                    AnalysisCode.SEMANTICS_CHANGED, Severity.ERROR,
                    op_index=i,
                    detail=(f"channel op {i} ({kind} on {targets}): "
                            "recorded superoperator disagrees with the "
                            f"Kraus-defined channel by {worst:.3g} on "
                            "random window density matrices")))
            if not _deco.superop_trace_preserving(
                    np.stack([got_m.real, got_m.imag]), k, eps):
                out.append(diag(
                    AnalysisCode.SEMANTICS_CHANGED, Severity.ERROR,
                    op_index=i,
                    detail=(f"channel op {i} ({kind} on {targets}): "
                            "recorded superoperator does not preserve "
                            "Tr(rho)")))
            i += 1
            continue
        # unitary op: must be followed by its conjugate shadow
        wires = op.targets + op.controls
        if any(q >= n for q in wires):
            out.append(diag(
                AnalysisCode.SEMANTICS_CHANGED, Severity.ERROR, op_index=i,
                detail=(f"op {i} ({op.kind} on {op.targets}) touches bra "
                        "wires but is not a recorded channel slot or a "
                        "ket-side op — the mirrored pairing is broken")))
            i += 1
            continue
        if i + 1 >= len(ops):
            out.append(diag(
                AnalysisCode.SEMANTICS_CHANGED, Severity.ERROR, op_index=i,
                detail=f"op {i} ({op.kind} on {op.targets}) has no "
                       "bra-side shadow"))
            break
        shadow = ops[i + 1]
        want_shadow = _shadow_op(op, n)
        if not _op_identical(shadow, want_shadow, eps):
            out.append(diag(
                AnalysisCode.SEMANTICS_CHANGED, Severity.ERROR,
                op_index=i + 1,
                detail=(f"op {i + 1} is not the conjugate shadow of op "
                        f"{i} ({op.kind} on {op.targets}): the conjugate "
                        "twist is wrong (U ⊗ U instead of U ⊗ U*, or "
                        "mismatched wires)")))
            i += 2
            continue
        # dense window certificate: [op, shadow] == conj(U) ⊗ U
        w = len(wires)
        if 2 * w <= _MAX_WINDOW_QUBITS:
            try:
                support = sorted(wires) + [q + n for q in sorted(wires)]
                pair_m = _window_unitary([op, shadow], support)
                # _window_unitary positions ops by SORTED support: embed
                # U onto the sorted ket order before taking conj(U) ⊗ U
                pos = {q: j for j, q in enumerate(sorted(wires))}
                perm_u = _embed_unitary(
                    w, _op_base(op), [pos[t] for t in op.targets],
                    [pos[c] for c in op.controls], op.control_states)
            except _TooWide:
                pass
            else:
                want_m = np.kron(perm_u.conj(), perm_u)
                err = float(np.max(np.abs(pair_m - want_m)))
                if err > eps:
                    out.append(diag(
                        AnalysisCode.SEMANTICS_CHANGED, Severity.ERROR,
                        op_index=i,
                        detail=(f"mirrored pair (ops {i}, {i + 1}) does "
                                f"not implement U rho U†: |delta| = "
                                f"{err:.3g} vs conj(U) ⊗ U")))
        i += 2
    return out


def check_density_plan(circuit, plan=None) -> list[Diagnostic]:
    """The density rollout gate: :func:`check_density_lowering` (the
    Choi-doubling itself — mirrored pairing, conjugate twist, channel
    superoperators vs the Kraus oracle) PLUS :func:`check_epoch_plan` (the
    epoch executor's fused lowering of the doubled circuit, proven by the
    same abstract domains that certify scheduler rewrites).  [] is a proof
    that the fused superoperator passes execute the density circuit the
    user recorded."""
    return (check_density_lowering(circuit)
            + check_epoch_plan(circuit, plan))


def probe_epoch_execution(circuit, *, atol: float = 5e-5,
                          seed: int = 0) -> list[Diagnostic]:
    """Run the ACTUAL epoch-executor kernels against the XLA gate engine on
    a random f32 state (``pl.pallas_call(interpret=True)`` on CPU — the
    same kernel code Mosaic compiles on a chip) and compare end states.
    One random-state agreement pins the whole window unitary with
    probability 1 up to the float tolerance; a disagreement is
    ``V_SEMANTICS_CHANGED`` with the witness amplitude.  Registers beyond
    ``_MAX_EPOCH_PROBE_QUBITS`` report ``V_UNVERIFIED_REGION`` (the probe
    would execute a 2^n state) and rely on :func:`check_epoch_plan` plus
    the tier-1 kernel property suite."""
    n = circuit.num_qubits
    if n > _MAX_EPOCH_PROBE_QUBITS:
        return [diag(AnalysisCode.UNVERIFIED_REGION, Severity.WARNING,
                     detail=(f"epoch execution probe skipped: {n} qubits > "
                             f"probe cap {_MAX_EPOCH_PROBE_QUBITS} (IR proof "
                             "and tier-1 kernel tests still apply)"))]
    import jax.numpy as jnp

    from ..circuit import compile_circuit
    from .serve_audit import _probe_state
    st = _probe_state(n, jnp.float32, seed)
    want = np.asarray(compile_circuit(circuit, engine="xla")(st))
    got = np.asarray(compile_circuit(circuit, engine="pallas")(st))
    err = np.abs(got - want)
    if err.max() > atol:
        k = int(np.unravel_index(err.argmax(), err.shape)[1])
        return [diag(AnalysisCode.SEMANTICS_CHANGED, Severity.ERROR,
                     detail=(f"epoch executor disagrees with the XLA engine "
                             f"at amplitude {k}: |delta| = {err.max():.3g} "
                             f"> {atol:.3g} on a random-state probe"))]
    return []
