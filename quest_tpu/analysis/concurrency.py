"""Pass 7: lock-discipline static audit over the serve/deploy/obs runtime.

PR 11 made quest_tpu a multi-replica deployment: thread-backed replica
pools, an affinity router reading a lock-free SLO health ring, one shared
labeled metrics registry, a batching service worker.  That is exactly the
concurrency surface a pod-scale deployment stresses — and nothing in the
analysis subsystem could prove a single lock is held where it must be.
This pass makes the locking discipline *checkable*, in the spirit of
lockset-based race detection (Eraser) and guarded-by annotation checking
(Clang thread-safety analysis), but over plain Python ``threading``:

- Per class that owns a lock (``threading.Lock`` / ``RLock`` /
  ``Condition`` instance attribute), every instance attribute's reads and
  writes are collected together with the **lexical lock scope** they run
  under (``with self._lock:`` blocks and ``acquire()``/``try/finally
  release()`` pairs).
- Accesses are checked against the annotation convention
  (docs/ANALYSIS.md "Concurrency audit"):

  - ``# guarded-by: <lockname>`` on the attribute-initialising assignment
    declares the guard; every post-``__init__`` write must hold it
    (``T_UNGUARDED_SHARED_WRITE``), every read should
    (``T_UNGUARDED_SHARED_READ``, WARNING).
  - ``# lock-free: <reason>`` declares a deliberately unlocked structure
    (the SLO health ring, single-word gauges); the reason string is
    REQUIRED (``T_LOCK_FREE_NO_REASON``) and the schedule-fuzzing harness
    (analysis/schedfuzz.py) stress-proves these surfaces dynamically.
    The same comment on an individual access line waives that one site.
  - ``# requires-lock: <lockname>`` on a helper method declares that its
    CALLERS must hold the lock; its body is analysed as holding it, and a
    call site that does not hold it is flagged.
  - An attribute written outside ``__init__`` with no annotation gets its
    guard *inferred* Eraser-style (the intersection of locks held across
    write sites) and a ``T_UNANNOTATED_SHARED_ATTR`` warning asking for
    the declaration.

- The same walk builds a cross-class **lock acquisition-order graph**
  (attribute-to-class bindings inferred from ``__init__``): a cycle is a
  deadlock two opposite-order threads can hit (``T_LOCK_ORDER_CYCLE``),
  including the degenerate self-cycle of re-acquiring a non-reentrant
  ``Lock``.
- Blocking operations inside a lock region (XLA compile/dispatch entry
  points, ``Future.result``, ``sleep``, thread ``join``, ``wait`` on
  anything that is not the held condition) are
  ``T_BLOCKING_CALL_UNDER_LOCK``: on the routing/admission hot path they
  serialise every contending thread behind device latency.

Everything is intra-class and lexical on purpose: a rule fires only on
provable violations of the declared (or unanimously inferred) discipline,
so the pass stays false-positive-free on a clean tree and is enforceable
in CI (``python -m quest_tpu.analysis --concurrency --json``) next to
``--self-lint``.  Construction (``__init__``) is exempt — an object under
construction is thread-private by the publication rules the rest of the
tree already follows.
"""

from __future__ import annotations

import ast
import os
import re

from .diagnostics import AnalysisCode, Diagnostic, Severity, diag

__all__ = ["audit_paths", "audit_package", "audit_source",
           "strip_first_lock_scope", "AUDIT_SUBPACKAGES"]

#: the quest_tpu subpackages the repo self-audit covers (the concurrent
#: runtime surface; the analysis package itself is host-single-threaded
#: except schedfuzz, whose scheduler is its own test subject).  grad and
#: parallel are swept too: neither owns a lock today (their shared state
#: is the serve cache's, audited via serve/), so the sweep holds them to
#: staying that way — a lock-owning class added there is auto-audited
AUDIT_SUBPACKAGES = ("serve", "deploy", "obs", "grad", "parallel")

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)")
_LOCKFREE_RE = re.compile(r"#\s*lock-free:\s*(.*?)\s*$")
_REQUIRES_RE = re.compile(r"#\s*requires-lock:\s*([A-Za-z_]\w*)")

#: threading constructors whose instance attributes count as locks
_LOCK_CTORS = {"Lock", "RLock", "Condition"}
#: the reentrant kinds: re-acquiring one you hold is NOT a self-deadlock
_REENTRANT_CTORS = {"RLock", "Condition"}

#: method calls that mutate their receiver in place: ``self.X.append(...)``
#: is a WRITE to X for lockset purposes
_MUTATORS = frozenset((
    "append", "appendleft", "extend", "insert", "pop", "popleft", "popitem",
    "remove", "clear", "add", "discard", "update", "setdefault",
    "move_to_end", "sort", "reverse",
))

#: attribute names whose call blocks (compile/dispatch, Future.result,
#: sleep, thread join) — flagged inside any lock region.  ``wait`` is
#: special-cased: waiting on the HELD condition releases it by contract.
_BLOCKING_ATTRS = frozenset((
    "sleep", "result", "block_until_ready", "lower", "compile",
    "entry_for", "single_program", "batch_program", "overlap_program",
    "epoch_program", "epoch_plane_program", "_get_program", "join", "wait",
))
#: dotted prefixes exempt from the blocking scan (``re.compile`` is a host
#: regex build, not an XLA compile)
_BLOCKING_EXEMPT_PREFIXES = ("re.",)

#: factory functions whose return type is a known locking class — lets the
#: lock-order graph bind ``self._cache = global_cache()`` style attributes
_FACTORY_CLASSES = {
    "global_cache": "CompileCache",
    "global_ledger": "Ledger",
    "global_counters": "RuntimeCounters",
    "recorder": "TraceRecorder",
}


def _dotted(node: ast.AST) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _self_attr(node: ast.AST) -> str | None:
    """'X' for a ``self.X`` attribute node, else None."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _Annotations:
    """Per-file comment annotations, looked up by line number.  A comment
    counts for a statement when it sits on the statement's first line or
    on the directly preceding line (a pure comment line)."""

    def __init__(self, source: str):
        self.lines = source.splitlines()

    def _line(self, lineno: int | None) -> str:
        if lineno is None or not 1 <= lineno <= len(self.lines):
            return ""
        return self.lines[lineno - 1]

    def _match(self, pattern: re.Pattern, lineno: int | None):
        m = pattern.search(self._line(lineno))
        if m is None and lineno is not None:
            prev = self._line(lineno - 1).strip()
            if prev.startswith("#"):
                m = pattern.search(prev)
        return m

    def guarded_by(self, lineno: int | None) -> str | None:
        m = self._match(_GUARDED_RE, lineno)
        return m.group(1) if m else None

    def lock_free(self, lineno: int | None) -> str | None:
        """The reason string of a ``# lock-free:`` annotation ('' when the
        annotation is present but unreasoned), None when absent."""
        m = self._match(_LOCKFREE_RE, lineno)
        return m.group(1) if m else None

    def requires_lock(self, lineno: int | None) -> str | None:
        m = self._match(_REQUIRES_RE, lineno)
        return m.group(1) if m else None

    def site_waived(self, lineno: int | None) -> bool:
        """A site-level waiver: a reasoned ``# lock-free:`` comment on the
        access line or the pure-comment line directly above it."""
        m = self._match(_LOCKFREE_RE, lineno)
        return bool(m and m.group(1))


class _AttrInfo:
    __slots__ = ("name", "guard", "lock_free", "init_line", "is_lock",
                 "lock_ctor", "init_writes_only")

    def __init__(self, name: str, init_line: int | None = None):
        self.name = name
        self.guard: str | None = None
        self.lock_free: str | None = None       # reason ('' = unreasoned)
        self.init_line = init_line
        self.is_lock = False
        self.lock_ctor: str | None = None
        self.init_writes_only = True


class _Access:
    __slots__ = ("attr", "method", "line", "kind", "held", "waived")

    def __init__(self, attr: str, method: str, line: int, kind: str,
                 held: tuple, waived: bool):
        self.attr = attr
        self.method = method
        self.line = line
        self.kind = kind                # "read" | "write"
        self.held = frozenset(held)
        self.waived = waived


class _ClassAudit:
    """One class's inferred concurrency facts."""

    def __init__(self, name: str, filename: str, line: int):
        self.name = name
        self.filename = filename
        self.line = line
        self.attrs: dict[str, _AttrInfo] = {}
        self.accesses: list[_Access] = []
        # lock attr -> ctor kind ("Lock" | "RLock" | "Condition")
        self.locks: dict[str, str] = {}
        # method name -> set of lock attrs it acquires lexically
        self.method_acquires: dict[str, set] = {}
        # method name -> lock it declares callers must hold
        self.method_requires: dict[str, str] = {}
        # self attr -> bound class name (for the cross-class lock graph)
        self.attr_classes: dict[str, str] = {}
        # (held_lock, attr, called_method, line) call events, resolved
        # against other classes once every file is parsed
        self.cross_calls: list[tuple] = []
        # (from_lock, to_lock, line) intra-class acquisition order
        self.intra_edges: list[tuple] = []
        # (dotted_call, line, held) blocking calls inside lock regions
        self.blocking: list[tuple] = []
        # (method, line, required_lock) requires-lock violations
        self.requires_violations: list[tuple] = []

    def lock_kind(self, lock: str) -> str:
        return self.locks.get(lock, "Lock")


class _MethodWalker:
    """Walks one method body tracking the lexical lock scope."""

    def __init__(self, audit: _ClassAudit, ann: _Annotations, method: str,
                 requires: str | None):
        self.audit = audit
        self.ann = ann
        self.method = method
        self.base_held: tuple = (requires,) if requires else ()

    # -- entry ----------------------------------------------------------------
    def walk(self, body: list) -> None:
        self._walk_body(body, self.base_held)

    # -- statement dispatch ---------------------------------------------------
    def _walk_body(self, stmts: list, held: tuple) -> None:
        i = 0
        while i < len(stmts):
            st = stmts[i]
            lk = self._acquire_target(st)
            if (lk is not None and i + 1 < len(stmts)
                    and isinstance(stmts[i + 1], ast.Try)
                    and self._releases(stmts[i + 1].finalbody, lk)):
                # self.L.acquire(); try: ... finally: self.L.release()
                self._note_acquisition(lk, held, st.lineno)
                tr = stmts[i + 1]
                inner = held + (lk,)
                self._walk_body(tr.body, inner)
                self._walk_body(tr.orelse, inner)
                for h in tr.handlers:
                    self._walk_body(h.body, inner)
                self._walk_body(tr.finalbody, inner)
                i += 2
                continue
            self._visit_stmt(st, held)
            i += 1

    def _acquire_target(self, st: ast.AST) -> str | None:
        if (isinstance(st, ast.Expr) and isinstance(st.value, ast.Call)
                and isinstance(st.value.func, ast.Attribute)
                and st.value.func.attr == "acquire"):
            name = _self_attr(st.value.func.value)
            if name in self.audit.locks:
                return name
        return None

    def _releases(self, finalbody: list, lock: str) -> bool:
        for st in finalbody:
            for node in ast.walk(st):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "release"
                        and _self_attr(node.func.value) == lock):
                    return True
        return False

    def _visit_stmt(self, st: ast.AST, held: tuple) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            # a nested def runs LATER, not under the lexically enclosing
            # lock: analyse its body with an empty scope so a deferred
            # closure can never inherit a guard it will not actually hold
            self._walk_body(st.body, ())
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            new = list(held)
            for item in st.items:
                self._scan_expr(item.context_expr, tuple(new))
                lk = _self_attr(item.context_expr)
                if lk in self.audit.locks:
                    self._note_acquisition(lk, tuple(new), st.lineno)
                    new.append(lk)
            self._walk_body(st.body, tuple(new))
            return
        if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (st.targets if isinstance(st, ast.Assign)
                       else [st.target])
            for t in targets:
                self._record_target(t, held)
            value = getattr(st, "value", None)
            if value is not None:
                self._scan_expr(value, held)
            return
        if isinstance(st, ast.Delete):
            for t in st.targets:
                self._record_target(t, held)
            return
        for _field, value in ast.iter_fields(st):
            if isinstance(value, list):
                if value and isinstance(value[0], ast.stmt):
                    self._walk_body(value, held)
                elif value and isinstance(value[0], ast.excepthandler):
                    for h in value:
                        self._walk_body(h.body, held)
                else:
                    for v in value:
                        if isinstance(v, ast.expr):
                            self._scan_expr(v, held)
            elif isinstance(value, ast.expr):
                self._scan_expr(value, held)

    def _note_acquisition(self, lock: str, held: tuple, line: int) -> None:
        self.audit.method_acquires.setdefault(self.method, set()).add(lock)
        for h in held:
            self.audit.intra_edges.append((h, lock, line))

    # -- targets (writes) -----------------------------------------------------
    def _record_target(self, node: ast.AST, held: tuple) -> None:
        if isinstance(node, (ast.Tuple, ast.List)):
            for el in node.elts:
                self._record_target(el, held)
            return
        if isinstance(node, ast.Starred):
            self._record_target(node.value, held)
            return
        base = node
        while isinstance(base, ast.Subscript):
            self._scan_expr(base.slice, held)
            base = base.value
        name = _self_attr(base)
        if name is not None:
            self._access(name, node.lineno, "write", held)
            return
        # non-self targets (locals, cross-object): scan for reads only
        self._scan_expr(base, held)

    # -- expressions ----------------------------------------------------------
    def _scan_expr(self, node: ast.AST, held: tuple) -> None:
        if node is None:
            return
        if isinstance(node, ast.Call):
            self._scan_call(node, held)
            return
        if isinstance(node, ast.Lambda):
            # lambdas are treated as executing at definition (sort keys,
            # callbacks invoked inline): same lock scope
            self._scan_expr(node.body, held)
            return
        name = _self_attr(node)
        if name is not None:
            self._access(name, node.lineno, "read", held)
            return
        for child in ast.iter_child_nodes(node):
            self._scan_expr(child, held)

    def _scan_call(self, node: ast.Call, held: tuple) -> None:
        func = node.func
        handled_func = False
        if isinstance(func, ast.Attribute):
            recv_attr = _self_attr(func.value)
            # self.X.mutator(...) => write to X
            if recv_attr is not None and func.attr in _MUTATORS:
                self._access(recv_attr, node.lineno, "write", held)
                handled_func = True
            # self.helper(...) where helper requires a lock
            if isinstance(func.value, ast.Name) and func.value.id == "self":
                req = self.audit.method_requires.get(func.attr)
                if req is not None and req not in held:
                    self.audit.requires_violations.append(
                        (self.method, node.lineno, (func.attr, req)))
                handled_func = True     # a method lookup is not a data read
            # self.X.method(...): record for cross-class lock-graph edges
            if (recv_attr is not None and held
                    and recv_attr not in self.audit.locks):
                for h in held:
                    self.audit.cross_calls.append(
                        (h, recv_attr, func.attr, node.lineno))
            # blocking calls under a lock
            if held and func.attr in _BLOCKING_ATTRS:
                dotted = _dotted(func)
                exempt = dotted.startswith(_BLOCKING_EXEMPT_PREFIXES)
                if func.attr == "wait" and recv_attr in held:
                    exempt = True       # Condition.wait releases the lock
                if func.attr in ("result", "join") and isinstance(
                        func.value, ast.Constant):
                    exempt = True       # "sep".join(...) et al.
                if not exempt and not self.ann.site_waived(node.lineno):
                    self.audit.blocking.append((dotted or func.attr,
                                                node.lineno, tuple(held)))
        elif isinstance(func, ast.Name) and held:
            if func.id in _BLOCKING_ATTRS and func.id == "sleep":
                self.audit.blocking.append((func.id, node.lineno,
                                            tuple(held)))
        if not handled_func:
            self._scan_expr(func, held)
        for arg in node.args:
            self._scan_expr(arg, held)
        for kw in node.keywords:
            self._scan_expr(kw.value, held)

    def _access(self, attr: str, line: int, kind: str, held: tuple) -> None:
        if attr in self.audit.locks:
            return                      # lock objects audit themselves
        info = self.audit.attrs.get(attr)
        if info is None:
            info = self.audit.attrs[attr] = _AttrInfo(attr)
        if kind == "write":
            info.init_writes_only = False
        self.audit.accesses.append(
            _Access(attr, self.method, line, kind, held,
                    self.ann.site_waived(line)))


def _lock_ctor_of(value: ast.AST) -> str | None:
    """'Lock' / 'RLock' / 'Condition' when ``value`` constructs one."""
    for node in ast.walk(value):
        if isinstance(node, ast.Call):
            last = _dotted(node.func).split(".")[-1]
            if last in _LOCK_CTORS:
                return last
    return None


def _bound_class(value: ast.AST, known_classes: set) -> str | None:
    """The audited class name ``value`` constructs (or a known factory
    returns), for attribute->class lock-graph bindings."""
    for node in ast.walk(value):
        if isinstance(node, ast.Call):
            last = _dotted(node.func).split(".")[-1]
            if last in known_classes:
                return last
            if last in _FACTORY_CLASSES:
                return _FACTORY_CLASSES[last]
    return None


def _parse_class(node: ast.ClassDef, filename: str,
                 ann: _Annotations) -> _ClassAudit:
    audit = _ClassAudit(node.name, filename, node.lineno)
    methods = [st for st in node.body
               if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef))]
    # pass A: the attribute catalog + lock set from __init__
    for fn in methods:
        if fn.name != "__init__":
            continue
        for st in ast.walk(fn):
            if not isinstance(st, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (st.targets if isinstance(st, ast.Assign)
                       else [st.target])
            value = st.value
            for t in targets:
                name = _self_attr(t)
                if name is None or value is None:
                    continue
                info = audit.attrs.get(name)
                if info is None:
                    info = audit.attrs[name] = _AttrInfo(name, st.lineno)
                elif info.init_line is None:
                    info.init_line = st.lineno
                ctor = _lock_ctor_of(value)
                if ctor is not None:
                    info.is_lock = True
                    info.lock_ctor = ctor
                    audit.locks[name] = ctor
                info.guard = ann.guarded_by(st.lineno)
                info.lock_free = ann.lock_free(st.lineno)
    # pass B: method-level requires-lock declarations (body analysis needs
    # the full table for call-site checks, so collect them all first)
    for fn in methods:
        req = ann.requires_lock(fn.lineno)
        if req is not None:
            audit.method_requires[fn.name] = req
    return audit


def _analyse_methods(audit: _ClassAudit, node: ast.ClassDef,
                     ann: _Annotations) -> None:
    for fn in node.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if fn.name == "__init__":
            continue        # construction is thread-private by publication
        _MethodWalker(audit, ann, fn.name,
                      audit.method_requires.get(fn.name)).walk(fn.body)


def _bind_attr_classes(audit: _ClassAudit, node: ast.ClassDef,
                       known_classes: set) -> None:
    for fn in node.body:
        if not (isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                and fn.name == "__init__"):
            continue
        for st in ast.walk(fn):
            if not isinstance(st, ast.Assign):
                continue
            for t in st.targets:
                name = _self_attr(t)
                if name is None:
                    continue
                bound = _bound_class(st.value, known_classes)
                if bound is not None:
                    audit.attr_classes[name] = bound


# ---------------------------------------------------------------------------
# per-class checking
# ---------------------------------------------------------------------------

def _check_class(audit: _ClassAudit) -> list[Diagnostic]:
    out: list[Diagnostic] = []
    f = audit.filename

    def emit(code: str, severity: Severity, line: int, detail: str) -> None:
        out.append(diag(code, severity, file=f, line=line,
                        detail=f"{audit.name}: {detail}"))

    by_attr: dict[str, list[_Access]] = {}
    for acc in audit.accesses:
        by_attr.setdefault(acc.attr, []).append(acc)

    for name, info in sorted(audit.attrs.items()):
        if info.is_lock:
            continue
        accesses = by_attr.get(name, [])
        writes = [a for a in accesses if a.kind == "write"]
        if not writes:
            continue            # set in __init__, read-only after: immutable
        if info.lock_free is not None:
            if not info.lock_free:
                emit(AnalysisCode.LOCK_FREE_NO_REASON, Severity.ERROR,
                     info.init_line or writes[0].line,
                     f"attribute '{name}' waived without a reason")
            continue            # deliberately unlocked: schedfuzz's job
        guard = info.guard
        if guard is None:
            # Eraser-style inference: the intersection of locks held over
            # every (unwaived) write site
            locksets = [a.held for a in writes if not a.waived]
            common = (locksets[0].intersection(*locksets[1:])
                      if locksets else frozenset())
            emit(AnalysisCode.UNANNOTATED_SHARED_ATTR, Severity.WARNING,
                 info.init_line or writes[0].line,
                 f"shared attribute '{name}' has no guarded-by/lock-free "
                 f"annotation (inferred guard: "
                 f"{sorted(common) if common else 'NONE'})")
            for a in writes:
                if a.waived:
                    continue
                if not a.held:
                    emit(AnalysisCode.UNGUARDED_SHARED_WRITE, Severity.ERROR,
                         a.line,
                         f"write to '{name}' in {a.method}() holds no lock")
            if not common and all(a.held or a.waived for a in writes):
                distinct = sorted({tuple(sorted(a.held)) for a in writes
                                   if not a.waived})
                if len(distinct) > 1:
                    emit(AnalysisCode.INCONSISTENT_GUARD, Severity.ERROR,
                         writes[-1].line,
                         f"'{name}' is written under disjoint locks "
                         f"{distinct}: no common guard exists")
            continue
        if guard not in audit.locks:
            emit(AnalysisCode.INCONSISTENT_GUARD, Severity.ERROR,
                 info.init_line or writes[0].line,
                 f"'{name}' declares guard '{guard}' but {audit.name} owns "
                 f"no such lock (locks: {sorted(audit.locks)})")
            continue
        for a in accesses:
            if a.waived or guard in a.held:
                continue
            if a.kind == "write":
                if a.held:
                    emit(AnalysisCode.INCONSISTENT_GUARD, Severity.ERROR,
                         a.line,
                         f"write to '{name}' in {a.method}() holds "
                         f"{sorted(a.held)}, not its declared guard "
                         f"'{guard}'")
                else:
                    emit(AnalysisCode.UNGUARDED_SHARED_WRITE, Severity.ERROR,
                         a.line,
                         f"write to '{name}' in {a.method}() without its "
                         f"declared guard '{guard}'")
            else:
                emit(AnalysisCode.UNGUARDED_SHARED_READ, Severity.WARNING,
                     a.line,
                     f"read of '{name}' in {a.method}() without its "
                     f"declared guard '{guard}'")

    for method, line, (callee, req) in audit.requires_violations:
        emit(AnalysisCode.UNGUARDED_SHARED_WRITE, Severity.ERROR, line,
             f"{method}() calls {callee}() which requires-lock '{req}' "
             f"without holding it")

    for dotted, line, held in audit.blocking:
        emit(AnalysisCode.BLOCKING_CALL_UNDER_LOCK, Severity.ERROR, line,
             f"blocking call {dotted}(...) while holding {sorted(held)}")

    return out


# ---------------------------------------------------------------------------
# the lock acquisition-order graph
# ---------------------------------------------------------------------------

def _lock_graph_report(audits: list[_ClassAudit]) -> tuple[list, list,
                                                           list[Diagnostic]]:
    """(edge rows, cycles, diagnostics) for the acquisition-order graph."""
    by_name = {a.name: a for a in audits}
    edges: dict[tuple, tuple] = {}
    out: list[Diagnostic] = []
    for a in audits:
        for frm, to, line in a.intra_edges:
            if frm == to:
                if a.lock_kind(frm) not in _REENTRANT_CTORS:
                    out.append(diag(
                        AnalysisCode.LOCK_ORDER_CYCLE, Severity.ERROR,
                        file=a.filename, line=line,
                        detail=(f"{a.name}: re-acquiring non-reentrant lock "
                                f"'{frm}' while holding it: self-deadlock")))
                continue
            edges.setdefault((f"{a.name}.{frm}", f"{a.name}.{to}"),
                             (a.filename, line))
        for held, attr, called, line in a.cross_calls:
            target = by_name.get(a.attr_classes.get(attr, ""))
            if target is None:
                continue
            for lk in target.method_acquires.get(called, ()):
                frm, to = f"{a.name}.{held}", f"{target.name}.{lk}"
                if frm != to:
                    edges.setdefault((frm, to), (a.filename, line))
    adj: dict[str, list[str]] = {}
    for (frm, to) in edges:
        adj.setdefault(frm, []).append(to)
    color: dict[str, int] = {}
    stack: list[str] = []
    cycles: list[list[str]] = []

    def dfs(n: str) -> None:
        color[n] = 1
        stack.append(n)
        for m in sorted(adj.get(n, ())):
            if color.get(m, 0) == 0:
                dfs(m)
            elif color.get(m) == 1:
                cyc = stack[stack.index(m):] + [m]
                if not any(set(c) == set(cyc) for c in cycles):
                    cycles.append(cyc)
        stack.pop()
        color[n] = 2

    for n in sorted(adj):
        if color.get(n, 0) == 0:
            dfs(n)
    for cyc in cycles:
        loc = edges.get((cyc[0], cyc[1]))
        out.append(diag(AnalysisCode.LOCK_ORDER_CYCLE, Severity.ERROR,
                        file=loc[0] if loc else None,
                        line=loc[1] if loc else None,
                        detail="acquisition-order cycle "
                               + " -> ".join(cyc)))
    edge_rows = [{"from": frm, "to": to, "file": fl, "line": ln}
                 for (frm, to), (fl, ln) in sorted(edges.items())]
    return edge_rows, cycles, out


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def _audit_sources(sources: list[tuple]) -> tuple[dict, list[Diagnostic]]:
    """Audit ``[(filename, source), ...]`` together (cross-file lock graph).
    Returns (report document, diagnostics)."""
    audits: list[_ClassAudit] = []
    parsed: list[tuple] = []
    for filename, source in sources:
        tree = ast.parse(source, filename=filename)
        ann = _Annotations(source)
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                audit = _parse_class(node, filename, ann)
                if audit.locks:
                    audits.append(audit)
                    parsed.append((audit, node, ann))
    known = {a.name for a in audits}
    for audit, node, ann in parsed:
        _bind_attr_classes(audit, node, known)
        _analyse_methods(audit, node, ann)
    diagnostics: list[Diagnostic] = []
    class_rows = []
    for audit in audits:
        found = _check_class(audit)
        diagnostics += found
        attr_rows = {}
        for name, info in sorted(audit.attrs.items()):
            if info.is_lock:
                continue
            accesses = [a for a in audit.accesses if a.attr == name]
            if not accesses and info.init_writes_only:
                continue
            attr_rows[name] = {
                "guard": info.guard,
                "lock_free": info.lock_free,
                "writes": sum(a.kind == "write" for a in accesses),
                "reads": sum(a.kind == "read" for a in accesses),
            }
        class_rows.append({
            "name": audit.name,
            "file": audit.filename,
            "line": audit.line,
            "locks": {k: v for k, v in sorted(audit.locks.items())},
            "attrs": attr_rows,
            "findings": len(found),
        })
    edge_rows, cycles, graph_diags = _lock_graph_report(audits)
    diagnostics += graph_diags
    report = {
        "files": len(sources),
        "classes": class_rows,
        "lock_graph": {"edges": edge_rows, "cycles": cycles},
        "findings": len(diagnostics),
    }
    return report, diagnostics


def audit_source(source: str, filename: str = "<string>") -> list[Diagnostic]:
    """Audit one module's source text (the mutation-harness entry point)."""
    _report, diagnostics = _audit_sources([(filename, source)])
    return diagnostics


def audit_paths(paths) -> tuple[dict, list[Diagnostic]]:
    """Audit ``.py`` files / directory trees together."""
    files: list[str] = []
    for path in paths:
        if os.path.isdir(path):
            for root, _dirs, names in os.walk(path):
                files.extend(os.path.join(root, f) for f in sorted(names)
                             if f.endswith(".py"))
        else:
            files.append(path)
    sources = []
    for f in files:
        with open(f, "r", encoding="utf-8") as fh:
            sources.append((f, fh.read()))
    return _audit_sources(sources)


def audit_package() -> tuple[dict, list[Diagnostic]]:
    """Audit the installed quest_tpu serve/deploy/obs trees (the
    ``--concurrency`` CLI target and the repo self-audit)."""
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return audit_paths([os.path.join(pkg_root, sub)
                        for sub in AUDIT_SUBPACKAGES])


# ---------------------------------------------------------------------------
# the adversarial mutation helper (mirrors PR 3's mutation-harness pattern)
# ---------------------------------------------------------------------------

def strip_first_lock_scope(source: str, lock: str = "_lock") -> str:
    """Return ``source`` with the FIRST ``with self.<lock>:`` statement
    removed and its body dedented in place — the adversarial self-test's
    mutation: the auditor must flag the newly unguarded accesses
    (tests/test_concurrency.py and the CI lint job both assert it)."""
    tree = ast.parse(source)
    target: ast.With | None = None
    for node in ast.walk(tree):
        if isinstance(node, ast.With) and target is None:
            for item in node.items:
                if _self_attr(item.context_expr) == lock:
                    target = node
                    break
    if target is None:
        raise ValueError(f"no 'with self.{lock}:' statement in source")
    lines = source.splitlines(keepends=True)
    body_col = target.body[0].col_offset
    dedent = body_col - target.col_offset
    out = []
    body_first = target.body[0].lineno
    body_last = target.end_lineno or body_first
    for i, line in enumerate(lines, 1):
        if i == target.lineno:
            continue                    # drop the `with self._lock:` line
        if body_first <= i <= body_last and line[:dedent].isspace():
            line = line[dedent:]
        out.append(line)
    return "".join(out)
