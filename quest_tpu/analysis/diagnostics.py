"""Structured diagnostics shared by the three static-analysis passes.

The runtime validation layer (validation.py) mirrors the reference's
QuEST_validation.c: symbolic ``E_*`` codes raised as exceptions at call time.
The analysis passes report the SAME codes — a diagnostic that predicts a
runtime failure carries the exact ``ErrorCode`` the op would raise, so a CI
log line maps 1:1 onto the exception a production run would have died with.
Findings with no runtime twin (memory projections, eager/compiled drift,
purity lint) use analysis-only code families: ``A_*`` for circuit/abstract
analysis, ``H_*`` for optimization hints, ``P_*`` for source purity rules,
``V_*`` for the scheduler translation validator (analysis/equivalence.py),
``T_*`` for the concurrency lock-discipline audit (analysis/concurrency.py),
``O_*`` for the runtime ledgers (quest_tpu/obs), and ``S_*`` for the
compile-economics static checker (analysis/staticcheck.py): recompile
hazards, unlifted gate payloads, hot-path host syncs and f64 promotion.
"""

from __future__ import annotations

import dataclasses
import enum

from ..validation import MESSAGES as _ERROR_MESSAGES


class Severity(enum.IntEnum):
    """Ordering matters: the CLI fails on max(severity) >= ERROR."""
    HINT = 0
    WARNING = 1
    ERROR = 2


class AnalysisCode:
    """Analysis-only diagnostic codes (ErrorCode-style symbolic strings)."""

    # circuit-level projections (no runtime exception twin)
    STATE_EXCEEDS_MESH_MEMORY = "A_STATE_EXCEEDS_MESH_MEMORY"
    UNKNOWN_GATE_KIND = "A_UNKNOWN_GATE_KIND"
    INVALID_BIT_PERMUTATION = "A_INVALID_BIT_PERMUTATION"
    SCHEDULE_COMM_REGRESSION = "A_SCHEDULE_COMM_REGRESSION"
    OVERLAP_MODEL_REGRESSION = "A_OVERLAP_MODEL_REGRESSION"
    # eager-vs-compiled abstract-eval drift
    EAGER_COMPILED_DTYPE_MISMATCH = "A_EAGER_COMPILED_DTYPE_MISMATCH"
    EAGER_COMPILED_SHAPE_MISMATCH = "A_EAGER_COMPILED_SHAPE_MISMATCH"
    EAGER_COMPILED_SHARDING_MISMATCH = "A_EAGER_COMPILED_SHARDING_MISMATCH"
    OPERAND_DTYPE_DRIFT = "A_OPERAND_DTYPE_DRIFT"
    # translation validation of scheduler/optimizer rewrites (equivalence.py)
    SEMANTICS_CHANGED = "V_SEMANTICS_CHANGED"
    UNVERIFIED_REGION = "V_UNVERIFIED_REGION"
    # lowered-jaxpr / compiled-HLO audit (jaxpr_audit.py)
    COLLECTIVE_COUNT_MISMATCH = "A_COLLECTIVE_COUNT_MISMATCH"
    UNEXPECTED_ALLGATHER = "A_UNEXPECTED_ALLGATHER"
    DONATION_UNUSED = "A_DONATION_UNUSED"
    COLLECTIVE_NOT_OVERLAPPED = "A_COLLECTIVE_NOT_OVERLAPPED"
    # deployment-shape projections (parallel/planner.py)
    SUBTILE_SHARD = "A_SUBTILE_SHARD"
    # serving-layer parameter-lift audit (analysis/serve_audit.py)
    PARAM_LIFT_DIVERGENCE = "A_PARAM_LIFT_DIVERGENCE"
    # model-vs-measured runtime ledger (quest_tpu/obs/ledger.py); the code
    # string is defined there — the ledger must warn without importing the
    # analysis package
    MODEL_DRIFT = "O_MODEL_DRIFT"
    # numeric drift ledger (quest_tpu/obs/numerics.py); code strings
    # defined there for the same reason
    NUMERIC_DRIFT = "O_NUMERIC_DRIFT"
    NUMERIC_NAN = "O_NUMERIC_NAN"
    # probe purity contract of the --numeric-report mode: the instrumented
    # program's primary output must be bit-identical to the uninstrumented
    # one
    NUMERIC_PROBE_DIVERGENCE = "A_NUMERIC_PROBE_DIVERGENCE"
    # optimization hints
    ADJACENT_INVERSE_PAIR = "H_ADJACENT_INVERSE_PAIR"
    FUSABLE_1Q_RUN = "H_FUSABLE_1Q_RUN"
    # source purity lint
    TRACED_PYTHON_BRANCH = "P_TRACED_PYTHON_BRANCH"
    HOST_CAST_ON_TRACED = "P_HOST_CAST_ON_TRACED"
    NUMPY_ON_TRACED = "P_NUMPY_ON_TRACED"
    ANGLE_NOT_F64 = "P_ANGLE_NOT_F64"
    CALLBACK_IN_SHARD_MAP = "P_HOST_CALLBACK_IN_SHARD_MAP"
    IMPORT_TIME_STATE_MUTATION = "P_IMPORT_TIME_STATE_MUTATION"
    DAEMON_THREAD_LEAK = "P_DAEMON_THREAD_LEAK"
    # concurrency lock-discipline audit (analysis/concurrency.py) and its
    # dynamic twin, the schedule-fuzzing harness (analysis/schedfuzz.py)
    UNGUARDED_SHARED_WRITE = "T_UNGUARDED_SHARED_WRITE"
    UNGUARDED_SHARED_READ = "T_UNGUARDED_SHARED_READ"
    INCONSISTENT_GUARD = "T_INCONSISTENT_GUARD"
    LOCK_ORDER_CYCLE = "T_LOCK_ORDER_CYCLE"
    BLOCKING_CALL_UNDER_LOCK = "T_BLOCKING_CALL_UNDER_LOCK"
    UNANNOTATED_SHARED_ATTR = "T_UNANNOTATED_SHARED_ATTR"
    LOCK_FREE_NO_REASON = "T_LOCK_FREE_NO_REASON"
    SCHEDULE_FUZZ_FAILURE = "T_SCHEDULE_FUZZ_FAILURE"
    # compile-economics static checker (analysis/staticcheck.py) and its
    # jaxpr-side sibling pass (jaxpr_audit.py trace-diff helpers)
    UNLIFTED_LITERAL = "S_UNLIFTED_LITERAL"
    RECOMPILE_HAZARD = "S_RECOMPILE_HAZARD"
    HOST_SYNC_IN_HOT_PATH = "S_HOST_SYNC_IN_HOT_PATH"
    X64_PROMOTION = "S_X64_PROMOTION"
    CLASS_NOT_CLOSED = "S_CLASS_NOT_CLOSED"


ANALYSIS_MESSAGES = {
    AnalysisCode.STATE_EXCEEDS_MESH_MEMORY:
        "The statevector's per-device working set exceeds the device's HBM; "
        "the program will OOM at allocation. Shard over more devices or drop "
        "to precision 1.",
    AnalysisCode.UNKNOWN_GATE_KIND:
        "Unknown gate kind: _apply_one would raise ValueError at trace time.",
    AnalysisCode.INVALID_BIT_PERMUTATION:
        "A 'bitperm' op's destination payload is not a permutation of its "
        "target wires: apply_bit_permutation would fail its permutation "
        "assertion at trace time.",
    AnalysisCode.SCHEDULE_COMM_REGRESSION:
        "The comm-aware scheduler produced a circuit the planner models as "
        "MORE communication than the input (collectives or bytes over ICI "
        "increased): a scheduler cost-model regression.",
    AnalysisCode.OVERLAP_MODEL_REGRESSION:
        "The overlap-aware time model predicts the pipelined executor "
        "SLOWER than the serial schedule: chunking must never cost wall "
        "time in the model (hideable events pipeline to max(compute, comm) "
        "+ ramp; everything else stays serial), so this is an executor "
        "cost-model regression.",
    AnalysisCode.EAGER_COMPILED_DTYPE_MISMATCH:
        "Eager and compiled paths disagree on the output dtype of this op; "
        "the two paths would produce numerically different states.",
    AnalysisCode.EAGER_COMPILED_SHAPE_MISMATCH:
        "Eager and compiled paths disagree on the output shape of this op.",
    AnalysisCode.EAGER_COMPILED_SHARDING_MISMATCH:
        "Eager and compiled paths disagree on the output sharding of this op.",
    AnalysisCode.OPERAND_DTYPE_DRIFT:
        "The compiled path feeds this kernel an operand of a different dtype "
        "than the eager API contract; eager and compiled states would drift "
        "(the circuit.py multiRotateZ f32-angle bug class).",
    AnalysisCode.SEMANTICS_CHANGED:
        "The rewritten circuit provably implements a DIFFERENT unitary than "
        "its input: a scheduler/optimizer correctness bug.  The abstract "
        "domains (Pauli tableau / phase polynomial / dense window) found a "
        "concrete disagreement witness.",
    AnalysisCode.UNVERIFIED_REGION:
        "The translation validator could not prove this rewritten region "
        "equivalent: every abstract domain lost precision (non-Clifford, "
        "non-diagonal, window too wide for the dense check).  Not a proven "
        "bug — but this rewrite is running without a semantics proof.",
    AnalysisCode.COLLECTIVE_COUNT_MISMATCH:
        "The lowered program contains MORE collectives than the planner's "
        "comm model predicts for this circuit: the comm model and XLA's "
        "partitioner disagree, so scheduler decisions are being made "
        "against a wrong cost model.",
    AnalysisCode.UNEXPECTED_ALLGATHER:
        "The lowered program gathers state-sized data although the planner "
        "models the circuit as communication-free: a sharding annotation "
        "has been lost and the state is round-tripping through a gather.",
    AnalysisCode.DONATION_UNUSED:
        "A donate=True program compiled WITHOUT an input/output buffer "
        "alias: the donation is silently ignored and iteration pays a full "
        "extra state allocation per step.",
    AnalysisCode.COLLECTIVE_NOT_OVERLAPPED:
        "The compiled program issues a collective the overlap plan expected "
        "to hide with NO async start/done separation around it: the "
        "backend serialised communication against compute, so the "
        "pipelined executor's chunking buys no wall time here (expected on "
        "CPU meshes; a regression on TPU).",
    AnalysisCode.SUBTILE_SHARD:
        "Each per-device shard is smaller than one full 128-lane row: "
        "kernel reshapes re-tile across devices even for gates the "
        "wire-position comm model rates shard-local, so every dense gate "
        "is charged the 'subtile' comm class. Use fewer devices (or more "
        "qubits) so a shard holds at least one lane row.",
    AnalysisCode.PARAM_LIFT_DIVERGENCE:
        "The serve cache's parameter-lifted program for this structural "
        "class diverges from the eager per-circuit path: the skeleton + "
        "operand-vector reconstruction is not provably the same circuit "
        "(translation-validator witness), the lifted (state, params) "
        "executable disagrees with the eager oracle on a probe state, or "
        "an angle-perturbed twin failed to share the class's cache entry. "
        "Serving would return wrong amplitudes for EVERY request of the "
        "class.",
    AnalysisCode.MODEL_DRIFT:
        "The measured runtime of this compiled program left the planner "
        "model's calibrated band (wall-clock ratio on calibrated hardware, "
        "or compiled-HLO collectives beyond the per-event lowering bound): "
        "scheduling/engine decisions are being made against a model that "
        "no longer describes this deployment — re-calibrate "
        "MEASURED_EFFICIENCY or investigate the partitioner "
        "(docs/OBSERVABILITY.md).",
    AnalysisCode.NUMERIC_DRIFT:
        "A numeric probe measured norm/trace drift (or a Hermiticity "
        "deviation) outside the precision-and-depth-derived ulp-growth "
        "band: a kernel on this backend is not norm-preserving — the "
        "wrong-norms-on-chip symptom class of the f64 X64-rewriter "
        "miscompiles (docs/OBSERVABILITY.md 'Numeric health').",
    AnalysisCode.NUMERIC_NAN:
        "A numeric probe observed NaN/Inf amplitudes in a result "
        "register: the state is poisoned and every downstream consumer "
        "of this structural class is being served garbage.  The serve "
        "flight ring dumps on the first such outcome and the deploy "
        "router quarantines the (class, replica) placement.",
    AnalysisCode.NUMERIC_PROBE_DIVERGENCE:
        "The probe-instrumented program's PRIMARY output differs from "
        "the uninstrumented program's: a probe leaked into the main "
        "dataflow instead of being grafted beside it, so probed serving "
        "would change tenants' answers.  Probes must be pure reductions "
        "(obs/numerics.py).",
    AnalysisCode.ADJACENT_INVERSE_PAIR:
        "Adjacent gates on identical wires compose to the identity and can "
        "be cancelled.",
    AnalysisCode.FUSABLE_1Q_RUN:
        "A run of consecutive single-qubit gates on one target can be fused "
        "into a single 2x2 matrix (one HBM pass instead of one per gate); "
        "see Circuit.optimize().",
    AnalysisCode.TRACED_PYTHON_BRANCH:
        "Python control flow on a traced value inside a jitted function: the "
        "branch is resolved at trace time, not per element. Use jnp.where / "
        "lax.cond, or mark the argument static.",
    AnalysisCode.HOST_CAST_ON_TRACED:
        "Host cast (float/int/bool) on a traced value inside a jitted "
        "function: this forces a trace-time ConcretizationTypeError or a "
        "silent host round-trip.",
    AnalysisCode.NUMPY_ON_TRACED:
        "numpy call on a traced value inside a jitted function: np.* "
        "executes at trace time on the host and freezes the value into the "
        "compiled program. Use the jnp equivalent.",
    AnalysisCode.ANGLE_NOT_F64:
        "apply_multi_rotate_z angle operand is cast to a non-float64 dtype; "
        "the eager API passes float64 (api.py multiRotateZ), so a narrower "
        "cast here makes compiled f32 states drift from eager ones.",
    AnalysisCode.CALLBACK_IN_SHARD_MAP:
        "Host callback inside a shard_map region: the callback runs "
        "per-shard on every device and serialises the collective schedule.",
    AnalysisCode.IMPORT_TIME_STATE_MUTATION:
        "Module-import-time mutation of process-global state (jax.config, "
        "global RNG state, or process hooks like atexit.register): import "
        "order silently changes behaviour for every consumer of the "
        "process.  Allowlisted sites only: quest_tpu/_compat.py (the x64 "
        "default) and quest_tpu/obs/trace.py (the span recorder's "
        "crash-dump hook).",
    AnalysisCode.DAEMON_THREAD_LEAK:
        "A threading.Thread started in serve/ or deploy/ is neither joined "
        "on a shutdown()/close() path nor daemonized with a '# daemon-ok: "
        "<reason>' comment: the deployment would leak a worker (or block "
        "interpreter exit) every time this code path runs.",
    AnalysisCode.UNGUARDED_SHARED_WRITE:
        "A shared instance attribute of a lock-owning class is written "
        "without holding its guard lock (declared '# guarded-by:' or "
        "inferred from the other write sites): a concurrent reader or "
        "writer can observe a torn or lost update.  Hold the guard, or "
        "annotate the attribute '# lock-free: <reason>' if the unlocked "
        "access is deliberate.",
    AnalysisCode.UNGUARDED_SHARED_READ:
        "A guarded shared attribute is read without its guard lock: the "
        "read can observe mid-update state.  Take the guard, or waive the "
        "site with '# lock-free: <reason>' when the tear is tolerated by "
        "construction (e.g. a single-word hot-path gauge).",
    AnalysisCode.INCONSISTENT_GUARD:
        "The same shared attribute is accessed under DIFFERENT locks at "
        "different sites: no single lock serialises its writers, so the "
        "locking provides no mutual exclusion at all for this attribute.",
    AnalysisCode.LOCK_ORDER_CYCLE:
        "The cross-class lock acquisition-order graph contains a cycle: "
        "two threads taking the locks in opposite orders deadlock.  Break "
        "the cycle by moving one call outside the lock region (or by "
        "imposing one global acquisition order).",
    AnalysisCode.BLOCKING_CALL_UNDER_LOCK:
        "A blocking operation (compile/dispatch, Future.result, sleep, "
        "thread join, non-condition wait) executes inside a lock region on "
        "the serving hot path: every thread contending for the lock stalls "
        "behind device or wall-clock latency.  Move the blocking work "
        "outside the lock (copy state in, publish results after).",
    AnalysisCode.UNANNOTATED_SHARED_ATTR:
        "A mutable shared attribute of a lock-owning class carries neither "
        "'# guarded-by: <lock>' nor '# lock-free: <reason>' on its "
        "initialising assignment: the lock discipline for it is undeclared "
        "and cannot be machine-checked (docs/ANALYSIS.md pass 7).",
    AnalysisCode.LOCK_FREE_NO_REASON:
        "A '# lock-free:' annotation with an EMPTY reason string: the "
        "waiver exists to record WHY the unlocked access is safe (torn-read "
        "tolerance, single-word store, set-once-before-traffic); an "
        "unreasoned waiver is a refused waiver.",
    AnalysisCode.SCHEDULE_FUZZ_FAILURE:
        "The schedule-fuzzing harness (analysis/schedfuzz.py) drove a "
        "forced thread interleaving in which a lock-free read surface "
        "returned an internally inconsistent snapshot or a concurrent "
        "operation raised: a real runtime race, not a static projection.",
    AnalysisCode.UNLIFTED_LITERAL:
        "A continuous gate parameter (angle / channel probability) is a "
        "Python literal at the builder call site: served through an opaque "
        "class (overlap or pallas engine, where payloads are NOT lifted "
        "into the param_vector operand) the literal becomes a compiled "
        "constant and every distinct value compiles its own XLA program — "
        "the 'cached but not lifted' regression class.  Bind the value "
        "from data, or waive a deliberately fixed circuit with "
        "'# unlifted-ok: <reason>'.",
    AnalysisCode.RECOMPILE_HAZARD:
        "A jit boundary is keyed so that routine inputs change the compile "
        "key: a jax.jit wrapper constructed and invoked per call (a fresh "
        "cache per invocation), or a float literal passed to a declared "
        "static argument (one compiled program PER VALUE of a continuous "
        "knob).  Hoist the wrapper / make the argument an operand, or "
        "waive with '# recompile-ok: <reason>'.",
    AnalysisCode.HOST_SYNC_IN_HOT_PATH:
        "A host-synchronising call (.item(), block_until_ready, "
        "jax.device_get, np.asarray/np.array) executes on the serve/deploy "
        "submission hot path: if the value is a device array the submitter "
        "thread blocks on a device transfer, adding device latency to "
        "EVERY tenant's admission — the worker thread owns device waits, "
        "the submitter must not.  Move it behind the queue, or waive a "
        "provably-host value with '# host-sync-ok: <reason>'.",
    AnalysisCode.X64_PROMOTION:
        "A float64-forcing dtype flow inside a traced function (a NumPy "
        "strong-typed scalar mixed into traced arithmetic, or an explicit "
        ".astype(float64)): under x64 this silently promotes f32 programs "
        "to f64 before TPU lowering — straight into the XLA:TPU "
        "X64-rewriter miscompile wall (ROADMAP item 3).  Use weak Python "
        "scalars / jnp casts tied to the state dtype, or waive a "
        "deliberate f64 path with '# x64-ok: <reason>'.",
    AnalysisCode.CLASS_NOT_CLOSED:
        "Re-tracing this served structural class with a perturbed operand "
        "vector changed the program itself (a trace constant, literal or "
        "equation differs, or the perturbed twin missed the cache entry): "
        "the class is not closed over its parameters, so EVERY request "
        "with new angles recompiles — one XLA program per request instead "
        "of one per class (serve/cache.py's core economic invariant).",
}


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One analysis finding.  ``code`` is an ErrorCode or AnalysisCode
    string; location is an op index (circuit passes) or file:line (lint)."""

    code: str
    severity: Severity
    message: str
    op_index: int | None = None
    file: str | None = None
    line: int | None = None

    @property
    def location(self) -> str:
        if self.file is not None:
            return f"{self.file}:{self.line}" if self.line else self.file
        if self.op_index is not None:
            return f"op[{self.op_index}]"
        return "<circuit>"

    def format(self) -> str:
        return f"{self.severity.name.lower()}[{self.code}] {self.location}: {self.message}"


def message_for(code: str) -> str:
    """Canonical text for any diagnostic code: validation's MESSAGES for the
    shared ``E_*`` codes, the analysis table for the rest."""
    return ANALYSIS_MESSAGES.get(code) or _ERROR_MESSAGES.get(code) or code


def diag(code: str, severity: Severity, *, op_index: int | None = None,
         file: str | None = None, line: int | None = None,
         detail: str | None = None) -> Diagnostic:
    msg = message_for(code)
    if detail:
        msg = f"{msg} [{detail}]"
    return Diagnostic(code, severity, msg, op_index=op_index, file=file,
                      line=line)


def max_severity(diagnostics) -> Severity | None:
    worst = None
    for d in diagnostics:
        if worst is None or d.severity > worst:
            worst = d.severity
    return worst
