"""The deploy subsystem's selftest: a multi-replica storm with teeth.

Drives a :class:`~quest_tpu.deploy.pool.ReplicaPool` (>= 2 replicas, one
shared persistent executable store) with the serve selftest's synthetic
tenant mix and gates the properties that make a deployment a deployment:

- **Bit-identity.**  Every routed request's state equals the single-replica
  serial execution of the same circuit — replication must never change a
  tenant's answer, whichever replica served it.
- **Cache economics.**  Aggregate hit rate >= 0.9 after warm-up: affinity
  placement is keeping each class's one-executable-per-class cache hot on
  one replica (a spraying router would pay one miss per class PER replica).
- **Cold start.**  A fresh replica warmed from the persistent store must
  reach first-result-per-class STRICTLY faster than a cold-compiled one,
  with ZERO compiles (obs/counters.py compile counters + the cache's own
  ``compiles`` stat — persisted executables really are executables, not
  recompile hints).
- **Shed path.**  With one replica's queue artificially saturated,
  deadline-carrying requests route to the next-best affinity candidate and
  the deployment's deadline hit rate stays ABOVE the single-saturated-
  replica baseline measured in the same run.
- **One scrape.**  The merged Prometheus document parses and carries
  per-replica labeled series (``{replica="i"}``).
- **Traceability** (``--trace``).  The run exports through the
  cross-process merge path with zero schema problems and a ``deploy.route``
  span per routed submit.

Multi-process mode (the CI ``deploy-selftest`` job): N processes under one
``jax.distributed`` coordinator each run the full local selftest against
ONE shared store, save their trace shard and per-process document to a
sync directory, and process 0 merges the shards into one validated trace
and aggregates every process's verdict into the final JSON.  The worker
processes exercise ``broadcast_hot_keys`` (degrading gracefully where the
backend cannot collective — the pinned CPU jaxlib) and the shared-store
write races (atomic renames converge).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

__all__ = ["run_selftest", "coldstart_compare", "shed_gate"]

_SEED = 11


def _check(checks: dict, name: str, ok: bool, detail: str = "") -> bool:
    checks[name] = {"ok": bool(ok), "detail": detail}
    return bool(ok)


def coldstart_compare(store_dir: str, classes: list,
                      dtype=None) -> dict:
    """Warm-vs-cold replica cold start over ``classes`` (a list of
    ``(label, circuit)`` representatives): seconds from cache construction
    to one completed request per class, plus the compile evidence.

    The WARM side attaches the shared store and bulk-loads it before
    serving (load time is charged to its cold-start — that is the honest
    deployment cost); the COLD side compiles every class from scratch.
    Process-wide compile counters (obs/counters.py) are sampled around
    each side so "warm skipped the compiles" is asserted against the same
    instrument bench rows use, not just this cache's own bookkeeping.

    A PRODUCER cache first serves the probe shapes once with the store
    attached — the warm peer whose traffic persisted these executables
    (a storm that only ever batched would persist only batch-shaped
    programs, and a warm-up can only skip compiles whose shapes a peer
    actually served)."""
    import jax.numpy as jnp

    from .. import obs as _obs
    from ..serve.cache import CompileCache
    from .persist import ExecutableStore

    def first_results(cache) -> float:
        t0 = time.perf_counter()
        for _label, circ in classes:
            st = jnp.zeros((2, 1 << circ.num_qubits),
                           jnp.float64 if dtype is None else dtype
                           ).at[0, 0].set(1.0)
            out = cache.execute(circ.key(), st, num_qubits=circ.num_qubits)
            out.block_until_ready()
        return time.perf_counter() - t0

    producer = CompileCache().attach_store(ExecutableStore(store_dir))
    first_results(producer)
    report: dict = {}
    for mode in ("cold", "warm"):
        cache = CompileCache()
        before = _obs.global_counters().snapshot()["compiles_total"]
        t0 = time.perf_counter()
        if mode == "warm":
            store = ExecutableStore(store_dir, readonly=True)
            warmed = store.warm(cache)
        else:
            warmed = None
        serve_s = first_results(cache)
        total_s = time.perf_counter() - t0
        after = _obs.global_counters().snapshot()["compiles_total"]
        report[mode] = {
            "coldstart_seconds": total_s,
            "first_results_seconds": serve_s,
            "compiles": cache.stats["compiles"],
            "global_compiles_delta": after - before,
            "persist_hits": cache.stats["persist_hits"],
            "persist_stale": cache.stats["persist_stale"],
            "warmed": warmed,
        }
    report["speedup"] = (report["cold"]["coldstart_seconds"]
                         / max(report["warm"]["coldstart_seconds"], 1e-9))
    return report


def shed_gate(probe_circuit, *, num_replicas: int = 2,
              deadline_ms: float = 60_000.0, probes: int = 8,
              fillers: int = 29, max_queue: int = 32) -> dict:
    """The router-shed proof, baseline included.

    **Baseline**: ``probes`` deadline-carrying requests queued into ONE
    saturated, paused service whose deadlines expire before the worker
    starts — the hit rate a deployment would see if it kept routing into
    the saturated replica.  **Deployment**: a paused pool where the probe
    class's affinity replica is prefilled past the shed threshold; the
    router must place every deadline'd probe on another replica, and once
    the pool runs, every probe completes in budget."""
    from ..circuit import random_circuit
    from ..serve.service import QuESTService
    from ..validation import QuESTError
    from .pool import ReplicaPool

    # baseline: the single saturated replica
    svc = QuESTService(max_batch=4, max_queue=max_queue, seed=_SEED,
                      start=False)
    base_futs = []
    for _ in range(probes):
        base_futs.append(svc.submit(probe_circuit, deadline_ms=40.0))
    time.sleep(0.25)                      # every deadline expires queued
    svc.start()
    svc.drain(timeout=120)
    base_hits = sum(1 for f in base_futs
                    if f.exception() is None)
    baseline_rate = base_hits / probes
    svc.shutdown()

    pool = ReplicaPool(num_replicas, max_batch=4, max_queue=max_queue,
                       seed=_SEED, start=False)
    try:
        ck = pool.router.class_key(probe_circuit)
        affinity = pool.router.candidates(ck)[0]
        sat_replica = next(r for r in pool.replicas if r.index == affinity)
        filler = random_circuit(4, depth=1, seed=1)
        for _ in range(fillers):
            try:
                sat_replica.service.submit(filler)
            except QuESTError:
                break
        saturation = sat_replica.service.queue_saturation()
        # the decision itself, not the placement table: a shed deliberately
        # leaves stickiness untouched so affinity returns after recovery
        _r, decision = pool.router.route(probe_circuit,
                                         deadline_ms=deadline_ms)
        routed_away = (decision["replica"] != affinity
                       and bool(decision["shed_from"]))
        probe_futs = [pool.submit(probe_circuit, deadline_ms=deadline_ms)
                      for _ in range(probes)]
        pool.start()
        pool.drain(timeout=240)
        hits = sum(1 for f in probe_futs if f.exception() is None
                   and f.result().state is not None)
        shed_count = pool.metrics.counter_total("shed_total")
        return {
            "baseline_hit_rate": baseline_rate,
            "deployment_hit_rate": hits / probes,
            "affinity_replica": affinity,
            "affinity_saturation": saturation,
            "routed_away": bool(routed_away),
            "shed_decisions": shed_count,
            "probes": probes,
        }
    finally:
        pool.shutdown(drain=True, timeout=120)


def run_selftest(as_json: bool = False, scale: int = 1,
                 replicas: int = 2, store_dir: str | None = None,
                 trace: bool | None = None,
                 sync_dir: str | None = None,
                 process_index: int = 0, process_count: int = 1) -> int:
    """Run the deployment storm; print the verdict (human text, or ONE
    JSON document with ``--json``).  Returns the process exit status:
    0 iff every check passed (in multi-process mode, on process 0: iff
    every PROCESS passed and the shards merged into a valid trace)."""
    import shutil
    import tempfile

    own_store = store_dir is None
    if own_store:
        store_dir = tempfile.mkdtemp(prefix="quest_deploy_store_")
    try:
        return _run_selftest(as_json=as_json, scale=scale,
                             replicas=replicas, store_dir=store_dir,
                             trace=trace, sync_dir=sync_dir,
                             process_index=process_index,
                             process_count=process_count)
    finally:
        if own_store:
            shutil.rmtree(store_dir, ignore_errors=True)


def _write_json_atomic(path: str, obj) -> None:
    """A rendezvous file must never be readable half-written: a peer
    treats its existence as 'ready'."""
    import tempfile

    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               suffix=".tmp")
    with os.fdopen(fd, "w", encoding="utf-8") as fh:
        json.dump(obj, fh, default=float)
    os.replace(tmp, path)


def _run_selftest(as_json: bool, scale: int, replicas: int, store_dir: str,
                  trace: bool | None, sync_dir: str | None,
                  process_index: int, process_count: int) -> int:
    import jax.numpy as jnp

    from .. import obs as _obs
    from ..serve.cache import CompileCache
    from ..serve.metrics import parse_prometheus
    from ..serve.selftest import workload_classes
    from .pool import ReplicaPool, broadcast_hot_keys

    def echo(line: str) -> None:
        if not as_json:
            print(line)

    multiproc = process_count > 1
    if trace is None:
        trace = os.environ.get("QUEST_TPU_TRACE") == "1" or multiproc
    if trace:
        _obs.enable_tracing()
        _obs.reset_tracing()

    checks: dict = {}
    ok = True

    # --- the storm through the pool ---------------------------------------
    from ..obs.slo import SLOConfig
    pool = ReplicaPool(replicas, store_dir=store_dir, max_batch=16,
                       max_delay_ms=10, seed=_SEED, start=False,
                       slo=SLOConfig(window_s=3600.0))
    ok &= _check(checks, "replicas", len(pool.replicas) >= 2,
                 f"{len(pool.replicas)} replicas (need >= 2)")
    classes = workload_classes(scale)
    submitted = []
    longest = max(len(cs) for _, cs, _ in classes)
    for i in range(longest):
        for label, circuits, shots in classes:
            if i < len(circuits):
                deadline = 600_000.0 if label == "qft8" else None
                submitted.append(
                    (label, circuits[i],
                     pool.submit(circuits[i], shots=shots,
                                 deadline_ms=deadline)))
    pool.start()
    ok &= _check(checks, "drain", pool.drain(timeout=600),
                 f"{len(submitted)} routed requests drained")

    # bit-identity vs the single-replica serial execution (one fresh cache
    # outside the pool = exactly what one QuESTService would compute)
    oracle = CompileCache()
    seen: set = set()
    exact = True
    for label, circ, fut in submitted:
        try:
            res = fut.result(timeout=60)
        except Exception:
            continue               # counted by the no_failures check below
        if label in seen:
            continue
        seen.add(label)
        st = jnp.zeros((2, 1 << circ.num_qubits),
                       jnp.float64).at[0, 0].set(1.0)
        want = np.asarray(oracle.execute(circ.key(), st,
                                         num_qubits=circ.num_qubits))
        if not np.array_equal(res.state, want):
            exact = False
            echo(f"FAIL {label}: routed state != single-replica serial "
                 f"(max |diff| {np.abs(res.state - want).max():.3g})")
    failed = 0
    for _, _, f in submitted:
        try:
            failed += f.exception(timeout=60) is not None
        except Exception:          # not done / cancelled: also a failure
            failed += 1
    ok &= _check(checks, "results_bit_identical_to_single_replica", exact,
                 f"{len(seen)} classes checked against the serial oracle")
    ok &= _check(checks, "no_failures", failed == 0,
                 f"{failed} failed futures of {len(submitted)}")

    # aggregate cache economics across the pool
    hits = sum(r.cache.stats["hits"] for r in pool.replicas)
    misses = sum(r.cache.stats["misses"] for r in pool.replicas)
    rate = hits / (hits + misses) if hits + misses else 0.0
    ok &= _check(checks, "cache_hit_rate", rate >= 0.9,
                 f"aggregate hit rate {rate:.3f} over {hits + misses} "
                 f"lookups across {len(pool.replicas)} replica caches")

    # the labeled one-scrape contract
    prom = pool.prometheus()
    try:
        parsed = parse_prometheus(prom)
        routed = parsed.get("quest_serve_routed_total", {})
        labeled = [ls for ls in routed if "replica=" in ls]
        per_replica = parsed.get("quest_serve_cache_hit_rate", {})
        ok &= _check(checks, "prometheus_labeled",
                     bool(labeled) and len(per_replica) >= len(pool.replicas),
                     f"{len(parsed)} families; routed_total labels "
                     f"{sorted(routed)}; {len(per_replica)} per-replica "
                     "cache_hit_rate series")
    except ValueError as exc:
        ok &= _check(checks, "prometheus_labeled", False, str(exc))

    # persistence happened and nothing was refused mid-run
    store_snap = pool.store.snapshot()
    stale = sum(r.cache.stats["persist_stale"] for r in pool.replicas)
    ok &= _check(checks, "store_populated",
                 store_snap["entries"] > 0 and stale == 0,
                 f"{store_snap['entries']} persisted executables, "
                 f"{stale} stale refusals")

    # hot-key broadcast (collective where the backend can, local echo
    # where it cannot — both prove the plumbing end-to-end)
    hot = broadcast_hot_keys(pool.hot_keys())
    ok &= _check(checks, "hot_keys_broadcast", len(hot) > 0,
                 f"{len(hot)} hot keys published")

    metrics = pool.metrics_dict()
    router_snap = pool.router.snapshot()
    pool.shutdown()

    # --- cold start: warm-loaded vs cold-compiled replica ------------------
    reps = [(label, cs[0]) for label, cs, _ in classes]
    cold = coldstart_compare(store_dir, reps)
    ok &= _check(
        checks, "coldstart_warm_beats_cold",
        cold["warm"]["coldstart_seconds"] < cold["cold"]["coldstart_seconds"]
        and cold["warm"]["compiles"] == 0
        and cold["warm"]["global_compiles_delta"] == 0
        and cold["warm"]["persist_hits"] > 0
        and cold["cold"]["compiles"] >= len(reps),
        f"warm {cold['warm']['coldstart_seconds']:.3f}s "
        f"({cold['warm']['compiles']} compiles, "
        f"{cold['warm']['persist_hits']} persisted loads) vs cold "
        f"{cold['cold']['coldstart_seconds']:.3f}s "
        f"({cold['cold']['compiles']} compiles): {cold['speedup']:.1f}x")

    # --- the shed path -----------------------------------------------------
    from ..circuit import qft_circuit
    shed = shed_gate(qft_circuit(8), num_replicas=max(2, replicas))
    ok &= _check(
        checks, "shed_path",
        shed["routed_away"] and shed["shed_decisions"] > 0
        and shed["deployment_hit_rate"] > shed["baseline_hit_rate"],
        f"saturated replica {shed['affinity_replica']} "
        f"(saturation {shed['affinity_saturation']:.2f}) shed "
        f"{shed['shed_decisions']:.0f} decision(s); deployment hit rate "
        f"{shed['deployment_hit_rate']:.2f} > saturated baseline "
        f"{shed['baseline_hit_rate']:.2f}")

    # --- trace export ------------------------------------------------------
    trace_doc = None
    shard = None
    if trace:
        shard = _obs.process_shard()
        trace_doc = _obs.merge_shards([shard])
        problems = _obs.validate_chrome_trace(trace_doc)
        route_spans = [e for e in trace_doc["traceEvents"]
                       if e.get("name") == "deploy.route"]
        ok &= _check(checks, "trace_valid",
                     not problems and len(route_spans) >= len(submitted),
                     f"{len(route_spans)} deploy.route span(s) (need >= "
                     f"{len(submitted)}), {len(problems)} schema problem(s)"
                     + (f"; first: {problems[0]}" if problems else ""))

    doc = {
        "ok": bool(ok),
        "process_index": process_index,
        "process_count": process_count,
        "checks": checks,
        "replicas": metrics["replicas"],
        "router": router_snap,
        "store": store_snap,
        "coldstart": cold,
        "shed": shed,
        "prometheus": prom,
        "hot_keys": hot,
    }
    if trace_doc is not None and not multiproc:
        doc["trace"] = trace_doc

    # --- multi-process rendezvous ------------------------------------------
    if multiproc:
        assert sync_dir, "multi-process mode needs --sync-dir"
        os.makedirs(sync_dir, exist_ok=True)
        _write_json_atomic(
            os.path.join(sync_dir, f"shard_p{process_index}.json"), shard)
        _write_json_atomic(
            os.path.join(sync_dir, f"selftest_p{process_index}.json"), doc)
        if process_index != 0:
            # worker verdict travels through its file; print it too
            print(json.dumps({"ok": doc["ok"], "process_index":
                              process_index}, default=float)
                  if as_json else f"process {process_index}: "
                  f"{'ok' if doc['ok'] else 'FAIL'}")
            return 0 if ok else 1
        # process 0: wait for every peer, merge, aggregate
        peers = {}
        shards = [shard]
        deadline = time.monotonic() + 300.0
        for p in range(1, process_count):
            spath = os.path.join(sync_dir, f"shard_p{p}.json")
            jpath = os.path.join(sync_dir, f"selftest_p{p}.json")
            # writes are atomic (tmp + rename), so a readable file is a
            # complete file — retry until both artifacts land or time out
            peer = peer_shard = last_exc = None
            while time.monotonic() < deadline:
                try:
                    with open(jpath, encoding="utf-8") as fh:
                        peer = json.load(fh)
                    peer_shard = _obs.load_shard(spath)
                    break
                except (OSError, ValueError) as exc:
                    last_exc = exc
                    time.sleep(0.2)
            if peer is None or peer_shard is None:
                ok &= _check(checks, f"peer_{p}", False,
                             f"peer artifacts unreadable: {last_exc}")
                continue
            peers[p] = peer
            shards.append(peer_shard)
            ok &= _check(checks, f"peer_{p}", bool(peers[p].get("ok")),
                         "peer selftest "
                         + ("passed" if peers[p].get("ok") else
                            json.dumps(peers[p].get("checks"))[:400]))
        merged = _obs.merge_shards(shards)
        problems = _obs.validate_chrome_trace(merged)
        pids = {e.get("pid") for e in merged["traceEvents"]}
        ok &= _check(checks, "merged_trace_valid",
                     not problems and len(shards) == process_count
                     and len(pids) >= process_count,
                     f"{len(shards)}/{process_count} shards merged into "
                     f"{len(pids)} process track(s), "
                     f"{len(problems)} schema problem(s)"
                     + (f"; first: {problems[0]}" if problems else ""))
        doc["ok"] = bool(ok)
        doc["peers"] = peers
        doc["trace"] = merged

    if as_json:
        print(json.dumps(doc, default=float))
    else:
        for name, r in checks.items():
            echo(f"[{'ok' if r['ok'] else 'FAIL'}] {name}: {r['detail']}")
        echo("--- coldstart ---")
        echo(json.dumps(cold, indent=1, default=float))
        echo("--- shed ---")
        echo(json.dumps(shed, indent=1, default=float))
        echo("--- prometheus (head) ---")
        echo("\n".join(prom.splitlines()[:40]))
    return 0 if ok else 1
