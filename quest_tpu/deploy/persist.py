"""Persistent AOT compile cache: serialized XLA executables on disk.

The serve-layer compile cache (serve/cache.py) holds exactly ONE
ahead-of-time-compiled executable per structural class — in process memory.
Every new process (a scaled-out replica, a restarted pod) pays the full XLA
compile for every class from scratch, which is exactly the cost that makes
scale-out expensive at pod scale.  This module makes the cache durable:

- Each compiled program is serialized through the XLA executable
  serialization path (``jax.experimental.serialize_executable``: the PJRT
  executable blob plus its arg/result trees) — NOT through ``jax.export``,
  whose deserialized StableHLO still pays the backend compile on load; the
  whole point here is that a warm replica compiles NOTHING.
- Entries are keyed by the cache's own identity — the structural class key
  (``Circuit.key(structural=True)`` + :class:`~quest_tpu.serve.cache.CacheOptions`)
  and the program tag (signature / batch shape / donation) — hashed into a
  filename, with the class's skeleton/operand-offset metadata carried
  alongside so a cold cache can re-materialize the full
  :class:`~quest_tpu.serve.cache.CacheEntry` without re-running the
  scheduler's search.
- Every file carries a PROVENANCE HEADER (jax/jaxlib versions, backend
  platform, device kind and count, the active calibration ``profile_id``
  from obs/calibrate.py) plus a SHA-256 of the payload.  Loading validates
  the header FIRST, against the live process (:func:`validate_entry_header`,
  mirroring ``calibrate.validate_profile``'s contract shape): any
  provenance mismatch or payload-digest mismatch REFUSES the entry — the
  consumer recompiles and counts a ``persist_stale`` miss.  An executable
  compiled under a different jaxlib is undefined behaviour at run time;
  refusing at load time is the bugfix-by-construction.  The payload is
  unpickled only AFTER the digest check passes, so a tampered file is
  rejected before any byte of it reaches the deserializer.

File layout (one file per program, atomic tmp+rename writes so concurrent
replicas can share one store directory):

    8-byte magic  | 4-byte big-endian header length | header JSON | payload

The payload is ``pickle((skey, tag, entry_meta, exe_bytes, in_tree,
out_tree))``.  Only ``jax.stages.Compiled`` programs persist; opaque
callables (overlap / Pallas-epoch classes, whose payloads are compiled in
host-side) are skipped and recorded as ``save_skipped`` — they recompile on
each process like before, documented in docs/DEPLOY.md.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import struct
import tempfile
import threading
import time

__all__ = ["STORE_FORMAT", "ExecutableStore", "entry_key",
           "live_provenance", "validate_entry_header"]

#: the store schema tag (bumped on incompatible changes)
STORE_FORMAT = "quest-tpu-executable-v1"

_MAGIC = b"QXCSTOR1"
_SUFFIX = ".qxc"

#: provenance fields that must match the live process EXACTLY for an entry
#: to load — a serialized executable is only defined for the stack that
#: produced it, and a calibration change re-decides engines per class
STRICT_PROVENANCE = ("jax", "jaxlib", "platform", "device_kind",
                     "device_count", "calibration")


def live_provenance() -> dict:
    """The provenance stamp of THIS process: the fields a persisted
    executable must match to be loadable here."""
    import jax
    import jaxlib
    try:
        devs = jax.devices()
        platform = devs[0].platform
        device_kind = getattr(devs[0], "device_kind", "")
        device_count = len(devs)
    except Exception:
        platform, device_kind, device_count = "unknown", "", 0
    from ..obs.calibrate import active_profile
    prof = active_profile()
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": platform,
        "device_kind": device_kind,
        "device_count": device_count,
        "calibration": prof.profile_id if prof is not None else "",
    }


def entry_key(skey, tag) -> str:
    """Stable filename hash of one (structural class, program tag) pair.
    ``repr`` of the key material is deterministic: both are nested tuples
    of primitives, frozen dataclasses (GateOp, CacheOptions) and dtype/
    sharding strings."""
    return hashlib.sha256(repr((skey, tag)).encode()).hexdigest()[:24]


def validate_entry_header(header: dict, live: dict | None = None) -> list:
    """Schema + provenance check; returns the problem list (empty = valid),
    the same contract shape as ``calibrate.validate_profile`` and
    ``export.validate_chrome_trace``.  ``live=None`` checks schema only
    (offline tooling); pass :func:`live_provenance` to gate loading."""
    problems: list = []
    if not isinstance(header, dict):
        return ["header is not a JSON object"]
    if header.get("format") != STORE_FORMAT:
        problems.append(f"format is {header.get('format')!r}, "
                        f"not {STORE_FORMAT!r}")
    for field in ("key", "payload_sha256", "payload_bytes", "provenance",
                  "created_epoch_s"):
        if field not in header:
            problems.append(f"missing field {field!r}")
    prov = header.get("provenance")
    if prov is not None and not isinstance(prov, dict):
        problems.append("provenance is not an object")
        prov = None
    if live is not None and isinstance(prov, dict):
        for field in STRICT_PROVENANCE:
            have, want = prov.get(field), live.get(field)
            if have != want:
                problems.append(
                    f"provenance mismatch on {field!r}: entry was built "
                    f"under {have!r}, this process runs {want!r}")
    return problems


def _is_serializable_program(call) -> bool:
    import jax
    return isinstance(call, jax.stages.Compiled)


class ExecutableStore:
    """One directory of persisted executables shared by any number of
    replica processes.  Thread-safe; writes are atomic (tmp + rename), so
    concurrent replicas racing to persist the same class converge on one
    valid file.

    ``stats``: saves / save_skipped (non-serializable programs) /
    hits / stale (provenance or digest refusals) / absent / errors
    (deserialization failures — counted, never raised: persistence must
    never be the thing that kills a serving process)."""

    def __init__(self, root: str, *, readonly: bool = False):
        self.root = str(root)
        self.readonly = bool(readonly)
        self._lock = threading.Lock()
        self.stats = {"saves": 0, "save_skipped": 0, "hits": 0,  # guarded-by: _lock
                      "stale": 0, "absent": 0, "errors": 0}
        if not readonly:
            os.makedirs(self.root, exist_ok=True)

    # -- paths --------------------------------------------------------------
    def _path(self, key: str) -> str:
        return os.path.join(self.root, key + _SUFFIX)

    def keys(self) -> list:
        """Hashed entry keys present on disk (the broadcastable hot list)."""
        try:
            return sorted(f[:-len(_SUFFIX)] for f in os.listdir(self.root)
                          if f.endswith(_SUFFIX))
        except OSError:
            return []

    # -- writing ------------------------------------------------------------
    def put(self, skey, tag, call, nbytes: int, entry_meta: dict) -> bool:
        """Persist one compiled program (write-through from the cache's
        compile path, or an explicit export).  Returns True iff a file was
        written.  Non-``jax.stages.Compiled`` programs are skipped —
        opaque overlap/epoch callables have no serializable executable."""
        if self.readonly:
            return False
        if not _is_serializable_program(call):
            with self._lock:
                self.stats["save_skipped"] += 1
            return False
        try:
            from jax.experimental import serialize_executable as _se
            exe_bytes, in_tree, out_tree = _se.serialize(call)
            payload = pickle.dumps(
                (skey, tag, entry_meta, exe_bytes, in_tree, out_tree))
        except Exception:
            with self._lock:
                self.stats["save_skipped"] += 1
            return False
        key = entry_key(skey, tag)
        header = {
            "format": STORE_FORMAT,
            "created_epoch_s": time.time(),
            "key": key,
            "tag_kind": str(tag[0]) if isinstance(tag, tuple) and tag else "",
            "num_qubits": entry_meta.get("num_qubits"),
            "nbytes": int(nbytes),
            "payload_sha256": hashlib.sha256(payload).hexdigest(),
            "payload_bytes": len(payload),
            "provenance": live_provenance(),
        }
        hjson = json.dumps(header, sort_keys=True).encode()
        blob = _MAGIC + struct.pack(">I", len(hjson)) + hjson + payload
        tmp = None
        try:
            fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
            with os.fdopen(fd, "wb") as fh:
                fh.write(blob)
            os.replace(tmp, self._path(key))
        except OSError:
            if tmp is not None:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
            with self._lock:
                self.stats["errors"] += 1
            return False
        with self._lock:
            self.stats["saves"] += 1
        return True

    # -- reading ------------------------------------------------------------
    def read_header(self, key: str) -> dict | None:
        """The provenance header of one entry (no payload touched)."""
        try:
            with open(self._path(key), "rb") as fh:
                if fh.read(len(_MAGIC)) != _MAGIC:
                    return None
                (hlen,) = struct.unpack(">I", fh.read(4))
                return json.loads(fh.read(hlen).decode())
        except (OSError, ValueError, struct.error):
            return None

    def _read(self, key: str):
        """(header, payload) of one entry, digest-checked; ``"absent"``
        when the file does not exist, None on any malformation (the caller
        counts the refusal)."""
        try:
            with open(self._path(key), "rb") as fh:
                if fh.read(len(_MAGIC)) != _MAGIC:
                    return None
                (hlen,) = struct.unpack(">I", fh.read(4))
                header = json.loads(fh.read(hlen).decode())
                payload = fh.read()
        except FileNotFoundError:
            return "absent"
        except (OSError, ValueError, struct.error):
            return None
        if not isinstance(header, dict):
            return None
        if header.get("payload_bytes") != len(payload):
            return None
        if hashlib.sha256(payload).hexdigest() != header.get("payload_sha256"):
            return None
        return header, payload

    def fetch(self, skey, tag):
        """One program by live cache identity.  Returns
        ``(status, call, nbytes)`` with status ``"hit"`` (call is the
        loaded executable), ``"stale"`` (present but refused: provenance or
        digest mismatch — the caller must recompile and count the miss) or
        ``"absent"``.  A deserialization failure reports ``"absent"`` to
        the caller (recompile, no ``persist_stale``) — it is counted
        store-side as ``errors``, not a provenance refusal."""
        status, loaded = self._load(entry_key(skey, tag))
        if status != "hit":
            return ("stale" if status == "stale" else "absent"), None, 0
        _key2, _tag2, meta, call, nbytes = loaded
        return "hit", call, nbytes

    def _load(self, key: str):
        """Validate + deserialize one entry.  Returns ``(status, result)``
        — status ``"hit"`` with ``(skey, tag, entry_meta, call, nbytes)``,
        else ``"absent"`` / ``"stale"`` / ``"error"`` with None, each
        counted in its OWN stat: ``stale`` means provenance/digest refusal
        and nothing else.  The payload is unpickled only after the
        header's digest and provenance checks both pass."""
        read = self._read(key)
        if read == "absent":
            # a broadcast hot key the local store never held (or a file
            # deleted under us) is NOT provenance drift — keep the
            # ``stale`` gauge meaning what it says
            with self._lock:
                self.stats["absent"] += 1
            return "absent", None
        if read is None:
            with self._lock:
                self.stats["stale"] += 1
            return "stale", None
        header, payload = read
        if validate_entry_header(header, live_provenance()):
            with self._lock:
                self.stats["stale"] += 1
            return "stale", None
        try:
            skey, tag, meta, exe_bytes, in_tree, out_tree = \
                pickle.loads(payload)
            from jax.experimental import serialize_executable as _se
            call = _se.deserialize_and_load(exe_bytes, in_tree, out_tree)
        except Exception:
            with self._lock:
                self.stats["errors"] += 1
            return "error", None
        with self._lock:
            self.stats["hits"] += 1
        return "hit", (skey, tag, meta, call,
                       int(header.get("nbytes", 1 << 20)))

    # -- warm-up ------------------------------------------------------------
    def warm(self, cache, keys: list | None = None) -> dict:
        """Load persisted executables into ``cache`` (a
        ``serve.cache.CompileCache``): re-materialize each entry's class
        metadata (skeleton, operand offsets — so warmed mesh classes skip
        the schedule search too) and install the executable WITHOUT
        touching the compile counters — a warmed replica's first request
        per class is a cache hit that compiled nothing.

        ``keys=None`` loads everything on disk; pass the hot-key list a
        warm peer broadcast (deploy/pool.py) to warm selectively.  Returns
        ``{"loaded", "refused", "requested"}``."""
        want = self.keys() if keys is None else [k for k in keys]
        loaded = refused = 0
        for key in want:
            status, got = self._load(key)
            if status != "hit":
                refused += 1
                continue
            skey, tag, meta, call, nbytes = got
            try:
                entry = cache.install_entry(
                    skey, meta["num_qubits"], meta["options"],
                    meta["skeleton"], meta["offsets"], meta["num_params"],
                    hamil=meta.get("hamil"))
                cache.install_program(entry, tag, call, nbytes)
            except Exception:
                with self._lock:
                    self.stats["errors"] += 1
                refused += 1
                continue
            loaded += 1
        return {"loaded": loaded, "refused": refused,
                "requested": len(want)}

    def snapshot(self) -> dict:
        with self._lock:
            d = dict(self.stats)
        d["entries"] = len(self.keys())
        d["root"] = self.root
        return d
