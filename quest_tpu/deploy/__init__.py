"""quest_tpu.deploy — pod-scale serving: replica pool, SLO-aware router,
persistent AOT compile cache.

The serve layer (quest_tpu/serve) is one ``QuESTService`` on one process
group; the reference picks its backend at build time and runs one process
group forever (PAPER.md layer map).  This package is the jax_graft answer
at the other end of the scale axis — the deployment that multiplies the
single-replica service:

- ``pool.py``: N data-parallel **replicas** (thread-backed for CPU/CI and
  single-host; one-per-process under a ``jax.distributed`` coordinator for
  real pods), each wrapping one ``QuESTService`` with its own compile
  cache/SLO monitor/flight recorder, all sharing ONE labeled metrics
  registry (``{replica="i"}`` Prometheus labels, serve/metrics.py).
- ``router.py``: the front door — structural-class **affinity** placement
  (rendezvous hashing keeps each class's one-executable-per-class cache
  hot on one replica) that yields to the LIVE SLO monitor: a saturated or
  budget-burning replica sheds to the next-best affinity candidate, and an
  eviction-induced cache miss re-places the class instead of re-warming
  the evicting replica by stale habit.
- ``persist.py``: the **persistent compile cache** — serialized XLA
  executables on disk keyed by structural class + program tag, with a
  tamper-evident provenance header (jaxlib/platform/calibration) that
  REFUSES stale entries; cold replicas warm by loading the store, guided
  by a ``multihost_utils``-style broadcast of a warm peer's hot class
  keys.  A warmed replica serves its first request per class with ZERO
  compiles.

``python -m quest_tpu.deploy --selftest`` is the gate; docs/DEPLOY.md the
architecture note.
"""

from .persist import (ExecutableStore, STORE_FORMAT, entry_key,  # noqa: F401
                      live_provenance, validate_entry_header)
from .pool import (Replica, ReplicaPool, broadcast_hot_keys,  # noqa: F401
                   process_replica)
from .router import Router, RouterConfig  # noqa: F401

__all__ = [
    "ExecutableStore", "STORE_FORMAT", "entry_key", "live_provenance",
    "validate_entry_header",
    "Replica", "ReplicaPool", "process_replica", "broadcast_hot_keys",
    "Router", "RouterConfig",
]
