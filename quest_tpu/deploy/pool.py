"""Replica pool: N data-parallel QuESTServices behind one router.

The deployment unit the north star asks for: each **replica** wraps one
:class:`~quest_tpu.serve.service.QuESTService` with its OWN compile cache,
SLO monitor and flight recorder — replicas are fully data-parallel (a
request executes on exactly one), so nothing here needs a cross-process
collective and the pool scales to however many process groups the launcher
brings up.  Two deployment shapes, one code path:

- **Thread-backed** (:class:`ReplicaPool`): N replicas in one process,
  each service's worker thread its own lane.  This is the CPU test/CI
  path, the bench substrate, and an honest single-host deployment (JAX
  releases the GIL during device execution, so replica workers overlap).
- **Process-backed** (:func:`process_replica`): one replica per process
  under a ``jax.distributed`` coordinator — ``jax.process_index()`` names
  the replica, every process runs the same code, and the observability
  exports (trace shards, labeled scrapes, selftest documents) merge
  offline exactly like obs/aggregate.py trace shards do.

All replicas share ONE metrics registry through per-replica labeled views
(serve/metrics.py ``Metrics.labeled``), so :meth:`ReplicaPool.prometheus`
is a single scrape where every per-replica series carries a
``{replica="i"}`` label — one TYPE line per family, N samples under it.

Warm-up: with a persistent executable store attached
(deploy/persist.py), every replica's cache loads instead of compiling.
:meth:`ReplicaPool.warm` additionally front-loads the store BEFORE traffic
arrives, optionally restricted to the hot-key list a warm peer published —
:func:`broadcast_hot_keys` carries that list over the same
``multihost_utils`` broadcast primitive as ``broadcast_host_epoch``
(degrading to the local list where the backend cannot collective, e.g.
the pinned CPU jaxlib)."""

from __future__ import annotations

import json
import threading
import time

from .. import obs as _obs
from ..serve.cache import CompileCache
from ..serve.metrics import Metrics
from ..serve.service import QuESTService
from .persist import ExecutableStore, entry_key
from .router import Router, RouterConfig

__all__ = ["Replica", "ReplicaPool", "process_replica",
           "broadcast_hot_keys"]


class Replica:
    """One serving lane: index + service + its own compile cache (the
    affinity contract NEEDS per-replica caches — a shared cache would make
    placement irrelevant and the byte budget a single point of pressure).

    ``seed`` should differ per replica (the pool passes ``seed + index``)
    so two requests that happen to get the same request id on different
    replicas still draw distinct sample streams."""

    def __init__(self, index: int, *, store: ExecutableStore | None = None,
                 cache: CompileCache | None = None,
                 cache_max_bytes: int | None = None, metrics=None,
                 seed: int = 0, start: bool = True, **service_kwargs):
        self.index = int(index)
        self.cache = cache if cache is not None \
            else CompileCache(max_bytes=cache_max_bytes)
        if store is not None:
            self.cache.attach_store(store)
        self.store = store
        self.metrics = metrics if metrics is not None else Metrics()
        self.created_monotonic = time.monotonic()
        self.service = QuESTService(cache=self.cache, metrics=self.metrics,
                                    seed=seed, start=start,
                                    **service_kwargs)

    def health(self) -> dict:
        """The router's per-decision read: the service's lock-free SLO
        health snapshot (obs/slo.py)."""
        return self.service.slo.health()

    def hot_keys(self) -> list:
        """Store keys of every program THIS replica holds compiled — what
        a warm peer publishes for broadcast warm-up."""
        return sorted(entry_key(skey, tag)
                      for skey, tag in self.cache.program_keys())

    def warm(self, keys: list | None = None) -> dict:
        """Load persisted executables into this replica's cache (all of
        the store, or just a peer's hot-key list).  Returns the store's
        ``{"loaded", "refused", "requested"}`` summary."""
        if self.store is None:
            return {"loaded": 0, "refused": 0, "requested": 0}
        return self.store.warm(self.cache, keys)

    def snapshot(self) -> dict:
        return {
            "replica": self.index,
            "cache": self.cache.snapshot(),
            "slo": self.service.slo.snapshot(),
            "health": self.health(),
            "queue_saturation": self.service.queue_saturation(),
        }

    def shutdown(self, drain: bool = True,
                 timeout: float | None = None) -> None:
        self.service.shutdown(drain=drain, timeout=timeout)


def broadcast_hot_keys(local_keys: list, max_bytes: int = 1 << 16) -> list:
    """Publish process 0's hot-key list to every process (the
    ``multihost_utils`` broadcast of ROADMAP item 1, carrying executable
    identities instead of timestamps).  Keys beyond the buffer are
    truncated deterministically (sorted order) — warm-up hints are
    best-effort.  Where the backend cannot collective this degrades to the
    LOCAL list (parallel/mesh.py ``broadcast_payload``)."""
    from ..parallel.mesh import broadcast_payload
    keys = sorted(str(k) for k in local_keys)
    data = json.dumps(keys).encode()
    while keys and len(data) > max_bytes - 4:
        # always strictly shrink: at len 1 this empties the list, so an
        # oversized single key degrades to no hints instead of spinning
        keys = keys[:len(keys) - max(1, len(keys) // 4)]
        data = json.dumps(keys).encode()
    out = broadcast_payload(data, max_bytes)
    try:
        got = json.loads(out.decode())
        return [str(k) for k in got] if isinstance(got, list) else keys
    except ValueError:
        return keys


def process_replica(*, store_dir: str | None = None, seed: int = 0,
                    metrics=None, **service_kwargs) -> Replica:
    """THIS process's replica in a process-backed deployment: the caller
    has already run ``jax.distributed.initialize`` (the launcher's job, as
    with any SPMD program); ``jax.process_index()`` names the replica and
    labels its metrics.  All processes may share one ``store_dir`` — store
    writes are atomic, and racing replicas converge on one valid file."""
    from ..parallel.mesh import process_info
    index = process_info()["process_index"]
    store = ExecutableStore(store_dir) if store_dir else None
    m = metrics if metrics is not None else Metrics()
    return Replica(index, store=store, seed=seed + index,
                   metrics=m.labeled(replica=str(index)), **service_kwargs)


class ReplicaPool:
    """N thread-backed replicas + the SLO-aware affinity router, presented
    as one service: ``submit`` routes, ``prometheus()`` is the one labeled
    scrape, ``drain``/``shutdown`` fan out."""

    def __init__(self, num_replicas: int = 2, *,
                 store_dir: str | None = None,
                 cache_max_bytes: int | None = None,
                 router_config: RouterConfig | None = None,
                 metrics: Metrics | None = None, seed: int = 0,
                 start: bool = True, **service_kwargs):
        if num_replicas < 1:
            raise ValueError(f"num_replicas must be >= 1, got {num_replicas}")
        self.metrics = metrics if metrics is not None else Metrics()
        self.store = ExecutableStore(store_dir) if store_dir else None
        self.replicas = [
            Replica(i, store=self.store, seed=seed + i,
                    cache_max_bytes=cache_max_bytes,
                    metrics=self.metrics.labeled(replica=str(i)),
                    start=start, **service_kwargs)
            for i in range(int(num_replicas))
        ]
        self.router = Router(self.replicas, config=router_config,
                             metrics=self.metrics.labeled())
        self._lock = threading.Lock()
        self._shutdown = False      # guarded-by: _lock
        # set once the first shutdown()'s fan-out has joined; concurrent
        # later callers wait on it instead of returning mid-teardown
        self._shutdown_done = threading.Event()

    # -- serving ------------------------------------------------------------
    def submit(self, circuit, params=None, shots: int = 0,
               deadline_ms: float | None = None, initial_state=None):
        return self.router.submit(circuit, params=params, shots=shots,
                                  deadline_ms=deadline_ms,
                                  initial_state=initial_state)

    def submit_gradient(self, circuit, params=None, hamiltonian=None,
                        deadline_ms: float | None = None,
                        initial_state=None, probes: bool | None = None):
        """Gradient front door (quest_tpu/grad): routed by the gradient
        class's own affinity, served by one replica's
        ``QuESTService.submit_gradient``."""
        return self.router.submit_gradient(
            circuit, params=params, hamiltonian=hamiltonian,
            deadline_ms=deadline_ms, initial_state=initial_state,
            probes=probes)

    def start(self) -> "ReplicaPool":
        for r in self.replicas:
            r.service.start()
        return self

    def drain(self, timeout: float | None = None) -> bool:
        end = None if timeout is None else time.monotonic() + timeout
        ok = True
        for r in self.replicas:
            left = None if end is None else max(0.0, end - time.monotonic())
            ok &= r.service.drain(timeout=left)
        return ok

    def shutdown(self, drain: bool = True,
                 timeout: float | None = None) -> None:
        """Fan-out shutdown of every replica.  Idempotent like
        ``QuESTService.shutdown``: a second call (operator retry, context-
        manager exit after an explicit call) is a no-op, not an error —
        and a CONCURRENT second call waits for the first fan-out to join,
        so returning always means every replica is stopped."""
        with self._lock:
            first = not self._shutdown
            self._shutdown = True
        if not first:
            self._shutdown_done.wait(timeout=timeout)
            return
        try:
            # parallel shutdown: one slow replica must not serialize the rest
            threads = [threading.Thread(target=r.shutdown,
                                        kwargs={"drain": drain,
                                                "timeout": timeout})
                       for r in self.replicas]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            self._shutdown_done.set()

    def __enter__(self) -> "ReplicaPool":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=exc == (None, None, None))

    # -- warm-up ------------------------------------------------------------
    def warm(self, keys: list | None = None) -> list:
        """Warm every replica from the attached store (optionally only the
        given hot keys); returns the per-replica summaries."""
        return [r.warm(keys) for r in self.replicas]

    def hot_keys(self) -> list:
        keys: set = set()
        for r in self.replicas:
            keys.update(r.hot_keys())
        return sorted(keys)

    # -- observability ------------------------------------------------------
    def metrics_dict(self) -> dict:
        return {
            "replicas": [r.snapshot() for r in self.replicas],
            "router": self.router.snapshot(),
            "store": self.store.snapshot() if self.store else None,
            "registry": self.metrics.as_dict(),
        }

    def prometheus(self) -> str:
        """ONE scrape for the whole deployment: the shared registry (every
        per-replica counter/gauge a labeled sample under one family) plus
        per-replica cache/SLO splices labeled ``{replica="i"}``, the
        process-wide obs counters, and the store/router gauges — all
        splices point-in-time (the labeled ``extra_gauges`` groups), never
        written into the registry where they would go stale or outlive a
        retired replica."""
        groups: list = []
        for r in self.replicas:
            splice = {f"cache_{k}": v for k, v in r.cache.snapshot().items()
                      if isinstance(v, (int, float))}
            splice.update({f"slo_{k}": v
                           for k, v in r.service.slo.gauges().items()})
            splice["queue_saturation_live"] = r.service.queue_saturation()
            groups.append((splice, {"replica": str(r.index)}))
        extra = {f"obs_{k}": v for k, v in _obs.obs_snapshot().items()}
        extra["replicas"] = len(self.replicas)
        if self.store is not None:
            extra.update({f"store_{k}": v
                          for k, v in self.store.snapshot().items()
                          if isinstance(v, (int, float))})
        groups.append((extra, None))
        return self.metrics.to_prometheus(extra_gauges=groups)
