"""``python -m quest_tpu.deploy`` — the deployment-layer CLI.

``--selftest`` runs the multi-replica storm (selftest.py): >= 2 replicas
behind the SLO-aware affinity router over one shared persistent executable
store, gating bit-identity against single-replica serial execution, an
aggregate cache hit rate >= 0.9, a strictly-faster warm-loaded cold start
with ZERO compiles, the router shed path against a saturated-replica
baseline, and the labeled one-scrape Prometheus contract.  ``--json``
emits ONE machine-readable document for the CI gate.

Multi-process (the CI ``deploy-selftest`` job): launch one invocation per
process with ``--processes N --process-id I --coordinator HOST:PORT
--store DIR --sync-dir DIR``; every process initializes the
``jax.distributed`` coordinator, runs the storm against the SHARED store,
and writes its trace shard + document into the sync directory; process 0
merges the shards into one validated multi-track Chrome trace and
aggregates every process's verdict.  Exit status 0 iff every check (and,
on process 0, every peer) passed.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m quest_tpu.deploy",
        description="Pod-scale serving: replica pool, SLO-aware router, "
                    "persistent compile cache (docs/DEPLOY.md).")
    parser.add_argument("--selftest", action="store_true",
                        help="run the multi-replica deployment storm")
    parser.add_argument("--replicas", type=int, default=2,
                        help="replica count for the selftest pool "
                             "(default 2)")
    parser.add_argument("--scale", type=int, default=1,
                        help="workload multiplier (default 1: 64 requests)")
    parser.add_argument("--store", default=None,
                        help="persistent executable store directory "
                             "(default: a fresh temp dir; share one across "
                             "processes in multi-process mode)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit ONE machine-readable JSON document")
    parser.add_argument("--trace", action="store_true",
                        help="record through the span recorder and "
                             "export/validate the Chrome trace (forced on "
                             "in multi-process mode)")
    parser.add_argument("--processes", type=int, default=1,
                        help="total process count under one "
                             "jax.distributed coordinator")
    parser.add_argument("--process-id", type=int, default=0,
                        help="this process's index (multi-process mode)")
    parser.add_argument("--coordinator", default=None,
                        help="HOST:PORT of the jax.distributed "
                             "coordinator (multi-process mode)")
    parser.add_argument("--sync-dir", default=None,
                        help="shared directory for shard/document "
                             "rendezvous (multi-process mode)")
    args = parser.parse_args(argv)
    if not args.selftest:
        parser.print_usage()
        return 2
    if args.processes > 1:
        if not args.sync_dir or not args.store:
            parser.error("multi-process mode needs --sync-dir and --store")
        import jax
        if jax.process_count() != args.processes:
            # joining a coordinator must happen BEFORE any JAX computation,
            # and importing quest_tpu already runs some — so the join
            # happens at package-import time, driven by the env var the
            # launcher sets (quest_tpu/__init__.py).  A late --coordinator
            # attempt is made for computation-free stacks, with the env-var
            # recipe in the failure message.
            try:
                if not args.coordinator:
                    raise RuntimeError("no coordinator joined")
                jax.distributed.initialize(
                    coordinator_address=args.coordinator,
                    num_processes=args.processes,
                    process_id=args.process_id)
            except RuntimeError as exc:
                parser.error(
                    f"process {args.process_id} is not part of a "
                    f"{args.processes}-process jax.distributed group "
                    f"({exc}); launch with QUEST_TPU_DISTRIBUTED="
                    f"HOST:PORT,{args.processes},{args.process_id} in the "
                    "environment so the coordinator joins at import time")
    from .selftest import run_selftest
    return run_selftest(as_json=args.as_json, scale=max(1, args.scale),
                        replicas=max(1, args.replicas), store_dir=args.store,
                        trace=True if args.trace else None,
                        sync_dir=args.sync_dir,
                        process_index=args.process_id,
                        process_count=args.processes)


if __name__ == "__main__":
    sys.exit(main())
