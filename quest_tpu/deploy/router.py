"""SLO-aware class-affinity router: the deployment's front door.

Placement problem: the serve cache compiles ONE executable per structural
class, so spraying a class's traffic across N replicas multiplies its
compile cost (and its cache bytes) by N.  The router therefore places by
**class affinity**: `Circuit.key(structural=True)` hashes to a rendezvous
(highest-random-weight) order over the replicas, and a class's traffic
sticks to its first-choice replica — the per-replica compile cache stays
hot, and adding or removing a replica re-places only ~1/N of the classes
(the rendezvous property; a modulo hash would reshuffle everything).

Stickiness yields to LIVE health, read per decision from the two cheap
surfaces built for exactly this:

- ``service.queue_saturation()`` — the live queue fraction.  A replica at
  or past ``shed_saturation`` sheds EVERY request (admission there risks
  ``E_QUEUE_FULL`` bounces).
- ``slo.health()`` — the lock-free windowed snapshot (obs/slo.py).  A
  replica whose short-window burn rate is at or past ``shed_burn`` sheds
  requests that CARRY a deadline (they would land in a queue already
  eating its error budget); deadline-free requests still stick (they
  consume no budget, and keeping them local preserves cache heat).

A shed request moves to the next-best candidate in ITS OWN affinity order
— so a class's overflow lands on a deterministic second replica and warms
exactly one extra cache, not a random one per request.

Affinity can also go stale from the OTHER side: a replica that evicts a
class under cache byte pressure keeps its affinity but no longer holds the
executable.  The router learns this from the cache-outcome feedback on
every completed request (``ServeResult.cache_outcome``): a **miss reported
for a class the router had previously confirmed hot on that replica** means
the class was evicted there — the sticky placement is dropped, the
(class, replica) pair enters a cooldown, and the next request re-places
onto the next-best candidate instead of re-warming the evicting replica by
stale habit (tests/test_deploy.py pins the interplay).

Probed deployments (obs/numerics.py) add a third feedback channel:
``ServeResult.numeric_health``.  ``quarantine_nans`` consecutive NaN/Inf
outcomes for a (class, replica) pair quarantine that placement for
``quarantine_s`` — a placement that poisons a class's registers (a
miscompiled executable, a bad device) is worse than a cold cache, so the
class re-places while the pair sits out (``report_numeric``;
``quest_serve_numeric_quarantined_total{replica=...}``).

Every decision is a traced span (``deploy.route``: class key, chosen
replica, sticky/shed/cooldown disposition) and a labeled counter in the
deployment's one registry (``quest_serve_routed_total{replica="i"}``,
``..._shed_total``, ``..._replaced_total``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time

from .. import obs as _obs
from ..validation import ErrorCode, QuESTError

__all__ = ["RouterConfig", "Router"]


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Shed policy knobs.  ``shed_saturation`` is the live queue fraction
    at which a replica sheds all traffic; ``shed_burn`` the short-window
    burn rate at which it sheds deadline-carrying traffic;
    ``cooldown_s`` how long an evicted (class, replica) pair is avoided
    before affinity may return.  ``quarantine_nans`` is how many
    CONSECUTIVE NaN/Inf numeric outcomes (ServeResult.numeric_health —
    probed services only) a (class, replica) pair may produce before it is
    quarantined for ``quarantine_s``: a placement that keeps poisoning a
    class's registers is worse than a cold cache, so the router re-places
    the class instead of re-feeding the bad executable by sticky habit
    (docs/DEPLOY.md "numeric quarantine")."""
    shed_saturation: float = 0.8
    shed_burn: float = 1.0
    cooldown_s: float = 30.0
    quarantine_nans: int = 2
    quarantine_s: float = 300.0


class Router:
    """Places requests over a list of replicas (``deploy.pool.Replica``
    duck-type: ``.index``, ``.service``, ``.health()``)."""

    def __init__(self, replicas, config: RouterConfig | None = None,
                 metrics=None):
        self.replicas = list(replicas)
        self.config = config if config is not None else RouterConfig()
        self.metrics = metrics
        self._lock = threading.Lock()
        self._placement: dict = {}   # guarded-by: _lock (class_key -> replica index)
        self._confirmed: set = set()  # guarded-by: _lock ((class_key, index): seen a hit)
        self._cooldown: dict = {}    # guarded-by: _lock ((class_key, index) -> t_until)
        self._nan_strikes: dict = {}  # guarded-by: _lock ((class_key, index) -> [strikes, t_last])
        self._quarantine: dict = {}  # guarded-by: _lock ((class_key, index) -> t_until)

    # -- affinity -----------------------------------------------------------
    def class_key(self, circuit) -> str:
        return _obs.key_hash((circuit.num_qubits,
                              circuit.key(structural=True)))

    def grad_class_key(self, circuit, hamiltonian) -> str:
        """Affinity key of a GRADIENT class (quest_tpu/grad): the ansatz
        op tuple plus the Hamiltonian's packed term masks — a gradient
        class is a routable class of its own, with its own rendezvous
        order, sticky placement, cooldown and NaN quarantine, distinct
        from the same circuit's forward class (they are different
        executables with different cache economics)."""
        from ..grad import adjoint as _gradadj
        return _obs.key_hash(
            (circuit.num_qubits,
             _gradadj.grad_group_signature(
                 circuit, _gradadj.hamil_masks(hamiltonian))))

    def candidates(self, class_key: str) -> list:
        """Replica indices in rendezvous (HRW) order for this class:
        deterministic, uniform over classes, and stable under replica
        count changes except for the classes whose winner left."""
        return sorted(
            (r.index for r in self.replicas),
            key=lambda i: hashlib.sha256(
                f"{class_key}|{i}".encode()).hexdigest(),
            reverse=True)

    # -- the decision -------------------------------------------------------
    def _shed_reason(self, replica, has_deadline: bool) -> str | None:
        cfg = self.config
        if replica.service.queue_saturation() >= cfg.shed_saturation:
            return "saturation"
        if has_deadline and replica.health()["burn_rate"] >= cfg.shed_burn:
            return "burn"
        return None

    def route(self, circuit, deadline_ms: float | None = None,
              class_key: str | None = None):
        """Pick the replica for one request; returns ``(replica,
        decision)`` where ``decision`` is the JSON-ready record the span
        and the selftest document carry.  ``class_key`` lets a caller that
        already derived the key (submit()) skip the second structural
        walk."""
        t0 = time.perf_counter()
        ck = class_key if class_key is not None else self.class_key(circuit)
        order = self.candidates(ck)
        hrw_first = order[0]       # before the sticky/cooldown reorders
        now = time.monotonic()
        with self._lock:
            sticky = self._placement.get(ck)
            # prune on the way through: without this the dicts grow one
            # entry per eviction/quarantine for the process lifetime
            for pair in [p for p, t in self._cooldown.items() if t <= now]:
                del self._cooldown[pair]
            for pair in [p for p, t in self._quarantine.items() if t <= now]:
                del self._quarantine[pair]
                self._nan_strikes.pop(pair, None)
            # strikes decay too: a strike older than quarantine_s is not
            # "consecutive" with a NaN weeks later, and without this prune
            # the dict grows one entry per (class, replica) that ever
            # produced a single NaN for the process lifetime
            for pair in [p for p, (_, t) in self._nan_strikes.items()
                         if now - t > self.config.quarantine_s]:
                del self._nan_strikes[pair]
            cooled = {i for i in order if (ck, i) in self._cooldown}
            quarantined = {i for i in order if (ck, i) in self._quarantine}
        avoid = cooled | quarantined
        if sticky is not None and sticky in order:
            order = [sticky] + [i for i in order if i != sticky]
        if len(avoid) < len(order):
            # skip cooled/quarantined replicas only while an alternative
            # exists: a fully-avoided class still gets served somewhere
            order = ([i for i in order if i not in avoid]
                     + [i for i in order if i in avoid])
        by_index = {r.index: r for r in self.replicas}
        chosen = None
        shed_from: list = []
        for i in order:
            reason = self._shed_reason(by_index[i], deadline_ms is not None)
            if reason is None:
                chosen = i
                break
            shed_from.append({"replica": i, "reason": reason})
        if chosen is None:
            # every replica is shedding: least-loaded wins — degraded, but
            # a router must always route
            chosen = min(order,
                         key=lambda i: by_index[i].service.queue_saturation())
        if not shed_from:
            # a SHED decision must not rewrite the sticky placement: a
            # transient saturation spike would otherwise migrate the class
            # permanently onto the survivor (its affinity replica's warm
            # executable orphaned) — overflow serves elsewhere, affinity
            # returns the moment the replica stops shedding
            with self._lock:
                self._placement[ck] = chosen
        decision = {"class_key": ck, "replica": chosen,
                    "affinity": hrw_first if sticky is None else sticky,
                    "sticky": sticky is not None,
                    "shed_from": shed_from,
                    "cooldown_skipped": sorted(cooled),
                    "quarantine_skipped": sorted(quarantined)}
        if self.metrics is not None and shed_from:
            self.metrics.inc("shed_total",
                             labels={"replica": str(shed_from[0]["replica"]),
                                     "reason": shed_from[0]["reason"]})
        _obs.emit_span("deploy.route", t0=t0,
                       dur=time.perf_counter() - t0, class_key=ck,
                       replica=chosen, sticky=decision["sticky"],
                       shed=len(shed_from))
        return by_index[chosen], decision

    # -- submission ---------------------------------------------------------
    def submit(self, circuit, params=None, shots: int = 0,
               deadline_ms: float | None = None, initial_state=None):
        """Route + submit; the returned Future resolves exactly like
        ``QuESTService.submit``'s.  A replica whose queue bounces the
        request (``E_QUEUE_FULL`` raced past the saturation read) is
        retried at the remaining candidates before the bounce propagates."""
        ck = self.class_key(circuit)
        return self._routed_submit(
            circuit, ck, deadline_ms,
            lambda replica: replica.service.submit(
                circuit, params=params, shots=shots,
                deadline_ms=deadline_ms, initial_state=initial_state))

    def submit_gradient(self, circuit, params=None, hamiltonian=None,
                        deadline_ms: float | None = None,
                        initial_state=None, probes: bool | None = None):
        """Route + submit one ``(energy, gradient)`` request
        (``QuESTService.submit_gradient``; quest_tpu/grad).  The gradient
        class's OWN affinity key places it — same sticky/shed/bounce
        policy as forward traffic, and the done-callback feeds the same
        eviction re-placement and NaN quarantine (a ``GradResult`` carries
        ``cache_outcome`` and ``numeric_health`` exactly like a
        ``ServeResult``, so a backward-pass NaN on a probed deployment
        quarantines the placement)."""
        if hamiltonian is None:
            # same clean error surface as QuESTService.submit_gradient —
            # grad_class_key would otherwise die inside hamil_masks
            raise TypeError(
                "submit_gradient(circuit, params, hamiltonian) requires a "
                "PauliHamil: the energy head is <psi|H|psi>")
        ck = self.grad_class_key(circuit, hamiltonian)
        return self._routed_submit(
            circuit, ck, deadline_ms,
            lambda replica: replica.service.submit_gradient(
                circuit, params=params, hamiltonian=hamiltonian,
                deadline_ms=deadline_ms, initial_state=initial_state,
                probes=probes))

    def _routed_submit(self, circuit, ck: str, deadline_ms, do_submit):
        """The shared route + bounce-retry + feedback tail of
        :meth:`submit` / :meth:`submit_gradient`."""
        replica, _decision = self.route(circuit, deadline_ms, class_key=ck)
        by_index = {r.index: r for r in self.replicas}
        tried = set()
        while True:
            try:
                fut = do_submit(replica)
                break
            except QuESTError as exc:
                if exc.code != ErrorCode.QUEUE_FULL:
                    raise
                tried.add(replica.index)
                fallback = [i for i in self.candidates(ck)
                            if i not in tried]
                if not fallback:
                    raise
                # a bounce retry must still honour the shed policy: raw
                # affinity order would send the request straight back into
                # the saturated replica route() just steered around
                healthy = [i for i in fallback
                           if self._shed_reason(by_index[i],
                                                deadline_ms is not None)
                           is None]
                if self.metrics is not None:
                    self.metrics.inc("bounce_retries_total",
                                     labels={"replica": str(replica.index)})
                replica = by_index[(healthy or fallback)[0]]
        idx = replica.index
        if self.metrics is not None:
            # counted at ADMISSION, not at route(): a bounced request is
            # attributed to the replica that actually accepted it
            self.metrics.inc("routed_total", labels={"replica": str(idx)})
        fut.add_done_callback(
            lambda f, ck=ck, idx=idx: self._on_done(ck, idx, f))
        return fut

    def _on_done(self, class_key: str, index: int, fut) -> None:
        if fut.cancelled() or fut.exception() is not None:
            return
        result = fut.result()
        outcome = getattr(result, "cache_outcome", None)
        self.report(class_key, index, outcome)
        health = getattr(result, "numeric_health", None)
        if health is not None:
            self.report_numeric(
                class_key, index,
                ok=not (health.get("nan_count") or health.get("inf_count")))

    def report(self, class_key: str, index: int,
               outcome: str | None) -> None:
        """Cache-outcome feedback (also callable directly by out-of-band
        monitors).  hit => the class is confirmed resident on ``index``;
        miss AFTER a confirmed hit => the replica evicted it — drop the
        sticky placement and cool the pair so the next request re-places."""
        if outcome == "hit":
            with self._lock:
                self._confirmed.add((class_key, index))
            return
        if outcome != "miss":
            return
        with self._lock:
            if (class_key, index) not in self._confirmed:
                return                 # first-contact miss: normal cold start
            self._confirmed.discard((class_key, index))
            if self._placement.get(class_key) == index:
                del self._placement[class_key]
            self._cooldown[(class_key, index)] = (
                time.monotonic() + self.config.cooldown_s)
        if self.metrics is not None:
            self.metrics.inc("replaced_total",
                             labels={"replica": str(index)})

    def report_numeric(self, class_key: str, index: int, ok: bool) -> None:
        """Numeric-health feedback from a probed result (obs/numerics.py;
        also callable directly by out-of-band monitors).  A clean outcome
        resets the pair's strike count, and so does ``quarantine_s`` of
        silence (a strike weeks old is not "consecutive" with a fresh
        NaN); ``quarantine_nans`` CONSECUTIVE NaN/Inf outcomes quarantine
        the (class, replica) placement for ``quarantine_s`` — the sticky
        placement is dropped and route() avoids the pair while any
        alternative replica exists, so the class re-places instead of
        feeding the poisoning executable forever."""
        pair = (class_key, index)
        quarantined = False
        with self._lock:
            if ok:
                self._nan_strikes.pop(pair, None)
                return
            now = time.monotonic()
            strikes, t_last = self._nan_strikes.get(pair, (0, now))
            if now - t_last > self.config.quarantine_s:
                strikes = 0     # stale window: not consecutive in time
            strikes += 1
            self._nan_strikes[pair] = (strikes, now)
            if (strikes >= self.config.quarantine_nans
                    and pair not in self._quarantine):
                quarantined = True
                self._quarantine[pair] = (time.monotonic()
                                          + self.config.quarantine_s)
                if self._placement.get(class_key) == index:
                    del self._placement[class_key]
                self._confirmed.discard(pair)
        if quarantined and self.metrics is not None:
            self.metrics.inc("numeric_quarantined_total",
                             labels={"replica": str(index)})

    # -- introspection ------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "placements": dict(self._placement),
                "confirmed": sorted(f"{ck}@{i}"
                                    for ck, i in self._confirmed),
                "cooling": sorted(f"{ck}@{i}"
                                  for (ck, i), t in self._cooldown.items()
                                  if t > time.monotonic()),
                "quarantined": sorted(
                    f"{ck}@{i}"
                    for (ck, i), t in self._quarantine.items()
                    if t > time.monotonic()),
            }
