"""Differentiable parametric circuits: jax.grad / jax.vmap / optax-native
variational simulation.

No reference analogue — QuEST (C99, ref: /root/reference/QuEST) has no
gradient capability at all; a VQE/QAOA user of the reference must build
parameter-shift differentiation by hand, one full circuit execution per
shifted parameter (2·P executions per gradient).  This module is the
capability the TPU re-architecture buys outright: the gate engine
(ops/apply.py) keeps matrices as *runtime values* inside the traced program,
so gates built from traced parameters make the whole simulation one
differentiable XLA program — `jax.grad` computes the full parameter gradient
in a single forward+adjoint pass, `jax.vmap` batches circuit executions over
parameter sets onto the MXU, and both compose with the same GSPMD sharding
as every other program in the framework (the state argument may live on a
device mesh; parameters are replicated and the adjoint's psum is inserted by
the partitioner).

Structure stays static, parameters stay traced: a :class:`ParamCircuit`
records the gate list host-side exactly like :class:`~quest_tpu.circuit.Circuit`
(whose static gates it inherits), but rotation angles may be
:class:`Param` placeholders — indices into a flat parameter vector, with an
optional affine transform (``2.0 * p``, ``p + shift``) resolved inside the
trace.  Density-matrix mode applies the conjugated column-side shadow of
every gate (same rule as the eager API, ref: QuEST.c:8-10) and additionally
admits *differentiable noise*: the decoherence channels (ops/decoherence.py)
already take their probabilities as traced scalars, so channel strengths can
be Params too — gradients through dephasing/depolarising/damping come from
the same adjoint pass.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from .circuit import Circuit, GateOp, _apply_one, _shadow_op
from .ops import apply as _ap
from .ops import calc as _calc
from .ops import decoherence as _dec
from . import precision as _prec

__all__ = ["Param", "ParamCircuit", "build", "state_fn", "expectation_fn",
           "adjoint_gradient_fn"]


@dataclasses.dataclass(frozen=True)
class Param:
    """Placeholder for entry ``index`` of the parameter vector, carrying an
    affine transform: the traced angle is ``scale * params[index] + shift``.
    Supports ``2.0 * p``, ``-p``, ``p + 0.5``, ``p - 0.5``."""

    index: int
    scale: float = 1.0
    shift: float = 0.0

    def __mul__(self, f):
        f = float(f)
        return Param(self.index, self.scale * f, self.shift * f)

    __rmul__ = __mul__

    def __neg__(self):
        return self * -1.0

    def __add__(self, f):
        return Param(self.index, self.scale, self.shift + float(f))

    __radd__ = __add__

    def __sub__(self, f):
        return self + (-float(f))


@dataclasses.dataclass(frozen=True)
class ParamOp:
    """A recorded parametric operation.  ``param`` is a Param or a float
    (floats trace as constants, so a ParamCircuit needs no special-casing of
    bound angles).  ``codes`` carries the Pauli string for kind 'mrp'."""

    kind: str          # rx|ry|rz|phase|mrz|mrp|dephase|dephase2|depolarise|damp
    targets: tuple
    controls: tuple = ()
    control_states: tuple = ()
    param: object = None
    codes: tuple | None = None


_NOISE_KINDS = ("dephase", "dephase2", "depolarise", "damp")


class ParamCircuit(Circuit):
    """A Circuit whose rotation angles (and channel probabilities, in density
    mode) may be traced parameters.  Static gates (h, x, cnot, unitary, …)
    are inherited from :class:`Circuit` and embedded as constants."""

    def __init__(self, num_qubits: int):
        super().__init__(num_qubits)
        self.num_params = 0

    # --- parameter allocation ---------------------------------------------
    def param(self) -> Param:
        """Allocate the next parameter slot and return its placeholder."""
        p = Param(self.num_params)
        self.num_params += 1
        return p

    def params(self, k: int) -> list:
        return [self.param() for _ in range(k)]

    # --- parametric gates --------------------------------------------------
    def _p(self, kind, targets, angle, controls=(), control_states=(), codes=None):
        self.ops.append(ParamOp(kind, tuple(targets), tuple(controls),
                                tuple(control_states), angle, codes))
        return self

    def rx(self, target, angle):
        if not isinstance(angle, Param):
            return super().rx(target, angle)
        return self._p("rx", (target,), angle)

    def ry(self, target, angle):
        if not isinstance(angle, Param):
            return super().ry(target, angle)
        return self._p("ry", (target,), angle)

    def rz(self, target, angle):
        if not isinstance(angle, Param):
            return super().rz(target, angle)
        return self._p("rz", (target,), angle)

    def phase_shift(self, target, angle, controls=()):
        if not isinstance(angle, Param):
            return super().phase_shift(target, angle, controls)
        return self._p("phase", (target,), angle, tuple(controls))

    def multi_rotate_z(self, targets, angle):
        """exp(-i angle/2 Z⊗..⊗Z) on ``targets`` (ref: multiRotateZ).
        Non-Param angles take the static diagonal path (fusable)."""
        if not isinstance(angle, Param):
            return super().multi_rotate_z(targets, angle)
        return self._p("mrz", tuple(targets), angle)

    def multi_rotate_pauli(self, targets, paulis, angle):
        """exp(-i angle/2 P⊗..) for a Pauli string (ref: multiRotatePauli,
        QuEST_common.c:411-448 — basis-change to Z, parity rotation, undo).
        Non-Param angles take the static gate path (fusable)."""
        codes = tuple(int(p) for p in paulis)
        assert len(codes) == len(tuple(targets))
        if not isinstance(angle, Param):
            return super().multi_rotate_pauli(targets, codes, angle)
        return self._p("mrp", tuple(targets), angle, codes=codes)

    # --- parametric noise channels (density mode only) ---------------------
    def dephase(self, target, prob):
        """mixDephasing with a (possibly trained) probability."""
        return self._p("dephase", (target,), prob)

    def two_qubit_dephase(self, q1, q2, prob):
        return self._p("dephase2", (q1, q2), prob)

    def depolarise(self, target, prob):
        return self._p("depolarise", (target,), prob)

    def damp(self, target, prob):
        return self._p("damp", (target,), prob)

    def optimize(self, max_pack: int = 7):
        """The native fusion engine packs static matrices only; a circuit
        with parametric ops must stay unfused (XLA still fuses elementwise
        chains inside the compiled program)."""
        if any(isinstance(op, ParamOp) for op in self.ops):
            raise ValueError(
                "ParamCircuit.optimize: native gate fusion requires static "
                "gates; run optimize() before adding parametric ops")
        return super().optimize(max_pack)


# ---------------------------------------------------------------------------
# traced gate construction
# ---------------------------------------------------------------------------

def _angle(p, params):
    # params is coerced to a float dtype by _runner, so constants keep their
    # fractional part and Param affine transforms stay exact
    if isinstance(p, Param):
        return params[p.index] * p.scale + p.shift
    return jnp.asarray(p, dtype=params.dtype)


def _rx_pair(theta):
    c, s = jnp.cos(theta / 2), jnp.sin(theta / 2)
    z = jnp.zeros_like(c)
    re = jnp.stack([jnp.stack([c, z]), jnp.stack([z, c])])
    im = jnp.stack([jnp.stack([z, -s]), jnp.stack([-s, z])])
    return jnp.stack([re, im])


def _ry_pair(theta):
    c, s = jnp.cos(theta / 2), jnp.sin(theta / 2)
    re = jnp.stack([jnp.stack([c, -s]), jnp.stack([s, c])])
    return jnp.stack([re, jnp.zeros_like(re)])


def _rz_diag(theta):
    h = theta / 2
    return jnp.stack([jnp.stack([jnp.cos(h), jnp.cos(h)]),
                      jnp.stack([-jnp.sin(h), jnp.sin(h)])])


def _phase_diag(theta):
    one, zero = jnp.ones_like(theta), jnp.zeros_like(theta)
    return jnp.stack([jnp.stack([one, jnp.cos(theta)]),
                      jnp.stack([zero, jnp.sin(theta)])])


def _apply_mrp(state, theta, targets, codes, conj):
    """multiRotatePauli with a traced angle: the eager API's implementation
    (basis-change to Z, parity rotation, undo — api.py
    _multi_rotate_pauli_statevec) is trace-compatible, so reuse it."""
    from .api import _multi_rotate_pauli_statevec  # lazy: api is the upper layer

    return _multi_rotate_pauli_statevec(state, targets, codes, theta, conj)


def _apply_param_op(state, op: ParamOp, params, shadow_n: int | None,
                    invert: bool = False):
    """Apply one parametric op; if ``shadow_n`` is set (density mode), also
    apply the conjugated column-side twin on targets/controls + n.  The
    conjugate of exp(-iθG/2) is the same gate at -θ for real generators
    (rx, rz, phase, mrz) and at +θ for ry (imaginary generator).
    ``invert=True`` applies the gate's inverse (every parametric kind is a
    rotation, so the inverse is the same kind at -θ; statevector only)."""
    theta = _angle(op.param, params)
    if invert:
        assert shadow_n is None and op.kind not in _NOISE_KINDS
        theta = -theta
    t, c, cs = op.targets, op.controls, op.control_states
    dt = state.dtype

    if op.kind in _NOISE_KINDS:
        if shadow_n is None:
            raise ValueError(
                f"noise op {op.kind!r} requires density=True (channels act on "
                "the doubled Choi space)")
        prob = theta
        if op.kind == "dephase":
            return _dec.mix_dephasing(state, prob, t[0], shadow_n)
        if op.kind == "dephase2":
            return _dec.mix_two_qubit_dephasing(state, prob, t[0], t[1], shadow_n)
        if op.kind == "depolarise":
            return _dec.mix_depolarising(state, prob, t[0], shadow_n)
        return _dec.mix_damping(state, prob, t[0], shadow_n)

    sides = [(t, c, False)]
    if shadow_n is not None:
        sides.append((tuple(q + shadow_n for q in t),
                      tuple(q + shadow_n for q in c), True))
    for targets, controls, conj in sides:
        a = -theta if (conj and op.kind != "ry") else theta
        if op.kind == "rx":
            state = _ap.apply_matrix(state, _rx_pair(a).astype(dt), targets,
                                     controls, cs)
        elif op.kind == "ry":
            state = _ap.apply_matrix(state, _ry_pair(a).astype(dt), targets,
                                     controls, cs)
        elif op.kind == "rz":
            state = _ap.apply_diagonal(state, _rz_diag(a).astype(dt), targets,
                                       controls, cs)
        elif op.kind == "phase":
            state = _ap.apply_diagonal(state, _phase_diag(a).astype(dt),
                                       targets, controls, cs)
        elif op.kind == "mrz":
            state = _ap.apply_multi_rotate_z(state, a, targets)
        elif op.kind == "mrp":
            state = _apply_mrp(state, theta, targets, op.codes, conj)
        else:
            raise ValueError(f"unknown parametric op kind {op.kind!r}")
    return state


# ---------------------------------------------------------------------------
# program construction
# ---------------------------------------------------------------------------

def _runner(pc: ParamCircuit, density: bool, remat_every: int = 0):
    ops = tuple(pc.ops)
    n = pc.num_qubits

    def apply_ops(block, params, state):
        for op in block:
            if isinstance(op, GateOp):
                state = _apply_one(state, op)
                if density:
                    state = _apply_one(state, _shadow_op(op, n))
            else:
                state = _apply_param_op(state, op, params,
                                        n if density else None)
        return state

    def run(params, state):
        params = jnp.asarray(params)
        if not jnp.issubdtype(params.dtype, jnp.floating):
            params = params.astype(_prec.CONFIG.real_dtype)
        if remat_every and remat_every > 0:
            # rematerialise per block: jax.grad then tapes one state per
            # BLOCK (recomputing each block's interior in the backward
            # sweep) instead of one per op — the memory control for noisy
            # circuits, where the adjoint method's uncompute cannot apply
            for i in range(0, len(ops), remat_every):
                block = ops[i:i + remat_every]
                state = jax.checkpoint(
                    lambda p, s, _b=block: apply_ops(_b, p, s))(params, state)
            return state
        return apply_ops(ops, params, state)

    return run


def build(pc: ParamCircuit, density: bool = False, remat_every: int = 0):
    """Compile to a jitted pure ``(params, state) -> state``.

    ``state`` is the usual (2, 2^m) SoA real pair (m = n for statevectors,
    2n Choi-flattened for ``density=True``) and may be sharded over a device
    mesh; ``params`` is a flat real vector of ``pc.num_params`` entries.
    The result differentiates (``jax.grad`` w.r.t. params or state) and
    vmaps (batched params and/or states).

    ``remat_every=K`` wraps every K ops in ``jax.checkpoint`` so reverse-mode
    tapes one state per block instead of one per op (forward recompute in
    the backward sweep) — use for gradients of DEEP noisy/density circuits;
    unitary statevector circuits should prefer :func:`adjoint_gradient_fn`,
    which needs no taping at all."""
    return jax.jit(_runner(pc, density, remat_every))


def _zero_state(num_qubits: int, density: bool, dtype):
    m = 2 * num_qubits if density else num_qubits
    return jnp.zeros((2, 1 << m), dtype=dtype).at[0, 0].set(1.0)


def state_fn(pc: ParamCircuit, init=None, density: bool = False):
    """Jitted ``params -> state``: the circuit applied to ``init`` (default
    |0…0⟩, or ρ=|0…0⟩⟨0…0| in density mode).  ``init`` may be an amplitude
    pair array or a Qureg (whose density flag then wins)."""
    init, density = _resolve_init(pc, init, density)
    run = _runner(pc, density)

    @jax.jit
    def fn(params):
        state = (_zero_state(pc.num_qubits, density, _prec.CONFIG.real_dtype)
                 if init is None else init)
        return run(params, state)

    return fn


def _resolve_init(pc, init, density):
    if init is None:
        return None, density
    if hasattr(init, "amps") and hasattr(init, "is_density_matrix"):  # Qureg
        return init.amps, init.is_density_matrix
    return jnp.asarray(init), density


def expectation_fn(pc: ParamCircuit, hamil, init=None, density: bool = False,
                   coeffs_arg: bool = False, remat_every: int = 0):
    """Jitted ``params -> <H>``: run the circuit from ``init`` and evaluate
    the PauliHamil expectation with the fused one-pass Pauli-sum kernel
    (ops/calc.py — no workspace clone, one structured pass per term).  This is the
    VQE/QAOA objective: compose with ``jax.value_and_grad`` for energy and
    full gradient in one forward+adjoint program, or ``jax.vmap`` for
    batched multi-start optimisation.

    ``coeffs_arg=True`` returns ``(params, coeffs) -> <H(coeffs)>`` instead:
    the term coefficients become a traced argument (the Pauli strings stay
    static), so ``jax.grad`` also differentiates through the HAMILTONIAN —
    the Hamiltonian-learning/fitting idiom (∂<H>/∂c_t is just <P_t>, and the
    adjoint pass delivers the whole vector at once)."""
    from .api import _pauli_sum_masks, _pauli_sum_terms  # lazy: api is the upper layer

    codes = np.asarray(hamil.pauli_codes)
    cf = jnp.asarray(np.asarray(hamil.term_coeffs, dtype=np.float64))
    init, density = _resolve_init(pc, init, density)
    run = _runner(pc, density, remat_every)
    n = pc.num_qubits
    if density:
        xm, zym, yc = _pauli_sum_masks(codes)
    else:
        terms = _pauli_sum_terms(codes)

    def _energy(params, coeffs):
        state = (_zero_state(n, density, _prec.CONFIG.real_dtype)
                 if init is None else init)
        state = run(params, state)
        if density:
            return _calc.expec_pauli_sum_densmatr(state, xm, zym, yc, coeffs, n)
        return _calc.expec_pauli_sum_statevec(state, terms, coeffs)

    if coeffs_arg:
        return jax.jit(lambda params, coeffs: _energy(params, jnp.asarray(coeffs)))
    return jax.jit(lambda params: _energy(params, cf))


# ---------------------------------------------------------------------------
# adjoint-mode differentiation: O(1)-memory gradients of unitary circuits
#
# jax.grad of expectation_fn tapes every intermediate state (depth x 2^n
# memory) for the reverse pass.  A unitary circuit needs none of that: the
# reverse pass can UNCOMPUTE states by applying gate inverses, holding only
# |psi_k> and the adjoint state |lambda> = H|psi> (the adjoint-differentiation
# method of quantum simulation).  For each parametric gate U_k = exp(-i th
# G/2), dE/dth = 2 Re<lambda| dU_k |psi_{k-1}> = Im<lambda| G |psi_k>, so the
# sweep applies the (projected) generator G to a scratch copy, takes one
# inner product, then uncomputes both states — three live statevectors for
# ANY depth, where taped reverse-mode holds depth+1.
# ---------------------------------------------------------------------------

_Z_DIAG = np.stack([np.array([1.0, -1.0]), np.zeros(2)])


def _inverse_gate_op(op: GateOp) -> GateOp:
    """Host-side inverse of a static gate record (adjoint method requires
    the circuit to be unitary; diagonals invert by reciprocal so any
    unit-modulus diagonal is exact)."""
    if op.kind in ("x", "y", "swap"):
        return op  # self-inverse
    if op.kind == "mrz":
        return GateOp("mrz", op.targets, op.controls, op.control_states,
                      (-op.matrix[0],), None)
    p = op.payload()
    if op.kind == "matrix":
        inv = np.stack([p[0].T, -p[1].T])  # conjugate transpose
    elif op.kind == "diagonal":
        mag2 = p[0] ** 2 + p[1] ** 2
        inv = np.stack([p[0] / mag2, -p[1] / mag2])
    else:
        raise ValueError(f"adjoint method cannot invert gate kind {op.kind!r}")
    return GateOp(op.kind, op.targets, op.controls, op.control_states,
                  tuple(inv.ravel()), op.shape)


def _gen_inner_im(lam, psi, op: ParamOp):
    """Im<lambda| P_c G |psi> for the op's generator, plus the kind's
    prefactor: rotations exp(-i th G/2) contribute Im(.), the phase gate
    exp(+i th P) contributes -2 Im(.)."""
    cs = op.control_states or (1,) * len(op.controls)
    mult = 1.0
    chi = psi
    if op.kind == "rx":
        chi = _ap.apply_pauli_x(chi, op.targets[0], (), ())
    elif op.kind == "ry":
        chi = _ap.apply_pauli_y(chi, op.targets[0], (), ())
    elif op.kind == "rz":
        chi = _ap.apply_diagonal(chi, jnp.asarray(_Z_DIAG, dtype=chi.dtype),
                                 op.targets)
    elif op.kind == "phase":
        proj1 = np.stack([np.array([0.0, 1.0]), np.zeros(2)])
        chi = _ap.apply_diagonal(chi, jnp.asarray(proj1, dtype=chi.dtype),
                                 op.targets)
        mult = -2.0
    elif op.kind == "mrz":
        k = len(op.targets)
        par = np.array([1.0 - 2.0 * (bin(i).count("1") % 2)
                        for i in range(1 << k)])
        base = np.stack([par, np.zeros_like(par)])
        chi = _ap.apply_diagonal(chi, jnp.asarray(base, dtype=chi.dtype),
                                 op.targets)
    elif op.kind == "mrp":
        if not any(op.codes):
            # all-identity string: the forward applies NOTHING (reference
            # convention, QuEST_common.c:436-437), so dU/dtheta = 0 — without
            # this skip chi = psi would contribute a spurious Im<lam|psi>
            return jnp.zeros((), dtype=psi.dtype)
        for t, code in zip(op.targets, op.codes):
            if code == 1:
                chi = _ap.apply_pauli_x(chi, t, (), ())
            elif code == 2:
                chi = _ap.apply_pauli_y(chi, t, (), ())
            elif code == 3:
                chi = _ap.apply_diagonal(chi, jnp.asarray(_Z_DIAG, dtype=chi.dtype),
                                         (t,))
    else:
        raise ValueError(f"adjoint method cannot differentiate {op.kind!r}")
    if op.controls:
        # projector over the controls: a 0/1 diagonal with a single 1 at the
        # all-controls-match index (the P_c in the controlled generator
        # P_c (x) G) — one convention for every parametric kind
        full = np.zeros((2, 1 << len(op.controls)))
        idx = sum(int(s) << i for i, s in enumerate(cs))
        full[0, idx] = 1.0
        chi = _ap.apply_diagonal(chi, jnp.asarray(full, dtype=chi.dtype),
                                 op.controls)
    # Im<lam|chi> in the STATE dtype: the f64-accumulating inner product
    # materialises 2x-size converted copies, which is what pushed the
    # 28-qubit adjoint program over HBM (16.08 of 15.75 GiB)
    return mult * jnp.sum(lam[0] * chi[1] - lam[1] * chi[0])


def adjoint_gradient_fn(pc: ParamCircuit, hamil, init=None):
    """Jitted ``params -> (energy, gradient)`` by the adjoint method —
    matching ``jax.grad(expectation_fn(...))`` to machine precision at
    THREE live statevectors for any circuit depth (taped reverse-mode
    holds depth+1 intermediate states, which is what OOMs deep large-n
    circuits).

    Requires a unitary statevector circuit (no noise ops; any recorded
    static matrix must be unitary — its inverse is taken as the conjugate
    transpose); violations raise ``QuESTError`` with the gradient-serving
    validation codes (``E_GRADIENT_NOT_UNITARY`` for noise channels and
    non-unitary payloads, ``E_GRADIENT_DENSITY_MODE`` for density
    registers) — the same codes ``QuESTService.submit_gradient`` rejects
    with at admission.  The sweep itself is the shared serving body
    (quest_tpu/grad/adjoint.py ``adjoint_terms_fn``); this wrapper closes
    it over the initial state and the Hamiltonian's coefficients, where
    the serve cache keeps both as runtime operands.  TPU-native extension;
    no reference analogue."""
    from .grad.adjoint import (adjoint_terms_fn, hamil_masks,
                               validate_gradient_circuit)
    from .validation import ErrorCode, MESSAGES, QuESTError

    validate_gradient_circuit(pc, "adjoint_gradient_fn")
    terms = hamil_masks(hamil)
    cf = jnp.asarray(np.asarray(hamil.term_coeffs, dtype=np.float64))
    init, density = _resolve_init(pc, init, False)
    if density:
        raise QuESTError(ErrorCode.GRADIENT_DENSITY_MODE,
                         MESSAGES[ErrorCode.GRADIENT_DENSITY_MODE],
                         "adjoint_gradient_fn")
    n = pc.num_qubits
    body = adjoint_terms_fn(pc.ops, n, pc.num_params, terms)

    @jax.jit
    def value_and_grad(params):
        psi = (_zero_state(n, False, _prec.CONFIG.real_dtype)
               if init is None else init)
        return body(psi, params, cf)

    return value_and_grad
