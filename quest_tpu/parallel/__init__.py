"""Distribution layer: device meshes, amplitude sharding, explicit collectives.

The reference's distribution is component 10 of SURVEY.md §2 — an MPI
communication planner (QuEST_cpu_distributed.c) deciding per gate whether a
pairwise chunk exchange is needed.  Here the same decisions exist at three
levels:

1. implicit — every op in quest_tpu.ops is a pure jnp program; GSPMD
   partitions it over the mesh and inserts collective-permute / all-gather /
   psum automatically (the default path, used by the API layer);
2. explicit — :mod:`.collectives` provides shard_map-based building blocks
   (pairwise exchange over a hypercube edge, global reductions) mirroring the
   reference's primitives one-for-one, for kernels that want manual control;
3. diagnostic — :mod:`.planner` reports which gates of a circuit are
   shard-local vs cross-shard for a given mesh, the analogue of the
   reference's halfMatrixBlockFitsInChunk decision procedure
   (QuEST_cpu_distributed.c:356-361);
4. optimizer — :mod:`.scheduler` consumes the planner's cost model to
   REWRITE circuits: commutation-DAG reordering, permutation epochs, fused
   swap networks and a greedy placement search (Circuit.schedule /
   compile_circuit(num_devices=...), docs/SCHEDULER.md);
5. pipelined — :mod:`.executor` lowers the scheduled circuit with every
   cross-shard collective chunked and double-buffered against gate compute
   (compile_circuit(..., overlap=True), docs/SCHEDULER.md "Pipelined
   execution").
"""

from .mesh import make_amps_mesh, amp_sharding, replicated_sharding  # noqa: F401
from .collectives import (pairwise_exchange, global_sum,  # noqa: F401
                          gather_full_state)
from .planner import (comm_plan, comm_summary, is_shard_local,  # noqa: F401
                      local_qubit_count, recommend_pipeline_chunks,
                      sub_tile_shard, time_model)
from .scheduler import (commutation_dag, greedy_placement,  # noqa: F401
                        schedule, schedule_savings)
from .executor import (overlapped_program, plan_overlap,  # noqa: F401
                       predict_overlap)
