"""Explicit shard-level collectives via shard_map.

One-for-one TPU translations of the reference's MPI primitives (SURVEY §2b):

| reference (QuEST_cpu_distributed.c)        | here                       |
|--------------------------------------------|----------------------------|
| exchangeStateVectors MPI_Sendrecv (:479)   | ``pairwise_exchange``      |
| MPI_Allreduce(SUM) (:88, :1260, ...)       | ``global_sum``             |
| copyVecIntoMatrixPairState MPI_Bcast (:371)| ``gather_full_state``      |

The default API path never calls these — GSPMD derives the same collectives
from sharding propagation.  They exist for manual-control kernels (ring
pipelines, Pallas RDMA experiments) and as an executable specification of the
communication pattern.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .._compat import shard_map

from .mesh import AMPS_AXIS


def pairwise_exchange(state: jax.Array, mesh: Mesh, distance: int) -> jax.Array:
    """Exchange whole shards between partner devices ``d`` and ``d ^ distance``
    (the hypercube edge of a gate on sharded qubit ``log2(distance)`` above
    the local range — ref: getChunkPairId, QuEST_cpu_distributed.c:303-312).

    Returns the partner's shard in place of ours (the reference's
    pairStateVec, without the 2x memory mirror: XLA streams the permute)."""
    n_dev = mesh.devices.size
    perm = [(d, d ^ distance) for d in range(n_dev)]

    @partial(shard_map, mesh=mesh, in_specs=P(None, AMPS_AXIS),
             out_specs=P(None, AMPS_AXIS))
    def exchange(shard):
        return jax.lax.ppermute(shard, AMPS_AXIS, perm)

    return exchange(state)


def global_sum(values: jax.Array, mesh: Mesh) -> jax.Array:
    """Sum a per-shard reduction across the mesh (ref: MPI_Allreduce(SUM))."""

    @partial(shard_map, mesh=mesh, in_specs=P(None, AMPS_AXIS), out_specs=P())
    def reduce(shard):
        return jax.lax.psum(jnp.sum(shard, axis=-1, keepdims=True), AMPS_AXIS)

    return jnp.sum(reduce(values))


def gather_full_state(state: jax.Array, mesh: Mesh) -> jax.Array:
    """Replicate the full state onto every device (ref: the rotating MPI_Bcast
    of copyVecIntoMatrixPairState, QuEST_cpu_distributed.c:371-413)."""

    @partial(shard_map, mesh=mesh, in_specs=P(None, AMPS_AXIS),
             out_specs=P(None), check_vma=False)
    def gather(shard):
        return jax.lax.all_gather(shard, AMPS_AXIS, axis=1, tiled=True)

    return gather(state)
