"""Communication planner / diagnostics.

The reference decides at runtime, per gate, whether MPI communication is
needed (halfMatrixBlockFitsInChunk, QuEST_cpu_distributed.c:356-361) and
routes dense multi-target gates through swap-rerouting (:1381-1479).  Under
GSPMD those decisions are made by the partitioner at compile time; this
module reproduces them as an inspectable plan so users can see — before
compiling — which gates of a circuit will ride ICI and what each costs.
"""

from __future__ import annotations

import dataclasses


def is_shard_local(target: int, num_qubits: int, num_devices: int) -> bool:
    """A gate on ``target`` touches only in-shard amplitude pairs iff the
    target lies below the sharded range (ref: halfMatrixBlockFitsInChunk)."""
    if num_devices <= 1:
        return True
    local_qubits = num_qubits - (num_devices.bit_length() - 1)
    return target < local_qubits


@dataclasses.dataclass
class GatePlan:
    index: int
    kind: str
    targets: tuple
    local: bool
    comm: str          # 'none' | 'permute' | 'reshard'
    bytes_moved: int   # per device, one direction


def comm_plan(circuit, num_devices: int, bytes_per_amp: int = 8) -> list:
    """Static communication plan of a :class:`quest_tpu.Circuit` over an
    n-device amplitude mesh.  ``bytes_per_amp`` defaults to f32 SoA (8 B)."""
    from ..ops.apply import _control_style

    n = circuit.num_qubits
    shard_amps = (1 << n) // num_devices
    plans = []
    for i, op in enumerate(circuit.ops):
        cross = [t for t in op.targets
                 if not is_shard_local(t, n, num_devices)]
        cross_c = [c for c in op.controls
                   if not is_shard_local(c, n, num_devices)]
        # a prefix-control on a SHARDED axis: under the default slice style
        # the slice-update makes GSPMD exchange (measured: collective-permute
        # + all-reduce); the select style masks elementwise — zero collectives
        ctrl_comm = bool(cross_c) and _control_style() == "slice"

        if op.kind == "mrz":
            # parity-phase rotation: iota+popcount elementwise multiply
            # (ops/apply.py apply_multi_rotate_z) — comm-free on any sharding
            plans.append(GatePlan(i, op.kind, op.targets, True, "none", 0))
            continue

        if op.kind == "diagonal":
            # diagonal gates are broadcast multiplies — comm-free — and the
            # engine absorbs controls into the factor only while
            # targets+controls fit one expanded diagonal (<= 16 wires,
            # ops/apply.py apply_diagonal); beyond that apply_diagonal
            # ALWAYS slice-updates (it has no select-style branch), which
            # communicates on a sharded control regardless of
            # QUEST_TPU_CONTROL_STYLE
            absorbed = (not op.controls
                        or len(op.targets) + len(op.controls) <= 16)
            if absorbed or not cross_c:
                plans.append(GatePlan(i, op.kind, op.targets, True, "none", 0))
            else:
                plans.append(GatePlan(i, op.kind, op.targets, False, "permute",
                                      shard_amps * bytes_per_amp))
            continue

        if not cross:
            if ctrl_comm:
                plans.append(GatePlan(i, op.kind, op.targets, False, "permute",
                                      shard_amps * bytes_per_amp))
            else:
                plans.append(GatePlan(i, op.kind, op.targets, True, "none", 0))
        elif len(op.targets) == 1:
            # cross-shard target; a slice-style sharded control adds its own
            # exchange on top of the pairwise permute
            extra = shard_amps * bytes_per_amp if ctrl_comm else 0
            plans.append(GatePlan(i, op.kind, op.targets, False, "permute",
                                  shard_amps * bytes_per_amp + extra))
        else:
            # dense multi-target with sharded targets: GSPMD reshards (the
            # reference's swap-rerouting, one all-to-all each way)
            extra = shard_amps * bytes_per_amp if ctrl_comm else 0
            plans.append(GatePlan(i, op.kind, op.targets, False, "reshard",
                                  2 * shard_amps * bytes_per_amp + extra))
    return plans
