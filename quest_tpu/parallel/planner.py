"""Communication planner / diagnostics.

The reference decides at runtime, per gate, whether MPI communication is
needed (halfMatrixBlockFitsInChunk, QuEST_cpu_distributed.c:356-361) and
routes dense multi-target gates through swap-rerouting (:1381-1479).  Under
GSPMD those decisions are made by the partitioner at compile time; this
module reproduces them as an inspectable plan so users can see — before
compiling — which gates of a circuit will ride ICI and what each costs.
"""

from __future__ import annotations

import dataclasses

from .. import obs as _obs


def local_qubit_count(num_qubits: int, num_devices: int) -> int:
    """Number of shard-local qubits of an ``num_devices``-way amplitude mesh:
    positions ``>= local_qubit_count`` index the sharded prefix of the
    amplitude axis (ref: the chunk-size arithmetic around
    halfMatrixBlockFitsInChunk, QuEST_cpu_distributed.c:356-361)."""
    if num_devices <= 1:
        return num_qubits
    return num_qubits - (num_devices.bit_length() - 1)


def is_shard_local(target: int, num_qubits: int, num_devices: int) -> bool:
    """A gate on ``target`` touches only in-shard amplitude pairs iff the
    target lies below the sharded range (ref: halfMatrixBlockFitsInChunk)."""
    return target < local_qubit_count(num_qubits, num_devices)


@dataclasses.dataclass
class GatePlan:
    index: int
    kind: str
    targets: tuple
    local: bool
    comm: str          # 'none' | 'permute' | 'reshard'
    bytes_moved: int   # per device, one direction


def sub_tile_shard(num_qubits: int, num_devices: int) -> bool:
    """True iff each per-device shard is SMALLER than one full lane row
    (2^l amps, the minor dim of the (8, 128) register tile).  In that
    regime the wire-position comm model above is incomplete: the kernels'
    grouped views keep the lane block as their minor axis, so a shard that
    cannot hold one lane row re-tiles across devices on every reshape —
    gates that are "local" by wire position still communicate (found by
    the PR 3 lowered-program audit on a 9-qubit register over 8 devices:
    64 amps/shard vs the 128-wide lane; a 512-amp 12q/8-device shard
    holds whole lane rows and audits clean).  :func:`comm_plan` charges
    such gates the ``subtile`` comm class and the analyzer emits a
    WARNING (``A_SUBTILE_SHARD``)."""
    from ..ops.apply import _blocks
    if num_devices <= 1:
        return False
    lane = _blocks(num_qubits)[0]
    return (1 << num_qubits) // num_devices < (1 << lane)


def comm_plan(circuit, num_devices: int, bytes_per_amp: int = 8) -> list:
    """Static communication plan of a :class:`quest_tpu.Circuit` over an
    n-device amplitude mesh.  ``bytes_per_amp`` defaults to f32 SoA (8 B).

    On sub-tile shards (:func:`sub_tile_shard`) every dense-kind gate is
    charged one extra shard pass as the ``subtile`` comm class, however
    local its wires: below one register tile the layout itself is
    interleaved across devices and reshapes communicate."""
    from ..ops.apply import _control_style

    n = circuit.num_qubits
    shard_amps = (1 << n) // num_devices
    plans = []
    for i, op in enumerate(circuit.ops):
        cross = [t for t in op.targets
                 if not is_shard_local(t, n, num_devices)]
        cross_c = [c for c in op.controls
                   if not is_shard_local(c, n, num_devices)]
        # a prefix-control on a SHARDED axis: under the default slice style
        # the slice-update makes GSPMD exchange (measured: collective-permute
        # + all-reduce); the select style masks elementwise — zero collectives
        ctrl_comm = bool(cross_c) and _control_style() == "slice"

        if op.kind == "mrz":
            # parity-phase rotation: iota+popcount elementwise multiply
            # (ops/apply.py apply_multi_rotate_z) — comm-free on any sharding
            plans.append(GatePlan(i, op.kind, op.targets, True, "none", 0))
            continue

        if op.kind == "bitperm":
            # fused qubit permutation (parallel/scheduler.py): one grouped
            # transpose.  All cross-shard moves ride ONE all-to-all (the
            # whole point of fusing a swap network), so a bitperm touching
            # the sharded range costs one reshard total; a shard-local one
            # is pure local data movement
            if cross:
                plans.append(GatePlan(i, op.kind, op.targets, False,
                                      "reshard", 2 * shard_amps * bytes_per_amp))
            else:
                plans.append(GatePlan(i, op.kind, op.targets, True, "none", 0))
            continue

        if op.kind == "diagonal":
            # diagonal gates are broadcast multiplies — comm-free — and the
            # engine absorbs controls into the factor only while
            # targets+controls fit one expanded diagonal (<= 16 wires,
            # ops/apply.py apply_diagonal); beyond that apply_diagonal
            # ALWAYS slice-updates (it has no select-style branch), which
            # communicates on a sharded control regardless of
            # QUEST_TPU_CONTROL_STYLE
            absorbed = (not op.controls
                        or len(op.targets) + len(op.controls) <= 16)
            if absorbed or not cross_c:
                plans.append(GatePlan(i, op.kind, op.targets, True, "none", 0))
            else:
                plans.append(GatePlan(i, op.kind, op.targets, False, "permute",
                                      shard_amps * bytes_per_amp))
            continue

        if not cross:
            if ctrl_comm:
                plans.append(GatePlan(i, op.kind, op.targets, False, "permute",
                                      shard_amps * bytes_per_amp))
            else:
                plans.append(GatePlan(i, op.kind, op.targets, True, "none", 0))
        elif len(op.targets) == 1:
            # cross-shard target; a slice-style sharded control adds its own
            # exchange on top of the pairwise permute
            extra = shard_amps * bytes_per_amp if ctrl_comm else 0
            plans.append(GatePlan(i, op.kind, op.targets, False, "permute",
                                  shard_amps * bytes_per_amp + extra))
        else:
            # dense multi-target with sharded targets: GSPMD reshards (the
            # reference's swap-rerouting, one all-to-all each way)
            extra = shard_amps * bytes_per_amp if ctrl_comm else 0
            plans.append(GatePlan(i, op.kind, op.targets, False, "reshard",
                                  2 * shard_amps * bytes_per_amp + extra))
    if sub_tile_shard(n, num_devices):
        # below one register tile, "local" dense kernels still re-tile
        # across devices; diagonal/mrz stay elementwise broadcasts
        for j, p in enumerate(plans):
            if p.comm == "none" and p.kind not in ("diagonal", "mrz"):
                plans[j] = GatePlan(p.index, p.kind, p.targets, False,
                                    "subtile", shard_amps * bytes_per_amp)
    return plans


def comm_summary(circuit, num_devices: int, bytes_per_amp: int = 8) -> dict:
    """Aggregate view of :func:`comm_plan` — the scheduler's objective
    terms: how many collectives the circuit issues on an ``num_devices``-way
    mesh and how many bytes they move (per device, one direction)."""
    plans = comm_plan(circuit, num_devices, bytes_per_amp)
    return {
        "ops": len(plans),
        "comm_events": sum(1 for p in plans if p.comm != "none"),
        "permute_events": sum(1 for p in plans if p.comm == "permute"),
        "reshard_events": sum(1 for p in plans if p.comm == "reshard"),
        "subtile_events": sum(1 for p in plans if p.comm == "subtile"),
        "bytes_moved": sum(p.bytes_moved for p in plans),
    }


# ---------------------------------------------------------------------------
# ICI time model (SURVEY §7.5 / BASELINE north star)
#
# Extends the comm plan into wall-time estimates: per gate, t is
# compute + comm serially, or max(compute, comm) + the per-chunk ramp when
# the overlapped executor pipelines the event (see GateTime.total_s) —
# with compute as HBM-roofline passes at a MEASURED
# efficiency (calibrated against the single-chip bench rows this model can
# check), comm as bytes over ICI links.  Chip figures are the public specs
# used by the scaling literature (jax-ml.github.io/scaling-book): per-chip
# HBM bandwidth, per-link one-way ICI bandwidth, link count (torus degree).
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ChipSpec:
    name: str
    hbm_bytes_per_sec: float
    ici_link_bytes_per_sec: float  # one-way, per link
    ici_links: int                 # torus degree (v5e 2-D: 4, v5p 3-D: 6)
    hbm_bytes: float
    vmem_bytes: float = 128 * 2**20  # on-chip vector memory (both gens)


V5E = ChipSpec("v5e", 819e9, 4.5e10, 4, 16e9)
V5P = ChipSpec("v5p", 2765e9, 9e10, 6, 95e9)

#: name -> spec, for resolving a calibration profile's reference chip
_CHIPS_BY_NAME = {"v5e": V5E, "v5p": V5P}

# smallest per-chunk collective worth issuing: below ~this many seconds on
# the wire a chunk is latency- not bandwidth-bound, and further splitting
# stops buying overlap (the per-chunk ramp of GateTime.total_s grows
# without shrinking the hidden span)
_MIN_CHUNK_COMM_SECONDS = 4e-6
# pipeline depth cap: beyond this the scheduling overhead (one async
# start/done pair per chunk) outweighs the ramp reduction
_MAX_PIPELINE_CHUNKS = 16

# Measured single-chip HBM efficiency (achieved/peak) per engine class, from
# the recorded bench rows (BENCH_r04/r05: hbm_peak_frac of the matching
# config).  The model multiplies the roofline by these, so its single-chip
# predictions reproduce the measured rows by construction and its MULTI-chip
# projections inherit measured compute behaviour rather than peak-paper
# numbers.
MEASURED_EFFICIENCY = {
    "f32_gate": 0.18,     # calibrated: model == measured random24_f32_unfused
    "f32_fused": 0.26,    # random24_f32_fused hbm_peak_frac (r04: 0.20-0.27)
    "f32_inplace": 0.29,  # qft_30q in-place engine (r04/r05: 0.27-0.31)
    "f64_gate": 0.065,    # random24_f64_unfused (r05; X64-emulated stack)
    "f64_best": 0.21,     # best measured f64 flip-kernel window (r05)
    # the general epoch executor (ops/epoch_pallas.py) inherits the in-place
    # engine class it generalizes: its passes are the same aliased
    # block/fiber kernels the qft_30q rows measured at 0.27-0.31.  The
    # three pass kinds get their own classes so a calibration profile can
    # fit them separately (obs/calibrate.py measures each):
    "pallas_epoch": 0.29,        # fused block passes, full (128,8,128) walk
    "pallas_epoch_pack": 0.29,   # staged high-qubit pack passes
    # the degenerate single-block geometry (10 <= n <= 16): the whole state
    # is one VMEM tile, so passes are launch-latency- not bandwidth-bound;
    # the default is deliberately conservative until a profile fits it
    "pallas_epoch_small": 0.20,
    # passes containing fused SUPEROPERATOR stages (density noise channels
    # lowered as elementwise bit-flip/select stages, ops/epoch_pallas.py
    # _apply_super_spec): still one aliased HBM read+write, but the stage
    # arithmetic is VPU flips/selects rather than MXU contractions, so the
    # default is priced below the matmul classes until a calibration
    # profile fits the real cost (obs/calibrate.py measures a
    # damping-layer block pass as the ``super_block`` row)
    "pallas_epoch_super": 0.22,
}


def efficiency_for(engine_class: str, chip: "ChipSpec | None" = None) -> float:
    """The live efficiency constant for ``engine_class``: the active
    calibration profile's fitted value (obs/calibrate.py — measured on the
    deployment's own backend by ``analysis --calibrate``) when one is
    loaded, else the hard-coded :data:`MEASURED_EFFICIENCY` default.  This
    is the ONE read point every model in this module goes through, so
    loading a profile retunes ``time_model`` / ``engine_time_model`` /
    ``select_engine`` and the scheduler's placement search together.

    A fitted efficiency is DEFINED relative to the reference chip the
    profile was built against (``pass_s = 2·bytes / (profile_chip_peak ·
    eff)``); when the caller scores against a DIFFERENT ``chip`` spec the
    value is rescaled by the reference-peak ratio so the implied pass
    seconds — the thing that was actually measured — are preserved
    exactly (a v5e-referenced profile consumed by a ``--chip v5p`` model
    must not silently mis-scale by the HBM-peak ratio)."""
    from ..obs import calibrate as _cal
    prof = _cal.active_profile()
    if prof is not None:
        fitted = prof.efficiencies.get(engine_class)
        if fitted:
            fitted = float(fitted)
            ref = _CHIPS_BY_NAME.get(prof.chip)
            if chip is not None and ref is not None \
                    and ref.name != chip.name:
                fitted *= ref.hbm_bytes_per_sec / chip.hbm_bytes_per_sec
            return fitted
    return MEASURED_EFFICIENCY[engine_class]


def calibration_provenance() -> dict:
    """The provenance stamp engine decisions and ledger records carry:
    the active profile's summary (id, platform, age, band), or the
    explicit ``{"source": "default"}`` marker so a consumer can always
    tell WHICH constants produced a decision."""
    from ..obs import calibrate as _cal
    summary = _cal.active_summary()
    if summary is None:
        return {"source": "default"}
    return {"source": "profile", **summary}


def _collective_bytes_per_sec(chip: "ChipSpec", comm_class: str) -> float | None:
    """Fitted effective bytes/sec for a comm class from the active
    calibration profile (the harness's ppermute/bitperm sweep), or None
    to use the chip-spec formula.  The fitted constant absorbs topology —
    it was measured on the deployment's own mesh."""
    from ..obs import calibrate as _cal
    prof = _cal.active_profile()
    if prof is None:
        return None
    bw = prof.collective_bytes_per_sec.get(
        "permute" if comm_class in ("permute", "subtile") else "reshard")
    return float(bw) if bw else None


def memory_footprint(num_qubits: int, num_devices: int = 1,
                     precision: int = 1, is_density_matrix: bool = False,
                     transient_factor: float = 2.0) -> dict:
    """Static memory model of one register over an amplitude mesh.

    ``precision`` follows the precision.py convention (1 -> f32 SoA, 8 B per
    amplitude; else f64, 16 B).  ``transient_factor`` models XLA's working
    set: a non-donated gate program holds input and output buffers of the
    sharded state live at once (2.0); in-place plane engines with donation
    run at 1.0.  Consumed by quest_tpu.analysis (the pre-flight OOM check)
    and exposed for capacity planning next to time_model."""
    n = num_qubits * (2 if is_density_matrix else 1)
    bytes_per_amp = 8 if precision == 1 else 16
    state_bytes = (1 << n) * bytes_per_amp
    shard_bytes = state_bytes // max(num_devices, 1)
    return {
        "num_qubits": num_qubits,
        "state_bytes": state_bytes,
        "shard_bytes": shard_bytes,
        "peak_shard_bytes": int(shard_bytes * transient_factor),
        "bytes_per_amp": bytes_per_amp,
        "devices": num_devices,
        "sub_tile_shard": sub_tile_shard(n, num_devices),
    }


@dataclasses.dataclass
class GateTime:
    index: int
    kind: str
    comm: str
    compute_s: float
    comm_s: float
    pipeline_chunks: int = 1   # chunks the overlapped executor splits into
    hideable: bool = False     # can the executor pipeline comm behind compute?

    @property
    def total_s(self) -> float:
        # The executor fully serializes pairwise exchange and gate
        # arithmetic unless pipelined (the exchanged halves gate the FMA) —
        # so the base cost is the SUM, not the old optimistic midpoint.  A
        # hideable event split into C chunks pipelines to
        # max(compute, comm) plus the per-chunk ramp min(compute, comm)/C:
        # the first chunk's collective has nothing yet to hide behind
        # (parallel/executor.py; docs/SCHEDULER.md "Pipelined execution").
        if self.hideable and self.pipeline_chunks > 1:
            return max(self.compute_s, self.comm_s) + \
                min(self.compute_s, self.comm_s) / self.pipeline_chunks
        return self.compute_s + self.comm_s


def time_model(circuit, num_devices: int, chip: ChipSpec = V5E,
               precision: int = 1,
               efficiency: float | None = None,
               pipeline_chunks: int = 1) -> list:
    """Per-gate wall-time estimates for ``circuit`` over an
    ``num_devices``-chip amplitude mesh of ``chip``s.

    compute = passes x 2 x shard_bytes / (hbm_bw x efficiency);
    comm    = bytes_moved / ici_link_bw ('permute'/'subtile': the
    reference's pairwise exchange — one partner, one link) or bytes_moved
    x (D-1)/D / (links x ici_link_bw) ('reshard': all-to-all spread over
    the torus links).  Efficiency defaults to the live value for the
    precision's engine class (:func:`efficiency_for`: the active
    calibration profile's fitted constant, else MEASURED_EFFICIENCY);
    with a profile loaded the comm terms likewise use the fitted
    collective bytes/sec in place of the chip-spec formula.

    ``pipeline_chunks > 1`` models the overlapped executor
    (parallel/executor.py): pairwise-exchange events on plain dense
    targets are marked hideable and costed ``max(compute, comm)`` plus the
    per-chunk ramp instead of the serial sum.  Window-level refinement
    (epoch sandwiches hiding a whole bracketed run) lives in
    :func:`quest_tpu.parallel.executor.predict_overlap`, which consumes
    these per-gate figures."""
    from ..validation import validate_num_ranks
    validate_num_ranks(num_devices, "time_model")
    bytes_per_amp = 8 if precision == 1 else 16
    if efficiency is None:
        efficiency = efficiency_for(
            "f32_gate" if precision == 1 else "f64_gate", chip)
    shard_bytes = (1 << circuit.num_qubits) // num_devices * bytes_per_amp
    hbm = chip.hbm_bytes_per_sec * efficiency
    bw_permute = _collective_bytes_per_sec(chip, "permute")
    bw_reshard = _collective_bytes_per_sec(chip, "reshard")
    out = []
    for plan in comm_plan(circuit, num_devices, bytes_per_amp):
        compute = 2.0 * shard_bytes / hbm
        if plan.comm == "none":
            comm = 0.0
        elif plan.comm in ("permute", "subtile"):
            comm = (plan.bytes_moved / bw_permute if bw_permute
                    else plan.bytes_moved / chip.ici_link_bytes_per_sec)
        elif bw_reshard:    # fitted aggregate reshard bandwidth
            comm = plan.bytes_moved / bw_reshard
        else:  # reshard: all-to-all over every torus link
            comm = (plan.bytes_moved * (num_devices - 1) / num_devices
                    / (chip.ici_links * chip.ici_link_bytes_per_sec))
        op = circuit.ops[plan.index]
        hideable = (pipeline_chunks > 1 and plan.comm == "permute"
                    and op.kind in ("matrix", "x", "y")
                    and len(op.targets) == 1 and not op.controls)
        out.append(GateTime(plan.index, plan.kind, plan.comm, compute, comm,
                            pipeline_chunks, hideable))
    return out


def recommend_pipeline_chunks(num_qubits: int, num_devices: int,
                              chip: ChipSpec = V5E,
                              precision: int = 1) -> int:
    """Chunk count the overlapped executor should split each shard into,
    from shard bytes vs the chip's VMEM and ICI figures.

    Lower bound: two in-flight chunks (the one computing and the one on
    the wire) plus their outputs must fit VMEM, so C >= 4 x shard_bytes /
    vmem.  Upper bound: a chunk's pairwise exchange must stay
    bandwidth-bound (>= _MIN_CHUNK_COMM_SECONDS on one link), else the
    per-chunk async overhead eats the hidden span.  Power of two, clamped
    to [1, _MAX_PIPELINE_CHUNKS]; 1 means "do not chunk" (the degenerate
    monolithic path)."""
    if num_devices <= 1:
        return 1
    shard_bytes = memory_footprint(num_qubits, num_devices,
                                   precision)["shard_bytes"]
    need = max(1, -(-4 * shard_bytes // int(chip.vmem_bytes)))  # ceil div
    c = 1
    while c < need:
        c *= 2
    latency_cap = max(1, int(shard_bytes
                             / (chip.ici_link_bytes_per_sec
                                * _MIN_CHUNK_COMM_SECONDS)))
    while c > 1 and c > latency_cap:
        c //= 2
    return min(c, _MAX_PIPELINE_CHUNKS)


def project_random_circuit(num_qubits: int, depth: int, num_devices: int,
                           chip: ChipSpec = V5P, precision: int = 2,
                           efficiency: float | None = None) -> dict:
    """Project the BASELINE north-star workload (Haar 1q layer + CZ ladder
    per depth) on a multi-chip mesh; returns the auditable breakdown
    published in docs/DESIGN.md.

    The per-layer structure mirrors bench.py bench_random: one 1q gate per
    qubit (local below the sharded range, pairwise-exchange above) plus the
    CZ ladder, modeled as UNFUSED per-gate diagonal sweeps (comm-free but
    one HBM pass each — a deliberately conservative bias; the engines fuse
    the ladder into fewer passes)."""
    from ..circuit import random_circuit

    circuit = random_circuit(num_qubits, depth=1, seed=0)
    times = time_model(circuit, num_devices, chip, precision, efficiency)
    layer_s = sum(t.total_s for t in times)
    comm_s = sum(t.comm_s for t in times)
    compute_s = sum(t.compute_s for t in times)
    total_s = layer_s * depth
    amps = (1 << num_qubits)
    gates = num_qubits * depth  # credited 1q amplitude updates
    per_chip = amps * gates / total_s / num_devices
    return {
        "qubits": num_qubits, "depth": depth, "devices": num_devices,
        "chip": chip.name, "precision": precision,
        "sharded_qubits": num_devices.bit_length() - 1,
        "layer_seconds": layer_s, "total_seconds": total_s,
        "layer_comm_seconds": comm_s, "layer_compute_seconds": compute_s,
        "amp_updates_per_sec_per_chip": per_chip,
        "vs_1e8_target": per_chip / 1e8,
    }


# ---------------------------------------------------------------------------
# engine dimension: XLA gate engine vs the Pallas epoch executor
# (ops/epoch_pallas.py) as the compiled-circuit backend.  The scheduler and
# compile_circuit(engine="auto") pick per circuit from the SAME pass-count x
# MEASURED_EFFICIENCY roofline the rest of this module uses, so the choice
# is inspectable before compiling (the module's founding contract).
# ---------------------------------------------------------------------------

#: engines ``compile_circuit`` accepts; "auto" resolves through
#: :func:`select_engine` before anything is keyed or compiled
ENGINES = ("auto", "xla", "pallas")


def engine_time_model(circuit, chip: ChipSpec = V5E, precision: int = 1,
                      plan=None) -> dict:
    """Single-chip wall-time comparison of the two compiled-circuit
    backends for ``circuit``: the per-gate XLA engine (one HBM pass per op,
    ``f32_gate``/``f64_gate`` efficiency — the deliberately conservative
    convention of :func:`time_model`) vs the Pallas epoch executor's fused
    lowering (``plan.hbm_passes`` aliased passes; block passes at the
    measured ``pallas_epoch`` efficiency — or ``pallas_epoch_small`` below
    the full block-walk floor, where the whole state is one VMEM tile and
    passes are latency- not bandwidth-bound — staged pack passes at
    ``pallas_epoch_pack``, fallback XLA segments at the gate efficiency).
    Returns the auditable breakdown ``select_engine`` scores;
    ``pallas_seconds`` is None outside the epoch engine's envelope."""
    from ..ops import epoch_pallas as _ep
    n = circuit.num_qubits
    bytes_per_amp = 8 if precision == 1 else 16
    state_bytes = (1 << n) * bytes_per_amp
    eff_xla = efficiency_for("f32_gate" if precision == 1 else "f64_gate",
                             chip)
    pass_s_xla = 2.0 * state_bytes / (chip.hbm_bytes_per_sec * eff_xla)
    block_class = ("pallas_epoch_small" if n < _ep.HIGH_BASE
                   else "pallas_epoch")
    pass_s_block = 2.0 * state_bytes / (
        chip.hbm_bytes_per_sec * efficiency_for(block_class, chip))
    pass_s_pack = 2.0 * state_bytes / (
        chip.hbm_bytes_per_sec * efficiency_for("pallas_epoch_pack", chip))
    pass_s_super = 2.0 * state_bytes / (
        chip.hbm_bytes_per_sec * efficiency_for("pallas_epoch_super", chip))
    out = {
        "num_qubits": n,
        "ops": len(circuit.ops),
        "xla_hbm_passes": len(circuit.ops),
        "xla_seconds": len(circuit.ops) * pass_s_xla,
        "pallas_supported": _ep.epoch_supported(n, precision),
        "pallas_seconds": None,
        "pallas_hbm_passes": None,
    }
    if not out["pallas_supported"]:
        return out
    if plan is None:
        plan = _ep.plan_circuit(circuit.key(), n)
    out["pallas_hbm_passes"] = plan.hbm_passes
    # a pass carrying >= 1 fused superoperator stage (density noise
    # channels) is priced at the super class — but never BELOW its kind's
    # class (the degenerate single-block geometry is latency-bound at the
    # small class whatever the stage mix): the HBM traffic is the same one
    # aliased read+write, the stage arithmetic is the slower flip/select
    # form
    plain_block = plan.block_passes - plan.super_block_passes
    plain_pack = plan.pack_passes - plan.super_pack_passes
    out["pallas_seconds"] = (
        plain_block * pass_s_block
        + plain_pack * pass_s_pack
        + plan.super_block_passes * max(pass_s_block, pass_s_super)
        + plan.super_pack_passes * max(pass_s_pack, pass_s_super)
        + plan.xla_ops * pass_s_xla)
    out["pallas_pass_breakdown"] = {
        "pallas_passes": plan.pallas_passes,
        "block_passes": plan.block_passes,
        "pack_passes": plan.pack_passes,
        "super_passes": plan.super_passes,
        "super_stages": plan.super_stages,
        "block_efficiency_class": block_class,
        "xla_fallback_ops": plan.xla_ops,
        "deferred_perm_ops": plan.deferred_ops,
    }
    return out


def select_engine(circuit, num_devices: int | None = None,
                  chip: ChipSpec = V5E, precision: int = 1,
                  requested: str = "auto", backend: str | None = None) -> dict:
    """Resolve the compiled-circuit engine for a deployment.  The decision
    is recorded as a ``planner.select_engine`` span (engine + reason) when
    tracing is on — see :func:`_select_engine_impl` for the rules.
    """
    with _obs.span("planner.select_engine", requested=requested,
                   num_devices=num_devices or 1) as sp:
        choice = _select_engine_impl(circuit, num_devices, chip, precision,
                                     requested, backend)
        # every engine decision carries calibration provenance: which
        # constants (fitted profile vs hard-coded defaults) scored it
        choice["calibration"] = calibration_provenance()
        if sp is not None:
            sp.attrs["engine"] = choice["engine"]
            sp.attrs["reason"] = choice["reason"]
            sp.attrs["calibration"] = choice["calibration"].get(
                "profile_id", "default")
        return choice


def _select_engine_impl(circuit, num_devices: int | None = None,
                        chip: ChipSpec = V5E, precision: int = 1,
                        requested: str = "auto",
                        backend: str | None = None) -> dict:
    """Resolve the compiled-circuit engine for a deployment.

    Returns ``{"engine", "reason", "model", "plan"}`` with ``engine`` in
    ``("xla", "pallas")``.  ``requested="pallas"`` forces the epoch
    executor wherever its envelope admits the register (interpret mode off
    TPU — the CI/test path) and raises ``QuESTError``
    ``E_INVALID_SCHEDULE_OPTION`` where it cannot hold (mesh deployments:
    the deferred qubit map renames amplitude-index bits, which MUST be
    materialized before any sharded collective — docs/DESIGN.md — so the
    engine is single-device; f64; n outside [17, 30]).

    ``requested="auto"`` picks by the :func:`engine_time_model` roofline on
    ``chip`` — a TPU-class spec, so the choice is deterministic and
    cache-key-stable — but only commits to Pallas when ``backend``
    (default: the live jax backend) actually compiles Mosaic: off-TPU the
    kernels run in interpret mode, a correctness tool, not an engine.
    ``QUEST_TPU_EPOCH_ENGINE=1`` overrides the backend guard (CI);
    ``=0`` pins auto to XLA."""
    import os

    from ..ops import epoch_pallas as _ep
    if requested not in ENGINES:
        raise ValueError(f"engine must be one of {ENGINES}, got {requested!r}")

    def xla(reason, model=None):
        return {"engine": "xla", "reason": reason, "model": model,
                "plan": None}

    multi = num_devices is not None and num_devices > 1
    supported = _ep.epoch_supported(circuit.num_qubits, precision)
    if requested == "xla":
        return xla("requested")
    if multi or not supported:
        # name the REMAINING out-of-envelope case precisely: meshes, f64,
        # and the n range are all that is left — cross-group 2q windows,
        # controlled dense on high qubits and small registers are now
        # in-envelope, and >= 3-target cross-group dense gates / wide
        # diagonals fall back PER OP inside the plan, never rejecting the
        # circuit
        dq = getattr(circuit, "density_qubits", None)
        if multi:
            reason = ("multi-device mesh: the deferred qubit map must "
                      "materialize before sharded collectives")
        elif precision != 1:
            reason = ("f64 state: the epoch engines are f32 plane kernels "
                      "(use engine='xla' for f64)")
        elif dq is not None:
            # a density circuit's register is the Choi-doubled 2n-qubit
            # vector, so the [MIN_QUBITS, MAX_QUBITS] envelope reads as a
            # density window of [ceil(MIN/2), MAX/2] qubits
            reason = (f"density register outside {-(-_ep.MIN_QUBITS // 2)} "
                      f"<= n <= {_ep.MAX_QUBITS // 2}: the Choi-doubled "
                      f"vector is 2n = {circuit.num_qubits} register "
                      f"qubits, outside [{_ep.MIN_QUBITS}, {_ep.MAX_QUBITS}]")
        else:
            reason = (f"register outside {_ep.MIN_QUBITS} <= n <= "
                      f"{_ep.MAX_QUBITS}: no degenerate block geometry "
                      f"below {_ep.MIN_QUBITS} qubits, int32 amplitude "
                      f"indices overflow above {_ep.MAX_QUBITS}")
        if requested == "pallas":
            from ..validation import MESSAGES, ErrorCode, QuESTError
            raise QuESTError(ErrorCode.INVALID_SCHEDULE_OPTION,
                             MESSAGES[ErrorCode.INVALID_SCHEDULE_OPTION]
                             + f" engine='pallas' unavailable: {reason}.",
                             "select_engine")
        return xla(reason)
    if requested == "pallas":
        plan = _ep.plan_circuit(circuit.key(), circuit.num_qubits)
        return {"engine": "pallas", "reason": "requested",
                "model": engine_time_model(circuit, chip, precision,
                                           plan=plan),
                "plan": plan}
    # auto: cheap guards BEFORE the plan build — the default
    # compile_circuit path must stay trivial wherever the answer is XLA
    # anyway (off-TPU backends run Pallas in interpret mode)
    env = os.environ.get("QUEST_TPU_EPOCH_ENGINE")
    if env == "0":
        return xla("QUEST_TPU_EPOCH_ENGINE=0")
    if env != "1":
        import jax
        live = backend or jax.default_backend()
        if live != "tpu":
            return xla(f"backend {live!r} runs Pallas in interpret mode")
    plan = _ep.plan_circuit(circuit.key(), circuit.num_qubits)
    model = engine_time_model(circuit, chip, precision, plan=plan)
    if plan.pallas_passes == 0:
        return xla("no epoch-supported windows", model)
    if model["pallas_seconds"] >= model["xla_seconds"]:
        return xla("modeled slower than the XLA engine", model)
    return {"engine": "pallas",
            "reason": (f"modeled {model['xla_seconds'] / model['pallas_seconds']:.1f}x "
                       f"vs XLA ({model['pallas_hbm_passes']} fused passes "
                       f"vs {model['xla_hbm_passes']})"),
            "model": model, "plan": plan}


def engine_summary(circuit, num_devices: int | None = None,
                   chip: ChipSpec = V5E, precision: int = 1,
                   requested: str = "auto") -> dict:
    """Per-epoch engine report for the analysis CLI's ``--schedule`` view:
    which engine each epoch of the (scheduled) circuit runs on and what the
    lowering costs, so ``A_SCHEDULE_COMM_REGRESSION`` comparisons are
    engine-aware.  Epochs are the epoch executor's segments on one device;
    on a mesh the whole circuit is one XLA row (see :func:`select_engine`).
    Unlike ``select_engine`` this REPORTS an infeasible forced engine (as
    the XLA row it would fall back to) instead of raising — the schedule
    report must describe any deployment."""
    from ..validation import QuESTError
    try:
        choice = select_engine(circuit, num_devices, chip, precision,
                               requested)
    except QuESTError as e:
        choice = {"engine": "xla", "reason": str(e), "plan": None,
                  "calibration": calibration_provenance()}
    epochs = []
    if choice["plan"] is not None and choice["engine"] == "pallas":
        for i, seg in enumerate(choice["plan"].segments):
            epochs.append({
                "epoch": i, "engine": seg.engine, "ops": len(seg.ops),
                "hbm_passes": (len(seg.passes) if seg.engine == "pallas"
                               else len(seg.ops)),
            })
    else:
        epochs.append({"epoch": 0, "engine": "xla", "ops": len(circuit.ops),
                       "hbm_passes": len(circuit.ops)})
    return {"engine": choice["engine"], "reason": choice["reason"],
            "epochs": epochs,
            "calibration": choice.get("calibration",
                                      calibration_provenance()),
            "deferred_perm_ops": (choice["plan"].deferred_ops
                                  if choice["plan"] is not None else 0)}
