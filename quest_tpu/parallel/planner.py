"""Communication planner / diagnostics.

The reference decides at runtime, per gate, whether MPI communication is
needed (halfMatrixBlockFitsInChunk, QuEST_cpu_distributed.c:356-361) and
routes dense multi-target gates through swap-rerouting (:1381-1479).  Under
GSPMD those decisions are made by the partitioner at compile time; this
module reproduces them as an inspectable plan so users can see — before
compiling — which gates of a circuit will ride ICI and what each costs.
"""

from __future__ import annotations

import dataclasses


def is_shard_local(target: int, num_qubits: int, num_devices: int) -> bool:
    """A gate on ``target`` touches only in-shard amplitude pairs iff the
    target lies below the sharded range (ref: halfMatrixBlockFitsInChunk)."""
    if num_devices <= 1:
        return True
    local_qubits = num_qubits - (num_devices.bit_length() - 1)
    return target < local_qubits


@dataclasses.dataclass
class GatePlan:
    index: int
    kind: str
    targets: tuple
    local: bool
    comm: str          # 'none' | 'permute' | 'reshard'
    bytes_moved: int   # per device, one direction


def comm_plan(circuit, num_devices: int, bytes_per_amp: int = 8) -> list:
    """Static communication plan of a :class:`quest_tpu.Circuit` over an
    n-device amplitude mesh.  ``bytes_per_amp`` defaults to f32 SoA (8 B)."""
    from ..ops.apply import _control_style

    n = circuit.num_qubits
    shard_amps = (1 << n) // num_devices
    plans = []
    for i, op in enumerate(circuit.ops):
        if op.kind == "diagonal":
            # diagonal gates never move data, controls included — the engine
            # absorbs controls into the broadcast factor
            # (ref: QuEST_cpu.c:2978-3109; ops/apply.py apply_diagonal)
            plans.append(GatePlan(i, op.kind, op.targets, True, "none", 0))
            continue
        cross = [t for t in op.targets
                 if not is_shard_local(t, n, num_devices)]
        cross_c = [c for c in op.controls
                   if not is_shard_local(c, n, num_devices)]
        if not cross and cross_c:
            # a prefix-control on a SHARDED axis: under the default slice
            # style the slice-update makes GSPMD exchange (measured:
            # collective-permute + all-reduce); the select style masks
            # elementwise instead — zero collectives
            if _control_style() == "select":
                plans.append(GatePlan(i, op.kind, op.targets, True, "none", 0))
            else:
                plans.append(GatePlan(i, op.kind, op.targets, False, "permute",
                                      shard_amps * bytes_per_amp))
        elif not cross:
            plans.append(GatePlan(i, op.kind, op.targets, True, "none", 0))
        elif len(op.targets) == 1:
            plans.append(GatePlan(i, op.kind, op.targets, False, "permute",
                                  shard_amps * bytes_per_amp))
        else:
            # dense multi-target with sharded targets: GSPMD reshards (the
            # reference's swap-rerouting, one all-to-all each way)
            plans.append(GatePlan(i, op.kind, op.targets, False, "reshard",
                                  2 * shard_amps * bytes_per_amp))
    return plans
