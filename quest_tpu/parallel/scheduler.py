"""Comm-aware circuit scheduler: commutation DAG + placement search driving
the routed executor.

The reference pays swap-rerouting per wide gate and never fixed it (the TODO
at QuEST_cpu_distributed.c:1376-1379); PR 1's deferred routing
(ops/apply.py apply_matrix_routed) only amortises swaps between consecutive
gates that happen to share a layout, and the planner's cost model
(parallel/planner.py comm_plan / time_model) was purely diagnostic.  This
module turns the planner into an optimizer: it reorders and rewrites a
recorded :class:`quest_tpu.Circuit` so the compiled program issues fewer
cross-shard collectives on an amplitude mesh, without changing the unitary
it implements.

Three cooperating passes, all pure host work over the GateOp IR:

1. **Commutation DAG** (:func:`commutation_dag`).  Two ops commute whenever
   every shared wire sees a *diagonal* action from both: diagonal/parity
   (``diagonal``/``mrz``) payloads are diagonal on all their wires, and any
   gate is diagonal on its control wires — so phase ladders commute with
   each other and slide through Z-controls, while dense targets
   (``matrix``/``x``/``y``/``swap``/``bitperm``) order against everything
   sharing their wires.  Ops on disjoint wires commute trivially (no edge).

2. **Epoch scheduling** (:func:`reorder_ops` + :func:`_lower_epochs`).
   A topological order that (a) sinks comm-free ops eagerly between epochs
   and (b) groups dense gates by *routing signature* — the cross-shard
   target set plus the minor-block reroute the gate engine would perform —
   so gates needing the same layout run back-to-back and the routed
   executor pays each permutation once.  Grouped cross-shard runs whose
   modeled collective cost exceeds two boundary permutations are *lowered*:
   one fused ``bitperm`` moves the sharded targets into shard-local prefix
   positions, the run executes comm-free on relabeled wires, and the same
   ``bitperm`` (an involution) restores the layout.

3. **Swap-network fusion** (:func:`_fuse_swap_runs`).  A run of ``swap``
   ops (e.g. the QFT's trailing bit reversal) is one net permutation; it is
   refactored as ``L2 . T . L1`` where ``T`` is a single prefix-axis
   ``bitperm`` (ONE all-to-all carries every cross-shard move) and
   ``L1``/``L2`` are shard-local staging swaps — instead of one collective
   per cross-shard pairwise swap.

4. **Placement search** (:func:`greedy_placement`).  A greedy logical->
   physical relabeling scored by :func:`planner.time_model`'s ICI model:
   hot dense wires are hill-climbed out of the sharded range; the
   relabeling is applied as boundary ``bitperm`` ops (entry permutation +
   one reconcile at the end), and is only adopted when the modeled end-to-
   end time — boundary collectives included — improves, so circuits with
   uniformly hot wires keep the identity placement.

Entry points: :meth:`quest_tpu.Circuit.schedule`,
``compile_circuit(..., num_devices=...)``, and :func:`schedule_savings`
(the before/after report behind ``python -m quest_tpu.analysis
--schedule``).  See docs/SCHEDULER.md.
"""

from __future__ import annotations

import bisect
import dataclasses

from .. import obs as _obs
from . import planner as _planner

__all__ = ["commutation_dag", "reorder_ops", "schedule", "schedule_savings",
           "greedy_placement", "apply_placement"]

# kinds whose payload acts diagonally on every wire they touch
_DIAG_KINDS = ("diagonal", "mrz")
# dense-on-target kinds the placement weight tracks
_DENSE_KINDS = ("matrix", "x", "y", "y*", "swap", "bitperm")


def _op_wires(op) -> tuple:
    return op.targets + op.controls


def _acts_diagonally(op, wire: int) -> bool:
    """True iff ``op``'s action on ``wire`` is diagonal in the computational
    basis: control wires always (a controlled gate is block-diagonal in its
    control basis, whatever the control state), and diagonal/parity payloads
    on their targets too."""
    if op.kind in _DIAG_KINDS:
        return True
    return wire in op.controls


@dataclasses.dataclass
class CommutationDAG:
    """Dependency DAG over a GateOp list: an edge i -> j means op j must not
    be reordered before op i (they share a wire on which at least one acts
    densely)."""
    preds: list
    succs: list

    def __len__(self) -> int:
        return len(self.preds)


def commutation_dag(ops) -> CommutationDAG:
    """Build the commutation DAG.  Per wire we keep the last densely-acting
    op and the diagonally-acting ops recorded since: a new diagonal op on
    the wire depends only on the last dense one (diagonals commute among
    themselves and slide through controls); a new dense op depends on the
    last dense op AND every diagonal recorded since (it would not commute
    past any of them)."""
    preds: list = [set() for _ in ops]
    succs: list = [set() for _ in ops]

    def edge(a: int, b: int) -> None:
        if a != b:
            succs[a].add(b)
            preds[b].add(a)

    last_dense: dict = {}
    diag_since: dict = {}
    for i, op in enumerate(ops):
        for w in dict.fromkeys(_op_wires(op)):
            d = last_dense.get(w)
            if _acts_diagonally(op, w):
                if d is not None:
                    edge(d, i)
                diag_since.setdefault(w, []).append(i)
            else:
                if d is not None:
                    edge(d, i)
                for j in diag_since.get(w, ()):
                    edge(j, i)
                last_dense[w] = i
                diag_since[w] = []
    return CommutationDAG(preds, succs)


def _cross_targets(op, n: int, num_devices: int) -> tuple:
    return tuple(t for t in op.targets
                 if not _planner.is_shard_local(t, n, num_devices))


def _reroute_sig(op, n: int) -> tuple:
    """The minor-block reroute the gate engine would perform for this dense
    gate at identity layout (ops/apply.py _gate_plan) — gates sharing it can
    share one physical routing in the routed executor."""
    if op.kind != "matrix":
        return ()
    from ..ops import apply as _ap
    cs = op.control_states or (1,) * len(op.controls)
    try:
        plan = _ap._gate_plan(n, op.targets, op.controls, tuple(cs), False)
    except Exception:
        return ()  # unroutable gates are the validation layer's finding
    return plan.reroute


def _epoch_sig(op, n: int, num_devices: int):
    """Routing-signature grouping key, or None for routing-neutral ops
    (comm-free, or position-agnostic under the executor's live perm)."""
    if op.kind != "matrix":
        return None
    cross = _cross_targets(op, n, num_devices)
    reroute = _reroute_sig(op, n)
    if not cross and not reroute:
        return None
    return (cross, reroute)


def reorder_ops(ops, n: int, num_devices: int) -> list:
    """Greedy topological order over the commutation DAG: routing-neutral
    ops are emitted as soon as they are ready (sunk between epochs), and
    among routing-carrying ops the current epoch's signature is preferred,
    so same-layout gates run back-to-back.  Deterministic: ties break on
    the original op index."""
    dag = commutation_dag(ops)
    indeg = [len(p) for p in dag.preds]
    ready = sorted(i for i, d in enumerate(indeg) if d == 0)
    sigs = [_epoch_sig(op, n, num_devices) for op in ops]
    out: list = []
    current = None
    while ready:
        pick = None
        for i in ready:  # routing-neutral first
            if sigs[i] is None:
                pick = i
                break
        if pick is None and current is not None:
            for i in ready:  # then the open epoch
                if sigs[i] == current:
                    pick = i
                    break
        if pick is None:
            pick = ready[0]  # open the next epoch at the earliest ready op
            current = sigs[pick]
        ready.remove(pick)
        out.append(ops[pick])
        for j in sorted(dag.succs[pick]):
            indeg[j] -= 1
            if indeg[j] == 0:
                bisect.insort(ready, j)  # ready stays sorted for stable ties
    assert len(out) == len(ops)
    return out


# ---------------------------------------------------------------------------
# permutation lowering: content maps -> IR ops
# ---------------------------------------------------------------------------

def _cycles(mapping: dict) -> list:
    from ..ops.apply import _perm_cycles
    return _perm_cycles({k: v for k, v in mapping.items() if k != v})


def _bitperm_op(mapping: dict):
    """One fused ``bitperm`` GateOp realizing a prefix content map."""
    from ..circuit import GateOp
    support = tuple(sorted(mapping))
    return GateOp("bitperm", support, (), (),
                  tuple(float(mapping[w]) for w in support), None)


def _swap_ops(mapping: dict) -> list:
    """Pairwise-swap GateOps realizing a content map (cycle a1->a2->...->ak
    as swaps (a1,a2),(a1,a3),...,(a1,ak))."""
    from ..circuit import GateOp
    out = []
    for cyc in _cycles(mapping):
        for x in cyc[1:]:
            out.append(GateOp("swap", (cyc[0], x)))
    return out


def _perm_to_ops(n: int, cmap: dict, num_devices: int) -> list:
    """Lower a content permutation (``cmap[src] = dst``) to IR ops paying at
    most ONE cross-shard collective.

    Factors ``perm = L2 . T . L1``: ``L1`` stages minor-block content bound
    for the sharded range at shard-local prefix positions (pairwise swaps
    through the matrix engine — comm-free), ``T`` is one prefix-only
    ``bitperm`` finalising every sharded position (one transpose, one
    all-to-all), and ``L2`` = ``perm . (T . L1)^-1`` touches only
    shard-local wires (prefix-local cycles fuse into a second comm-free
    ``bitperm``; minor cycles stay pairwise swaps)."""
    cmap = {k: v for k, v in cmap.items() if k != v}
    if not cmap:
        return []
    from ..ops.apply import _blocks
    l, s = _blocks(n)
    lo = l + s
    local_q = _planner.local_qubit_count(n, num_devices)
    support = set(cmap) | set(cmap.values())
    full = {w: cmap.get(w, w) for w in support}

    if local_q <= lo or all(max(cyc) < local_q for cyc in _cycles(full)):
        # nothing crosses the sharded range (or there is no prefix room to
        # stage through): emit the local form directly
        return _local_perm_ops(full, lo)

    # L1: stage minor content destined for a sharded position.  A staging
    # wire may itself be part of the permutation — L2 absorbs the
    # displacement exactly — as long as its OWN content stays shard-local
    # (otherwise T would have to pick it up from a minor position)
    free = [q for q in range(local_q - 1, lo - 1, -1) if q not in support]
    busy_ok = [q for q in range(local_q - 1, lo - 1, -1)
               if q in support and full[q] < local_q]
    staging = free + busy_ok
    needs_staging = [o for o in sorted(full)
                     if o < lo and full[o] >= local_q]
    if len(staging) < len(needs_staging):
        return _local_perm_ops(full, lo)  # no room: plain pairwise form
    l1: dict = {}
    for o in needs_staging:
        st = staging.pop(0)
        l1[o] = st
        l1[st] = o
    after_l1 = {w: l1.get(w, w) for w in set(full) | set(l1)}

    # T: finalise every sharded position in one prefix transpose
    t_map: dict = {}
    for p in sorted(full.values()):
        if p >= local_q:
            src = after_l1[next(o for o, d in full.items() if d == p)]
            assert src >= lo, (src, p)
            t_map[src] = p
    # close T into a permutation of prefix wires: positions receiving new
    # content whose own content has no assignment yet drain into the wires
    # content left (all shard-local, see docs/SCHEDULER.md)
    open_dst = sorted(set(t_map.values()) - set(t_map))
    open_src = sorted(set(t_map) - set(t_map.values()))
    for p, d in zip(open_dst, open_src):
        assert d < local_q, (p, d)
        t_map[p] = d

    # L2 = perm . (T . L1)^-1, computed by simulating content positions
    pos: dict = {}
    for o in support | set(l1):
        c = l1.get(o, o)
        pos[o] = t_map.get(c, c)
    l2 = {}
    for o, p in pos.items():
        want = full.get(o, o)
        if p != want:
            l2[p] = want
    assert all(max(cyc) < local_q for cyc in _cycles(l2)), l2

    ops = _swap_ops(l1)
    ops.append(_bitperm_op(t_map))
    ops += _local_perm_ops(l2, lo)
    return ops


def _local_perm_ops(cmap: dict, lo: int) -> list:
    """Shard-local permutation: prefix-only cycles fuse into one comm-free
    ``bitperm`` pass; cycles touching the minor blocks stay pairwise.  The
    split is :func:`ops.apply.split_prefix_cycles` — the same rule the
    routed executor's reconcile_perm applies at runtime."""
    from ..ops.apply import split_prefix_cycles
    fused, rest = split_prefix_cycles(
        {k: v for k, v in cmap.items() if k != v}, lo)
    ops = []
    if fused:
        ops.append(_bitperm_op(fused))
    ops += _swap_ops(rest)
    return ops


# ---------------------------------------------------------------------------
# swap-network fusion
# ---------------------------------------------------------------------------

def _net_swap_map(run) -> dict:
    """Net content map of a run of ``swap`` ops."""
    at: dict = {}  # position -> origin
    for op in run:
        a, b = op.targets
        at[a], at[b] = at.get(b, b), at.get(a, a)
    return {o: p for p, o in at.items() if p != o}


def _fuse_swap_runs(ops, n: int, num_devices: int) -> list:
    """Replace each maximal run of consecutive ``swap`` ops by the fused
    lowering of its net permutation (:func:`_perm_to_ops`) whenever that
    strictly reduces modeled collectives — the QFT's trailing bit reversal
    collapses from one reshard per cross-shard pair to one all-to-all."""
    out: list = []
    i = 0
    while i < len(ops):
        if ops[i].kind != "swap":
            out.append(ops[i])
            i += 1
            continue
        j = i
        while j < len(ops) and ops[j].kind == "swap":
            j += 1
        run = ops[i:j]
        fused = _perm_to_ops(n, _net_swap_map(run), num_devices)
        if _comm_cost(fused, n, num_devices) < _comm_cost(run, n, num_devices) \
                or (len(run) > 1 and len(fused) < len(run)
                    and _comm_cost(fused, n, num_devices)
                    == _comm_cost(run, n, num_devices)):
            out.extend(fused)
        else:
            out.extend(run)
        i = j
    return out


def _comm_cost(ops, n: int, num_devices: int) -> tuple:
    """(comm events, bytes moved) of an op list under the planner model."""
    from ..circuit import Circuit
    c = Circuit(n)
    c.ops = list(ops)
    s = _planner.comm_summary(c, num_devices)
    return (s["comm_events"], s["bytes_moved"])


# ---------------------------------------------------------------------------
# epoch lowering: grouped cross-shard runs -> bitperm-bracketed local runs
# ---------------------------------------------------------------------------

def _relabel_op(op, mapping: dict):
    """Wire-relabeled twin of ``op`` (bitperm payloads are wires too)."""
    from ..circuit import GateOp
    t = tuple(mapping.get(q, q) for q in op.targets)
    c = tuple(mapping.get(q, q) for q in op.controls)
    mat = op.matrix
    if op.kind == "bitperm":
        mat = tuple(float(mapping.get(int(d), int(d))) for d in op.matrix)
    if t == op.targets and c == op.controls and mat == op.matrix:
        return op
    return GateOp(op.kind, t, c, op.control_states, mat, op.shape)


def _op_unit_cost(op, n: int, num_devices: int) -> int:
    """Planner comm units of one op (shard-sized passes over ICI): the
    exact :func:`planner.comm_plan` model with bytes_per_amp=1, so reshard=2,
    permute=1, plus any slice-style sharded-control surcharge."""
    from ..circuit import Circuit
    c = Circuit(n)
    c.ops = [op]
    plan = _planner.comm_plan(c, num_devices, 1)[0]
    shard_amps = (1 << n) // num_devices
    return plan.bytes_moved // shard_amps


def _epoch_member_wires(op, n: int, num_devices: int) -> tuple:
    """Sharded wires a dense gate would stop paying for if relabeled into
    the shard-local range: cross targets AND cross controls (a slice-style
    control on a sharded axis exchanges too — planner.comm_plan)."""
    return tuple(w for w in _op_wires(op)
                 if not _planner.is_shard_local(w, n, num_devices))


def _lower_epochs(ops, n: int, num_devices: int) -> list:
    """Bracket grouped cross-shard dense runs between two fused ``bitperm``
    boundary permutations that pull every sharded wire of the run into a
    shard-local prefix position: the bracketed gates execute comm-free on
    relabeled wires, and the layout is restored by the same involution.
    Applied only when the planner-model savings strictly beat the two
    boundary collectives (2 units each)."""
    from ..ops.apply import _blocks
    lo = sum(_blocks(n))
    local_q = _planner.local_qubit_count(n, num_devices)
    if num_devices <= 1 or local_q <= lo:
        return list(ops)
    out: list = []
    i = 0
    while i < len(ops):
        op = ops[i]
        if op.kind != "matrix" or _op_unit_cost(op, n, num_devices) == 0:
            out.append(op)
            i += 1
            continue
        # grow a window of cross-shard dense gates and interleaved ops that
        # stay clear of the sharded range
        union: set = set(_epoch_member_wires(op, n, num_devices))
        last_member = i
        benefit = _op_unit_cost(op, n, num_devices)
        j = i + 1
        while j < len(ops):
            nxt = ops[j]
            cost = _op_unit_cost(nxt, n, num_devices)
            if nxt.kind == "matrix" and cost:
                cand = union | set(_epoch_member_wires(nxt, n, num_devices))
                if len(cand) > local_q - lo:
                    break
                union = cand
                benefit += cost
                last_member = j
            elif cost or any(w >= local_q for w in _op_wires(nxt)) \
                    or nxt.kind == "bitperm":
                break  # touches the sharded range some other way: barrier
            else:
                j += 1
                continue
            j += 1
        window = ops[i:last_member + 1]
        window_wires = set()
        for w_op in window:
            window_wires |= set(_op_wires(w_op))
        dests = [q for q in range(local_q - 1, lo - 1, -1)
                 if q not in window_wires and q not in union]
        if benefit > 4 and len(dests) >= len(union):
            rho = {}
            for c_wire, d_wire in zip(sorted(union), dests):
                rho[c_wire] = d_wire
                rho[d_wire] = c_wire
            boundary = _bitperm_op(rho)
            out.append(boundary)
            out.extend(_relabel_op(w_op, rho) for w_op in window)
            out.append(boundary)  # rho is an involution
        else:
            out.extend(window)
        i = last_member + 1
    return out


# ---------------------------------------------------------------------------
# placement search
# ---------------------------------------------------------------------------

def _dense_weight(ops) -> dict:
    """Per-wire dense-gate pressure: what the placement search tries to keep
    out of the sharded range."""
    w: dict = {}
    for op in ops:
        if op.kind in _DENSE_KINDS:
            unit = 2 if (op.kind == "matrix" and len(op.targets) > 1) else 1
            for t in op.targets:
                w[t] = w.get(t, 0) + unit
    return w


def _model_seconds(circuit, num_devices: int, chip, precision: int) -> float:
    return sum(t.total_s
               for t in _planner.time_model(circuit, num_devices, chip,
                                            precision))


def apply_placement(circuit, sigma: tuple, num_devices: int):
    """Relabel ``circuit`` by the placement ``sigma`` (logical wire q runs
    on physical position sigma[q]); equivalence is preserved for ARBITRARY
    input states by an entry permutation realizing sigma and one reconcile
    (sigma^-1) at the end, both in the fused :func:`_perm_to_ops` form."""
    from ..circuit import Circuit
    n = circuit.num_qubits
    if tuple(sigma) == tuple(range(n)):
        out = Circuit(n)
        out.ops = list(circuit.ops)
        return out
    inv = [0] * n
    for q, p in enumerate(sigma):
        inv[p] = q
    mapping = {q: p for q, p in enumerate(sigma) if q != p}
    out = Circuit(n)
    out.ops = (_perm_to_ops(n, mapping, num_devices)
               + [_relabel_op(op, mapping) for op in circuit.ops]
               + _perm_to_ops(n, {p: q for q, p in mapping.items()},
                              num_devices))
    return out


def greedy_placement(circuit, num_devices: int, chip=None,
                     precision: int = 1, max_rounds: int | None = None) -> tuple:
    """Greedy initial logical->physical placement scored by
    :func:`planner.time_model`: repeatedly try moving the heaviest
    still-sharded wire to the lightest shard-local position (a transposition
    of the current placement) and keep the swap iff the modeled end-to-end
    seconds — boundary permutations included — strictly improve.  Returns
    the placement as a tuple (identity when nothing wins, e.g. when every
    wire is equally hot)."""
    chip = chip or _planner.V5E
    n = circuit.num_qubits
    sigma = list(range(n))
    local_q = _planner.local_qubit_count(n, num_devices)
    # local_q <= 0: every wire is sharded (num_devices >= 2^n, which the
    # reference permits) — no shard-local position exists to trade with
    if num_devices <= 1 or local_q >= n or local_q <= 0:
        return tuple(sigma)
    weight = _dense_weight(circuit.ops)
    best = _model_seconds(apply_placement(circuit, tuple(sigma), num_devices),
                          num_devices, chip, precision)
    rounds = max_rounds if max_rounds is not None else n - local_q
    for _ in range(rounds):
        # heaviest logical wire currently placed in the sharded range,
        # lightest placed shard-local
        hot = max((q for q in range(n) if sigma[q] >= local_q),
                  key=lambda q: (weight.get(q, 0), -q))
        cold = min((q for q in range(n) if sigma[q] < local_q),
                   key=lambda q: (weight.get(q, 0), q))
        if weight.get(hot, 0) <= weight.get(cold, 0):
            break  # already balanced: no swap can help
        cand = list(sigma)
        cand[hot], cand[cold] = cand[cold], cand[hot]
        score = _model_seconds(
            apply_placement(circuit, tuple(cand), num_devices),
            num_devices, chip, precision)
        if score < best:
            sigma, best = cand, score
        else:
            break
    return tuple(sigma)


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def _carry_density_meta(circuit, out) -> None:
    """Propagate a DensityCircuit's metadata (density_qubits +
    channel_slots/channel_log) onto the scheduled copy, remapping the
    index-based channel records through payload-tuple identity — the same
    provenance the serve cache's operand-offset map rides (the scheduler
    preserves payload tuples through reorder and relabel).  Downstream
    consumers (select_engine's density window reason, the analyzer's
    channel-aware payload validation, serve admission) all read the
    attributes via ``getattr``, so a plain Circuit carrying them is
    equivalent."""
    recs = getattr(circuit, "channel_log", None)
    if getattr(circuit, "density_qubits", None) is None:
        return
    by_payload = {id(circuit.ops[rec[0]].matrix): rec for rec in (recs or ())}
    log = []
    slots = set()
    for j, op in enumerate(out.ops):
        rec = by_payload.pop(id(op.matrix), None)
        if rec is not None:
            slots.add(j)
            log.append((j,) + tuple(rec[1:]))
    if by_payload:
        # a channel op did not survive the rewrite identically: carry NO
        # density metadata rather than a wrong (or half-carried) view —
        # density_qubits without the channel map would make the analyzer
        # validate surviving superoperators as unitaries and the density
        # prover report phantom pairing breaks.  The scheduled copy still
        # runs correctly; only density-specific validation and reporting
        # degrade.
        return
    out.density_qubits = circuit.density_qubits
    out.channel_slots = slots
    out.channel_log = log


def schedule(circuit, num_devices: int, *, chip=None, precision: int = 1,
             placement: bool = True, reorder: bool = True,
             overlap: bool = False, pipeline_chunks: int | None = None,
             **unknown):
    """Comm-aware scheduled copy of ``circuit`` for an ``num_devices``-way
    amplitude mesh.  Pure host rewrite of the GateOp IR; the returned
    Circuit implements the SAME unitary (every pass is an exact algebraic
    refactoring) and is what ``compile_circuit(..., num_devices=...)``
    feeds the routed executor.

    ``overlap=True`` (implied by a ``pipeline_chunks`` value) additionally
    attaches a static chunking plan (parallel/executor.py plan_overlap):
    ``compile_circuit(..., overlap=True)`` then lowers each comm event as
    ``pipeline_chunks`` independent chunked collectives pipelined against
    gate compute.  ``pipeline_chunks=None`` takes the planner's
    recommendation (:func:`planner.recommend_pipeline_chunks`); a
    non-power-of-two or non-integer count raises
    ``E_INVALID_SCHEDULE_OPTION``.  The plan never changes the op list —
    chunking is layout-only, provable via
    ``analysis.equivalence.check_overlap_plan``.

    Invalid deployments are rejected with validation-layer codes before
    any rewriting: a non-integer, < 1 or non-power-of-two ``num_devices``
    raises ``E_INVALID_NUM_RANKS`` (the amplitude mesh shards the 2^n axis
    in halves), an unknown keyword raises ``E_INVALID_SCHEDULE_OPTION``
    instead of silently proceeding.  With ``QUEST_TPU_VALIDATE_SCHEDULE=1``
    the output is translation-validated against the input
    (analysis/equivalence.py) and a disproof raises ``QuESTError``
    ``V_SEMANTICS_CHANGED``; unverifiable regions warn."""
    import os
    import warnings

    from ..circuit import Circuit
    from ..validation import ErrorCode, QuESTError, validate_num_ranks
    if unknown:
        from ..validation import MESSAGES
        raise QuESTError(ErrorCode.INVALID_SCHEDULE_OPTION,
                         MESSAGES[ErrorCode.INVALID_SCHEDULE_OPTION]
                         + f" Got: {sorted(unknown)}.", "schedule")
    if not isinstance(num_devices, int) or isinstance(num_devices, bool):
        from ..validation import MESSAGES
        raise QuESTError(ErrorCode.INVALID_NUM_RANKS,
                         MESSAGES[ErrorCode.INVALID_NUM_RANKS], "schedule")
    validate_num_ranks(num_devices, "schedule")
    chip = chip or _planner.V5E
    overlap = overlap or pipeline_chunks is not None
    if overlap:
        # validate (and resolve) the chunk count BEFORE any rewriting, so a
        # bad option never half-schedules
        from . import executor as _exec
        if pipeline_chunks is None:
            pipeline_chunks = _planner.recommend_pipeline_chunks(
                circuit.num_qubits, num_devices, chip, precision)
        pipeline_chunks = _exec.validate_pipeline_chunks(pipeline_chunks,
                                                         "schedule")
    n = circuit.num_qubits
    with _obs.span("scheduler.schedule", num_devices=num_devices,
                   ops_in=len(circuit.ops), overlap=bool(overlap)) as sp:
        ops = list(circuit.ops)
        if reorder and num_devices > 1:
            ops = reorder_ops(ops, n, num_devices)
        staged = Circuit(n)
        staged.ops = ops
        if placement and num_devices > 1:
            sigma = greedy_placement(staged, num_devices, chip, precision)
            staged = apply_placement(staged, sigma, num_devices)
            ops = staged.ops
        ops = _fuse_swap_runs(ops, n, num_devices)
        ops = _lower_epochs(ops, n, num_devices)
        out = Circuit(n)
        out.ops = ops
        _carry_density_meta(circuit, out)
        if overlap:
            out._overlap_plan = _exec.plan_overlap(out, num_devices,
                                                   pipeline_chunks)
        if sp is not None:
            sp.attrs["ops_out"] = len(ops)
            sp.attrs["comm_events"] = _planner.comm_summary(
                out, num_devices)["comm_events"]
    if os.environ.get("QUEST_TPU_VALIDATE_SCHEDULE") == "1":
        from ..analysis.diagnostics import Severity
        from ..analysis.equivalence import check_equivalence
        found = check_equivalence(circuit, out)
        errors = [d for d in found if d.severity >= Severity.ERROR]
        if errors:
            raise QuESTError(errors[0].code,
                             "schedule() produced a non-equivalent circuit: "
                             + "; ".join(d.message for d in errors),
                             "schedule")
        for d in found:
            warnings.warn(f"schedule(): {d.format()}", RuntimeWarning,
                          stacklevel=2)
    return out


def schedule_savings(circuit, num_devices: int, *, bytes_per_amp: int = 8,
                     chip=None, precision: int = 1, scheduled=None,
                     pipeline_chunks: int | None = None,
                     engine: str = "auto") -> dict:
    """Before/after report of what scheduling buys: planner-predicted
    collective counts, bytes over ICI, and modeled seconds.  The payload
    behind ``python -m quest_tpu.analysis --schedule`` and the predicted
    columns of bench.py's scheduled-vs-unscheduled rows.

    With ``pipeline_chunks`` (or a ``scheduled`` circuit carrying an
    overlap plan) the report grows the overlapped executor's predicted
    columns: ``model_seconds_overlapped`` and ``predicted_hidden_frac``
    from :func:`executor.predict_overlap` — the CI gate asserts the
    overlap-aware model never predicts a slowdown vs the serial schedule.

    The report is engine-aware (``engine``: "auto" | "xla" | "pallas"):
    ``engine_chosen`` / ``engine_epochs`` record which compiled-circuit
    backend the planner picks per epoch of the SCHEDULED circuit
    (:func:`planner.engine_summary`), so ``A_SCHEDULE_COMM_REGRESSION``
    comparisons and bench pairs always say which engine the numbers
    describe."""
    chip = chip or _planner.V5E
    if scheduled is None:
        scheduled = schedule(circuit, num_devices, chip=chip,
                             precision=precision,
                             pipeline_chunks=pipeline_chunks)
    before = _planner.comm_summary(circuit, num_devices, bytes_per_amp)
    after = _planner.comm_summary(scheduled, num_devices, bytes_per_amp)
    sec_before = _model_seconds(circuit, num_devices, chip, precision)
    sec_after = _model_seconds(scheduled, num_devices, chip, precision)
    overlap_cols = {}
    plan = getattr(scheduled, "_overlap_plan", None)
    if pipeline_chunks is not None or plan is not None:
        from . import executor as _exec
        o = _exec.predict_overlap(scheduled, num_devices,
                                  pipeline_chunks, chip=chip,
                                  precision=precision)
        overlap_cols = {
            "pipeline_chunks": o["pipeline_chunks"],
            "model_seconds_overlapped": o["model_seconds_overlapped"],
            "predicted_hidden_frac": o["predicted_hidden_frac"],
            "chunked_events": o["chunked_events"],
            "hideable_events": o["hideable_events"],
        }
    eng = _planner.engine_summary(scheduled, num_devices, chip, precision,
                                  requested=engine)
    return {
        **overlap_cols,
        "engine_chosen": eng["engine"],
        "engine_reason": eng["reason"],
        "engine_epochs": eng["epochs"],
        # which constants scored this schedule (fitted calibration profile
        # vs hard-coded defaults): every model column above was computed by
        # time_model/engine_summary through planner.efficiency_for
        "calibration": eng["calibration"],
        "engine_deferred_perm_ops": eng["deferred_perm_ops"],
        "num_devices": num_devices,
        "ops_before": before["ops"], "ops_after": after["ops"],
        "comm_events_before": before["comm_events"],
        "comm_events_after": after["comm_events"],
        "reshard_events_before": before["reshard_events"],
        "reshard_events_after": after["reshard_events"],
        "comm_bytes_before": before["bytes_moved"],
        "comm_bytes_after": after["bytes_moved"],
        "model_seconds_before": sec_before,
        "model_seconds_after": sec_after,
        "comm_events_saved_frac": (
            (before["comm_events"] - after["comm_events"])
            / before["comm_events"] if before["comm_events"] else 0.0),
        "comm_bytes_saved_frac": (
            (before["bytes_moved"] - after["bytes_moved"])
            / before["bytes_moved"] if before["bytes_moved"] else 0.0),
    }
