"""Mesh construction and amplitude-axis sharding.

Layout contract (identical to the reference's chunk-per-rank layout,
ref: QuEST_cpu_distributed.c:186-195): device d of an n-device mesh owns the
contiguous global amplitude window [d*2^n/D, (d+1)*2^n/D).  Power-of-2 device
counts only (ref: validateNumRanks, QuEST_validation.c:299) — every
cross-shard gate partner is then a hypercube edge ``d ^ 2^(q-local)``, which
maps onto ICI torus links as single-hop collective-permutes.

Multi-host: pass ``jax.distributed.initialize()``-discovered devices; the
mesh spans hosts and GSPMD routes ICI within a pod and DCN across pods.  The
highest qubits should sit on the slowest links — with the contiguous layout
the highest qubit maps to the outermost mesh axis, which is exactly the
DCN-adjacent one.
"""

from __future__ import annotations

import time

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AMPS_AXIS = "amps"


def process_info() -> dict:
    """``{"process_index", "process_count"}`` of the live JAX runtime —
    the stamp every cross-process artifact (trace shards, checkpoints)
    carries.  Falls back to the single-process identity (0 of 1) when JAX
    is not importable/initialised, so observability exports never fail for
    lack of a distributed runtime."""
    try:
        import jax
        return {"process_index": int(jax.process_index()),
                "process_count": int(jax.process_count())}
    except Exception:
        return {"process_index": 0, "process_count": 1}


def broadcast_host_epoch() -> tuple[float, float]:
    """``(base_epoch_s, local_offset_s)``: process 0's epoch clock is
    broadcast to every process (the same ``multihost_utils``
    ``broadcast_one_to_all`` pattern ``seed_quest_default`` uses for the
    reference's seed bcast), and each process estimates its own host-clock
    offset against it as ``midpoint(local before, local after) - base``.

    The midpoint bounds the estimate's error by half the broadcast's
    round-trip — microseconds on ICI, milliseconds on DCN — which is enough
    to line request spans up across host tracks in one merged trace
    (obs/aggregate.py).  Single-process: ``(time.time(), 0.0)`` with no
    collective (the degenerate merge must not require a distributed
    runtime).  Multi-process this is a COLLECTIVE: every process must call
    it, like any other broadcast.

    Backends that cannot run cross-process collectives at all (the pinned
    jaxlib's CPU backend — docs/DESIGN.md "Known stack regressions")
    degrade to offset 0.0 rather than raise: observability must never be
    the thing that kills a run, and on an NTP-synced fleet the raw epoch
    clocks are already close."""
    if process_info()["process_count"] <= 1:
        return time.time(), 0.0
    try:
        from jax.experimental import multihost_utils
        t_before = time.time()
        base = float(multihost_utils.broadcast_one_to_all(
            np.asarray([time.time()], np.float64))[0])
        t_after = time.time()
        return base, 0.5 * (t_before + t_after) - base
    except Exception:
        return time.time(), 0.0


def broadcast_payload(data: bytes, max_bytes: int = 1 << 16) -> bytes:
    """Broadcast process 0's byte payload to every process — the same
    ``multihost_utils.broadcast_one_to_all`` primitive as
    :func:`broadcast_host_epoch`, carrying an opaque length-prefixed buffer
    instead of a timestamp (the deploy layer ships hot compile-cache class
    keys over it so cold replicas warm from the persistent store in peer
    order; quest_tpu/deploy/pool.py).

    Every process passes its OWN ``data`` (non-zero ranks' payloads are
    ignored, as with any bcast) and receives process 0's.  The buffer is
    padded to ``max_bytes`` so the collective has one static shape; a
    payload longer than ``max_bytes - 4`` raises ``ValueError`` at the
    sender.  Single-process: the identity, no collective.  Backends that
    cannot run cross-process collectives (the pinned jaxlib's CPU backend,
    docs/DESIGN.md "Known stack regressions") degrade to returning the
    LOCAL payload rather than raise — warm-up hints are an optimization,
    never the thing that kills a launch."""
    if len(data) > max_bytes - 4:
        raise ValueError(f"payload of {len(data)} bytes exceeds the "
                         f"{max_bytes - 4}-byte broadcast buffer")
    if process_info()["process_count"] <= 1:
        return data
    buf = np.zeros(max_bytes, np.uint8)
    buf[:4] = np.frombuffer(np.uint32(len(data)).tobytes(), np.uint8)
    buf[4:4 + len(data)] = np.frombuffer(data, np.uint8)
    try:
        from jax.experimental import multihost_utils
        out = np.asarray(multihost_utils.broadcast_one_to_all(buf),
                         np.uint8)
        n = int(np.frombuffer(out[:4].tobytes(), np.uint32)[0])
        if n > max_bytes - 4:
            return data
        return out[4:4 + n].tobytes()
    except Exception:
        return data


def make_amps_mesh(devices) -> Mesh:
    """1-D mesh over the amplitude axis (power-of-2 device count)."""
    devices = np.asarray(devices)
    n = devices.size
    if n & (n - 1):
        raise ValueError(f"device count must be a power of 2, got {n}")
    return Mesh(devices, (AMPS_AXIS,))


def amp_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding of a (2, 2^n) SoA pair: re/im replicated, amps split."""
    return NamedSharding(mesh, P(None, AMPS_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
