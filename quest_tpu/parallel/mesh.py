"""Mesh construction and amplitude-axis sharding.

Layout contract (identical to the reference's chunk-per-rank layout,
ref: QuEST_cpu_distributed.c:186-195): device d of an n-device mesh owns the
contiguous global amplitude window [d*2^n/D, (d+1)*2^n/D).  Power-of-2 device
counts only (ref: validateNumRanks, QuEST_validation.c:299) — every
cross-shard gate partner is then a hypercube edge ``d ^ 2^(q-local)``, which
maps onto ICI torus links as single-hop collective-permutes.

Multi-host: pass ``jax.distributed.initialize()``-discovered devices; the
mesh spans hosts and GSPMD routes ICI within a pod and DCN across pods.  The
highest qubits should sit on the slowest links — with the contiguous layout
the highest qubit maps to the outermost mesh axis, which is exactly the
DCN-adjacent one.
"""

from __future__ import annotations

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AMPS_AXIS = "amps"


def make_amps_mesh(devices) -> Mesh:
    """1-D mesh over the amplitude axis (power-of-2 device count)."""
    devices = np.asarray(devices)
    n = devices.size
    if n & (n - 1):
        raise ValueError(f"device count must be a power of 2, got {n}")
    return Mesh(devices, (AMPS_AXIS,))


def amp_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding of a (2, 2^n) SoA pair: re/im replicated, amps split."""
    return NamedSharding(mesh, P(None, AMPS_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
