"""Pipelined cross-shard execution engine: chunked collectives
double-buffered against gate compute.

The reference's distributed path fully serializes pairwise exchange and
gate arithmetic (QuEST_cpu_distributed.c: exchangeStateVectors completes,
*then* the compute loop runs), and our default compiled path inherits the
same structure — each scheduler-emitted boundary permutation lowers to one
monolithic state-sized collective the chip sits idle behind.  This module
restructures the program so XLA *can* hide ICI time behind HBM/MXU work,
the canonical TPU optimization: every comm-carrying event is split into C
independent per-chunk sub-programs, so the collective for chunk i+1 has no
data dependence on the gate run over chunk i and the compiler's async
collective start/done scheduling interleaves them.

Two chunking engines, planned statically by :func:`plan_overlap`:

1. **Pairwise shard_map engine** (``kind='pairwise'``).  A dense 1-target
   gate on a sharded wire is the reference's MPI_Sendrecv exchange.  Here
   it is lowered explicitly through ``shard_map``: the per-shard state is
   split into C contiguous chunks, each chunk's partner half rides its own
   ``lax.ppermute``, and the gate's combine arithmetic
   (``out = u[b,b]*mine + u[b,1-b]*theirs`` on device bit ``b``) executes
   on chunk i while chunk i+1 permutes.

2. **Window slicing engine** (``kind='window'``).  A boundary ``bitperm``
   (and, when the scheduler emitted an epoch sandwich
   ``bitperm . gates . bitperm``, the WHOLE sandwich) is chunked along
   amplitude-index bits its ops never touch: fixing those bits slices the
   state into C interleaved sub-states on which the window acts
   independently, so chunking is *layout-only* — each chunk runs the
   wire-renumbered window through the ordinary engines and GSPMD lowers
   one 1/C-sized all-to-all per chunk instead of one monolithic reshard.

An event with no free chunk bits (or no compute to hide — a lone
comm-dominated reshard) stays monolithic; the planner's overlap-aware cost
(:class:`planner.GateTime`) and :func:`predict_overlap` charge it serially,
and the lowered-program audit (analysis/jaxpr_audit.py) reports
``A_COLLECTIVE_NOT_OVERLAPPED`` when a collective the plan expected to
hide compiles without async start/done separation.

Entry points: ``compile_circuit(..., num_devices=, overlap=True)``,
``Circuit.schedule(..., overlap=True, pipeline_chunks=C)`` (kwargs
validated through ``E_INVALID_SCHEDULE_OPTION``), and
:func:`overlapped_program` / :func:`predict_overlap` for direct use.
See docs/SCHEDULER.md "Pipelined execution".
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs as _obs
from . import planner as _planner

__all__ = ["ChunkedEvent", "OverlapPlan", "plan_overlap",
           "overlapped_program", "predict_overlap",
           "validate_pipeline_chunks"]


def validate_pipeline_chunks(pipeline_chunks, func=None) -> int:
    """A chunk count must be a power-of-two int >= 1 (the chunk axis halves
    the shard's amplitude index like the mesh halves the global one);
    anything else raises the validation layer's
    ``E_INVALID_SCHEDULE_OPTION``."""
    from ..validation import MESSAGES, ErrorCode, QuESTError
    c = pipeline_chunks
    if (isinstance(c, int) and not isinstance(c, bool) and c >= 1
            and (c & (c - 1)) == 0):
        return c
    raise QuESTError(
        ErrorCode.INVALID_SCHEDULE_OPTION,
        MESSAGES[ErrorCode.INVALID_SCHEDULE_OPTION]
        + f" pipeline_chunks must be a power-of-two integer >= 1, got "
        f"{pipeline_chunks!r}.", func or "schedule")


@dataclasses.dataclass(frozen=True)
class ChunkedEvent:
    """One comm event the executor pipelines: ops ``[start, stop)`` of the
    scheduled circuit run chunked.  ``chunk_bits`` are the amplitude-index
    bit positions sliced into the chunk axis ('window' engine; empty for
    'pairwise', which splits the shard contiguously); ``chunks`` is the
    effective per-event chunk count after clamping to the free bits."""
    start: int
    stop: int
    kind: str          # 'pairwise' | 'window'
    chunk_bits: tuple
    chunks: int
    comm: str          # planner comm class of the event
    hideable: bool     # does compute exist for the pipeline to hide comm?


@dataclasses.dataclass(frozen=True)
class OverlapPlan:
    num_qubits: int
    num_devices: int
    pipeline_chunks: int
    events: tuple

    def event_at(self, i: int):
        for e in self.events:
            if e.start == i:
                return e
        return None


def _op_used_bits(op) -> set:
    """Every amplitude-index bit position an op reads or writes non-trivially
    (bitperm destinations included: its payload names positions, not data)."""
    used = set(op.targets) | set(op.controls)
    if op.kind == "bitperm":
        used |= {int(d) for d in op.matrix}
    return used


def plan_overlap(circuit, num_devices: int, pipeline_chunks: int) -> OverlapPlan:
    """Static chunking plan for ``circuit`` over an ``num_devices``-way
    amplitude mesh: walk the planner's comm plan and, per comm event,
    choose an engine and the chunk bits.  Pure host work; the plan is part
    of the compiled program's static structure and is what
    ``analysis.equivalence.check_overlap_plan`` proves layout-only."""
    c_total = validate_pipeline_chunks(pipeline_chunks, "plan_overlap")
    n = circuit.num_qubits
    local_q = _planner.local_qubit_count(n, num_devices)
    events: list = []
    if num_devices <= 1 or local_q <= 0:
        return OverlapPlan(n, num_devices, c_total, ())
    plans = _planner.comm_plan(circuit, num_devices)
    ops = circuit.ops
    shard_amps = (1 << n) // num_devices
    want_bits = (c_total - 1).bit_length()  # log2
    i = 0
    while i < len(ops):
        if plans[i].comm == "none":
            i += 1
            continue
        op = ops[i]
        if (plans[i].comm == "permute" and op.kind in ("matrix", "x", "y")
                and len(op.targets) == 1 and not op.controls
                and op.targets[0] >= local_q and c_total <= shard_amps):
            events.append(ChunkedEvent(i, i + 1, "pairwise", (), c_total,
                                       plans[i].comm, True))
            i += 1
            continue
        # window engine: a lone collective op, or — when this is a
        # scheduler epoch bracket — the whole bitperm.gates.bitperm
        # sandwich, whose interior compute the chunk pipeline then hides
        stop = i + 1
        hideable = False
        if op.kind == "bitperm":
            j = i + 1
            while j < len(ops) and plans[j].comm == "none":
                j += 1
            if j < len(ops) and j > i + 1 and ops[j] == op:
                stop = j + 1
                hideable = True
        used: set = set()
        for w_op in ops[i:stop]:
            used |= _op_used_bits(w_op)
        free = [b for b in range(local_q - 1, -1, -1) if b not in used]
        from ..ops.apply import _blocks
        lo = sum(_blocks(n))
        # prefer tile-aligned prefix bits; minor bits still slice correctly
        free = [b for b in free if b >= lo] + [b for b in free if b < lo]
        c_bits = min(want_bits, len(free))
        bits = tuple(sorted(free[:c_bits], reverse=True))
        events.append(ChunkedEvent(i, stop, "window", bits, 1 << c_bits,
                                   plans[i].comm, hideable))
        i = stop
    return OverlapPlan(n, num_devices, c_total, tuple(events))


# ---------------------------------------------------------------------------
# engine 1: explicit shard_map pairwise exchange, chunk-pipelined
# ---------------------------------------------------------------------------

def _pair_matrix(op) -> np.ndarray:
    if op.kind == "matrix":
        return op.payload()
    if op.kind == "x":
        return np.stack([np.array([[0.0, 1.0], [1.0, 0.0]]),
                         np.zeros((2, 2))])
    if op.kind == "y":
        return np.stack([np.zeros((2, 2)),
                         np.array([[0.0, -1.0], [1.0, 0.0]])])
    raise ValueError(f"pairwise engine cannot lower kind {op.kind!r}")


def _pairwise_overlapped(state: jax.Array, op, mesh, chunks: int) -> jax.Array:
    """Dense 1-target gate on a sharded wire as C chunked explicit
    exchanges: the reference's MPI_Sendrecv path
    (QuEST_cpu_distributed.c:479 exchangeStateVectors +
    statevec_unitaryDistributed), except each ``lax.ppermute`` carries one
    chunk and the combine FMA of chunk i overlaps chunk i+1's wire time."""
    from .._compat import shard_map
    from ..ops.apply import num_qubits_of
    from .mesh import AMPS_AXIS
    from jax.sharding import PartitionSpec as P

    n = num_qubits_of(state)
    n_dev = mesh.devices.size
    local_q = _planner.local_qubit_count(n, n_dev)
    d = op.targets[0] - local_q
    perm = [(r, r ^ (1 << d)) for r in range(n_dev)]
    u = jnp.asarray(_pair_matrix(op), state.dtype)

    @partial(shard_map, mesh=mesh, in_specs=P(None, AMPS_AXIS),
             out_specs=P(None, AMPS_AXIS))
    def run(shard):
        rank = jax.lax.axis_index(AMPS_AXIS)
        b = (rank >> d) & 1
        # row b of u makes OUR half: out = u[b,b]*mine + u[b,1-b]*theirs
        urr, uri = u[0, b, b], u[1, b, b]
        upr, upi = u[0, b, 1 - b], u[1, b, 1 - b]
        csz = shard.shape[1] // chunks
        pieces = []
        for k in range(chunks):
            mine = jax.lax.slice_in_dim(shard, k * csz, (k + 1) * csz, axis=1)
            theirs = jax.lax.ppermute(mine, AMPS_AXIS, perm)
            re = (urr * mine[0] - uri * mine[1]
                  + upr * theirs[0] - upi * theirs[1])
            im = (urr * mine[1] + uri * mine[0]
                  + upr * theirs[1] + upi * theirs[0])
            pieces.append(jnp.stack([re, im]))
        return jnp.concatenate(pieces, axis=1)

    return run(state)


# ---------------------------------------------------------------------------
# engine 2: window slicing along untouched bits (layout-only chunking)
# ---------------------------------------------------------------------------

def _renumber(bits: tuple, n: int) -> dict:
    """Wire map of the reduced index space after slicing out ``bits``."""
    removed = set(bits)
    return {q: q - sum(1 for b in bits if b < q)
            for q in range(n) if q not in removed}


def _controlled_payload(op) -> np.ndarray:
    """(2, 2^m, 2^m) real pair of ``op`` over its FULL wire list (targets
    LSB-first, then controls): controls embedded as identity blocks, the
    same local convention as analysis/equivalence.py's oracle."""
    p = op.payload()
    if not op.controls:
        return p
    k = len(op.targets)
    m = k + len(op.controls)
    cs = [int(s) for s in (op.control_states or (1,) * len(op.controls))]
    base = p[0] + 1j * p[1]
    full = np.zeros((1 << m, 1 << m), dtype=complex)
    for col in range(1 << m):
        if not all(((col >> (k + j)) & 1) == s for j, s in enumerate(cs)):
            full[col, col] = 1.0
            continue
        rest = col >> k << k
        for row_sub in range(1 << k):
            full[rest | row_sub, col] = base[row_sub, col & ((1 << k) - 1)]
    return np.stack([full.real, full.imag])


def _apply_dense_invariant(state: jax.Array, op) -> jax.Array:
    """Dense gate with CHUNK-INVARIANT arithmetic: the wire axes are moved
    to the front and contracted as one fixed-order complex matmul, so the
    per-amplitude FMA sequence is identical at every reduced state size.
    The ordinary engines pick reroutes and tile groupings by absolute wire
    position — mathematically equal but floating-point DIFFERENT summation
    orders — which would break the executor's bit-identical-across-C
    contract (tests/test_executor.py)."""
    from ..ops.apply import num_qubits_of
    n = num_qubits_of(state)
    wires = op.targets + op.controls
    m = len(wires)
    p = _controlled_payload(op)
    ur = jnp.asarray(p[0], state.dtype)
    ui = jnp.asarray(p[1], state.dtype)
    t = state.reshape((2,) + (2,) * n)
    # payload bit j indexes wires[j] (LSB-first): axis order MSB-first
    src = tuple(1 + (n - 1 - q) for q in reversed(wires))
    dst = tuple(range(1, m + 1))
    t = jnp.moveaxis(t, src, dst)
    shape = t.shape
    t = t.reshape(2, 1 << m, -1)
    xr, xi = t[0], t[1]
    out = jnp.stack([ur @ xr - ui @ xi, ur @ xi + ui @ xr])
    return jnp.moveaxis(out.reshape(shape), dst, src).reshape(2, -1)


def _apply_reduced(state: jax.Array, op) -> jax.Array:
    from ..circuit import _apply_one
    if op.kind == "bitperm":
        # chunk slices renumber wires below the tile boundary; force the
        # single-transpose form so the chunked collective stays ONE
        # all-to-all instead of a per-swap chain (apply.py allow_minor)
        from ..ops.apply import apply_bit_permutation
        return apply_bit_permutation(
            state, op.targets, tuple(int(x) for x in op.matrix),
            allow_minor=True)
    if op.kind == "matrix":
        return _apply_dense_invariant(state, op)
    # every other kind is per-amplitude movement / single-multiply work,
    # which rounds identically at any reduced size
    return _apply_one(state, op)


def _window_chunked(state: jax.Array, window_ops: tuple,
                    chunk_bits: tuple) -> jax.Array:
    """Run ``window_ops`` as 2^len(chunk_bits) independent sub-programs,
    one per assignment of the (untouched) chunk bits.  Exact by
    construction: ops that never read or move a bit act identically on
    each slice along it, so this is a pure re-layout of the monolithic
    program — the property ``analysis.equivalence.check_overlap_plan``
    certifies per event."""
    from ..ops.apply import num_qubits_of
    from ..parallel.scheduler import _relabel_op

    n = num_qubits_of(state)
    if not chunk_bits:
        for op in window_ops:
            state = _apply_reduced(state, op)
        return state
    c = len(chunk_bits)
    bits = tuple(sorted(chunk_bits, reverse=True))  # MSB-first, like dims
    shift = _renumber(bits, n)
    reduced = [_relabel_op(op, shift) for op in window_ops]
    t = state.reshape((2,) + (2,) * n)
    chunk_axes = tuple(1 + (n - 1 - b) for b in bits)
    keep_shape = tuple(dim for a, dim in enumerate(t.shape)
                       if a not in chunk_axes)
    outs = []
    for k in range(1 << c):
        idx: list = [slice(None)] * t.ndim
        for j, ax in enumerate(chunk_axes):
            idx[ax] = (k >> (c - 1 - j)) & 1
        xk = t[tuple(idx)].reshape(2, -1)
        for op in reduced:
            xk = _apply_reduced(xk, op)
        outs.append(xk.reshape(keep_shape))
    stacked = jnp.stack(outs, axis=1).reshape((2,) + (2,) * c
                                              + keep_shape[1:])
    merged = jnp.moveaxis(stacked, tuple(range(1, c + 1)), chunk_axes)
    return merged.reshape(2, -1)


# ---------------------------------------------------------------------------
# the executor
# ---------------------------------------------------------------------------

def _run_ops_overlapped(state: jax.Array, ops: tuple, plan: OverlapPlan,
                        mesh) -> jax.Array:
    from ..circuit import _apply_one
    by_start = {e.start: e for e in plan.events}
    i = 0
    while i < len(ops):
        e = by_start.get(i)
        if e is None:
            state = _apply_one(state, ops[i])
            i += 1
        elif e.kind == "pairwise":
            state = _pairwise_overlapped(state, ops[i], mesh, e.chunks)
            i = e.stop
        else:
            state = _window_chunked(state, ops[e.start:e.stop], e.chunk_bits)
            i = e.stop
    return state


def overlapped_program(circuit, num_devices: int,
                       pipeline_chunks: int | None = None, *,
                       mesh=None, donate: bool = False):
    """Jitted ``state -> state`` running ``circuit`` through the pipelined
    executor on an ``num_devices``-way amplitude mesh.  Uses the overlap
    plan ``Circuit.schedule(..., overlap=True)`` attached, else plans here
    (``pipeline_chunks=None`` takes :func:`planner.recommend_pipeline_chunks`).
    Output sharding is pinned to the mesh's amplitude sharding so trailing
    permutations cannot be virtualised into an output-layout drift (the
    bench.py pair methodology).  Overlapped programs are rebuilt per call —
    they carry a mesh — so cache the returned function, not the circuit."""
    from ..validation import MESSAGES, ErrorCode, QuESTError, \
        validate_num_ranks
    from .mesh import amp_sharding, make_amps_mesh
    validate_num_ranks(num_devices, "overlapped_program")
    plan = getattr(circuit, "_overlap_plan", None)
    if plan is None or plan.num_devices != num_devices or (
            pipeline_chunks is not None
            and plan.pipeline_chunks != pipeline_chunks):
        if pipeline_chunks is None:
            pipeline_chunks = _planner.recommend_pipeline_chunks(
                circuit.num_qubits, num_devices)
        plan = plan_overlap(circuit, num_devices,
                            validate_pipeline_chunks(pipeline_chunks,
                                                     "overlapped_program"))
    if mesh is None:
        devices = jax.devices()
        if len(devices) < num_devices:
            raise QuESTError(
                ErrorCode.INVALID_NUM_RANKS,
                MESSAGES[ErrorCode.INVALID_NUM_RANKS]
                + f" The overlapped executor needs {num_devices} devices; "
                f"this process has {len(devices)}.", "overlapped_program")
        mesh = make_amps_mesh(devices[:num_devices])
    ops = circuit.key()

    def run(state: jax.Array) -> jax.Array:
        return _run_ops_overlapped(state, ops, plan, mesh)

    jitted = jax.jit(run, out_shardings=amp_sharding(mesh),
                     donate_argnums=(0,) if donate else ())

    def traced(state: jax.Array) -> jax.Array:
        # overlapped dispatch span (free while tracing is off): the chunked
        # collective schedule shows up as one host region per call
        if not _obs.tracing_enabled():
            return jitted(state)
        with _obs.span("executor.overlapped_run", num_devices=num_devices,
                       pipeline_chunks=plan.pipeline_chunks, ops=len(ops)):
            return jitted(state)

    traced.lower = jitted.lower      # the bench/audit HLO-inspection hook
    return traced


# ---------------------------------------------------------------------------
# the overlap-aware cost report (planner prediction for bench/CI)
# ---------------------------------------------------------------------------

def predict_overlap(circuit, num_devices: int,
                    pipeline_chunks: int | None = None, *,
                    chip=None, precision: int = 1) -> dict:
    """Event-level overlap prediction: for each planned event, serial cost
    is the window's summed compute + comm; pipelined cost is
    ``max(compute, comm) + min(compute, comm)/C`` (the per-chunk ramp) when
    the event is hideable, serial otherwise (a lone comm-dominated reshard
    has nothing to hide behind).  ``predicted_hidden_frac`` is the fraction
    of total comm seconds the model expects hidden — the column bench.py
    prints next to the measured delta."""
    chip = chip or _planner.V5E
    if pipeline_chunks is None:
        plan = getattr(circuit, "_overlap_plan", None)
        pipeline_chunks = (plan.pipeline_chunks if plan is not None
                           else _planner.recommend_pipeline_chunks(
                               circuit.num_qubits, num_devices, chip,
                               precision))
    c_total = validate_pipeline_chunks(pipeline_chunks, "predict_overlap")
    plan = plan_overlap(circuit, num_devices, c_total)
    times = _planner.time_model(circuit, num_devices, chip, precision)
    by_start = {e.start: e for e in plan.events}
    serial = overlapped = comm_total = 0.0
    events_out = []
    i = 0
    while i < len(times):
        e = by_start.get(i)
        if e is None:
            t = times[i]
            serial += t.compute_s + t.comm_s
            overlapped += t.compute_s + t.comm_s
            comm_total += t.comm_s
            i += 1
            continue
        span = times[e.start:e.stop]
        comp = sum(t.compute_s for t in span)
        comm = sum(t.comm_s for t in span)
        serial += comp + comm
        comm_total += comm
        if e.hideable and e.chunks > 1:
            cost = max(comp, comm) + min(comp, comm) / e.chunks
        else:
            cost = comp + comm
        overlapped += cost
        events_out.append({
            "start": e.start, "stop": e.stop, "engine": e.kind,
            "comm": e.comm, "chunks": e.chunks, "hideable": e.hideable,
            "compute_s": comp, "comm_s": comm, "serial_s": comp + comm,
            "overlapped_s": cost,
        })
        i = e.stop
    return {
        "num_devices": num_devices,
        "pipeline_chunks": c_total,
        "events": events_out,
        "chunked_events": sum(1 for e in plan.events if e.chunks > 1),
        "hideable_events": sum(1 for e in plan.events if e.hideable),
        "model_seconds_serial": serial,
        "model_seconds_overlapped": overlapped,
        "model_comm_seconds": comm_total,
        "predicted_hidden_frac": ((serial - overlapped) / comm_total
                                  if comm_total else 0.0),
    }
