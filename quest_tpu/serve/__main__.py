"""``python -m quest_tpu.serve`` — the serving-layer CLI.

``--selftest`` runs the synthetic multi-tenant workload (selftest.py):
three single-device structural classes plus, on an 8+-device backend, a
scheduled mesh class — asserting bit-identical results against the eager
oracle, a >= 0.9 cache hit rate and a well-formed Prometheus export, then
printing the metrics.  ``--json`` switches stdout to ONE machine-readable
document (``{"ok":, "checks":, "metrics":, "prometheus":,
"flight_recorder":, "slo":}`` plus ``"trace"`` under ``--trace``: the
merged multi-track Chrome trace from obs/aggregate.py; plus ``"numeric"``
under ``--probes`` / ``QUEST_TPU_NUMERIC_PROBES=1``: the numeric drift
ledger + injected-corruption trip from obs/numerics.py) for the CI gate.
Exit status 0 iff every check passed.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m quest_tpu.serve",
        description="Batched multi-tenant circuit-execution service "
                    "(docs/SERVING.md).")
    parser.add_argument("--selftest", action="store_true",
                        help="run the synthetic multi-tenant workload and "
                             "print its metrics")
    parser.add_argument("--scale", type=int, default=1,
                        help="workload multiplier for the selftest "
                             "(default 1: 64 single-device requests)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit ONE machine-readable JSON document")
    parser.add_argument("--trace", action="store_true",
                        help="record the run through the span recorder "
                             "(quest_tpu/obs) and export/validate the "
                             "Chrome-trace JSON; QUEST_TPU_TRACE=1 does "
                             "the same")
    parser.add_argument("--probes", action="store_true",
                        help="serve the workload through the numeric-"
                             "probe-instrumented programs (quest_tpu/obs/"
                             "numerics.py) and gate the numeric-health "
                             "checks; QUEST_TPU_NUMERIC_PROBES=1 does "
                             "the same")
    parser.add_argument("--gradients", action="store_true",
                        help="run the gradient workload phase (quest_tpu/"
                             "grad): mixed forward+gradient storm with "
                             "bit-identity, oracle, hit-rate, NaN-trip "
                             "and router-quarantine gates; "
                             "QUEST_TPU_GRAD_SELFTEST=1 does the same")
    parser.add_argument("--density", action="store_true",
                        help="run the noisy density-matrix phase: a probed "
                             "probability-sweep storm of one noisy "
                             "structural class with hit-rate, bit-identity,"
                             " trace/Hermiticity health, fused-superop-plan"
                             " and Kraus-admission gates; "
                             "QUEST_TPU_DENSITY_SELFTEST=1 does the same")
    args = parser.parse_args(argv)
    if not args.selftest:
        parser.print_usage()
        return 2
    from .selftest import run_selftest
    return run_selftest(as_json=args.as_json, scale=max(1, args.scale),
                        trace=True if args.trace else None,
                        probes=True if args.probes else None,
                        gradients=True if args.gradients else None,
                        density=True if args.density else None)


if __name__ == "__main__":
    sys.exit(main())
