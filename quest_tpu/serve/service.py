"""QuESTService: the multi-tenant front door over the compile cache.

``submit(circuit, params=None, shots=0, deadline_ms=None)`` returns a
``concurrent.futures.Future``; a background worker groups queued requests by
structural class and runs each group as one vmapped microbatch (batch.py)
through the parameter-lifted cache (cache.py).  The queue is BOUNDED —
overflow raises ``E_QUEUE_FULL`` at submit time (backpressure belongs at the
front door, not in an unbounded deque that OOMs the host) — and deadlines
are enforced when a request would enter a batch: an expired request
completes exceptionally with ``E_DEADLINE_EXCEEDED`` instead of occupying a
batch slot and making every co-batched request later.

Measurement sampling is per-request and batching-invariant: request ``i``
draws from its OWN MT19937 stream seeded ``(service_seed, request_id)`` —
the reference's one global stream (QuEST_common.c:155-170) would make
outcomes depend on scheduling order, which a batching server must never do.
Results are bit-identical to serial per-circuit execution in the default
``batch_mode='map'``: the lifted program runs the same routed op chain with
the same operand values, and the ``lax.map`` batch lowering keeps the
per-element jaxpr identical to the singleton program.  ``batch_mode='vmap'``
vectorizes across the batch instead — measurably faster on wide batches,
bit-exact only to the last f64 ulp (XLA's batched FMA fusion differs; see
docs/SERVING.md).
"""

from __future__ import annotations

import dataclasses
import math
import os
import threading
import time
from concurrent.futures import Future, InvalidStateError

import jax
import jax.numpy as jnp
import numpy as np

from .. import circuit as _circ
from .. import obs as _obs
from ..grad import GradResult
from ..grad import adjoint as _gradadj
from ..obs import numerics as _numerics
from ..obs.export import EXECUTION_SPAN
from ..obs.flight import FlightRecorder
from ..obs.slo import SLOConfig, SLOMonitor
from ..rng import MT19937
from ..validation import ErrorCode, MESSAGES, QuESTError
from . import batch as _batch
from .cache import CacheOptions, CompileCache, global_cache
from .metrics import BATCH_BUCKETS, Metrics

__all__ = ["QuESTService", "ServeResult", "GradResult"]

_U32 = 0xFFFFFFFF


@dataclasses.dataclass
class ServeResult:
    """One completed request: the final (2, 2^n) SoA state, the per-request
    sample draws (``shots`` joint outcomes over all qubits, or None), and
    the batch context it executed in.  ``cache_outcome`` reports whether
    this request's class lookup hit or missed the compile cache — the
    affinity feedback the deployment router (quest_tpu/deploy/router.py)
    re-places on when a replica evicts a class under byte pressure.
    ``numeric_health`` (probed requests only) is the numeric-probe record
    of THIS request's result — norm drift vs the ulp band, NaN/Inf
    counts, findings (obs/numerics.py); the router quarantines a (class,
    replica) placement on repeated NaN outcomes read from here."""
    state: np.ndarray
    samples: np.ndarray | None
    batch_size: int
    request_id: int
    cache_outcome: str | None = None
    numeric_health: dict | None = None


@dataclasses.dataclass
class _Request:
    rid: int
    ops: tuple
    num_qubits: int
    params: np.ndarray
    shots: int
    deadline: float | None          # absolute time.monotonic(), or None
    initial_state: np.ndarray | None
    future: Future
    enqueue_t: float
    group_key: tuple
    class_key: str = ""             # obs.key_hash(structural part), for SLO/trace
    probes: bool = False            # numeric-probe-instrumented execution
    expected_norm: float = 1.0      # drift baseline: the input state's norm
    # gradient requests (submit_gradient): ops is the ParamCircuit op
    # tuple, params the parameter vector, and the three fields below carry
    # the Hamiltonian side of the class (quest_tpu/grad)
    grad: bool = False
    coeffs: np.ndarray | None = None    # term coefficients (runtime operand)
    masks: tuple | None = None          # packed term masks (structural)
    grad_num_params: int = 0
    # density requests (a DensityCircuit submitted through submit()): the
    # density qubit count n of the Choi-doubled 2n-qubit register — selects
    # the densmatr probe/ledger kind and rho-diagonal sampling
    density: int | None = None


class QuESTService:
    """Batched circuit-execution service over one device (default) or a
    ``num_devices``-way amplitude mesh (requests are scheduled through the
    PR 2 comm-aware scheduler once per structural class).

    Knobs: ``max_batch``/``max_delay_ms`` bound the microbatch aggregator
    (a group executes when it fills OR when its oldest request has waited
    the delay); ``max_queue`` bounds admission; ``seed`` roots the
    per-request sample streams; ``start=False`` defers the worker so a
    caller can stage a burst and then :meth:`start` it as one batch wave
    (benchmarks, tests)."""

    def __init__(self, *, num_devices: int | None = None,
                 overlap: bool = False, pipeline_chunks: int | None = None,
                 max_batch: int = 16, max_delay_ms: float = 2.0,
                 max_queue: int = 1024, seed: int = 0, dtype=None,
                 batch_mode: str = "map",
                 cache: CompileCache | None = None,
                 metrics: Metrics | None = None,
                 flight_capacity: int = 256,
                 slo: SLOMonitor | SLOConfig | None = None,
                 probes: bool | None = None,
                 numeric_ledger: "_numerics.NumericLedger | None" = None,
                 start: bool = True):
        if batch_mode not in ("map", "vmap"):
            raise ValueError(
                f"batch_mode must be 'map' or 'vmap', got {batch_mode!r}")
        self.batch_mode = batch_mode
        if overlap and (num_devices is None or num_devices < 2):
            raise QuESTError(ErrorCode.INVALID_SCHEDULE_OPTION,
                             MESSAGES[ErrorCode.INVALID_SCHEDULE_OPTION]
                             + " overlap=True requires num_devices=.",
                             "QuESTService")
        self._options = CacheOptions(num_devices=num_devices, overlap=overlap,
                                     pipeline_chunks=pipeline_chunks)
        self.max_batch = max(1, int(max_batch))
        self.max_delay_s = max(0.0, float(max_delay_ms) / 1000.0)
        self.max_queue = max(1, int(max_queue))
        self.seed = int(seed)
        self.dtype = jnp.float64 if dtype is None else dtype
        self._cache = cache if cache is not None else global_cache()
        self.metrics = metrics if metrics is not None else Metrics()
        # flight recorder (quest_tpu/obs/flight.py): the bounded ring of
        # recent request records dumped on E_QUEUE_FULL / deadline drops /
        # execution errors
        self.flight_recorder = FlightRecorder(capacity=flight_capacity)
        # SLO monitor (quest_tpu/obs/slo.py): windowed per-class latency,
        # deadline hit rate and burn-rate early warning — always on, like
        # the metrics registry (one deque append per completed request)
        self.slo = slo if isinstance(slo, SLOMonitor) else SLOMonitor(slo)
        # numeric-health probes (quest_tpu/obs/numerics.py): opt-in per
        # service (or fleet-wide via QUEST_TPU_NUMERIC_PROBES=1), with a
        # per-submit override; probed requests execute the instrumented
        # program variant and record into the numeric drift ledger
        if probes is None:
            probes = os.environ.get("QUEST_TPU_NUMERIC_PROBES") == "1"
        self.default_probes = bool(probes)
        # a PRIVATE ledger per service (unless injected): the scrape and
        # metrics_dict splice this ledger's totals, and attributing
        # another component's findings to this service would point an
        # operator's alert at the wrong replica (the process-global
        # ledger remains the CLI/bench recording target)
        self.numeric_ledger = (numeric_ledger if numeric_ledger is not None
                               else _numerics.NumericLedger())
        self._sharding = None
        if num_devices is not None and num_devices > 1:
            from ..parallel.mesh import amp_sharding, make_amps_mesh
            devices = jax.devices()
            if len(devices) < num_devices:
                raise QuESTError(ErrorCode.INVALID_NUM_RANKS,
                                 MESSAGES[ErrorCode.INVALID_NUM_RANKS]
                                 + f" ({len(devices)} devices visible, "
                                 f"{num_devices} requested.)", "QuESTService")
            self._sharding = amp_sharding(make_amps_mesh(devices[:num_devices]))
        self._cond = threading.Condition()
        self._queue: list[_Request] = []    # guarded-by: _cond
        self._inflight = 0                  # guarded-by: _cond
        self._next_rid = 0                  # guarded-by: _cond
        self._accepting = True              # guarded-by: _cond
        self._stop = False                  # guarded-by: _cond
        self._draining = False              # guarded-by: _cond
        self._batch_seq = 0                 # guarded-by: _cond
        self._reject_seq = 0                # guarded-by: _cond
        # daemon-ok: joined in shutdown(); daemonized so an abandoned
        # service (no shutdown call) never blocks interpreter exit
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="quest-serve-worker")
        self._started = False               # guarded-by: _cond
        self._shutdown = False              # guarded-by: _cond
        # set once when the FIRST shutdown() finishes tearing down; later
        # callers wait on it so "shutdown returned" always means "stopped"
        self._shutdown_done = threading.Event()
        if start:
            self.start()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "QuESTService":
        # check-then-act AND the Thread.start both happen under the
        # condition: two concurrent start() calls used to double-start the
        # worker (RuntimeError; the schedule fuzzer reproduces the
        # interleaving, tests/test_concurrency.py), and starting outside
        # the lock would let a concurrent shutdown() observe _started and
        # join a thread that has not booted yet.  Thread.start only waits
        # for the interpreter's bootstrap, not for _run to take the
        # condition, so holding it here cannot deadlock.
        with self._cond:
            if not self._started:
                self._started = True
                self._worker.start()
        return self

    def drain(self, timeout: float | None = None) -> bool:
        """Block until every queued and in-flight request has completed.
        Returns False on timeout."""
        end = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            self._draining = True
            self._cond.notify_all()
            try:
                while self._queue or self._inflight:
                    left = None if end is None else end - time.monotonic()
                    if left is not None and left <= 0:
                        return False
                    self._cond.wait(timeout=0.05 if left is None
                                    else min(0.05, left))
            finally:
                self._draining = False
        return True

    def shutdown(self, drain: bool = True, timeout: float | None = None) -> None:
        """Stop accepting requests; with ``drain`` (default) finish
        everything queued first, otherwise fail pending requests with
        ``E_SERVICE_SHUTDOWN``.  Idempotent: a second call is a no-op,
        not an error (the pool's parallel shutdown fan-out and operator
        retries both depend on it) — a CONCURRENT second call waits for
        the first teardown to finish, so returning always means the
        service is stopped."""
        with self._cond:
            first = not self._shutdown
            self._shutdown = True
            if first:
                self._accepting = False
                started = self._started
        if not first:
            self._shutdown_done.wait(timeout=timeout)
            return
        try:
            if drain and started:
                self.drain(timeout=timeout)
            with self._cond:
                dropped, self._queue = self._queue, []
                self._stop = True
                self._cond.notify_all()
            for req in dropped:
                self._fail(req, QuESTError(
                    ErrorCode.SERVICE_SHUTDOWN,
                    MESSAGES[ErrorCode.SERVICE_SHUTDOWN], "shutdown"))
            if started:
                self._worker.join(timeout=timeout)
        finally:
            self._shutdown_done.set()

    def __enter__(self) -> "QuESTService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=exc == (None, None, None))

    # -- submission ---------------------------------------------------------
    def submit(self, circuit, params=None, shots: int = 0,
               deadline_ms: float | None = None,
               initial_state=None, probes: bool | None = None) -> Future:
        """Enqueue one request; the Future resolves to a
        :class:`ServeResult` (or raises ``QuESTError`` for deadline expiry,
        or whatever the execution raised).

        ``params`` overrides the circuit's own operand vector (the
        multi-tenant idiom: ONE recorded ansatz object, per-user angles) —
        it must match the structural class's operand count.  ``shots``
        joint outcomes over all qubits are drawn from the request's private
        RNG stream.  ``deadline_ms`` is relative to submission.
        ``probes`` overrides the service's numeric-probe default for this
        request: a probed request runs the probe-instrumented program
        variant (primary output bit-identical) and carries a
        ``numeric_health`` record on its result and flight record.

        A :class:`~quest_tpu.circuit.DensityCircuit` submits a NOISY
        density-matrix workload: the recorded ops are already the
        Choi-doubled 2n-qubit program (mirrored unitaries + channel
        superoperators), so the class lifts, batches and routes like any
        other — one compiled program per (skeleton, channel mask), channel
        probabilities riding in the operand vector.  Admission validates
        every channel operand slice trace-preserving
        (``E_INVALID_KRAUS_OPS`` — a params override cannot smuggle in a
        malformed map), probed requests graft the DENSITY probe (trace +
        Hermiticity, judged as ``densmatr`` by the numeric ledger), the
        drift baseline is the initial state's TRACE, and ``shots`` sample
        from rho's diagonal."""
        if not isinstance(circuit, _circ.Circuit):
            raise TypeError(f"submit takes a Circuit, got {type(circuit)!r}")
        from ..autodiff import ParamCircuit, ParamOp
        if (isinstance(circuit, ParamCircuit)
                and any(isinstance(op, ParamOp) for op in circuit.ops)):
            raise TypeError(
                "submit takes a concrete Circuit; a ParamCircuit with "
                "traced parameters is a gradient workload — use "
                "submit_gradient(circuit, params, hamiltonian)")
        ops = circuit.key()
        expected = int(sum(_circ.op_param_count(op) for op in ops))
        if params is None:
            pvec = _circ.param_vector(ops)
        else:
            if self._options.overlap:
                raise ValueError(
                    "overlap services take parameters embedded in the "
                    "circuit: the pipelined executor compiles payloads in")
            # host-sync-ok: params are host scalars by the submit contract
            pvec = np.asarray(params, np.float64).ravel()
            if pvec.shape != (expected,):
                raise ValueError(
                    f"params has {pvec.shape[0]} scalars; this circuit's "
                    f"structural class takes {expected}")
        state0 = None
        if initial_state is not None:
            # host-sync-ok: initial states are host data by the contract
            state0 = np.asarray(initial_state)
            if state0.shape != (2, 1 << circuit.num_qubits):
                raise ValueError(
                    f"initial_state must be (2, 2^n) SoA, got {state0.shape}")
        shots = int(shots)
        if shots < 0:
            raise ValueError("shots must be >= 0")
        density = getattr(circuit, "density_qubits", None)
        if density is not None:
            # channel admission: every channel slot's superoperator operand
            # (recorded payload OR the params override's slice) must
            # preserve Tr(rho) — E_INVALID_KRAUS_OPS at the front door,
            # never silent trace drift on the worker
            _circ.validate_density_operands(
                circuit, pvec if params is not None else None, "submit")
        probed = self.default_probes if probes is None else bool(probes)
        # the probe flag is part of the BATCHING key (a probed and an
        # unprobed request run different compiled programs and must not
        # co-batch) but NOT of the class identity the SLO monitor, the
        # flight ring and the router aggregate on — probing is an
        # observability mode, not a different workload class.  The density
        # marker joins it for the same reason: the probed density twin is a
        # different executable.
        group_key = (circuit.num_qubits, circuit.key(structural=True),
                     state0 is None, probed, density)
        return self._enqueue(ops=ops, num_qubits=circuit.num_qubits,
                             pvec=pvec, shots=shots, deadline_ms=deadline_ms,
                             state0=state0, group_key=group_key,
                             probed=probed, density=density)

    def submit_gradient(self, circuit, params=None, hamiltonian=None,
                        deadline_ms: float | None = None,
                        initial_state=None,
                        probes: bool | None = None) -> Future:
        """Enqueue one ``(energy, gradient)`` request; the Future resolves
        to a :class:`~quest_tpu.grad.GradResult` (quest_tpu/grad — the
        adjoint-differentiation serving path).

        ``circuit`` is a :class:`~quest_tpu.autodiff.ParamCircuit` (the
        recorded ansatz — ONE object shared by every tenant of the class);
        ``params`` its flat parameter vector for this request;
        ``hamiltonian`` a :class:`~quest_tpu.matrices.PauliHamil` whose
        packed term masks join the structural class (same Pauli structure
        = one compiled program; coefficients are a runtime operand, so a
        coefficient sweep stays on one executable).  Admission enforces
        the adjoint method's contract with the gradient validation codes:
        a noise channel or non-unitary payload raises
        ``E_GRADIENT_NOT_UNITARY``, a density register
        ``E_GRADIENT_DENSITY_MODE`` — rejected HERE, not on the worker.
        Same-class requests microbatch exactly like forward traffic (the
        gradient flag joins the batching key, so gradient and forward
        groups never co-batch on one program), and batched gradients are
        bit-identical to the serial loop under the default
        ``batch_mode='map'``."""
        from ..autodiff import ParamCircuit
        if hamiltonian is None:
            raise TypeError(
                "submit_gradient(circuit, params, hamiltonian) requires a "
                "PauliHamil: the energy head is <psi|H|psi>")
        if not isinstance(circuit, ParamCircuit):
            raise TypeError(
                f"submit_gradient takes a ParamCircuit, got {type(circuit)!r}")
        if self._options.overlap or (self._options.num_devices or 1) > 1:
            raise QuESTError(
                ErrorCode.INVALID_SCHEDULE_OPTION,
                MESSAGES[ErrorCode.INVALID_SCHEDULE_OPTION]
                + " Gradient serving is single-device: the adjoint sweep "
                "is not scheduled through the mesh/overlap executors.",
                "submit_gradient")
        # admission-time validation (satellite: the error surface) — the
        # same codes adjoint_gradient_fn raises, so a bad circuit fails
        # the SUBMITTER, never the worker thread
        _gradadj.validate_gradient_circuit(circuit, "submit_gradient")
        if hamiltonian.num_qubits != circuit.num_qubits:
            raise QuESTError(
                ErrorCode.MISMATCHING_PAULI_HAMIL_QUREG_NUM_QUBITS,
                MESSAGES[ErrorCode.MISMATCHING_PAULI_HAMIL_QUREG_NUM_QUBITS],
                "submit_gradient")
        masks = _gradadj.hamil_masks(hamiltonian)
        # host-sync-ok: Hamiltonian coefficients are host floats by contract
        coeffs = np.asarray(hamiltonian.term_coeffs, np.float64).ravel()
        if coeffs.shape != (len(masks),):
            raise ValueError(
                f"hamiltonian has {len(masks)} terms but "
                f"{coeffs.shape[0]} coefficients")
        if params is None:
            raise TypeError(
                "submit_gradient requires the parameter vector (the "
                "request's angles for the shared ansatz)")
        # host-sync-ok: params are host scalars by the submit contract
        pvec = np.asarray(params, np.float64).ravel()
        if pvec.shape != (circuit.num_params,):
            raise ValueError(
                f"params has {pvec.shape[0]} scalars; this ansatz takes "
                f"{circuit.num_params}")
        state0 = None
        if initial_state is not None:
            # host-sync-ok: initial states are host data by the contract
            state0 = np.asarray(initial_state)
            if state0.shape != (2, 1 << circuit.num_qubits):
                if state0.shape == (2, 1 << (2 * circuit.num_qubits)):
                    # a Choi-doubled register: the density-mode rejection,
                    # not a generic shape complaint
                    raise QuESTError(
                        ErrorCode.GRADIENT_DENSITY_MODE,
                        MESSAGES[ErrorCode.GRADIENT_DENSITY_MODE],
                        "submit_gradient")
                raise ValueError(
                    f"initial_state must be (2, 2^n) SoA, got {state0.shape}")
        probed = self.default_probes if probes is None else bool(probes)
        sig = _gradadj.grad_group_signature(circuit, masks)
        group_key = (circuit.num_qubits, sig, state0 is None, probed)
        return self._enqueue(ops=tuple(circuit.ops),
                             num_qubits=circuit.num_qubits, pvec=pvec,
                             shots=0, deadline_ms=deadline_ms, state0=state0,
                             group_key=group_key, probed=probed, grad=True,
                             coeffs=coeffs, masks=masks,
                             grad_num_params=circuit.num_params,
                             span="serve.submit_gradient")

    def _enqueue(self, *, ops, num_qubits, pvec, shots, deadline_ms, state0,
                 group_key, probed, grad=False, coeffs=None, masks=None,
                 grad_num_params=0, density=None,
                 span="serve.submit") -> Future:
        """The shared admission tail of :meth:`submit` /
        :meth:`submit_gradient`: bounded-queue entry, backpressure,
        flight/SLO/span bookkeeping — one code path so the two front
        doors can never drift on the backpressure contract."""
        func = "submit_gradient" if grad else "submit"
        class_key = _obs.key_hash(group_key[:3])
        now = time.monotonic()
        deadline = None if deadline_ms is None else now + float(deadline_ms) / 1000.0
        # the numeric drift baseline is the REQUEST'S OWN input norm: a
        # caller-supplied initial state need not be unit-norm (only the
        # shape is validated above), and judging it against 1.0 would
        # report the tenant's scaling as a kernel miscompile.  Computed
        # HERE, on the submitter's thread — a per-request constant has no
        # business on the worker's latency-critical result loop
        expected_norm = 1.0
        if probed and state0 is not None:
            s0 = state0.astype(np.float64, copy=False)
            if density is not None:
                # the density probe's first field is Tr(rho), so the drift
                # baseline is the INPUT's trace, not its L2 norm
                dim = 1 << int(density)
                expected_norm = float(np.trace(s0[0].reshape(dim, dim)))
            else:
                expected_norm = float(np.sum(s0[0] * s0[0] + s0[1] * s0[1]))
        t0p = time.perf_counter()
        fut: Future = Future()
        with self._cond:
            if not self._accepting or self._stop:
                raise QuESTError(ErrorCode.SERVICE_SHUTDOWN,
                                 MESSAGES[ErrorCode.SERVICE_SHUTDOWN],
                                 func)
            if len(self._queue) >= self.max_queue:
                self.metrics.inc("queue_rejected_total")
                depth = len(self._queue)
                # rejected requests never receive a real request id; the
                # flight record gets a distinct NEGATIVE id so a bounce can
                # never alias (or later mis-resolve) an admitted request
                self._reject_seq += 1
                rejected_rid = -self._reject_seq
                rid = None
            else:
                rid = self._next_rid
                self._next_rid += 1
                self._queue.append(_Request(rid, ops, num_qubits,
                                            pvec, shots, deadline, state0,
                                            fut, now, group_key, class_key,
                                            probed, expected_norm, grad,
                                            coeffs, masks, grad_num_params,
                                            density))
                depth = len(self._queue)
                self.metrics.inc("requests_submitted_total")
                if grad:
                    self.metrics.inc("grad_requests_submitted_total")
                self.metrics.set_gauge("queue_depth", depth)
                self._cond.notify_all()
        # saturation is sampled on EVERY admission attempt, bounces
        # included: the gauge must rise before E_QUEUE_FULL starts, not
        # first appear in the post-mortem
        self.slo.observe_queue(depth, self.max_queue)
        if rid is None:
            # backpressure is the flight recorder's headline moment: record
            # the bounce and dump the ring for the post-mortem
            self.flight_recorder.reject(rejected_rid, class_key, depth)
            self.flight_recorder.dump(ErrorCode.QUEUE_FULL)
            raise QuESTError(ErrorCode.QUEUE_FULL,
                             MESSAGES[ErrorCode.QUEUE_FULL], func)
        self.flight_recorder.admit(rid, class_key, depth,
                                   deadline_ms=deadline_ms)
        _obs.emit_span(span, t0=t0p, dur=time.perf_counter() - t0p,
                       request_id=rid, class_key=class_key,
                       queue_depth=depth)
        return fut

    # -- worker -------------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stop:
                    self._cond.wait()
                if self._stop and not self._queue:
                    return
                head = self._queue[0]
                group = _batch.group_ready(self._queue, head.group_key,
                                           self.max_batch)
                # fill the batch: LOOP the wait (any submit's notify wakes
                # us), flushing only when the group is full or the oldest
                # request has genuinely waited out max_delay_ms
                fill_deadline = head.enqueue_t + self.max_delay_s
                while (len(group) < self.max_batch and not self._stop
                       and not self._draining):
                    left = fill_deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._cond.wait(timeout=left)
                    group = _batch.group_ready(self._queue, head.group_key,
                                               self.max_batch)
                for req in group:
                    self._queue.remove(req)
                self._inflight += len(group)
                self._batch_seq += 1
                batch_id = self._batch_seq
                self.metrics.set_gauge("queue_depth", len(self._queue))
            try:
                self._execute(group, batch_id)
            finally:
                with self._cond:
                    self._inflight -= len(group)
                    self._cond.notify_all()

    @staticmethod
    def _fail(req: _Request, exc: BaseException) -> None:
        """Deliver an exception, tolerating a future the caller cancelled
        or that already completed — a tenant's cancel() must never be able
        to kill the worker thread."""
        try:
            req.future.set_exception(exc)
        except InvalidStateError:
            pass

    def _state(self, req: _Request):
        if req.initial_state is None:
            st = jnp.zeros((2, 1 << req.num_qubits),
                           self.dtype).at[0, 0].set(1.0)
        else:
            st = jnp.asarray(req.initial_state, self.dtype)
        if self._sharding is not None:
            st = jax.device_put(st, self._sharding)
        return st

    def _execute(self, group: list, batch_id: int = 0) -> None:
        now = time.monotonic()
        live = []
        deadline_drops = 0
        for req in group:
            if req.deadline is not None and now > req.deadline:
                self.metrics.inc("deadline_expired_total")
                self.flight_recorder.resolve(req.rid, "deadline",
                                             batch_id=batch_id,
                                             wait_s=now - req.enqueue_t)
                self.slo.observe(req.class_key, now - req.enqueue_t,
                                 deadline_ok=False)
                deadline_drops += 1
                self._fail(req, QuESTError(
                    ErrorCode.DEADLINE_EXCEEDED,
                    MESSAGES[ErrorCode.DEADLINE_EXCEEDED], "submit"))
            elif not req.future.set_running_or_notify_cancel():
                self.flight_recorder.resolve(req.rid, "cancelled",
                                             batch_id=batch_id)
                continue        # caller cancelled before execution: drop
            else:
                live.append(req)
        if deadline_drops:
            # deadline expiry is as much a "something is wrong NOW" moment
            # as a queue bounce (the queue sat long enough to eat a tenant's
            # whole budget): dump the ring once on the first drop in a batch
            # so the post-mortem shows what the co-queued requests were
            # doing, without a storm of drops producing a storm of dumps
            self.flight_recorder.dump(ErrorCode.DEADLINE_EXCEEDED)
        if not live:
            return
        completed: set = set()
        try:
            with _obs.span("serve.execute_batch", batch=batch_id,
                           size=len(live)) as bsp:
                # one lookup PER REQUEST (not per group): the hit/miss
                # counters are the per-request serving economics — 64
                # same-class requests are 1 miss + 63 hits however they
                # happen to batch.  Each lookup runs under its request's
                # context so the cache's spans correlate, and reports its
                # hit/miss outcome through the notes channel.
                is_grad = live[0].grad    # group key includes the flag
                outcomes: dict = {}
                for req in live:
                    with _obs.request(req.rid), \
                            _obs.collect_notes() as notes:
                        if is_grad:
                            entry = self._cache.grad_entry_for(
                                req.ops, req.num_qubits,
                                req.grad_num_params, req.masks,
                                self._options)
                        else:
                            entry = self._cache.entry_for(req.ops,
                                                          req.num_qubits,
                                                          self._options)
                    outcomes[req.rid] = notes.get("cache_outcome", "miss")
                probed = live[0].probes   # group key includes the flag
                t0 = time.perf_counter()
                energies = grads = None
                if is_grad:
                    energies, grads, probe_vecs, padded = \
                        _batch.execute_grad_group(
                            self._cache, entry, live, self._state,
                            self.max_batch, mode=self.batch_mode,
                            probes=probed)
                    jax.block_until_ready(grads[-1])
                elif entry.skeleton is None:
                    # opaque overlapped class (PR 4): per-request programs.
                    # The program is opaque, so the probe runs as a
                    # separate pure reduction over the finished state —
                    # same values, one extra dispatch (documented in
                    # docs/OBSERVABILITY.md "Numeric health")
                    states = [self._cache.overlap_program(entry, req.ops)
                              .call(self._state(req)) for req in live]
                    padded = len(live)
                    probe_vecs = ([_numerics.state_probe_vector(st)
                                   for st in states] if probed else None)
                    jax.block_until_ready(states[-1])
                else:
                    states, probe_vecs, padded = _batch.execute_group(
                        self._cache, entry, live, self._state,
                        self.max_batch, mode=self.batch_mode, probes=probed,
                        density=live[0].density)   # group key includes it
                    jax.block_until_ready(states[-1])
                dt = time.perf_counter() - t0
                class_key = _obs.key_hash(entry.skey)
                parent = bsp.span_id if bsp is not None else None
            self.metrics.inc("batches_total")
            if is_grad:
                self.metrics.inc("grad_batches_total")
            self.metrics.observe("batch_size", len(live),
                                 buckets=BATCH_BUCKETS)
            self.metrics.observe("execute_seconds", dt)
            if padded > len(live):
                self.metrics.inc("padded_requests_total", padded - len(live))
            done_t = time.monotonic()
            nan_dumped = False
            # ONE device-to-host transfer for the whole batch's probe
            # vectors: per-row np.asarray in the loop below would issue
            # one D2H sync per request on the latency-critical path
            probe_host = (np.asarray(jnp.stack(probe_vecs))
                          if probed else None)
            for i, req in enumerate(live):
                st = grads[i] if is_grad else states[i]
                # the per-request execution span: the trace's link from a
                # request_id to what ran for it (class, engine, cache
                # outcome, batch) — the correlation contract
                # validate_chrome_trace enforces
                _obs.emit_span(
                    EXECUTION_SPAN, t0=t0, dur=dt, parent_id=parent,
                    request_id=req.rid, class_key=class_key,
                    engine=entry.options.engine, cache=outcomes[req.rid],
                    batch=batch_id, batch_size=len(live),
                    queue_wait_s=round(done_t - dt - req.enqueue_t, 6))
                health = None
                if probed:
                    # the numeric ledger judges the probe (NaN/Inf first,
                    # then drift vs the depth-derived ulp band) and keeps
                    # the per-class aggregation the scrape reports; the
                    # drift baseline was fixed at submit time (the
                    # request's own input norm).  Gradient probes read the
                    # ROUND-TRIPPED |psi> (forward + uncompute, so the
                    # band covers ~3x the op count) with backward-pass
                    # NaN/Inf folded in from the energy and gradient
                    depth = (3 * len(req.ops) + len(req.masks)
                             if is_grad else len(req.ops))
                    rec = self.numeric_ledger.record(
                        class_key, probe_host[i],
                        kind=("densmatr" if req.density is not None
                              else "statevec"),
                        engine=entry.options.engine, dtype=str(st.dtype),
                        num_qubits=(req.density if req.density is not None
                                    else req.num_qubits), num_ops=depth,
                        class_key=class_key,
                        expected_norm=req.expected_norm, warn=False)
                    health = rec.as_health()
                    self.metrics.inc("numeric_probed_total")
                    self.metrics.set_gauge(
                        "numeric_last_norm_drift",
                        rec.norm_drift if math.isfinite(rec.norm_drift)
                        else -1.0)
                    if rec.nan_count or rec.inf_count:
                        self.metrics.inc("numeric_nan_total")
                    if any(_numerics.NUMERIC_DRIFT in f
                           for f in rec.findings):
                        self.metrics.inc("numeric_drift_total")
                if is_grad:
                    result = GradResult(float(energies[i]), np.asarray(st),
                                        len(live), req.rid,
                                        outcomes[req.rid], health)
                    self.metrics.inc("grad_requests_completed_total")
                else:
                    samples = self._sample(st, req) if req.shots else None
                    result = ServeResult(np.asarray(st), samples,
                                         len(live), req.rid,
                                         outcomes[req.rid], health)
                try:
                    req.future.set_result(result)
                except InvalidStateError:
                    self.flight_recorder.resolve(req.rid, "cancelled",
                                                 batch_id=batch_id)
                    continue        # raced a cancel mid-execution
                # "ok" is recorded only once the result is DELIVERED, so a
                # later request's failure in this loop cannot be confused
                # with (or overwrite) a completed one
                completed.add(req.rid)
                self.flight_recorder.resolve(
                    req.rid, "ok", batch_id=batch_id,
                    wait_s=done_t - dt - req.enqueue_t, exec_s=dt,
                    numeric_health=health)
                if (health is not None and not nan_dumped
                        and (health["nan_count"] or health["inf_count"])):
                    # a poisoned register is as much a "something is wrong
                    # NOW" moment as a queue bounce: dump the ring ONCE on
                    # the first NaN/Inf outcome in a batch (after the
                    # resolve above, so the dump shows this record's
                    # numeric_health), not once per poisoned request
                    nan_dumped = True
                    self.flight_recorder.dump(_numerics.NUMERIC_NAN)
                self.metrics.inc("requests_completed_total")
                self.metrics.observe("request_latency_seconds",
                                     done_t - req.enqueue_t)
                # windowed SLO sample: deadline_ok=None when no deadline
                # was stated (latency tracked, no error budget consumed).
                # A deadline'd request only HITS if it completed IN TIME —
                # admission-time enforcement lets a request that was
                # admitted punctually still finish late, and counting that
                # as a hit would blind the burn-rate warning to exactly
                # the slow-execution incidents it exists for
                self.slo.observe(req.class_key, done_t - req.enqueue_t,
                                 deadline_ok=done_t <= req.deadline
                                 if req.deadline is not None else None)
        except Exception as exc:  # noqa: BLE001 — forwarded to the futures
            failed = 0
            fail_t = time.monotonic()
            for req in live:
                if req.rid in completed:
                    continue    # delivered before the failure: outcome ok
                failed += 1
                self.flight_recorder.resolve(
                    req.rid, f"error:{type(exc).__name__}",
                    batch_id=batch_id)
                if req.deadline is not None:
                    # a failed deadline'd request did not meet its
                    # objective: burn budget, or a crash-loop outage reads
                    # as a 1.0 hit rate while every request dies
                    self.slo.observe(req.class_key,
                                     fail_t - req.enqueue_t,
                                     deadline_ok=False)
                self._fail(req, exc)
            self.flight_recorder.dump(f"error:{type(exc).__name__}")
            self.metrics.inc("requests_failed_total", failed)

    def _sample(self, state, req: _Request) -> np.ndarray:
        """``req.shots`` joint outcomes over all qubits from the request's
        PRIVATE MT19937 stream seeded (service_seed, request_id): the same
        inverse-CDF draw as the API's sampleOutcomes, but isolated so
        batching order can never change any request's outcomes.  Density
        requests sample from rho's DIAGONAL (the outcome distribution of a
        mixed state — the NISQ-emulation serving scenario), negative
        rounding dust clipped to zero."""
        from ..ops import measure as _meas
        if req.density is not None:
            diag = np.asarray(_meas.densmatr_diagonal(
                jnp.asarray(state), req.density)[0], np.float64)
            probs = np.maximum(diag, 0.0)
        else:
            probs = np.asarray(_meas.prob_all_outcomes(
                state, tuple(range(req.num_qubits))))
        cdf = np.cumsum(probs)
        total = cdf[-1]
        if not np.isfinite(total) or total <= 0:
            raise ValueError(f"unnormalisable result state (sum {total})")
        gen = MT19937()
        gen.init_by_array([self.seed & _U32, req.rid & _U32])
        draws = gen.genrand_real1_batch(req.shots)
        outcomes = np.searchsorted(cdf, draws * total, side="right")
        last_pos = np.nonzero(probs > 0)[0][-1]
        self.metrics.inc("samples_drawn_total", req.shots)
        return np.minimum(outcomes, last_pos).astype(np.int64)

    # -- observability ------------------------------------------------------
    def queue_saturation(self) -> float:
        """LIVE queue fullness (depth / max_queue), read without the lock
        (a list ``len`` is atomic).  The SLO monitor's saturation is
        sampled at admissions, so a replica that traffic has already been
        routed AWAY from would report its last (high) sample forever; a
        router must read the live value to ever un-shed it."""
        # lock-free: atomic len() of an always-valid list (a torn read is off by at most one request)
        return len(self._queue) / self.max_queue

    def metrics_dict(self) -> dict:
        d = self.metrics.as_dict()
        d["cache"] = self._cache.snapshot()
        d["cache_hit_rate"] = d["cache"]["hit_rate"]
        d["obs"] = self._obs_gauges()
        d["slo"] = self.slo.snapshot()
        d["numeric"] = self.numeric_ledger.snapshot()
        d["numeric"]["by_class"] = self.numeric_ledger.by_class()
        return d

    def _obs_gauges(self) -> dict:
        """Tracing/ledger/flight-recorder counters spliced into the same
        registry as the service metrics: ONE Prometheus scrape covers the
        whole observability surface (docs/OBSERVABILITY.md)."""
        g = dict(_obs.obs_snapshot())
        g["flight_depth"] = len(self.flight_recorder.records())
        g["flight_dumps"] = self.flight_recorder.dumps
        return g

    def prometheus(self) -> str:
        cache = self._cache.snapshot()
        extra = {f"cache_{k}": v for k, v in cache.items()
                 if isinstance(v, (int, float))}
        extra.update({f"obs_{k}": v for k, v in self._obs_gauges().items()})
        extra.update({f"slo_{k}": v for k, v in self.slo.gauges().items()})
        # the numeric-health gauges of the ONE scrape (quest_serve_numeric_*):
        # ledger totals spliced point-in-time, next to the registry's
        # numeric_probed/nan/drift counters
        extra.update({f"numeric_ledger_{k}": v
                      for k, v in self.numeric_ledger.gauges().items()})
        return self.metrics.to_prometheus(extra_gauges=extra)
