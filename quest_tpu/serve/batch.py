"""Request aggregation: same-class microbatches executed as one vmap.

The serving win on top of the compile cache: once every request of a
structural class runs through ONE ``(state, params)`` program, requests that
arrive together can run as a SINGLE batched-over-params program — one
dispatch, one compiled executable for the whole group, instead of
per-request launches.  Two lowerings (cache.py ``batch_program``): the
default ``lax.map`` form whose per-element jaxpr is IDENTICAL to the
singleton program (batched results bit-identical to serial execution — the
serving contract), and a ``vmap`` form that vectorizes across the batch for
throughput at last-ulp f64 tolerance.  Initial states are broadcast when
every request starts from the shared |0..0> (the multi-tenant fast path) or
stacked when any request carries its own state.

Batch sizes are PADDED up to the next power of two (duplicating the last
request's operands; surplus rows are sliced off) so the number of distinct
compiled batch shapes per class is O(log max_batch), not O(max_batch) — a
ragged-size workload would otherwise recompile for every arrival count and
wreck the cache-hit economics the subsystem exists for.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

__all__ = ["bucket_size", "group_ready", "execute_group",
           "execute_grad_group"]


def bucket_size(m: int, max_batch: int) -> int:
    """Padded batch size: next power of two >= m, capped at max_batch."""
    b = 1
    while b < m:
        b <<= 1
    return min(b, max(1, max_batch))


def group_ready(queue, key, max_batch: int) -> list:
    """The next microbatch for ``key``: up to ``max_batch`` queued requests
    of the same group key, in arrival order (FIFO fairness within a class)."""
    out = []
    for req in queue:
        if req.group_key == key:
            out.append(req)
            if len(out) >= max_batch:
                break
    return out


def execute_group(cache, entry, requests, state_factory, max_batch: int,
                  mode: str = "map", probes: bool = False,
                  density: "int | None" = None):
    """Run one same-class microbatch; returns ``(states, probes, batch)``
    where ``states`` is a list of per-request (2, 2^n) device arrays in
    request order, ``probes`` the matching list of numeric probe vectors
    (``None`` when probing is off — obs/numerics.py), and ``batch`` the
    padded batch size executed (1 for the singleton fall-through).

    Singletons skip vmap entirely — a lone request runs the class's plain
    single program (no batch-shaped compile for a class that never
    batches).  Groups pad to :func:`bucket_size` and run broadcast or
    stacked depending on whether any request carries its own initial
    state.  ``probes=True`` routes through the probe-instrumented program
    variants (cache.py ``*_probed_program``): same lowering, one auxiliary
    probe output, primary outputs bit-identical.  ``density`` (the density
    qubit count of a Choi-doubled class) selects the density-probe twins —
    trace + Hermiticity instead of the statevector norm."""
    m = len(requests)
    assert m >= 1
    if m == 1:
        req = requests[0]
        state = state_factory(req)
        params = cache._check_params(entry, req.params)
        if probes:
            out, pv = cache.single_program(
                entry, state, probes=True, density=density).call(
                state, params)
            return [out], [pv], 1
        out = cache.single_program(entry, state).call(state, params)
        return [out], None, 1
    batch = bucket_size(m, max_batch)
    pvec = [np.asarray(r.params, np.float64).ravel() for r in requests]
    pvec += [pvec[-1]] * (batch - m)
    pb = jnp.asarray(np.stack(pvec))
    stacked = any(r.initial_state is not None for r in requests)
    if stacked:
        states = [state_factory(r) for r in requests]
        states += [states[-1]] * (batch - m)
        sb = jnp.stack(states)
        prog = cache.batch_program(entry, states[0], batch, stacked=True,
                                   mode=mode, probes=probes, density=density)
        outs = prog.call(sb, pb)
    else:
        state = state_factory(requests[0])
        prog = cache.batch_program(entry, state, batch, stacked=False,
                                   mode=mode, probes=probes, density=density)
        outs = prog.call(state, pb)
    if probes:
        outs, pvs = outs
        return [outs[i] for i in range(m)], [pvs[i] for i in range(m)], batch
    return [outs[i] for i in range(m)], None, batch


def execute_grad_group(cache, entry, requests, state_factory, max_batch: int,
                       mode: str = "map", probes: bool = False):
    """Gradient twin of :func:`execute_group`: run one same-class adjoint
    microbatch; returns ``(energies, grads, probes, batch)`` — per-request
    energy scalars and (P,) gradient rows in request order, the matching
    probe vectors (``None`` when probing is off), and the padded batch
    size executed.  Params AND term coefficients stack on axis 0 (one
    class = one mask shape, but tenants may weight terms differently);
    padding duplicates the last request's rows exactly like the forward
    path, and the default ``lax.map`` lowering keeps batched gradients
    bit-identical to the serial loop (the serving contract, satellite-
    pinned in tests/test_grad.py)."""
    m = len(requests)
    assert m >= 1
    if m == 1:
        req = requests[0]
        state = state_factory(req)
        params = jnp.asarray(np.asarray(req.params, np.float64).ravel())
        coeffs = jnp.asarray(np.asarray(req.coeffs, np.float64).ravel())
        out = cache.grad_single_program(entry, state, probes=probes).call(
            state, params, coeffs)
        if probes:
            e, g, pv = out
            return [e], [g], [pv], 1
        e, g = out
        return [e], [g], None, 1
    # lax.map needs >= 2 rows for the shared-body codegen contract (see
    # cache.grad_single_program); bucket_size already returns >= 2 here
    batch = bucket_size(m, max_batch)
    pvec = [np.asarray(r.params, np.float64).ravel() for r in requests]
    cvec = [np.asarray(r.coeffs, np.float64).ravel() for r in requests]
    pvec += [pvec[-1]] * (batch - m)
    cvec += [cvec[-1]] * (batch - m)
    pb = jnp.asarray(np.stack(pvec))
    cb = jnp.asarray(np.stack(cvec))
    stacked = any(r.initial_state is not None for r in requests)
    if stacked:
        states = [state_factory(r) for r in requests]
        states += [states[-1]] * (batch - m)
        sb = jnp.stack(states)
        prog = cache.grad_batch_program(entry, states[0], batch,
                                        stacked=True, mode=mode,
                                        probes=probes)
        outs = prog.call(sb, pb, cb)
    else:
        state = state_factory(requests[0])
        prog = cache.grad_batch_program(entry, state, batch, stacked=False,
                                        mode=mode, probes=probes)
        outs = prog.call(state, pb, cb)
    energies, grads = outs[0], outs[1]
    out_e = [energies[i] for i in range(m)]
    out_g = [grads[i] for i in range(m)]
    if probes:
        return out_e, out_g, [outs[2][i] for i in range(m)], batch
    return out_e, out_g, None, batch
