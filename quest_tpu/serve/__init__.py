"""quest_tpu.serve — batched multi-tenant circuit-execution service.

The production front door the ROADMAP's north star asks for: a bounded-queue
service (:class:`QuESTService`) that canonicalizes each submitted circuit to
its structural class, compiles ONE parameter-lifted XLA program per class
(cache.py), aggregates same-class requests into vmapped microbatches
(batch.py), enforces deadlines and backpressure (service.py), and exports
metrics as a dict and Prometheus text (metrics.py).

``python -m quest_tpu.serve --selftest`` runs a synthetic multi-tenant
workload and prints the metrics (the CI gate); see docs/SERVING.md.
"""

from .cache import (CacheOptions, CompileCache, circuit_from_params,  # noqa: F401
                    global_cache)
from .metrics import Metrics, parse_prometheus  # noqa: F401
from .service import GradResult, QuESTService, ServeResult  # noqa: F401

__all__ = ["QuESTService", "ServeResult", "GradResult", "CompileCache",
           "CacheOptions", "global_cache", "circuit_from_params", "Metrics",
           "parse_prometheus"]
