"""Synthetic multi-tenant serving workload: the serve subsystem's selftest.

Drives :class:`QuESTService` with the traffic shape the subsystem exists
for — many tenants, few structural classes: a 10q VQE ansatz under 40
different angle assignments (the parameter-lifted cache's headline case),
a repeated 8q QFT (identical payloads, pure cache hits), a sampled 6q
random-circuit class exercising per-request RNG streams, and — when the
backend exposes an 8-device mesh — a 12q QFT class served through the PR 2
comm-aware scheduler.  Checks results bit-identically against the eager
per-circuit oracle, pins the cache hit rate, and proves the Prometheus
export well-formed.

This is the CI gate (``python -m quest_tpu.serve --selftest``; ci.yml
``serve-selftest`` job) and the default workload of the serve audit
(``python -m quest_tpu.analysis --serve-audit``, analysis/serve_audit.py).
"""

from __future__ import annotations

import json
import math

import numpy as np

__all__ = ["vqe_ansatz", "workload_classes", "audit_circuits",
           "run_selftest"]

_SEED = 7


def vqe_ansatz(num_qubits: int, layers: int, seed: int):
    """Rotation + entangler ansatz: per-layer ry wall, CNOT ladder, rz wall
    — the compactUnitary/rotation shape of the reference's hot path
    (QuEST_common.c) with every angle a liftable operand."""
    from ..circuit import Circuit
    rng = np.random.default_rng(seed)
    c = Circuit(num_qubits)
    for layer in range(layers):
        for q in range(num_qubits):
            c.ry(q, float(rng.uniform(-math.pi, math.pi)))
        for q in range(layer % 2, num_qubits - 1, 2):
            c.cnot(q, q + 1)
        for q in range(num_qubits):
            c.rz(q, float(rng.uniform(-math.pi, math.pi)))
    return c


def workload_classes(scale: int = 1) -> list:
    """The synthetic tenant mix: ``(label, [circuits], shots)`` per
    structural class.  ``scale`` multiplies request counts."""
    from ..circuit import qft_circuit, random_circuit
    return [
        ("vqe10", [vqe_ansatz(10, 2, seed=s) for s in range(40 * scale)], 0),
        ("qft8", [qft_circuit(8) for _ in range(10 * scale)], 0),
        ("random6_sampled",
         [random_circuit(6, depth=2, seed=s) for s in range(14 * scale)], 64),
    ]


def audit_circuits() -> list:
    """One representative + one angle-perturbed twin per structural class
    (the serve audit's default workload)."""
    from ..circuit import qft_circuit, random_circuit
    return [
        ("vqe10", vqe_ansatz(10, 2, seed=0), vqe_ansatz(10, 2, seed=1)),
        ("qft8", qft_circuit(8), qft_circuit(8)),
        ("random6", random_circuit(6, depth=2, seed=0),
         random_circuit(6, depth=2, seed=1)),
    ]


def _check(checks: dict, name: str, ok: bool, detail: str = "") -> bool:
    checks[name] = {"ok": bool(ok), "detail": detail}
    return bool(ok)


def _expected_samples(state, seed: int, request_id: int,
                      shots: int) -> np.ndarray:
    """The per-request sampling oracle: what QuESTService._sample must
    have drawn for this (service seed, request id) — ONE definition so
    the forward and gradient selftest phases can never drift from each
    other on the recipe."""
    import jax.numpy as jnp

    from ..ops import measure as _meas
    from ..rng import MT19937

    n = int(np.log2(np.asarray(state).shape[-1]))
    probs = np.asarray(_meas.prob_all_outcomes(jnp.asarray(state),
                                               tuple(range(n))))
    cdf = np.cumsum(probs)
    gen = MT19937()
    gen.init_by_array([seed, request_id])
    draws = gen.genrand_real1_batch(shots)
    expect = np.searchsorted(cdf, draws * cdf[-1], side="right")
    return np.minimum(expect,
                      np.nonzero(probs > 0)[0][-1]).astype(np.int64)


def _run_gradient_phase(checks: dict, echo) -> tuple:
    """The gradient workload phase (``--gradients``; ci.yml
    ``grad-selftest``): a mixed forward+gradient storm through ONE
    service — 32 same-ansatz different-angle ``submit_gradient`` requests
    (quest_tpu/grad) interleaved with 16 sampled forward requests — then
    the gates:

    - ``grad_bit_identity``: every batched gradient result is
      BIT-IDENTICAL to the class's serial program on the same operands;
    - ``grad_forward_isolation``: the interleaved forward requests stay
      bit-identical to serial execution AND their per-request MT19937
      sample streams match the oracle — gradient traffic on the same
      service must not perturb forward batching or RNG isolation;
    - ``grad_oracle``: energies/gradients agree with
      ``jax.value_and_grad(expectation_fn(...))`` (taped reverse-mode
      through an independent program);
    - ``grad_hit_rate``: >= 0.9 over the phase's fresh cache (1 gradient
      class + 1 forward class across 48 requests);
    - ``grad_nan_trips``: a probed request whose Hamiltonian carries a
      NaN coefficient (the backward pass' adjoint state is poisoned; the
      forward |psi> round-trips clean) records ``O_NUMERIC_NAN`` on the
      ledger, attaches it to the result and dumps the flight ring;
    - ``grad_nan_quarantine``: on a 2-replica probed deployment, two
      consecutive NaN gradient outcomes quarantine the (class, replica)
      placement (deploy/router.py ``report_numeric``).

    Returns ``(ok, doc_block)``."""
    import jax
    import jax.numpy as jnp

    from ..autodiff import expectation_fn
    from ..grad import adjoint as _gradadj
    from ..models import hardware_efficient_ansatz, tfim_hamiltonian
    from ..obs import numerics as _num
    from .cache import CompileCache
    from .service import QuESTService

    ok = True
    n = 8
    cache = CompileCache()
    ledger = _num.NumericLedger()
    svc = QuESTService(max_batch=16, max_delay_ms=10, seed=_SEED,
                       cache=cache, numeric_ledger=ledger, start=False)
    pc = hardware_efficient_ansatz(n, 2)
    hamil = tfim_hamiltonian(n)
    rng = np.random.default_rng(_SEED)
    grad_params = [rng.uniform(-np.pi, np.pi, pc.num_params)
                   for _ in range(32)]
    fwd_circuits = [vqe_ansatz(n, 1, seed=s) for s in range(16)]
    grad_futs, fwd_futs = [], []
    for i in range(32):
        grad_futs.append(svc.submit_gradient(pc, grad_params[i], hamil))
        if i < len(fwd_circuits):
            fwd_futs.append(svc.submit(fwd_circuits[i], shots=32))
    svc.start()
    ok &= _check(checks, "grad_drain", svc.drain(timeout=900),
                 "mixed forward+gradient storm drained")
    grads = [f.result(timeout=120) for f in grad_futs]
    fwds = [f.result(timeout=120) for f in fwd_futs]
    batch_sizes = sorted({g.batch_size for g in grads})

    # batched == serial, bitwise (the gradient serving contract)
    masks = _gradadj.hamil_masks(hamil)
    entry = cache.grad_entry_for(tuple(pc.ops), n, pc.num_params, masks)
    st = jnp.zeros((2, 1 << n), jnp.float64).at[0, 0].set(1.0)
    cf = jnp.asarray(np.asarray(hamil.term_coeffs, np.float64))
    serial = cache.grad_single_program(entry, st)
    exact = True
    for p, res in zip(grad_params, grads):
        e, g = serial.call(st, jnp.asarray(p), cf)
        if float(e) != res.energy or not np.array_equal(np.asarray(g),
                                                        res.gradient):
            exact = False
            echo(f"FAIL gradient request {res.request_id}: batched "
                 "(energy, grad) != serial program")
    ok &= _check(checks, "grad_bit_identity", exact,
                 f"32 gradients, batch sizes {batch_sizes}")

    # interleaved forward requests: bit-identity + RNG isolation
    fwd_ok = True
    for circuit, res in zip(fwd_circuits, fwds):
        want = np.asarray(cache.execute(circuit.key(), st,
                                        num_qubits=n))
        if not np.array_equal(res.state, want):
            fwd_ok = False
            echo("FAIL interleaved forward request: state != serial")
        if not np.array_equal(res.samples,
                              _expected_samples(want, _SEED,
                                                res.request_id, 32)):
            fwd_ok = False
            echo("FAIL interleaved forward request: sample stream diverged")
    ok &= _check(checks, "grad_forward_isolation", fwd_ok,
                 f"{len(fwds)} sampled forward requests interleaved")

    # independent taped-AD oracle on a few requests
    oracle = jax.jit(jax.value_and_grad(expectation_fn(pc, hamil)))
    worst = 0.0
    for p, res in list(zip(grad_params, grads))[:4]:
        v, g = oracle(jnp.asarray(p))
        worst = max(worst, abs(float(v) - res.energy),
                    float(np.abs(res.gradient - np.asarray(g)).max()))
    ok &= _check(checks, "grad_oracle", worst < 1e-9,
                 f"max |adjoint - jax.grad| = {worst:.3g}")

    snap = cache.snapshot()
    ok &= _check(checks, "grad_hit_rate", snap["hit_rate"] >= 0.9,
                 f"hit rate {snap['hit_rate']:.3f} over "
                 f"{snap['hits'] + snap['misses']} lookups "
                 f"({snap['compiles']} compiles)")

    # probed NaN injection: a NaN term coefficient poisons the ADJOINT
    # state (lam = H|psi>), not the forward register — exactly the
    # backward-pass corruption the probe's grad/energy fold exists for
    dumps_before = svc.flight_recorder.dumps
    bad = tfim_hamiltonian(n)
    bad.term_coeffs[0] = float("nan")
    nan_res = svc.submit_gradient(pc, grad_params[0], bad,
                                  probes=True).result(timeout=300)
    led = ledger.snapshot()
    nan_ok = (nan_res.numeric_health is not None
              and nan_res.numeric_health["nan_count"] > 0
              and any(_num.NUMERIC_NAN in f
                      for f in nan_res.numeric_health["findings"])
              and led["nan_total"] >= 1
              and svc.flight_recorder.dumps > dumps_before)
    ok &= _check(checks, "grad_nan_trips", nan_ok,
                 f"nan_count {nan_res.numeric_health['nan_count']}, ledger "
                 f"nan_total {led['nan_total']}, flight dumps "
                 f"{svc.flight_recorder.dumps - dumps_before}")
    svc.shutdown()

    # router quarantine on a probed 2-replica deployment (small class so
    # the probed program compile stays cheap)
    from ..deploy import ReplicaPool, RouterConfig
    pc4 = hardware_efficient_ansatz(4, 1)
    h4 = tfim_hamiltonian(4)
    bad4 = tfim_hamiltonian(4)
    bad4.term_coeffs[0] = float("nan")
    p4 = rng.uniform(-1, 1, pc4.num_params)
    pool = ReplicaPool(num_replicas=2, probes=True, max_delay_ms=0,
                       seed=_SEED,
                       router_config=RouterConfig(quarantine_nans=2))
    with pool:
        for _ in range(2):   # two CONSECUTIVE NaN outcomes on one class
            pool.submit_gradient(pc4, p4, bad4).result(timeout=300)
        quarantined = list(pool.router.snapshot()["quarantined"])
        # the clean gradient class still serves while the pair sits out
        clean = pool.submit_gradient(pc4, p4, h4).result(timeout=300)
    ok &= _check(checks, "grad_nan_quarantine",
                 len(quarantined) >= 1 and clean.numeric_health is not None
                 and not clean.numeric_health["findings"],
                 f"{len(quarantined)} quarantined placement(s); clean class "
                 "served clean")

    doc = {"requests": {"gradient": len(grads), "forward": len(fwds)},
           "batch_sizes": batch_sizes,
           "cache": snap,
           "oracle_max_abs_diff": worst,
           "nan_injection": {"health": nan_res.numeric_health,
                             "ledger": led},
           "quarantine": quarantined,
           "ledger": ledger.snapshot()}
    return ok, doc


def _run_density_phase(checks: dict, echo) -> tuple:
    """The noisy density-matrix workload phase (``--density``; ci.yml
    ``numeric-selftest``): a probed 24-request probability sweep of ONE
    noisy structural class — same skeleton (mirrored Haar layer + damping
    + depolarising + dephasing on a 6-qubit density register), per-tenant
    channel probabilities — through a fresh service, then the gates:

    - ``density_hit_rate``: >= 0.9 — probabilities live in the operand
      vector, so the whole sweep is ONE compiled class;
    - ``density_bit_identity``: every batched result equals the serial
      ``_run_ops`` execution of its own doubled circuit, bitwise;
    - ``density_health``: every result carries a clean ``densmatr``
      numeric_health record — trace within the ulp band of 1, Hermiticity
      deviation within the band, zero findings;
    - ``density_plan_fused``: the class's epoch plan (the TPU lowering of
      the same op tuple) carries fused superoperator passes and ZERO
      XLA-fallback ops;
    - ``density_kraus_rejected``: a params override carrying a
      non-trace-preserving channel slice bounces with
      ``E_INVALID_KRAUS_OPS`` at admission.

    Returns ``(ok, doc_block)``."""
    import jax.numpy as jnp

    from ..circuit import (DensityCircuit, _run_ops, op_param_count,
                           param_vector)
    from ..obs import numerics as _num
    from ..ops import epoch_pallas as _ep
    from ..validation import ErrorCode, QuESTError
    from .cache import CompileCache
    from .service import QuESTService

    ok = True
    n = 6
    rng = np.random.default_rng(_SEED)

    def haar() -> np.ndarray:
        g = rng.normal(size=(2, 2)) + 1j * rng.normal(size=(2, 2))
        u, r = np.linalg.qr(g)
        return u * (np.diag(r) / np.abs(np.diag(r)))

    gates = [haar() for _ in range(n)]

    def noisy(p_damp: float, p_depol: float, p_deph: float) -> DensityCircuit:
        dc = DensityCircuit(n)
        for q in range(n):
            dc.unitary(q, gates[q])
        for q in range(0, n, 2):
            dc.damp(q, p_damp)
        for q in range(1, n, 2):
            dc.depolarise(q, p_depol)
        dc.dephase(0, p_deph)
        return dc

    cache = CompileCache()
    ledger = _num.NumericLedger()
    svc = QuESTService(max_batch=8, max_delay_ms=10, seed=_SEED,
                       cache=cache, numeric_ledger=ledger, probes=True,
                       start=False)
    sweep = [(0.002 * i, 0.003 * i, 0.004 * i) for i in range(1, 25)]
    circuits = [noisy(*p) for p in sweep]
    futs = [svc.submit(c, shots=16) for c in circuits]
    svc.start()
    ok &= _check(checks, "density_drain", svc.drain(timeout=900),
                 "24-request probability sweep drained")
    results = [f.result(timeout=120) for f in futs]

    snap = cache.snapshot()
    ok &= _check(checks, "density_hit_rate", snap["hit_rate"] >= 0.9,
                 f"hit rate {snap['hit_rate']:.3f} over "
                 f"{snap['hits'] + snap['misses']} lookups "
                 f"({snap['compiles']} compiles — 1 noisy class across "
                 "the sweep)")

    exact = True
    st = jnp.zeros((2, 1 << (2 * n)), jnp.float64).at[0, 0].set(1.0)
    for c, r in zip(circuits, results):
        if not np.array_equal(np.asarray(_run_ops(st, c.key())), r.state):
            exact = False
            echo(f"FAIL density request {r.request_id}: batched state "
                 "!= serial doubled-circuit execution")
    ok &= _check(checks, "density_bit_identity", exact,
                 f"{len(results)} probed results vs serial execution")

    healths = [r.numeric_health for r in results
               if r.numeric_health is not None]
    healthy = (len(healths) == len(results)
               and all(h["kind"] == "densmatr" and not h["findings"]
                       for h in healths))
    # guard the aggregates: a probe regression (missing health record)
    # must FAIL the check below, not crash the selftest before its JSON
    worst_tr = max((abs(h["norm"] - 1.0) for h in healths),
                   default=float("nan"))
    worst_h = max((h["herm_dev"] for h in healths), default=float("nan"))
    ok &= _check(checks, "density_health", healthy,
                 f"max |trace - 1| = {worst_tr:.3g}, max herm_dev = "
                 f"{worst_h:.3g}, zero findings")

    # zero XLA fallbacks, the whole noisy window in <= 2 fused passes,
    # and the cross-group channels as superoperator stages (channels whose
    # doubled pair happens to land inside ONE axis group lower as plain
    # dense stages — equally fused, just not counted here)
    plan = _ep.plan_circuit(circuits[0].key(), 2 * n)
    ok &= _check(checks, "density_plan_fused",
                 plan.xla_ops == 0 and plan.super_stages >= 3
                 and plan.pallas_passes <= 2,
                 f"{plan.pallas_passes} fused pass(es), "
                 f"{plan.super_stages} superoperator stage(s), "
                 f"{plan.xla_ops} XLA fallback op(s)")

    bad = param_vector(circuits[0].ops).copy()
    off = 0
    for i, op in enumerate(circuits[0].ops):
        if i in circuits[0].channel_slots and op.kind == "matrix":
            bad[off] = 7.0      # breaks trace preservation of the slice
            break
        off += op_param_count(op)
    rejected = False
    try:
        svc.submit(circuits[0], params=bad)
    except QuESTError as e:
        rejected = e.code == ErrorCode.INVALID_KRAUS_OPS
    ok &= _check(checks, "density_kraus_rejected", rejected,
                 "non-trace-preserving operand slice bounced with "
                 "E_INVALID_KRAUS_OPS")
    svc.shutdown()

    doc = {"requests": len(results), "cache": snap,
           "plan": plan.summary(),
           "max_trace_drift": worst_tr, "max_herm_dev": worst_h,
           "ledger": ledger.snapshot()}
    return ok, doc


def run_selftest(as_json: bool = False, scale: int = 1,
                 trace: bool | None = None,
                 probes: bool | None = None,
                 gradients: bool | None = None,
                 density: bool | None = None) -> int:
    """Run the workload through fresh services sharing one fresh cache;
    print metrics (human text, or ONE JSON document with ``--json``).
    Returns the process exit status: 0 iff every check passed.

    ``trace=True`` (or ``QUEST_TPU_TRACE=1``) records the whole run through
    the span recorder (quest_tpu/obs): the JSON document then carries the
    exported Chrome-trace under ``"trace"`` — produced through the
    CROSS-PROCESS merge path (obs/aggregate.py: this process's shard,
    merged; the degenerate single-process merge is the identity, so the
    document is byte-equal to the direct export while exercising the
    multi-host pipeline CI gates on) — and a ``trace_valid`` check gates
    the extended export schema: every execution span linked to its
    request_id with class key / engine / cache outcome, zero orphans
    across processes (the ci.yml ``obs-selftest`` contract).  The
    flight-recorder ring (``"flight_recorder"``) and the windowed SLO view
    (``"slo"``: per-class latency, deadline hit rate + burn rate, queue
    saturation — obs/slo.py) are included unconditionally — both are
    always on.

    ``probes=True`` (or ``QUEST_TPU_NUMERIC_PROBES=1``) serves the whole
    workload through the probe-instrumented program variants
    (obs/numerics.py): the document grows a ``"numeric"`` block (ledger
    totals, per-class aggregation, the injected-corruption trip) and three
    checks — ``numeric_clean`` (zero NaN/drift findings on the clean
    workload), ``numeric_attached`` (every result carries its
    numeric_health record) and ``numeric_corruption_trips`` (each planted
    corruption trips the ledger) — the ci.yml ``numeric-selftest``
    contract.  The existing bit-identity check doubles as the
    instrumented-vs-uninstrumented proof: probed results are compared
    against the UNPROBED serial oracle.

    ``gradients=True`` (or ``QUEST_TPU_GRAD_SELFTEST=1``) additionally
    runs the gradient workload phase (:func:`_run_gradient_phase`; the
    ci.yml ``grad-selftest`` contract): a mixed forward+gradient storm
    with bit-identity, forward-isolation, oracle, hit-rate, NaN-trip and
    router-quarantine gates, reported under the document's
    ``"gradient"`` block.

    ``density=True`` (or ``QUEST_TPU_DENSITY_SELFTEST=1``) additionally
    runs the noisy density-matrix phase (:func:`_run_density_phase`; part
    of the ci.yml ``numeric-selftest`` contract): a probed
    probability-sweep storm of ONE noisy structural class with hit-rate,
    bit-identity, trace/Hermiticity-health, fused-superoperator-plan and
    Kraus-admission gates, reported under ``"density"``."""
    import os

    import jax
    import jax.numpy as jnp

    from .. import obs as _obs
    from ..circuit import _run_ops
    from .cache import CompileCache
    from .metrics import parse_prometheus
    from .service import QuESTService

    def echo(line: str) -> None:
        if not as_json:
            print(line)

    if trace is None:
        trace = os.environ.get("QUEST_TPU_TRACE") == "1"
    if trace:
        _obs.enable_tracing()
        _obs.reset_tracing()
    if probes is None:
        probes = os.environ.get("QUEST_TPU_NUMERIC_PROBES") == "1"
    if gradients is None:
        gradients = os.environ.get("QUEST_TPU_GRAD_SELFTEST") == "1"
    if density is None:
        density = os.environ.get("QUEST_TPU_DENSITY_SELFTEST") == "1"

    from ..obs import numerics as _num
    numeric_ledger = _num.NumericLedger() if probes else None

    cache = CompileCache()
    checks: dict = {}
    ok = True

    from ..obs.slo import SLOConfig
    # a wide SLO window: the correctness verification below (mesh class,
    # serial + eager oracles) runs for minutes on a slow CI host, and the
    # windowed per-class view must still hold the workload's samples when
    # the slo_clean gate reads it
    svc = QuESTService(max_batch=16, max_delay_ms=10, seed=_SEED,
                       cache=cache, slo=SLOConfig(window_s=3600.0),
                       probes=probes, numeric_ledger=numeric_ledger,
                       start=False)
    submitted = []  # (label, circuit, shots, future)
    classes = workload_classes(scale)
    # interleave classes round-robin: the aggregator must re-group them.
    # The qft8 class carries a (generous) deadline so the SLO monitor's
    # deadline-hit-rate / burn-rate path is exercised by the gate, not
    # just the no-objective latency path.
    longest = max(len(cs) for _, cs, _ in classes)
    for i in range(longest):
        for label, circuits, shots in classes:
            if i < len(circuits):
                deadline = 600_000.0 if label == "qft8" else None
                submitted.append((label, circuits[i], shots,
                                  svc.submit(circuits[i], shots=shots,
                                             deadline_ms=deadline)))
    svc.start()
    drained = svc.drain(timeout=600)
    ok &= _check(checks, "drain", drained, "queue drained within timeout")
    # snapshot the SLO view NOW, while the drained workload is fresh in
    # the window; this one snapshot is the document's "slo" block
    slo = svc.slo.snapshot()

    # mesh class through the PR 2 scheduler (composition proof)
    mesh_pair = None
    if len(jax.devices()) >= 8:
        from ..circuit import qft_circuit
        svc_mesh = QuESTService(num_devices=8, max_batch=8, max_delay_ms=10,
                                seed=_SEED, cache=cache, probes=probes,
                                numeric_ledger=numeric_ledger, start=False)
        mesh_circ = qft_circuit(12)
        mesh_futs = [svc_mesh.submit(qft_circuit(12)) for _ in range(8)]
        svc_mesh.start()
        ok &= _check(checks, "mesh_drain", svc_mesh.drain(timeout=600))
        mesh_pair = (mesh_circ, mesh_futs)
        svc_mesh.shutdown()

    # correctness, two contracts per class (docs/SERVING.md "numerics"):
    # (1) the batched result is BIT-IDENTICAL to serial per-circuit
    #     execution — batching must never change a tenant's answer;
    # (2) it agrees with the constant-embedded eager program to a couple of
    #     f64 ulps (the two compilations may legally differ in FMA
    #     contraction; exact equivalence is machine-proven by
    #     `python -m quest_tpu.analysis --serve-audit`)
    seen: set = set()
    exact = True
    worst_ulp = 0.0
    n_checked = 0
    for label, circuit, shots, fut in submitted:
        if label in seen:
            continue
        seen.add(label)
        res = fut.result(timeout=60)
        st = jnp.zeros((2, 1 << circuit.num_qubits),
                       jnp.float64).at[0, 0].set(1.0)
        serial = np.asarray(cache.execute(circuit.key(), st,
                                          num_qubits=circuit.num_qubits))
        if not np.array_equal(res.state, serial):
            exact = False
            echo(f"FAIL {label}: batched state != serial execution "
                 f"(max |diff| {np.abs(res.state - serial).max():.3g})")
        eager = np.asarray(_run_ops(st, circuit.key()))
        worst_ulp = max(worst_ulp, float(np.abs(res.state - eager).max()))
        n_checked += 1
        if shots:
            if not np.array_equal(res.samples,
                                  _expected_samples(serial, _SEED,
                                                    res.request_id, shots)):
                exact = False
                echo(f"FAIL {label}: sample stream diverged from the "
                     "per-request MT19937 oracle")
    ok &= _check(checks, "results_bit_identical_to_serial", exact,
                 f"{n_checked} classes checked")
    ok &= _check(checks, "results_near_eager_oracle", worst_ulp < 1e-14,
                 f"max |served - eager| = {worst_ulp:.3g}")

    if mesh_pair is not None:
        circ, futs = mesh_pair
        st = jnp.zeros((2, 1 << circ.num_qubits),
                       jnp.float64).at[0, 0].set(1.0)
        want = np.asarray(_run_ops(st, circ.key()))
        worst = max(float(np.abs(f.result(timeout=60).state - want).max())
                    for f in futs)
        ok &= _check(checks, "mesh_results", worst < 1e-10,
                     f"scheduled x8 class max |diff| {worst:.3g}")

    # every future resolved successfully
    failed = sum(1 for _, _, _, f in submitted if f.exception() is not None)
    ok &= _check(checks, "no_failures", failed == 0,
                 f"{failed} failed futures of {len(submitted)}")

    snap = cache.snapshot()
    hit_rate = snap["hit_rate"]
    ok &= _check(checks, "cache_hit_rate", hit_rate >= 0.9,
                 f"hit rate {hit_rate:.3f} over {snap['hits'] + snap['misses']}"
                 f" lookups ({snap['compiles']} compiles)")

    prom = svc.prometheus()
    try:
        parsed = parse_prometheus(prom)
        ok &= _check(checks, "prometheus_parses", True,
                     f"{len(parsed)} metric families")
    except ValueError as exc:
        ok &= _check(checks, "prometheus_parses", False, str(exc))

    metrics = svc.metrics_dict()
    # ONE snapshot serves both homes (metrics_dict re-snapshots on every
    # call; two point-in-time copies in one document would just invite
    # diff-hunting between them)
    metrics["slo"] = slo
    flight = svc.flight_recorder.snapshot()

    # the windowed SLO view (obs/slo.py): the default workload must show a
    # clean objective — every deadline'd request met it (the qft8 class
    # carried one), zero budget burn, no O_SLO_BURN warnings
    ok &= _check(checks, "slo_clean",
                 slo["deadline"]["hit_rate"] == 1.0
                 and slo["deadline"]["burn_rate"] == 0.0
                 and slo["deadline"]["hits_total"] > 0
                 and not slo["warnings"] and slo["classes"],
                 f"hit rate {slo['deadline']['hit_rate']:.3f} over "
                 f"{slo['deadline']['hits_total']} deadline'd request(s), "
                 f"burn {slo['deadline']['burn_rate']:.2f}, "
                 f"{len(slo['classes'])} windowed class(es), "
                 f"{len(slo['warnings'])} warning(s)")

    numeric_doc = None
    if probes:
        # the numeric-health gate (obs/numerics.py; ci.yml
        # numeric-selftest): the CLEAN workload must record zero NaN and
        # zero drift findings with every result carrying its
        # numeric_health record — and the ledger must provably be able to
        # fail: each injected corruption (scaled state, NaN-poisoned
        # amplitude, non-Hermitian density perturbation) trips it on a
        # throwaway ledger (the PR 3/12 mutation-harness pattern)
        snap_n = numeric_ledger.snapshot()
        ok &= _check(checks, "numeric_clean",
                     snap_n["nan_total"] == 0 and snap_n["drift_total"] == 0
                     and snap_n["probed_total"] >= len(submitted),
                     f"{snap_n['probed_total']} probed request(s), "
                     f"{snap_n['nan_total']} NaN, {snap_n['drift_total']} "
                     "drift finding(s)")
        attached = [f.result(timeout=60).numeric_health
                    for _, _, _, f in submitted
                    if f.exception() is None]
        ok &= _check(checks, "numeric_attached",
                     len(attached) == len(submitted)
                     and all(h is not None and not h["findings"]
                             for h in attached),
                     f"{sum(h is not None for h in attached)} of "
                     f"{len(submitted)} results carry a clean "
                     "numeric_health record")
        trip = _num.corruption_selftest()
        ok &= _check(checks, "numeric_corruption_trips", trip["ok"],
                     json.dumps(trip["trips"]))
        numeric_doc = {"ledger": snap_n,
                       "by_class": numeric_ledger.by_class(),
                       "corruption": trip}

    gradient_doc = None
    if gradients:
        g_ok, gradient_doc = _run_gradient_phase(checks, echo)
        ok &= g_ok

    density_doc = None
    if density:
        d_ok, density_doc = _run_density_phase(checks, echo)
        ok &= d_ok

    trace_doc = None
    if trace:
        # export THROUGH the cross-process merge (obs/aggregate.py): the
        # single-process degenerate merge is the identity, asserted here,
        # so the CI gate exercises the multi-host path on every run
        direct = _obs.chrome_trace()
        trace_doc = _obs.merge_shards([_obs.process_shard()])
        ok &= _check(checks, "trace_merge_identity",
                     json.dumps(trace_doc, default=float)
                     == json.dumps(direct, default=float),
                     "single-process merged trace byte-equals the direct "
                     "export")
        problems = _obs.validate_chrome_trace(trace_doc)
        exec_spans = [e for e in trace_doc["traceEvents"]
                      if e.get("name") == "serve.request"]
        want = len(submitted)
        ok &= _check(checks, "trace_valid",
                     not problems and len(exec_spans) >= want,
                     f"{len(exec_spans)} execution span(s) (need >= {want}),"
                     f" {len(problems)} schema problem(s)"
                     + (f"; first: {problems[0]}" if problems else ""))
    svc.shutdown()
    if as_json:
        doc = {"ok": bool(ok), "checks": checks, "metrics": metrics,
               "prometheus": prom, "flight_recorder": flight, "slo": slo}
        if numeric_doc is not None:
            doc["numeric"] = numeric_doc
        if gradient_doc is not None:
            doc["gradient"] = gradient_doc
        if density_doc is not None:
            doc["density"] = density_doc
        if trace_doc is not None:
            doc["trace"] = trace_doc
        print(json.dumps(doc, default=float))
    else:
        for name, r in checks.items():
            echo(f"[{'ok' if r['ok'] else 'FAIL'}] {name}: {r['detail']}")
        echo("--- metrics ---")
        echo(json.dumps(metrics, indent=1, default=float))
        echo("--- prometheus ---")
        echo(prom)
        if trace:
            echo("--- trace ---")
            echo(_obs.trace_report())
    return 0 if ok else 1
