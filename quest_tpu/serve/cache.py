"""Parameter-lifted compilation cache: ONE XLA program per structural class.

The defect this fixes, at its root: ``compile_circuit`` keyed programs on
``circuit.key()``, which embeds every gate payload — so a million users
running the SAME VQE ansatz with different rotation angles meant a million
identical-shape XLA compiles (the reference's own hot path has the same
character: ``compactUnitary``/rotation decompositions in QuEST_common.c are
angle-parameterized gates whose angles are runtime data, not program
structure).  Here a circuit is canonicalized to its STRUCTURAL key
(``Circuit.key(structural=True)``: op kinds, wires, arities, mesh/schedule
options — continuous payloads lifted out into a flat float64 operand vector,
``circuit.param_vector``), and ONE donating jitted ``(state, params)``
program is compiled per structural class.  Each request then supplies its
angles as a runtime operand — a cache hit costs an operand-vector build, not
an XLA compile.

Scheduled classes (``num_devices > 1``) compose with the PR 2 scheduler: the
class REPRESENTATIVE is scheduled once and the scheduled op order is recorded
as a skeleton whose per-op operand slots point back into the ORIGINAL op
order (payload provenance survives the scheduler because reordering and
placement relabeling preserve payload tuples, scheduler.py ``_relabel_op``) —
so later requests of the class pay neither the schedule search nor the
compile.  Overlapped classes (PR 4) are cached but NOT lifted: the pipelined
executor embeds payloads host-side, so their programs key on the full op
tuple within the class entry (documented in docs/SERVING.md).

Compiled programs are ahead-of-time lowered (``jit(...).lower().compile()``)
so the cache — not jax's per-function trace cache — owns every executable:
hit/miss/eviction/compile counters are exact, and entries are LRU-evicted
against a total compiled-bytes budget (compiled executables pin device
memory for constants and temp buffers; an evicted class just recompiles on
next use).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from .. import circuit as _circ
from .. import obs as _obs

__all__ = ["CacheOptions", "CacheEntry", "CompileCache", "global_cache",
           "circuit_from_params", "DEFAULT_MAX_BYTES"]

DEFAULT_MAX_BYTES = int(os.environ.get("QUEST_TPU_SERVE_CACHE_BYTES",
                                       str(1 << 30)))

_FRESH_STATS = {"hits": 0, "misses": 0, "evictions": 0,
                "compiles": 0, "compile_seconds": 0.0, "entry_bytes": 0,
                "persist_hits": 0, "persist_stale": 0, "persist_saves": 0}


@dataclasses.dataclass(frozen=True)
class CacheOptions:
    """Execution options that select a DIFFERENT compiled program and are
    therefore part of the structural key (mesh width, scheduler overlap,
    compiled-circuit engine) — precision is not listed because the state
    dtype is part of every program signature already.

    ``engine`` must be RESOLVED ("xla" | "pallas"; ``compile_circuit``
    resolves "auto" through the planner BEFORE building options), so a
    class compiled through the XLA gate engine is never served to a
    request planned for the Pallas epoch executor and the hit/miss
    counters stay truthful per engine."""
    num_devices: int | None = None
    overlap: bool = False
    pipeline_chunks: int | None = None
    engine: str = "xla"


@dataclasses.dataclass
class _Program:
    call: object          # the AOT-compiled executable (or opaque callable)
    nbytes: int


@dataclasses.dataclass
class CacheEntry:
    """One structural class: the (scheduled) skeleton, the operand-slot map
    back into the original op order, and every compiled signature of the
    class (singleton / batched / donating variants).

    GRADIENT entries (``grad_entry_for``) reuse this record with
    ``skeleton`` holding the ParamCircuit's op tuple (Param placeholders
    are structural by construction, so no offset map exists —
    ``offsets=None``) and ``hamil`` the Hamiltonian's packed term masks;
    their programs are the adjoint ``(state, params, coeffs) ->
    (energy, grad)`` variants."""
    skey: tuple
    options: CacheOptions
    num_qubits: int | None
    skeleton: tuple | None          # structural op tuple; None => opaque (overlap)
    offsets: tuple | None           # per-skeleton-op offset into the param vector
    num_params: int
    programs: dict = dataclasses.field(default_factory=dict)
    nbytes: int = 0
    alive: bool = True
    hamil: tuple | None = None      # packed term masks => gradient entry kind


def _provenance_offsets(orig_ops, sched_ops) -> tuple:
    """Map each scheduled op's operand slot back to its offset in the
    ORIGINAL op order's param vector.  The scheduler preserves payload
    tuples through reordering and wire relabeling (scheduler.py
    ``_relabel_op`` passes ``op.matrix`` through untouched for non-bitperm
    kinds), so tuple identity is the provenance; value equality is the
    defensive fallback for interned payloads."""
    by_id: dict[int, int] = {}
    by_val: dict[tuple, list] = {}
    off = 0
    for op in orig_ops:
        c = _circ.op_param_count(op)
        if c:
            by_id[id(op.matrix)] = off
            by_val.setdefault((op.kind, op.shape, op.matrix), []).append(off)
        off += c
    total = off
    offsets: list[int | None] = []
    used: set[int] = set()
    for op in sched_ops:
        if _circ.op_param_count(op) == 0:
            offsets.append(None)
            continue
        o = by_id.get(id(op.matrix))
        if o is None or o in used:
            o = next((cand for cand in
                      by_val.get((op.kind, op.shape, op.matrix), ())
                      if cand not in used), None)
        if o is None:
            raise AssertionError(
                f"scheduler broke payload provenance: {op.kind} on "
                f"{op.targets} has no unmatched source op")
        used.add(o)
        offsets.append(o)
    if len(used) != len(by_id):
        raise AssertionError(
            f"scheduled circuit dropped {len(by_id) - len(used)} "
            "parameterized op(s)")
    return tuple(offsets), total


def circuit_from_params(num_qubits: int, skeleton, offsets, params) -> "_circ.Circuit":
    """Rebuild a concrete Circuit from a structural skeleton + operand
    vector — the inverse of the lift.  Used by the serve audit
    (analysis/serve_audit.py): for a SCHEDULED skeleton this reconstructs
    exactly the circuit the cached program executes for ``params``, which
    the PR 3 translation validator can then prove equivalent to the
    original request circuit."""
    params = np.asarray(params, np.float64).ravel()
    c = _circ.Circuit(num_qubits)
    for op, off in zip(skeleton, offsets):
        n_par = _circ.op_param_count(op)
        if n_par == 0:
            c.ops.append(op)
            continue
        payload = tuple(float(x) for x in params[off:off + n_par])
        shape = op.shape if op.kind != "mrz" else None
        c.ops.append(_circ.GateOp(op.kind, op.targets, op.controls,
                                  op.control_states, payload, shape))
    return c


def _state_sig(state) -> tuple:
    sharding = getattr(state, "sharding", None)
    return (tuple(state.shape), str(state.dtype), repr(sharding))


def _compiled_bytes(compiled) -> int:
    """Device footprint of one AOT executable for the eviction budget:
    generated code + temp allocations when the backend reports them, HLO
    text length as the backend-agnostic fallback (proportional to program
    size, which is what the budget needs to rank)."""
    try:
        ma = compiled.memory_analysis()
        size = (int(getattr(ma, "generated_code_size_in_bytes", 0) or 0)
                + int(getattr(ma, "temp_size_in_bytes", 0) or 0))
        if size > 0:
            return size
    except Exception:
        pass
    try:
        return len(compiled.as_text())
    except Exception:
        return 1 << 20


class CompileCache:
    """LRU of :class:`CacheEntry` bounded by total compiled bytes.

    ``stats``: hits / misses / evictions (structural-class lookups),
    compiles / compile_seconds (per-executable), entry_bytes / entries.
    One process-global instance (:func:`global_cache`) backs BOTH
    ``compile_circuit(donate=True)`` and every :class:`QuESTService` unless
    a service is constructed with its own cache — one cache, one eviction
    policy."""

    def __init__(self, max_bytes: int | None = None):
        self.max_bytes = DEFAULT_MAX_BYTES if max_bytes is None else int(max_bytes)
        self._lock = threading.RLock()
        self._entries: "OrderedDict[tuple, CacheEntry]" = OrderedDict()  # guarded-by: _lock
        self.stats = dict(_FRESH_STATS)         # guarded-by: _lock
        self.compile_times: list[float] = []    # guarded-by: _lock
        # optional persistent executable store (deploy/persist.py): when
        # attached, _get_program consults it before compiling (a store hit
        # installs a deserialized executable and touches NO compile
        # counter) and writes every fresh compile through to it
        # lock-free: set once by attach_store at provisioning time, before traffic; readers tolerate either epoch
        self._store = None

    def attach_store(self, store) -> "CompileCache":
        """Back this cache with a persistent executable store
        (``quest_tpu.deploy.persist.ExecutableStore``).  Misses consult the
        store before compiling: a valid entry loads as ``persist_hits``
        (zero compiles), a present-but-provenance-mismatched entry is
        REFUSED, recompiled, and counted ``persist_stale`` (docs/DEPLOY.md
        "persistence keying"); fresh compiles write through."""
        self._store = store
        return self

    # -- structural lookup --------------------------------------------------
    def entry_for(self, ops, num_qubits: int | None = None,
                  options: CacheOptions = CacheOptions()) -> CacheEntry:
        """The cache's one lookup: structural-key hit returns the existing
        class entry (programs and schedule included); a miss canonicalizes
        ``ops`` — scheduling the representative when the options carry a
        mesh — and registers a fresh entry."""
        skey = (num_qubits, tuple(_circ.structural_op(op) for op in ops),
                options)
        with _obs.span("cache.lookup", class_key=_obs.key_hash(skey),
                       engine=options.engine) as sp:
            with self._lock:
                e = self._entries.get(skey)
                if e is not None:
                    self._entries.move_to_end(skey)
                    self.stats["hits"] += 1
                    if sp is not None:
                        sp.attrs["outcome"] = "hit"
                    _obs.note("cache_outcome", "hit")
                    return e
                self.stats["misses"] += 1
            if sp is not None:
                sp.attrs["outcome"] = "miss"
            _obs.note("cache_outcome", "miss")
            e = self._build_entry(skey, tuple(ops), num_qubits, options)
        with self._lock:
            have = self._entries.get(skey)
            if have is not None:      # raced with another thread's build
                self._entries.move_to_end(skey)
                return have
            self._entries[skey] = e
            self.stats["entry_bytes"] += e.nbytes
            self._evict_locked()
        return e

    def _build_entry(self, skey, ops, num_qubits, options) -> CacheEntry:
        if options.overlap or options.engine == "pallas":
            # the pipelined executor (PR 4) and the Pallas epoch executor
            # both embed payloads host-side (the epoch planner folds them
            # into kernel constants and composed packs): cached,
            # byte-budgeted, but not parameter-lifted — their programs key
            # on the full op tuple within the class entry
            return CacheEntry(skey, options, num_qubits, None, None,
                              int(sum(_circ.op_param_count(op) for op in ops)))
        if options.num_devices is not None and options.num_devices > 1:
            c = _circ.Circuit(num_qubits)
            c.ops = list(ops)
            sched = c.schedule(options.num_devices)
            offsets, total = _provenance_offsets(ops, sched.ops)
            skeleton = tuple(_circ.structural_op(op) for op in sched.ops)
            return CacheEntry(skey, options, num_qubits, skeleton, offsets,
                              total)
        skeleton = tuple(_circ.structural_op(op) for op in ops)
        offsets, off = [], 0
        for op in ops:
            c = _circ.op_param_count(op)
            offsets.append(off if c else None)
            off += c
        return CacheEntry(skey, options, num_qubits, skeleton,
                          tuple(offsets), off)

    # -- program compilation ------------------------------------------------
    def _entry_meta(self, entry: CacheEntry) -> dict:
        """The class metadata a persisted program carries so a COLD cache
        can re-materialize the CacheEntry (scheduled skeleton included —
        warmed mesh classes skip the schedule search too)."""
        return {"num_qubits": entry.num_qubits, "options": entry.options,
                "skeleton": entry.skeleton, "offsets": entry.offsets,
                "num_params": entry.num_params, "hamil": entry.hamil}

    def install_entry(self, skey, num_qubits, options, skeleton, offsets,
                      num_params, hamil=None) -> CacheEntry:
        """Register a class entry from persisted metadata (the store's
        warm-up path) — idempotent, and deliberately NOT a hit or a miss:
        pre-population is provisioning, not traffic.  ``hamil`` (packed
        term masks) re-materializes a GRADIENT entry."""
        with self._lock:
            e = self._entries.get(skey)
            if e is not None:
                return e
            e = CacheEntry(skey, options, num_qubits, skeleton, offsets,
                           num_params, hamil=hamil)
            self._entries[skey] = e
            self._evict_locked()
            return e

    def install_program(self, entry: CacheEntry, tag: tuple, call,
                        nbytes: int) -> _Program:
        """Install an already-built executable under ``entry`` (a store
        load or a peer transfer).  Counts ``persist_hits`` — and nothing on
        the compile counters: the whole point of persistence is that this
        path compiled NOTHING."""
        with self._lock:
            p = entry.programs.get(tag)
            if p is not None:
                return p
            p = entry.programs[tag] = _Program(call, int(nbytes))
            entry.nbytes += int(nbytes)
            self.stats["persist_hits"] += 1
            if entry.alive:
                self.stats["entry_bytes"] += int(nbytes)
                self._evict_locked()
            return p

    def program_keys(self) -> list:
        """Every live (structural key, program tag) pair holding a
        compiled program — the hot list a warm replica broadcasts so cold
        peers know WHICH store entries to load first (deploy/pool.py)."""
        with self._lock:
            return [(e.skey, tag) for e in self._entries.values()
                    for tag in e.programs]

    def _get_program(self, entry: CacheEntry, tag: tuple, build) -> _Program:
        with self._lock:
            p = entry.programs.get(tag)
            if p is not None:
                return p
        if self._store is not None:
            status, call, nbytes = self._store.fetch(entry.skey, tag)
            if status == "hit":
                with _obs.span("cache.persist_load",
                               class_key=_obs.key_hash(entry.skey),
                               tag=str(tag[0])):
                    return self.install_program(entry, tag, call, nbytes)
            if status == "stale":
                # present but refused (jaxlib/calibration provenance or
                # digest mismatch): recompile below, and say so — a fleet
                # quietly recompiling everything after an upgrade should
                # show up on the scrape, not in a post-mortem
                with self._lock:
                    self.stats["persist_stale"] += 1
        t0 = time.perf_counter()
        with _obs.span("cache.compile", class_key=_obs.key_hash(entry.skey),
                       tag=str(tag[0]), engine=entry.options.engine):
            call = build()
        dt = time.perf_counter() - t0
        # every compile also folds into the process-wide runtime counters
        # (obs/counters.py): the one Prometheus scrape reports compile
        # seconds across ALL caches and ad-hoc jits, not just this one
        _obs.record_compile(dt)
        nbytes = _compiled_bytes(call)
        with self._lock:
            p = entry.programs.get(tag)
            if p is not None:       # raced: keep the first, drop ours
                return p
            p = entry.programs[tag] = _Program(call, nbytes)
            entry.nbytes += nbytes
            self.stats["compiles"] += 1
            self.stats["compile_seconds"] += dt
            self.compile_times.append(dt)
            if len(self.compile_times) > 4096:
                del self.compile_times[:2048]
            if entry.alive:
                self.stats["entry_bytes"] += nbytes
                self._evict_locked()
        if self._store is not None:
            # write-through (best-effort; opaque callables skip): the NEXT
            # process to serve this class loads instead of compiling
            if self._store.put(entry.skey, tag, call, nbytes,
                               self._entry_meta(entry)):
                with self._lock:
                    self.stats["persist_saves"] += 1
        return p

    @staticmethod
    def _lifted_one(entry: CacheEntry, probes: bool,
                    density: int | None = None):
        """The per-request ``(state, params) -> out`` body every lifted
        program variant lowers: ONE definition, so the probed and plain
        twins can never desynchronize on the gate chain.  ``probes=True``
        adds the numeric probe (obs/numerics.py) as an auxiliary output
        behind its optimization barrier — a pure reduction grafted beside
        the main dataflow, so the primary output is bit-identical to the
        unprobed lowering (pinned in tier-1 for every engine path).
        ``density`` (the density qubit count of a Choi-doubled request)
        grafts the DENSITY probe instead: trace + Hermiticity deviation,
        the per-batch health contract of served noisy-circuit classes."""
        skeleton, offsets = entry.skeleton, entry.offsets

        def one(st, params):
            out = _circ._run_ops_routed(st, skeleton, params, offsets)
            if probes:
                from ..obs import numerics as _num
                return out, _num.grafted_probe(out, density_qubits=density)
            return out

        return one

    def single_program(self, entry: CacheEntry, state, *,
                       donate: bool = False,
                       probes: bool = False,
                       density: int | None = None) -> _Program:
        """The class's ``(state, params) -> state`` executable for this
        state signature; ``probes=True`` compiles the probe-instrumented
        variant ``-> (state, probe_vec)`` under its own tag (byte budget
        and persistent store govern it like any other signature).
        Probed programs are never donating (the serving path that probes
        does not donate).  ``density`` selects the density-probe twin —
        the UNPROBED lowering is identical either way, so only probed
        tags split on it."""
        assert entry.skeleton is not None, "opaque (overlap) entries have no lifted program"
        assert not (donate and probes), "probed programs are not donating"
        if probes and density is not None:
            tag = ("single_probed_dm", int(density), _state_sig(state))
        elif probes:
            tag = ("single_probed", _state_sig(state))
        else:
            tag = ("single", bool(donate), _state_sig(state))
        n_par = entry.num_params
        one = self._lifted_one(entry, probes, density if probes else None)

        def build():
            jfn = jax.jit(one, donate_argnums=(0,) if donate else ())
            pav = jax.ShapeDtypeStruct((n_par,), jnp.float64)
            return jfn.lower(state, pav).compile()

        return self._get_program(entry, tag, build)

    def single_probed_program(self, entry: CacheEntry, state) -> _Program:
        """Probe-instrumented twin of :meth:`single_program` (same
        lowering via ``probes=True``)."""
        return self.single_program(entry, state, probes=True)

    def batch_program(self, entry: CacheEntry, state, batch: int, *,
                      stacked: bool = False, mode: str = "map",
                      probes: bool = False,
                      density: int | None = None) -> _Program:
        """The microbatch executable: params stacked on axis 0, initial
        state broadcast (``stacked=False``, the shared-|0..0> fast path) or
        per-request (``stacked=True``).  ``state`` is the UNBATCHED
        prototype; its signature keys the program.

        ``mode='map'`` (default) lowers the batch as ``lax.map`` — the
        per-element computation is the IDENTICAL jaxpr to the singleton
        program, so batched results are bit-identical to serial execution
        (the serving contract).  ``mode='vmap'`` lowers one vectorized
        program — on dense-gate circuits XLA's batched FMA fusion can
        differ from the unbatched codegen in the LAST ULP (measured ~4e-17
        on f64 CPU), so it trades the bit-identity guarantee for
        throughput; see docs/SERVING.md.

        ``probes=True`` compiles the probe-instrumented variant through
        the SAME three-way lowering with a per-request probe vector as
        the second output — ``(states, probes)`` stacked on axis 0."""
        assert entry.skeleton is not None
        if mode not in ("map", "vmap"):
            raise ValueError(f"batch mode must be 'map' or 'vmap', got {mode!r}")
        if probes and density is not None:
            head: tuple = ("batch_probed_dm", int(density))
        else:
            head = ("batch_probed" if probes else "batch",)
        tag = head + (int(batch), bool(stacked), mode, _state_sig(state))
        n_par = entry.num_params
        one = self._lifted_one(entry, probes, density if probes else None)

        def build():
            if mode == "vmap":
                def run(st, pb):
                    return jax.vmap(one, in_axes=(0 if stacked else None, 0))(st, pb)
            elif stacked:
                def run(sb, pb):
                    return jax.lax.map(lambda xs: one(xs[0], xs[1]), (sb, pb))
            else:
                def run(st, pb):
                    return jax.lax.map(lambda p: one(st, p), pb)

            pav = jax.ShapeDtypeStruct((batch, n_par), jnp.float64)
            sav = (jax.ShapeDtypeStruct((batch,) + tuple(state.shape),
                                        state.dtype) if stacked else state)
            return jax.jit(run).lower(sav, pav).compile()

        return self._get_program(entry, tag, build)

    def batch_probed_program(self, entry: CacheEntry, state, batch: int, *,
                             stacked: bool = False,
                             mode: str = "map") -> _Program:
        """Probe-instrumented twin of :meth:`batch_program` (same
        lowering via ``probes=True``)."""
        return self.batch_program(entry, state, batch, stacked=stacked,
                                  mode=mode, probes=True)

    def overlap_program(self, entry: CacheEntry, ops: tuple, *,
                        donate: bool = False) -> _Program:
        """Opaque per-payload program for an overlapped class (PR 4
        executor; payloads compile-time).  Keyed on the FULL op tuple
        inside the class entry so the byte budget still governs it."""
        tag = ("overlap", bool(donate), ops)

        def build():
            from ..parallel import executor as _exec
            c = _circ.Circuit(entry.num_qubits)
            c.ops = list(ops)
            sched = c.schedule(entry.options.num_devices, overlap=True,
                               pipeline_chunks=entry.options.pipeline_chunks)
            # a plain callable: _compiled_bytes falls through to its
            # flat-rate charge, which is all the budget needs here
            return _exec.overlapped_program(sched, entry.options.num_devices,
                                            donate=donate)

        return self._get_program(entry, tag, build)

    def epoch_program(self, entry: CacheEntry, ops: tuple, *,
                      donate: bool = False) -> _Program:
        """Opaque per-payload program for a Pallas-epoch class
        (ops/epoch_pallas.py; payloads are kernel constants and composed
        packs, so — like overlap classes — the program keys on the FULL op
        tuple inside the class entry and the byte budget still governs
        it."""
        tag = ("epoch", bool(donate), ops)

        def build():
            from ..ops import epoch_pallas as _ep
            return _ep.jit_program(ops, donate=donate)

        return self._get_program(entry, tag, build)

    def epoch_plane_program(self, entry: CacheEntry, ops: tuple, *,
                            donate: bool = True) -> _Program:
        """The plane-pair twin of :meth:`epoch_program`: a donated
        ``(re, im) -> (re, im)`` executable (ops/epoch_pallas.py
        ``jit_program_planes``) so plane-storage callers never stack the
        (2, N) pair — the entry ``compile_circuit`` threads through as
        ``run.planes``.  Cached under the class entry like every other
        signature, so the byte budget governs it too."""
        tag = ("epoch_planes", bool(donate), ops)

        def build():
            from ..ops import epoch_pallas as _ep
            return _ep.jit_program_planes(ops, donate=donate)

        return self._get_program(entry, tag, build)

    def epoch_plane_runner(self, ops, donate: bool = True):
        """``(re, im) -> (re, im)`` adapter over the pallas class's cached
        plane program (the ``compile_circuit`` hook; see
        :meth:`epoch_plane_program`)."""
        ops = tuple(ops)
        options = CacheOptions(engine="pallas")
        resolved: dict = {}

        def run(re, im):
            hit = resolved.get("p")
            if hit is None or not hit[0].alive:
                entry = self.entry_for(ops, options=options)
                prog = self.epoch_plane_program(entry, ops, donate=donate)
                resolved["p"] = hit = (entry, prog.call)
            return hit[1](re, im)

        return run

    # -- the GRADIENT entry kind (quest_tpu/grad) ---------------------------
    def grad_entry_for(self, ops, num_qubits: int, num_params: int, masks,
                       options: CacheOptions = CacheOptions()) -> CacheEntry:
        """Structural lookup for an adjoint-gradient class: ONE entry per
        (num_qubits, ParamCircuit op tuple, Hamiltonian packed-mask tuple,
        options).  No payload lift is needed — ``Param`` placeholders are
        already structural and a recorded ansatz's static gates are
        identical across tenants — so the op tuple itself is the skeleton;
        the masks join the key because they select the Pauli-sum head's
        data movement (coefficients ride as a runtime operand).  Hits and
        misses land on the same counters as forward classes: gradient
        lookups are part of the same serving economics."""
        skey = ("grad", num_qubits, tuple(ops), tuple(masks), options)
        with _obs.span("cache.lookup", class_key=_obs.key_hash(skey),
                       engine=options.engine, grad=True) as sp:
            with self._lock:
                e = self._entries.get(skey)
                if e is not None:
                    self._entries.move_to_end(skey)
                    self.stats["hits"] += 1
                    if sp is not None:
                        sp.attrs["outcome"] = "hit"
                    _obs.note("cache_outcome", "hit")
                    return e
                self.stats["misses"] += 1
            if sp is not None:
                sp.attrs["outcome"] = "miss"
            _obs.note("cache_outcome", "miss")
            e = CacheEntry(skey, options, num_qubits, tuple(ops), None,
                           int(num_params), hamil=tuple(masks))
        with self._lock:
            have = self._entries.get(skey)
            if have is not None:      # raced with another thread's build
                self._entries.move_to_end(skey)
                return have
            self._entries[skey] = e
            self.stats["entry_bytes"] += e.nbytes
            self._evict_locked()
        return e

    @staticmethod
    def _grad_one(entry: CacheEntry, probes: bool, barriers: bool = True):
        """The per-request adjoint body ``(state, params, coeffs) ->
        (energy, grad[, probe])`` every gradient program variant lowers —
        ONE definition (grad/adjoint.py ``adjoint_terms_fn``), so the
        probed and plain twins can never desynchronize on the sweep.

        The probed variant extends PR 13's numeric probes to the ADJOINT
        path: the probe vector is taken from the round-tripped |psi>
        (forward then fully uncomputed — its norm must equal the input
        norm, so uncompute drift is judged against the ulp band) with
        NaN/Inf counts of the energy and gradient folded in, so a NaN
        born in the backward sweep (a poisoned adjoint state) trips the
        ledger even though |psi> itself round-trips clean.  Probe inputs
        pass through ``optimization_barrier`` so the primary (energy,
        grad) outputs compile bit-identical to the unprobed program.

        ``barriers=False`` builds the barrier-free twin for the vmap
        throughput lowering (``optimization_barrier`` has no batching
        rule on this jax; vmap mode makes no bit-identity claims)."""
        from ..grad.adjoint import adjoint_terms_fn

        body = adjoint_terms_fn(entry.skeleton, entry.num_qubits,
                                entry.num_params, entry.hamil,
                                return_state=probes, barriers=barriers)
        if not probes:
            return body

        from ..obs import numerics as _num

        def one(st, params, coeffs):
            energy, grads, psi = body(st, params, coeffs)
            if barriers:
                pv = _num.grafted_probe(psi)
                eb, gb = jax.lax.optimization_barrier((energy, grads))
            else:
                pv = _num.state_probe_vector(psi)
                eb, gb = energy, grads
            nan = (jnp.sum(jnp.isnan(gb)) + jnp.isnan(eb)).astype(pv.dtype)
            inf = (jnp.sum(jnp.isinf(gb)) + jnp.isinf(eb)).astype(pv.dtype)
            return energy, grads, pv.at[2].add(nan).at[3].add(inf)

        return one

    def grad_single_program(self, entry: CacheEntry, state, *,
                            probes: bool = False) -> _Program:
        """The gradient class's ``(state, params, coeffs) -> (energy,
        grad)`` executable for this state signature (``probes=True``: the
        instrumented ``-> (energy, grad, probe_vec)`` twin under its own
        tag — byte budget and persistence govern both like any other
        signature).

        Lowered as a DUPLICATED-ROW ``lax.map`` pair (the request's
        operands stacked twice, element 0 returned): ``lax.map`` compiles
        ONE loop body for any trip count >= 2, but a trip count of 1 is
        unrolled into the surrounding program where XLA's fusion may
        contract the sweep's FMAs differently (measured: one-ulp gradient
        drift vs the batched program on CPU).  Running the lone request
        as a pair keeps every gradient execution on the SAME body codegen
        — bit-identity across batching by construction, at one duplicated
        element per singleton dispatch (docs/SERVING.md)."""
        assert entry.hamil is not None, "not a gradient entry"
        tag = ("grad_single_probed" if probes else "grad_single",
               _state_sig(state))
        n_par, n_terms = entry.num_params, len(entry.hamil)
        one = self._grad_one(entry, probes)

        def build():
            def run(st, p, c):
                outs = jax.lax.map(lambda xs: one(st, xs[0], xs[1]),
                                   (jnp.stack([p, p]), jnp.stack([c, c])))
                return jax.tree_util.tree_map(lambda x: x[0], outs)

            pav = jax.ShapeDtypeStruct((n_par,), jnp.float64)
            cav = jax.ShapeDtypeStruct((n_terms,), jnp.float64)
            return jax.jit(run).lower(state, pav, cav).compile()

        return self._get_program(entry, tag, build)

    def grad_batch_program(self, entry: CacheEntry, state, batch: int, *,
                           stacked: bool = False, mode: str = "map",
                           probes: bool = False) -> _Program:
        """The gradient microbatch executable: params AND coeffs stacked
        on axis 0 (requests of one class share masks but may carry
        different coefficients), initial state broadcast or per-request.
        Same three-way lowering as :meth:`batch_program`: the default
        ``lax.map`` compiles ONE loop body shared by every trip count
        >= 2 (the singleton program is a duplicated-row pair for exactly
        this reason — see :meth:`grad_single_program`), so batched
        gradients are bit-identical to serial execution; ``mode='vmap'``
        trades that for vectorized throughput."""
        assert entry.hamil is not None, "not a gradient entry"
        if mode not in ("map", "vmap"):
            raise ValueError(f"batch mode must be 'map' or 'vmap', got {mode!r}")
        if mode == "map" and batch < 2:
            raise ValueError(
                "gradient map-mode batches are >= 2 rows (a 1-trip "
                "lax.map unrolls into a different fusion context; "
                "execute_grad_group pads singletons)")
        tag = ("grad_batch_probed" if probes else "grad_batch", int(batch),
               bool(stacked), mode, _state_sig(state))
        n_par, n_terms = entry.num_params, len(entry.hamil)
        one = self._grad_one(entry, probes, barriers=(mode != "vmap"))

        def build():
            if mode == "vmap":
                def run(st, pb, cb):
                    return jax.vmap(one, in_axes=(0 if stacked else None,
                                                  0, 0))(st, pb, cb)
            elif stacked:
                def run(sb, pb, cb):
                    return jax.lax.map(lambda xs: one(*xs), (sb, pb, cb))
            else:
                def run(st, pb, cb):
                    return jax.lax.map(lambda xs: one(st, xs[0], xs[1]),
                                       (pb, cb))

            pav = jax.ShapeDtypeStruct((batch, n_par), jnp.float64)
            cav = jax.ShapeDtypeStruct((batch, n_terms), jnp.float64)
            sav = (jax.ShapeDtypeStruct((batch,) + tuple(state.shape),
                                        state.dtype) if stacked else state)
            return jax.jit(run).lower(sav, pav, cav).compile()

        return self._get_program(entry, tag, build)

    # -- execution front-ends -----------------------------------------------
    def execute(self, ops, state, params=None, *, num_qubits=None,
                options: CacheOptions = CacheOptions(),
                donate: bool = False):
        """One-call lookup + compile-if-needed + run for a single request.

        ``engine="pallas"`` composes with neither ``overlap`` nor a mesh
        (compile_circuit rejects both; here too rather than silently
        preferring one), and — like every pallas entry point — falls back
        to the plain XLA class for non-f32 states."""
        if options.engine == "pallas":
            if options.overlap or (options.num_devices or 1) > 1:
                raise ValueError(
                    "engine='pallas' is single-device and incompatible with "
                    "overlap=True (the deferred qubit map must materialize "
                    "before sharded collectives; docs/DESIGN.md)")
            if state.dtype != jnp.float32:   # f32-only engine
                options = dataclasses.replace(options, engine="xla")
        entry = self.entry_for(ops, num_qubits, options)
        if entry.skeleton is None:
            if options.engine == "pallas":
                return self.epoch_program(entry, tuple(ops),
                                          donate=donate).call(state)
            return self.overlap_program(entry, tuple(ops),
                                        donate=donate).call(state)
        if params is None:
            params = _circ.param_vector(ops)
        params = self._check_params(entry, params)
        prog = self.single_program(entry, state, donate=donate)
        return prog.call(state, params)

    def donating_runner(self, ops, engine: str = "xla"):
        """The ``compile_circuit(donate=True)`` adapter: a ``state ->
        state`` callable over this op tuple's operand vector and the
        class's shared donating program.  The resolved (entry, program) is
        memoized per state signature in the closure — donate exists for
        tight iteration loops, which must not take the process-global cache
        lock (or inflate the per-request hit counters) once per step; only
        an evicted entry re-enters the cache.

        ``engine="pallas"`` routes the class through the epoch executor's
        opaque donating program (its own class key: CacheOptions.engine);
        non-f32 states fall back to the lifted XLA program of the plain
        class — the epoch engine is f32-only."""
        ops = tuple(ops)
        params = jnp.asarray(_circ.param_vector(ops))
        options = CacheOptions(engine=engine)
        resolved: dict = {}

        def run(state):
            sig = _state_sig(state)
            hit = resolved.get(sig)
            if hit is None or not hit[0].alive:
                if engine == "pallas" and state.dtype == jnp.float32:
                    entry = self.entry_for(ops, options=options)
                    prog = self.epoch_program(entry, ops, donate=True)
                    call = prog.call
                else:
                    entry = self.entry_for(ops)
                    prog = self.single_program(entry, state, donate=True)
                    call = (lambda st, _p=prog: _p.call(st, params))
                resolved.clear()     # one live signature per loop in practice
                resolved[sig] = hit = (entry, call)
            return hit[1](state)

        return run

    def _check_params(self, entry: CacheEntry, params):
        params = jnp.asarray(params, jnp.float64).ravel()
        if params.shape != (entry.num_params,):
            raise ValueError(
                f"operand vector has {params.shape[0]} scalars; this "
                f"structural class takes {entry.num_params}")
        return params

    # -- bookkeeping --------------------------------------------------------
    # requires-lock: _lock
    def _evict_locked(self) -> None:
        """Drop least-recently-used classes until the byte budget holds.
        The most recent entry always survives (a budget smaller than one
        program must still serve that program)."""
        while (self.stats["entry_bytes"] > self.max_bytes
               and len(self._entries) > 1):
            _, e = self._entries.popitem(last=False)
            e.alive = False
            self.stats["entry_bytes"] -= e.nbytes
            self.stats["evictions"] += 1

    def hit_rate(self) -> float:
        with self._lock:
            total = self.stats["hits"] + self.stats["misses"]
            return self.stats["hits"] / total if total else 0.0

    def snapshot(self) -> dict:
        with self._lock:
            d = dict(self.stats)
            d["entries"] = len(self._entries)
            d["max_bytes"] = self.max_bytes
            d["hit_rate"] = (d["hits"] / (d["hits"] + d["misses"])
                             if d["hits"] + d["misses"] else 0.0)
            times = sorted(self.compile_times)
            if times:
                d["compile_seconds_p50"] = times[len(times) // 2]
                d["compile_seconds_p99"] = times[min(len(times) - 1,
                                                     round(0.99 * (len(times) - 1)))]
            return d

    def clear(self) -> None:
        with self._lock:
            for e in self._entries.values():
                e.alive = False
            self._entries.clear()
            self.stats = dict(_FRESH_STATS)
            self.compile_times = []


_GLOBAL: CompileCache | None = None
_GLOBAL_LOCK = threading.Lock()


def global_cache() -> CompileCache:
    """The process-wide cache shared by ``compile_circuit(donate=True)``
    and default-constructed services — the single eviction policy."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = CompileCache()
        return _GLOBAL
