"""Serving metrics: thread-safe counters, gauges and histograms with dict
and Prometheus text exports.

The reference has no observability surface at all (a QuEST run reports
through ``reportQuregParams`` printfs); a serving layer lives or dies by its
metrics — queue depth tells you when to shed load, the cache hit rate is THE
number that says parameter lifting is working, and latency percentiles are
the SLO.  Kept dependency-free on purpose: the container must not grow a
prometheus_client requirement, and the text exposition format is a stable,
trivially-writable contract (one ``name{labels} value`` line per sample).

Counters and gauges take an optional ``labels`` dict so one registry can
carry per-replica series (``quest_serve_requests_completed_total{replica="2"}``)
as real Prometheus labels instead of name-mangling — the shape a pod-scale
deployment (quest_tpu/deploy) scrapes as ONE document.  :meth:`Metrics.labeled`
returns a VIEW over the same registry that stamps its base labels onto every
counter/gauge write, so N replica services share one scrape with one TYPE
line per family.  Histograms stay unlabeled: a deployment-level latency
histogram aggregates replicas (per-replica percentiles live in each
replica's windowed SLO monitor, obs/slo.py).

Histograms keep both fixed buckets (the Prometheus export) and a bounded
reservoir of raw observations (exact p50/p99 for the dict export — at serve
request rates a few thousand retained floats are noise)."""

from __future__ import annotations

import threading

__all__ = ["Metrics", "parse_prometheus",
           "LATENCY_BUCKETS", "BATCH_BUCKETS"]

# seconds; spans sub-ms CPU microbatches to stuck-queue outliers
LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)
BATCH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)

_RESERVOIR_CAP = 8192  # raw observations kept per histogram (FIFO halved)


def _label_key(labels: dict | None) -> tuple:
    """Canonical hashable form of a labels dict: sorted (name, value)
    pairs, values coerced to str.  ``None``/empty -> () (the unlabeled
    series — exactly the pre-labels registry behaviour)."""
    if not labels:
        return ()
    items = []
    for k in sorted(labels):
        name = str(k)
        if not name.replace("_", "").isalnum() or name[0].isdigit():
            raise ValueError(f"bad label name {name!r}")
        items.append((name, str(labels[k])))
    return tuple(items)


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(key: tuple) -> str:
    """The rendered ``k="v",...`` body (no braces) for a canonical label
    key — also the sample-name suffix ``as_dict`` uses, matching what
    :func:`parse_prometheus` returns as the labels string."""
    return ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in key)


def _sample_name(name: str, key: tuple) -> str:
    return f"{name}{{{_label_str(key)}}}" if key else name


class _Histogram:
    __slots__ = ("buckets", "counts", "total", "count", "raw")

    def __init__(self, buckets):
        self.buckets = tuple(float(b) for b in buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self.total = 0.0
        self.count = 0
        self.raw: list[float] = []

    def observe(self, value: float) -> None:
        value = float(value)
        self.total += value
        self.count += 1
        for i, b in enumerate(self.buckets):
            if value <= b:
                self.counts[i] += 1
                break
        else:
            self.counts[-1] += 1
        self.raw.append(value)
        if len(self.raw) > _RESERVOIR_CAP:
            # drop the oldest half: percentiles stay recent-biased, O(1) amortised
            del self.raw[:_RESERVOIR_CAP // 2]

    def percentile(self, q: float) -> float:
        # the one shared percentile definition (obs/slo.py): the registry's
        # lifetime p99 and an SLO window's p99 must never differ on method
        from ..obs.slo import nearest_rank_percentile
        return nearest_rank_percentile(self.raw, q)

    def summary(self) -> dict:
        mean = self.total / self.count if self.count else 0.0
        return {"count": self.count, "sum": self.total, "mean": mean,
                "p50": self.percentile(50.0), "p99": self.percentile(99.0)}


class Metrics:
    """A tiny metric registry: ``inc``/``set_gauge``/``observe`` and two
    exports — ``as_dict()`` for programmatic callers (the selftest gate)
    and ``to_prometheus()`` for scrapers.  All methods are thread-safe.

    ``inc``/``set_gauge`` take an optional ``labels`` dict; every distinct
    label set is its own sample under the one metric family.  Unlabeled
    calls are the ``()`` label set, so the pre-labels API is unchanged."""

    def __init__(self, prefix: str = "quest_serve"):
        self.prefix = prefix
        self._lock = threading.Lock()
        # family name -> {canonical label key -> value}
        self._counters: dict[str, dict[tuple, float]] = {}  # guarded-by: _lock
        self._gauges: dict[str, dict[tuple, float]] = {}    # guarded-by: _lock
        self._hists: dict[str, _Histogram] = {}             # guarded-by: _lock

    # -- recording ----------------------------------------------------------
    def inc(self, name: str, value: float = 1.0,
            labels: dict | None = None) -> None:
        key = _label_key(labels)
        with self._lock:
            fam = self._counters.setdefault(name, {})
            fam[key] = fam.get(key, 0.0) + value

    def set_gauge(self, name: str, value: float,
                  labels: dict | None = None) -> None:
        key = _label_key(labels)
        with self._lock:
            self._gauges.setdefault(name, {})[key] = float(value)

    def observe(self, name: str, value: float, buckets=LATENCY_BUCKETS) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _Histogram(buckets)
            h.observe(value)

    def counter(self, name: str, labels: dict | None = None) -> float:
        key = _label_key(labels)
        with self._lock:
            return self._counters.get(name, {}).get(key, 0.0)

    def counter_total(self, name: str) -> float:
        """Sum of a counter family across ALL label sets — the deployment
        view of a per-replica-labeled counter."""
        with self._lock:
            return sum(self._counters.get(name, {}).values())

    def labeled(self, **labels) -> "_LabeledMetrics":
        """A view over THIS registry that stamps ``labels`` onto every
        counter/gauge write (histograms pass through unlabeled — they
        aggregate at deployment level).  N replica services constructed
        with ``pool_metrics.labeled(replica=str(i))`` share one registry,
        one scrape, one TYPE line per family."""
        return _LabeledMetrics(self, _label_key(labels))

    # -- export -------------------------------------------------------------
    def as_dict(self) -> dict:
        """Counters/gauges keyed by SAMPLE name: the plain family name for
        the unlabeled series, ``name{k="v"}`` for labeled ones (the same
        sample-name strings :func:`parse_prometheus` round-trips)."""
        with self._lock:
            return {
                "counters": {_sample_name(n, k): v
                             for n, fam in self._counters.items()
                             for k, v in fam.items()},
                "gauges": {_sample_name(n, k): v
                           for n, fam in self._gauges.items()
                           for k, v in fam.items()},
                "histograms": {k: h.summary() for k, h in self._hists.items()},
            }

    def to_prometheus(self, extra_gauges: dict | list | None = None,
                      extra_labels: dict | None = None) -> str:
        """The Prometheus text exposition format.  ``extra_gauges`` lets the
        service splice point-in-time values into the same scrape without
        them living in the registry — the ONE-scrape contract
        (docs/OBSERVABILITY.md): ``QuESTService.prometheus()`` splices the
        cache snapshot (``cache_*``), the tracing/ledger/flight counters
        (``obs_*``) and the windowed SLO view (``slo_*`` — hit rate, burn
        rates, queue saturation from quest_tpu/obs/slo.py) next to the
        cumulative registry families.  ``extra_labels`` stamps a label set
        onto every spliced extra gauge (the deployment scrape labels each
        replica's cache/SLO splice ``{replica="i"}``).

        ``extra_gauges`` may also be a LIST of ``(gauges_dict, labels)``
        groups — N differently-labeled splices in one scrape (the
        ``ReplicaPool`` case) without any of them entering the registry:
        splices are point-in-time by contract, and a registry-resident
        copy would go stale (and outlive a retired replica)."""
        with self._lock:
            counters = {n: dict(fam) for n, fam in self._counters.items()}
            gauges = {n: dict(fam) for n, fam in self._gauges.items()}
            hists = {k: (h.buckets, list(h.counts), h.total, h.count)
                     for k, h in self._hists.items()}
        if extra_gauges:
            groups = (extra_gauges if isinstance(extra_gauges, list)
                      else [(extra_gauges, extra_labels)])
            for group, labels in groups:
                if isinstance(extra_gauges, list) and extra_labels:
                    # the list form must not silently drop extra_labels:
                    # they underlay every group (group labels win ties)
                    labels = {**extra_labels, **(labels or {})}
                key = _label_key(labels)
                for k, v in group.items():
                    gauges.setdefault(k, {})[key] = float(v)
        p = self.prefix
        lines: list[str] = []
        for name in sorted(counters):
            full = f"{p}_{name}"
            lines.append(f"# TYPE {full} counter")
            for key in sorted(counters[name]):
                lines.append(f"{_sample_name(full, key)} "
                             f"{_fmt(counters[name][key])}")
        for name in sorted(gauges):
            full = f"{p}_{name}"
            lines.append(f"# TYPE {full} gauge")
            for key in sorted(gauges[name]):
                lines.append(f"{_sample_name(full, key)} "
                             f"{_fmt(gauges[name][key])}")
        for name in sorted(hists):
            buckets, counts, total, count = hists[name]
            full = f"{p}_{name}"
            lines.append(f"# TYPE {full} histogram")
            cum = 0
            for b, c in zip(buckets, counts[:-1]):
                cum += c
                lines.append(f'{full}_bucket{{le="{_fmt(b)}"}} {cum}')
            cum += counts[-1]
            lines.append(f'{full}_bucket{{le="+Inf"}} {cum}')
            lines.append(f"{full}_sum {_fmt(total)}")
            lines.append(f"{full}_count {count}")
        return "\n".join(lines) + "\n"


class _LabeledMetrics:
    """A label-stamping view over a shared :class:`Metrics` registry (see
    :meth:`Metrics.labeled`).  Duck-typed to the registry surface the
    service consumes; exports delegate to the base registry (ONE scrape)."""

    def __init__(self, base: Metrics, key: tuple):
        self._base = base
        self._key = key
        self.prefix = base.prefix

    @property
    def base_labels(self) -> dict:
        return dict(self._key)

    def _merged(self, labels: dict | None) -> dict:
        merged = dict(self._key)
        if labels:
            merged.update({str(k): str(v) for k, v in labels.items()})
        return merged

    def inc(self, name: str, value: float = 1.0,
            labels: dict | None = None) -> None:
        self._base.inc(name, value, labels=self._merged(labels))

    def set_gauge(self, name: str, value: float,
                  labels: dict | None = None) -> None:
        self._base.set_gauge(name, value, labels=self._merged(labels))

    def observe(self, name: str, value: float, buckets=LATENCY_BUCKETS) -> None:
        self._base.observe(name, value, buckets)

    def counter(self, name: str, labels: dict | None = None) -> float:
        return self._base.counter(name, labels=self._merged(labels))

    def counter_total(self, name: str) -> float:
        return self._base.counter_total(name)

    def labeled(self, **labels) -> "_LabeledMetrics":
        return _LabeledMetrics(self._base, _label_key(self._merged(labels)))

    def as_dict(self) -> dict:
        return self._base.as_dict()

    def to_prometheus(self, extra_gauges=None,
                      extra_labels: dict | None = None) -> str:
        if isinstance(extra_gauges, list):
            extra_gauges = [(g, self._merged(labels))
                            for g, labels in extra_gauges]
            return self._base.to_prometheus(extra_gauges)
        merged = self._merged(extra_labels) if extra_gauges else None
        return self._base.to_prometheus(extra_gauges, extra_labels=merged)


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def parse_prometheus(text: str) -> dict:
    """Strict-enough parser for the exposition format this module emits
    (used by the CI gate and tests to prove the export is well-formed).
    Returns ``{metric_sample_name: {label_string_or_'': value}}``; raises
    ``ValueError`` on any malformed line or on a histogram whose cumulative
    bucket counts decrease."""
    samples: dict[str, dict[str, float]] = {}
    last_hist_cum: dict[str, float] = {}
    for ln, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) < 4 or parts[1] not in ("TYPE", "HELP"):
                raise ValueError(f"line {ln}: malformed comment {line!r}")
            if parts[1] == "TYPE" and parts[3] not in (
                    "counter", "gauge", "histogram", "summary", "untyped"):
                raise ValueError(f"line {ln}: unknown metric type {parts[3]!r}")
            continue
        name_part, _, value_part = line.rpartition(" ")
        if not name_part:
            raise ValueError(f"line {ln}: no value in {line!r}")
        try:
            value = float(value_part)
        except ValueError:
            raise ValueError(f"line {ln}: bad value {value_part!r}") from None
        labels = ""
        name = name_part
        if "{" in name_part:
            if not name_part.endswith("}"):
                raise ValueError(f"line {ln}: malformed labels in {line!r}")
            name, _, labels = name_part.partition("{")
            labels = labels[:-1]
        if not name.replace("_", "").replace(":", "").isalnum():
            raise ValueError(f"line {ln}: bad metric name {name!r}")
        samples.setdefault(name, {})[labels] = value
        if name.endswith("_bucket"):
            prev = last_hist_cum.get(name)
            if prev is not None and value < prev:
                raise ValueError(
                    f"line {ln}: histogram {name} buckets not cumulative")
            last_hist_cum[name] = value
    if not samples:
        raise ValueError("no metric samples found")
    return samples
