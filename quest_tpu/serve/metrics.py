"""Serving metrics: thread-safe counters, gauges and histograms with dict
and Prometheus text exports.

The reference has no observability surface at all (a QuEST run reports
through ``reportQuregParams`` printfs); a serving layer lives or dies by its
metrics — queue depth tells you when to shed load, the cache hit rate is THE
number that says parameter lifting is working, and latency percentiles are
the SLO.  Kept dependency-free on purpose: the container must not grow a
prometheus_client requirement, and the text exposition format is a stable,
trivially-writable contract (one ``name{labels} value`` line per sample).

Histograms keep both fixed buckets (the Prometheus export) and a bounded
reservoir of raw observations (exact p50/p99 for the dict export — at serve
request rates a few thousand retained floats are noise)."""

from __future__ import annotations

import threading

__all__ = ["Metrics", "parse_prometheus",
           "LATENCY_BUCKETS", "BATCH_BUCKETS"]

# seconds; spans sub-ms CPU microbatches to stuck-queue outliers
LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)
BATCH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)

_RESERVOIR_CAP = 8192  # raw observations kept per histogram (FIFO halved)


class _Histogram:
    __slots__ = ("buckets", "counts", "total", "count", "raw")

    def __init__(self, buckets):
        self.buckets = tuple(float(b) for b in buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self.total = 0.0
        self.count = 0
        self.raw: list[float] = []

    def observe(self, value: float) -> None:
        value = float(value)
        self.total += value
        self.count += 1
        for i, b in enumerate(self.buckets):
            if value <= b:
                self.counts[i] += 1
                break
        else:
            self.counts[-1] += 1
        self.raw.append(value)
        if len(self.raw) > _RESERVOIR_CAP:
            # drop the oldest half: percentiles stay recent-biased, O(1) amortised
            del self.raw[:_RESERVOIR_CAP // 2]

    def percentile(self, q: float) -> float:
        # the one shared percentile definition (obs/slo.py): the registry's
        # lifetime p99 and an SLO window's p99 must never differ on method
        from ..obs.slo import nearest_rank_percentile
        return nearest_rank_percentile(self.raw, q)

    def summary(self) -> dict:
        mean = self.total / self.count if self.count else 0.0
        return {"count": self.count, "sum": self.total, "mean": mean,
                "p50": self.percentile(50.0), "p99": self.percentile(99.0)}


class Metrics:
    """A tiny metric registry: ``inc``/``set_gauge``/``observe`` and two
    exports — ``as_dict()`` for programmatic callers (the selftest gate)
    and ``to_prometheus()`` for scrapers.  All methods are thread-safe."""

    def __init__(self, prefix: str = "quest_serve"):
        self.prefix = prefix
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, _Histogram] = {}

    # -- recording ----------------------------------------------------------
    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def observe(self, name: str, value: float, buckets=LATENCY_BUCKETS) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _Histogram(buckets)
            h.observe(value)

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    # -- export -------------------------------------------------------------
    def as_dict(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.summary() for k, h in self._hists.items()},
            }

    def to_prometheus(self, extra_gauges: dict | None = None) -> str:
        """The Prometheus text exposition format.  ``extra_gauges`` lets the
        service splice point-in-time values into the same scrape without
        them living in the registry — the ONE-scrape contract
        (docs/OBSERVABILITY.md): ``QuESTService.prometheus()`` splices the
        cache snapshot (``cache_*``), the tracing/ledger/flight counters
        (``obs_*``) and the windowed SLO view (``slo_*`` — hit rate, burn
        rates, queue saturation from quest_tpu/obs/slo.py) next to the
        cumulative registry families."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {k: (h.buckets, list(h.counts), h.total, h.count)
                     for k, h in self._hists.items()}
        if extra_gauges:
            gauges.update({k: float(v) for k, v in extra_gauges.items()})
        p = self.prefix
        lines: list[str] = []
        for name in sorted(counters):
            full = f"{p}_{name}"
            lines.append(f"# TYPE {full} counter")
            lines.append(f"{full} {_fmt(counters[name])}")
        for name in sorted(gauges):
            full = f"{p}_{name}"
            lines.append(f"# TYPE {full} gauge")
            lines.append(f"{full} {_fmt(gauges[name])}")
        for name in sorted(hists):
            buckets, counts, total, count = hists[name]
            full = f"{p}_{name}"
            lines.append(f"# TYPE {full} histogram")
            cum = 0
            for b, c in zip(buckets, counts[:-1]):
                cum += c
                lines.append(f'{full}_bucket{{le="{_fmt(b)}"}} {cum}')
            cum += counts[-1]
            lines.append(f'{full}_bucket{{le="+Inf"}} {cum}')
            lines.append(f"{full}_sum {_fmt(total)}")
            lines.append(f"{full}_count {count}")
        return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def parse_prometheus(text: str) -> dict:
    """Strict-enough parser for the exposition format this module emits
    (used by the CI gate and tests to prove the export is well-formed).
    Returns ``{metric_sample_name: {label_string_or_'': value}}``; raises
    ``ValueError`` on any malformed line or on a histogram whose cumulative
    bucket counts decrease."""
    samples: dict[str, dict[str, float]] = {}
    last_hist_cum: dict[str, float] = {}
    for ln, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) < 4 or parts[1] not in ("TYPE", "HELP"):
                raise ValueError(f"line {ln}: malformed comment {line!r}")
            if parts[1] == "TYPE" and parts[3] not in (
                    "counter", "gauge", "histogram", "summary", "untyped"):
                raise ValueError(f"line {ln}: unknown metric type {parts[3]!r}")
            continue
        name_part, _, value_part = line.rpartition(" ")
        if not name_part:
            raise ValueError(f"line {ln}: no value in {line!r}")
        try:
            value = float(value_part)
        except ValueError:
            raise ValueError(f"line {ln}: bad value {value_part!r}") from None
        labels = ""
        name = name_part
        if "{" in name_part:
            if not name_part.endswith("}"):
                raise ValueError(f"line {ln}: malformed labels in {line!r}")
            name, _, labels = name_part.partition("{")
            labels = labels[:-1]
        if not name.replace("_", "").replace(":", "").isalnum():
            raise ValueError(f"line {ln}: bad metric name {name!r}")
        samples.setdefault(name, {})[labels] = value
        if name.endswith("_bucket"):
            prev = last_hist_cum.get(name)
            if prev is not None and value < prev:
                raise ValueError(
                    f"line {ln}: histogram {name} buckets not cumulative")
            last_hist_cum[name] = value
    if not samples:
        raise ValueError("no metric samples found")
    return samples
