"""Runtime precision configuration.

The reference (QuEST) fixes precision at *compile* time via the ``QuEST_PREC``
CMake flag selecting ``qreal`` = float / double / long double
(ref: QuEST/include/QuEST_precision.h:28-68).  On TPU the idiomatic equivalent
is a *runtime* dtype choice: precision 1 -> float32/complex64 (native TPU
width, fast path), precision 2 -> float64/complex128 (XLA-emulated f64 on TPU,
bit-comparable with the CPU reference).  Long-double (precision 4) has no TPU
equivalent and maps to precision 2.
"""

from __future__ import annotations

import os

import jax.numpy as jnp

# complex128 support requires x64 mode; _compat enables it at import (the
# one allowlisted import-time jax.config mutation — see analysis/purity.py
# P_IMPORT_TIME_STATE_MUTATION).  float32 quregs are still first-class
# (dtype is per-Qureg), x64 only widens what JAX *allows*, not what we
# allocate.
from . import _compat  # noqa: F401  (x64 side effect)

# REAL_EPS per precision (ref: QuEST_precision.h:35,49,64)
_REAL_EPS = {1: 1e-5, 2: 1e-13, 4: 1e-14}

_DEFAULT_PRECISION = int(os.environ.get("QUEST_TPU_PRECISION", "2"))

_WARNED_PREC4 = False


class PrecisionConfig:
    """Mutable global default precision; per-Qureg dtype can override."""

    def __init__(self, precision: int = _DEFAULT_PRECISION):
        self.set(precision)

    def set(self, precision: int) -> None:
        if precision not in (1, 2, 4):
            raise ValueError(f"precision must be 1, 2 or 4, got {precision}")
        if precision == 4:
            global _WARNED_PREC4
            if not _WARNED_PREC4:
                _WARNED_PREC4 = True
                import warnings
                warnings.warn(
                    "precision 4 (long double, QuEST_precision.h:51-66) has no "
                    "TPU equivalent; precision 4 is retained (get_precision() "
                    "reports 4, REAL_EPS uses the long-double table entry "
                    "1e-14) but amplitudes are stored as float64, the widest "
                    "TPU-representable real.",
                    RuntimeWarning, stacklevel=3)
        self.precision = precision
        self.real_eps = _REAL_EPS[precision]
        if precision == 1:
            self.real_dtype = jnp.float32
            self.complex_dtype = jnp.complex64
        else:
            self.real_dtype = jnp.float64
            self.complex_dtype = jnp.complex128


CONFIG = PrecisionConfig()


def set_precision(precision: int) -> None:
    """Set the global default precision for newly created Quregs."""
    CONFIG.set(precision)


def get_precision() -> int:
    return CONFIG.precision


def real_eps(dtype=None) -> float:
    """Numerical tolerance for the given real/complex dtype (default: global)."""
    if dtype is None:
        return CONFIG.real_eps
    dtype = jnp.dtype(dtype)
    if dtype in (jnp.dtype(jnp.float32), jnp.dtype(jnp.complex64)):
        return _REAL_EPS[1]
    return _REAL_EPS[2]


def complex_dtype_for(precision: int):
    return jnp.complex64 if precision == 1 else jnp.complex128


def real_dtype_of(complex_dtype):
    return jnp.float32 if jnp.dtype(complex_dtype) == jnp.dtype(jnp.complex64) else jnp.float64


def storage_dtype(dtype):
    """Map any requested dtype to the SoA real storage dtype.

    TPU XLA rejects complex element types at program boundaries, so amplitude
    arrays are stored as (re, im) real pairs; complex dtype requests map to
    the matching real width."""
    dtype = jnp.dtype(dtype)
    if dtype in (jnp.dtype(jnp.complex64), jnp.dtype(jnp.float32)):
        return jnp.dtype(jnp.float32)
    return jnp.dtype(jnp.float64)
