"""The Qureg: a quantum register backed by a (possibly sharded) jax.Array.

Ref analogue: struct Qureg (QuEST.h:203-234).  Differences by design:
- amplitudes are one (2, 2^n) real jax.Array — the reference's SoA re/im
  layout, but as a single stacked array (TPU XLA rejects complex element
  types at program boundaries; see ops/apply.py);
- there is no pairStateVec: the reference needs a same-size receive buffer for
  every MPI exchange (2x memory, ref QuEST_cpu.c:1292-1295); GSPMD's
  collective-permute streams shards without a user-visible mirror;
- chunkId/numChunks disappear: a sharded jax.Array carries its own layout.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from .environment import QuESTEnv
from .precision import CONFIG, storage_dtype
from .qasm import QASMLogger
from .validation import validate_create_num_qubits


@functools.lru_cache(maxsize=64)
def _repin_fn(sharding):
    """Compiled identity resharding to ``sharding``.  Cached per sharding:
    jit caches traces on the function OBJECT, so a fresh lambda per call
    would retrace + recompile every reshard."""
    return jax.jit(lambda x: x, out_shardings=sharding)


REPIN_COUNT = 0  # corrective reshards taken (should stay 0: see _repin)


def _repin(value: jax.Array, sharding) -> jax.Array:
    """Re-lay ``value`` out as ``sharding``.

    The compiled identity is the primary path: it is the universally valid
    reshard (``jax.device_put``'s eager path asserts on non-Named shardings
    with a different device order, observed on multi-process meshes — jax
    dispatch.py ``_different_device_order_reshard``), it dispatches
    asynchronously, and the trace is cached per sharding.  ``device_put``
    remains as the fallback should a sharding ever reject the jit route.

    This is now the DEBUG FALLBACK, not the mechanism: the eager API
    dispatches ops with the env sharding pinned inside the compiled program
    (api.py `_pinned` -> ops/apply.py `constrained_op`; init programs via
    `constrained_init`), so this corrective pass should never run —
    `REPIN_COUNT` tracks invocations and the distributed tests assert it
    stays zero across eager sequences."""
    global REPIN_COUNT
    REPIN_COUNT += 1
    try:
        return _repin_fn(sharding)(value)
    except Exception:
        return jax.device_put(value, sharding)


# Statevectors at or above this size keep PLANE-PAIR storage (separate re
# and im arrays) instead of the stacked (2, 2^n) array: the in-place Pallas
# engines donate plane buffers, and at the 30-qubit f32 single-chip ceiling
# (8 GiB state on a 15.75 GiB chip) the one extra state-sized transient a
# plane<->stack conversion costs is exactly what does not fit.  Tests patch
# this down to exercise plane mode at small sizes.
PLANE_STORAGE_MIN_BYTES = 8 << 30

# Materialising the stacked array from planes costs one extra state-sized
# transient; at/above this state size that transient exceeds the chip, so
# the amps getter refuses (separate knob so tests can run plane STORAGE at
# small sizes while still exercising materialisation).
PLANE_MATERIALIZE_LIMIT_BYTES = 8 << 30

# Plane-pair storage exists for the ACCELERATOR memory ceiling; on a CPU
# backend the same byte count carries no plane-only gate restriction, so a
# 30q f32 register on a single-device CPU env must keep the full gate set
# instead of dying with E_PLANE_ONLY_1Q.  The env var overrides the backend
# gate both ways: "1" forces plane storage on CPU (tests drive the Pallas
# engines in interpret mode), "0" disables it even on an accelerator.
PLANE_STORAGE_ENV = "QUEST_TPU_PLANE_STORAGE"


def _plane_storage_enabled() -> bool:
    value = os.environ.get(PLANE_STORAGE_ENV)
    if value is not None:
        # only explicit truthy spellings force-enable; "no"/"off"/garbage
        # all disable, so a user opting out can't accidentally opt in
        return value.strip().lower() in ("1", "on", "true", "yes", "force")
    return jax.default_backend() != "cpu"


class Qureg:
    """Mutable shell over an immutable amplitude array (functional core,
    imperative surface — the QuEST API mutates, jnp does not).

    ``amps`` has shape (2, 2^n): stacked (re, im) real parts — see
    ops/apply.py for why complex dtypes are avoided on TPU.

    Huge single-device f32 statevectors instead hold ``planes`` (re, im as
    separate arrays, see PLANE_STORAGE_MIN_BYTES) plus a logical->physical
    ``qubit_map``: in-place engines that end in a qubit permutation (the
    unordered 30q QFT's trailing bit reversal) record the permutation in
    the map instead of paying the data movement, and the API translates
    targets/amplitude indices through it (SURVEY §7.5's deferred-layout
    table, single-device regime)."""

    def __init__(self, num_qubits: int, env: QuESTEnv,
                 is_density_matrix: bool = False, dtype=None):
        self.num_qubits_represented = num_qubits
        self.is_density_matrix = is_density_matrix
        self.num_qubits_in_state_vec = num_qubits * (2 if is_density_matrix else 1)
        self.env = env
        self.dtype = storage_dtype(dtype if dtype is not None else CONFIG.real_dtype)
        self._amps: jax.Array | None = None
        self._planes: tuple | None = None
        # qubit_map[logical] = physical amplitude-index bit; identity unless
        # a plane-mode engine deferred a permutation
        self.qubit_map: tuple | None = None
        self.qasm = QASMLogger(num_qubits)
        if env is not None and hasattr(env, "_register"):
            env._register(self)  # weak: lets syncQuESTEnv barrier this env

    # --- plane-pair storage ------------------------------------------------
    def uses_plane_storage(self) -> bool:
        """True for single-device f32 statevectors at/above the plane
        threshold (the regime served by the in-place Pallas engines) on an
        accelerator backend — or wherever QUEST_TPU_PLANE_STORAGE forces
        the decision (see _plane_storage_enabled)."""
        return (not self.is_density_matrix
                and self.dtype == jnp.dtype(jnp.float32)
                and (self.env is None or self.env.sharding is None)
                and 2 * 4 * self.num_amps_total >= PLANE_STORAGE_MIN_BYTES
                and _plane_storage_enabled())

    @property
    def planes(self):
        """(re, im) plane pair.  Plane-mode registers return their storage
        directly; stacked registers return transient views."""
        if self._planes is not None:
            return self._planes
        if self._amps is not None:
            return (self._amps[0], self._amps[1])
        return None

    def set_planes(self, re: jax.Array, im: jax.Array,
                   qubit_map: tuple | None = None) -> None:
        """Install plane-pair amplitude storage (drops any stacked array).
        ``qubit_map`` records a pending logical->physical bit permutation."""
        self._planes = (re, im)
        self._amps = None
        self.qubit_map = qubit_map

    def take_planes(self):
        """Remove and return (re, im) for DONATION into an in-place engine:
        the register drops its references so the engine may alias the
        buffers.  Callers must set_planes() the result back."""
        if self._planes is not None:
            planes = self._planes
            self._planes = None
            return planes
        amps = self._amps
        if amps is None:
            # a destroyed (or never-initialised) register has no buffers to
            # donate; surface the API-level error, not a bare TypeError from
            # subscripting None
            from .validation import ErrorCode, _throw
            _throw(ErrorCode.QUREG_NOT_INITIALISED, "take_planes")
        self._amps = None
        return (amps[0], amps[1])

    def logical_to_physical(self, q: int) -> int:
        return q if self.qubit_map is None else self.qubit_map[q]

    def permute_amp_index(self, index: int) -> int:
        """Map a logical amplitude index to its physical location."""
        if self.qubit_map is None:
            return index
        out = 0
        for logical, physical in enumerate(self.qubit_map):
            out |= ((index >> logical) & 1) << physical
        return out

    # --- ref-compatible aliases -------------------------------------------
    @property
    def num_amps_total(self) -> int:
        return 1 << self.num_qubits_in_state_vec

    @property
    def numQubitsRepresented(self) -> int:
        return self.num_qubits_represented

    @property
    def isDensityMatrix(self) -> bool:
        return self.is_density_matrix

    # --- amplitude management ---------------------------------------------
    @property
    def amps(self) -> jax.Array | None:
        if self._planes is not None:
            if self.uses_plane_storage():
                # plane-mode registers never silently convert: an implicit
                # plane->stacked materialisation costs one extra state-sized
                # transient (does not fit at the plane threshold) and would
                # quietly route engines' workloads off the in-place path
                from .validation import ErrorCode, _throw
                _throw(ErrorCode.PLANE_ONLY)
            # sub-threshold registers (an in-place engine handed back plane
            # buffers, e.g. applyFullQFT at 17-29q) convert transparently
            return self.materialize_stacked()
        return self._amps

    def materialize_stacked(self) -> jax.Array:
        """Explicitly convert plane storage to the stacked (2, 2^n) array,
        reconciling any deferred qubit permutation.  Costs one extra
        state-sized transient — refused at/above the ceiling."""
        if self._planes is not None:
            if 2 * self.dtype.itemsize * self.num_amps_total >= PLANE_MATERIALIZE_LIMIT_BYTES:
                from .validation import ErrorCode, _throw
                _throw(ErrorCode.PLANE_ONLY, "materialize_stacked")
            re, im = self._planes
            self._planes = None
            st = jnp.stack([re, im])
            del re, im
            if self.qubit_map is not None:
                # reconcile the deferred permutation physically: pairwise
                # swaps until every logical bit sits at its own position
                # (callers of the stacked array assume physical == logical)
                from .ops.apply import swap_qubit_amps
                pos = list(self.qubit_map)
                for logical in range(len(pos)):
                    p = pos[logical]
                    if p == logical:
                        continue
                    other = pos.index(logical)
                    st = swap_qubit_amps(st, p, logical)
                    pos[other], pos[logical] = p, logical
                self.qubit_map = None
            self._amps = st
        return self._amps

    @amps.setter
    def amps(self, value) -> None:
        """Every amplitude install re-pins the env's sharding: the eager op
        path jits without out_shardings, so GSPMD is free to hand back a
        different (even fully replicated) layout — on a multi-host mesh that
        would silently un-distribute the state.  The reference never faces
        this (each MPI rank owns its chunk by construction,
        ref: QuEST_cpu_distributed.c:129-160); here the Qureg re-asserts the
        layout whenever the compiler drifted from it (a no-op otherwise)."""
        if (value is not None and self.env is not None
                and self.env.sharding is not None
                and getattr(value, "sharding", None) != self.env.sharding):
            value = _repin(value, self.env.sharding)
        self._amps = value
        # installing ANY value (including None — destroyQureg's eager free)
        # supersedes plane storage; keeping the planes would leak the 8 GiB
        # pair in exactly the regime plane storage exists for
        self._planes = None
        self.qubit_map = None

    def set_amps_array(self, amps: jax.Array) -> None:
        """Install a new amplitude array, preserving the Qureg's sharding."""
        self.amps = amps

    def sharded(self, amps: jax.Array) -> jax.Array:
        if self.env is not None and self.env.sharding is not None:
            return jax.device_put(amps, self.env.sharding)
        return amps

    def __repr__(self) -> str:
        kind = "density-matrix" if self.is_density_matrix else "state-vector"
        return (f"Qureg({kind}, qubits={self.num_qubits_represented}, "
                f"amps=2^{self.num_qubits_in_state_vec}, dtype={self.dtype}, "
                f"devices={self.env.num_ranks if self.env else 1})")


def create_qureg(num_qubits: int, env: QuESTEnv, dtype=None) -> Qureg:
    """Ref analogue: createQureg (QuEST.c:36-48) — statevector in |0..0>."""
    validate_create_num_qubits(num_qubits, env, "createQureg")
    from .ops import init as init_ops
    q = Qureg(num_qubits, env, is_density_matrix=False, dtype=dtype)
    if q.uses_plane_storage():
        q.set_planes(*init_ops.zero_state_planes(q.num_amps_total, q.dtype))
    else:
        q.set_amps_array(init_ops.build_state(
            init_ops.zero_state, (q.num_amps_total, q.dtype),
            env.sharding if env is not None else None))
    return q


def create_density_qureg(num_qubits: int, env: QuESTEnv, dtype=None) -> Qureg:
    """Ref analogue: createDensityQureg (QuEST.c:50-62) — ρ = |0..0><0..0|."""
    validate_create_num_qubits(num_qubits, env, "createDensityQureg", factor=2)
    from .ops import init as init_ops
    q = Qureg(num_qubits, env, is_density_matrix=True, dtype=dtype)
    q.set_amps_array(init_ops.build_state(
        init_ops.zero_state, (q.num_amps_total, q.dtype),
        env.sharding if env is not None else None))
    return q


def create_clone_qureg(qureg: Qureg, env: QuESTEnv) -> Qureg:
    """Ref analogue: createCloneQureg (QuEST.c)."""
    q = Qureg(qureg.num_qubits_represented, env,
              is_density_matrix=qureg.is_density_matrix, dtype=qureg.dtype)
    q.set_amps_array(qureg.amps)
    q.qasm = qureg.qasm.clone()
    return q


def destroy_qureg(qureg: Qureg, env: QuESTEnv | None = None) -> None:
    """Ref analogue: destroyQureg — drop the device buffer eagerly."""
    qureg.amps = None
