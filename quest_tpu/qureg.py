"""The Qureg: a quantum register backed by a (possibly sharded) jax.Array.

Ref analogue: struct Qureg (QuEST.h:203-234).  Differences by design:
- amplitudes are one (2, 2^n) real jax.Array — the reference's SoA re/im
  layout, but as a single stacked array (TPU XLA rejects complex element
  types at program boundaries; see ops/apply.py);
- there is no pairStateVec: the reference needs a same-size receive buffer for
  every MPI exchange (2x memory, ref QuEST_cpu.c:1292-1295); GSPMD's
  collective-permute streams shards without a user-visible mirror;
- chunkId/numChunks disappear: a sharded jax.Array carries its own layout.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .environment import QuESTEnv
from .precision import CONFIG, storage_dtype
from .qasm import QASMLogger
from .validation import validate_create_num_qubits


@functools.lru_cache(maxsize=64)
def _repin_fn(sharding):
    """Compiled identity resharding to ``sharding``.  Cached per sharding:
    jit caches traces on the function OBJECT, so a fresh lambda per call
    would retrace + recompile every reshard."""
    return jax.jit(lambda x: x, out_shardings=sharding)


def _repin(value: jax.Array, sharding) -> jax.Array:
    """Re-lay ``value`` out as ``sharding``.

    The compiled identity is the primary path: it is the universally valid
    reshard (``jax.device_put``'s eager path asserts on non-Named shardings
    with a different device order, observed on multi-process meshes — jax
    dispatch.py ``_different_device_order_reshard``), it dispatches
    asynchronously, and the trace is cached per sharding.  ``device_put``
    remains as the fallback should a sharding ever reject the jit route.

    A drifted eager op pays one resharding pass here; the deeper fix —
    pinning the layout inside each op's compiled program via
    ``with_sharding_constraint`` (a static ``out_sharding`` argument on the
    op layer) — would remove the corrective pass entirely and is the
    natural next step if eager multi-device dispatch becomes a hot path
    (compiled whole-circuit programs never take this branch)."""
    try:
        return _repin_fn(sharding)(value)
    except Exception:
        return jax.device_put(value, sharding)


class Qureg:
    """Mutable shell over an immutable amplitude array (functional core,
    imperative surface — the QuEST API mutates, jnp does not).

    ``amps`` has shape (2, 2^n): stacked (re, im) real parts — see
    ops/apply.py for why complex dtypes are avoided on TPU."""

    def __init__(self, num_qubits: int, env: QuESTEnv,
                 is_density_matrix: bool = False, dtype=None):
        self.num_qubits_represented = num_qubits
        self.is_density_matrix = is_density_matrix
        self.num_qubits_in_state_vec = num_qubits * (2 if is_density_matrix else 1)
        self.env = env
        self.dtype = storage_dtype(dtype if dtype is not None else CONFIG.real_dtype)
        self._amps: jax.Array | None = None
        self.qasm = QASMLogger(num_qubits)
        if env is not None and hasattr(env, "_register"):
            env._register(self)  # weak: lets syncQuESTEnv barrier this env

    # --- ref-compatible aliases -------------------------------------------
    @property
    def num_amps_total(self) -> int:
        return 1 << self.num_qubits_in_state_vec

    @property
    def numQubitsRepresented(self) -> int:
        return self.num_qubits_represented

    @property
    def isDensityMatrix(self) -> bool:
        return self.is_density_matrix

    # --- amplitude management ---------------------------------------------
    @property
    def amps(self) -> jax.Array | None:
        return self._amps

    @amps.setter
    def amps(self, value) -> None:
        """Every amplitude install re-pins the env's sharding: the eager op
        path jits without out_shardings, so GSPMD is free to hand back a
        different (even fully replicated) layout — on a multi-host mesh that
        would silently un-distribute the state.  The reference never faces
        this (each MPI rank owns its chunk by construction,
        ref: QuEST_cpu_distributed.c:129-160); here the Qureg re-asserts the
        layout whenever the compiler drifted from it (a no-op otherwise)."""
        if (value is not None and self.env is not None
                and self.env.sharding is not None
                and getattr(value, "sharding", None) != self.env.sharding):
            value = _repin(value, self.env.sharding)
        self._amps = value

    def set_amps_array(self, amps: jax.Array) -> None:
        """Install a new amplitude array, preserving the Qureg's sharding."""
        self.amps = amps

    def sharded(self, amps: jax.Array) -> jax.Array:
        if self.env is not None and self.env.sharding is not None:
            return jax.device_put(amps, self.env.sharding)
        return amps

    def __repr__(self) -> str:
        kind = "density-matrix" if self.is_density_matrix else "state-vector"
        return (f"Qureg({kind}, qubits={self.num_qubits_represented}, "
                f"amps=2^{self.num_qubits_in_state_vec}, dtype={self.dtype}, "
                f"devices={self.env.num_ranks if self.env else 1})")


def create_qureg(num_qubits: int, env: QuESTEnv, dtype=None) -> Qureg:
    """Ref analogue: createQureg (QuEST.c:36-48) — statevector in |0..0>."""
    validate_create_num_qubits(num_qubits, env, "createQureg")
    from .ops import init as init_ops
    q = Qureg(num_qubits, env, is_density_matrix=False, dtype=dtype)
    q.set_amps_array(init_ops.zero_state(q.num_amps_total, q.dtype))
    return q


def create_density_qureg(num_qubits: int, env: QuESTEnv, dtype=None) -> Qureg:
    """Ref analogue: createDensityQureg (QuEST.c:50-62) — ρ = |0..0><0..0|."""
    validate_create_num_qubits(num_qubits, env, "createDensityQureg", factor=2)
    from .ops import init as init_ops
    q = Qureg(num_qubits, env, is_density_matrix=True, dtype=dtype)
    q.set_amps_array(init_ops.zero_state(q.num_amps_total, q.dtype))
    return q


def create_clone_qureg(qureg: Qureg, env: QuESTEnv) -> Qureg:
    """Ref analogue: createCloneQureg (QuEST.c)."""
    q = Qureg(qureg.num_qubits_represented, env,
              is_density_matrix=qureg.is_density_matrix, dtype=qureg.dtype)
    q.set_amps_array(qureg.amps)
    q.qasm = qureg.qasm.clone()
    return q


def destroy_qureg(qureg: Qureg, env: QuESTEnv | None = None) -> None:
    """Ref analogue: destroyQureg — drop the device buffer eagerly."""
    qureg.amps = None
