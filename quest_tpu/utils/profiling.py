"""Tracing/profiling — a new capability over the reference, which has no
observability beyond reportState (SURVEY §5: "The TPU build should add real
tracing as a new capability, not a port").

Three layers:
- device traces: :func:`trace` / :func:`annotate` wrap the JAX profiler so a
  simulation shows up in XProf/TensorBoard with named regions;
- circuit cost model: :func:`circuit_stats` reports, before compiling, how
  many HBM passes / MXU contractions / collective ops a circuit will cost on
  an ``n``-qubit state over ``num_ranks`` shards — the static analogue of the
  reference's per-gate comm decision (QuEST_cpu_distributed.c:356-361).
  Since the epoch engine (ops/epoch_pallas.py) the default pass count is the
  ENGINE-AWARE one (``select_engine`` + the fused epoch plan);
  ``fused=False`` keeps the historical one-pass-per-op model;
- wall-clock: :func:`timed` measures a jitted program with dispatch overhead
  subtracted, the methodology bench.py uses.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time

import jax


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a device trace of the enclosed block into ``log_dir``."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region that shows up on the trace timeline."""
    return jax.profiler.TraceAnnotation(name)


@dataclasses.dataclass
class CircuitStats:
    """Static cost report for one circuit application."""
    num_ops: int                 # ops after any fusion
    hbm_passes: int              # full-state read+write sweeps
    mxu_contractions: int        # dense matmul ops (MXU work)
    diagonal_ops: int            # broadcast multiplies (VPU only)
    cross_shard_ops: int         # ops touching the sharded prefix qubits
    bytes_per_pass: int          # state size in bytes (one direction)
    permutation_ops: int = 0     # swap/bitperm: data movement, not MXU work
    engine: str = "xla"          # backend the pass count describes
    deferred_perm_ops: int = 0   # perms the epoch engine absorbs (0 passes)
    super_passes: int = 0        # fused passes carrying superoperator stages
    super_stages: int = 0        # density channels fused with zero extra passes
    density_qubits: int | None = None  # Choi-doubled register: n density qubits

    def __str__(self):
        gb = self.bytes_per_pass / 1e9
        dens = (f" [density {self.density_qubits}q doubled]"
                if self.density_qubits is not None else "")
        sup = (f", {self.super_stages} superop stages in "
               f"{self.super_passes} passes" if self.super_stages else "")
        return (f"{self.num_ops} ops: {self.mxu_contractions} dense (MXU), "
                f"{self.diagonal_ops} diagonal (VPU), "
                f"{self.permutation_ops} permutation, "
                f"{self.cross_shard_ops} cross-shard; "
                f"~{self.hbm_passes} HBM passes x {gb:.3g} GB "
                f"({self.engine} engine{sup}){dens}")


def circuit_stats(circuit, num_qubits: int | None = None,
                  num_ranks: int = 1, bytes_per_real: int = 4,
                  fused: bool = True, chip=None) -> CircuitStats:
    """Analyse a :class:`~quest_tpu.circuit.Circuit` without compiling it.

    An op is "cross-shard" when it targets (or is controlled on) one of the
    top ``log2(num_ranks)`` qubits — the ops whose GSPMD partitioning inserts
    collectives, the reference's pairwise-exchange case
    (ref: QuEST_cpu_distributed.c:303-312).  ``swap``/``bitperm`` ops are
    data movement (``permutation_ops``), not MXU contractions.

    ``fused=True`` (default) routes the HBM-pass count through the SAME
    engine cost model ``compile_circuit(engine="auto")`` dispatches on
    (parallel/planner.py ``select_engine`` at TPU-class specs): when the
    Pallas epoch executor (ops/epoch_pallas.py) would run the circuit, the
    reported passes are the plan's FUSED count — a 28q QFT is 22 passes,
    not 420 — with ``engine``/``deferred_perm_ops`` recording the decision.
    ``fused=False`` is the historical per-op model: one full read+write
    sweep per un-fused op, whatever the engine would actually do."""
    n = num_qubits if num_qubits is not None else circuit.num_qubits
    shard_qubits = max(num_ranks.bit_length() - 1, 0)
    lo = n - shard_qubits  # qubits >= lo live on the sharded axis prefix
    dense = diag = perm = cross = 0
    for op in circuit.ops:
        wires = tuple(op.targets) + tuple(op.controls)
        if op.kind in ("diagonal", "mrz"):  # mrz: elementwise parity phase
            diag += 1
        elif op.kind in ("swap", "bitperm"):
            perm += 1
        else:
            dense += 1
        if any(q >= lo for q in wires):
            cross += 1
    num_ops = len(circuit.ops)
    hbm_passes = num_ops  # one read+write sweep per un-fused op
    engine = "xla"
    deferred = 0
    super_passes = super_stages = 0
    if fused and num_ranks <= 1 and circuit.ops:
        # spec-level engine decision (backend pinned to "tpu" so the stats
        # are deployment stats, not dev-box stats): the epoch plan's fused
        # pass count replaces the per-op sweep count when pallas wins
        from ..parallel import planner as _planner
        shim = circuit
        if n != circuit.num_qubits:
            from ..circuit import Circuit
            shim = Circuit(n)
            shim.ops = list(circuit.ops)
        precision = 1 if bytes_per_real == 4 else 2
        try:
            choice = _planner.select_engine(shim, 1,
                                            chip or _planner.V5E,
                                            precision, "auto",
                                            backend="tpu")
        except Exception:
            choice = {"engine": "xla", "plan": None}
        if choice["engine"] == "pallas" and choice["plan"] is not None:
            engine = "pallas"
            hbm_passes = choice["plan"].hbm_passes
            deferred = choice["plan"].deferred_ops
            super_passes = choice["plan"].super_passes
            super_stages = choice["plan"].super_stages
    return CircuitStats(
        num_ops=num_ops,
        hbm_passes=hbm_passes,
        mxu_contractions=dense,
        diagonal_ops=diag,
        cross_shard_ops=cross,
        bytes_per_pass=2 * (1 << n) * bytes_per_real,
        permutation_ops=perm,
        engine=engine,
        deferred_perm_ops=deferred,
        super_passes=super_passes,
        super_stages=super_stages,
        density_qubits=getattr(circuit, "density_qubits", None),
    )


def timed(fn, *args, reps: int = 1):
    """Wall-clock a jitted ``fn(*args)`` with compile + dispatch overhead
    excluded: warm call first, then ``reps`` timed calls bounded by
    ``block_until_ready``.  Returns (seconds_per_call, last_result)."""
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / max(reps, 1), out
