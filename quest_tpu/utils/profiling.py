"""Tracing/profiling hooks — a new capability over the reference, which has
no observability beyond reportState (SURVEY §5).  Thin wrappers over the JAX
profiler so simulations can be inspected in XProf/TensorBoard."""

from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a device trace of the enclosed block into ``log_dir``."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region that shows up on the trace timeline."""
    return jax.profiler.TraceAnnotation(name)
