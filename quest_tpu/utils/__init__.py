"""Auxiliary subsystems: checkpointing, profiling, environment reporting.

The reference has none of these (SURVEY §5: no tracing, no checkpointing —
its nearest checkpoint equivalent is a CSV dump via reportState,
QuEST_common.c:216-232).  They are first-class here because long distributed
simulations on pods need them."""

from .checkpoint import save_qureg, load_qureg  # noqa: F401
from .profiling import trace, annotate, circuit_stats, timed  # noqa: F401
