"""Sharded checkpoint save/restore for Quregs.

The reference's only full-state escape hatches are setAmps/getAmp and a CSV
dump (ref: QuEST.c:781-795, QuEST_common.c:216-232) — nothing resumable.
Here a Qureg checkpoints to a directory of per-shard ``.npy`` files plus a
JSON manifest, written shard-by-shard from each device buffer (no full-state
host materialisation), and restores onto any mesh whose sharding divides the
amplitude count — the idiomatic orbax-style layout without requiring the
orbax dependency for a plain array pair.  Save and restore are both
multi-host capable over a shared filesystem: each process writes/reads only
its addressable shards, with file names keyed on global offsets.
"""

from __future__ import annotations

import json
import os

import jax
import numpy as np


def save_qureg(qureg, directory: str) -> None:
    """Write the Qureg's amplitudes and metadata under ``directory``.

    Multi-host capable: every process writes only its ADDRESSABLE shards,
    under globally-unique names keyed by the shard's global start offset
    (so no cross-process coordination is needed for the data files);
    process 0 then writes the manifest, derived from the global sharding
    layout rather than from any gathered data.  Requires ``directory`` on
    a filesystem all hosts share — the same contract as orbax.  Peak host
    memory is one device shard."""
    os.makedirs(directory, exist_ok=True)
    amps = qureg.amps
    # owner of each distinct shard window = the LOWEST process holding it,
    # so cross-host replication never writes the same file from two hosts
    owner: dict = {}
    for device, idx in amps.sharding.devices_indices_map(amps.shape).items():
        start = int(idx[1].start or 0)
        p = device.process_index
        owner[start] = p if start not in owner else min(owner[start], p)
    me = jax.process_index()
    written = set()
    for shard in amps.addressable_shards:
        start = int(shard.index[1].start or 0)
        if owner[start] != me or start in written:
            continue
        written.add(start)
        np.save(os.path.join(directory, f"shard_{start:020d}.npy"),
                np.asarray(shard.data))
    if jax.process_count() > 1:
        # all data files must exist before the manifest announces them
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("quest_tpu_checkpoint_data")
    if jax.process_index() == 0:
        starts = sorted(owner)
        meta = {
            "num_qubits": qureg.num_qubits_represented,
            "is_density_matrix": bool(qureg.is_density_matrix),
            "dtype": str(np.dtype(qureg.dtype)),
            "num_shards": len(starts),
            "shards": [{"file": f"shard_{s:020d}.npy", "start": s}
                       for s in starts],
        }
        with open(os.path.join(directory, "manifest.json"), "w") as f:
            json.dump(meta, f, indent=1)
    if jax.process_count() > 1:
        # no process may return (and start reading) before the manifest exists
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("quest_tpu_checkpoint_manifest")


def load_qureg(directory: str, env):
    """Recreate a Qureg from a checkpoint directory onto ``env``'s mesh.

    Restores shard-by-shard: each target device's slice is assembled from
    the (memory-mapped) checkpoint files covering its index range and
    device_put directly, then the global array is built with
    ``jax.make_array_from_single_device_arrays`` — peak host memory is one
    device shard, never the full state, so restore scales to states larger
    than host RAM."""
    import quest_tpu as qt

    with open(os.path.join(directory, "manifest.json")) as f:
        meta = json.load(f)
    n = meta["num_qubits"]
    dtype = np.dtype(meta["dtype"])
    if meta["is_density_matrix"]:
        q = qt.createDensityQureg(n, env, dtype=dtype)
    else:
        q = qt.createQureg(n, env, dtype=dtype)
    total = q.num_amps_total
    shape = (2, total)

    # memory-mapped views of the checkpoint files (reads only touched ranges)
    files = [(rec["start"],
              np.load(os.path.join(directory, rec["file"]), mmap_mode="r"))
             for rec in meta["shards"]]
    files.sort(key=lambda t: t[0])

    def read_range(lo: int, hi: int) -> np.ndarray:
        part = np.empty((2, hi - lo), dtype=dtype)
        for start, data in files:
            end = start + data.shape[1]
            if end <= lo or start >= hi:
                continue
            a, b = max(lo, start), min(hi, end)
            part[:, a - lo:b - lo] = data[:, a - start:b - start]
        return part

    sharding = q.amps.sharding
    buffers = []
    for device, index in sharding.addressable_devices_indices_map(shape).items():
        sl = index[1]
        lo = sl.start or 0
        hi = sl.stop if sl.stop is not None else total
        buffers.append(jax.device_put(read_range(lo, hi), device))
    arr = jax.make_array_from_single_device_arrays(shape, sharding, buffers)
    q.set_amps_array(arr)
    return q
