"""Sharded checkpoint save/restore for Quregs.

The reference's only full-state escape hatches are setAmps/getAmp and a CSV
dump (ref: QuEST.c:781-795, QuEST_common.c:216-232) — nothing resumable.
Here a Qureg checkpoints to a directory of per-shard ``.npy`` files plus a
JSON manifest, written shard-by-shard from each device buffer (no full-state
host materialisation), and restores onto any mesh whose sharding divides the
amplitude count — the idiomatic orbax-style layout without requiring the
orbax dependency for a plain array pair.
"""

from __future__ import annotations

import json
import os

import jax
import numpy as np


def save_qureg(qureg, directory: str) -> None:
    """Write the Qureg's amplitudes and metadata under ``directory``.

    Multi-host note: each process sees only its addressable shards; a
    correct multi-host checkpoint needs one directory per process (or a
    shared filesystem with per-process file names).  Until that lands we
    refuse rather than write a silently partial checkpoint."""
    if jax.process_count() > 1:
        raise NotImplementedError(
            "save_qureg on multi-host meshes needs per-process shard files; "
            "gather to one host or checkpoint with orbax for now")
    os.makedirs(directory, exist_ok=True)
    meta = {
        "num_qubits": qureg.num_qubits_represented,
        "is_density_matrix": bool(qureg.is_density_matrix),
        "dtype": str(np.dtype(qureg.dtype)),
        "num_shards": 0,
    }
    shards = []
    amps = qureg.amps
    # write each addressable shard without gathering the full state
    for i, shard in enumerate(sorted(amps.addressable_shards,
                                     key=lambda s: s.index[1].start or 0)):
        fn = f"shard_{i:05d}.npy"
        np.save(os.path.join(directory, fn), np.asarray(shard.data))
        start = shard.index[1].start or 0
        shards.append({"file": fn, "start": int(start)})
    meta["num_shards"] = len(shards)
    meta["shards"] = shards
    with open(os.path.join(directory, "manifest.json"), "w") as f:
        json.dump(meta, f, indent=1)


def load_qureg(directory: str, env):
    """Recreate a Qureg from a checkpoint directory onto ``env``'s mesh.

    Restores shard-by-shard: each target device's slice is assembled from
    the (memory-mapped) checkpoint files covering its index range and
    device_put directly, then the global array is built with
    ``jax.make_array_from_single_device_arrays`` — peak host memory is one
    device shard, never the full state, so restore scales to states larger
    than host RAM."""
    import quest_tpu as qt

    with open(os.path.join(directory, "manifest.json")) as f:
        meta = json.load(f)
    n = meta["num_qubits"]
    dtype = np.dtype(meta["dtype"])
    if meta["is_density_matrix"]:
        q = qt.createDensityQureg(n, env, dtype=dtype)
    else:
        q = qt.createQureg(n, env, dtype=dtype)
    total = q.num_amps_total
    shape = (2, total)

    # memory-mapped views of the checkpoint files (reads only touched ranges)
    files = [(rec["start"],
              np.load(os.path.join(directory, rec["file"]), mmap_mode="r"))
             for rec in meta["shards"]]
    files.sort(key=lambda t: t[0])

    def read_range(lo: int, hi: int) -> np.ndarray:
        part = np.empty((2, hi - lo), dtype=dtype)
        for start, data in files:
            end = start + data.shape[1]
            if end <= lo or start >= hi:
                continue
            a, b = max(lo, start), min(hi, end)
            part[:, a - lo:b - lo] = data[:, a - start:b - start]
        return part

    sharding = q.amps.sharding
    buffers = []
    for device, index in sharding.addressable_devices_indices_map(shape).items():
        sl = index[1]
        lo = sl.start or 0
        hi = sl.stop if sl.stop is not None else total
        buffers.append(jax.device_put(read_range(lo, hi), device))
    arr = jax.make_array_from_single_device_arrays(shape, sharding, buffers)
    q.set_amps_array(arr)
    return q
