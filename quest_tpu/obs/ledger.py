"""Model-vs-measured runtime ledger: the drift detector over the planner.

PRs 2/4/6 each added a *predictive* cost model — comm events and bytes
(planner.comm_summary), hideable fractions (executor.predict_overlap),
fused HBM passes (epoch_pallas.plan_circuit) — and the bench rows record
model next to measurement, but nothing systematically compared them at
runtime: model drift was caught only when someone hand-read a BENCH row.
The ledger is that comparison as a pipeline: every compiled run can record
one :class:`DriftRecord` holding the planner's predicted seconds / HBM
passes / collective count next to the measured wall time and the
compiled-HLO collective count (analysis/jaxpr_audit.py's observation), and
the ledger emits an ``O_MODEL_DRIFT`` warning when measurement leaves the
calibrated band.

Two drift rules, deliberately asymmetric:

- **Collective counts** are platform-independent (the partitioner emits the
  same HLO on the CPU mesh CI uses and the TPU pod production uses), so
  they are checked everywhere: more than ``COLLECTIVES_PER_EVENT`` compiled
  collectives per predicted comm event — the same factor bound
  analysis/jaxpr_audit.py gates on — or ANY measured collective on a run
  predicted comm-free is drift.
- **Wall time** is only checked against the model when the constants the
  model ran on describe the hardware the run executed on.  With a
  **calibration profile** loaded (obs/calibrate.py — the planner is then
  reading efficiencies fitted on THIS backend by ``analysis
  --calibrate``) the wall band is checked on *any* platform, against the
  profile's fitted residual spread instead of the hard-coded default
  band: calibration is exactly what makes a CPU wall clock comparable to
  the model.  Without a profile the old gate stands — ``platform ==
  "tpu"`` or an explicit ``calibrated=True``, with the default band
  :data:`DEFAULT_WALL_BAND` ([1/3, 3], the spread of the BENCH rows
  MEASURED_EFFICIENCY was fit on) — because the defaults are a TPU
  roofline and judging a CPU clock against them would flag every CI run.

Every record carries **calibration provenance** (profile id, age,
residual-derived band — or the explicit ``{"source": "default"}``
marker) plus the runtime counters of its run when the caller has them
(compile wall seconds, HBM watermark — obs/counters.py).  This is what
turns ``MEASURED_EFFICIENCY`` calibration (ROADMAP item 2) from a
one-off into a pipeline: ``O_MODEL_DRIFT`` says re-calibrate, ``analysis
--calibrate`` re-fits the constants, and the refreshed profile's band is
what the next run is judged by.
"""

from __future__ import annotations

import dataclasses
import threading
import warnings

__all__ = ["DriftRecord", "Ledger", "global_ledger", "MODEL_DRIFT",
           "DEFAULT_WALL_BAND", "COLLECTIVES_PER_EVENT"]

#: the diagnostic code drift findings carry (analysis CLI severity: WARNING)
MODEL_DRIFT = "O_MODEL_DRIFT"

#: measured/predicted wall-clock band considered "the model holds";
#: calibrated from the BENCH rows MEASURED_EFFICIENCY was fit on
DEFAULT_WALL_BAND = (1.0 / 3.0, 3.0)

#: how many compiled-HLO collectives one predicted comm event may
#: legitimately lower to — mirrors analysis/jaxpr_audit._HLO_OPS_PER_EVENT
#: (a pairwise exchange spells as all-gather + all-reduce partial-sum pairs
#: per SoA plane); kept as a local constant so obs stays dependency-free
COLLECTIVES_PER_EVENT = 6

#: ledger retention: FIFO beyond this (long-running serve processes must
#: not grow the ledger without bound)
_MAX_RECORDS = 1024


@dataclasses.dataclass
class DriftRecord:
    """One run's model-vs-measured row.  ``findings`` is empty when the
    measurement sits inside every applicable band."""
    label: str
    engine: str
    num_devices: int
    platform: str
    predicted_seconds: float | None = None
    measured_seconds: float | None = None
    predicted_hbm_passes: int | None = None
    predicted_collectives: int | None = None
    measured_hlo_collectives: int | None = None
    wall_ratio: float | None = None          # measured / predicted
    wall_checked: bool = False
    findings: tuple = ()
    # which constants judged this run (planner.calibration_provenance())
    # and the wall band that applied — so a drift row is auditable without
    # knowing what profile happened to be live at record time
    calibration: dict | None = None
    wall_band: tuple | None = None
    # runtime counters of the run (obs/counters.py), when the caller has
    # them: compile wall seconds and the live-HBM peak watermark
    compile_seconds: float | None = None
    hbm_peak_bytes: int | None = None

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class Ledger:
    """Thread-safe store of :class:`DriftRecord`; :meth:`record` computes
    the drift checks and warns (``O_MODEL_DRIFT``) on any finding."""

    def __init__(self, wall_band: tuple = DEFAULT_WALL_BAND,
                 collectives_per_event: int = COLLECTIVES_PER_EVENT):
        self.wall_band = (float(wall_band[0]), float(wall_band[1]))
        self.collectives_per_event = int(collectives_per_event)
        self._lock = threading.Lock()
        self._records: list[DriftRecord] = []   # guarded-by: _lock
        self.drift_total = 0                    # guarded-by: _lock

    def record(self, label: str, *, engine: str = "xla",
               num_devices: int = 1, platform: str = "cpu",
               predicted_seconds: float | None = None,
               measured_seconds: float | None = None,
               predicted_hbm_passes: int | None = None,
               predicted_collectives: int | None = None,
               measured_hlo_collectives: int | None = None,
               calibrated: bool = False, warn: bool = True,
               compile_seconds: float | None = None,
               hbm_peak_bytes: int | None = None) -> DriftRecord:
        """Record one run.  Pass whatever the caller has — every check only
        fires when both of its sides are present.

        The wall gate resolves in this order: an ACTIVE calibration
        profile (obs/calibrate.py) checks the wall on any platform
        against its fitted residual band — the predictions were made
        with constants measured on this backend, so the comparison is
        meaningful everywhere; else ``calibrated=True`` opts a non-TPU
        run into the ledger's default band (legacy explicit opt-in);
        else only ``platform == "tpu"`` runs are judged.  Either way the
        record carries the provenance and the band that applied."""
        from ..parallel.planner import calibration_provenance
        calibration = calibration_provenance()
        findings: list[str] = []
        wall_ratio = None
        wall_checked = False
        lo, hi = self.wall_band
        if calibration.get("source") == "profile":
            lo, hi = calibration["wall_band"]
        if predicted_seconds and measured_seconds is not None:
            wall_ratio = measured_seconds / predicted_seconds
            wall_checked = (calibration.get("source") == "profile"
                            or calibrated or platform == "tpu")
            if wall_checked and not lo <= wall_ratio <= hi:
                source = ("the calibration profile "
                          + calibration["profile_id"]
                          if calibration.get("source") == "profile"
                          else "MEASURED_EFFICIENCY")
                findings.append(
                    f"wall {measured_seconds:.3g}s is {wall_ratio:.2f}x the "
                    f"model's {predicted_seconds:.3g}s (band [{lo:.2f}, "
                    f"{hi:.2f}]): re-calibrate {source} for "
                    f"engine {engine!r} (analysis --calibrate)")
        if (predicted_collectives is not None
                and measured_hlo_collectives is not None):
            bound = predicted_collectives * self.collectives_per_event
            if predicted_collectives == 0 and measured_hlo_collectives > 0:
                findings.append(
                    f"{measured_hlo_collectives} compiled collectives on a "
                    "run modeled comm-free: a sharding annotation was lost")
            elif measured_hlo_collectives > bound:
                findings.append(
                    f"{measured_hlo_collectives} compiled collectives vs "
                    f"{predicted_collectives} predicted events (bound "
                    f"{bound}): the comm model undercosts this circuit")
        rec = DriftRecord(label, engine, num_devices, platform,
                          predicted_seconds, measured_seconds,
                          predicted_hbm_passes, predicted_collectives,
                          measured_hlo_collectives, wall_ratio, wall_checked,
                          tuple(findings), calibration, (lo, hi),
                          compile_seconds, hbm_peak_bytes)
        with self._lock:
            self._records.append(rec)
            if len(self._records) > _MAX_RECORDS:
                del self._records[:_MAX_RECORDS // 2]
            self.drift_total += len(findings)
        if warn:
            for f in findings:
                warnings.warn(f"{MODEL_DRIFT}[{label}] {f}", RuntimeWarning,
                              stacklevel=2)
        return rec

    # -- reading ------------------------------------------------------------
    def records(self) -> list[DriftRecord]:
        with self._lock:
            return list(self._records)

    def as_dicts(self) -> list[dict]:
        return [r.as_dict() for r in self.records()]

    def snapshot(self) -> dict:
        with self._lock:
            return {"records": len(self._records),
                    "drift_total": self.drift_total}

    def clear(self) -> None:
        with self._lock:
            self._records = []
            self.drift_total = 0


_GLOBAL: Ledger | None = None
_GLOBAL_LOCK = threading.Lock()


def global_ledger() -> Ledger:
    """The process-wide ledger (bench rows, the serve layer and the
    ``--trace-report`` CLI all record into one place)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = Ledger()
        return _GLOBAL
