"""quest_tpu.obs — the observability layer: spans, ledger, flight recorder.

The reference has no observability surface at all (``reportQuregParams``
printfs; SURVEY §5 calls real tracing out as a new capability, not a port),
and the predictive cost models of the scheduler/planner/epoch planner had no
systematic runtime counterpart: model drift was only caught when someone
hand-read a bench row.  This package closes that loop, dependency-free:

- ``trace.py``: a thread-safe span recorder with ``request_id`` correlation
  propagated from the serving front door through cache lookup, schedule
  search, engine selection, epoch planning and execution; host spans wrap
  device work in ``jax.profiler.TraceAnnotation`` so they line up with
  XProf timelines.
- ``export.py``: Chrome-trace/Perfetto JSON export, a schema validator (the
  CI gate), and a human ``--trace-report`` view.
- ``ledger.py``: the model-vs-measured runtime ledger — every compiled run
  can record the planner's predicted seconds / HBM passes / collective
  count next to measured wall time and the compiled-HLO collective count,
  emitting ``O_MODEL_DRIFT`` when measurement leaves the calibrated band.
- ``flight.py``: a bounded ring buffer of recent serve request records
  (admission, queue wait, batch id, deadline outcome, error code) dumped on
  ``E_QUEUE_FULL``/crash and exposed via ``--selftest --json``.

See docs/OBSERVABILITY.md.
"""

from .trace import (Span, TraceRecorder, collect_notes, current_request_id,  # noqa: F401
                    disable_tracing, emit_span, enable_tracing, key_hash,
                    note, obs_snapshot, recorder, request, reset_tracing,
                    span, tracing_enabled)
from .ledger import DriftRecord, Ledger, global_ledger  # noqa: F401
from .flight import FlightRecord, FlightRecorder  # noqa: F401
from .export import chrome_trace, trace_report, validate_chrome_trace  # noqa: F401

__all__ = [
    "Span", "TraceRecorder", "recorder", "span", "emit_span", "request",
    "current_request_id", "note", "collect_notes", "enable_tracing",
    "disable_tracing", "reset_tracing", "tracing_enabled", "obs_snapshot",
    "Ledger", "DriftRecord", "global_ledger",
    "FlightRecorder", "FlightRecord",
    "chrome_trace", "trace_report", "validate_chrome_trace",
]
