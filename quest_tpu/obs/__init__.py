"""quest_tpu.obs — the observability layer: spans, ledger, flight recorder.

The reference has no observability surface at all (``reportQuregParams``
printfs; SURVEY §5 calls real tracing out as a new capability, not a port),
and the predictive cost models of the scheduler/planner/epoch planner had no
systematic runtime counterpart: model drift was only caught when someone
hand-read a bench row.  This package closes that loop, dependency-free:

- ``trace.py``: a thread-safe span recorder with ``request_id`` correlation
  propagated from the serving front door through cache lookup, schedule
  search, engine selection, epoch planning and execution; host spans wrap
  device work in ``jax.profiler.TraceAnnotation`` so they line up with
  XProf timelines.
- ``export.py``: Chrome-trace/Perfetto JSON export, a schema validator (the
  CI gate), and a human ``--trace-report`` view.
- ``ledger.py``: the model-vs-measured runtime ledger — every compiled run
  can record the planner's predicted seconds / HBM passes / collective
  count next to measured wall time and the compiled-HLO collective count,
  emitting ``O_MODEL_DRIFT`` when measurement leaves the calibrated band.
- ``flight.py``: a bounded ring buffer of recent serve request records
  (admission, queue wait, batch id, deadline outcome, error code) dumped on
  ``E_QUEUE_FULL``/deadline drops/crash and exposed via ``--selftest
  --json``.

PR 8 grew the layer from one process's eyes to the fleet's:

- ``aggregate.py``: cross-process trace aggregation — per-process recorder
  shards stamped with ``jax.process_index()`` and a broadcast-aligned
  clock base, merged into ONE Chrome trace with a track per process
  (request spans correlated by ``request_id``; the single-process merge is
  the identity).
- ``slo.py``: the serve SLO monitor — windowed per-structural-class
  latency, deadline hit rate, queue saturation, and burn-rate early
  warning (``O_SLO_BURN``), wired into ``QuESTService`` and the one
  Prometheus scrape.
- ``regress.py``: the perf-regression ledger — the committed
  ``BENCH_r0*.json`` trajectory parsed (truncated tails recovered) and
  gated row-by-row against the best comparable prior round
  (``python bench.py --compare``; the CI ``bench-regress`` job).

PR 9 closed the cost-model loop:

- ``calibrate.py``: the on-device calibration harness — times the real
  execution primitives (per-gate appliers by qubit position class,
  Pallas epoch passes, collectives by payload bytes), fits the
  planner's constants from the measurements, and persists a versioned
  **calibration profile** that ``planner.time_model`` /
  ``select_engine`` / the scheduler's placement search load in place of
  the hard-coded defaults (``analysis --calibrate``; the CI
  ``calibrate-selftest`` job).  With a profile active the ledger checks
  walls on ANY platform against the fitted residual band.
PR 13 added the correctness half — numeric-health telemetry:

- ``numerics.py``: on-device numeric probes (norm / total probability,
  max |amp|^2, NaN/Inf counts, density trace + Hermiticity deviation)
  compiled as auxiliary outputs BESIDE the primary dataflow (primary
  output bit-identical by construction), the precision-and-depth-derived
  ulp-growth band, and the **numeric drift ledger** — ``O_NUMERIC_DRIFT``
  / ``O_NUMERIC_NAN`` findings with per-structural-class aggregation.
  Served through ``QuESTService(probes=True)`` /
  ``QUEST_TPU_NUMERIC_PROBES=1``, the ``quest_serve_numeric_*`` scrape
  gauges, the deploy router's NaN quarantine and ``analysis
  --numeric-report``.

- ``counters.py``: runtime counters — process-wide compile wall seconds,
  dispatch walls, and the live-HBM watermark (``device.memory_stats()``)
  — recorded into trace spans, ledger records, bench rows, and the one
  Prometheus scrape (including calibration-staleness gauges).

See docs/OBSERVABILITY.md.
"""

from .trace import (Span, TraceRecorder, collect_notes, current_request_id,  # noqa: F401
                    disable_tracing, emit_span, enable_tracing, key_hash,
                    note, obs_snapshot, recorder, request, reset_tracing,
                    span, tracing_enabled)
from .ledger import DriftRecord, Ledger, global_ledger  # noqa: F401
from .numerics import (NumericLedger, NumericRecord,  # noqa: F401
                       corruption_selftest, densmatr_probe_vector,
                       epoch_pass_probes, global_numeric_ledger,
                       state_probe_vector, ulp_band)
from .flight import FlightRecord, FlightRecorder  # noqa: F401
from .export import chrome_trace, trace_report, validate_chrome_trace  # noqa: F401
from .aggregate import (load_shard, merge_files, merge_shards,  # noqa: F401
                        process_shard, save_shard)
from .slo import SLOConfig, SLOMonitor  # noqa: F401
from .counters import (RuntimeCounters, global_counters, hbm_watermark,  # noqa: F401
                       record_compile, record_dispatch,
                       update_hbm_watermark)
from .calibrate import (CalibrationProfile, active_profile,  # noqa: F401
                        active_summary, activate as activate_calibration,
                        deactivate as deactivate_calibration, load_profile,
                        make_profile, run_calibration, save_profile,
                        use_profile, validate_profile)
from . import regress  # noqa: F401

__all__ = [
    "Span", "TraceRecorder", "recorder", "span", "emit_span", "request",
    "current_request_id", "note", "collect_notes", "enable_tracing",
    "disable_tracing", "reset_tracing", "tracing_enabled", "obs_snapshot",
    "Ledger", "DriftRecord", "global_ledger",
    "NumericLedger", "NumericRecord", "global_numeric_ledger",
    "state_probe_vector", "densmatr_probe_vector", "epoch_pass_probes",
    "ulp_band", "corruption_selftest",
    "FlightRecorder", "FlightRecord",
    "chrome_trace", "trace_report", "validate_chrome_trace",
    "process_shard", "save_shard", "load_shard", "merge_shards",
    "merge_files",
    "SLOConfig", "SLOMonitor",
    "RuntimeCounters", "global_counters", "record_compile",
    "record_dispatch", "hbm_watermark", "update_hbm_watermark",
    "CalibrationProfile", "run_calibration", "make_profile",
    "save_profile", "load_profile", "validate_profile",
    "activate_calibration", "deactivate_calibration", "active_profile",
    "active_summary", "use_profile",
    "regress",
]
