"""Trace export: Chrome-trace/Perfetto JSON, schema validation, human view.

``chrome_trace`` renders the recorder's spans in the Trace Event Format
(the ``chrome://tracing`` / Perfetto "complete event" shape: ``ph: "X"``
with microsecond ``ts``/``dur``), one ``tid`` lane per recording thread, so
a serve run opens directly in Perfetto next to an XProf capture of the same
process (the spans wrapped device work in ``jax.profiler.TraceAnnotation``
under the same names).

``validate_chrome_trace`` is the CI gate's schema check
(docs/OBSERVABILITY.md; ci.yml ``obs-selftest``): every event well-formed,
every ``parent_id`` resolving to a present span (zero orphans), and every
serve execution span (``serve.request``) carrying its ``request_id``,
``class_key``, ``engine`` and ``cache`` outcome — the correlation contract
that makes a trace navigable from any request.

``trace_report`` is the human view behind ``python -m quest_tpu.analysis
--trace-report``: spans grouped per request and aggregated per name.
"""

from __future__ import annotations

from .trace import Span, TraceRecorder, recorder as _recorder

__all__ = ["chrome_trace", "validate_chrome_trace", "trace_report",
           "EXECUTION_SPAN", "EXECUTION_SPAN_ATTRS"]

#: the serving layer's per-request execution span name (serve/service.py)
EXECUTION_SPAN = "serve.request"
#: attributes every execution span must carry (the acceptance contract)
EXECUTION_SPAN_ATTRS = ("class_key", "engine", "cache")


def chrome_trace(spans: list[Span] | None = None,
                 recorder: TraceRecorder | None = None) -> dict:
    """Trace Event Format document for ``spans`` (default: the process
    recorder's).  Timestamps are microseconds relative to the recorder's
    trace origin; ``args`` carries span/parent/request ids plus every
    structured attribute."""
    rec = recorder if recorder is not None else _recorder()
    if spans is None:
        spans = rec.spans()
    tids = {}
    events = []
    for sp in spans:
        tid = tids.setdefault(sp.thread, len(tids) + 1)
        args = {"span_id": sp.span_id, "parent_id": sp.parent_id,
                "request_id": sp.request_id}
        args.update(sp.attrs)
        events.append({
            "name": sp.name, "ph": "X", "pid": 1, "tid": tid,
            "ts": (sp.t0 - rec.t0_perf) * 1e6,
            "dur": sp.dur * 1e6,
            "args": args,
        })
    meta = [{"name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
             "args": {"name": thread}} for thread, tid in tids.items()]
    return {"displayTimeUnit": "ms",
            "otherData": {"origin_epoch_s": rec.t0_epoch,
                          "dropped_spans": rec.snapshot()["dropped"]},
            "traceEvents": meta + events}


def validate_chrome_trace(doc: dict) -> list[str]:
    """Schema-check an exported document; returns the list of problems
    (empty = valid).  Checked: every complete event carries name/ts/dur and
    a ``span_id``; span ids are unique ACROSS the whole document (a merged
    multi-process trace namespaces per-process ids — obs/aggregate.py);
    every non-None ``parent_id`` resolves to a present span (zero orphans)
    AND to a span on the same process track (a cross-track parent link
    would mean the per-process namespacing broke); every ``serve.request``
    event carries a ``request_id`` and the EXECUTION_SPAN_ATTRS.

    Merged documents (``otherData.processes`` present) additionally must
    name every declared process track (a ``process_name`` meta event per
    pid), carry a clock offset per process, and contain no event on an
    undeclared track."""
    problems: list[str] = []
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["no traceEvents array"]
    complete = [e for e in events if e.get("ph") == "X"]
    ids: set = set()
    pid_of: dict = {}
    for i, e in enumerate(complete):
        for field in ("name", "ts", "dur", "pid", "tid"):
            if field not in e:
                problems.append(f"event {i} missing {field!r}")
        args = e.get("args") or {}
        sid = args.get("span_id")
        if sid is None:
            problems.append(f"event {i} ({e.get('name')}) has no span_id")
            continue
        if sid in ids:
            problems.append(f"duplicate span_id {sid}")
        ids.add(sid)
        pid_of[sid] = e.get("pid")
    for e in complete:
        args = e.get("args") or {}
        parent = args.get("parent_id")
        if parent is not None and parent not in ids:
            problems.append(
                f"span {args.get('span_id')} ({e.get('name')}) is an "
                f"orphan: parent_id {parent} not in this trace")
        elif parent is not None and pid_of[parent] != e.get("pid"):
            problems.append(
                f"span {args.get('span_id')} ({e.get('name')}) parents "
                f"across process tracks: parent {parent} lives on pid "
                f"{pid_of[parent]}, span on pid {e.get('pid')}")
        if e.get("name") == EXECUTION_SPAN:
            if args.get("request_id") is None:
                problems.append(
                    f"execution span {args.get('span_id')} has no "
                    "request_id")
            for attr in EXECUTION_SPAN_ATTRS:
                if args.get(attr) in (None, ""):
                    problems.append(
                        f"execution span {args.get('span_id')} missing "
                        f"attr {attr!r}")
    declared = (doc.get("otherData") or {}).get("processes")
    if declared is not None:
        # a merged multi-process document (obs/aggregate.py): the declared
        # track set is a contract, not a hint
        declared_pids = {p + 1 for p in declared}
        named_pids = {e.get("pid") for e in events
                      if e.get("ph") == "M" and e.get("name") == "process_name"}
        for p in sorted(declared):
            if p + 1 not in named_pids:
                problems.append(f"declared process {p} has no process_name "
                                "meta event")
            if str(p) not in ((doc.get("otherData") or {})
                              .get("clock_offsets_s") or {}):
                problems.append(f"declared process {p} has no clock offset")
        for e in complete:
            if e.get("pid") not in declared_pids:
                problems.append(
                    f"span {((e.get('args') or {}).get('span_id'))} sits on "
                    f"undeclared process track pid {e.get('pid')}")
    return problems


def _fmt_s(seconds: float) -> str:
    if seconds >= 1.0:
        return f"{seconds:.3f}s"
    if seconds >= 1e-3:
        return f"{seconds * 1e3:.2f}ms"
    return f"{seconds * 1e6:.0f}us"


def trace_report(spans: list[Span] | None = None,
                 recorder: TraceRecorder | None = None) -> str:
    """Human summary: per-name aggregates, then per-request span trees
    (children indented under their parents, durations inline)."""
    rec = recorder if recorder is not None else _recorder()
    if spans is None:
        spans = rec.spans()
    if not spans:
        return "trace: no spans recorded (tracing disabled?)"
    lines = [f"trace: {len(spans)} span(s)"]
    agg: dict = {}
    for sp in spans:
        count, total = agg.get(sp.name, (0, 0.0))
        agg[sp.name] = (count + 1, total + sp.dur)
    lines.append("by span name:")
    for name in sorted(agg, key=lambda k: -agg[k][1]):
        count, total = agg[name]
        lines.append(f"  {name:<28} x{count:<5} total {_fmt_s(total)}")
    by_request: dict = {}
    for sp in spans:
        by_request.setdefault(sp.request_id, []).append(sp)
    children: dict = {}
    for sp in spans:
        children.setdefault(sp.parent_id, []).append(sp)

    def emit(sp: Span, depth: int, group_ids: set) -> None:
        attrs = " ".join(f"{k}={v}" for k, v in sorted(sp.attrs.items()))
        lines.append(f"  {'  ' * depth}{sp.name} {_fmt_s(sp.dur)}"
                     + (f"  [{attrs}]" if attrs else ""))
        for child in sorted(children.get(sp.span_id, ()),
                            key=lambda s: s.t0):
            if child.span_id in group_ids:  # stay inside this request's tree
                emit(child, depth + 1, group_ids)

    for rid in sorted(by_request, key=lambda r: (r is None, r)):
        group = by_request[rid]
        group_ids = {sp.span_id for sp in group}
        span_time = sum(sp.dur for sp in group)
        label = "unattributed" if rid is None else f"request {rid}"
        lines.append(f"{label}: {len(group)} span(s), {_fmt_s(span_time)}")
        for sp in sorted(group, key=lambda s: s.t0):
            if sp.parent_id is None or sp.parent_id not in group_ids:
                emit(sp, 1, group_ids)
    return "\n".join(lines)
