"""Trace export: Chrome-trace/Perfetto JSON, schema validation, human view.

``chrome_trace`` renders the recorder's spans in the Trace Event Format
(the ``chrome://tracing`` / Perfetto "complete event" shape: ``ph: "X"``
with microsecond ``ts``/``dur``), one ``tid`` lane per recording thread, so
a serve run opens directly in Perfetto next to an XProf capture of the same
process (the spans wrapped device work in ``jax.profiler.TraceAnnotation``
under the same names).

``validate_chrome_trace`` is the CI gate's schema check
(docs/OBSERVABILITY.md; ci.yml ``obs-selftest``): every event well-formed,
every ``parent_id`` resolving to a present span (zero orphans), and every
serve execution span (``serve.request``) carrying its ``request_id``,
``class_key``, ``engine`` and ``cache`` outcome — the correlation contract
that makes a trace navigable from any request.

``trace_report`` is the human view behind ``python -m quest_tpu.analysis
--trace-report``: spans grouped per request and aggregated per name.
"""

from __future__ import annotations

from .trace import Span, TraceRecorder, recorder as _recorder

__all__ = ["chrome_trace", "validate_chrome_trace", "trace_report",
           "EXECUTION_SPAN", "EXECUTION_SPAN_ATTRS"]

#: the serving layer's per-request execution span name (serve/service.py)
EXECUTION_SPAN = "serve.request"
#: attributes every execution span must carry (the acceptance contract)
EXECUTION_SPAN_ATTRS = ("class_key", "engine", "cache")


def chrome_trace(spans: list[Span] | None = None,
                 recorder: TraceRecorder | None = None) -> dict:
    """Trace Event Format document for ``spans`` (default: the process
    recorder's).  Timestamps are microseconds relative to the recorder's
    trace origin; ``args`` carries span/parent/request ids plus every
    structured attribute."""
    rec = recorder if recorder is not None else _recorder()
    if spans is None:
        spans = rec.spans()
    tids = {}
    events = []
    for sp in spans:
        tid = tids.setdefault(sp.thread, len(tids) + 1)
        args = {"span_id": sp.span_id, "parent_id": sp.parent_id,
                "request_id": sp.request_id}
        args.update(sp.attrs)
        events.append({
            "name": sp.name, "ph": "X", "pid": 1, "tid": tid,
            "ts": (sp.t0 - rec.t0_perf) * 1e6,
            "dur": sp.dur * 1e6,
            "args": args,
        })
    meta = [{"name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
             "args": {"name": thread}} for thread, tid in tids.items()]
    return {"displayTimeUnit": "ms",
            "otherData": {"origin_epoch_s": rec.t0_epoch,
                          "dropped_spans": rec.snapshot()["dropped"]},
            "traceEvents": meta + events}


def validate_chrome_trace(doc: dict) -> list[str]:
    """Schema-check an exported document; returns the list of problems
    (empty = valid).  Checked: every complete event carries name/ts/dur and
    a ``span_id``; span ids are unique ACROSS the whole document (a merged
    multi-process trace namespaces per-process ids — obs/aggregate.py);
    every non-None ``parent_id`` resolves to a present span (zero orphans)
    AND to a span on the same process track (a cross-track parent link
    would mean the per-process namespacing broke); every ``serve.request``
    event carries a ``request_id`` and the EXECUTION_SPAN_ATTRS.

    Merged documents (``otherData.processes`` present) additionally must
    name every declared process track (a ``process_name`` meta event per
    pid), carry a clock offset per process, and contain no event on an
    undeclared track."""
    problems: list[str] = []
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["no traceEvents array"]
    complete = [e for e in events if e.get("ph") == "X"]
    ids: set = set()
    pid_of: dict = {}
    for i, e in enumerate(complete):
        for field in ("name", "ts", "dur", "pid", "tid"):
            if field not in e:
                problems.append(f"event {i} missing {field!r}")
        args = e.get("args") or {}
        sid = args.get("span_id")
        if sid is None:
            problems.append(f"event {i} ({e.get('name')}) has no span_id")
            continue
        if sid in ids:
            problems.append(f"duplicate span_id {sid}")
        ids.add(sid)
        pid_of[sid] = e.get("pid")
    for e in complete:
        args = e.get("args") or {}
        parent = args.get("parent_id")
        if parent is not None and parent not in ids:
            problems.append(
                f"span {args.get('span_id')} ({e.get('name')}) is an "
                f"orphan: parent_id {parent} not in this trace")
        elif parent is not None and pid_of[parent] != e.get("pid"):
            problems.append(
                f"span {args.get('span_id')} ({e.get('name')}) parents "
                f"across process tracks: parent {parent} lives on pid "
                f"{pid_of[parent]}, span on pid {e.get('pid')}")
        if e.get("name") == EXECUTION_SPAN:
            if args.get("request_id") is None:
                problems.append(
                    f"execution span {args.get('span_id')} has no "
                    "request_id")
            for attr in EXECUTION_SPAN_ATTRS:
                if args.get(attr) in (None, ""):
                    problems.append(
                        f"execution span {args.get('span_id')} missing "
                        f"attr {attr!r}")
    declared = (doc.get("otherData") or {}).get("processes")
    if declared is not None:
        # a merged multi-process document (obs/aggregate.py): the declared
        # track set is a contract, not a hint
        declared_pids = {p + 1 for p in declared}
        named_pids = {e.get("pid") for e in events
                      if e.get("ph") == "M" and e.get("name") == "process_name"}
        for p in sorted(declared):
            if p + 1 not in named_pids:
                problems.append(f"declared process {p} has no process_name "
                                "meta event")
            if str(p) not in ((doc.get("otherData") or {})
                              .get("clock_offsets_s") or {}):
                problems.append(f"declared process {p} has no clock offset")
        for e in complete:
            if e.get("pid") not in declared_pids:
                problems.append(
                    f"span {((e.get('args') or {}).get('span_id'))} sits on "
                    f"undeclared process track pid {e.get('pid')}")
    return problems


def _fmt_s(seconds: float) -> str:
    if seconds >= 1.0:
        return f"{seconds:.3f}s"
    if seconds >= 1e-3:
        return f"{seconds * 1e3:.2f}ms"
    return f"{seconds * 1e6:.0f}us"


def _render_spans(spans: list[Span], lines: list) -> None:
    """The shared per-span-set rendering: per-name aggregates, then
    per-request span trees (children indented, durations inline)."""
    agg: dict = {}
    for sp in spans:
        count, total = agg.get(sp.name, (0, 0.0))
        agg[sp.name] = (count + 1, total + sp.dur)
    lines.append("by span name:")
    for name in sorted(agg, key=lambda k: -agg[k][1]):
        count, total = agg[name]
        lines.append(f"  {name:<28} x{count:<5} total {_fmt_s(total)}")
    by_request: dict = {}
    for sp in spans:
        by_request.setdefault(sp.request_id, []).append(sp)
    children: dict = {}
    for sp in spans:
        children.setdefault(sp.parent_id, []).append(sp)

    def emit(sp: Span, depth: int, group_ids: set) -> None:
        attrs = " ".join(f"{k}={v}" for k, v in sorted(sp.attrs.items()))
        lines.append(f"  {'  ' * depth}{sp.name} {_fmt_s(sp.dur)}"
                     + (f"  [{attrs}]" if attrs else ""))
        for child in sorted(children.get(sp.span_id, ()),
                            key=lambda s: s.t0):
            if child.span_id in group_ids:  # stay inside this request's tree
                emit(child, depth + 1, group_ids)

    for rid in sorted(by_request, key=lambda r: (r is None, r)):
        group = by_request[rid]
        group_ids = {sp.span_id for sp in group}
        span_time = sum(sp.dur for sp in group)
        label = "unattributed" if rid is None else f"request {rid}"
        lines.append(f"{label}: {len(group)} span(s), {_fmt_s(span_time)}")
        for sp in sorted(group, key=lambda s: s.t0):
            if sp.parent_id is None or sp.parent_id not in group_ids:
                emit(sp, 1, group_ids)


def _doc_spans(events: list, thread_names: dict) -> list[Span]:
    """Rebuild :class:`Span` views from one process track's complete
    events (the merged-document report path; ids/attrs live in args)."""
    spans = []
    for e in events:
        args = dict(e.get("args") or {})
        spans.append(Span(
            e.get("name", "?"), args.pop("span_id", None),
            args.pop("parent_id", None), args.pop("request_id", None),
            float(e.get("ts", 0.0)) / 1e6, float(e.get("dur", 0.0)) / 1e6,
            thread_names.get(e.get("tid"), f"tid {e.get('tid')}"),
            {k: v for k, v in args.items() if k != "process"}))
    return spans


def _merged_trace_report(doc: dict) -> str:
    """The report over a MERGED Chrome-trace document
    (obs/aggregate.py merge_shards/merge_files): one section per process
    track — named, with its clock offset noted — instead of assuming the
    single-process recorder.  A degenerate (single-process) merge renders
    as one unlabeled section, matching the recorder path's shape."""
    events = doc.get("traceEvents") or []
    complete = [e for e in events if e.get("ph") == "X"]
    if not complete:
        return "trace: no spans recorded (tracing disabled?)"
    other = doc.get("otherData") or {}
    declared = other.get("processes")
    offsets = other.get("clock_offsets_s") or {}
    hosts = other.get("hosts") or {}
    by_pid: dict = {}
    for e in complete:
        by_pid.setdefault(e.get("pid"), []).append(e)
    proc_names: dict = {}
    thread_names: dict = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            proc_names[e.get("pid")] = (e.get("args") or {}).get("name")
        elif e.get("name") == "thread_name":
            thread_names.setdefault(e.get("pid"), {})[e.get("tid")] = \
                (e.get("args") or {}).get("name")
    multi = declared is not None and len(by_pid) >= 1
    lines = [f"merged trace: {len(complete)} span(s) across "
             f"{len(by_pid)} process(es)"
             + (f", dropped {other['dropped_spans']}"
                if other.get("dropped_spans") else "")]
    for pid in sorted(by_pid):
        if multi:
            p = pid - 1
            off = offsets.get(str(p), 0.0)
            name = proc_names.get(pid) or f"process {p}"
            host = hosts.get(str(p))
            lines.append(f"-- {name}"
                         + (f" on {host}" if host and host not in name
                            else "")
                         + f" (clock offset {off:+.6f}s): "
                         f"{len(by_pid[pid])} span(s)")
        _render_spans(_doc_spans(by_pid[pid],
                                 thread_names.get(pid, {})), lines)
    return "\n".join(lines)


def trace_report(spans: list[Span] | dict | None = None,
                 recorder: TraceRecorder | None = None) -> str:
    """Human summary: per-name aggregates, then per-request span trees
    (children indented under their parents, durations inline).

    ``spans`` may also be a MERGED multi-process Chrome-trace document
    (``obs.merge_shards``/``merge_files`` output): the report then
    renders one section per process track, each named and annotated with
    its clock offset, so a pod capture reads as one document instead of
    N islands."""
    if isinstance(spans, dict):
        return _merged_trace_report(spans)
    rec = recorder if recorder is not None else _recorder()
    if spans is None:
        spans = rec.spans()
    if not spans:
        return "trace: no spans recorded (tracing disabled?)"
    lines = [f"trace: {len(spans)} span(s)"]
    _render_spans(spans, lines)
    return "\n".join(lines)
