"""Perf-regression ledger: gate the BENCH trajectory, don't just record it.

Five rounds of ``BENCH_r0*.json`` snapshots exist and every row now carries
a provenance stamp (PR 7) — but the history was write-only: a regression in
a headline row (``qft_30q_f32_public_api`` at 2.59e11 amps/s) would ship
silently, because nothing ever read two rounds side by side.  This module
is the reader and the gate:

- :func:`load_history` parses the committed history files, which are
  DRIVER-wrapped (``{"n", "cmd", "rc", "tail", "parsed"}``) and imperfect
  in exactly the ways real telemetry is: r01 is a timeout with no data,
  r02 has a parsed headline but no matrix, and r03–r05 carry only the
  truncated *tail* of the output line.  Rows are recovered from those
  tails by scanning for balanced ``{"name": ...}`` JSON objects — a row
  that survived truncation is a row we can gate on; rows that didn't are
  reported as unrecoverable, never silently invented.
- :func:`compare` matches rows between a current document and the best
  comparable prior row — same row name AND same platform (a CPU dev-box
  run is never judged against a TPU history row; ``unknown`` platforms,
  the pre-provenance rounds, match anything since the history is
  single-fleet) — and flags ``status: "regressed"`` when

      value < best_comparable_prior * (1 - tolerance)

  with ``tolerance`` default :data:`DEFAULT_TOLERANCE` (20%) and
  per-row overrides for rows with known larger run-to-run spread.
  Rows marked ``validation_only`` (the CPU-mesh communication-structure
  configs) are compared and reported but do NOT gate by default: their
  wall clocks measure a virtual-device CPU mesh, not the chip, and round
  over round they swing with host load (docs/OBSERVABILITY.md has the
  tolerance table).

The CLI is ``python bench.py --compare`` (one JSON report document on
stdout, exit 1 iff a gating row regressed) and the CI ``bench-regress``
job runs it twice: once over the real committed history (must pass), once
with ``--inject`` scaling a headline row by 0.75 (must fail) — the gate
gates itself.  Dependency-free like the rest of ``quest_tpu.obs``: the CI
job needs nothing beyond the stdlib to refuse a regressing PR.
"""

from __future__ import annotations

import json
import os
import re

__all__ = ["DEFAULT_TOLERANCE", "DEFAULT_ROW_TOLERANCES", "PERF_REGRESSION",
           "recover_rows", "load_round", "load_history", "compare",
           "default_history_paths"]

#: the regression finding code (analysis severity taxonomy: ERROR — unlike
#: O_MODEL_DRIFT/O_SLO_BURN this one fails CI, that is its whole point)
PERF_REGRESSION = "O_PERF_REGRESSION"

#: default per-row tolerance: fail on > 20% amps/s regression vs the best
#: comparable prior row
DEFAULT_TOLERANCE = 0.20

#: per-row overrides for rows with measured larger run-to-run spread
#: (docs/OBSERVABILITY.md "regression-gate tolerances" documents why):
#: the serve row times a threaded queue+batch wall (scheduling jitter on a
#: shared host), the f64 density row is the slowest config on a shared-chip
#: tunnel with observed bad-window noise (bench.py best-of-2 bounds but
#: does not remove it)
DEFAULT_ROW_TOLERANCES = {
    "serve_vqe_16q_batch64": 0.40,
    "vqe_grad_16q_batch64": 0.40,
    "densmatr_14q_damping_depol_f64": 0.30,
    # density rows share the f64 row's shared-chip spread; the f32 row
    # additionally changed meaning in PR 15 (it now compiles the whole
    # noisy layer through engine="auto" on the Choi-doubled register —
    # the first comparable round under the new path sets the new floor)
    "densmatr_14q_damping_depol_f32": 0.30,
    "densmatr_16q_kraus_auto_engine": 0.30,
}

_NAME_ROW = re.compile(r'\{"name":')
_METRIC_DOC = re.compile(r'\{"metric":')


def _scan_objects(text: str, pattern: re.Pattern) -> list:
    """Every balanced JSON object starting at a ``pattern`` match.  The
    history tails are TRUNCATED AT THE FRONT, so the first row fragment is
    usually cut mid-object — raw_decode fails on it and succeeds on every
    complete one after; recovery is exactly the survivable suffix."""
    decoder = json.JSONDecoder()
    out = []
    for m in pattern.finditer(text):
        try:
            obj, _end = decoder.raw_decode(text, m.start())
        except ValueError:
            continue
        out.append(obj)
    return out


def _row_platform(row: dict, round_platform: str) -> str:
    cfg = row.get("config") or {}
    return (cfg.get("platform")
            or (cfg.get("provenance") or {}).get("platform")
            or round_platform)


def _normalize_row(row: dict, round_platform: str) -> dict | None:
    """A matrix row as the compare shape, or None for error rows."""
    if row.get("error") is not None or not isinstance(
            row.get("value"), (int, float)):
        return None
    cfg = row.get("config") or {}
    compile_s = cfg.get("compile_seconds")
    return {"name": row["name"], "value": float(row["value"]),
            "platform": _row_platform(row, round_platform),
            "validation_only": bool(cfg.get("validation_only", False)),
            # PR 9 rows stamp compile wall seconds (obs/counters.py);
            # compare() REPORTS their deltas next to amps/s, never gates —
            # a compile-time jump is a diagnosis lead, not a throughput
            # regression (docs/OBSERVABILITY.md)
            "compile_seconds": (float(compile_s)
                                if isinstance(compile_s, (int, float))
                                else None)}


def recover_rows(text: str) -> tuple[dict | None, list[dict]]:
    """(headline document or None, matrix row dicts) recovered from raw
    bench output text — including a front-truncated tail."""
    docs = _scan_objects(text, _METRIC_DOC)
    headline = docs[0] if docs else None
    rows = [r for r in _scan_objects(text, _NAME_ROW)
            if isinstance(r.get("name"), str)
            and ("value" in r or "error" in r)]
    if headline is not None:
        # the full document embeds the matrix rows (the scan re-finds them
        # as separate matches): keep the document's copy, don't double-count
        names = {e.get("name") for e in headline.get("matrix") or []}
        rows = list(headline.get("matrix") or ()) \
            + [r for r in rows if r["name"] not in names]
    return headline, rows


def load_round(path: str) -> dict:
    """One history file as ``{label, path, rc, platform, rows, skipped,
    recovered}`` — ``rows`` keyed by row name (the parsed document when the
    driver captured one, else whatever the truncated tail still holds)."""
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    label = os.path.splitext(os.path.basename(path))[0]
    if isinstance(doc, dict) and "tail" in doc and "rc" in doc:
        rc = doc.get("rc")
        parsed = doc.get("parsed")
        recovered = False
        if parsed and (parsed.get("matrix") or parsed.get("value")):
            headline, raw_rows = parsed, list(parsed.get("matrix") or ())
        else:
            headline, raw_rows = recover_rows(doc.get("tail") or "")
            recovered = True
    else:           # a raw `python bench.py` output document
        rc, recovered = 0, False
        headline, raw_rows = doc, list(doc.get("matrix") or ())
    round_platform = "unknown"
    if headline is not None:
        round_platform = (headline.get("config") or {}).get(
            "platform", "unknown") or "unknown"
    if round_platform == "unknown":
        for r in raw_rows:
            p = _row_platform(r, "unknown")
            # a mesh row's platform is the virtual CPU mesh, not the
            # round's chip — never promote it to the round default
            # (pre-PR4 rounds carried the platform without the
            # validation_only marker, hence the devices guard too)
            cfg = r.get("config") or {}
            if p != "unknown" and not cfg.get("validation_only") \
                    and not cfg.get("devices"):
                round_platform = p
                break
    rows: dict = {}
    skipped: list = []
    if headline is not None and isinstance(headline.get("value"),
                                           (int, float)):
        head_compile = (headline.get("config") or {}).get("compile_seconds")
        rows["headline"] = {
            "name": "headline", "value": float(headline["value"]),
            "platform": (headline.get("config") or {}).get(
                "platform", round_platform),
            "validation_only": False,
            "compile_seconds": (float(head_compile)
                                if isinstance(head_compile, (int, float))
                                else None)}
    for raw in raw_rows:
        norm = _normalize_row(raw, round_platform)
        if norm is None:
            skipped.append({"name": raw.get("name"),
                            "error": raw.get("error")})
            continue
        rows[norm["name"]] = norm
    return {"label": label, "path": path, "rc": rc,
            "platform": round_platform, "rows": rows, "skipped": skipped,
            "recovered": recovered}


def default_history_paths(root: str | None = None) -> list[str]:
    """The committed ``BENCH_r*.json`` trajectory, oldest first."""
    import glob
    if root is None:
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    return sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))


def load_history(paths: list[str] | None = None) -> list[dict]:
    return [load_round(p) for p in (paths if paths is not None
                                    else default_history_paths())]


def _comparable(a_platform: str, b_platform: str) -> bool:
    # "unknown" (pre-provenance rounds) matches anything: the committed
    # history is a single fleet's trajectory, and refusing to compare
    # would un-gate most of it.  Two KNOWN platforms must agree.
    if "unknown" in (a_platform, b_platform):
        return True
    return a_platform == b_platform


def compare(current: dict, priors: list[dict], *,
            default_tolerance: float = DEFAULT_TOLERANCE,
            row_tolerances: dict | None = None,
            include_validation: bool = False) -> dict:
    """ONE report document comparing ``current`` round against the best
    comparable row anywhere in ``priors``.  ``ok`` is False iff any
    GATING row regressed past its tolerance; validation-only rows gate
    only with ``include_validation``."""
    tol_map = dict(DEFAULT_ROW_TOLERANCES)
    tol_map.update(row_tolerances or {})
    report_rows: list = []
    regressed = improved = new = ok_count = 0
    for name in sorted(current["rows"]):
        row = current["rows"][name]
        tolerance = tol_map.get(name, default_tolerance)
        best = None
        best_round = None
        best_row = None
        for prior in priors:
            cand = prior["rows"].get(name)
            if cand is None or not _comparable(row["platform"],
                                               cand["platform"]):
                continue
            if best is None or cand["value"] > best:
                best, best_round = cand["value"], prior["label"]
                best_row = cand
        gating = include_validation or not row["validation_only"]
        entry = {"name": name, "value": row["value"],
                 "platform": row["platform"],
                 "validation_only": row["validation_only"],
                 "tolerance": tolerance, "gating": gating,
                 "best_prior": best, "best_prior_round": best_round}
        # compile-time delta next to amps/s — REPORTED, never gated: the
        # compile wall measures the toolchain, not the kernels, and jumps
        # with jax/jaxlib upgrades that are not this repo's regression
        cur_compile = row.get("compile_seconds")
        prior_compile = (best_row or {}).get("compile_seconds")
        entry["compile_seconds"] = cur_compile
        entry["prior_compile_seconds"] = prior_compile
        entry["compile_delta_frac"] = (
            cur_compile / prior_compile - 1.0
            if cur_compile and prior_compile else None)
        if best is None:
            entry["status"] = "new"
            entry["ratio"] = None
            new += 1
        else:
            ratio = row["value"] / best
            entry["ratio"] = ratio
            if ratio < 1.0 - tolerance:
                entry["status"] = "regressed"
                entry["code"] = PERF_REGRESSION
                entry["detail"] = (
                    f"{name}: {row['value']:.3g} amps/s is "
                    f"{(1.0 - ratio):.1%} below the best comparable prior "
                    f"{best:.3g} ({best_round}); tolerance {tolerance:.0%}")
                regressed += 1
            elif ratio > 1.0 + tolerance:
                entry["status"] = "improved"
                improved += 1
            else:
                entry["status"] = "ok"
                ok_count += 1
        report_rows.append(entry)
    gating_regressions = [r for r in report_rows
                          if r["status"] == "regressed" and r["gating"]]
    return {
        "metric": "bench_compare",
        "current": current["label"],
        "history": [p["label"] for p in priors],
        "default_tolerance": default_tolerance,
        "rows": report_rows,
        "summary": {
            "rows": len(report_rows),
            "regressed": regressed,
            "gating_regressions": len(gating_regressions),
            "improved": improved, "ok": ok_count, "new": new,
            "unrecoverable_prior_rounds": [p["label"] for p in priors
                                           if not p["rows"]],
        },
        "ok": not gating_regressions,
    }
