"""Numeric-health telemetry: on-device probes + the numeric drift ledger.

The reference exposes ``calcTotalProb`` as THE runtime sanity check — QuEST
users call it mid-circuit to confirm the register is still a unit-norm
state (PAPER.md L3/L4 validation surface).  Our observability stack (PRs
7-9) watches only *time*: spans, SLO burn, calibration drift.  Nothing
watches the *numbers*, even though the two hardest open ROADMAP items are
numerical at the core (item 3's f64 miscompiles show up as "wrong norms
on-chip"; item 4's density channels must provably preserve trace and
Hermiticity).  This module is the correctness half of the observability
story:

- **Probe kernels** (:func:`state_probe_vector`,
  :func:`densmatr_probe_vector`): pure reductions — L2 norm / total
  probability (density: the trace), max |amp|^2, NaN and Inf counts, and
  the Hermiticity deviation for Choi-flattened density matrices (via the
  same reduction shapes as ops/calc.py) — compiled as AUXILIARY outputs
  beside the primary dataflow.  A probe reads the state, it never writes
  it, so the primary output of a probe-instrumented program is
  bit-identical to the uninstrumented one (pinned in tier-1 for every
  engine path; the serve cache's ``*_probed_program`` variants are built
  on exactly this contract).
- **The ulp-growth band** (:func:`ulp_band`): the precision-and-depth-
  derived envelope measured norm drift is judged against.  Unitary gates
  preserve the norm exactly in exact arithmetic; floating-point rounding
  random-walks it by ~eps per pass, so after D passes the drift envelope
  is ``SAFETY * eps(dtype) * sqrt(D)``.  The safety factor covers the
  walk's constant and dense-gate accumulation order; the band is
  deliberately generous enough that a clean workload NEVER trips it (the
  CI ``numeric-selftest`` gate runs 64 probed requests at zero findings)
  while a 1e-3-scaled state or a miscompiled f64 kernel (wrong norms
  on-chip — ROADMAP item 3's symptom) trips it by orders of magnitude.
- **The numeric ledger** (:class:`NumericLedger`) — sibling of
  obs/ledger.py's model-vs-measured ledger: every probed run records one
  :class:`NumericRecord`; NaN/Inf counts raise ``O_NUMERIC_NAN``, drift
  outside the band (norm, density trace, or Hermiticity deviation) raises
  ``O_NUMERIC_DRIFT``, with per-structural-class aggregation
  (:meth:`NumericLedger.by_class`) so a fleet scrape can say WHICH class
  went bad, not just that something did.
- **Epoch per-pass probes** (:func:`epoch_pass_probes`): the plan of
  ops/epoch_pallas.py executed pass by pass with a probe at every fused
  HBM-pass boundary — one probe point per Pallas pass and per XLA
  fallback segment — independently confirming the planner's fused-pass
  boundaries (the probe-point count must equal the plan's pass count) and
  giving the f64 double-float work of ROADMAP item 3 a per-pass
  norm-drift oracle.  Norm, NaN and Inf probes are invariant under the
  engine's deferred qubit map, so probing between passes needs no
  materialization.
- **Adversarial injections** (:func:`corruption_selftest`): a scaled
  state, a NaN-poisoned amplitude and a non-Hermitian density
  perturbation MUST each trip the ledger — the PR 3/12 mutation-harness
  pattern applied to the numeric gate itself, run by the serve selftest
  and the CI ``numeric-selftest`` job.

Serving wires this end to end: ``QuESTService(probes=True)`` (or
``QUEST_TPU_NUMERIC_PROBES=1``) serves every request through the
probe-instrumented program variant, attaches a ``numeric_health`` record
to each :class:`~quest_tpu.serve.service.ServeResult` and flight-ring
record, dumps the ring on the first NaN outcome, exports
``quest_serve_numeric_*`` in the one Prometheus scrape, and the deploy
router quarantines a (class, replica) placement on repeated NaN outcomes
(docs/OBSERVABILITY.md "Numeric health").
"""

from __future__ import annotations

import dataclasses
import math
import threading
import warnings
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["NUMERIC_DRIFT", "NUMERIC_NAN", "PROBE_FIELDS", "ulp_band",
           "state_probe_vector", "densmatr_probe_vector", "probe_dict",
           "run_ops_probed", "epoch_pass_probes",
           "NumericRecord", "NumericLedger", "global_numeric_ledger",
           "inject_scale", "inject_nan", "inject_nonhermitian",
           "corruption_selftest", "DEFAULT_SAFETY"]

#: diagnostic code for measured drift outside the ulp-growth band
#: (analysis CLI severity: WARNING — the obs taxonomy next to
#: O_MODEL_DRIFT / O_SLO_BURN)
NUMERIC_DRIFT = "O_NUMERIC_DRIFT"

#: diagnostic code for NaN/Inf amplitudes observed by a probe (analysis
#: CLI severity: ERROR — a poisoned register serves garbage to every
#: downstream consumer); also the flight-ring dump reason
NUMERIC_NAN = "O_NUMERIC_NAN"

#: the probe vector layout, one fixed shape for statevectors and density
#: matrices so every instrumented program signature is identical:
#: ``norm`` is the L2 norm (total probability) for statevectors and the
#: trace for density matrices; ``herm_dev`` is 0 for statevectors and the
#: max |rho - rho^H| element for density matrices
PROBE_FIELDS = ("norm", "max_amp2", "nan_count", "inf_count", "herm_dev")

#: ulp-band safety factor: covers the rounding walk's constant and the
#: accumulation-order spread of dense multi-target gates.  Chosen so the
#: committed clean workloads (serve selftest, 17q QFT, random24) sit
#: orders of magnitude inside the band in BOTH precisions while a 0.1%
#: scale corruption overshoots it by >1e6 ulps
DEFAULT_SAFETY = 128.0

#: ledger retention, FIFO beyond this (mirrors obs/ledger.py: a
#: long-running serve process must not grow the ledger without bound)
_MAX_RECORDS = 1024

_ACC = jnp.float64


def ulp_band(num_ops: int, dtype, safety: float = DEFAULT_SAFETY) -> float:
    """Allowed |norm - expected| after ``num_ops`` compiled passes in
    ``dtype``: ``safety * eps * sqrt(D)`` — per-pass rounding random-walks
    the norm by ~eps, so drift grows with the square root of depth, not
    linearly (the linear bound would hide real miscompiles behind depth)."""
    eps = float(jnp.finfo(jnp.dtype(dtype)).eps)
    return float(safety) * eps * math.sqrt(max(1.0, float(num_ops)))


@jax.jit
def state_probe_vector(state: jax.Array) -> jax.Array:
    """The (5,) probe vector of a (2, 2^n) SoA statevector — a pure
    reduction grafted BESIDE the main dataflow (never into it): L2 norm
    (ops/calc.py total_prob_statevec's accumulation discipline), max
    |amp|^2, NaN count, Inf count, herm_dev=0.  Safe as an auxiliary
    output of any compiled program: it reads the state and writes nothing,
    so the primary output stays bit-identical."""
    re, im = state[0].astype(_ACC), state[1].astype(_ACC)
    mag2 = re * re + im * im
    nan = jnp.sum((jnp.isnan(state[0]) | jnp.isnan(state[1]))
                  .astype(jnp.int32)).astype(_ACC)
    inf = jnp.sum((jnp.isinf(state[0]) | jnp.isinf(state[1]))
                  .astype(jnp.int32)).astype(_ACC)
    return jnp.stack([jnp.sum(mag2), jnp.max(mag2), nan, inf,
                      jnp.zeros((), _ACC)])


@partial(jax.jit, static_argnames=("num_qubits",))
def densmatr_probe_vector(state: jax.Array, num_qubits: int) -> jax.Array:
    """The (5,) probe vector of a Choi-flattened (2, 4^n) density matrix:
    trace (ops/calc.py total_prob_densmatr's diagonal reduction), max
    |rho_ij|^2, NaN/Inf counts, and the Hermiticity deviation
    max |rho - rho^H| — the invariant ROADMAP item 4's fused noise
    channels must provably preserve."""
    from ..ops.measure import densmatr_diagonal
    dim = 1 << num_qubits
    re, im = state[0].astype(_ACC), state[1].astype(_ACC)
    mag2 = re * re + im * im
    nan = jnp.sum((jnp.isnan(state[0]) | jnp.isnan(state[1]))
                  .astype(jnp.int32)).astype(_ACC)
    inf = jnp.sum((jnp.isinf(state[0]) | jnp.isinf(state[1]))
                  .astype(jnp.int32)).astype(_ACC)
    trace = jnp.sum(densmatr_diagonal(state, num_qubits)[0].astype(_ACC))
    # rho[r, c] lives at r + c*2^n (the getDensityAmp convention), so the
    # (col, row)-shaped view's transpose is the adjoint's layout
    mr = re.reshape(dim, dim)
    mi = im.reshape(dim, dim)
    herm = jnp.maximum(jnp.max(jnp.abs(mr - mr.T)),
                       jnp.max(jnp.abs(mi + mi.T)))
    return jnp.stack([trace, jnp.max(mag2), nan, inf, herm])


def grafted_probe(state: jax.Array,
                  density_qubits: int | None = None) -> jax.Array:
    """:func:`state_probe_vector` behind an ``optimization_barrier`` — THE
    graft point for instrumented programs.  The barrier stops XLA from
    fusing the probe reduction into the kernels producing ``state`` (a
    fused magnitude-sum inside a ``lax.map`` body was observed to perturb
    the final gate's FMA contraction by one ulp), so the primary output
    compiles exactly as if the probe were absent: the bit-identity
    contract by construction, not by luck.

    ``density_qubits`` grafts the DENSITY probe instead
    (:func:`densmatr_probe_vector`: trace + Hermiticity deviation) — the
    per-batch acceptance harness of served noisy-circuit classes."""
    barriered = jax.lax.optimization_barrier(state)
    if density_qubits is not None:
        return densmatr_probe_vector(barriered, int(density_qubits))
    return state_probe_vector(barriered)


def probe_dict(vec) -> dict:
    """Host-side dict view of a probe vector (floats, JSON-ready)."""
    vec = np.asarray(vec, np.float64).ravel()
    return {name: float(vec[i]) for i, name in enumerate(PROBE_FIELDS)}


def run_ops_probed(state: jax.Array, ops: tuple):
    """Probe-instrumented twin of circuit._run_ops: ONE jitted program
    returning ``(final_state, probe_vector)`` — the probe is an auxiliary
    output computed from the final state inside the same XLA program, the
    primary output bit-identical to the uninstrumented run (the analysis
    ``--numeric-report`` mode asserts exactly that)."""
    return _run_ops_probed_jit(state, tuple(ops))


@partial(jax.jit, static_argnames=("ops",))
def _run_ops_probed_jit(state: jax.Array, ops: tuple):
    from ..circuit import _run_ops_routed
    out = _run_ops_routed(state, ops)
    return out, grafted_probe(out)


# ---------------------------------------------------------------------------
# epoch-engine per-pass probe points
# ---------------------------------------------------------------------------

def _plane_probe(re: jax.Array, im: jax.Array) -> dict:
    """Probe of (re, im) plane-pair storage.  Norm and NaN/Inf counts are
    permutation-invariant, so a probe at any fused-pass boundary is valid
    WITHOUT materializing the engine's deferred qubit map."""
    r = re.astype(_ACC)
    i = im.astype(_ACC)
    mag2 = r * r + i * i
    nan = int(jnp.sum((jnp.isnan(re) | jnp.isnan(im)).astype(jnp.int32)))
    inf = int(jnp.sum((jnp.isinf(re) | jnp.isinf(im)).astype(jnp.int32)))
    return {"norm": float(jnp.sum(mag2)), "max_amp2": float(jnp.max(mag2)),
            "nan_count": nan, "inf_count": inf}


@partial(jax.jit, static_argnames=("ops",))
def _xla_segment_planes(re: jax.Array, im: jax.Array, ops: tuple):
    """One jitted program per XLA fallback segment of an epoch plan — the
    same fusion context the uninstrumented ``jit_program`` gives the
    segment (``pallas_call`` boundaries are opaque to XLA fusion, so the
    segment subgraph compiles identically standalone), where an EAGER
    per-op chain could legally differ in the last ulp of FMA contraction
    and fake a probe divergence."""
    from ..circuit import _apply_one
    st = jnp.stack([re, im])
    for op in ops:
        st = _apply_one(st, op)
    return st[0], st[1]


def _plane_probe_density(re: jax.Array, im: jax.Array, n: int) -> dict:
    """Density twin of :func:`_plane_probe`: trace of rho and the
    Hermiticity deviation on the Choi-flattened planes (plus the NaN/Inf
    counts).  Trace and Hermiticity read the row/column bit pairing, so —
    unlike the norm probe — they are only layout-valid when the deferred
    qubit map is the identity; ``epoch_pass_probes`` gates on
    ``plan.deferred_ops == 0`` before using this probe per pass."""
    dim = 1 << n
    mr = re.astype(_ACC).reshape(dim, dim)
    mi = im.astype(_ACC).reshape(dim, dim)
    nan = int(jnp.sum((jnp.isnan(re) | jnp.isnan(im)).astype(jnp.int32)))
    inf = int(jnp.sum((jnp.isinf(re) | jnp.isinf(im)).astype(jnp.int32)))
    mag2 = mr * mr + mi * mi
    herm = jnp.maximum(jnp.max(jnp.abs(mr - mr.T)),
                       jnp.max(jnp.abs(mi + mi.T)))
    return {"trace": float(jnp.sum(jnp.diagonal(mr))),
            "max_amp2": float(jnp.max(mag2)),
            "herm_dev": float(herm), "nan_count": nan, "inf_count": inf}


def epoch_pass_probes(ops: tuple, num_qubits: int, state: jax.Array,
                      density_qubits: int | None = None):
    """Run the epoch plan (ops/epoch_pallas.py) pass by pass with a probe
    at every fused-pass boundary: one probe point per Pallas pass (block or
    pack) and one per XLA fallback segment.  Returns ``(final_state,
    points, plan_summary)`` where ``points`` is the ordered list of
    ``{"pass": tag, "kind": ..., "norm": ..., ...}`` probe dicts.

    The probe-point count equals ``plan.pallas_passes`` plus the number of
    XLA segments — an independent runtime confirmation of the planner's
    fused-pass boundaries (the plan said N HBM passes; N probes observed
    N intermediate states).  The final state is bit-identical to the
    uninstrumented ``jit_program`` run: the passes are the same aliased
    kernels, probes only read the planes between them.

    ``density_qubits`` probes a Choi-doubled register with the DENSITY
    invariants instead — trace of rho and the Hermiticity deviation at
    every fused-pass boundary, the per-pass acceptance harness for the
    fused superoperator stages (a channel that breaks trace preservation
    or Hermiticity is caught at ITS pass, not at the end of the program).
    Trace/Hermiticity read the row/column bit pairing, so when the plan
    carries a deferred permutation the per-pass points fall back to the
    layout-invariant norm probe and the density probe runs once after the
    final reconcile."""
    from .. import _compat
    from ..ops import epoch_pallas as _ep
    from ..ops.apply import reconcile_perm_planes
    ops = tuple(ops)
    plan = _ep.plan_circuit(ops, num_qubits)
    density_per_pass = density_qubits is not None and plan.deferred_ops == 0

    def probe(re, im):
        if density_per_pass:
            return _plane_probe_density(re, im, int(density_qubits))
        return _plane_probe(re, im)

    re, im = state[0], state[1]
    points: list = []
    idx = 0
    for segment in plan.segments:
        if segment.engine == "pallas":
            for p in segment.passes:
                with _compat.enable_x64(False):
                    if p.kind == "block":
                        re, im = _ep._run_block_pass(re, im, p)
                    else:
                        re, im = _ep._run_pack_pass(re, im, p)
                points.append({"pass": idx, "kind": p.kind,
                               **probe(re, im)})
                idx += 1
        else:
            # whole segment as ONE jitted program, traced x64-off like
            # jit_program: the fusion context matches the uninstrumented
            # run, so bit-identity cannot break on multi-op segments
            with _compat.enable_x64(False):
                re, im = _xla_segment_planes(re, im, tuple(segment.ops))
            points.append({"pass": idx, "kind": "xla",
                           **probe(re, im)})
            idx += 1
    with _compat.enable_x64(False):
        re, im = reconcile_perm_planes(re, im, plan.residual_perm)
    if density_qubits is not None and not density_per_pass:
        points.append({"pass": "final", "kind": "reconciled",
                       **_plane_probe_density(re, im, int(density_qubits))})
    return jnp.stack([re, im]), points, plan.summary()


# ---------------------------------------------------------------------------
# the numeric drift ledger
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class NumericRecord:
    """One probed run's numeric-health row.  ``findings`` is empty when
    every probe sits inside its band; ``probe_points`` carries the
    per-pass probes of an epoch-instrumented run (empty otherwise)."""
    label: str
    kind: str                    # 'statevec' | 'densmatr'
    engine: str
    dtype: str
    num_qubits: int | None
    num_ops: int
    class_key: str | None
    norm: float
    max_amp2: float
    nan_count: int
    inf_count: int
    herm_dev: float
    expected_norm: float
    norm_drift: float
    band: float
    findings: tuple = ()
    probe_points: tuple = ()

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def as_health(self) -> dict:
        """The compact ``numeric_health`` payload a ServeResult / flight
        record carries: the numbers plus the findings, no provenance."""
        return {"kind": self.kind, "norm": self.norm,
                "norm_drift": self.norm_drift,
                "band": self.band, "max_amp2": self.max_amp2,
                "nan_count": self.nan_count, "inf_count": self.inf_count,
                "herm_dev": self.herm_dev, "findings": list(self.findings)}


class NumericLedger:
    """Thread-safe store of :class:`NumericRecord`; :meth:`record` runs
    the NaN/drift checks and warns (``O_NUMERIC_NAN`` /
    ``O_NUMERIC_DRIFT``) on any finding — the sibling of
    obs/ledger.py's model-vs-measured Ledger, judging values instead of
    wall clocks."""

    def __init__(self, safety: float = DEFAULT_SAFETY):
        self.safety = float(safety)
        self._lock = threading.Lock()
        self._records: list[NumericRecord] = []  # guarded-by: _lock
        self.nan_total = 0                       # guarded-by: _lock
        self.drift_total = 0                     # guarded-by: _lock
        self.probed_total = 0                    # guarded-by: _lock

    def record(self, label: str, probe, *, kind: str = "statevec",
               engine: str = "xla", dtype="float64",
               num_qubits: int | None = None, num_ops: int = 0,
               class_key: str | None = None, expected_norm: float = 1.0,
               probe_points=(), warn: bool = True) -> NumericRecord:
        """Record one probed run.  ``probe`` is a probe vector
        (:data:`PROBE_FIELDS` order) or its dict view.  NaN/Inf counts
        are checked first (a poisoned norm is NaN itself); drift is then
        judged against the precision-and-depth-derived band
        :func:`ulp_band`; for density probes the Hermiticity deviation
        is judged against the same band."""
        p = probe if isinstance(probe, dict) else probe_dict(probe)
        dtype_s = str(jnp.dtype(dtype)) if not isinstance(dtype, str) else dtype
        # rounding drift is RELATIVE to the state's magnitude: a tenant's
        # legitimately scaled input (expected norm S^2) accumulates
        # ~S^2·eps·sqrt(D) of absolute drift, so the band scales with the
        # baseline (floored at 1.0 — a tiny-norm state still gets the
        # unit-scale band, not a vanishing one)
        band = (ulp_band(num_ops, dtype_s, self.safety)
                * max(1.0, abs(float(expected_norm))))
        nan = int(p["nan_count"])
        inf = int(p["inf_count"])
        norm = float(p["norm"])
        drift = abs(norm - float(expected_norm))
        findings: list[str] = []
        if nan or inf:
            findings.append(
                f"{NUMERIC_NAN}: {nan} NaN / {inf} Inf amplitude(s) in the "
                f"{kind} result — the register is poisoned; every "
                "downstream consumer of this class's results is serving "
                "garbage")
        else:
            if not math.isfinite(drift) or drift > band:
                findings.append(
                    f"{NUMERIC_DRIFT}: {'trace' if kind == 'densmatr' else 'norm'} "
                    f"{norm:.17g} drifted {drift:.3g} from "
                    f"{expected_norm:.3g} (band {band:.3g} = "
                    f"{self.safety:.0f} ulp(" + dtype_s + ") * sqrt("
                    f"{max(num_ops, 1)})): a kernel is not norm-preserving "
                    "on this backend (the ROADMAP item 3 symptom class)")
            if kind == "densmatr" and float(p["herm_dev"]) > band:
                findings.append(
                    f"{NUMERIC_DRIFT}: Hermiticity deviation "
                    f"{float(p['herm_dev']):.3g} exceeds the band "
                    f"{band:.3g}: a density channel broke rho = rho^H")
        rec = NumericRecord(label, kind, engine, dtype_s, num_qubits,
                            int(num_ops), class_key, norm,
                            float(p["max_amp2"]), nan, inf,
                            float(p["herm_dev"]), float(expected_norm),
                            float(drift), band, tuple(findings),
                            tuple(probe_points))
        with self._lock:
            self._records.append(rec)
            if len(self._records) > _MAX_RECORDS:
                del self._records[:_MAX_RECORDS // 2]
            self.probed_total += 1
            if nan or inf:
                self.nan_total += 1
            self.drift_total += sum(NUMERIC_DRIFT in f for f in findings)
        if warn:
            for f in findings:
                warnings.warn(f"[{label}] {f}", RuntimeWarning, stacklevel=2)
        return rec

    # -- reading ------------------------------------------------------------
    def records(self) -> list[NumericRecord]:
        with self._lock:
            return list(self._records)

    def as_dicts(self) -> list[dict]:
        return [r.as_dict() for r in self.records()]

    def by_class(self) -> dict:
        """Per-structural-class aggregation: the scrape-side answer to
        WHICH class went numerically bad (records without a class key
        aggregate under ``"-"``)."""
        out: dict = {}
        for r in self.records():
            ck = r.class_key or "-"
            agg = out.setdefault(ck, {"count": 0, "nan_records": 0,
                                      "drift_findings": 0,
                                      "worst_drift": 0.0,
                                      "worst_band": 0.0})
            agg["count"] += 1
            agg["nan_records"] += 1 if (r.nan_count or r.inf_count) else 0
            agg["drift_findings"] += sum(NUMERIC_DRIFT in f
                                         for f in r.findings)
            if math.isfinite(r.norm_drift) and r.norm_drift > agg["worst_drift"]:
                agg["worst_drift"] = r.norm_drift
                agg["worst_band"] = r.band
        return out

    def snapshot(self) -> dict:
        with self._lock:
            return {"records": len(self._records),
                    "probed_total": self.probed_total,
                    "nan_total": self.nan_total,
                    "drift_total": self.drift_total}

    def gauges(self) -> dict:
        """Flat numeric view for the one Prometheus scrape (the service
        splices these as ``quest_serve_numeric_ledger_*``)."""
        return {k: float(v) for k, v in self.snapshot().items()}

    def clear(self) -> None:
        with self._lock:
            self._records = []
            self.nan_total = 0
            self.drift_total = 0
            self.probed_total = 0


_GLOBAL: NumericLedger | None = None
_GLOBAL_LOCK = threading.Lock()


def global_numeric_ledger() -> NumericLedger:
    """The process-wide numeric ledger — the ``--numeric-report`` CLI and
    the bench rows record here.  Services own a PRIVATE ledger by default
    (their scrape attributes findings to the right replica); pass
    ``QuESTService(numeric_ledger=global_numeric_ledger())`` to opt a
    service into the shared one."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = NumericLedger()
        return _GLOBAL


# ---------------------------------------------------------------------------
# adversarial corruption injections (the mutation-harness pattern)
# ---------------------------------------------------------------------------

def inject_scale(state, factor: float = 1.001) -> np.ndarray:
    """A uniformly scaled state: the norm leaves the ulp band while every
    amplitude stays finite — the shape of a lost renormalization or a
    miscompiled kernel that is 'almost' unitary."""
    return np.asarray(state) * float(factor)


def inject_nan(state, index: int = 0) -> np.ndarray:
    """One NaN-poisoned amplitude — the shape of an uninitialized buffer
    or a 0/0 in a collapsed-outcome renormalization."""
    out = np.array(state, copy=True)
    out[0, index] = np.nan
    return out


def inject_nonhermitian(state, num_qubits: int,
                        eps: float = 1e-3) -> np.ndarray:
    """A one-sided off-diagonal perturbation of a Choi-flattened density
    matrix: rho[0, 1] moves, rho[1, 0] does not — trace preserved,
    Hermiticity broken (the invariant ROADMAP item 4's fused channels
    must keep)."""
    out = np.array(state, copy=True)
    dim = 1 << num_qubits
    out[0, 0 + 1 * dim] += eps      # rho[r=0, c=1] at r + c*2^n
    return out


def corruption_selftest(ledger: NumericLedger | None = None,
                        num_qubits: int = 4) -> dict:
    """Prove the ledger can actually fail: each injected corruption MUST
    trip it (zero findings on the clean twins, >= 1 on every corrupted
    one).  Returns ``{"ok": bool, "trips": {...}}``; gated in the serve
    selftest and the CI ``numeric-selftest`` job — a numeric gate that
    cannot catch a planted corruption is not a gate."""
    led = ledger if ledger is not None else NumericLedger()
    n = num_qubits
    state = np.zeros((2, 1 << n))
    state[0, 0] = 1.0
    rho = np.zeros((2, 1 << (2 * n)))
    for k in range(1 << n):
        rho[0, k + (k << n)] = 1.0 / (1 << n)   # maximally mixed, Tr = 1

    def probe_sv(arr):
        return state_probe_vector(jnp.asarray(arr))

    def probe_dm(arr):
        return densmatr_probe_vector(jnp.asarray(arr), n)

    trips = {}
    clean_sv = led.record("clean_statevec", probe_sv(state), num_ops=4,
                          warn=False)
    clean_dm = led.record("clean_densmatr", probe_dm(rho), kind="densmatr",
                          num_qubits=n, num_ops=4, warn=False)
    scaled = led.record("inject_scale", probe_sv(inject_scale(state)),
                        num_ops=4, warn=False)
    nan = led.record("inject_nan", probe_sv(inject_nan(state)), num_ops=4,
                     warn=False)
    herm = led.record("inject_nonhermitian",
                      probe_dm(inject_nonhermitian(rho, n)),
                      kind="densmatr", num_qubits=n, num_ops=4, warn=False)
    trips["clean_statevec"] = len(clean_sv.findings)
    trips["clean_densmatr"] = len(clean_dm.findings)
    trips["inject_scale"] = len(scaled.findings)
    trips["inject_nan"] = len(nan.findings)
    trips["inject_nonhermitian"] = len(herm.findings)
    ok = (trips["clean_statevec"] == 0 and trips["clean_densmatr"] == 0
          and trips["inject_scale"] >= 1 and trips["inject_nan"] >= 1
          and trips["inject_nonhermitian"] >= 1
          and any(NUMERIC_NAN in f for f in nan.findings)
          and any(NUMERIC_DRIFT in f for f in scaled.findings)
          and any(NUMERIC_DRIFT in f for f in herm.findings))
    return {"ok": bool(ok), "trips": trips}
