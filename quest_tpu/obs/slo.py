"""Serve SLO monitor: windowed latency, deadline hit rate, burn rate.

The metrics registry (serve/metrics.py) keeps *cumulative* truth — total
requests, lifetime latency percentiles — which is the wrong shape for the
question an operator of a pod-scale deployment actually asks: "is the
service meeting its deadline objective NOW, and if not, how fast is it
burning the error budget?"  A lifetime p99 dilutes a live incident into
noise; a deadline counter says how many were ever missed, not whether the
miss *rate* is accelerating.  This module keeps the windowed view:

- **Per-structural-class latency** over a sliding window: a single global
  histogram would let one heavy class (a 22q mesh circuit) mask a latency
  cliff in a cheap one (an 8q QFT class) — per-class p50/p99/max is the
  resolution the class-affinity router of ROADMAP item 1 will balance on.
- **Deadline hit rate**: of the requests that carried a ``deadline_ms``,
  the windowed fraction that met it.  Requests without deadlines are
  tracked for latency but do not consume error budget (no objective was
  stated for them).
- **Queue saturation**: depth / max_queue sampled at every admission, with
  the window peak — the early load-shedding signal, since ``E_QUEUE_FULL``
  bounces only start after saturation has already hit 1.0.
- **Burn rate** (the SRE early-warning form): with objective ``target``
  (default 0.999 of deadline'd requests meeting their deadline), the error
  budget is ``1 - target``; the burn rate over window ``W`` is

      burn(W) = miss_rate(W) / (1 - target)

  i.e. 1.0 means the budget is being consumed exactly as fast as the
  objective allows; ``burn_warn`` (default 10) over the short window emits
  an ``O_SLO_BURN`` warning entry — alongside PR 7's ``O_MODEL_DRIFT`` in
  the analysis severity taxonomy — long before the monthly budget is gone.
  Both a short window (default 60 s: fast detection) and a long window
  (default 600 s: smooths batch-boundary blips) are reported; the warning
  keys off the short window, the long one is the page-worthiness context.

Everything is computed on read (``snapshot()``): the request hot path pays
one lock + deque append per completed request (asserted < 20 us/observe in
tests/test_obs.py — the PR 7 < 1% serve-bench overhead budget covers it),
and stays dependency-free like the rest of ``quest_tpu.obs``.
"""

from __future__ import annotations

import dataclasses
import threading
import time

__all__ = ["SLOConfig", "SLOMonitor", "SLO_BURN",
           "nearest_rank_percentile"]

#: the burn-rate warning code (analysis severity: WARNING), next to
#: ledger.MODEL_DRIFT in the O_* observability taxonomy
SLO_BURN = "O_SLO_BURN"

#: sample retention cap — bounds memory on a long-running service the same
#: way the flight ring and the metrics reservoir do
_MAX_SAMPLES = 16384

#: coarse latency edges for the :meth:`SLOMonitor.health` ring (mirrors
#: serve/metrics.py LATENCY_BUCKETS — duplicated, not imported, so obs
#: stays import-light; the p99 a router sheds on only needs bucket
#: resolution, the exact percentile definition stays in ``snapshot()``)
_HEALTH_LAT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                       0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

#: ring slots backing :meth:`SLOMonitor.health` — the short window divided
#: into this many time buckets (default config: 60 s / 30 = 2 s buckets)
_HEALTH_SLOTS = 30


def nearest_rank_percentile(xs: list, q: float) -> float:
    """Nearest-rank percentile over raw observations — THE percentile
    definition of the whole serving surface (the metrics registry's
    histogram summaries use it too, serve/metrics.py): one definition, so
    a p99 from the cumulative registry and a p99 from an SLO window can
    never disagree on method."""
    if not xs:
        return 0.0
    xs = sorted(xs)
    idx = min(len(xs) - 1, max(0, round(q / 100.0 * (len(xs) - 1))))
    return xs[int(idx)]


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """The objective and its windows.  ``deadline_hit_target`` is the SLO
    proper (fraction of deadline-carrying requests that must meet their
    deadline); ``window_s``/``long_window_s`` are the burn-rate windows;
    ``burn_warn`` is the short-window burn rate that raises ``O_SLO_BURN``;
    ``saturation_warn`` raises a warning entry when the window-peak queue
    saturation crosses it (load shedding is near)."""
    deadline_hit_target: float = 0.999
    window_s: float = 60.0
    long_window_s: float = 600.0
    burn_warn: float = 10.0
    saturation_warn: float = 0.8


class SLOMonitor:
    """Thread-safe windowed SLO state.  ``observe``/``observe_queue`` are
    the hot-path writers; ``snapshot()`` computes the windowed view and
    ``gauges()`` flattens it for the shared Prometheus scrape."""

    def __init__(self, config: SLOConfig | None = None):
        self.config = config if config is not None else SLOConfig()
        self._lock = threading.Lock()
        # (t_mono, class_key, latency_s, deadline_ok: bool | None)
        self._samples: list = []        # guarded-by: _lock
        # (t_mono, depth / capacity)
        self._saturation: list = []     # guarded-by: _lock
        self.deadline_misses_total = 0  # guarded-by: _lock
        self.deadline_hits_total = 0    # guarded-by: _lock
        self._h_width = max(self.config.window_s / _HEALTH_SLOTS, 1e-6)
        # the health ring (see health()): _HEALTH_SLOTS time buckets, each
        # [stamp, deadline_hits, deadline_misses, latency_bucket_counts].
        # Written under the lock (writers already hold it); READ without
        # any lock — slots are replaced wholesale when their stamp rolls
        # over, int increments are atomic under the GIL, and observe()
        # commits bucket counts before deadline counters so every torn
        # view stays internally consistent (the schedule fuzzer's
        # slo_health scenario stress-proves exactly this).
        # lock-free: torn-read-tolerant ring by store-order construction; proven by analysis/schedfuzz.py
        self._h_ring: list = [None] * _HEALTH_SLOTS
        # lock-free: single float store; the router's per-decision read needs no ordering
        self._sat_live = 0.0

    # -- recording ----------------------------------------------------------
    def observe(self, class_key: str, latency_s: float,
                deadline_ok: bool | None = None,
                now: float | None = None) -> None:
        """One completed (or deadline-dropped) request.  ``deadline_ok`` is
        None when the request carried no deadline — it is tracked for
        latency but consumes no error budget."""
        t = time.monotonic() if now is None else now
        with self._lock:
            self._samples.append((t, class_key, float(latency_s),
                                  deadline_ok))
            if deadline_ok is True:
                self.deadline_hits_total += 1
            elif deadline_ok is False:
                self.deadline_misses_total += 1
            if len(self._samples) > _MAX_SAMPLES:
                del self._samples[:_MAX_SAMPLES // 2]
            b = self._health_bucket(t)
            # the latency count commits BEFORE the deadline counters: a
            # lock-free health() reader walks hits/misses first and the
            # bucket counts after, so this store order is what keeps every
            # torn view satisfying deadlined <= window_samples (the
            # schedule fuzzer reproduced the inverted-order tear;
            # tests/test_concurrency.py pins it)
            lat = float(latency_s)
            counts = b[3]
            for i, edge in enumerate(_HEALTH_LAT_BUCKETS):
                if lat <= edge:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            if deadline_ok is True:
                b[1] += 1
            elif deadline_ok is False:
                b[2] += 1

    def observe_queue(self, depth: int, capacity: int,
                      now: float | None = None) -> None:
        """Queue depth at one admission (or bounce), as a saturation
        fraction of the bounded queue."""
        t = time.monotonic() if now is None else now
        frac = depth / capacity if capacity else 1.0
        self._sat_live = frac      # plain attr: the health() fast read
        with self._lock:
            self._saturation.append((t, frac))
            if len(self._saturation) > _MAX_SAMPLES:
                del self._saturation[:_MAX_SAMPLES // 2]

    # requires-lock: _lock
    def _health_bucket(self, t: float) -> list:
        """The ring slot for instant ``t`` (caller holds the lock): reused
        in place while its time stamp is current, replaced wholesale when
        the ring wraps onto it."""
        stamp = int(t / self._h_width)
        idx = stamp % _HEALTH_SLOTS
        b = self._h_ring[idx]
        if b is None or b[0] != stamp:
            b = self._h_ring[idx] = [stamp, 0, 0,
                                     [0] * (len(_HEALTH_LAT_BUCKETS) + 1)]
        return b

    def health(self, now: float | None = None) -> dict:
        """Cheap LOCK-FREE snapshot for a router's hot path: current queue
        saturation, a bucket-resolution short-window p99, and the
        short-window burn rate.  Reads plain attributes and walks the
        fixed-size health ring without taking the monitor's lock — a
        concurrent writer can tear the view by at most one in-flight
        sample, which routing tolerates by construction (asserted
        < 20 us/call in tests/test_slo.py, alongside the observe bound).

        This is deliberately NOT ``snapshot()``: that one copies every
        windowed sample and sorts per-class latencies — milliseconds on a
        loaded service, fine for a scrape, ruinous per-routing-decision."""
        t = time.monotonic() if now is None else now
        stamp_min = int(t / self._h_width) - _HEALTH_SLOTS + 1
        hits = misses = total = 0
        counts = [0] * (len(_HEALTH_LAT_BUCKETS) + 1)
        for b in self._h_ring:
            if b is None or b[0] < stamp_min:
                continue
            hits += b[1]
            misses += b[2]
            bc = b[3]
            for i in range(len(counts)):
                counts[i] += bc[i]
        total = sum(counts)
        budget = 1.0 - self.config.deadline_hit_target
        deadlined = hits + misses
        burn = ((misses / deadlined) / budget) if deadlined and budget > 0 \
            else 0.0
        p99 = 0.0
        if total:
            want = max(1, int(0.99 * total + 0.999999))
            cum = 0
            # overflow rank clamps to the TOP finite edge ("p99 >= 30 s"),
            # never inf: this dict lands verbatim in --json documents and
            # Infinity is not an RFC-JSON token
            p99 = _HEALTH_LAT_BUCKETS[-1]
            for i, edge in enumerate(_HEALTH_LAT_BUCKETS):
                cum += counts[i]
                if cum >= want:
                    p99 = edge       # upper bucket edge: a shed decision
                    break            # needs resolution, not exactness
        return {
            "saturation": self._sat_live,
            "p99_s": p99,
            "burn_rate": burn,
            "window_hits": hits,
            "window_misses": misses,
            "window_samples": total,
        }

    # -- reading ------------------------------------------------------------
    def _burn(self, samples: list, now: float, window: float) -> tuple:
        """(hits, misses, hit_rate, burn_rate) over [now - window, now]."""
        hits = misses = 0
        for t, _ck, _lat, ok in samples:
            if now - t > window or ok is None:
                continue
            if ok:
                hits += 1
            else:
                misses += 1
        total = hits + misses
        hit_rate = hits / total if total else 1.0
        budget = 1.0 - self.config.deadline_hit_target
        burn = ((misses / total) / budget) if total and budget > 0 else 0.0
        return hits, misses, hit_rate, burn

    def snapshot(self, now: float | None = None) -> dict:
        """The windowed SLO view: per-class latency over the short window,
        deadline hit rate + burn rates, queue saturation, and the warning
        entries (``O_SLO_BURN``) the early-warning contract is about."""
        cfg = self.config
        t = time.monotonic() if now is None else now
        with self._lock:
            samples = list(self._samples)
            saturation = list(self._saturation)
            # totals copied under the same lock as the samples they
            # summarise: a snapshot must be one consistent cut
            hits_total = self.deadline_hits_total
            misses_total = self.deadline_misses_total
        classes: dict = {}
        for ts, ck, lat, _ok in samples:
            if t - ts <= cfg.window_s:
                classes.setdefault(ck, []).append(lat)
        class_view = {
            ck: {"count": len(xs),
                 "mean_s": sum(xs) / len(xs),
                 "p50_s": nearest_rank_percentile(xs, 50.0),
                 "p99_s": nearest_rank_percentile(xs, 99.0),
                 "max_s": max(xs)}
            for ck, xs in sorted(classes.items())
        }
        h_s, m_s, rate_s, burn_s = self._burn(samples, t, cfg.window_s)
        h_l, m_l, rate_l, burn_l = self._burn(samples, t, cfg.long_window_s)
        sat_window = [f for ts, f in saturation if t - ts <= cfg.window_s]
        sat_now = saturation[-1][1] if saturation else 0.0
        sat_peak = max(sat_window) if sat_window else sat_now
        warnings: list = []
        if burn_s >= cfg.burn_warn:
            warnings.append({
                "code": SLO_BURN,
                "detail": (f"deadline error budget burning {burn_s:.1f}x "
                           f"sustainable over the last {cfg.window_s:.0f}s "
                           f"({m_s} miss(es) / {h_s + m_s} deadline'd "
                           f"request(s); long-window burn {burn_l:.1f}x): "
                           f"the {cfg.deadline_hit_target:.3%} objective "
                           "fails if this holds")})
        if sat_peak >= cfg.saturation_warn:
            warnings.append({
                "code": SLO_BURN,
                "detail": (f"queue saturation peaked at {sat_peak:.2f} in "
                           f"the last {cfg.window_s:.0f}s (warn at "
                           f"{cfg.saturation_warn:.2f}): E_QUEUE_FULL "
                           "bounces are imminent")})
        return {
            "target": cfg.deadline_hit_target,
            "window_s": cfg.window_s,
            "long_window_s": cfg.long_window_s,
            "classes": class_view,
            "deadline": {
                "window_hits": h_s, "window_misses": m_s,
                "hit_rate": rate_s,
                "long_hit_rate": rate_l,
                "burn_rate": burn_s,
                "long_burn_rate": burn_l,
                "hits_total": hits_total,
                "misses_total": misses_total,
            },
            "queue": {"saturation": sat_now, "peak_saturation": sat_peak},
            "warnings": warnings,
        }

    def gauges(self, now: float | None = None) -> dict:
        """Flat numeric view for the shared Prometheus scrape
        (``quest_serve_slo_*``); one scrape covers serving economics,
        tracing health AND the live SLO."""
        snap = self.snapshot(now=now)
        return {
            "deadline_hit_rate": snap["deadline"]["hit_rate"],
            "deadline_misses_total": snap["deadline"]["misses_total"],
            "burn_rate": snap["deadline"]["burn_rate"],
            "long_burn_rate": snap["deadline"]["long_burn_rate"],
            "queue_saturation": snap["queue"]["saturation"],
            "queue_peak_saturation": snap["queue"]["peak_saturation"],
            "burn_warnings": float(len(snap["warnings"])),
        }
