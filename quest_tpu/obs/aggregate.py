"""Cross-process trace aggregation: N recorder shards, ONE merged trace.

PR 7's recorder is deliberately per-process (one process, one trace) — but
the ROADMAP north star is a multi-replica multi-host deployment, where a
request's spans land on whichever process served it and a per-process trace
is an island.  This module is the bridge:

- :func:`process_shard` snapshots this process's recorder as a plain-JSON
  **shard** stamped with ``jax.process_index()`` and a host-clock offset
  estimated against process 0's epoch clock via a ``multihost_utils``
  broadcast (``parallel/mesh.py broadcast_host_epoch`` — the same
  ``broadcast_one_to_all`` pattern ``seed_quest_default`` reuses from the
  reference's seed bcast).
- :func:`merge_shards` renders any set of shards as ONE Chrome-trace
  document: one ``pid`` **track per process** (thread lanes within it),
  span ids namespaced per process so they stay globally unique, and every
  span's timestamp mapped onto process 0's timeline through the shard's
  clock offset — spans of the same wall-clock moment line up across host
  tracks, and a request that crossed processes is correlated by the PR 7
  ``request_id`` carried in every span's ``args``.
- the merged document passes the extended ``validate_chrome_trace``
  (obs/export.py): globally-unique span ids, zero orphans ACROSS processes
  (a parent id must resolve, and must resolve within its own process
  track), and a declared-process contract when the document is a merge.

Two invariants the tests pin:

- **Degenerate identity.**  Merging the single shard of a single-process
  run reproduces ``chrome_trace()`` byte-for-byte (same keys, same values,
  same order).  Single-process tooling — the selftest CI gate, Perfetto
  workflows, the atexit crash dump — cannot tell the merge path exists.
- **Clock-skew alignment.**  For shards whose offsets are exact, two spans
  recording the same epoch instant on different hosts get the same merged
  ``ts`` regardless of the skew between their host clocks (property-tested
  with synthetic skews in tests/test_obs_aggregate.py).

Shards are plain dicts (JSON-serializable as-is): a multi-host launcher
has each process :func:`save_shard` at shutdown (or on the atexit hook)
and any process — or an offline tool — :func:`merge_files` afterwards.
"""

from __future__ import annotations

import json

from .trace import Span, TraceRecorder, recorder as _recorder

__all__ = ["SHARD_FORMAT", "process_shard", "save_shard", "load_shard",
           "merge_shards", "merge_files"]

#: the shard schema tag (bumped on incompatible changes)
SHARD_FORMAT = "quest-tpu-trace-shard-v1"

#: per-process span-id namespace stride: merged id = span_id + index*STRIDE.
#: 2^40 is far above any recorder's id counter (DEFAULT_MAX_SPANS = 2^18)
#: and keeps process 0's ids IDENTITY-mapped — the degenerate-merge
#: contract.
_ID_STRIDE = 1 << 40


def process_shard(recorder: TraceRecorder | None = None, *,
                  align_clock: bool = True) -> dict:
    """This process's recorder as a serializable shard.

    ``align_clock=True`` (default) estimates this host's clock offset
    against process 0 via ``broadcast_host_epoch`` — a COLLECTIVE when
    ``jax.process_count() > 1`` (every process must call it, like the seed
    broadcast); single-process it is free and the offset is exactly 0.0.
    Pass ``align_clock=False`` to snapshot without any collective (offline
    merges can still align on the raw epoch clocks)."""
    import socket

    from ..parallel.mesh import broadcast_host_epoch, process_info
    rec = recorder if recorder is not None else _recorder()
    info = process_info()
    offset = 0.0
    if align_clock:
        _base, offset = broadcast_host_epoch()
    return {
        "format": SHARD_FORMAT,
        "process_index": info["process_index"],
        "process_count": info["process_count"],
        "host": socket.gethostname(),
        "t0_perf": rec.t0_perf,
        "t0_epoch": rec.t0_epoch,
        "clock_offset_s": offset,
        "dropped": rec.snapshot()["dropped"],
        "spans": [{"name": sp.name, "span_id": sp.span_id,
                   "parent_id": sp.parent_id, "request_id": sp.request_id,
                   "t0": sp.t0, "dur": sp.dur, "thread": sp.thread,
                   "attrs": dict(sp.attrs)} for sp in rec.spans()],
    }


def save_shard(path: str, recorder: TraceRecorder | None = None, *,
               align_clock: bool = True) -> dict:
    """Write this process's shard to ``path`` (one JSON document) and
    return it — the per-process half of a multi-host trace capture."""
    shard = process_shard(recorder, align_clock=align_clock)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(shard, fh)
    return shard


def load_shard(path: str) -> dict:
    with open(path, encoding="utf-8") as fh:
        shard = json.load(fh)
    if not isinstance(shard, dict) or shard.get("format") != SHARD_FORMAT:
        raise ValueError(f"{path}: not a {SHARD_FORMAT} shard "
                         f"(format={shard.get('format') if isinstance(shard, dict) else None!r})")
    return shard


def _remap(span_id, pindex: int):
    return None if span_id is None else span_id + pindex * _ID_STRIDE


def merge_shards(shards: list[dict]) -> dict:
    """ONE Chrome-trace document over ``shards`` (any order; one shard per
    process).  Track layout: ``pid = process_index + 1`` (a named process
    track per shard when the merge is non-degenerate), ``tid`` lanes per
    recording thread within each process.  Timestamps are microseconds on
    PROCESS 0's timeline: each span's host-epoch instant is corrected by
    its shard's ``clock_offset_s`` and re-based on process 0's trace
    origin, so simultaneous work lines up across host tracks.

    The single-shard process-0 merge is the IDENTITY: byte-identical to
    ``chrome_trace()`` of the same recorder (tests pin it), so every
    existing single-process consumer reads merged output unchanged."""
    if not shards:
        raise ValueError("merge_shards takes at least one shard")
    by_proc: dict = {}
    for sh in shards:
        if not isinstance(sh, dict) or sh.get("format") != SHARD_FORMAT:
            raise ValueError(f"not a {SHARD_FORMAT} shard: "
                             f"{sh.get('format') if isinstance(sh, dict) else sh!r}")
        p = int(sh["process_index"])
        if p in by_proc:
            raise ValueError(f"two shards claim process_index {p}")
        by_proc[p] = sh
    multi = len(by_proc) > 1
    # every shard's trace origin, mapped onto process 0's host clock
    aligned = {p: sh["t0_epoch"] - sh["clock_offset_s"]
               for p, sh in by_proc.items()}
    base_proc = 0 if 0 in by_proc else min(by_proc)
    base_epoch = aligned[base_proc]
    meta: list = []
    events: list = []
    dropped_total = 0
    for p in sorted(by_proc):
        sh = by_proc[p]
        pid = p + 1
        shift = aligned[p] - base_epoch     # exactly 0.0 for the base shard
        dropped_total += int(sh.get("dropped", 0))
        if multi:
            meta.append({"name": "process_name", "ph": "M", "pid": pid,
                         "args": {"name": f"process {p}"
                                          + (f" ({sh['host']})"
                                             if sh.get("host") else "")}})
        tids: dict = {}
        shard_events = []
        for sp in sh["spans"]:
            tid = tids.setdefault(sp["thread"], len(tids) + 1)
            args = {"span_id": _remap(sp["span_id"], p),
                    "parent_id": _remap(sp["parent_id"], p),
                    "request_id": sp["request_id"]}
            if multi:
                args["process"] = p
            args.update(sp["attrs"])
            shard_events.append({
                "name": sp["name"], "ph": "X", "pid": pid, "tid": tid,
                "ts": (sp["t0"] - sh["t0_perf"] + shift) * 1e6,
                "dur": sp["dur"] * 1e6,
                "args": args,
            })
        meta.extend({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid, "args": {"name": thread}}
                    for thread, tid in tids.items())
        events.extend(shard_events)
    other = {"origin_epoch_s": base_epoch, "dropped_spans": dropped_total}
    if multi:
        other["processes"] = sorted(by_proc)
        other["clock_offsets_s"] = {str(p): by_proc[p]["clock_offset_s"]
                                    for p in sorted(by_proc)}
        other["hosts"] = {str(p): by_proc[p].get("host", "")
                          for p in sorted(by_proc)}
    return {"displayTimeUnit": "ms",
            "otherData": other,
            "traceEvents": meta + events}


def merge_files(paths: list[str]) -> dict:
    """Load shards from ``paths`` and merge them — the offline half of a
    multi-host capture (each process ``save_shard``'d its own file)."""
    return merge_shards([load_shard(p) for p in paths])
