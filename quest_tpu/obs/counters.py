"""Runtime counters: compile wall seconds, dispatch walls, HBM watermark.

The predictive half of the cost loop (planner models, calibration
profiles) is host arithmetic; this module is the cheap always-available
measured half.  Three families, all dependency-free and thread-safe:

- **Compile seconds.**  Every XLA compile the process pays — the serve
  cache's AOT lowers (serve/cache.py ``_get_program``), the donated
  program adapter, the calibration harness's own probes — folds its wall
  seconds into one process-wide total, so "how much of this deployment's
  wall is compilation" is one gauge, not a per-cache spelunk.
- **Dispatch walls.**  Traced runs (``compile_circuit``'s ``circuit.run``
  span) record their host-side dispatch wall here too, so the scrape can
  report dispatch totals next to compile totals without replaying a trace.
- **HBM watermark.**  :func:`hbm_watermark` reads the live backend's
  ``device.memory_stats()`` (bytes in use + the allocator's peak) where
  the platform exposes it — TPU and GPU backends do, the CPU backend
  returns None — and :func:`update_hbm_watermark` folds the peak into the
  process counters so a serve scrape carries the high-water mark even
  between stats reads.

Consumers: ``obs.obs_snapshot()`` (and through it ``QuESTService``'s one
Prometheus scrape, as ``obs_*`` gauges), bench.py row configs
(``compile_seconds`` / ``hbm_peak_bytes``), and the ledger's per-run
``DriftRecord`` fields.  See docs/OBSERVABILITY.md "Runtime counters".
"""

from __future__ import annotations

import threading

__all__ = ["RuntimeCounters", "global_counters", "record_compile",
           "record_dispatch", "hbm_watermark", "update_hbm_watermark"]


class RuntimeCounters:
    """Thread-safe process totals.  One lock, plain adds — cheap enough to
    sit on the compile path (compiles are seconds; the lock is ns)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.compiles_total = 0             # guarded-by: _lock
        self.compile_seconds_total = 0.0    # guarded-by: _lock
        self.dispatches_total = 0           # guarded-by: _lock
        self.dispatch_seconds_total = 0.0   # guarded-by: _lock
        self.hbm_peak_bytes = 0             # guarded-by: _lock
        self.hbm_bytes_in_use = 0           # guarded-by: _lock

    def record_compile(self, seconds: float) -> None:
        with self._lock:
            self.compiles_total += 1
            self.compile_seconds_total += float(seconds)

    def record_dispatch(self, seconds: float) -> None:
        with self._lock:
            self.dispatches_total += 1
            self.dispatch_seconds_total += float(seconds)

    def record_hbm(self, bytes_in_use: int, peak_bytes: int) -> None:
        with self._lock:
            self.hbm_bytes_in_use = int(bytes_in_use)
            self.hbm_peak_bytes = max(self.hbm_peak_bytes, int(peak_bytes))

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "compiles_total": self.compiles_total,
                "compile_seconds_total": self.compile_seconds_total,
                "dispatches_total": self.dispatches_total,
                "dispatch_seconds_total": self.dispatch_seconds_total,
                "hbm_peak_bytes": self.hbm_peak_bytes,
                "hbm_bytes_in_use": self.hbm_bytes_in_use,
            }

    def reset(self) -> None:
        with self._lock:
            self.compiles_total = 0
            self.compile_seconds_total = 0.0
            self.dispatches_total = 0
            self.dispatch_seconds_total = 0.0
            self.hbm_peak_bytes = 0
            self.hbm_bytes_in_use = 0


_GLOBAL: RuntimeCounters | None = None
_GLOBAL_LOCK = threading.Lock()


def global_counters() -> RuntimeCounters:
    """The process-wide counters (the serve cache, compile_circuit and the
    bench harness all record into one place — like the global ledger)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = RuntimeCounters()
        return _GLOBAL


def record_compile(seconds: float) -> None:
    global_counters().record_compile(seconds)


def record_dispatch(seconds: float) -> None:
    global_counters().record_dispatch(seconds)


def hbm_watermark(device=None) -> dict | None:
    """Live device-memory stats of ``device`` (default: the first visible
    device), or None where the backend exposes none (the CPU backend).

    Returns ``{"bytes_in_use", "peak_bytes_in_use", "bytes_limit",
    "device_kind", "platform"}`` with missing allocator fields as 0 — the
    keys a capacity dashboard needs next to
    ``planner.memory_footprint``'s static model."""
    try:
        import jax
        dev = device if device is not None else jax.devices()[0]
        stats = dev.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return {
        "bytes_in_use": int(stats.get("bytes_in_use", 0) or 0),
        "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0) or 0),
        "bytes_limit": int(stats.get("bytes_limit", 0) or 0),
        "device_kind": getattr(dev, "device_kind", ""),
        "platform": getattr(dev, "platform", ""),
    }


def update_hbm_watermark(device=None) -> dict | None:
    """Read :func:`hbm_watermark` and fold it into the process counters;
    returns the reading (None where unavailable).  Call sites: bench rows
    after each timed config, serve batch completion under tracing."""
    wm = hbm_watermark(device)
    if wm is not None:
        global_counters().record_hbm(wm["bytes_in_use"],
                                     wm["peak_bytes_in_use"]
                                     or wm["bytes_in_use"])
    return wm
